"""Multi-process GSPMD self-test worker (the proof VERDICT r3 asked
for): a REAL dp x mp training job run as N coordinated jax processes.

Reference analog: test/legacy_test/test_dist_base.py:959 — the reference
proves its distributed stack by forking trainer processes with crafted
env and diffing loss curves against the single-process run. This module
is the forked trainer; tests/test_multiprocess.py is the harness, and
`python -m paddle_tpu.distributed.launch --nnodes N --rank r
 .../smoke.py` is the launch path it exercises end-to-end.

What one worker does:
1. `init_parallel_env()` — joins the jax.distributed coordination
   service (idempotent when the launcher already initialized it).
2. Cross-process TCPStore exercise (set/get/add across ranks).
3. Builds the tiny-Llama Trainer on a GLOBAL dp x mp mesh whose dp axis
   spans the process boundary — every dp gradient reduction is a real
   cross-process collective.
4. Runs SMOKE_STEPS training steps on deterministic data (every process
   feeds the same seeded GLOBAL batch; jax.device_put scatters the
   addressable shards), recording the loss curve.
5. multihost barrier, then saves a cross-process sharded checkpoint
   (each process writes its own addressable shards).
6. Rank 0 writes losses + run facts to SMOKE_OUT/result.json.

Env contract (set by the harness/launcher):
  PADDLE_MASTER / PADDLE_TRAINERS_NUM / PADDLE_TRAINER_ID  — rendezvous
  SMOKE_OUT      — output dir (result.json, checkpoint under ckpt/)
  SMOKE_STORE_PORT — port for the cross-process TCPStore exercise
  SMOKE_STEPS    — training steps (default 4)
  SMOKE_MESH     — "dp,mp" global mesh shape (default "2,4")
  SMOKE_OVERLAP  — >0: decomposed-FSDP-collective rings with this many
                   sub-chunks (TrainStepConfig.overlap_fsdp)
"""
from __future__ import annotations

import json
import os


def main():
    import numpy as np
    import jax

    import paddle_tpu
    import paddle_tpu.distributed as dist
    import paddle_tpu.optimizer as opt
    from paddle_tpu.distributed import checkpoint as ckpt
    from paddle_tpu.distributed.store import TCPStore
    from paddle_tpu.models import LlamaForCausalLM, tiny_llama_config
    from paddle_tpu.parallel import (Trainer, TrainStepConfig,
                                     llama_sharding_plan)

    dist.init_parallel_env()
    rank = jax.process_index()
    world = jax.process_count()
    n_local = len(jax.local_devices())
    n_global = len(jax.devices())
    assert world == int(os.environ["PADDLE_TRAINERS_NUM"]), \
        (world, os.environ["PADDLE_TRAINERS_NUM"])
    assert rank == int(os.environ["PADDLE_TRAINER_ID"])
    assert n_global == world * n_local
    assert dist.get_rank() == rank and dist.get_world_size() == world

    # -- cross-process store exercise (TCPStore equivalent) ----------------
    store = TCPStore(host="127.0.0.1",
                     port=int(os.environ["SMOKE_STORE_PORT"]),
                     world_size=world, is_master=(rank == 0))
    store.set(f"smoke_rank_{rank}", str(rank).encode())
    total = store.add("smoke_counter", rank + 1)   # eventually sums ranks
    for r in range(world):
        store.wait(f"smoke_rank_{r}", timeout=60)
        got = store.get(f"smoke_rank_{r}")
        assert got == str(r).encode(), (r, got)
    del total

    # -- global-mesh trainer ----------------------------------------------
    # SMOKE_MESH: legacy "2,4" = {"dp": 2, "mp": 4}, or the ordered
    # "name:size,name:size" form. ORDER sets the device layout: the
    # first axis varies slowest across jax.devices() (which groups by
    # process), so the FIRST axis is the one spanning the process
    # boundary — "mp:2,dp:4" makes every mp collective cross-process
    # (VERDICT r4 item 4; reference: fleet/base/topology.py:61
    # cartesian topo across hosts).
    spec = os.environ.get("SMOKE_MESH", "2,4")
    if ":" in spec:
        axes = {}
        for part in spec.split(","):
            k, v = part.split(":")
            axes[k] = int(v)
    else:
        dp, mp = (int(x) for x in spec.split(","))
        axes = {"dp": dp, "mp": mp}
    sz = 1
    for v in axes.values():
        sz *= v
    assert sz == n_global, (axes, n_global)
    from paddle_tpu.distributed.mesh import init_mesh
    mesh = init_mesh(axes)

    paddle_tpu.seed(0)
    kind = os.environ.get("SMOKE_TRAINER", "trainer")
    cfg = tiny_llama_config(num_hidden_layers=2)
    model = LlamaForCausalLM(cfg)
    optimizer = opt.AdamW(learning_rate=1e-3,
                          parameters=model.parameters())
    if kind == "pipeline":
        from paddle_tpu.parallel.pipeline import (PipelineConfig,
                                                  PipelineTrainer)
        tr = PipelineTrainer(
            model, optimizer, mesh=mesh,
            plan=llama_sharding_plan(mesh.jax_mesh.axis_names),
            config=PipelineConfig(
                compute_dtype=None,
                num_microbatches=int(os.environ.get("SMOKE_MICRO", "4"))))
    else:
        # SMOKE_OVERLAP=<chunks>: route the FSDP projections through
        # the decomposed ppermute rings (parallel/overlap.py) — the
        # harness pins this run's losses to the propagated-collective
        # reference (rtol 1e-5) with the fsdp axis spanning the
        # process boundary
        ov = int(os.environ.get("SMOKE_OVERLAP", "0"))
        tr = Trainer(model, optimizer, mesh=mesh,
                     plan=llama_sharding_plan(mesh.jax_mesh.axis_names),
                     config=TrainStepConfig(compute_dtype=None,
                                            overlap_fsdp=ov > 0,
                                            overlap_chunks=max(ov, 1)))

    steps = int(os.environ.get("SMOKE_STEPS", "4"))
    losses = []
    rng = np.random.RandomState(7)
    for _ in range(steps):
        ids = rng.randint(0, cfg.vocab_size, (8, 32)).astype("int32")
        loss = tr.step({"input_ids": ids, "labels": ids})
        losses.append(float(loss.numpy()))

    # -- barrier + cross-process sharded checkpoint ------------------------
    from jax.experimental import multihost_utils
    multihost_utils.sync_global_devices("smoke:pre_ckpt")
    tr.sync_to_model()
    out = os.environ["SMOKE_OUT"]
    ckpt.save_state_dict(model.state_dict(), os.path.join(out, "ckpt"))

    if rank == 0:
        with open(os.path.join(out, "result.json"), "w") as f:
            json.dump({"losses": losses, "world": world,
                       "devices_global": n_global,
                       "devices_local": n_local,
                       "mesh": list(axes.items()),
                       "trainer": kind,
                       "overlap": int(os.environ.get("SMOKE_OVERLAP",
                                                     "0"))}, f)
    multihost_utils.sync_global_devices("smoke:done")
    print(f"SMOKE_OK rank={rank} losses={losses}", flush=True)
    # this environment's XLA teardown aborts ("terminate called without
    # an active exception", SIGABRT) after a successful run; shut the
    # coordination service down cleanly, then skip interpreter teardown
    # so the harness sees the true exit status
    try:
        jax.distributed.shutdown()
    except Exception:       # lint: disable=silent-swallow -- best-effort coordination teardown right before os._exit
        pass
    os._exit(0)


if __name__ == "__main__":
    main()
