"""`python -m paddle_tpu.distributed.launch` — multi-host launcher
(reference: python/paddle/distributed/launch/main.py:20,
controllers/collective.py:22, controllers/master.py).

TPU-native: the reference forks one process per GPU and rendezvouses via
its HTTP/etcd Master; on TPU the unit is one process per HOST and the
rendezvous is jax.distributed's coordination service (the TCPStore
equivalent). So the launcher's job collapses to: parse the rendezvous
config, export the env jax.distributed.initialize reads, then exec the
training script in-process (no fork — XLA owns all local chips from one
process).
"""
from __future__ import annotations

import argparse
import os
import runpy
import sys

__all__ = ["launch", "main"]


def _parse(argv):
    p = argparse.ArgumentParser(
        prog="paddle_tpu.distributed.launch",
        description="Launch a distributed training script")
    p.add_argument("--master", default=None,
                   help="coordinator address host:port "
                        "(reference: --master etcd://... or http host)")
    p.add_argument("--nnodes", type=int,
                   default=int(os.environ.get("PADDLE_NNODES", "1")),
                   help="number of hosts")
    p.add_argument("--rank", type=int,
                   default=int(os.environ.get("PADDLE_TRAINER_ID", "-1")),
                   help="this host's rank (-1: from env/TPU metadata)")
    p.add_argument("--devices", default=None,
                   help="accepted for reference-compat; TPU chips are "
                        "owned by the single host process")
    p.add_argument("--job_id", default="default")
    p.add_argument("--log_dir", default=None)
    p.add_argument("script", help="training script")
    p.add_argument("script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def launch(script, script_args=(), master=None, nnodes=1, rank=-1,
           job_id="default", log_dir=None):
    """Programmatic entry. Sets the distributed env and runs `script`
    in-process under __main__."""
    env = os.environ
    env["PADDLE_NNODES"] = str(nnodes)
    env["PADDLE_TRAINERS_NUM"] = str(nnodes)
    if master:
        env["PADDLE_MASTER"] = master
        # jax.distributed.initialize reads these (or its args); exporting
        # both names keeps user scripts working with either API
        env["JAX_COORDINATOR_ADDRESS"] = master
    if rank >= 0:
        env["PADDLE_TRAINER_ID"] = str(rank)
        env["JAX_PROCESS_ID"] = str(rank)
    env["JAX_NUM_PROCESSES"] = str(nnodes)
    env["PADDLE_JOB_ID"] = job_id
    if log_dir:
        os.makedirs(log_dir, exist_ok=True)
        env["PADDLE_LOG_DIR"] = log_dir

    if nnodes > 1:
        import jax
        kw = {}
        if master:
            kw["coordinator_address"] = master
        if rank >= 0:
            kw["process_id"] = rank
            kw["num_processes"] = nnodes
        jax.distributed.initialize(**kw)

    sys.argv = [script] + list(script_args)
    runpy.run_path(script, run_name="__main__")


def main(argv=None):
    args = _parse(argv if argv is not None else sys.argv[1:])
    launch(args.script, args.script_args, master=args.master,
           nnodes=args.nnodes, rank=args.rank, job_id=args.job_id,
           log_dir=args.log_dir)


if __name__ == "__main__":  # pragma: no cover
    main()
