"""Auto-parallel (reference: python/paddle/distributed/auto_parallel/):
the static Engine + planner. `Engine` plans a mesh with the analytic
cost model, completes a sharding plan from the model structure, and
compiles the hybrid-parallel step via paddle_tpu.parallel."""
from paddle_tpu.distributed.auto_parallel.engine import (Engine, Strategy,
                                                         plan_mesh,
                                                         complete_plan)
from paddle_tpu.distributed.auto_parallel import engine as _engine


class _StaticNS:
    engine = _engine


static = _StaticNS()

__all__ = ["Engine", "Strategy", "plan_mesh", "complete_plan", "static"]
