"""Auto-parallel static Engine (reference:
python/paddle/distributed/auto_parallel/static/engine.py:61 Engine —
prepare/fit/evaluate/predict/cost over a planned distributed program;
the Completer (completion.py) infers per-tensor dist attributes and the
tuner/cost model (tuner/, cost/) picks the process mesh).

TPU-native collapse: "completion" is a name->PartitionSpec plan derived
from the model STRUCTURE (GSPMD propagates everything downstream, so
only parameter annotations are needed — the reference completes every
tensor in the program); the planner ranks candidate (dp, fsdp, mp, pp)
meshes with the same analytic roofline the auto_tuner uses, WITHOUT
launching trials; execution is the compiled Trainer/PipelineTrainer
step. Engine.cost() exposes the estimate like the reference's
Engine.cost interface.
"""
from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np
import jax
from jax.sharding import PartitionSpec as P

from paddle_tpu.parallel.plan import ShardingPlan


@dataclass
class Strategy:
    """(reference: auto_parallel/strategy.py Strategy). `auto_mode`
    'semi' uses the degrees given below; 'full' lets plan_mesh pick."""
    auto_mode: str = "full"          # 'full' | 'semi'
    dp_degree: int = 1
    fsdp_degree: int = 1
    mp_degree: int = 1
    pp_degree: int = 1
    num_microbatches: int = 4
    compute_dtype: str = "bfloat16"
    grad_accum_steps: int = 1
    extra: dict = field(default_factory=dict)


# ---------------------------------------------------------------------------
# mesh planning (tuner/cost equivalent, trial-free)
# ---------------------------------------------------------------------------

def _model_stats(model):
    from paddle_tpu.jit.functional import state_tensors
    n_params = 0
    for t in state_tensors(model).values():
        n_params += int(np.prod(t._value.shape))
    return n_params


def plan_mesh(model, n_devices, tuner_cfg=None):
    """Pick (dp, fsdp, mp, pp) for `n_devices` by ranking every feasible
    factorization with the auto_tuner's analytic cost model (reference:
    tuner/parallel_tuner.py + cost/estimate_cost — here no trials, pure
    estimate). Returns (axes dict, ranked candidates)."""
    from paddle_tpu.distributed.auto_tuner import (
        default_candidates, prune_candidates, _cost)

    cfg = dict(tuner_cfg or {})
    cfg.setdefault("num_devices", n_devices)
    cfg.setdefault("model_params", _model_stats(model))
    stack = _detect_stack(model)
    if stack is not None:
        cfg.setdefault("num_layers", len(stack[1]))
    cands = default_candidates(cfg)
    kept, _ = prune_candidates(cands, cfg)
    if not kept:
        kept = [{"dp_degree": n_devices, "mp_degree": 1, "pp_degree": 1,
                 "sharding_degree": 1, "micro_batch_size":
                 cfg.get("micro_batch_size", 1)}]
    ranked = sorted(kept, key=lambda c: _cost(c, cfg))
    best = ranked[0]
    axes = {}
    if best["pp_degree"] > 1:
        axes["pp"] = best["pp_degree"]
    if best["dp_degree"] > 1:
        axes["dp"] = best["dp_degree"]
    if best.get("sharding_degree", 1) > 1:
        axes["fsdp"] = best["sharding_degree"]
    if best["mp_degree"] > 1:
        axes["mp"] = best["mp_degree"]
    if not axes:
        axes["dp"] = n_devices
    return axes, ranked


def _detect_stack(model):
    try:
        from paddle_tpu.parallel.pipeline import detect_layer_stack
        return detect_layer_stack(model)
    except Exception:
        return None


# ---------------------------------------------------------------------------
# plan completion (Completer equivalent)
# ---------------------------------------------------------------------------

class NamePlan(ShardingPlan):
    """Exact param-name -> PartitionSpec plan (completion output)."""

    def __init__(self, table, default=P()):
        self.table = dict(table)
        self.default = default
        self.rules = []

    def spec_for(self, name, ndim=None):
        return self.table.get(name, self.default)

    def __repr__(self):
        rows = "\n".join(f"  {k}: {v}" for k, v in self.table.items())
        return f"NamePlan(\n{rows}\n)"


def complete_plan(model, mesh_axes):
    """Derive Megatron-style parameter shardings from the model's
    STRUCTURE (the Completer, reference auto_parallel/static/
    completion.py:132, reduced to what GSPMD needs):

    - nn.Embedding weights: vocab dim over 'mp', feature over 'fsdp'
      (VocabParallelEmbedding);
    - within any module that directly owns several nn.Linear sublayers,
      every Linear but the LAST is column-parallel P(fsdp, mp) and the
      last is row-parallel P(mp, fsdp) — this matches attention
      (q/k/v col, o row), transformer MLPs (gate/up col, down row) and
      BERT blocks without naming conventions;
    - lone output heads (a Linear whose out_features looks vocab-sized)
      are column-parallel; 1D params (norms, biases) replicate;
    - stacked expert parameters (a module exposing num_experts with
      (E, ...) 3-D weights) shard the expert dim over 'ep' (r5: the
      MoE rule the reference Completer gets from its moe spmd rules).
    """
    from paddle_tpu import nn
    mp = "mp" if "mp" in mesh_axes else None
    fsdp = "fsdp" if "fsdp" in mesh_axes else None
    ep = "ep" if "ep" in mesh_axes else None
    table = {}

    emb_dims = set()
    for name, sub in model.named_sublayers():
        if isinstance(sub, nn.Embedding):
            table[f"{name}.weight"] = P(mp, fsdp)
            emb_dims.add(sub.weight.shape[0])
        n_exp = getattr(sub, "num_experts", None)
        if n_exp:
            for pname, pt in sub.__dict__.get("_parameters", {}).items():
                if pt is not None and len(pt.shape) == 3 \
                        and pt.shape[0] == n_exp:
                    table[f"{name}.{pname}"] = P(ep)

    for name, sub in model.named_sublayers(include_self=True):
        linears = [(n, c) for n, c in sub.named_children()
                   if isinstance(c, nn.Linear)]
        if len(linears) >= 2:
            for n, c in linears[:-1]:
                table.setdefault(f"{name}.{n}.weight" if name else
                                 f"{n}.weight", P(fsdp, mp))
            ln, lc = linears[-1]
            table.setdefault(f"{name}.{ln}.weight" if name else
                             f"{ln}.weight", P(mp, fsdp))
        elif len(linears) == 1:
            n, c = linears[0]
            full = f"{name}.{n}.weight" if name else f"{n}.weight"
            out_f = c.weight.shape[1]
            if out_f in emb_dims or out_f >= 8 * c.weight.shape[0]:
                table.setdefault(full, P(fsdp, mp))   # vocab head
    return NamePlan(table)


# ---------------------------------------------------------------------------
# the Engine
# ---------------------------------------------------------------------------

class Engine:
    """reference: auto_parallel/static/engine.py:61. prepare() plans the
    mesh + completes the plan + builds the compiled step; fit/evaluate/
    predict drive it; cost() returns the analytic estimate."""

    def __init__(self, model=None, loss=None, optimizer=None, metrics=None,
                 strategy=None):
        self.model = model
        self.loss = loss
        self.optimizer = optimizer
        self.metrics = metrics
        self.strategy = strategy or Strategy()
        self.mesh_axes = None
        self.plan = None
        self.trainer = None
        self._ranked = None

    # -- planning ---------------------------------------------------------
    def prepare(self, n_devices=None, tuner_cfg=None):
        from paddle_tpu.distributed.mesh import init_mesh
        n = n_devices or len(jax.devices())
        st = self.strategy
        if st.auto_mode == "semi":
            axes = {k: v for k, v in
                    (("pp", st.pp_degree), ("dp", st.dp_degree),
                     ("fsdp", st.fsdp_degree), ("mp", st.mp_degree))
                    if v > 1} or {"dp": 1}
        else:
            axes, self._ranked = plan_mesh(self.model, n, tuner_cfg)
        self.mesh_axes = axes
        self.mesh = init_mesh(axes)
        self.plan = complete_plan(self.model, axes)

        from paddle_tpu.parallel import Trainer, TrainStepConfig
        if axes.get("pp", 1) > 1:
            from paddle_tpu.parallel.pipeline import (PipelineTrainer,
                                                      PipelineConfig)
            self.trainer = PipelineTrainer(
                self.model, self.optimizer, mesh=self.mesh,
                plan=self.plan,
                config=PipelineConfig(
                    compute_dtype=st.compute_dtype,
                    num_microbatches=st.num_microbatches))
        else:
            self.trainer = Trainer(
                self.model, self.optimizer, mesh=self.mesh.jax_mesh,
                plan=self.plan,
                config=TrainStepConfig(
                    compute_dtype=st.compute_dtype,
                    grad_accum_steps=st.grad_accum_steps))
        return self

    # -- execution --------------------------------------------------------
    def fit(self, train_data, epochs=1, steps_per_epoch=None, verbose=0):
        if self.trainer is None:
            self.prepare()
        losses = []
        for _ in range(epochs):
            for i, batch in enumerate(train_data):
                if steps_per_epoch is not None and i >= steps_per_epoch:
                    break
                losses.append(float(self.trainer.step(
                    self._as_batch(batch))))
        return losses

    def evaluate(self, eval_data, steps=None):
        from paddle_tpu.jit.functional import functional_call
        from paddle_tpu.core.tensor import Tensor
        self.trainer.sync_to_model()
        self.model.eval()
        tot, n = 0.0, 0
        try:
            for i, batch in enumerate(eval_data):
                if steps is not None and i >= steps:
                    break
                b = self._as_batch(batch)
                out = self.model(
                    Tensor(b["input_ids"], stop_gradient=True),
                    labels=Tensor(b["labels"], stop_gradient=True))
                loss = out[0] if isinstance(out, tuple) else out
                tot += float(loss)
                n += 1
        finally:
            self.model.train()
        return tot / max(n, 1)

    def predict(self, data):
        from paddle_tpu.core.tensor import Tensor
        self.trainer.sync_to_model()
        self.model.eval()
        try:
            out = [self.model(Tensor(self._as_batch(b)["input_ids"],
                                     stop_gradient=True))
                   for b in data]
        finally:
            self.model.train()
        return out

    def cost(self, tuner_cfg=None):
        """Analytic per-step time + per-chip memory for the prepared
        config (reference Engine.cost / cost/estimate_cost)."""
        from paddle_tpu.distributed.auto_tuner import (_cost,
                                                       _memory_bytes)
        axes = self.mesh_axes or {}
        cfg = {
            "dp_degree": axes.get("dp", 1),
            "mp_degree": axes.get("mp", 1),
            "pp_degree": axes.get("pp", 1),
            "sharding_degree": axes.get("fsdp", 1),
            "micro_batch_size": (tuner_cfg or {}).get(
                "micro_batch_size", 1),
        }
        tc = dict(tuner_cfg or {})
        tc.setdefault("num_devices",
                      int(np.prod(list(axes.values()))) if axes else 1)
        tc.setdefault("model_params", _model_stats(self.model))
        return {"step_time_s": _cost(cfg, tc),
                "memory_bytes_per_chip": _memory_bytes(cfg, tc)}

    @staticmethod
    def _as_batch(batch):
        from paddle_tpu.core.tensor import Tensor
        if isinstance(batch, dict):
            return {k: (v._value if isinstance(v, Tensor) else v)
                    for k, v in batch.items()}
        if isinstance(batch, (tuple, list)) and len(batch) == 2:
            x, y = batch
            return {"input_ids": x._value if isinstance(x, Tensor) else x,
                    "labels": y._value if isinstance(y, Tensor) else y}
        raise ValueError("batch must be a dict or an (input, label) pair")
