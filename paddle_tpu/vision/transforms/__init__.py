from paddle_tpu.vision.transforms.transforms import *  # noqa: F401,F403
from paddle_tpu.vision.transforms import functional  # noqa: F401
from paddle_tpu.vision.transforms.functional import (  # noqa: F401
    to_tensor, normalize, resize, pad, crop, center_crop, hflip, vflip,
    rotate, to_grayscale, adjust_brightness, adjust_contrast,
    adjust_saturation, adjust_hue, erase,
)
