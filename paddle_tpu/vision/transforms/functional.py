"""Functional image transforms (reference:
python/paddle/vision/transforms/functional.py + functional_cv2.py).

Images are numpy arrays HWC uint8/float (the "cv2 backend" of the
reference) or paddle Tensors CHW after `to_tensor`. PIL images are accepted
and converted if PIL happens to be importable; no hard dependency.
"""
from __future__ import annotations

import numbers

import numpy as np

from paddle_tpu.core.tensor import Tensor

__all__ = [
    "to_tensor", "normalize", "resize", "pad", "crop", "center_crop",
    "hflip", "vflip", "rotate", "to_grayscale", "adjust_brightness",
    "adjust_contrast", "adjust_saturation", "adjust_hue", "erase",
]


def _as_hwc(img) -> np.ndarray:
    if isinstance(img, Tensor):
        arr = img.numpy()
        if arr.ndim == 3 and arr.shape[0] in (1, 3, 4):
            arr = np.transpose(arr, (1, 2, 0))
        return arr
    if "PIL" in str(type(img)):
        return np.asarray(img)
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[:, :, None]
    return arr


def to_tensor(pic, data_format="CHW") -> Tensor:
    """HWC uint8 [0,255] -> CHW float32 [0,1] (reference to_tensor)."""
    arr = _as_hwc(pic)
    if arr.ndim == 2:
        arr = arr[:, :, None]
    if arr.dtype == np.uint8:
        arr = arr.astype("float32") / 255.0
    else:
        arr = arr.astype("float32")
    if data_format == "CHW":
        arr = np.transpose(arr, (2, 0, 1))
    return Tensor(arr)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    mean = np.asarray(mean, dtype="float32")
    std = np.asarray(std, dtype="float32")
    if isinstance(img, Tensor):
        shape = ([-1, 1, 1] if data_format == "CHW" else [1, 1, -1])
        from paddle_tpu import tensor as T
        m = Tensor(mean.reshape(shape))
        s = Tensor(std.reshape(shape))
        return T.divide(T.subtract(img, m), s)
    arr = _as_hwc(img).astype("float32")
    return (arr - mean.reshape(1, 1, -1)) / std.reshape(1, 1, -1)


def _interp_resize(arr: np.ndarray, h: int, w: int, interpolation: str):
    """Resize HWC numpy via jax.image (bilinear/nearest)."""
    import jax
    import jax.numpy as jnp
    method = {"bilinear": "linear", "nearest": "nearest",
              "bicubic": "cubic", "linear": "linear",
              "cubic": "cubic"}.get(interpolation, "linear")
    out = jax.image.resize(jnp.asarray(arr, jnp.float32),
                           (h, w, arr.shape[2]), method=method)
    out = np.asarray(out)
    if arr.dtype == np.uint8:
        out = np.clip(np.round(out), 0, 255).astype(np.uint8)
    return out


def resize(img, size, interpolation="bilinear"):
    tensor_in = isinstance(img, Tensor)
    arr = _as_hwc(img)
    h, w = arr.shape[:2]
    if isinstance(size, int):
        # short side -> size, keep aspect (reference semantics)
        if h <= w:
            nh, nw = size, max(1, int(round(w * size / h)))
        else:
            nh, nw = max(1, int(round(h * size / w))), size
    else:
        nh, nw = size
    out = _interp_resize(arr, nh, nw, interpolation)
    return to_tensor(out) if tensor_in else out


def pad(img, padding, fill=0, padding_mode="constant"):
    tensor_in = isinstance(img, Tensor)
    arr = _as_hwc(img)
    if isinstance(padding, numbers.Number):
        pl = pr = pt = pb = int(padding)
    elif len(padding) == 2:
        pl, pt = padding
        pr, pb = padding
    else:
        pl, pt, pr, pb = padding
    mode = {"constant": "constant", "edge": "edge", "reflect": "reflect",
            "symmetric": "symmetric"}[padding_mode]
    kwargs = {"constant_values": fill} if mode == "constant" else {}
    out = np.pad(arr, ((pt, pb), (pl, pr), (0, 0)), mode=mode, **kwargs)
    return to_tensor(out) if tensor_in else out


def crop(img, top, left, height, width):
    tensor_in = isinstance(img, Tensor)
    arr = _as_hwc(img)
    out = arr[top:top + height, left:left + width]
    return to_tensor(out) if tensor_in else out


def center_crop(img, output_size):
    if isinstance(output_size, numbers.Number):
        output_size = (int(output_size), int(output_size))
    arr = _as_hwc(img)
    h, w = arr.shape[:2]
    th, tw = output_size
    top = max(0, (h - th) // 2)
    left = max(0, (w - tw) // 2)
    return crop(img, top, left, th, tw)


def hflip(img):
    tensor_in = isinstance(img, Tensor)
    out = _as_hwc(img)[:, ::-1]
    return to_tensor(out) if tensor_in else np.ascontiguousarray(out)


def vflip(img):
    tensor_in = isinstance(img, Tensor)
    out = _as_hwc(img)[::-1]
    return to_tensor(out) if tensor_in else np.ascontiguousarray(out)


def rotate(img, angle, interpolation="nearest", expand=False, center=None,
           fill=0):
    """Rotate by angle degrees counter-clockwise (reference functional
    rotate; nearest-neighbour grid sample)."""
    tensor_in = isinstance(img, Tensor)
    arr = _as_hwc(img)
    h, w = arr.shape[:2]
    theta = np.deg2rad(angle)
    cos, sin = np.cos(theta), np.sin(theta)
    cy, cx = ((h - 1) / 2.0, (w - 1) / 2.0) if center is None else center
    if expand:
        nh = int(abs(h * cos) + abs(w * sin) + 0.5)
        nw = int(abs(w * cos) + abs(h * sin) + 0.5)
    else:
        nh, nw = h, w
    oy, ox = (nh - 1) / 2.0, (nw - 1) / 2.0
    yy, xx = np.meshgrid(np.arange(nh), np.arange(nw), indexing="ij")
    # inverse map output -> input
    sy = (yy - oy) * cos - (xx - ox) * sin + cy
    sx = (yy - oy) * sin + (xx - ox) * cos + cx
    syi = np.round(sy).astype(int)
    sxi = np.round(sx).astype(int)
    valid = (syi >= 0) & (syi < h) & (sxi >= 0) & (sxi < w)
    out = np.full((nh, nw, arr.shape[2]), fill, dtype=arr.dtype)
    out[valid] = arr[syi[valid], sxi[valid]]
    return to_tensor(out) if tensor_in else out


_GRAY_W = np.array([0.299, 0.587, 0.114], dtype="float32")


def to_grayscale(img, num_output_channels=1):
    tensor_in = isinstance(img, Tensor)
    arr = _as_hwc(img)
    gray = (arr[..., :3].astype("float32") @ _GRAY_W)
    if arr.dtype == np.uint8:
        gray = np.clip(np.round(gray), 0, 255).astype(np.uint8)
    out = np.repeat(gray[..., None], num_output_channels, axis=-1)
    return to_tensor(out) if tensor_in else out


def _blend(a, b, factor, dtype):
    out = a.astype("float32") * factor + b.astype("float32") * (1 - factor)
    if dtype == np.uint8:
        out = np.clip(out, 0, 255).astype(np.uint8)
    return out


def adjust_brightness(img, brightness_factor):
    tensor_in = isinstance(img, Tensor)
    arr = _as_hwc(img)
    out = _blend(arr, np.zeros_like(arr), brightness_factor, arr.dtype)
    return to_tensor(out) if tensor_in else out


def adjust_contrast(img, contrast_factor):
    tensor_in = isinstance(img, Tensor)
    arr = _as_hwc(img)
    mean = arr[..., :3].astype("float32").mean()
    out = _blend(arr, np.full_like(arr, mean), contrast_factor, arr.dtype)
    return to_tensor(out) if tensor_in else out


def adjust_saturation(img, saturation_factor):
    tensor_in = isinstance(img, Tensor)
    arr = _as_hwc(img)
    gray = _as_hwc(to_grayscale(arr, 3))
    out = _blend(arr, gray, saturation_factor, arr.dtype)
    return to_tensor(out) if tensor_in else out


def adjust_hue(img, hue_factor):
    """Shift hue in HSV space; hue_factor in [-0.5, 0.5]."""
    assert -0.5 <= hue_factor <= 0.5
    tensor_in = isinstance(img, Tensor)
    arr = _as_hwc(img)
    dtype = arr.dtype
    x = arr[..., :3].astype("float32") / (255.0 if dtype == np.uint8 else 1.0)
    mx = x.max(-1)
    mn = x.min(-1)
    diff = mx - mn + 1e-12
    r, g, b = x[..., 0], x[..., 1], x[..., 2]
    h = np.where(mx == r, ((g - b) / diff) % 6,
                 np.where(mx == g, (b - r) / diff + 2, (r - g) / diff + 4))
    h = (h / 6.0 + hue_factor) % 1.0
    s = np.where(mx > 0, diff / (mx + 1e-12), 0)
    v = mx
    i = np.floor(h * 6)
    f = h * 6 - i
    p, q, t = v * (1 - s), v * (1 - f * s), v * (1 - (1 - f) * s)
    i = (i.astype(int) % 6)[..., None]
    out = np.select(
        [i == 0, i == 1, i == 2, i == 3, i == 4, i == 5],
        [np.stack([v, t, p], -1), np.stack([q, v, p], -1),
         np.stack([p, v, t], -1), np.stack([p, q, v], -1),
         np.stack([t, p, v], -1), np.stack([v, p, q], -1)])
    if dtype == np.uint8:
        out = np.clip(np.round(out * 255), 0, 255).astype(np.uint8)
    if arr.shape[-1] > 3:
        out = np.concatenate([out, arr[..., 3:]], axis=-1)
    return to_tensor(out) if tensor_in else out


def erase(img, i, j, h, w, v, inplace=False):
    if isinstance(img, Tensor):
        arr = img.numpy().copy()
        arr[..., i:i + h, j:j + w] = v
        return Tensor(arr)
    arr = img if inplace else img.copy()
    arr[i:i + h, j:j + w] = v
    return arr
