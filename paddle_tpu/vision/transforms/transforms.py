"""Class-style transforms (reference:
python/paddle/vision/transforms/transforms.py — BaseTransform subclasses
composable with Compose)."""
from __future__ import annotations

import numbers
import random

import numpy as np

from paddle_tpu.vision.transforms import functional as F

__all__ = [
    "Compose", "BaseTransform", "ToTensor", "Normalize", "Resize",
    "RandomCrop", "CenterCrop", "RandomHorizontalFlip", "RandomVerticalFlip",
    "RandomResizedCrop", "ColorJitter", "Pad", "Grayscale", "Transpose",
    "RandomRotation", "RandomErasing", "BrightnessTransform",
    "ContrastTransform", "SaturationTransform", "HueTransform",
]


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class BaseTransform:
    """Single-image transform; keys/paired-data handling of the reference is
    simplified to the common single-image case."""

    def __init__(self, keys=None):
        self.keys = keys

    def __call__(self, img):
        return self._apply_image(img)

    def _apply_image(self, img):
        raise NotImplementedError


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW", keys=None):
        super().__init__(keys)
        self.data_format = data_format

    def _apply_image(self, img):
        return F.to_tensor(img, self.data_format)


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False,
                 keys=None):
        super().__init__(keys)
        if isinstance(mean, numbers.Number):
            mean = [mean, mean, mean]
        if isinstance(std, numbers.Number):
            std = [std, std, std]
        self.mean, self.std = mean, std
        self.data_format = data_format

    def _apply_image(self, img):
        return F.normalize(img, self.mean, self.std, self.data_format)


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = size
        self.interpolation = interpolation

    def _apply_image(self, img):
        return F.resize(img, self.size, self.interpolation)


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        super().__init__(keys)
        self.size = size

    def _apply_image(self, img):
        return F.center_crop(img, self.size)


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0,
                 padding_mode="constant", keys=None):
        super().__init__(keys)
        if isinstance(size, numbers.Number):
            size = (int(size), int(size))
        self.size = size
        self.padding = padding
        self.pad_if_needed = pad_if_needed
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        if self.padding is not None:
            img = F.pad(img, self.padding, self.fill, self.padding_mode)
        arr = F._as_hwc(img)
        h, w = arr.shape[:2]
        th, tw = self.size
        if self.pad_if_needed and (h < th or w < tw):
            img = F.pad(img, (0, 0, max(tw - w, 0), max(th - h, 0)),
                        self.fill, self.padding_mode)
            arr = F._as_hwc(img)
            h, w = arr.shape[:2]
        top = random.randint(0, h - th)
        left = random.randint(0, w - tw)
        return F.crop(img, top, left, th, tw)


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        return F.hflip(img) if random.random() < self.prob else img


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        return F.vflip(img) if random.random() < self.prob else img


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear", keys=None):
        super().__init__(keys)
        if isinstance(size, numbers.Number):
            size = (int(size), int(size))
        self.size = size
        self.scale = scale
        self.ratio = ratio
        self.interpolation = interpolation

    def _apply_image(self, img):
        arr = F._as_hwc(img)
        h, w = arr.shape[:2]
        area = h * w
        for _ in range(10):
            target = random.uniform(*self.scale) * area
            ar = np.exp(random.uniform(np.log(self.ratio[0]),
                                       np.log(self.ratio[1])))
            cw = int(round(np.sqrt(target * ar)))
            ch = int(round(np.sqrt(target / ar)))
            if 0 < cw <= w and 0 < ch <= h:
                top = random.randint(0, h - ch)
                left = random.randint(0, w - cw)
                img = F.crop(img, top, left, ch, cw)
                return F.resize(img, self.size, self.interpolation)
        return F.resize(F.center_crop(img, min(h, w)), self.size,
                        self.interpolation)


class ColorJitter(BaseTransform):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0,
                 keys=None):
        super().__init__(keys)
        self.brightness = brightness
        self.contrast = contrast
        self.saturation = saturation
        self.hue = hue

    def _apply_image(self, img):
        ops = []
        if self.brightness:
            f = random.uniform(max(0, 1 - self.brightness),
                               1 + self.brightness)
            ops.append(lambda x: F.adjust_brightness(x, f))
        if self.contrast:
            f2 = random.uniform(max(0, 1 - self.contrast), 1 + self.contrast)
            ops.append(lambda x: F.adjust_contrast(x, f2))
        if self.saturation:
            f3 = random.uniform(max(0, 1 - self.saturation),
                                1 + self.saturation)
            ops.append(lambda x: F.adjust_saturation(x, f3))
        if self.hue:
            f4 = random.uniform(-self.hue, self.hue)
            ops.append(lambda x: F.adjust_hue(x, f4))
        random.shuffle(ops)
        for op in ops:
            img = op(img)
        return img


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        super().__init__(keys)
        self.padding = padding
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        return F.pad(img, self.padding, self.fill, self.padding_mode)


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1, keys=None):
        super().__init__(keys)
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        return F.to_grayscale(img, self.num_output_channels)


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        super().__init__(keys)
        self.order = order

    def _apply_image(self, img):
        arr = F._as_hwc(img)
        return np.transpose(arr, self.order)


class RandomRotation(BaseTransform):
    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0, keys=None):
        super().__init__(keys)
        if isinstance(degrees, numbers.Number):
            degrees = (-degrees, degrees)
        self.degrees = degrees
        self.interpolation = interpolation
        self.expand = expand
        self.center = center
        self.fill = fill

    def _apply_image(self, img):
        angle = random.uniform(*self.degrees)
        return F.rotate(img, angle, self.interpolation, self.expand,
                        self.center, self.fill)


class RandomErasing(BaseTransform):
    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3),
                 value=0, inplace=False, keys=None):
        super().__init__(keys)
        self.prob = prob
        self.scale = scale
        self.ratio = ratio
        self.value = value
        self.inplace = inplace

    def _apply_image(self, img):
        if random.random() >= self.prob:
            return img
        from paddle_tpu.core.tensor import Tensor
        if isinstance(img, Tensor):
            h, w = img.shape[-2], img.shape[-1]
        else:
            arr = F._as_hwc(img)
            h, w = arr.shape[:2]
        area = h * w
        for _ in range(10):
            target = random.uniform(*self.scale) * area
            ar = random.uniform(*self.ratio)
            eh = int(round(np.sqrt(target / ar)))
            ew = int(round(np.sqrt(target * ar)))
            if eh < h and ew < w:
                top = random.randint(0, h - eh)
                left = random.randint(0, w - ew)
                return F.erase(img, top, left, eh, ew, self.value,
                               self.inplace)
        return img


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return img
        f = random.uniform(max(0, 1 - self.value), 1 + self.value)
        return F.adjust_brightness(img, f)


class ContrastTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return img
        f = random.uniform(max(0, 1 - self.value), 1 + self.value)
        return F.adjust_contrast(img, f)


class SaturationTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return img
        f = random.uniform(max(0, 1 - self.value), 1 + self.value)
        return F.adjust_saturation(img, f)


class HueTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return img
        f = random.uniform(-self.value, self.value)
        return F.adjust_hue(img, f)
