"""Vision datasets (reference: python/paddle/vision/datasets/ — MNIST,
FashionMNIST, Cifar10/100, ImageFolder/DatasetFolder, Flowers).

This environment has no network egress, so constructors take local files
(standard idx/pickle formats) via ``image_path``/``data_file`` like the
reference, and raise a clear error instead of downloading. ``FakeData``
provides deterministic synthetic images for tests and smoke training.
"""
from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile

import numpy as np

from paddle_tpu.io import Dataset

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100", "DatasetFolder",
           "ImageFolder", "FakeData"]


class FakeData(Dataset):
    """Deterministic synthetic image classification data."""

    def __init__(self, num_samples=256, image_shape=(3, 32, 32),
                 num_classes=10, transform=None, seed=0):
        self.num_samples = num_samples
        self.image_shape = tuple(image_shape)
        self.num_classes = num_classes
        self.transform = transform
        self._rng = np.random.RandomState(seed)
        # class-dependent mean so the task is learnable
        self._means = self._rng.randn(num_classes, *self.image_shape) \
            .astype("float32")
        self._labels = self._rng.randint(0, num_classes, num_samples)

    def __len__(self):
        return self.num_samples

    def __getitem__(self, idx):
        label = int(self._labels[idx])
        rng = np.random.RandomState(1000 + idx)
        img = (self._means[label]
               + 0.3 * rng.randn(*self.image_shape).astype("float32"))
        if self.transform is not None:
            img = self.transform(img)
        return img, np.int64(label)


def _require(path, what):
    if path is None or not os.path.exists(path):
        raise FileNotFoundError(
            f"{what} not found at {path!r}. This environment has no network "
            f"egress — place the standard dataset files locally and pass "
            f"their path, or use paddle_tpu.vision.datasets.FakeData for "
            f"synthetic data.")


class MNIST(Dataset):
    """idx-format MNIST (reference datasets/mnist.py). Pass image_path/
    label_path pointing at the standard *-ubyte.gz files."""

    NAME = "mnist"

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, backend="cv2"):
        self.mode = mode.lower()
        self.transform = transform
        _require(image_path, f"{self.NAME} images")
        _require(label_path, f"{self.NAME} labels")
        with gzip.open(label_path, "rb") as f:
            magic, n = struct.unpack(">II", f.read(8))
            self.labels = np.frombuffer(f.read(), dtype=np.uint8)[:n]
        with gzip.open(image_path, "rb") as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            self.images = np.frombuffer(f.read(), dtype=np.uint8) \
                .reshape(n, rows, cols)

    def __len__(self):
        return len(self.images)

    def __getitem__(self, idx):
        img = self.images[idx][:, :, None]  # HWC
        if self.transform is not None:
            img = self.transform(img)
        return img, np.int64(self.labels[idx])


class FashionMNIST(MNIST):
    NAME = "fashion-mnist"


class Cifar10(Dataset):
    """python-pickle CIFAR tarball (reference datasets/cifar.py)."""

    _N_CLASSES = 10

    def __init__(self, data_file=None, mode="train", transform=None,
                 backend="cv2"):
        self.mode = mode.lower()
        self.transform = transform
        _require(data_file, "cifar tar.gz")
        datas, labels = [], []
        want = "test_batch" if self.mode == "test" else "data_batch"
        if self._N_CLASSES == 100:
            want = "test" if self.mode == "test" else "train"
        with tarfile.open(data_file, "r:*") as tf:
            for member in tf.getmembers():
                base = os.path.basename(member.name)
                if not base.startswith(want):
                    continue
                batch = pickle.load(tf.extractfile(member), encoding="bytes")
                datas.append(batch[b"data"])
                key = b"labels" if b"labels" in batch else b"fine_labels"
                labels.extend(batch[key])
        self.data = np.concatenate(datas).reshape(-1, 3, 32, 32)
        self.labels = np.asarray(labels, dtype=np.int64)

    def __len__(self):
        return len(self.data)

    def __getitem__(self, idx):
        img = np.transpose(self.data[idx], (1, 2, 0))  # HWC uint8
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]


class Cifar100(Cifar10):
    _N_CLASSES = 100


_IMG_EXTS = (".jpg", ".jpeg", ".png", ".bmp", ".npy")


def _load_image(path):
    if path.endswith(".npy"):
        return np.load(path)
    try:
        from PIL import Image
        return np.asarray(Image.open(path).convert("RGB"))
    except ImportError as e:
        raise RuntimeError(
            f"cannot decode {path}: PIL unavailable; use .npy images") from e


class DatasetFolder(Dataset):
    """class-per-subdirectory layout (reference datasets/folder.py)."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.loader = loader or _load_image
        self.transform = transform
        extensions = extensions or _IMG_EXTS
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            cdir = os.path.join(root, c)
            for dirpath, _, files in sorted(os.walk(cdir)):
                for fn in sorted(files):
                    path = os.path.join(dirpath, fn)
                    ok = (is_valid_file(path) if is_valid_file
                          else fn.lower().endswith(extensions))
                    if ok:
                        self.samples.append((path, self.class_to_idx[c]))
        if not self.samples:
            raise RuntimeError(f"no valid images under {root}")

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        img = self.loader(path)
        if self.transform is not None:
            img = self.transform(img)
        return img, np.int64(target)


class ImageFolder(Dataset):
    """flat folder of images, no labels (reference folder.py ImageFolder)."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.loader = loader or _load_image
        self.transform = transform
        extensions = extensions or _IMG_EXTS
        self.samples = []
        for dirpath, _, files in sorted(os.walk(root)):
            for fn in sorted(files):
                path = os.path.join(dirpath, fn)
                ok = (is_valid_file(path) if is_valid_file
                      else fn.lower().endswith(extensions))
                if ok:
                    self.samples.append(path)

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, idx):
        img = self.loader(self.samples[idx])
        if self.transform is not None:
            img = self.transform(img)
        return [img]
