"""paddle.vision equivalent (reference: python/paddle/vision/)."""
from paddle_tpu.vision import transforms  # noqa: F401
from paddle_tpu.vision import datasets  # noqa: F401
from paddle_tpu.vision import models  # noqa: F401
from paddle_tpu.vision import ops  # noqa: F401

__all__ = ["transforms", "datasets", "models", "ops", "set_image_backend",
           "get_image_backend"]

_image_backend = "cv2"


def set_image_backend(backend):
    global _image_backend
    if backend not in ("pil", "cv2", "tensor"):
        raise ValueError(f"invalid backend {backend}")
    _image_backend = backend


def get_image_backend():
    return _image_backend


def image_load(path, backend=None):
    """Load an image file (reference: vision/image.py image_load).
    backend 'pil' returns a PIL Image; 'cv2'/'tensor' return an HWC uint8
    numpy array (no OpenCV in this image — PIL decodes either way)."""
    from PIL import Image
    import numpy as np
    be = backend or get_image_backend()
    img = Image.open(path)
    if be == "pil":
        return img
    return np.asarray(img)
