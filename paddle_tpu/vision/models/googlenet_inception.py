"""GoogLeNet + InceptionV3 (reference: python/paddle/vision/models/
googlenet.py, inceptionv3.py).
"""
from __future__ import annotations

import paddle_tpu.nn as nn
from paddle_tpu import tensor as T

__all__ = ["GoogLeNet", "googlenet", "InceptionV3", "inception_v3"]


def _cbr(in_c, out_c, k, stride=1, padding=0):
    return nn.Sequential(
        nn.Conv2D(in_c, out_c, k, stride=stride, padding=padding,
                  bias_attr=False),
        nn.BatchNorm2D(out_c), nn.ReLU())


class _Inception(nn.Layer):
    """GoogLeNet inception block (1x1 / 3x3 / 5x5 / pool-proj)."""

    def __init__(self, in_c, c1, c3r, c3, c5r, c5, proj):
        super().__init__()
        self.b1 = _cbr(in_c, c1, 1)
        self.b3 = nn.Sequential(_cbr(in_c, c3r, 1), _cbr(c3r, c3, 3,
                                                         padding=1))
        self.b5 = nn.Sequential(_cbr(in_c, c5r, 1), _cbr(c5r, c5, 5,
                                                         padding=2))
        self.bp = nn.Sequential(nn.MaxPool2D(3, stride=1, padding=1),
                                _cbr(in_c, proj, 1))

    def forward(self, x):
        return T.concat([self.b1(x), self.b3(x), self.b5(x), self.bp(x)],
                        axis=1)


class GoogLeNet(nn.Layer):
    """(reference: googlenet.py GoogLeNet). forward returns (main, aux1,
    aux2) like the reference."""

    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.stem = nn.Sequential(
            _cbr(3, 64, 7, stride=2, padding=3),
            nn.MaxPool2D(3, stride=2, padding=1),
            _cbr(64, 64, 1), _cbr(64, 192, 3, padding=1),
            nn.MaxPool2D(3, stride=2, padding=1))
        self.i3a = _Inception(192, 64, 96, 128, 16, 32, 32)
        self.i3b = _Inception(256, 128, 128, 192, 32, 96, 64)
        self.pool3 = nn.MaxPool2D(3, stride=2, padding=1)
        self.i4a = _Inception(480, 192, 96, 208, 16, 48, 64)
        self.i4b = _Inception(512, 160, 112, 224, 24, 64, 64)
        self.i4c = _Inception(512, 128, 128, 256, 24, 64, 64)
        self.i4d = _Inception(512, 112, 144, 288, 32, 64, 64)
        self.i4e = _Inception(528, 256, 160, 320, 32, 128, 128)
        self.pool4 = nn.MaxPool2D(3, stride=2, padding=1)
        self.i5a = _Inception(832, 256, 160, 320, 32, 128, 128)
        self.i5b = _Inception(832, 384, 192, 384, 48, 128, 128)
        self.with_pool = with_pool
        self.num_classes = num_classes
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.dropout = nn.Dropout(0.4)
            self.fc = nn.Linear(1024, num_classes)
            # aux heads (train-time; reference keeps them in forward)
            self.aux1 = nn.Sequential(nn.AdaptiveAvgPool2D(4),
                                      _cbr(512, 128, 1))
            self.aux1_fc = nn.Sequential(nn.Linear(128 * 16, 1024),
                                         nn.ReLU(), nn.Dropout(0.7),
                                         nn.Linear(1024, num_classes))
            self.aux2 = nn.Sequential(nn.AdaptiveAvgPool2D(4),
                                      _cbr(528, 128, 1))
            self.aux2_fc = nn.Sequential(nn.Linear(128 * 16, 1024),
                                         nn.ReLU(), nn.Dropout(0.7),
                                         nn.Linear(1024, num_classes))

    def forward(self, x):
        x = self.stem(x)
        x = self.pool3(self.i3b(self.i3a(x)))
        x = self.i4a(x)
        aux1 = None
        if self.num_classes > 0:
            aux1 = self.aux1_fc(T.flatten(self.aux1(x), 1))
        x = self.i4d(self.i4c(self.i4b(x)))
        aux2 = None
        if self.num_classes > 0:
            aux2 = self.aux2_fc(T.flatten(self.aux2(x), 1))
        x = self.pool4(self.i4e(x))
        x = self.i5b(self.i5a(x))
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = self.fc(self.dropout(T.flatten(x, 1)))
        return x, aux1, aux2


def googlenet(pretrained=False, **kwargs):
    from paddle_tpu.vision.models.densenet import _no_pretrained
    _no_pretrained(pretrained)
    return GoogLeNet(**kwargs)


class _IncA(nn.Layer):
    def __init__(self, in_c, pool_features):
        super().__init__()
        self.b1 = _cbr(in_c, 64, 1)
        self.b5 = nn.Sequential(_cbr(in_c, 48, 1), _cbr(48, 64, 5, padding=2))
        self.b3 = nn.Sequential(_cbr(in_c, 64, 1),
                                _cbr(64, 96, 3, padding=1),
                                _cbr(96, 96, 3, padding=1))
        self.bp = nn.Sequential(nn.AvgPool2D(3, stride=1, padding=1),
                                _cbr(in_c, pool_features, 1))

    def forward(self, x):
        return T.concat([self.b1(x), self.b5(x), self.b3(x), self.bp(x)], 1)


class _IncReduceA(nn.Layer):
    def __init__(self, in_c):
        super().__init__()
        self.b3 = _cbr(in_c, 384, 3, stride=2)
        self.b3d = nn.Sequential(_cbr(in_c, 64, 1),
                                 _cbr(64, 96, 3, padding=1),
                                 _cbr(96, 96, 3, stride=2))
        self.pool = nn.MaxPool2D(3, stride=2)

    def forward(self, x):
        return T.concat([self.b3(x), self.b3d(x), self.pool(x)], 1)


class _IncB(nn.Layer):
    def __init__(self, in_c, c7):
        super().__init__()
        self.b1 = _cbr(in_c, 192, 1)
        self.b7 = nn.Sequential(_cbr(in_c, c7, 1),
                                _cbr(c7, c7, (1, 7), padding=(0, 3)),
                                _cbr(c7, 192, (7, 1), padding=(3, 0)))
        self.b77 = nn.Sequential(_cbr(in_c, c7, 1),
                                 _cbr(c7, c7, (7, 1), padding=(3, 0)),
                                 _cbr(c7, c7, (1, 7), padding=(0, 3)),
                                 _cbr(c7, c7, (7, 1), padding=(3, 0)),
                                 _cbr(c7, 192, (1, 7), padding=(0, 3)))
        self.bp = nn.Sequential(nn.AvgPool2D(3, stride=1, padding=1),
                                _cbr(in_c, 192, 1))

    def forward(self, x):
        return T.concat([self.b1(x), self.b7(x), self.b77(x), self.bp(x)], 1)


class _IncReduceB(nn.Layer):
    def __init__(self, in_c):
        super().__init__()
        self.b3 = nn.Sequential(_cbr(in_c, 192, 1), _cbr(192, 320, 3,
                                                         stride=2))
        self.b7 = nn.Sequential(_cbr(in_c, 192, 1),
                                _cbr(192, 192, (1, 7), padding=(0, 3)),
                                _cbr(192, 192, (7, 1), padding=(3, 0)),
                                _cbr(192, 192, 3, stride=2))
        self.pool = nn.MaxPool2D(3, stride=2)

    def forward(self, x):
        return T.concat([self.b3(x), self.b7(x), self.pool(x)], 1)


class _IncC(nn.Layer):
    def __init__(self, in_c):
        super().__init__()
        self.b1 = _cbr(in_c, 320, 1)
        self.b3_stem = _cbr(in_c, 384, 1)
        self.b3_a = _cbr(384, 384, (1, 3), padding=(0, 1))
        self.b3_b = _cbr(384, 384, (3, 1), padding=(1, 0))
        self.b33_stem = nn.Sequential(_cbr(in_c, 448, 1),
                                      _cbr(448, 384, 3, padding=1))
        self.b33_a = _cbr(384, 384, (1, 3), padding=(0, 1))
        self.b33_b = _cbr(384, 384, (3, 1), padding=(1, 0))
        self.bp = nn.Sequential(nn.AvgPool2D(3, stride=1, padding=1),
                                _cbr(in_c, 192, 1))

    def forward(self, x):
        s = self.b3_stem(x)
        s2 = self.b33_stem(x)
        return T.concat([self.b1(x), self.b3_a(s), self.b3_b(s),
                         self.b33_a(s2), self.b33_b(s2), self.bp(x)], 1)


class InceptionV3(nn.Layer):
    """(reference: inceptionv3.py InceptionV3; input 299x299)."""

    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.stem = nn.Sequential(
            _cbr(3, 32, 3, stride=2), _cbr(32, 32, 3),
            _cbr(32, 64, 3, padding=1), nn.MaxPool2D(3, stride=2),
            _cbr(64, 80, 1), _cbr(80, 192, 3), nn.MaxPool2D(3, stride=2))
        self.blocks = nn.Sequential(
            _IncA(192, 32), _IncA(256, 64), _IncA(288, 64),
            _IncReduceA(288),
            _IncB(768, 128), _IncB(768, 160), _IncB(768, 160),
            _IncB(768, 192),
            _IncReduceB(768),
            _IncC(1280), _IncC(2048))
        self.with_pool = with_pool
        self.num_classes = num_classes
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.dropout = nn.Dropout(0.5)
            self.fc = nn.Linear(2048, num_classes)

    def forward(self, x):
        x = self.blocks(self.stem(x))
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = self.fc(self.dropout(T.flatten(x, 1)))
        return x


def inception_v3(pretrained=False, **kwargs):
    from paddle_tpu.vision.models.densenet import _no_pretrained
    _no_pretrained(pretrained)
    return InceptionV3(**kwargs)
