"""DenseNet (reference: python/paddle/vision/models/densenet.py)."""
from __future__ import annotations

import paddle_tpu.nn as nn
from paddle_tpu import tensor as T

__all__ = ["DenseNet", "densenet121", "densenet161", "densenet169",
           "densenet201", "densenet264"]

_CFG = {
    121: (64, 32, (6, 12, 24, 16)),
    161: (96, 48, (6, 12, 36, 24)),
    169: (64, 32, (6, 12, 32, 32)),
    201: (64, 32, (6, 12, 48, 32)),
    264: (64, 32, (6, 12, 64, 48)),
}


class _DenseLayer(nn.Layer):
    def __init__(self, in_c, growth_rate, bn_size, dropout):
        super().__init__()
        self.norm1 = nn.BatchNorm2D(in_c)
        self.relu = nn.ReLU()
        self.conv1 = nn.Conv2D(in_c, bn_size * growth_rate, 1,
                               bias_attr=False)
        self.norm2 = nn.BatchNorm2D(bn_size * growth_rate)
        self.conv2 = nn.Conv2D(bn_size * growth_rate, growth_rate, 3,
                               padding=1, bias_attr=False)
        self.dropout = nn.Dropout(dropout)

    def forward(self, x):
        out = self.conv1(self.relu(self.norm1(x)))
        out = self.conv2(self.relu(self.norm2(out)))
        out = self.dropout(out)
        return T.concat([x, out], axis=1)


class _Transition(nn.Sequential):
    def __init__(self, in_c, out_c):
        super().__init__(
            nn.BatchNorm2D(in_c), nn.ReLU(),
            nn.Conv2D(in_c, out_c, 1, bias_attr=False),
            nn.AvgPool2D(2, stride=2))


class DenseNet(nn.Layer):
    """(reference: densenet.py DenseNet — layers in {121,161,169,201,264})."""

    def __init__(self, layers=121, bn_size=4, dropout=0.0,
                 num_classes=1000, with_pool=True):
        super().__init__()
        if layers not in _CFG:
            raise ValueError(f"layers must be one of {sorted(_CFG)}")
        init_c, growth, blocks = _CFG[layers]
        self.features = [nn.Conv2D(3, init_c, 7, stride=2, padding=3,
                                   bias_attr=False),
                         nn.BatchNorm2D(init_c), nn.ReLU(),
                         nn.MaxPool2D(3, stride=2, padding=1)]
        c = init_c
        for bi, n_layers in enumerate(blocks):
            for li in range(n_layers):
                self.features.append(_DenseLayer(c, growth, bn_size,
                                                 dropout))
                c += growth
            if bi != len(blocks) - 1:
                self.features.append(_Transition(c, c // 2))
                c = c // 2
        self.features.append(nn.BatchNorm2D(c))
        self.features.append(nn.ReLU())
        self.features = nn.Sequential(*self.features)
        self.with_pool = with_pool
        self.num_classes = num_classes
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Linear(c, num_classes)

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = T.flatten(x, 1)
            x = self.classifier(x)
        return x


def _no_pretrained(pretrained):
    if pretrained:
        raise RuntimeError(
            "pretrained weights require network download, which this "
            "environment does not allow; load a local state_dict instead")


def _make(layers):
    def ctor(pretrained=False, **kwargs):
        _no_pretrained(pretrained)
        return DenseNet(layers=layers, **kwargs)
    ctor.__name__ = f"densenet{layers}"
    return ctor


densenet121 = _make(121)
densenet161 = _make(161)
densenet169 = _make(169)
densenet201 = _make(201)
densenet264 = _make(264)
