"""Vision model zoo (reference: python/paddle/vision/models/__init__.py)."""
from paddle_tpu.vision.models.resnet import *  # noqa: F401,F403
from paddle_tpu.vision.models.vgg import *  # noqa: F401,F403
from paddle_tpu.vision.models.small import *  # noqa: F401,F403
from paddle_tpu.vision.models.mobilenet import *  # noqa: F401,F403
from paddle_tpu.vision.models.vit import *  # noqa: F401,F403
