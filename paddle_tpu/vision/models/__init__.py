"""Vision model zoo (reference: python/paddle/vision/models/__init__.py)."""
from paddle_tpu.vision.models.resnet import *  # noqa: F401,F403
from paddle_tpu.vision.models.vgg import *  # noqa: F401,F403
from paddle_tpu.vision.models.small import *  # noqa: F401,F403
from paddle_tpu.vision.models.mobilenet import *  # noqa: F401,F403
from paddle_tpu.vision.models.vit import *  # noqa: F401,F403
from paddle_tpu.vision.models.densenet import (  # noqa: F401
    DenseNet, densenet121, densenet161, densenet169, densenet201,
    densenet264)
from paddle_tpu.vision.models.shufflenetv2 import (  # noqa: F401
    ShuffleNetV2, shufflenet_v2_x0_25, shufflenet_v2_x0_33,
    shufflenet_v2_x0_5, shufflenet_v2_x1_0, shufflenet_v2_x1_5,
    shufflenet_v2_x2_0, shufflenet_v2_swish)
from paddle_tpu.vision.models.googlenet_inception import (  # noqa: F401
    GoogLeNet, googlenet, InceptionV3, inception_v3)
