"""ShuffleNetV2 (reference: python/paddle/vision/models/shufflenetv2.py)."""
from __future__ import annotations

import paddle_tpu.nn as nn
from paddle_tpu import tensor as T

__all__ = ["ShuffleNetV2", "shufflenet_v2_x0_25", "shufflenet_v2_x0_33",
           "shufflenet_v2_x0_5", "shufflenet_v2_x1_0",
           "shufflenet_v2_x1_5", "shufflenet_v2_x2_0",
           "shufflenet_v2_swish"]

_STAGE_OUT = {
    0.25: (24, 24, 48, 96, 512),
    0.33: (24, 32, 64, 128, 512),
    0.5: (24, 48, 96, 192, 1024),
    1.0: (24, 116, 232, 464, 1024),
    1.5: (24, 176, 352, 704, 1024),
    2.0: (24, 244, 488, 976, 2048),
}
_REPEATS = (4, 8, 4)


def _channel_shuffle(x, groups):
    n, c, h, w = x.shape
    x = T.reshape(x, [n, groups, c // groups, h, w])
    x = T.transpose(x, [0, 2, 1, 3, 4])
    return T.reshape(x, [n, c, h, w])


def _conv_bn(in_c, out_c, k, stride, groups=1, act=None):
    layers = [nn.Conv2D(in_c, out_c, k, stride=stride,
                        padding=(k - 1) // 2, groups=groups,
                        bias_attr=False),
              nn.BatchNorm2D(out_c)]
    if act is not None:
        layers.append(act())
    return nn.Sequential(*layers)


class _InvertedResidual(nn.Layer):
    def __init__(self, in_c, out_c, stride, act):
        super().__init__()
        self.stride = stride
        branch = out_c // 2
        if stride == 1:
            self.branch2 = nn.Sequential(
                _conv_bn(in_c // 2, branch, 1, 1, act=act),
                _conv_bn(branch, branch, 3, 1, groups=branch),
                _conv_bn(branch, branch, 1, 1, act=act))
            self.branch1 = None
        else:
            self.branch1 = nn.Sequential(
                _conv_bn(in_c, in_c, 3, stride, groups=in_c),
                _conv_bn(in_c, branch, 1, 1, act=act))
            self.branch2 = nn.Sequential(
                _conv_bn(in_c, branch, 1, 1, act=act),
                _conv_bn(branch, branch, 3, stride, groups=branch),
                _conv_bn(branch, branch, 1, 1, act=act))

    def forward(self, x):
        if self.stride == 1:
            half = x.shape[1] // 2
            x1 = x[:, :half]
            x2 = x[:, half:]
            out = T.concat([x1, self.branch2(x2)], axis=1)
        else:
            out = T.concat([self.branch1(x), self.branch2(x)], axis=1)
        return _channel_shuffle(out, 2)


class ShuffleNetV2(nn.Layer):
    def __init__(self, scale=1.0, act="relu", num_classes=1000,
                 with_pool=True):
        super().__init__()
        if scale not in _STAGE_OUT:
            raise ValueError(f"scale must be one of {sorted(_STAGE_OUT)}")
        act_layer = nn.Swish if act == "swish" else nn.ReLU
        outs = _STAGE_OUT[scale]
        self.conv1 = _conv_bn(3, outs[0], 3, 2, act=act_layer)
        self.max_pool = nn.MaxPool2D(3, stride=2, padding=1)
        blocks = []
        in_c = outs[0]
        for si, reps in enumerate(_REPEATS):
            out_c = outs[si + 1]
            for i in range(reps):
                blocks.append(_InvertedResidual(
                    in_c, out_c, 2 if i == 0 else 1, act_layer))
                in_c = out_c
        self.blocks = nn.Sequential(*blocks)
        self.conv_last = _conv_bn(in_c, outs[-1], 1, 1, act=act_layer)
        self.with_pool = with_pool
        self.num_classes = num_classes
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(outs[-1], num_classes)

    def forward(self, x):
        x = self.max_pool(self.conv1(x))
        x = self.conv_last(self.blocks(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = T.flatten(x, 1)
            x = self.fc(x)
        return x


def _no_pretrained(pretrained):
    from paddle_tpu.vision.models.densenet import _no_pretrained as f
    f(pretrained)


def _make(scale, act="relu", suffix=None):
    def ctor(pretrained=False, **kwargs):
        _no_pretrained(pretrained)
        return ShuffleNetV2(scale=scale, act=act, **kwargs)
    ctor.__name__ = suffix or f"shufflenet_v2_x{scale}"
    return ctor


shufflenet_v2_x0_25 = _make(0.25)
shufflenet_v2_x0_33 = _make(0.33)
shufflenet_v2_x0_5 = _make(0.5)
shufflenet_v2_x1_0 = _make(1.0)
shufflenet_v2_x1_5 = _make(1.5)
shufflenet_v2_x2_0 = _make(2.0)
shufflenet_v2_swish = _make(1.0, act="swish", suffix="shufflenet_v2_swish")
