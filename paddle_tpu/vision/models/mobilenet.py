"""MobileNet V1/V2/V3 (reference: python/paddle/vision/models/
mobilenetv1.py, mobilenetv2.py, mobilenetv3.py)."""
from __future__ import annotations

import paddle_tpu.nn as nn
from paddle_tpu.tensor import flatten

__all__ = ["MobileNetV1", "mobilenet_v1", "MobileNetV2", "mobilenet_v2",
           "MobileNetV3Small", "MobileNetV3Large", "mobilenet_v3_small",
           "mobilenet_v3_large"]


def _make_divisible(v, divisor=8, min_value=None):
    min_value = min_value or divisor
    new_v = max(min_value, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


def _no_pretrained(p):
    from paddle_tpu.vision.models.resnet import _no_pretrained as f
    f(p)


class _ConvBNReLU(nn.Sequential):
    def __init__(self, in_c, out_c, k=3, stride=1, groups=1,
                 act=nn.ReLU, norm=nn.BatchNorm2D):
        pad = (k - 1) // 2
        layers = [nn.Conv2D(in_c, out_c, k, stride=stride, padding=pad,
                            groups=groups, bias_attr=False), norm(out_c)]
        if act is not None:
            layers.append(act())
        super().__init__(*layers)


# -- V1 ---------------------------------------------------------------------


class MobileNetV1(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        s = lambda c: max(8, int(c * scale))
        cfg = [  # (out, stride) of depthwise-separable blocks
            (64, 1), (128, 2), (128, 1), (256, 2), (256, 1), (512, 2),
            (512, 1), (512, 1), (512, 1), (512, 1), (512, 1), (1024, 2),
            (1024, 1)]
        layers = [_ConvBNReLU(3, s(32), 3, stride=2)]
        in_c = s(32)
        for out, stride in cfg:
            layers.append(_ConvBNReLU(in_c, in_c, 3, stride=stride,
                                      groups=in_c))  # depthwise
            layers.append(_ConvBNReLU(in_c, s(out), 1))  # pointwise
            in_c = s(out)
        self.features = nn.Sequential(*layers)
        self._out_c = in_c
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(in_c, num_classes)

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = flatten(x, 1)
            x = self.fc(x)
        return x


def mobilenet_v1(pretrained=False, scale=1.0, **kwargs):
    _no_pretrained(pretrained)
    return MobileNetV1(scale=scale, **kwargs)


# -- V2 ---------------------------------------------------------------------


class _InvertedResidual(nn.Layer):
    def __init__(self, inp, oup, stride, expand_ratio):
        super().__init__()
        self.stride = stride
        hidden = int(round(inp * expand_ratio))
        self.use_res = stride == 1 and inp == oup
        layers = []
        if expand_ratio != 1:
            layers.append(_ConvBNReLU(inp, hidden, 1, act=nn.ReLU6))
        layers += [
            _ConvBNReLU(hidden, hidden, 3, stride=stride, groups=hidden,
                        act=nn.ReLU6),
            nn.Conv2D(hidden, oup, 1, bias_attr=False),
            nn.BatchNorm2D(oup),
        ]
        self.conv = nn.Sequential(*layers)

    def forward(self, x):
        return x + self.conv(x) if self.use_res else self.conv(x)


class MobileNetV2(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        cfg = [  # t, c, n, s
            (1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
            (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1)]
        in_c = _make_divisible(32 * scale)
        last = _make_divisible(1280 * max(1.0, scale))
        layers = [_ConvBNReLU(3, in_c, 3, stride=2, act=nn.ReLU6)]
        for t, c, n, s in cfg:
            out = _make_divisible(c * scale)
            for i in range(n):
                layers.append(_InvertedResidual(in_c, out,
                                                s if i == 0 else 1, t))
                in_c = out
        layers.append(_ConvBNReLU(in_c, last, 1, act=nn.ReLU6))
        self.features = nn.Sequential(*layers)
        if with_pool:
            self.pool2d_avg = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Dropout(0.2), nn.Linear(last, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool2d_avg(x)
        if self.num_classes > 0:
            x = flatten(x, 1)
            x = self.classifier(x)
        return x


def mobilenet_v2(pretrained=False, scale=1.0, **kwargs):
    _no_pretrained(pretrained)
    return MobileNetV2(scale=scale, **kwargs)


# -- V3 ---------------------------------------------------------------------


class _SEBlock(nn.Layer):
    def __init__(self, c, r=4):
        super().__init__()
        self.pool = nn.AdaptiveAvgPool2D(1)
        self.fc1 = nn.Conv2D(c, _make_divisible(c // r), 1)
        self.relu = nn.ReLU()
        self.fc2 = nn.Conv2D(_make_divisible(c // r), c, 1)
        self.hsig = nn.Hardsigmoid()

    def forward(self, x):
        s = self.hsig(self.fc2(self.relu(self.fc1(self.pool(x)))))
        return x * s


class _V3Block(nn.Layer):
    def __init__(self, inp, hidden, out, k, stride, use_se, act):
        super().__init__()
        self.use_res = stride == 1 and inp == out
        layers = []
        if hidden != inp:
            layers.append(_ConvBNReLU(inp, hidden, 1, act=act))
        layers.append(_ConvBNReLU(hidden, hidden, k, stride=stride,
                                  groups=hidden, act=act))
        if use_se:
            layers.append(_SEBlock(hidden))
        layers += [nn.Conv2D(hidden, out, 1, bias_attr=False),
                   nn.BatchNorm2D(out)]
        self.block = nn.Sequential(*layers)

    def forward(self, x):
        return x + self.block(x) if self.use_res else self.block(x)


_V3_SMALL = [  # k, exp, out, se, act, stride
    (3, 16, 16, True, "relu", 2), (3, 72, 24, False, "relu", 2),
    (3, 88, 24, False, "relu", 1), (5, 96, 40, True, "hard", 2),
    (5, 240, 40, True, "hard", 1), (5, 240, 40, True, "hard", 1),
    (5, 120, 48, True, "hard", 1), (5, 144, 48, True, "hard", 1),
    (5, 288, 96, True, "hard", 2), (5, 576, 96, True, "hard", 1),
    (5, 576, 96, True, "hard", 1)]

_V3_LARGE = [
    (3, 16, 16, False, "relu", 1), (3, 64, 24, False, "relu", 2),
    (3, 72, 24, False, "relu", 1), (5, 72, 40, True, "relu", 2),
    (5, 120, 40, True, "relu", 1), (5, 120, 40, True, "relu", 1),
    (3, 240, 80, False, "hard", 2), (3, 200, 80, False, "hard", 1),
    (3, 184, 80, False, "hard", 1), (3, 184, 80, False, "hard", 1),
    (3, 480, 112, True, "hard", 1), (3, 672, 112, True, "hard", 1),
    (5, 672, 160, True, "hard", 2), (5, 960, 160, True, "hard", 1),
    (5, 960, 160, True, "hard", 1)]


class _MobileNetV3(nn.Layer):
    def __init__(self, cfg, last_c, scale=1.0, num_classes=1000,
                 with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        in_c = _make_divisible(16 * scale)
        layers = [_ConvBNReLU(3, in_c, 3, stride=2, act=nn.Hardswish)]
        for k, exp, out, se, act, stride in cfg:
            a = nn.ReLU if act == "relu" else nn.Hardswish
            layers.append(_V3Block(in_c, _make_divisible(exp * scale),
                                   _make_divisible(out * scale), k, stride,
                                   se, a))
            in_c = _make_divisible(out * scale)
        last_exp = _make_divisible(cfg[-1][1] * scale)
        layers.append(_ConvBNReLU(in_c, last_exp, 1, act=nn.Hardswish))
        self.features = nn.Sequential(*layers)
        self.lastconv_c = last_exp
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Linear(last_exp, last_c), nn.Hardswish(), nn.Dropout(0.2),
                nn.Linear(last_c, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = flatten(x, 1)
            x = self.classifier(x)
        return x


class MobileNetV3Small(_MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_V3_SMALL, 1024, scale, num_classes, with_pool)


class MobileNetV3Large(_MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_V3_LARGE, 1280, scale, num_classes, with_pool)


def mobilenet_v3_small(pretrained=False, scale=1.0, **kwargs):
    _no_pretrained(pretrained)
    return MobileNetV3Small(scale=scale, **kwargs)


def mobilenet_v3_large(pretrained=False, scale=1.0, **kwargs):
    _no_pretrained(pretrained)
    return MobileNetV3Large(scale=scale, **kwargs)
