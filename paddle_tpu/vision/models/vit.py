"""Vision Transformer (reference: python/paddle/vision/models — the
reference fork ships ViT via paddle.vision transformer models; patch-embed
+ pre-norm encoder. TPU-friendly: all matmuls batched, bf16-ready)."""
from __future__ import annotations

import numpy as np

import paddle_tpu.nn as nn
from paddle_tpu.core.tensor import Parameter
from paddle_tpu.tensor import concat, expand, transpose

__all__ = ["VisionTransformer", "vit_b_16", "vit_b_32", "vit_l_16",
           "vit_s_16"]


class _MLP(nn.Layer):
    def __init__(self, d, hidden, dropout=0.0):
        super().__init__()
        self.fc1 = nn.Linear(d, hidden)
        self.act = nn.GELU()
        self.fc2 = nn.Linear(hidden, d)
        self.drop = nn.Dropout(dropout)

    def forward(self, x):
        return self.drop(self.fc2(self.drop(self.act(self.fc1(x)))))


class _Block(nn.Layer):
    def __init__(self, d, heads, mlp_ratio=4.0, dropout=0.0,
                 attn_dropout=0.0):
        super().__init__()
        self.norm1 = nn.LayerNorm(d)
        self.attn = nn.MultiHeadAttention(d, heads, dropout=attn_dropout)
        self.norm2 = nn.LayerNorm(d)
        self.mlp = _MLP(d, int(d * mlp_ratio), dropout)

    def forward(self, x):
        x = x + self.attn(self.norm1(x))
        x = x + self.mlp(self.norm2(x))
        return x


class VisionTransformer(nn.Layer):
    def __init__(self, image_size=224, patch_size=16, embed_dim=768,
                 depth=12, num_heads=12, mlp_ratio=4.0, num_classes=1000,
                 dropout=0.0, attn_dropout=0.0):
        super().__init__()
        assert image_size % patch_size == 0
        self.num_classes = num_classes
        num_patches = (image_size // patch_size) ** 2
        self.patch_embed = nn.Conv2D(3, embed_dim, patch_size,
                                     stride=patch_size)
        rng = np.random.RandomState(0)
        self.cls_token = Parameter(
            (rng.randn(1, 1, embed_dim) * 0.02).astype("float32"),
            name="cls_token")
        self.pos_embed = Parameter(
            (rng.randn(1, num_patches + 1, embed_dim) * 0.02)
            .astype("float32"), name="pos_embed")
        self.pos_drop = nn.Dropout(dropout)
        self.blocks = nn.LayerList([
            _Block(embed_dim, num_heads, mlp_ratio, dropout, attn_dropout)
            for _ in range(depth)])
        self.norm = nn.LayerNorm(embed_dim)
        if num_classes > 0:
            self.head = nn.Linear(embed_dim, num_classes)

    def forward(self, x):
        B = x.shape[0]
        x = self.patch_embed(x)                       # B, D, H/P, W/P
        from paddle_tpu.tensor import reshape
        x = reshape(x, [B, x.shape[1], -1])           # B, D, N
        x = transpose(x, [0, 2, 1])                   # B, N, D
        cls = expand(self.cls_token, [B, 1, x.shape[2]])
        x = concat([cls, x], axis=1)
        x = self.pos_drop(x + self.pos_embed)
        for blk in self.blocks:
            x = blk(x)
        x = self.norm(x)
        cls_out = x[:, 0]
        if self.num_classes > 0:
            return self.head(cls_out)
        return cls_out


def _vit(pretrained, **kwargs):
    from paddle_tpu.vision.models.resnet import _no_pretrained
    _no_pretrained(pretrained)
    return VisionTransformer(**kwargs)


def vit_s_16(pretrained=False, **kwargs):
    return _vit(pretrained, embed_dim=384, depth=12, num_heads=6, **kwargs)


def vit_b_16(pretrained=False, **kwargs):
    return _vit(pretrained, patch_size=16, **kwargs)


def vit_b_32(pretrained=False, **kwargs):
    return _vit(pretrained, patch_size=32, **kwargs)


def vit_l_16(pretrained=False, **kwargs):
    return _vit(pretrained, embed_dim=1024, depth=24, num_heads=16, **kwargs)
