"""Vision ops (reference: python/paddle/vision/ops.py — nms, roi_align,
roi_pool, deform_conv2d, ...). TPU note: these are host-light ops used in
detection pipelines; nms is implemented with a fixed-iteration lax loop so
it can live inside jit when needed.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from paddle_tpu.core.tensor import Tensor
from paddle_tpu.nn.layer.layers import Layer as _Layer

__all__ = ["nms", "roi_align", "roi_pool", "box_area", "box_iou"]


def _val(x):
    return x._value if isinstance(x, Tensor) else jnp.asarray(x)


def box_area(boxes):
    b = _val(boxes)
    return Tensor((b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1]))


def _iou_matrix(a, b):
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.clip(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    area_a = (a[:, 2] - a[:, 0]) * (a[:, 3] - a[:, 1])
    area_b = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    return inter / (area_a[:, None] + area_b[None, :] - inter + 1e-10)


def box_iou(boxes1, boxes2):
    return Tensor(_iou_matrix(_val(boxes1), _val(boxes2)))


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """Hard NMS (reference ops.py nms). Returns kept indices sorted by
    score desc. Category-aware when category_idxs given."""
    b = np.asarray(_val(boxes))
    n = b.shape[0]
    s = np.arange(n, 0, -1, dtype=np.float32) if scores is None \
        else np.asarray(_val(scores))
    if category_idxs is not None:
        # offset boxes per category so cross-category boxes never overlap
        cat = np.asarray(_val(category_idxs))
        offset = (b.max() - b.min() + 1) * cat.astype(b.dtype)
        b = b + offset[:, None]
    order = np.argsort(-s)
    keep = []
    iou = np.asarray(_iou_matrix(jnp.asarray(b), jnp.asarray(b)))
    suppressed = np.zeros(n, dtype=bool)
    for i in order:
        if suppressed[i]:
            continue
        keep.append(i)
        suppressed |= iou[i] > iou_threshold
        suppressed[i] = True
    keep = np.asarray(keep, dtype=np.int64)
    if top_k is not None:
        keep = keep[:top_k]
    return Tensor(keep)


def _bilinear_sample(feat, y, x):
    """feat: (C,H,W); y,x: scalar grids (...,) -> (C, ...)"""
    H, W = feat.shape[1], feat.shape[2]
    y0 = jnp.clip(jnp.floor(y), 0, H - 1)
    x0 = jnp.clip(jnp.floor(x), 0, W - 1)
    y1 = jnp.clip(y0 + 1, 0, H - 1)
    x1 = jnp.clip(x0 + 1, 0, W - 1)
    wy1 = jnp.clip(y - y0, 0, 1)
    wx1 = jnp.clip(x - x0, 0, 1)
    wy0, wx0 = 1 - wy1, 1 - wx1
    y0i, y1i, x0i, x1i = (v.astype(jnp.int32) for v in (y0, y1, x0, x1))
    v00 = feat[:, y0i, x0i]
    v01 = feat[:, y0i, x1i]
    v10 = feat[:, y1i, x0i]
    v11 = feat[:, y1i, x1i]
    return (v00 * (wy0 * wx0) + v01 * (wy0 * wx1)
            + v10 * (wy1 * wx0) + v11 * (wy1 * wx1))


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    """RoIAlign (reference ops.py roi_align). x: (N,C,H,W); boxes: (R,4)
    x1,y1,x2,y2; boxes_num: rois per image."""
    xv = _val(x)
    bv = _val(boxes)
    nums = np.asarray(_val(boxes_num)).astype(int)
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    ratio = 2 if sampling_ratio <= 0 else sampling_ratio
    off = 0.5 if aligned else 0.0

    outs = []
    img_ids = np.repeat(np.arange(len(nums)), nums)
    for r in range(bv.shape[0]):
        feat = xv[int(img_ids[r])]
        x1, y1, x2, y2 = [bv[r, i] * spatial_scale - off for i in range(4)]
        rw = jnp.maximum(x2 - x1, 1e-3 if aligned else 1.0)
        rh = jnp.maximum(y2 - y1, 1e-3 if aligned else 1.0)
        bin_h, bin_w = rh / ph, rw / pw
        iy = (jnp.arange(ph * ratio) + 0.5) / ratio
        ix = (jnp.arange(pw * ratio) + 0.5) / ratio
        ys = y1 + iy * bin_h  # (ph*ratio,)
        xs = x1 + ix * bin_w
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        samples = _bilinear_sample(feat, gy, gx)  # (C, ph*r, pw*r)
        C = samples.shape[0]
        pooled = samples.reshape(C, ph, ratio, pw, ratio).mean((2, 4))
        outs.append(pooled)
    return Tensor(jnp.stack(outs))


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    """Max RoI pooling (reference ops.py roi_pool)."""
    xv = _val(x)
    bv = np.asarray(_val(boxes))
    nums = np.asarray(_val(boxes_num)).astype(int)
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    H, W = xv.shape[2], xv.shape[3]
    img_ids = np.repeat(np.arange(len(nums)), nums)
    outs = []
    for r in range(bv.shape[0]):
        feat = xv[int(img_ids[r])]
        x1 = int(np.round(bv[r, 0] * spatial_scale))
        y1 = int(np.round(bv[r, 1] * spatial_scale))
        x2 = max(int(np.round(bv[r, 2] * spatial_scale)) + 1, x1 + 1)
        y2 = max(int(np.round(bv[r, 3] * spatial_scale)) + 1, y1 + 1)
        x2, y2 = min(x2, W), min(y2, H)
        roi = feat[:, y1:y2, x1:x2]
        C, rh, rw = roi.shape
        cells = []
        ys = np.linspace(0, rh, ph + 1).astype(int)
        xs = np.linspace(0, rw, pw + 1).astype(int)
        for i in range(ph):
            for j in range(pw):
                sub = roi[:, ys[i]:max(ys[i + 1], ys[i] + 1),
                          xs[j]:max(xs[j + 1], xs[j] + 1)]
                cells.append(sub.max((1, 2)))
        outs.append(jnp.stack(cells, 1).reshape(C, ph, pw))
    return Tensor(jnp.stack(outs))


# ---------------------------------------------------------------------------
# deformable convolution (reference: vision/ops.py deform_conv2d ->
# CUDA kernel phi/kernels/gpu/deformable_conv_kernel.cu; here: offset
# sampling IS grid_sample-style bilinear gathers, which XLA fuses)
# ---------------------------------------------------------------------------

def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """x: (N, Cin, H, W); offset: (N, 2*dg*kh*kw, Ho, Wo);
    weight: (Cout, Cin/g, kh, kw); mask (v2): (N, dg*kh*kw, Ho, Wo)."""
    from paddle_tpu.core.dispatch import dispatch, OpDef

    st = (stride, stride) if isinstance(stride, int) else tuple(stride)
    pd = (padding, padding) if isinstance(padding, int) else tuple(padding)
    dl = (dilation, dilation) if isinstance(dilation, int) else tuple(dilation)

    # im2col per kernel tap: bilinear-gather each tap's samples, then one
    # big matmul against the reshaped weights (MXU-friendly)
    def f2(xa, off, w, b, m):
        n, cin, h, wd = xa.shape
        cout, cin_g, kh, kw = w.shape
        ho = (h + 2 * pd[0] - dl[0] * (kh - 1) - 1) // st[0] + 1
        wo = (wd + 2 * pd[1] - dl[1] * (kw - 1) - 1) // st[1] + 1
        xp = jnp.pad(xa, ((0, 0), (0, 0), (pd[0], pd[0]), (pd[1], pd[1])))
        hp, wp = xp.shape[2], xp.shape[3]
        off_r = off.reshape(n, deformable_groups, kh * kw, 2, ho, wo)
        m_r = (m.reshape(n, deformable_groups, kh * kw, ho, wo)
               if m is not None else None)
        oy = (jnp.arange(ho) * st[0])[:, None]
        ox = (jnp.arange(wo) * st[1])[None, :]
        cg = cin // deformable_groups
        cols = []
        for t in range(kh * kw):
            ki, kj = t // kw, t % kw
            sy = oy + ki * dl[0] + off_r[:, :, t, 0]       # (n, dg, ho, wo)
            sx = ox + kj * dl[1] + off_r[:, :, t, 1]
            y0 = jnp.floor(sy)
            x0 = jnp.floor(sx)
            wy = (sy - y0)[..., None]
            wx = (sx - x0)[..., None]

            def gat(yy, xx):
                inb = ((yy >= 0) & (yy < hp) & (xx >= 0) & (xx < wp))
                yc = jnp.clip(yy.astype(jnp.int32), 0, hp - 1)
                xc = jnp.clip(xx.astype(jnp.int32), 0, wp - 1)
                xg = xp.reshape(n, deformable_groups, cg, hp, wp)
                xg = jnp.moveaxis(xg, 2, 4)                # n,dg,hp,wp,cg
                bidx = jnp.arange(n)[:, None, None, None]
                gidx = jnp.arange(deformable_groups)[None, :, None, None]
                v = xg[bidx, gidx, yc, xc]                 # n,dg,ho,wo,cg
                return v * inb[..., None]

            val = (gat(y0, x0) * (1 - wy) * (1 - wx)
                   + gat(y0, x0 + 1) * (1 - wy) * wx
                   + gat(y0 + 1, x0) * wy * (1 - wx)
                   + gat(y0 + 1, x0 + 1) * wy * wx)
            if m_r is not None:
                val = val * m_r[:, :, t][..., None]
            cols.append(val)                               # n,dg,ho,wo,cg
        col = jnp.stack(cols, axis=-2)                 # n,dg,ho,wo,t,cg
        # channel order must match the weight's: original cin order is
        # [dg, cg] contiguous, so arrange (tap, dg, cg) and contract taps
        # and channels together
        col = jnp.moveaxis(col, 1, 4)                  # n,ho,wo,t,dg,cg
        col = col.reshape(n, ho, wo, kh * kw, cin)
        col_g = col.reshape(n, ho, wo, kh * kw, groups, cin_g)
        wg = w.reshape(groups, cout // groups, cin_g, kh, kw)
        wg = wg.reshape(groups, cout // groups, cin_g, kh * kw)
        out = jnp.einsum("nhwtgc,goct->ngohw", col_g, wg)
        out = out.reshape(n, cout, ho, wo)
        if b is not None:
            out = out + b.reshape(1, -1, 1, 1)
        return out

    return dispatch(OpDef("vision.deform_conv2d", f2),
                    (x, offset, weight, bias, mask), {})


class DeformConv2D(_Layer):
    """Layer form (reference: vision/ops.py DeformConv2D)."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        from paddle_tpu import nn
        ks = (kernel_size, kernel_size) if isinstance(kernel_size, int) \
            else tuple(kernel_size)
        self._conv_args = (stride, padding, dilation, deformable_groups,
                           groups)
        fan_in = in_channels // groups * ks[0] * ks[1]
        bound = 1.0 / np.sqrt(fan_in)
        self.weight = self.create_parameter(
            [out_channels, in_channels // groups, ks[0], ks[1]],
            attr=weight_attr,
            default_initializer=nn.initializer.Uniform(-bound, bound))
        self.bias = (None if bias_attr is False else
                     self.create_parameter([out_channels], attr=bias_attr,
                                           is_bias=True))

    def forward(self, x, offset, mask=None):
        stride, padding, dilation, dg, groups = self._conv_args
        return deform_conv2d(x, offset, self.weight, self.bias, stride,
                             padding, dilation, dg, groups, mask)


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0,
               name=None):
    """Position-sensitive RoI pooling (reference: vision/ops.py psroi_pool).
    x channels = out_channels * ph * pw; each bin pools its own channel
    group (average pooling within bin)."""
    xa, ba = _val(x), _val(boxes)
    ph = pw = output_size if isinstance(output_size, int) else None
    if ph is None:
        ph, pw = output_size
    n, c, h, w = xa.shape
    out_c = c // (ph * pw)
    outs = []
    bi = 0
    counts = np.asarray(_val(boxes_num)).tolist()
    for img, cnt in enumerate(counts):
        for k in range(cnt):
            x1, y1, x2, y2 = [float(v) for v in np.asarray(ba[bi])]
            bi += 1
            rx1, ry1 = x1 * spatial_scale, y1 * spatial_scale
            rx2, ry2 = x2 * spatial_scale, y2 * spatial_scale
            bh = max((ry2 - ry1) / ph, 0.1)
            bw = max((rx2 - rx1) / pw, 0.1)
            bins = []
            feat = xa[img].reshape(out_c, ph * pw, h, w)
            for i in range(ph):
                row = []
                for j in range(pw):
                    y0 = int(np.floor(ry1 + i * bh))
                    y2b = max(int(np.ceil(ry1 + (i + 1) * bh)), y0 + 1)
                    x0 = int(np.floor(rx1 + j * bw))
                    x2b = max(int(np.ceil(rx1 + (j + 1) * bw)), x0 + 1)
                    y0, y2b = np.clip([y0, y2b], 0, h)
                    x0, x2b = np.clip([x0, x2b], 0, w)
                    if y2b <= y0 or x2b <= x0:
                        row.append(jnp.zeros((out_c,), xa.dtype))
                    else:
                        region = feat[:, i * pw + j, y0:y2b, x0:x2b]
                        row.append(jnp.mean(region, axis=(1, 2)))
                bins.append(jnp.stack(row, axis=-1))
            outs.append(jnp.stack(bins, axis=-2))          # (C, ph, pw)
    return Tensor(jnp.stack(outs) if outs else
                  jnp.zeros((0, out_c, ph, pw), xa.dtype))


class _RoILayerBase(_Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._output_size = output_size
        self._spatial_scale = spatial_scale


class PSRoIPool(_RoILayerBase):
    def forward(self, x, boxes, boxes_num):
        return psroi_pool(x, boxes, boxes_num, self._output_size,
                          self._spatial_scale)


class RoIAlign(_RoILayerBase):
    def forward(self, x, boxes, boxes_num):
        return roi_align(x, boxes, boxes_num, self._output_size,
                         self._spatial_scale)


class RoIPool(_RoILayerBase):
    def forward(self, x, boxes, boxes_num):
        return roi_pool(x, boxes, boxes_num, self._output_size,
                        self._spatial_scale)


def box_coder(prior_box, prior_box_var, target_box, code_type="encode_center_size",
              box_normalized=True, axis=0, name=None):
    """Encode/decode bboxes against anchors (reference: vision/ops.py
    box_coder)."""
    pb, tb = _val(prior_box), _val(target_box)
    pv = (_val(prior_box_var) if prior_box_var is not None
          and not isinstance(prior_box_var, (list, tuple))
          else (jnp.asarray(prior_box_var, jnp.float32)
                if prior_box_var is not None else None))
    norm = 0.0 if box_normalized else 1.0
    pw = pb[:, 2] - pb[:, 0] + norm
    phh = pb[:, 3] - pb[:, 1] + norm
    pcx = pb[:, 0] + pw * 0.5
    pcy = pb[:, 1] + phh * 0.5
    if code_type == "encode_center_size":
        tw = tb[:, 2] - tb[:, 0] + norm
        th = tb[:, 3] - tb[:, 1] + norm
        tcx = tb[:, 0] + tw * 0.5
        tcy = tb[:, 1] + th * 0.5
        dx = (tcx[:, None] - pcx[None, :]) / pw[None, :]
        dy = (tcy[:, None] - pcy[None, :]) / phh[None, :]
        dw = jnp.log(tw[:, None] / pw[None, :])
        dh = jnp.log(th[:, None] / phh[None, :])
        out = jnp.stack([dx, dy, dw, dh], axis=-1)
        if pv is not None:
            out = out / pv.reshape(1, -1, 4) if pv.ndim == 2 else out / pv
        return Tensor(out)
    # decode_center_size: target (N, M, 4) deltas against priors
    d = tb
    if d.ndim == 2:
        d = d[:, None, :]
    if pv is not None:
        d = d * (pv.reshape(1, 1, 4) if pv.ndim == 1 else pv[None])
    if axis == 0:
        pcx_, pcy_, pw_, ph_ = (pcx[None, :], pcy[None, :], pw[None, :],
                                phh[None, :])
    else:
        pcx_, pcy_, pw_, ph_ = (pcx[:, None], pcy[:, None], pw[:, None],
                                phh[:, None])
    ocx = pcx_ + d[..., 0] * pw_
    ocy = pcy_ + d[..., 1] * ph_
    ow = jnp.exp(d[..., 2]) * pw_
    oh = jnp.exp(d[..., 3]) * ph_
    out = jnp.stack([ocx - ow * 0.5, ocy - oh * 0.5,
                     ocx + ow * 0.5 - norm, ocy + oh * 0.5 - norm], axis=-1)
    return Tensor(out)


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=[1.0],
              variance=[0.1, 0.1, 0.2, 0.2], flip=False, clip=False,
              steps=[0.0, 0.0], offset=0.5, min_max_aspect_ratios_order=False,
              name=None):
    """SSD prior boxes (reference: vision/ops.py prior_box)."""
    fa, ia = _val(input), _val(image)
    fh, fw = fa.shape[2], fa.shape[3]
    ih, iw = ia.shape[2], ia.shape[3]
    step_h = steps[1] or ih / fh
    step_w = steps[0] or iw / fw
    ars = list(aspect_ratios)
    if flip:
        ars = ars + [1.0 / a for a in aspect_ratios if a != 1.0]
    boxes = []
    for y in range(fh):
        for x in range(fw):
            cx = (x + offset) * step_w
            cy = (y + offset) * step_h
            cell = []
            for si, ms in enumerate(min_sizes):
                def _min_box():
                    bw = bh = ms / 2
                    cell.append([(cx - bw) / iw, (cy - bh) / ih,
                                 (cx + bw) / iw, (cy + bh) / ih])

                def _max_box():
                    if max_sizes:
                        s = np.sqrt(ms * max_sizes[si])
                        cell.append([(cx - s / 2) / iw, (cy - s / 2) / ih,
                                     (cx + s / 2) / iw, (cy + s / 2) / ih])

                def _ar_boxes(skip_one):
                    for a in ars:
                        if skip_one and abs(a - 1.0) < 1e-6:
                            continue
                        bw = ms * np.sqrt(a) / 2
                        bh = ms / np.sqrt(a) / 2
                        cell.append([(cx - bw) / iw, (cy - bh) / ih,
                                     (cx + bw) / iw, (cy + bh) / ih])

                if min_max_aspect_ratios_order:
                    # reference flag: [min, max, other-ars]
                    _min_box()
                    _max_box()
                    _ar_boxes(skip_one=True)
                else:
                    _ar_boxes(skip_one=False)
                    _max_box()
            boxes.append(cell)
    out = np.asarray(boxes, np.float32).reshape(fh, fw, -1, 4)
    if clip:
        out = np.clip(out, 0.0, 1.0)
    var = np.tile(np.asarray(variance, np.float32),
                  (fh, fw, out.shape[2], 1))
    return Tensor(jnp.asarray(out)), Tensor(jnp.asarray(var))


def yolo_box(x, img_size, anchors, class_num, conf_thresh=0.01,
             downsample_ratio=32, clip_bbox=True, name=None, scale_x_y=1.0,
             iou_aware=False, iou_aware_factor=0.5):
    """Decode YOLOv3 head output to boxes+scores (reference: vision/ops.py
    yolo_box)."""
    xa = _val(x)
    n, c, h, w = xa.shape
    na = len(anchors) // 2
    an = np.asarray(anchors, np.float32).reshape(na, 2)
    ioup = None
    if iou_aware:
        # layout (reference kernel yolo_box_op): first na channels are the
        # IoU predictions, then the regular na*(5+cls) head
        ioup = 1 / (1 + jnp.exp(-xa[:, :na].reshape(n, na, h, w)))
        xa = xa[:, na:]
    pred = xa.reshape(n, na, 5 + class_num, h, w)
    img = np.asarray(_val(img_size)).reshape(n, 2)
    gx = jnp.arange(w, dtype=jnp.float32)[None, None, None, :]
    gy = jnp.arange(h, dtype=jnp.float32)[None, None, :, None]
    sig = lambda t: 1 / (1 + jnp.exp(-t))
    bx = (sig(pred[:, :, 0]) * scale_x_y - (scale_x_y - 1) / 2 + gx) / w
    by = (sig(pred[:, :, 1]) * scale_x_y - (scale_x_y - 1) / 2 + gy) / h
    bw = jnp.exp(pred[:, :, 2]) * an[None, :, 0, None, None] / (
        downsample_ratio * w)
    bh = jnp.exp(pred[:, :, 3]) * an[None, :, 1, None, None] / (
        downsample_ratio * h)
    conf = sig(pred[:, :, 4])
    if ioup is not None:
        conf = (conf ** (1 - iou_aware_factor)) * (ioup ** iou_aware_factor)
    cls = sig(pred[:, :, 5:])
    scores = cls * conf[:, :, None]
    ih = img[:, 0].reshape(n, 1, 1, 1)
    iw = img[:, 1].reshape(n, 1, 1, 1)
    x1 = (bx - bw / 2) * iw
    y1 = (by - bh / 2) * ih
    x2 = (bx + bw / 2) * iw
    y2 = (by + bh / 2) * ih
    if clip_bbox:
        x1 = jnp.clip(x1, 0, iw - 1)
        y1 = jnp.clip(y1, 0, ih - 1)
        x2 = jnp.clip(x2, 0, iw - 1)
        y2 = jnp.clip(y2, 0, ih - 1)
    keep = (conf > conf_thresh)[:, :, :, :, None]
    boxes = jnp.stack([x1, y1, x2, y2], axis=-1) * keep
    boxes = boxes.reshape(n, -1, 4)
    scores = jnp.moveaxis(scores, 2, -1) * keep
    scores = scores.reshape(n, -1, class_num)
    return Tensor(boxes), Tensor(scores)


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio, gt_score=None,
              use_label_smooth=True, name=None, scale_x_y=1.0):
    raise NotImplementedError(
        "yolo_loss: compose yolo_box decode with the generic detection "
        "losses (bce/iou) — the fused CUDA training loss has no TPU "
        "equivalent; PaddleDetection-style models should compute the loss "
        "from yolo_box outputs")


def matrix_nms(bboxes, scores, score_threshold, post_threshold, nms_top_k,
               keep_top_k, use_gaussian=False, gaussian_sigma=2.0,
               background_label=0, normalized=True, return_index=False,
               return_rois_num=True, name=None):
    """Matrix NMS (reference: vision/ops.py matrix_nms, SOLOv2) — decayed
    scores instead of hard suppression; fully vectorized."""
    ba = np.asarray(_val(bboxes))
    sa = np.asarray(_val(scores))
    n, c, m = sa.shape
    all_out, all_idx, rois_num = [], [], []
    for b in range(n):
        dets = []
        for cls in range(c):
            if cls == background_label:
                continue
            sc = sa[b, cls]
            keep = np.nonzero(sc > score_threshold)[0]
            if keep.size == 0:
                continue
            order = keep[np.argsort(-sc[keep])][:nms_top_k]
            bx = ba[b, order]
            ss = sc[order]
            ious = np.asarray(_iou_matrix(jnp.asarray(bx), jnp.asarray(bx)))
            ious = np.triu(ious, 1)
            ious_cmax = ious.max(0)
            # decay_j = min_i f(iou_ij, cmax_i): the compensation term is
            # the HIGHER-scored box i's cmax (reference kernel
            # matrix_nms_kernel.cc:64 decay_score)
            comp = ious_cmax[:, None]
            with np.errstate(divide="ignore", invalid="ignore"):
                if use_gaussian:
                    dmat = np.exp((comp ** 2 - ious ** 2) * gaussian_sigma)
                else:
                    dmat = (1.0 - ious) / (1.0 - comp)
            # only pairs where i outranks j (upper triangle) decay j
            dmat = np.where(np.triu(np.ones_like(dmat), 1) > 0, dmat, 1.0)
            decay = dmat.min(0)
            dec = ss * decay
            for i, od in enumerate(order):
                if dec[i] >= post_threshold:
                    dets.append((cls, dec[i], *bx[i], b * c * m + cls * m
                                 + od))
        dets.sort(key=lambda d: -d[1])
        dets = dets[:keep_top_k]
        rois_num.append(len(dets))
        for d in dets:
            all_out.append(d[:6])
            all_idx.append(d[6])
    out = Tensor(jnp.asarray(np.asarray(all_out, np.float32).reshape(
        -1, 6)))
    res = [out]
    if return_index:
        res.append(Tensor(jnp.asarray(np.asarray(all_idx, np.int32))))
    if return_rois_num:
        res.append(Tensor(jnp.asarray(np.asarray(rois_num, np.int32))))
    return tuple(res) if len(res) > 1 else out


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, pixel_offset=False,
                             rois_num=None, name=None):
    """Assign RoIs to FPN levels by scale (reference: vision/ops.py
    distribute_fpn_proposals)."""
    rois = np.asarray(_val(fpn_rois))
    off = 1.0 if pixel_offset else 0.0
    ws = rois[:, 2] - rois[:, 0] + off
    hs = rois[:, 3] - rois[:, 1] + off
    scale = np.sqrt(np.maximum(ws * hs, 1e-6))
    lvl = np.floor(np.log2(scale / refer_scale + 1e-8)) + refer_level
    lvl = np.clip(lvl, min_level, max_level).astype(np.int64)
    if rois_num is not None:
        per_img = np.asarray(_val(rois_num)).ravel().tolist()
    else:
        per_img = [len(rois)]
    img_of = np.repeat(np.arange(len(per_img)), per_img)
    multi, order, nums = [], [], []
    for L in range(min_level, max_level + 1):
        # within a level, keep image-major order and report per-image
        # counts (reference: distribute_fpn_proposals rois_num path)
        idx = np.nonzero(lvl == L)[0]
        idx = idx[np.argsort(img_of[idx], kind="stable")]
        multi.append(Tensor(jnp.asarray(rois[idx])))
        order.extend(idx.tolist())
        nums.append(Tensor(jnp.asarray(np.asarray(
            [int((img_of[idx] == im).sum()) for im in
             range(len(per_img))], np.int32))))
    restore = np.zeros(len(rois), np.int32)
    restore[np.asarray(order, np.int32)] = np.arange(len(rois),
                                                     dtype=np.int32)
    return multi, Tensor(jnp.asarray(restore.reshape(-1, 1))), nums


def generate_proposals(scores, bbox_deltas, img_size, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       pixel_offset=False, return_rois_num=False,
                       name=None):
    """RPN proposal generation (reference: vision/ops.py
    generate_proposals): decode deltas on anchors -> clip -> filter small
    -> NMS."""
    sa = np.asarray(_val(scores))          # (N, A, H, W)
    da = np.asarray(_val(bbox_deltas))     # (N, 4A, H, W)
    an = np.asarray(_val(anchors)).reshape(-1, 4)
    va = np.asarray(_val(variances)).reshape(-1, 4)
    ims = np.asarray(_val(img_size))
    n = sa.shape[0]
    outs, nums, out_scores = [], [], []
    for b in range(n):
        s = sa[b].transpose(1, 2, 0).ravel()
        d = da[b].reshape(-1, 4, sa.shape[2], sa.shape[3]) \
            .transpose(2, 3, 0, 1).reshape(-1, 4)
        order = np.argsort(-s)[:pre_nms_top_n]
        s, d, A = s[order], d[order], an[order % len(an)] \
            if len(an) != len(s) else an[order]
        V = va[order % len(va)] if len(va) != len(s) else va[order]
        aw = A[:, 2] - A[:, 0] + (1.0 if pixel_offset else 0.0)
        ah = A[:, 3] - A[:, 1] + (1.0 if pixel_offset else 0.0)
        acx = A[:, 0] + aw / 2
        acy = A[:, 1] + ah / 2
        cx = acx + d[:, 0] * V[:, 0] * aw
        cy = acy + d[:, 1] * V[:, 1] * ah
        w = aw * np.exp(np.minimum(d[:, 2] * V[:, 2], 10))
        h = ah * np.exp(np.minimum(d[:, 3] * V[:, 3], 10))
        boxes = np.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2],
                         axis=1)
        ih, iw = ims[b, 0], ims[b, 1]
        boxes[:, 0::2] = np.clip(boxes[:, 0::2], 0, iw)
        boxes[:, 1::2] = np.clip(boxes[:, 1::2], 0, ih)
        keep = ((boxes[:, 2] - boxes[:, 0] >= min_size)
                & (boxes[:, 3] - boxes[:, 1] >= min_size))
        boxes, s = boxes[keep], s[keep]
        if len(boxes):
            kept = nms(Tensor(jnp.asarray(boxes)), nms_thresh,
                       Tensor(jnp.asarray(s)), top_k=post_nms_top_n)
            kidx = np.asarray(kept._value)
            boxes, s = boxes[kidx], s[kidx]
        outs.append(boxes)
        out_scores.append(s)
        nums.append(len(boxes))
    rois = Tensor(jnp.asarray(np.concatenate(outs).astype(np.float32)))
    rscores = Tensor(jnp.asarray(np.concatenate(out_scores)
                                 .astype(np.float32)))
    if return_rois_num:
        return rois, rscores, Tensor(jnp.asarray(np.asarray(nums, np.int32)))
    return rois, rscores


def read_file(filename, name=None):
    with open(filename, "rb") as f:
        data = np.frombuffer(f.read(), np.uint8)
    return Tensor(jnp.asarray(data))


def decode_jpeg(x, mode="unchanged", name=None):
    """(reference: vision/ops.py decode_jpeg — nvjpeg). Host-side PIL."""
    try:
        from PIL import Image
    except ImportError as e:
        raise RuntimeError("decode_jpeg needs Pillow on the host") from e
    import io as _io
    raw = bytes(np.asarray(_val(x)).astype(np.uint8))
    img = Image.open(_io.BytesIO(raw))
    if mode == "gray":
        img = img.convert("L")
    elif mode in ("rgb", "RGB"):
        img = img.convert("RGB")
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[None]
    else:
        arr = arr.transpose(2, 0, 1)
    return Tensor(jnp.asarray(arr))


__all__ += ["deform_conv2d", "DeformConv2D", "psroi_pool", "PSRoIPool",
            "RoIAlign", "RoIPool", "box_coder", "prior_box", "yolo_box",
            "yolo_loss", "matrix_nms", "distribute_fpn_proposals",
            "generate_proposals", "read_file", "decode_jpeg"]
