"""Vision ops (reference: python/paddle/vision/ops.py — nms, roi_align,
roi_pool, deform_conv2d, ...). TPU note: these are host-light ops used in
detection pipelines; nms is implemented with a fixed-iteration lax loop so
it can live inside jit when needed.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from paddle_tpu.core.tensor import Tensor

__all__ = ["nms", "roi_align", "roi_pool", "box_area", "box_iou"]


def _val(x):
    return x._value if isinstance(x, Tensor) else jnp.asarray(x)


def box_area(boxes):
    b = _val(boxes)
    return Tensor((b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1]))


def _iou_matrix(a, b):
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.clip(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    area_a = (a[:, 2] - a[:, 0]) * (a[:, 3] - a[:, 1])
    area_b = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    return inter / (area_a[:, None] + area_b[None, :] - inter + 1e-10)


def box_iou(boxes1, boxes2):
    return Tensor(_iou_matrix(_val(boxes1), _val(boxes2)))


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """Hard NMS (reference ops.py nms). Returns kept indices sorted by
    score desc. Category-aware when category_idxs given."""
    b = np.asarray(_val(boxes))
    n = b.shape[0]
    s = np.arange(n, 0, -1, dtype=np.float32) if scores is None \
        else np.asarray(_val(scores))
    if category_idxs is not None:
        # offset boxes per category so cross-category boxes never overlap
        cat = np.asarray(_val(category_idxs))
        offset = (b.max() - b.min() + 1) * cat.astype(b.dtype)
        b = b + offset[:, None]
    order = np.argsort(-s)
    keep = []
    iou = np.asarray(_iou_matrix(jnp.asarray(b), jnp.asarray(b)))
    suppressed = np.zeros(n, dtype=bool)
    for i in order:
        if suppressed[i]:
            continue
        keep.append(i)
        suppressed |= iou[i] > iou_threshold
        suppressed[i] = True
    keep = np.asarray(keep, dtype=np.int64)
    if top_k is not None:
        keep = keep[:top_k]
    return Tensor(keep)


def _bilinear_sample(feat, y, x):
    """feat: (C,H,W); y,x: scalar grids (...,) -> (C, ...)"""
    H, W = feat.shape[1], feat.shape[2]
    y0 = jnp.clip(jnp.floor(y), 0, H - 1)
    x0 = jnp.clip(jnp.floor(x), 0, W - 1)
    y1 = jnp.clip(y0 + 1, 0, H - 1)
    x1 = jnp.clip(x0 + 1, 0, W - 1)
    wy1 = jnp.clip(y - y0, 0, 1)
    wx1 = jnp.clip(x - x0, 0, 1)
    wy0, wx0 = 1 - wy1, 1 - wx1
    y0i, y1i, x0i, x1i = (v.astype(jnp.int32) for v in (y0, y1, x0, x1))
    v00 = feat[:, y0i, x0i]
    v01 = feat[:, y0i, x1i]
    v10 = feat[:, y1i, x0i]
    v11 = feat[:, y1i, x1i]
    return (v00 * (wy0 * wx0) + v01 * (wy0 * wx1)
            + v10 * (wy1 * wx0) + v11 * (wy1 * wx1))


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True):
    """RoIAlign (reference ops.py roi_align). x: (N,C,H,W); boxes: (R,4)
    x1,y1,x2,y2; boxes_num: rois per image."""
    xv = _val(x)
    bv = _val(boxes)
    nums = np.asarray(_val(boxes_num)).astype(int)
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    ratio = 2 if sampling_ratio <= 0 else sampling_ratio
    off = 0.5 if aligned else 0.0

    outs = []
    img_ids = np.repeat(np.arange(len(nums)), nums)
    for r in range(bv.shape[0]):
        feat = xv[int(img_ids[r])]
        x1, y1, x2, y2 = [bv[r, i] * spatial_scale - off for i in range(4)]
        rw = jnp.maximum(x2 - x1, 1e-3 if aligned else 1.0)
        rh = jnp.maximum(y2 - y1, 1e-3 if aligned else 1.0)
        bin_h, bin_w = rh / ph, rw / pw
        iy = (jnp.arange(ph * ratio) + 0.5) / ratio
        ix = (jnp.arange(pw * ratio) + 0.5) / ratio
        ys = y1 + iy * bin_h  # (ph*ratio,)
        xs = x1 + ix * bin_w
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        samples = _bilinear_sample(feat, gy, gx)  # (C, ph*r, pw*r)
        C = samples.shape[0]
        pooled = samples.reshape(C, ph, ratio, pw, ratio).mean((2, 4))
        outs.append(pooled)
    return Tensor(jnp.stack(outs))


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0):
    """Max RoI pooling (reference ops.py roi_pool)."""
    xv = _val(x)
    bv = np.asarray(_val(boxes))
    nums = np.asarray(_val(boxes_num)).astype(int)
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    H, W = xv.shape[2], xv.shape[3]
    img_ids = np.repeat(np.arange(len(nums)), nums)
    outs = []
    for r in range(bv.shape[0]):
        feat = xv[int(img_ids[r])]
        x1 = int(np.round(bv[r, 0] * spatial_scale))
        y1 = int(np.round(bv[r, 1] * spatial_scale))
        x2 = max(int(np.round(bv[r, 2] * spatial_scale)) + 1, x1 + 1)
        y2 = max(int(np.round(bv[r, 3] * spatial_scale)) + 1, y1 + 1)
        x2, y2 = min(x2, W), min(y2, H)
        roi = feat[:, y1:y2, x1:x2]
        C, rh, rw = roi.shape
        cells = []
        ys = np.linspace(0, rh, ph + 1).astype(int)
        xs = np.linspace(0, rw, pw + 1).astype(int)
        for i in range(ph):
            for j in range(pw):
                sub = roi[:, ys[i]:max(ys[i + 1], ys[i] + 1),
                          xs[j]:max(xs[j + 1], xs[j] + 1)]
                cells.append(sub.max((1, 2)))
        outs.append(jnp.stack(cells, 1).reshape(C, ph, pw))
    return Tensor(jnp.stack(outs))
