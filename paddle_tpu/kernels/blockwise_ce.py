"""Blockwise cross-entropy: hidden -> vocab projection fused with
softmax-CE, streamed over row (sequence) chunks and vocab blocks.

The train path's memory cap (BENCH r04-r05): `models/llama.py` reshapes
the lm_head output to `[-1, vocab]` and hands a [B*S, V] logits tensor
to cross_entropy — at Llama-3 vocab (128256) that tensor dwarfs every
activation and bounds the batch size. The reference keeps a hand-written
fusion library for exactly this (paddle/phi/kernels/fusion/gpu/,
fused_linear + softmax-CE epilogues); the TPU-native equivalent is this
module: the final hidden->vocab matmul and the softmax-CE reduction run
chunk by chunk, so neither forward NOR backward ever materializes the
[B*S, V] logits — the flash-attention treatment (recompute from a saved
row statistic) applied to the loss.

Math (identical to nn/functional/loss.py `_ce_mean_fused`, per row):

    lse_i    = logsumexp_v(x_i . W[:, v])
    picked_i = x_i . W[:, labels_i]
    loss     = sum_i valid_i * (lse_i - picked_i) / max(sum valid, 1)

Forward saves ONLY the per-row lse (N f32) + the valid count; backward
recomputes each chunk's logits from (x, W) and emits

    dlogits = (softmax - onehot) * g * valid / count

chunk by chunk, contracting immediately into dx (chunk, D) and a
running f32 dW accumulator — dlogits never exists at [N, V] either.

Two execution paths behind one `custom_vjp` (the paged-attention
pattern):

- **Pallas (TPU)**: grid (row-chunk, vocab-block) kernels; x chunks and
  W blocks stream through VMEM, the online-softmax state (m, l, picked)
  rides VMEM scratch across the vocab axis; backward is a dx kernel
  (vocab-fast grid, dx scratch) + a dW kernel (row-fast grid, (D, bv)
  f32 scratch) — the flash `_bwd_dkv_kernel` shape. Off-TPU a forced
  `kernel="pallas"` runs `interpret=True` (tier-1 parity coverage).
- **jnp (CPU / fallback)**: `jax.lax.scan` over row chunks (optionally
  an inner `fori_loop` over vocab blocks with online max) — the same
  math, same O(chunk x vocab_block) peak intermediate, XLA-fused.

Shape contract mirrors `paged_attention.decode_shape_problems`: the
AUTO path gates on `ce_shape_problems`, a forced "pallas" turns the
reasons into a ValueError naming every misaligned dim.
"""
from __future__ import annotations

import functools
import os as _os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from paddle_tpu.core.jax_compat import on_tpu as _on_tpu
from paddle_tpu.core.jax_compat import tpu_compiler_params

__all__ = ["blockwise_ce_loss", "ce_shape_problems", "check_ce_shapes",
           "logits_bytes_saved", "dense_logits_bytes"]

_NEG_INF = -1e30

# Pallas vocab-block default: W block (D, bv) bf16 + the (D, bv) f32 dW
# scratch must co-reside in VMEM (at D=4096, bv=512: 4MB + 8MB — tight
# but inside the 16MB budget with the x chunk)
_BLOCK_V = int(_os.environ.get("PADDLE_TPU_BCE_BLOCK_V", 512))


def _prec(dtype):
    return (jax.lax.Precision.DEFAULT
            if dtype in (jnp.bfloat16, jnp.float16)
            else jax.lax.Precision.HIGHEST)


# ---------------------------------------------------------------------------
# shape contract (decode_shape_problems style)
# ---------------------------------------------------------------------------

def ce_shape_problems(n, d, v, chunk, vocab_block=0, interpret=False):
    """Reasons this (n, d, v, chunk, vocab_block) geometry cannot take
    the Pallas blockwise-CE kernels; empty list = supported. The AUTO
    path gates on this, the forced path turns the reasons into a
    ValueError (the `check_decode_shapes` contract)."""
    problems = []
    if chunk < 1:
        problems.append(f"chunk must be >= 1 (got {chunk})")
    if vocab_block < 0:
        problems.append(f"vocab_block must be >= 0 (got {vocab_block})")
    if not interpret:
        # compiled Mosaic wants tileable blocks: the x chunk is
        # (chunk, d), the W block (d, bv) — f32/bf16 sublane + 128-lane
        if d % 128 != 0:
            problems.append(f"hidden % 128 == 0 required on TPU "
                            f"(got d={d})")
        if chunk % 8 != 0:
            problems.append(f"chunk % 8 == 0 required on TPU "
                            f"(got chunk={chunk})")
        bv = vocab_block or _BLOCK_V
        if bv % 128 != 0:
            problems.append(f"vocab_block % 128 == 0 required on TPU "
                            f"(got vocab_block={bv})")
    return problems


def check_ce_shapes(n, d, v, chunk, vocab_block=0, interpret=False):
    """Raise a descriptive ValueError naming every misaligned dim when
    the Pallas path cannot run; no-op when supported."""
    problems = ce_shape_problems(n, d, v, chunk, vocab_block, interpret)
    if problems:
        raise ValueError(
            "blockwise_ce_loss: shapes cannot take the Pallas kernels "
            "— " + "; ".join(problems)
            + '; use kernel="jnp" for the lax.scan fallback')


# ---------------------------------------------------------------------------
# memory accounting (telemetry / bench)
# ---------------------------------------------------------------------------

def dense_logits_bytes(n_rows, vocab, itemsize=2):
    """Bytes of the [N, V] logits tensor the dense loss path
    materializes (forward AND as the dlogits cotangent in backward)."""
    return int(n_rows) * int(vocab) * int(itemsize)


def logits_bytes_saved(n_rows, vocab, chunk, vocab_block=0, itemsize=2):
    """Dense-path logits bytes minus the blockwise path's peak
    O(chunk x vocab_block) logits-shaped intermediate — the
    `train.loss.logits_bytes_saved` gauge."""
    if chunk <= 0:
        return 0
    peak = min(int(chunk), int(n_rows)) * (
        min(int(vocab_block), int(vocab)) if vocab_block else int(vocab)
    ) * int(itemsize)
    return max(0, dense_logits_bytes(n_rows, vocab, itemsize) - peak)


# ---------------------------------------------------------------------------
# jnp fallback: lax.scan over row chunks (+ optional vocab fori)
# ---------------------------------------------------------------------------

def _pad_rows(x, labels, chunk, ignore_index):
    """Pad N up to a chunk multiple: zero rows + ignore_index labels
    (padded rows contribute nothing to loss, count, or gradients)."""
    n = x.shape[0]
    n_pad = -(-n // chunk) * chunk
    if n_pad != n:
        x = jnp.pad(x, ((0, n_pad - n), (0, 0)))
        labels = jnp.pad(labels, (0, n_pad - n),
                         constant_values=ignore_index)
    return x, labels, n_pad


def _chunk_lse_picked(xc, w, labels_c, vocab_block, v_valid):
    """One row chunk's (lse, picked), both f32 (chunk,). With
    vocab_block > 0 the (chunk, V) logits never exist — an inner
    fori_loop keeps the online max/sum state and streams (chunk, bv)
    score blocks (W pre-padded by the caller when V % bv != 0;
    `v_valid` = the real vocab, padded columns masked)."""
    v = v_valid
    prec = _prec(xc.dtype)
    if not vocab_block:
        s = jax.lax.dot_general(
            xc, w, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=prec)
        m = jnp.max(s, axis=-1)
        lse = m + jnp.log(jnp.sum(jnp.exp(s - m[:, None]), axis=-1))
        picked = jnp.take_along_axis(
            s, labels_c[:, None].astype(jnp.int32), axis=-1)[:, 0]
        return lse, picked
    bv = vocab_block
    nv = w.shape[1] // bv          # caller padded V to a bv multiple
    c = xc.shape[0]

    def vb_step(j, carry):
        m, l, picked = carry
        wj = jax.lax.dynamic_slice(w, (0, j * bv), (w.shape[0], bv))
        s = jax.lax.dot_general(
            xc, wj, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=prec)
        col = jax.lax.broadcasted_iota(jnp.int32, (c, bv), 1) + j * bv
        s_m = jnp.where(col < v, s, _NEG_INF)     # v = VALID vocab
        m_new = jnp.maximum(m, jnp.max(s_m, axis=-1))
        l = l * jnp.exp(m - m_new) + jnp.sum(
            jnp.exp(s_m - m_new[:, None]), axis=-1)
        picked = picked + jnp.sum(
            jnp.where(col == labels_c[:, None].astype(jnp.int32),
                      s, 0.0), axis=-1)
        return m_new, l, picked

    m0 = jnp.full((c,), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((c,), jnp.float32)
    p0 = jnp.zeros((c,), jnp.float32)
    m, l, picked = jax.lax.fori_loop(0, nv, vb_step, (m0, l0, p0))
    return m + jnp.log(jnp.maximum(l, 1e-30)), picked


def _pad_vocab(w, vocab_block):
    if not vocab_block:
        return w
    v = w.shape[1]
    v_pad = -(-v // vocab_block) * vocab_block
    if v_pad != v:
        w = jnp.pad(w, ((0, 0), (0, v_pad - v)))
    return w


def _fwd_jnp(x, w, labels, chunk, vocab_block, ignore_index):
    n = x.shape[0]
    xp, lp, n_pad = _pad_rows(x, labels, chunk, ignore_index)
    wp = _pad_vocab(w, vocab_block)
    nc = n_pad // chunk
    xb = xp.reshape(nc, chunk, x.shape[1])
    lb = lp.reshape(nc, chunk)
    # valid vocab stays w.shape[1]: padded columns are masked inside
    v = w.shape[1]

    def row_step(carry, xl):
        loss_sum, count = carry
        xc, lc = xl
        lse, picked = _chunk_lse_picked(xc, wp, lc, vocab_block, v)
        valid = lc != ignore_index
        loss_sum = loss_sum + jnp.sum(jnp.where(valid, lse - picked, 0.0))
        count = count + jnp.sum(valid.astype(jnp.float32))
        return (loss_sum, count), lse

    (loss_sum, count), lses = jax.lax.scan(
        row_step, (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32)),
        (xb, lb))
    count = jnp.maximum(count, 1.0)
    return loss_sum / count, lses, count


def _bwd_jnp(x, w, labels, lses, count, g, chunk, vocab_block,
             ignore_index):
    n, d = x.shape
    v = w.shape[1]
    xp, lp, n_pad = _pad_rows(x, labels, chunk, ignore_index)
    wp = _pad_vocab(w, vocab_block)
    nc = n_pad // chunk
    xb = xp.reshape(nc, chunk, d)
    lb = lp.reshape(nc, chunk)
    prec = _prec(x.dtype)
    gscale = g / count

    def row_step(dw_acc, xl):
        xc, lc, lse_c = xl
        scale = jnp.where(lc != ignore_index, gscale, 0.0)     # (chunk,)
        lab = lc[:, None].astype(jnp.int32)
        if not vocab_block:
            s = jax.lax.dot_general(
                xc, wp, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32, precision=prec)
            p = jnp.exp(s - lse_c[:, None])
            onehot = (jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
                      == lab)
            dvals = ((p - onehot.astype(jnp.float32))
                     * scale[:, None]).astype(xc.dtype)
            dx_c = jax.lax.dot_general(
                dvals, wp, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32, precision=prec)
            dw_acc = dw_acc + jax.lax.dot_general(
                xc, dvals, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32, precision=prec)
            return dw_acc, dx_c
        bv = vocab_block
        nv = wp.shape[1] // bv
        c = xc.shape[0]

        def vb_step(j, carry):
            dx_c, dw_a = carry
            wj = jax.lax.dynamic_slice(wp, (0, j * bv), (d, bv))
            s = jax.lax.dot_general(
                xc, wj, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32, precision=prec)
            col = jax.lax.broadcasted_iota(jnp.int32, (c, bv), 1) + j * bv
            p = jnp.where(col < v, jnp.exp(s - lse_c[:, None]), 0.0)
            dvals = ((p - (col == lab).astype(jnp.float32))
                     * scale[:, None]).astype(xc.dtype)
            dx_c = dx_c + jax.lax.dot_general(
                dvals, wj, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32, precision=prec)
            dw_j = jax.lax.dot_general(
                xc, dvals, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32, precision=prec)
            dw_a = jax.lax.dynamic_update_slice(
                dw_a, jax.lax.dynamic_slice(
                    dw_a, (0, j * bv), (d, bv)) + dw_j, (0, j * bv))
            return dx_c, dw_a

        dx_c, dw_acc = jax.lax.fori_loop(
            0, nv, vb_step, (jnp.zeros((c, d), jnp.float32), dw_acc))
        return dw_acc, dx_c

    dw0 = jnp.zeros((d, wp.shape[1]), jnp.float32)
    dw, dxs = jax.lax.scan(row_step, dw0, (xb, lb, lses))
    dx = dxs.reshape(n_pad, d)[:n].astype(x.dtype)
    return dx, dw[:, :v].astype(w.dtype)


# ---------------------------------------------------------------------------
# Pallas kernels
# ---------------------------------------------------------------------------

def _ce_fwd_kernel(x_ref, w_ref, lab_ref, lse_ref, pk_ref,
                   m_scr, l_scr, pk_scr, *, block_v, v_valid, nv):
    """Grid (row-chunk i, vocab-block j), j fastest. x chunk stays
    resident per i (constant block index elides the DMA); W blocks
    stream; the online-softmax state (m, l) and the picked-logit
    accumulator live in VMEM scratch; lse/picked flush at the last j.

    Everything stays 2D in the flash-kernel idiom (no 1D vectors, no
    int relayouts on TPU): labels arrive as an f32 (1, chunk) row —
    exact for any vocab < 2^24 — and transpose like the flash lse."""
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        pk_scr[...] = jnp.zeros_like(pk_scr)

    c = x_ref.shape[0]
    prec = _prec(x_ref.dtype)
    s = jax.lax.dot_general(
        x_ref[...], w_ref[...], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32, precision=prec)  # (c, bv)
    col = (jax.lax.broadcasted_iota(jnp.int32, (c, block_v), 1)
           + j * block_v).astype(jnp.float32)
    s_m = jnp.where(col < v_valid, s, _NEG_INF)
    lab_t = lab_ref[...].T                                # (c, 1) f32
    m = m_scr[:, :1]
    m_new = jnp.maximum(m, jnp.max(s_m, axis=-1, keepdims=True))
    l_scr[...] = (l_scr[...] * jnp.exp(m - m_new)
                  + jnp.sum(jnp.exp(s_m - m_new), axis=-1,
                            keepdims=True))
    pk_scr[...] += jnp.sum(
        jnp.where(col == lab_t, s, 0.0), axis=-1, keepdims=True)
    m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)

    @pl.when(j == nv - 1)
    def _store():
        lse = m_scr[:, :1] + jnp.log(jnp.maximum(l_scr[:, :1], 1e-30))
        lse_ref[...] = lse.T                              # (1, chunk)
        pk_ref[...] = pk_scr[:, :1].T


def _ce_dx_kernel(x_ref, w_ref, lab_ref, lse_ref, sc_ref, dx_ref,
                  acc_scr, *, block_v, v_valid, nv):
    """dx: grid (i, j) j fastest; dlogits recomputed per (c, bv) block
    from the saved lse, contracted into the (c, D) dx scratch; store at
    the last j. dlogits never exists beyond one block."""
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        acc_scr[...] = jnp.zeros_like(acc_scr)

    c = x_ref.shape[0]
    prec = _prec(x_ref.dtype)
    s = jax.lax.dot_general(
        x_ref[...], w_ref[...], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32, precision=prec)
    col = (jax.lax.broadcasted_iota(jnp.int32, (c, block_v), 1)
           + j * block_v).astype(jnp.float32)
    lse = lse_ref[...].T                                  # (c, 1)
    p = jnp.where(col < v_valid, jnp.exp(s - lse), 0.0)
    onehot = (col == lab_ref[...].T).astype(jnp.float32)
    dvals = ((p - onehot) * sc_ref[...].T).astype(x_ref.dtype)
    acc_scr[...] += jax.lax.dot_general(
        dvals, w_ref[...], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32, precision=prec)

    @pl.when(j == nv - 1)
    def _store():
        dx_ref[...] = acc_scr[...].astype(dx_ref.dtype)


def _ce_dw_kernel(x_ref, w_ref, lab_ref, lse_ref, sc_ref, dw_ref,
                  acc_scr, *, block_v, v_valid, nr):
    """dW: grid (j, i) i fastest; the (D, bv) f32 accumulator sweeps
    every row chunk for one W block and flushes once at the last i (the
    flash `_bwd_dkv_kernel` shape)."""
    jv = pl.program_id(0)
    ir = pl.program_id(1)

    @pl.when(ir == 0)
    def _init():
        acc_scr[...] = jnp.zeros_like(acc_scr)

    c = x_ref.shape[0]
    prec = _prec(x_ref.dtype)
    s = jax.lax.dot_general(
        x_ref[...], w_ref[...], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32, precision=prec)
    col = (jax.lax.broadcasted_iota(jnp.int32, (c, block_v), 1)
           + jv * block_v).astype(jnp.float32)
    p = jnp.where(col < v_valid, jnp.exp(s - lse_ref[...].T), 0.0)
    onehot = (col == lab_ref[...].T).astype(jnp.float32)
    dvals = ((p - onehot) * sc_ref[...].T).astype(x_ref.dtype)
    acc_scr[...] += jax.lax.dot_general(
        x_ref[...], dvals, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32, precision=prec)

    @pl.when(ir == nr - 1)
    def _store():
        dw_ref[...] = acc_scr[...].astype(dw_ref.dtype)


def _fwd_pallas(x, w, labels, chunk, vocab_block, ignore_index,
                interpret):
    n, d = x.shape
    v = w.shape[1]
    bv = vocab_block or _BLOCK_V
    bv = min(bv, -(-v // 128) * 128) if not interpret else min(bv, v)
    xp, lp, n_pad = _pad_rows(x, labels, chunk, ignore_index)
    v_pad = -(-v // bv) * bv
    wp = jnp.pad(w, ((0, 0), (0, v_pad - v))) if v_pad != v else w
    nc, nv = n_pad // chunk, v_pad // bv
    lab2 = lp.reshape(nc, chunk).astype(jnp.int32)
    # labels ride into the kernel as f32 rows (exact below 2^24): all
    # in-kernel compares stay f32 2D — no int relayouts for Mosaic
    labf = lab2.astype(jnp.float32)

    lse, picked = pl.pallas_call(
        functools.partial(_ce_fwd_kernel, block_v=bv, v_valid=v, nv=nv),
        grid=(nc, nv),
        in_specs=[
            pl.BlockSpec((chunk, d), lambda i, j: (i, 0)),
            pl.BlockSpec((d, bv), lambda i, j: (0, j)),
            pl.BlockSpec((1, chunk), lambda i, j: (i, 0)),
        ],
        out_specs=[pl.BlockSpec((1, chunk), lambda i, j: (i, 0)),
                   pl.BlockSpec((1, chunk), lambda i, j: (i, 0))],
        out_shape=[jax.ShapeDtypeStruct((nc, chunk), jnp.float32),
                   jax.ShapeDtypeStruct((nc, chunk), jnp.float32)],
        scratch_shapes=[pltpu.VMEM((chunk, 8), jnp.float32),
                        pltpu.VMEM((chunk, 8), jnp.float32),
                        pltpu.VMEM((chunk, 8), jnp.float32)],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(xp.reshape(nc * chunk, d), wp, labf)
    valid = lab2 != ignore_index
    count = jnp.maximum(jnp.sum(valid.astype(jnp.float32)), 1.0)
    loss = jnp.sum(jnp.where(valid, lse - picked, 0.0)) / count
    return loss, lse, count


def _bwd_pallas(x, w, labels, lses, count, g, chunk, vocab_block,
                ignore_index, interpret):
    n, d = x.shape
    v = w.shape[1]
    bv = vocab_block or _BLOCK_V
    bv = min(bv, -(-v // 128) * 128) if not interpret else min(bv, v)
    xp, lp, n_pad = _pad_rows(x, labels, chunk, ignore_index)
    v_pad = -(-v // bv) * bv
    wp = jnp.pad(w, ((0, 0), (0, v_pad - v))) if v_pad != v else w
    nc, nv = n_pad // chunk, v_pad // bv
    lab2 = lp.reshape(nc, chunk).astype(jnp.int32)
    labf = lab2.astype(jnp.float32)
    scale = jnp.where(lab2 != ignore_index, g / count, 0.0).astype(
        jnp.float32)
    x2 = xp.reshape(nc * chunk, d)

    dx = pl.pallas_call(
        functools.partial(_ce_dx_kernel, block_v=bv, v_valid=v, nv=nv),
        grid=(nc, nv),
        in_specs=[
            pl.BlockSpec((chunk, d), lambda i, j: (i, 0)),
            pl.BlockSpec((d, bv), lambda i, j: (0, j)),
            pl.BlockSpec((1, chunk), lambda i, j: (i, 0)),
            pl.BlockSpec((1, chunk), lambda i, j: (i, 0)),
            pl.BlockSpec((1, chunk), lambda i, j: (i, 0)),
        ],
        out_specs=pl.BlockSpec((chunk, d), lambda i, j: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n_pad, d), x.dtype),
        scratch_shapes=[pltpu.VMEM((chunk, d), jnp.float32)],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(x2, wp, labf, lses, scale)

    dw = pl.pallas_call(
        functools.partial(_ce_dw_kernel, block_v=bv, v_valid=v, nr=nc),
        grid=(nv, nc),
        in_specs=[
            pl.BlockSpec((chunk, d), lambda jv, ir: (ir, 0)),
            pl.BlockSpec((d, bv), lambda jv, ir: (0, jv)),
            pl.BlockSpec((1, chunk), lambda jv, ir: (ir, 0)),
            pl.BlockSpec((1, chunk), lambda jv, ir: (ir, 0)),
            pl.BlockSpec((1, chunk), lambda jv, ir: (ir, 0)),
        ],
        out_specs=pl.BlockSpec((d, bv), lambda jv, ir: (0, jv)),
        out_shape=jax.ShapeDtypeStruct((d, v_pad), w.dtype),
        scratch_shapes=[pltpu.VMEM((d, bv), jnp.float32)],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(x2, wp, labf, lses, scale)
    return dx[:n], dw[:, :v]


# ---------------------------------------------------------------------------
# custom_vjp glue + public entry
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _bce(x, w, labels, chunk, vocab_block, ignore_index, use_pallas,
         interpret):
    loss, _ = _bce_fwd(x, w, labels, chunk, vocab_block, ignore_index,
                       use_pallas, interpret)
    return loss


def _bce_fwd(x, w, labels, chunk, vocab_block, ignore_index, use_pallas,
             interpret):
    if use_pallas:
        loss, lses, count = _fwd_pallas(x, w, labels, chunk, vocab_block,
                                        ignore_index, interpret)
    else:
        loss, lses, count = _fwd_jnp(x, w, labels, chunk, vocab_block,
                                     ignore_index)
    return loss, (x, w, labels, lses, count)


def _bce_bwd(chunk, vocab_block, ignore_index, use_pallas, interpret,
             res, g):
    x, w, labels, lses, count = res
    g = jnp.asarray(g, jnp.float32)
    if use_pallas:
        dx, dw = _bwd_pallas(x, w, labels, lses, count, g, chunk,
                             vocab_block, ignore_index, interpret)
    else:
        dx, dw = _bwd_jnp(x, w, labels, lses, count, g, chunk,
                          vocab_block, ignore_index)
    return dx, dw, None


_bce.defvjp(_bce_fwd, _bce_bwd)


def blockwise_ce_loss(x, w, labels, *, chunk, vocab_block=0,
                      ignore_index=-100, kernel=None, interpret=False):
    """Mean softmax cross-entropy of `x @ w` against int `labels`,
    without materializing the [N, V] logits in forward or backward.

    x: (N, D) hidden rows; w: (D, V) projection (tied-embedding callers
    transpose first); labels: (N,) int, `ignore_index` rows excluded
    from the mean (matching `F.cross_entropy(..., reduction="mean")`).
    chunk: rows per streamed block — the peak logits-shaped
    intermediate is (chunk, vocab_block or V). N not divisible by
    `chunk` and V not divisible by `vocab_block` are padded + masked.

    kernel: None = auto (Pallas on TPU when `ce_shape_problems` is
    empty, the lax.scan fallback otherwise); "pallas" forces the
    kernels (off-TPU via interpret mode — the paged-attention parity
    pattern); "jnp" forces the fallback. Returns a scalar f32 loss;
    differentiable in (x, w) via a custom_vjp that recomputes each
    chunk's logits from the saved row lse.
    """
    if kernel not in (None, "pallas", "jnp"):
        raise ValueError(f"kernel must be None|'pallas'|'jnp', "
                         f"got {kernel!r}")
    if x.ndim != 2 or w.ndim != 2 or labels.ndim != 1:
        raise ValueError(
            f"blockwise_ce_loss wants x (N, D), w (D, V), labels (N,); "
            f"got {x.shape}, {w.shape}, {labels.shape}")
    if x.shape[1] != w.shape[0] or x.shape[0] != labels.shape[0]:
        raise ValueError(
            f"shape mismatch: x {x.shape}, w {w.shape}, "
            f"labels {labels.shape}")
    if chunk < 1:
        raise ValueError(f"chunk must be >= 1 (got {chunk})")
    n, d = x.shape
    v = w.shape[1]
    if kernel == "pallas":
        on_tpu = _on_tpu()
        interpret = interpret or not on_tpu
        check_ce_shapes(n, d, v, chunk, vocab_block, interpret)
        use_pallas = True
    elif kernel == "jnp":
        use_pallas = False
    else:
        use_pallas = (_on_tpu() and not ce_shape_problems(
            n, d, v, chunk, vocab_block, interpret))
    return _bce(x, w, jnp.asarray(labels).astype(jnp.int32),
                int(chunk), int(vocab_block), int(ignore_index),
                use_pallas, bool(interpret))
