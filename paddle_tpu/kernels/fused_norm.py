"""Fused RMSNorm+residual-add and fused RoPE apply (Pallas TPU + jnp).

The other two train-path ops XLA fuses poorly enough to matter at step
scale (ISSUE 14; reference kernels fused_layernorm_kernel.cu rmsnorm
branch and fused_rope under paddle/phi/kernels/fusion/gpu/):

- **RMSNorm + residual**: the decoder block's `h = residual + attn_out;
  normed = rms_norm(h)` chain reads h twice (once for the add's
  consumer, once for the norm's f32 stat pass) and jax AD of the
  unfused chain re-reads everything again backward. Here
  `rms_norm_residual` does the add, the f32 mean-square, and the
  scale-by-weight in ONE pass over x (the residual sum is written in
  the same pass as the norm output), with a `custom_vjp` whose backward
  is the closed-form RMSNorm gradient from the saved per-row rstd —
  one read of (h, g) instead of AD's slice/concat chain.
- **RoPE**: the half-split rotation (`o1 = x1 c - x2 s; o2 = x2 c + x1
  s`) lowers as slice/concat pairs XLA pads into relayout copies.
  `rope_apply` precomputes full-width cos / sign-folded sin tables once
  (tiny: (S, D)) and the kernel does two multiplies + one lane
  rotation per tile; the backward is the INVERSE rotation — the same
  kernel with -sin on the cotangent (the incubate `_apply_rope_neox`
  trick, kept).

Both ops run the Pallas kernels on TPU when their shape contract holds
(`*_shape_problems` — the `decode_shape_problems` style: the AUTO path
gates silently, a forced "pallas" raises naming every misaligned dim)
and fall back to jnp with IDENTICAL math elsewhere, so CPU tier-1
exercises the exact numerics the TPU path ships (plus interpret-mode
kernel parity, the paged-attention pattern).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from paddle_tpu.core.jax_compat import on_tpu as _on_tpu
from paddle_tpu.core.jax_compat import tpu_compiler_params

__all__ = ["rms_norm_residual", "rope_apply",
           "norm_shape_problems", "check_norm_shapes",
           "rope_shape_problems", "check_rope_shapes"]

# rows per grid cell (both kernels); padded rows are zeros and sliced off
_BLOCK_ROWS = 256


# ---------------------------------------------------------------------------
# shape contracts
# ---------------------------------------------------------------------------

def norm_shape_problems(d, interpret=False):
    """Reasons the Pallas RMSNorm+residual kernel cannot take a row
    width d; empty = supported."""
    problems = []
    if not interpret and d % 128 != 0:
        problems.append(f"hidden % 128 == 0 required on TPU (got d={d})")
    return problems


def check_norm_shapes(d, interpret=False):
    problems = norm_shape_problems(d, interpret)
    if problems:
        raise ValueError(
            "rms_norm_residual: shapes cannot take the Pallas kernel — "
            + "; ".join(problems)
            + '; use kernel="jnp" for the fused-jnp fallback')


def rope_shape_problems(d, interpret=False):
    """Reasons the Pallas RoPE kernel cannot take head_dim d."""
    problems = []
    if d % 2 != 0:
        problems.append(f"head_dim must be even (got d={d})")
    if not interpret:
        if d % 8 != 0:
            problems.append(f"head_dim % 8 == 0 required on TPU "
                            f"(got d={d})")
    return problems


def check_rope_shapes(d, interpret=False):
    problems = rope_shape_problems(d, interpret)
    if problems:
        raise ValueError(
            "rope_apply: shapes cannot take the Pallas kernel — "
            + "; ".join(problems)
            + '; use kernel="jnp" for the fused-jnp fallback')


# ---------------------------------------------------------------------------
# RMSNorm + residual
# ---------------------------------------------------------------------------

def _rmsn_fwd_math(h, w, eps):
    """Shared forward math — EXACTLY `nn/functional/norm.py _rms_norm`
    (the eager `rms_norm_ref` defop): f32 stats, f32 scale-by-weight,
    cast back. The parity pin in tests depends on this being the same
    expression tree."""
    hf = h.astype(jnp.float32)
    ms = jnp.mean(jnp.square(hf), axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(ms + eps)
    y = (hf * rstd * w.astype(jnp.float32)).astype(h.dtype)
    return y, rstd


def _rmsn_bwd_math(h, w, rstd, gy, gh):
    """Closed-form RMSNorm backward from the saved rstd:
    dh = rstd * (gy*w - xhat * mean(gy*w*xhat)) + gh;  dw = sum gy*xhat.
    One pass over (h, gy) — what jax AD spreads across the rsqrt/mean
    chain re-reads."""
    hf = h.astype(jnp.float32)
    gyf = gy.astype(jnp.float32)
    wf = w.astype(jnp.float32)
    xhat = hf * rstd
    dxhat = gyf * wf
    c = jnp.mean(dxhat * xhat, axis=-1, keepdims=True)
    dh = rstd * (dxhat - xhat * c)
    if gh is not None:
        dh = dh + gh.astype(jnp.float32)
    dw = jnp.sum(gyf * xhat, axis=tuple(range(h.ndim - 1)))
    return dh.astype(h.dtype), dw.astype(w.dtype)


def _rmsn_fwd_kernel(x_ref, res_ref, w_ref, y_ref, h_ref, rstd_ref, *,
                     eps, has_res):
    x = x_ref[...]
    h = x + res_ref[...] if has_res else x
    h_ref[...] = h
    hf = h.astype(jnp.float32)
    ms = jnp.mean(hf * hf, axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(ms + eps)                        # (bn, 1)
    # w_ref[...] is the 2D (1, d) row — broadcast, never a 1D vector
    # (the flash-kernel Mosaic idiom)
    y_ref[...] = (hf * rstd
                  * w_ref[...].astype(jnp.float32)).astype(y_ref.dtype)
    # transposed (8, bn) store: full (8, 128) f32 tiles (the flash lse
    # layout lesson)
    rstd_ref[...] = jnp.broadcast_to(rstd.T, rstd_ref.shape)


def _rmsn_fwd_kernel_nores(x_ref, w_ref, y_ref, h_ref, rstd_ref, *, eps):
    return _rmsn_fwd_kernel(x_ref, None, w_ref, y_ref, h_ref, rstd_ref,
                            eps=eps, has_res=False)


def _rmsn_bwd_kernel(h_ref, w_ref, rstd_ref, gy_ref, gh_ref, dh_ref,
                     dwp_ref, *, has_gh):
    hf = h_ref[...].astype(jnp.float32)
    gyf = gy_ref[...].astype(jnp.float32)
    wf = w_ref[...].astype(jnp.float32)                   # (1, d)
    rstd = rstd_ref[:1, :].T                              # (bn, 1)
    xhat = hf * rstd
    dxhat = gyf * wf
    c = jnp.mean(dxhat * xhat, axis=-1, keepdims=True)
    dh = rstd * (dxhat - xhat * c)
    if has_gh:
        dh = dh + gh_ref[...].astype(jnp.float32)
    dh_ref[...] = dh.astype(dh_ref.dtype)
    # per-block dW partial (1, d); summed outside (rows/bn terms)
    dwp_ref[...] = jnp.sum(gyf * xhat, axis=0, keepdims=True)


def _rmsn_fwd_pallas(x2, res2, w, eps, interpret):
    n, d = x2.shape
    bn = min(_BLOCK_ROWS, n)
    n_pad = -(-n // bn) * bn
    pads = ((0, n_pad - n), (0, 0))
    xp = jnp.pad(x2, pads) if n_pad != n else x2
    args = [xp]
    in_specs = [pl.BlockSpec((bn, d), lambda i: (i, 0))]
    if res2 is not None:
        rp = jnp.pad(res2, pads) if n_pad != n else res2
        args.append(rp)
        in_specs.append(pl.BlockSpec((bn, d), lambda i: (i, 0)))
        kernel = functools.partial(_rmsn_fwd_kernel, eps=eps,
                                   has_res=True)
    else:
        kernel = functools.partial(_rmsn_fwd_kernel_nores, eps=eps)
    args.append(w.reshape(1, d))
    in_specs.append(pl.BlockSpec((1, d), lambda i: (0, 0)))
    y, h, rstd_t = pl.pallas_call(
        kernel,
        grid=(n_pad // bn,),
        in_specs=in_specs,
        out_specs=[pl.BlockSpec((bn, d), lambda i: (i, 0)),
                   pl.BlockSpec((bn, d), lambda i: (i, 0)),
                   pl.BlockSpec((8, bn), lambda i: (0, i))],
        out_shape=[jax.ShapeDtypeStruct((n_pad, d), x2.dtype),
                   jax.ShapeDtypeStruct((n_pad, d), x2.dtype),
                   jax.ShapeDtypeStruct((8, n_pad), jnp.float32)],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel",)),
        interpret=interpret,
    )(*args)
    return y[:n], h[:n], rstd_t


def _rmsn_bwd_pallas(h2, w, rstd_t, gy2, gh2, interpret):
    n, d = h2.shape
    bn = min(_BLOCK_ROWS, n)
    n_pad = -(-n // bn) * bn
    pads = ((0, n_pad - n), (0, 0))
    hp = jnp.pad(h2, pads) if n_pad != n else h2
    gyp = jnp.pad(gy2, pads) if n_pad != n else gy2
    args = [hp, w.reshape(1, d), rstd_t]
    in_specs = [pl.BlockSpec((bn, d), lambda i: (i, 0)),
                pl.BlockSpec((1, d), lambda i: (0, 0)),
                pl.BlockSpec((8, bn), lambda i: (0, i))]
    args.append(gyp)
    in_specs.append(pl.BlockSpec((bn, d), lambda i: (i, 0)))
    if gh2 is not None:
        ghp = jnp.pad(gh2, pads) if n_pad != n else gh2
        args.append(ghp)
        in_specs.append(pl.BlockSpec((bn, d), lambda i: (i, 0)))
        kernel = functools.partial(_rmsn_bwd_kernel, has_gh=True)
    else:
        kernel = functools.partial(
            lambda h_ref, w_ref, r_ref, gy_ref, dh_ref, dwp_ref, kern:
            kern(h_ref, w_ref, r_ref, gy_ref, None, dh_ref, dwp_ref),
            kern=functools.partial(_rmsn_bwd_kernel, has_gh=False))
    dh, dwp = pl.pallas_call(
        kernel,
        grid=(n_pad // bn,),
        in_specs=in_specs,
        out_specs=[pl.BlockSpec((bn, d), lambda i: (i, 0)),
                   pl.BlockSpec((1, d), lambda i: (i, 0))],
        out_shape=[jax.ShapeDtypeStruct((n_pad, d), h2.dtype),
                   jax.ShapeDtypeStruct((n_pad // bn, d), jnp.float32)],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel",)),
        interpret=interpret,
    )(*args)
    return dh[:n], jnp.sum(dwp, axis=0).astype(w.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _rmsn_res(x2, res2, w, eps, use_pallas, interpret):
    y, h, _ = _rmsn_res_fwd_impl(x2, res2, w, eps, use_pallas, interpret)
    return y, h


def _rmsn_res_fwd_impl(x2, res2, w, eps, use_pallas, interpret):
    if use_pallas:
        y, h, rstd_t = _rmsn_fwd_pallas(x2, res2, w, eps, interpret)
        return y, h, rstd_t
    h = x2 + res2
    y, rstd = _rmsn_fwd_math(h, w, eps)
    return y, h, rstd


def _rmsn_res_fwd(x2, res2, w, eps, use_pallas, interpret):
    y, h, rstd = _rmsn_res_fwd_impl(x2, res2, w, eps, use_pallas,
                                    interpret)
    return (y, h), (h, w, rstd)


def _rmsn_res_bwd(eps, use_pallas, interpret, res, g):
    gy, gh = g
    h, w, rstd = res
    if use_pallas:
        dh, dw = _rmsn_bwd_pallas(h, w, rstd, gy, gh, interpret)
    else:
        dh, dw = _rmsn_bwd_math(h, w, rstd, gy, gh)
    return dh, dh, dw


_rmsn_res.defvjp(_rmsn_res_fwd, _rmsn_res_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def _rmsn_plain(x2, w, eps, use_pallas, interpret):
    y, _, _ = _rmsn_plain_fwd_impl(x2, w, eps, use_pallas, interpret)
    return y


def _rmsn_plain_fwd_impl(x2, w, eps, use_pallas, interpret):
    if use_pallas:
        return _rmsn_fwd_pallas(x2, None, w, eps, interpret)
    y, rstd = _rmsn_fwd_math(x2, w, eps)
    return y, x2, rstd


def _rmsn_plain_fwd(x2, w, eps, use_pallas, interpret):
    y, h, rstd = _rmsn_plain_fwd_impl(x2, w, eps, use_pallas, interpret)
    return y, (h, w, rstd)


def _rmsn_plain_bwd(eps, use_pallas, interpret, res, gy):
    h, w, rstd = res
    if use_pallas:
        dh, dw = _rmsn_bwd_pallas(h, w, rstd, gy, None, interpret)
    else:
        dh, dw = _rmsn_bwd_math(h, w, rstd, gy, None)
    return dh, dw


_rmsn_plain.defvjp(_rmsn_plain_fwd, _rmsn_plain_bwd)


def rms_norm_residual(x, weight, residual=None, epsilon=1e-6,
                      kernel=None, interpret=False):
    """Fused `h = x + residual; y = rms_norm(h) * weight` in one pass.

    x / residual: (..., d) same shape; weight: (d,). Returns (y, h) —
    both in x's dtype; with residual=None, h IS x (the plain fused
    norm, still one custom_vjp op). Matches the eager `rms_norm_ref`
    defop's numerics exactly (f32 stats, f32 scale, cast back).

    kernel: None = auto (Pallas on TPU when `norm_shape_problems` is
    empty, fused-jnp otherwise); "pallas" forces the kernel (off-TPU
    via interpret mode); "jnp" forces the fallback.
    """
    if kernel not in (None, "pallas", "jnp"):
        raise ValueError(f"kernel must be None|'pallas'|'jnp', "
                         f"got {kernel!r}")
    d = x.shape[-1]
    if weight.shape != (d,):
        raise ValueError(f"weight must be ({d},), got {weight.shape}")
    if residual is not None and residual.shape != x.shape:
        raise ValueError(f"residual shape {residual.shape} != x shape "
                         f"{x.shape}")
    if kernel == "pallas":
        interpret = interpret or not _on_tpu()
        check_norm_shapes(d, interpret)
        use_pallas = True
    elif kernel == "jnp":
        use_pallas = False
    else:
        use_pallas = _on_tpu() and not norm_shape_problems(d, interpret)
    lead = x.shape[:-1]
    x2 = x.reshape(-1, d)
    eps = float(epsilon)
    if residual is None:
        y = _rmsn_plain(x2, weight, eps, use_pallas, bool(interpret))
        return y.reshape(lead + (d,)), x
    r2 = residual.reshape(-1, d)
    y, h = _rmsn_res(x2, r2, weight, eps, use_pallas, bool(interpret))
    return y.reshape(lead + (d,)), h.reshape(lead + (d,))


# ---------------------------------------------------------------------------
# fused RoPE apply
# ---------------------------------------------------------------------------

def _rope_fwd_math(x, cos_f, sin_f):
    """x (n, h, d); cos_f (n, d) full-width cos; sin_f (n, d) = the
    SIGN-FOLDED sin table concat(-sin, sin). out = x*cos + roll(x)*sin
    where roll swaps the halves — identical math to the incubate
    `_rope_neox_raw` half-split form, f32 compute, cast back."""
    d = x.shape[-1]
    d2 = d // 2
    xf = x.astype(jnp.float32)
    rolled = jnp.concatenate([xf[..., d2:], xf[..., :d2]], axis=-1)
    out = (xf * cos_f[:, None, :] + rolled * sin_f[:, None, :])
    return out.astype(x.dtype)


def _rope_kernel(x_ref, cos_ref, sin_ref, o_ref):
    x = x_ref[...].astype(jnp.float32)              # (bn, h, d)
    d = x.shape[-1]
    d2 = d // 2
    rolled = jnp.concatenate([x[..., d2:], x[..., :d2]], axis=-1)
    cos = cos_ref[...][:, None, :]                  # (bn, 1, d)
    sin = sin_ref[...][:, None, :]
    o_ref[...] = (x * cos + rolled * sin).astype(o_ref.dtype)


def _rope_pallas(x3, cos_f, sin_f, interpret):
    n, h, d = x3.shape
    bn = min(_BLOCK_ROWS, n)
    n_pad = -(-n // bn) * bn
    if n_pad != n:
        x3 = jnp.pad(x3, ((0, n_pad - n), (0, 0), (0, 0)))
        cos_f = jnp.pad(cos_f, ((0, n_pad - n), (0, 0)))
        sin_f = jnp.pad(sin_f, ((0, n_pad - n), (0, 0)))
    out = pl.pallas_call(
        _rope_kernel,
        grid=(n_pad // bn,),
        in_specs=[pl.BlockSpec((bn, h, d), lambda i: (i, 0, 0)),
                  pl.BlockSpec((bn, d), lambda i: (i, 0)),
                  pl.BlockSpec((bn, d), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((bn, h, d), lambda i: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((n_pad, h, d), x3.dtype),
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel",)),
        interpret=interpret,
    )(x3, cos_f, sin_f)
    return out[:n]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _rope(x3, cos_f, sin_f, use_pallas, interpret):
    if use_pallas:
        return _rope_pallas(x3, cos_f, sin_f, interpret)
    return _rope_fwd_math(x3, cos_f, sin_f)


def _rope_fwd(x3, cos_f, sin_f, use_pallas, interpret):
    return _rope(x3, cos_f, sin_f, use_pallas, interpret), (cos_f, sin_f)


def _rope_bwd(use_pallas, interpret, res, g):
    cos_f, sin_f = res
    # the backward of a rotation is the INVERSE rotation — the same
    # forward on the cotangent with the angle negated (the incubate
    # _apply_rope_neox trick). Half-split: dx1 = g1 c + g2 s,
    # dx2 = g2 c - g1 s; in the sign-folded full-width form that is
    # exactly sin_f -> -sin_f (concat(-s, s) -> concat(s, -s)).
    sin_b = -sin_f
    if use_pallas:
        dx = _rope_pallas(g, cos_f, sin_b, interpret)
    else:
        dx = _rope_fwd_math(g, cos_f, sin_b)
    return dx, jnp.zeros_like(cos_f), jnp.zeros_like(sin_f)


_rope.defvjp(_rope_fwd, _rope_bwd)


def _cos_sin_rows(positions, d, theta, dtype):
    """Full-width f32 tables per row: cos_f (n, d) = concat(cos, cos),
    sin_f (n, d) = concat(-sin, sin) (the sign fold that turns the
    half-split rotation into mul/roll/mul/add). positions: (n,) i32."""
    inv_freq = 1.0 / (theta ** (
        jnp.arange(0, d, 2, dtype=jnp.float32) / d))
    ang = positions.astype(jnp.float32)[:, None] * inv_freq   # (n, d/2)
    cos = jnp.cos(ang)
    sin = jnp.sin(ang)
    cos_f = jnp.concatenate([cos, cos], axis=-1)
    sin_f = jnp.concatenate([-sin, sin], axis=-1)
    return cos_f.astype(dtype), sin_f.astype(dtype)


def rope_apply(x, positions=None, theta=10000.0, kernel=None,
               interpret=False):
    """NeoX/Llama RoPE on x (B, S, H, D) in one fused pass.

    positions: (S,) or (B, S) int positions (None = arange(S)). Exact
    numerics of the incubate `_apply_rope_neox` half-split apply (f32
    compute, cast back); backward is the inverse rotation via
    custom_vjp. kernel: None = auto (Pallas on TPU when
    `rope_shape_problems` is empty), "pallas" forced (interpret
    off-TPU), "jnp" forced.
    """
    if kernel not in (None, "pallas", "jnp"):
        raise ValueError(f"kernel must be None|'pallas'|'jnp', "
                         f"got {kernel!r}")
    b, s, h, d = x.shape
    if d % 2 != 0:
        raise ValueError(f"head_dim must be even (got {d})")
    if kernel == "pallas":
        interpret = interpret or not _on_tpu()
        check_rope_shapes(d, interpret)
        use_pallas = True
    elif kernel == "jnp":
        use_pallas = False
    else:
        use_pallas = _on_tpu() and not rope_shape_problems(d, interpret)
    if positions is None:
        pos = jnp.tile(jnp.arange(s, dtype=jnp.int32), b)
    else:
        pos = jnp.asarray(positions).astype(jnp.int32)
        if pos.ndim == 1:
            pos = jnp.tile(pos, b)
        else:
            pos = pos.reshape(-1)
    cos_f, sin_f = _cos_sin_rows(pos, d, float(theta), jnp.float32)
    x3 = x.reshape(b * s, h, d)
    out = _rope(x3, cos_f, sin_f, use_pallas, bool(interpret))
    return out.reshape(b, s, h, d)
