"""Fused weight-only-int8 matmul Pallas kernel.

Reference capability: paddle/phi/kernels/weight_quantize_kernel.h +
fusion/gpu/fused_weight_only_linear — the llm.int8-style W8A16 path where
int8 weights are dequantized INSIDE the matmul kernel.

Why a kernel: XLA lowers `qw.astype(bf16) * scale @ x` as a separate
dequant fusion that MATERIALIZES the full bf16 weight in HBM every call
(measured 0.89x vs plain bf16 on v5e — worse than not quantizing).
Fusing the convert+scale into the matmul's K-loop keeps weight traffic
at 1 byte/element, which is the whole point of W8A16 for bandwidth-bound
decode shapes.

Layout: x (M, K) bf16 @ qw (K, N) int8 * scale (N,) f32 -> (M, N).
Grid (M/bm, N/bn, K/bk), K innermost ("arbitrary"), f32 VMEM accumulator,
dequant epilogue applied once at the last K step.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from paddle_tpu.core.jax_compat import tpu_compiler_params

__all__ = ["weight_only_int8_matmul", "pick_block_m"]


def pick_block_m(M: int):
    """Largest VMEM-safe M tile dividing M (None if M doesn't tile —
    callers then take the XLA fallback instead of an unbounded bm=M
    accumulator that blows VMEM for large ragged batch*seq)."""
    for c in (256, 128, 64, 32, 16, 8):
        if M % c == 0:
            return c
    return M if M <= 256 else None


def _kernel(x_ref, qw_ref, s_ref, o_ref, acc_ref, *, nk):
    @pl.when(pl.program_id(2) == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    w = qw_ref[...].astype(jnp.bfloat16)     # in-register dequant (tile)
    # precision pinned: the package default (FLAGS_matmul_precision
    # "highest") requests f32-emulated bf16 passes Mosaic can't lower
    acc_ref[...] += jax.lax.dot(
        x_ref[...].astype(jnp.bfloat16), w,
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.DEFAULT)

    @pl.when(pl.program_id(2) == nk - 1)
    def _epilogue():
        o_ref[...] = (acc_ref[...]
                      * s_ref[...].astype(jnp.float32)).astype(o_ref.dtype)


def weight_only_int8_matmul(x, qw, scale, block_m=None, block_n=512,
                            block_k=512, out_dtype=jnp.bfloat16,
                            interpret=False):
    """x (..., K) bf16/f32 @ int8 qw (K, N), `scale` (N,) f32 already
    divided by the quant bound (i.e. w ~= qw * scale). Shapes must tile:
    K % block_k == 0 and N % block_n == 0 (callers fall back to the XLA
    path otherwise — see QuantizedLinear)."""
    lead = x.shape[:-1]
    K = x.shape[-1]
    N = qw.shape[1]
    M = 1
    for d in lead:
        M *= d
    x2 = x.reshape(M, K)
    if block_m is None:
        block_m = pick_block_m(M)
        if block_m is None:
            raise ValueError(
                f"M={M} has no tile-able block_m; use the XLA fallback")
    if M % block_m != 0:
        raise ValueError(f"M={M} not divisible by block_m={block_m}")
    bm = block_m
    nk = K // block_k
    grid = (M // bm, N // block_n, nk)
    out = pl.pallas_call(
        functools.partial(_kernel, nk=nk),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, block_k), lambda i, j, k: (i, k)),
            pl.BlockSpec((block_k, block_n), lambda i, j, k: (k, j)),
            pl.BlockSpec((1, block_n), lambda i, j, k: (0, j)),
        ],
        out_specs=pl.BlockSpec((bm, block_n), lambda i, j, k: (i, j)),
        out_shape=jax.ShapeDtypeStruct((M, N), out_dtype),
        scratch_shapes=[pltpu.VMEM((bm, block_n), jnp.float32)],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        cost_estimate=pl.CostEstimate(
            flops=2 * M * N * K,
            bytes_accessed=M * K * 2 + K * N + M * N * 2,
            transcendentals=0),
        interpret=interpret,
    )(x2, qw, scale.reshape(1, N))
    return out.reshape(lead + (N,))
