"""FlashAttention for TPU (Pallas).

Replaces the reference's vendored FA2 CUDA library (reference:
third_party/flashattn + paddle/phi/kernels/gpu/flash_attn_kernel.cu,
python surface python/paddle/nn/functional/flash_attention.py) with a
TPU-native pair:

- forward: a Pallas kernel — one grid cell per (batch, head, q-block),
  online-softmax accumulation over k/v blocks streamed through VMEM, MXU
  matmuls in f32 accumulation. Causal cells whose k-block lies entirely
  above the diagonal are skipped via the loop bound.
- backward: rematerialising chunked attention (lax.scan over k/v blocks
  with jax.checkpoint per block) differentiated by JAX AD. Exact same math
  as the forward, O(S·D) residual memory — the FA2 recompute strategy
  expressed as a program transform instead of a second handwritten kernel.

Layouts: public entry takes paddle's (batch, seq, heads, head_dim).
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def _pick_block(seq, target):
    """Largest power-of-two block <= target that divides/covers seq."""
    b = min(target, max(8, 1 << (seq - 1).bit_length()))
    return min(b, target)


# ---------------------------------------------------------------------------
# Pallas forward kernel
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, *, sm_scale, causal, block_k,
                kv_valid):
    # k arrives pre-transposed as (1, 1, d, sk) so the q @ k dot uses the
    # standard (1),(0) contraction — Mosaic only lowers bf16 matmuls in
    # that form
    bq, d = q_ref.shape[2], q_ref.shape[3]
    kv_pad = k_ref.shape[3]
    iq = pl.program_id(2)

    # keep operands in the input dtype (bf16): the MXU multiplies bf16 at
    # full rate with f32 accumulation; upcasting operands to f32 halves
    # throughput. f32 inputs keep HIGHEST precision (exact f32) — only
    # bf16/f16 operands use the native one-pass mode.
    q = (q_ref[0, 0] * jnp.asarray(sm_scale, q_ref.dtype))
    prec = (jax.lax.Precision.DEFAULT
            if q_ref.dtype in (jnp.bfloat16, jnp.float16)
            else jax.lax.Precision.HIGHEST)

    nk_total = kv_pad // block_k
    if causal:
        # number of k-blocks touching rows [iq*bq, (iq+1)*bq)
        nk = jnp.minimum(((iq + 1) * bq + block_k - 1) // block_k, nk_total)
    else:
        nk = nk_total

    def body(j, carry):
        m, l, acc = carry
        kj = k_ref[0, 0, :, pl.ds(j * block_k, block_k)]   # (d, bk)
        vj = v_ref[0, 0, pl.ds(j * block_k, block_k), :]   # (bk, d)
        s = jax.lax.dot_general(
            q, kj, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=prec)                              # (bq, bk) f32
        # bf16: the package-global 'highest' would force an f32-contract
        # form Mosaic can't lower; bf16 inputs with f32 accumulation IS
        # the full-rate MXU mode
        col = jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 1) \
            + j * block_k
        valid = col < kv_valid
        if causal:
            row = jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0) \
                + iq * bq
            valid = jnp.logical_and(valid, col <= row)
        s = jnp.where(valid, s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jax.lax.dot_general(
            p.astype(vj.dtype), vj, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=prec)
        return m_new, l_new, acc_new

    m0 = jnp.full((bq, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    acc0 = jnp.zeros((bq, d), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, nk, body, (m0, l0, acc0))
    o_ref[0, 0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def _flash_fwd_pallas(q, k, v, causal, sm_scale, block_q=512, block_k=512,
                      interpret=False):
    """q,k,v: (B, H, S, D) with equal head counts. Returns (B, H, Sq, D)."""
    b, h, sq, d = q.shape
    sk = k.shape[2]
    bq = min(block_q, sq)
    bk = min(block_k, sk)
    # pad seqs to block multiples
    sq_p = (sq + bq - 1) // bq * bq
    sk_p = (sk + bk - 1) // bk * bk
    if sq_p != sq:
        q = jnp.pad(q, ((0, 0), (0, 0), (0, sq_p - sq), (0, 0)))
    if sk_p != sk:
        k = jnp.pad(k, ((0, 0), (0, 0), (0, sk_p - sk), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, sk_p - sk), (0, 0)))

    kt = jnp.swapaxes(k, 2, 3)   # (b, h, d, sk): XLA fuses the transpose
    kernel = functools.partial(_fwd_kernel, sm_scale=sm_scale,
                               causal=causal, block_k=bk, kv_valid=sk)
    out = pl.pallas_call(
        kernel,
        grid=(b, h, sq_p // bq),
        in_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, d, sk_p), lambda bi, hi, qi: (bi, hi, 0, 0)),
            pl.BlockSpec((1, 1, sk_p, d), lambda bi, hi, qi: (bi, hi, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, bq, d),
                               lambda bi, hi, qi: (bi, hi, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b, h, sq_p, d), q.dtype),
        interpret=interpret,
    )(q, kt, v)
    return out[:, :, :sq, :]


# ---------------------------------------------------------------------------
# Chunked (blockwise) attention in pure jax — backward path + CPU fallback
# ---------------------------------------------------------------------------

def _chunked_attention(q, k, v, causal, sm_scale, block_q=512, block_k=512):
    """(B,H,S,D) exact attention via online softmax over k/v blocks.
    jax.checkpoint per block => O(S·D) residuals under AD."""
    b, h, sq, d = q.shape
    sk = k.shape[2]
    bq = min(block_q, sq)
    bk = min(block_k, sk)
    sq_p = (sq + bq - 1) // bq * bq
    sk_p = (sk + bk - 1) // bk * bk
    qp = jnp.pad(q, ((0, 0), (0, 0), (0, sq_p - sq), (0, 0)))
    kp = jnp.pad(k, ((0, 0), (0, 0), (0, sk_p - sk), (0, 0)))
    vp = jnp.pad(v, ((0, 0), (0, 0), (0, sk_p - sk), (0, 0)))
    nq, nk = sq_p // bq, sk_p // bk

    qb = qp.reshape(b, h, nq, bq, d)
    kb = kp.reshape(b, h, nk, bk, d)
    vb = vp.reshape(b, h, nk, bk, d)

    @jax.checkpoint
    def block(qi, kj, vj, iq, jk):
        prec = (jax.lax.Precision.DEFAULT
                if qi.dtype in (jnp.bfloat16, jnp.float16)
                else jax.lax.Precision.HIGHEST)
        qf = qi * jnp.asarray(sm_scale, qi.dtype)
        s = jnp.einsum("...qd,...kd->...qk", qf, kj,
                       preferred_element_type=jnp.float32,
                       precision=prec)
        col = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1) + jk * bk
        valid = col < sk
        if causal:
            row = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0) + iq * bq
            valid = jnp.logical_and(valid, col <= row)
        s = jnp.where(valid, s, _NEG_INF)
        m = jnp.max(s, axis=-1, keepdims=True)
        p = jnp.exp(s - m)
        l = jnp.sum(p, axis=-1, keepdims=True)
        o = jnp.einsum("...qk,...kd->...qd", p.astype(vj.dtype), vj,
                       preferred_element_type=jnp.float32,
                       precision=prec)
        return m, l, o

    def q_block(iq, qi):
        def kv_step(carry, jk):
            m, l, acc = carry
            mj, lj, oj = block(qi, kb[:, :, jk], vb[:, :, jk], iq, jk)
            m_new = jnp.maximum(m, mj)
            alpha = jnp.exp(m - m_new)
            beta = jnp.exp(mj - m_new)
            l_new = l * alpha + lj * beta
            acc_new = acc * alpha + oj * beta
            return (m_new, l_new, acc_new), None

        m0 = jnp.full((b, h, bq, 1), _NEG_INF, jnp.float32)
        l0 = jnp.zeros((b, h, bq, 1), jnp.float32)
        a0 = jnp.zeros((b, h, bq, d), jnp.float32)
        (m, l, acc), _ = jax.lax.scan(kv_step, (m0, l0, a0),
                                      jnp.arange(nk))
        return (acc / jnp.maximum(l, 1e-30)).astype(q.dtype)

    outs = jax.lax.map(lambda i: q_block(i, qb[:, :, i]), jnp.arange(nq))
    out = jnp.moveaxis(outs, 0, 2).reshape(b, h, sq_p, d)
    return out[:, :, :sq, :]


# ---------------------------------------------------------------------------
# custom_vjp glue
# ---------------------------------------------------------------------------

def _on_tpu():
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash(q, k, v, causal, sm_scale):
    if _on_tpu():
        return _flash_fwd_pallas(q, k, v, causal, sm_scale)
    return _chunked_attention(q, k, v, causal, sm_scale)


def _flash_fwd_rule(q, k, v, causal, sm_scale):
    return _flash(q, k, v, causal, sm_scale), (q, k, v)


def _flash_bwd_rule(causal, sm_scale, res, g):
    q, k, v = res
    _, vjp = jax.vjp(
        lambda q_, k_, v_: _chunked_attention(q_, k_, v_, causal, sm_scale),
        q, k, v)
    return vjp(g)


_flash.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def flash_attention_bhsd(q, k, v, causal=False, sm_scale=None):
    """(B, H, S, D) entry. GQA: kv head count may divide q head count."""
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    hq, hk = q.shape[1], k.shape[1]
    if hk != hq:
        rep = hq // hk
        k = jnp.repeat(k, rep, axis=1)
        v = jnp.repeat(v, rep, axis=1)
    return _flash(q, k, v, causal, sm_scale)


def flash_attention_bshd(q, k, v, causal=False, sm_scale=None):
    """Paddle layout (B, S, H, D) (reference flash_attention surface)."""
    out = flash_attention_bhsd(
        jnp.swapaxes(q, 1, 2), jnp.swapaxes(k, 1, 2),
        jnp.swapaxes(v, 1, 2), causal=causal, sm_scale=sm_scale)
    return jnp.swapaxes(out, 1, 2)
