"""FlashAttention for TPU (Pallas), forward + backward kernels.

Replaces the reference's vendored FA2 CUDA library (reference:
third_party/flashattn + paddle/phi/kernels/gpu/flash_attn_kernel.cu,
python surface python/paddle/nn/functional/flash_attention.py) with a
TPU-native implementation:

- forward: a Pallas kernel — one grid cell per (batch, head, q-block),
  online-softmax accumulation over k/v blocks streamed through VMEM, MXU
  matmuls in f32 accumulation. Causal cells whose k-block lies entirely
  above the diagonal are skipped via the loop bound. Also emits the
  row logsumexp (LSE) for the backward pass, stored TRANSPOSED as
  (b, h, 8, sq) f32 — full (8,128) tiles; a (sq, 8) layout wastes 15/16
  of every tile's bandwidth on the minor-dim padding (r4 trace).
- backward, small kv (the common training shape after the GQA fold):
  ONE fused Pallas kernel — grid (b, h, q-block), k/v + full-kv f32
  dk/dv scratch VMEM-resident — produces dq, dk and dv from a single
  softmax recompute (_bwd_fused_kernel).
- backward, larger kv: two Pallas kernels in FA2 style —
    dq: grid (b, h, q-block); recompute p from q,k and the saved LSE,
        ds = p * (dO·vT - delta), accumulate dq += ds @ k.
    dkv: grid (b, h, k-block); loop over q-blocks at/below the diagonal,
        dv += p^T·dO and dk += ds^T·q with f32 accumulators carried
        through the loop.
  delta = rowsum(dO * O) is precomputed in XLA (one fused pass).
- CPU fallback (and the bwd-of-bwd path): rematerialising chunked
  attention (lax.scan over k/v blocks with jax.checkpoint) differentiated
  by JAX AD — exact same math with O(S·D) residual memory.

Two kernel layouts per direction, selected by kv size: below
_KV_VMEM_BYTES the whole k/v sits in VMEM per (b, h) (fastest — one
fetch, no per-block grid overhead); above it, 4D-grid variants stream
one k/v block per grid step with the softmax state / accumulators in
VMEM scratch, so single-chip sequence length is bounded by HBM only
(verified: 32K tokens trains on one 16G v5e). Multi-chip long context
goes through ring/context-parallel (distributed/context_parallel.py),
which shards the sequence before the kernel sees it.

Layouts: public entry takes paddle's (batch, seq, heads, head_dim).
"""
from __future__ import annotations

import functools
import math
import os as _os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30
_LOG2E = 1.4426950408889634  # kernels exponentiate in base 2: exp(x) = exp2(x*log2e)
# LSE/delta sublane replication rows in the TRANSPOSED (b, h, rows, sq)
# layout: 8 = the f32 sublane tile, so every (8, 128) tile is fully
# used. (The r1-r3 (b, h, sq, lanes) layout padded the 8- or 128-wide
# minor dim into (8,128) tiles; the r4 trace measured its delta twin
# broadcasting at 33 GB/s — 4.3 ms/step of layout waste.)
_LSE_ROWS = 8

if _os.environ.get("PADDLE_TPU_FLASH_LSE_LANES"):
    import warnings as _warnings

    _warnings.warn(
        "PADDLE_TPU_FLASH_LSE_LANES no longer exists: the r4 transposed "
        "(b, h, 8, sq) lse layout removed the lane-width knob entirely "
        "(every tile is full). The env var is ignored.")

# A/B flag: run the softmax exponentials in bf16 (packed VPU rate)
# instead of f32. Changes numerics by ~1e-3 relative on p; the l/lse
# accumulations stay f32.
_BF16_EXP = _os.environ.get("PADDLE_TPU_FLASH_BF16_EXP", "0") in ("1",
                                                                  "true")


def _exp2(x):
    if _BF16_EXP:
        return jnp.exp2(x.astype(jnp.bfloat16))
    return jnp.exp2(x)

# Tuning knobs (swept on v5e: (512,512) best in the full train step; larger
# q-blocks win in kernel isolation but lose in context)
_BLOCK_Q = int(_os.environ.get("PADDLE_TPU_FLASH_BLOCK_Q", 512))
_BLOCK_K = int(_os.environ.get("PADDLE_TPU_FLASH_BLOCK_K", 512))
_BLOCK_Q_BWD = int(_os.environ.get("PADDLE_TPU_FLASH_BLOCK_Q_BWD", 512))
_BLOCK_K_BWD = int(_os.environ.get("PADDLE_TPU_FLASH_BLOCK_K_BWD", 512))
# streamed-kv (long-sequence) kernels want much larger k blocks: fewer
# grid steps and fewer lse/delta re-reads. S=16k b1 on v5e measured
# 9.2k tok/s at bk=512 vs 13.9k at bk=2048.
_BLOCK_K_STREAM = int(_os.environ.get("PADDLE_TPU_FLASH_BLOCK_K_STREAM",
                                      2048))
# hand q to the whole-kv forward kernel TRANSPOSED (b, h, d, s) so the
# producer-side swapaxes fuses instead of XLA inserting a relayout copy
# at the pallas boundary (A/B flag; see _flash_fwd_pallas)
_QT = _os.environ.get("PADDLE_TPU_FLASH_QT", "0") in ("1", "true")


def _tuned_blocks(which, b, h, sq, sk, d, dtype, causal, seg_len=None):
    """(bq, bk) for the whole-kv kernels from the runtime autotune cache
    (reference: phi/kernels/autotune/cache.h AlgorithmsCache). Explicit
    env vars always win (the old behavior); cached/seeded shapes (the
    bench family ships pre-seeded) never sweep; a NEW shape on a real
    TPU is measured once standalone across a NARROW candidate set —
    narrow deliberately: big q-blocks win in kernel isolation but lose
    in the full train step (round-2 sweep), so only in-context-safe
    configs compete — and the winner is persisted to disk."""
    default = ((_BLOCK_Q, _BLOCK_K) if which == "flash_fwd"
               else (_BLOCK_Q_BWD, _BLOCK_K_BWD))
    env_keys = (("PADDLE_TPU_FLASH_BLOCK_Q", "PADDLE_TPU_FLASH_BLOCK_K")
                if which == "flash_fwd" else
                ("PADDLE_TPU_FLASH_BLOCK_Q_BWD",
                 "PADDLE_TPU_FLASH_BLOCK_K_BWD"))
    if any(k in _os.environ for k in env_keys):
        return default
    from paddle_tpu.core import autotune
    dname = {"bfloat16": "bf16", "float32": "f32",
             "float16": "f16"}.get(jnp.dtype(dtype).name,
                                   jnp.dtype(dtype).name)
    key = (f"q{sq}_s{sk}_d{d}_{dname}_c{int(bool(causal))}"
           + ("_g" if seg_len is not None else ""))
    prep: dict = {}

    def measure(cfg):
        import numpy as np
        if not prep:
            rng = np.random.default_rng(0)
            mb, mh = min(b, 2), min(h, 4)
            prep["qkv"] = [
                jnp.asarray(rng.standard_normal((mb, mh, s_, d)), dtype)
                for s_ in (sq, sk, sk)]
            if which == "flash_bwd":
                # explicit blocks: the prep forward must not trigger a
                # nested flash_fwd sweep
                o, lse = _flash_fwd_pallas(
                    *prep["qkv"], causal, 1.0 / math.sqrt(d),
                    block_q=_BLOCK_Q, block_k=_BLOCK_K, stream_kv=False,
                    seg_len=seg_len)
                prep["o"], prep["lse"] = o, lse
                prep["g"] = jnp.asarray(
                    np.random.default_rng(1).standard_normal(o.shape),
                    dtype)
        mq, mk, mv = prep["qkv"]
        if which == "flash_fwd":
            def run():
                return _flash_fwd_pallas(
                    mq, mk, mv, causal, 1.0 / math.sqrt(d),
                    block_q=cfg[0], block_k=cfg[1], stream_kv=False,
                    seg_len=seg_len)[0]
        else:
            def run():
                return _flash_bwd_pallas(
                    mq, mk, mv, prep["o"], prep["lse"], prep["g"],
                    causal, 1.0 / math.sqrt(d), block_q=cfg[0],
                    block_k=cfg[1], stream_kv=False, seg_len=seg_len)[0]
        return autotune.time_fn(run)

    cands = [c for c in ((512, 512), (256, 512), (512, 256), (256, 256))
             if c[0] <= max(sq, 256) and c[1] <= max(sk, 256)
             and (seg_len is None or seg_len % c[0] == 0)]
    bq, bk = autotune.choose(which, key, cands, measure, default)
    return bq, bk


def _prec(dtype):
    """MXU precision: bf16/f16 operands use the native one-pass mode (full
    rate, f32 accumulation); f32 operands keep exact f32. The package-global
    'highest' default would emulate bf16 matmuls in f32 at a fraction of
    the rate."""
    return (jax.lax.Precision.DEFAULT
            if dtype in (jnp.bfloat16, jnp.float16)
            else jax.lax.Precision.HIGHEST)


def _pick_block(seq, target):
    """Largest power-of-two block <= target that divides/covers seq."""
    b = min(target, max(8, 1 << (seq - 1).bit_length()))
    return min(b, target)


# ---------------------------------------------------------------------------
# Pallas forward kernel
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, sm_scale, causal,
                block_k, kv_valid, seg_len=None, q_transposed=False):
    # lse_ref is None on the inference path (save_lse=False): the LSE
    # write is only needed as the backward's softmax residual.
    # seg_len: GQA fold — the q axis is G concatenated length-seg_len
    # segments (one per q-head sharing this kv head); causal masking is
    # per-segment (row mod seg_len).
    # k arrives pre-transposed as (1, 1, d, sk): the (1),(0) contraction is
    # the fastest Mosaic form for the hot q @ k dot. ((1,),(1,)) also
    # lowers for bf16 — the backward kernels use it (verified on v5e).
    if q_transposed:   # q arrives (1, 1, d, bq): XLA's preferred
        #                activation layout — no boundary relayout copy;
        #                the score dot consumes the transposed lhs
        #                directly (contract dim-0/dim-0, no VMEM
        #                transpose). Measured -2% on v5e (BASELINE.md
        #                round-3 perf attempts) — off by default, kept
        #                for re-testing on other TPU generations.
        bq, d = q_ref.shape[3], q_ref.shape[2]
    else:
        bq, d = q_ref.shape[2], q_ref.shape[3]
    kv_pad = k_ref.shape[3]
    iq = pl.program_id(2)

    # fold log2(e) into the scale once on (bq, d) instead of an extra
    # multiply on every (bq, sk) score: all exponentials below are exp2,
    # and the saved LSE is base-2
    q = (q_ref[0, 0] * jnp.asarray(sm_scale * _LOG2E, q_ref.dtype))
    prec = _prec(q_ref.dtype)

    nk_total = kv_pad // block_k
    if causal:
        # number of k-blocks touching this q-block's (segment-local) rows
        start = iq * bq
        if seg_len is not None:
            start = start % seg_len
        nk = jnp.minimum((start + bq + block_k - 1) // block_k, nk_total)
        # blocks fully below the diagonal (and inside valid kv) need no
        # element mask at all — pure MXU + softmax
        n_full = jnp.minimum(start // block_k, kv_valid // block_k)
    else:
        nk = nk_total
        n_full = kv_valid // block_k

    # with bq == bk, aligned kv and aligned segments, the only masked
    # block is the diagonal one and its causal mask is the STATIC lower
    # triangle — loop-invariant, so Mosaic hoists it out of the masked
    # loop instead of regenerating j-offset iotas per iteration
    static_tri = (causal and bq == block_k and kv_valid % block_k == 0
                  and (seg_len is None or seg_len % block_k == 0))

    def body(j, carry, masked=True):
        m, l, acc = carry
        kj = k_ref[0, 0, :, pl.ds(j * block_k, block_k)]   # (d, bk)
        vj = v_ref[0, 0, pl.ds(j * block_k, block_k), :]   # (bk, d)
        if q_transposed:
            # q is (d, bq): contract both dim-0 — the MXU streams the
            # transposed lhs natively, no VMEM transpose
            s = jax.lax.dot_general(
                q, kj, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
                precision=prec)                          # (bq, bk) f32
        else:
            s = jax.lax.dot_general(
                q, kj, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
                precision=prec)                          # (bq, bk) f32
        # bf16: the package-global 'highest' would force an f32-contract
        # form Mosaic can't lower; bf16 inputs with f32 accumulation IS
        # the full-rate MXU mode
        if masked and static_tri:
            tri = (jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 1)
                   <= jax.lax.broadcasted_iota(jnp.int32, (bq, block_k),
                                               0))
            s = jnp.where(tri, s, _NEG_INF)
        elif masked:
            col = jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 1) \
                + j * block_k
            valid = col < kv_valid
            if causal:
                row = jax.lax.broadcasted_iota(jnp.int32, (bq, block_k),
                                               0) + start
                valid = jnp.logical_and(valid, col <= row)
            s = jnp.where(valid, s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = _exp2(s - m_new)
        alpha = jnp.exp2(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True,
                                    dtype=jnp.float32)
        acc_new = acc * alpha + jax.lax.dot_general(
            p.astype(vj.dtype), vj, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=prec)
        return m_new, l_new, acc_new

    m0 = jnp.full((bq, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    acc0 = jnp.zeros((bq, d), jnp.float32)
    carry = jax.lax.fori_loop(
        0, n_full, functools.partial(body, masked=False), (m0, l0, acc0))
    m, l, acc = jax.lax.fori_loop(n_full, nk, body, carry)
    o_ref[0, 0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
    if lse_ref is not None:
        # TRANSPOSED lse store (rows, bq): the old (bq, 8) f32 layout
        # tiled (8,128) wasted 15/16 of every tile's bandwidth (r4
        # trace: its downstream delta twin broadcast ran at 33 GB/s)
        lse_t = (m + jnp.log2(jnp.maximum(l, 1e-30))).T   # (1, bq), base-2
        lse_ref[0, 0] = jnp.broadcast_to(lse_t, (lse_ref.shape[2], bq))


def _fwd_kernel_stream(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr,
                       acc_scr, *, sm_scale, causal, kv_valid, nk_total,
                       seg_len=None):
    """4D-grid forward: grid (b, h, iq, jk) streams one k/v block per step
    with the softmax state in VMEM scratch. Used when whole-k/v no longer
    fits the per-kernel VMEM budget (long sequences); the 3D variant above
    is faster at short kv (k/v fetched once per (b,h), no per-block grid
    overhead)."""
    bq, d = q_ref.shape[2], q_ref.shape[3]
    bk = k_ref.shape[3]
    iq = pl.program_id(2)
    jk = pl.program_id(3)

    @pl.when(jk == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    start = iq * bq
    if seg_len is not None:
        start = start % seg_len
    run = (jk * bk <= start + bq - 1) if causal else True
    full = (jk + 1) * bk <= kv_valid
    if causal:
        full = jnp.logical_and(full, (jk + 1) * bk - 1 <= start)

    prec = _prec(q_ref.dtype)

    def compute(masked):
        q = (q_ref[0, 0] * jnp.asarray(sm_scale * _LOG2E, q_ref.dtype))
        kj = k_ref[0, 0]                                   # (d, bk)
        vj = v_ref[0, 0]                                   # (bk, d)
        s = jax.lax.dot_general(
            q, kj, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=prec)
        if masked:
            col = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1) \
                + jk * bk
            valid = col < kv_valid
            if causal:
                row = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0) \
                    + start
                valid = jnp.logical_and(valid, col <= row)
            s = jnp.where(valid, s, _NEG_INF)
        m = m_scr[:, :1]
        l = l_scr[:, :1]
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = _exp2(s - m_new)
        alpha = jnp.exp2(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True,
                                    dtype=jnp.float32)
        acc_scr[...] = acc_scr[...] * alpha + jax.lax.dot_general(
            p.astype(vj.dtype), vj, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=prec)
        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(jnp.logical_and(run, full))
    def _unmasked():
        compute(False)

    @pl.when(jnp.logical_and(run, jnp.logical_not(full)))
    def _masked():
        compute(True)

    @pl.when(jk == nk_total - 1)
    def _store():
        l = jnp.maximum(l_scr[:, :1], 1e-30)
        o_ref[0, 0] = (acc_scr[...] / l).astype(o_ref.dtype)
        if lse_ref is not None:
            lse_t = (m_scr[:, :1] + jnp.log2(l)).T            # (1, bq)
            lse_ref[0, 0] = jnp.broadcast_to(lse_t,
                                             (lse_ref.shape[2], bq))


# whole-k/v per grid cell is faster but caps kv length; beyond this byte
# budget (k+v resident per kernel) the streamed 4D-grid variants kick in.
# 3MB: S=8k (2.1MB k+v at d=64) stays whole-kv, S=16k (4.2MB) streams —
# the whole-kv dq kernel at 16k measured 17.5M scoped vmem (>16M limit)
# inside the full remat train step.
_KV_VMEM_BYTES = int(_os.environ.get("PADDLE_TPU_FLASH_KV_VMEM",
                                     3 * 1024 * 1024))




def _stream_block_k(sk, d, itemsize, dtype=None):
    """Streamed-path k-block width: as wide as the tuned/target width
    allows WITHOUT the per-cell resident k+v block pair exceeding the
    same VMEM budget that triggered streaming (a flat 2048 at large d or
    f32 would recreate the whole-kv overflow the budget exists to
    avoid). The target comes from the autotune cache (seeded with the
    round-2 sweep: 2048 at 8k-32k) unless the env var is set."""
    target = _BLOCK_K_STREAM
    if "PADDLE_TPU_FLASH_BLOCK_K_STREAM" not in _os.environ:
        from paddle_tpu.core import autotune
        name = jnp.dtype(dtype).name if dtype is not None else "bf16"
        name = {"bfloat16": "bf16", "float32": "f32",
                "float16": "f16"}.get(name, name)
        target = autotune.get("flash_stream_bk", f"s{sk}_{name}") \
            or _BLOCK_K_STREAM
    budget_elems = _KV_VMEM_BYTES // (2 * d * itemsize)
    capped = max(512, (budget_elems // 512) * 512)
    return min(int(target), capped, sk)


def _auto_stream_kv(sk_p, d, itemsize):
    """True when whole-k/v per (b, h) would exceed the VMEM budget (k and
    v each sk_p*d elements). Shared by fwd and bwd so both directions
    always pick the same kernel layout."""
    return sk_p * d * 2 * itemsize > _KV_VMEM_BYTES


def _ki_clamp(bq, bk, causal, seg_len):
    """For streamed k/v block index maps: clamp ki to the last block this
    q-row actually needs (causal), so above-diagonal grid steps revisit
    the previous block — Pallas elides the DMA for a repeated index —
    instead of fetching data the kernel body then skips."""
    def clamp(qi, ki):
        if not causal:
            return ki
        start = qi * bq
        if seg_len is not None:
            start = start % seg_len
        return jnp.minimum(ki, (start + bq - 1) // bk)
    return clamp


def _flash_fwd_pallas(q, k, v, causal, sm_scale, block_q=None, block_k=None,
                      interpret=False, save_lse=True, seg_len=None,
                      stream_kv=None):
    """q,k,v: (B, H, S, D) with equal head counts. seg_len: the q axis is
    G concatenated segments of this length (GQA fold; requires block
    alignment — callers gate on it). stream_kv: force (True) / forbid
    (False) the 4D streamed-kv kernel; None = auto by kv size.
    Returns (out (B,H,Sq,D), lse (B,H,8,Sq_pad) f32 TRANSPOSED | None)."""
    b, h, sq, d = q.shape
    sk = k.shape[2]
    will_stream = (stream_kv if stream_kv is not None
                   else _auto_stream_kv(sk, d, k.dtype.itemsize))
    if block_q is None and block_k is None and not will_stream:
        # streamed shapes skip whole-kv tuning entirely: sweeping the
        # whole-kv kernels at a VMEM-overflowing kv size is exactly what
        # _auto_stream_kv exists to avoid, and the streamed path picks
        # its own bk via _stream_block_k
        tq, tk = _tuned_blocks("flash_fwd", b, h, sq, sk, d, q.dtype,
                               causal, seg_len)
    else:
        tq, tk = block_q or _BLOCK_Q, block_k or _BLOCK_K
    bq = min(tq, sq)
    bk = min(tk, sk)
    if seg_len is not None:
        assert sq % seg_len == 0 and seg_len % bq == 0, (sq, seg_len, bq)
    # pad seqs to block multiples
    sq_p = (sq + bq - 1) // bq * bq
    sk_p = (sk + bk - 1) // bk * bk
    if sq_p != sq:
        q = jnp.pad(q, ((0, 0), (0, 0), (0, sq_p - sq), (0, 0)))
    if sk_p != sk:
        k = jnp.pad(k, ((0, 0), (0, 0), (0, sk_p - sk), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, sk_p - sk), (0, 0)))

    if stream_kv is None:
        stream_kv = _auto_stream_kv(sk_p, d, k.dtype.itemsize)
    if stream_kv and block_k is None:
        bk2 = _stream_block_k(sk, d, k.dtype.itemsize, k.dtype)
        if bk2 > bk:
            bk = bk2
            sk_p = (sk + bk - 1) // bk * bk
            if sk_p != k.shape[2]:
                pad = sk_p - sk
                k = jnp.pad(k[:, :, :sk],
                            ((0, 0), (0, 0), (0, pad), (0, 0)))
                v = jnp.pad(v[:, :, :sk],
                            ((0, 0), (0, 0), (0, pad), (0, 0)))
    kt = jnp.swapaxes(k, 2, 3)   # (b, h, d, sk): XLA fuses the transpose

    if stream_kv:
        kernel = functools.partial(
            _fwd_kernel_stream, sm_scale=sm_scale, causal=causal,
            kv_valid=sk, nk_total=sk_p // bk, seg_len=seg_len)
        qspec = pl.BlockSpec((1, 1, bq, d),
                             lambda bi, hi, qi, ki: (bi, hi, qi, 0))
        grid = (b, h, sq_p // bq, sk_p // bk)
        clamp = _ki_clamp(bq, bk, causal, seg_len)
        in_specs = [
            qspec,
            pl.BlockSpec((1, 1, d, bk),
                         lambda bi, hi, qi, ki: (bi, hi, 0, clamp(qi, ki))),
            pl.BlockSpec((1, 1, bk, d),
                         lambda bi, hi, qi, ki: (bi, hi, clamp(qi, ki), 0)),
        ]
        lspec = pl.BlockSpec((1, 1, _LSE_ROWS, bq),
                             lambda bi, hi, qi, ki: (bi, hi, 0, qi))
        scratch = [pltpu.VMEM((bq, _LSE_ROWS), jnp.float32),
                   pltpu.VMEM((bq, _LSE_ROWS), jnp.float32),
                   pltpu.VMEM((bq, d), jnp.float32)]
    else:
        # PADDLE_TPU_FLASH_QT=1: hand q over TRANSPOSED (b, h, d, sq)
        # so the swapaxes fuses into q's producer instead of XLA
        # inserting a relayout copy (~5ms/step, NOTES_r2) at the pallas
        # boundary; the kernel then uses a transposed-lhs dot. Measured
        # SLOWER than eating the copy on v5e — default off.
        q_t = _QT
        kernel = functools.partial(_fwd_kernel, sm_scale=sm_scale,
                                   causal=causal, block_k=bk, kv_valid=sk,
                                   seg_len=seg_len, q_transposed=q_t)
        qspec = ospec = pl.BlockSpec((1, 1, bq, d),
                                     lambda bi, hi, qi: (bi, hi, qi, 0))
        if q_t:
            q = jnp.swapaxes(q, 2, 3)
            qspec = pl.BlockSpec((1, 1, d, bq),
                                 lambda bi, hi, qi: (bi, hi, 0, qi))
        grid = (b, h, sq_p // bq)
        in_specs = [
            qspec,
            pl.BlockSpec((1, 1, d, sk_p),
                         lambda bi, hi, qi: (bi, hi, 0, 0)),
            pl.BlockSpec((1, 1, sk_p, d),
                         lambda bi, hi, qi: (bi, hi, 0, 0)),
        ]
        lspec = pl.BlockSpec((1, 1, _LSE_ROWS, bq),
                             lambda bi, hi, qi: (bi, hi, 0, qi))
        scratch = []
    if stream_kv:
        ospec = qspec
    out_specs = [ospec]
    out_shape = [jax.ShapeDtypeStruct((b, h, sq_p, d), q.dtype)]
    if save_lse:
        out_specs.append(lspec)
        out_shape.append(
            jax.ShapeDtypeStruct((b, h, _LSE_ROWS, sq_p), jnp.float32))
    else:
        kernel = functools.partial(
            lambda q_ref, k_ref, v_ref, o_ref, *scr, kern: kern(
                q_ref, k_ref, v_ref, o_ref, None, *scr), kern=kernel)
    outs = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=scratch,
        interpret=interpret,
    )(q, kt, v)
    out = outs[0]
    lse = outs[1] if save_lse else None
    return out[:, :, :sq, :], lse


# ---------------------------------------------------------------------------
# Pallas backward kernels (FA2: recompute p from LSE, no O(S^2) residuals)
# ---------------------------------------------------------------------------

def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   *, sm_scale, causal, block_k, kv_valid, seg_len=None):
    bq, d = q_ref.shape[2], q_ref.shape[3]
    kv_pad = k_ref.shape[2]
    iq = pl.program_id(2)

    q = (q_ref[0, 0] * jnp.asarray(sm_scale * _LOG2E, q_ref.dtype))
    do = do_ref[0, 0]
    lse = lse_ref[0, 0, :1, :].T                   # (bq, 1) f32
    delta = delta_ref[0, 0, :1, :].T               # (bq, 1) f32
    prec = _prec(q_ref.dtype)

    nk_total = kv_pad // block_k
    if causal:
        start = iq * bq
        if seg_len is not None:
            start = start % seg_len
        nk = jnp.minimum((start + bq + block_k - 1) // block_k, nk_total)
        n_full = jnp.minimum(start // block_k, kv_valid // block_k)
    else:
        nk = nk_total
        n_full = kv_valid // block_k

    def body(j, acc, masked=True):
        kj = k_ref[0, 0, pl.ds(j * block_k, block_k), :]   # (bk, d)
        vj = v_ref[0, 0, pl.ds(j * block_k, block_k), :]   # (bk, d)
        s = jax.lax.dot_general(
            q, kj, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32, precision=prec)  # (bq, bk)
        if masked:
            col = jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 1) \
                + j * block_k
            valid = col < kv_valid
            if causal:
                row = jax.lax.broadcasted_iota(jnp.int32, (bq, block_k),
                                               0) + start
                valid = jnp.logical_and(valid, col <= row)
            s = jnp.where(valid, s, _NEG_INF)
        p = _exp2(s - lse)                                   # (bq, bk)
        dp = jax.lax.dot_general(
            do, vj, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32, precision=prec)  # (bq, bk)
        ds = p * (dp - delta) * sm_scale
        return acc + jax.lax.dot_general(
            ds.astype(kj.dtype), kj, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=prec)  # (bq, d)

    acc0 = jnp.zeros((bq, d), jnp.float32)
    acc = jax.lax.fori_loop(0, n_full,
                            functools.partial(body, masked=False), acc0)
    acc = jax.lax.fori_loop(n_full, nk, body, acc)
    dq_ref[0, 0] = acc.astype(dq_ref.dtype)


def _bwd_dq_kernel_stream(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          dq_ref, acc_scr, *, sm_scale, causal, kv_valid,
                          nk_total, seg_len=None):
    """4D-grid dq: grid (b, h, iq, jk) streams one k/v block per step,
    dq accumulates in VMEM scratch (long-kv counterpart of
    _bwd_dq_kernel, same reasoning as _fwd_kernel_stream)."""
    bq, d = q_ref.shape[2], q_ref.shape[3]
    bk = k_ref.shape[2]
    iq = pl.program_id(2)
    jk = pl.program_id(3)

    @pl.when(jk == 0)
    def _init():
        acc_scr[...] = jnp.zeros_like(acc_scr)

    start = iq * bq
    if seg_len is not None:
        start = start % seg_len
    run = (jk * bk <= start + bq - 1) if causal else True
    full = (jk + 1) * bk <= kv_valid
    if causal:
        full = jnp.logical_and(full, (jk + 1) * bk - 1 <= start)

    prec = _prec(q_ref.dtype)

    def compute(masked):
        q = (q_ref[0, 0] * jnp.asarray(sm_scale * _LOG2E, q_ref.dtype))
        do = do_ref[0, 0]
        lse = lse_ref[0, 0, :1, :].T
        delta = delta_ref[0, 0, :1, :].T
        kj = k_ref[0, 0]                                   # (bk, d)
        vj = v_ref[0, 0]                                   # (bk, d)
        s = jax.lax.dot_general(
            q, kj, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32, precision=prec)
        if masked:
            col = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1) \
                + jk * bk
            valid = col < kv_valid
            if causal:
                row = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0) \
                    + start
                valid = jnp.logical_and(valid, col <= row)
            s = jnp.where(valid, s, _NEG_INF)
        p = _exp2(s - lse)
        dp = jax.lax.dot_general(
            do, vj, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32, precision=prec)
        ds = p * (dp - delta) * sm_scale
        acc_scr[...] += jax.lax.dot_general(
            ds.astype(kj.dtype), kj, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=prec)

    @pl.when(jnp.logical_and(run, full))
    def _unmasked():
        compute(False)

    @pl.when(jnp.logical_and(run, jnp.logical_not(full)))
    def _masked():
        compute(True)

    @pl.when(jk == nk_total - 1)
    def _store():
        dq_ref[0, 0] = acc_scr[...].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_scr, dv_scr, *, sm_scale, causal,
                    nq_total, q_valid, kv_valid, seg_len=None):
    # Grid (b, h, ik, jq): jq (fastest axis) streams q/do/lse/delta blocks
    # while k/v stay resident (same block index => Pallas skips the DMA);
    # dk/dv accumulate in VMEM scratch and store once at the last jq.
    # This keeps per-cell VMEM O(bq + bk) — a flat q stream would need the
    # whole (folded) q/lse/delta per cell and overflows VMEM.
    bk, d = k_ref.shape[2], k_ref.shape[3]
    bq = q_ref.shape[2]
    ik = pl.program_id(2)
    jq = pl.program_id(3)

    @pl.when(jq == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    # segment-local start row of this q block (GQA fold: causality is per
    # length-seg_len segment)
    start = jq * bq
    if seg_len is not None:
        start = start % seg_len
    run = (start + bq - 1 >= ik * bk) if causal else True
    # cells with every (row, col) pair valid skip the element mask
    full = jnp.logical_and((ik + 1) * bk <= kv_valid,
                           (jq + 1) * bq <= q_valid)
    if causal:
        full = jnp.logical_and(full, (ik + 1) * bk - 1 <= start)

    def _compute(masked):
        # everything in the TRANSPOSED (bk, bq) orientation: sT = k·qT,
        # so dv = pT·do and dk = dsT·q contract directly with no (bq,bk)
        # transposes on the hot path (only the (bq,1) lse/delta vectors
        # get relaid out to (1,bq))
        prec = _prec(q_ref.dtype)
        k = k_ref[0, 0]                                         # (bk, d)
        v = v_ref[0, 0]                                         # (bk, d)
        qj = (q_ref[0, 0]
              * jnp.asarray(sm_scale * _LOG2E, q_ref.dtype))    # (bq, d)
        doj = do_ref[0, 0]                                      # (bq, d)
        lse_t = lse_ref[0, 0, :1, :]                            # (1, bq)
        delta_t = delta_ref[0, 0, :1, :]                        # (1, bq)
        s_t = jax.lax.dot_general(
            k, qj, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32, precision=prec)  # (bk, bq)
        if masked:
            col = jax.lax.broadcasted_iota(jnp.int32, (bk, bq), 0) \
                + ik * bk
            row_g = jax.lax.broadcasted_iota(jnp.int32, (bk, bq), 1) \
                + jq * bq
            valid = jnp.logical_and(col < kv_valid, row_g < q_valid)
            if causal:
                row_c = jax.lax.broadcasted_iota(jnp.int32, (bk, bq), 1) \
                    + start
                valid = jnp.logical_and(valid, col <= row_c)
            s_t = jnp.where(valid, s_t, _NEG_INF)
        p_t = _exp2(s_t - lse_t)                             # (bk, bq)
        dv_scr[...] += jax.lax.dot_general(
            p_t.astype(doj.dtype), doj, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=prec)  # (bk, d)
        dp_t = jax.lax.dot_general(
            v, doj, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32, precision=prec)  # (bk, bq)
        ds_t = p_t * (dp_t - delta_t) * sm_scale                 # (bk, bq)
        dk_scr[...] += jax.lax.dot_general(
            ds_t.astype(qj.dtype), qj, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=prec)  # (bk, d)

    @pl.when(jnp.logical_and(run, full))
    def _compute_unmasked():
        _compute(False)

    @pl.when(jnp.logical_and(run, jnp.logical_not(full)))
    def _compute_masked():
        _compute(True)

    @pl.when(jq == nq_total - 1)
    def _store():
        # undo the sm_scale*log2e folded into qj when accumulating dk
        # (dk = ds^T @ q with q unscaled; qj above was pre-scaled for s)
        dk_ref[0, 0] = (dk_scr[...] / (sm_scale * _LOG2E)).astype(
            dk_ref.dtype)
        dv_ref[0, 0] = dv_scr[...].astype(dv_ref.dtype)


def _bwd_fused_kernel(q_ref, k_ref, v_ref, do_ref, o_ref, lse_ref,
                      dq_ref, dk_ref, dv_ref, dk_scr, dv_scr, *,
                      sm_scale, causal, block_k, q_valid, kv_valid,
                      nq_total, seg_len=None):
    """Single-pass FA2 backward: dq, dk and dv from ONE softmax recompute.

    Grid (b, h, jq). Per (b, h): k/v stay VMEM-resident (constant block
    index => one DMA); q/do/o and the transposed (8, bq) lse stream per
    q-block — each block is read exactly once per (b, h) sweep, so this
    costs the same HBM bytes as keeping them resident. delta comes from
    o IN-REGISTER (sum(do*o)), not a materialized array. dq accumulates in
    the fori_loop carry and writes per cell; dk/dv accumulate across the
    whole jq sweep in full-kv f32 scratch and store once at the last jq
    (the dk/dv output block index is constant per (b, h), so Pallas
    flushes it exactly once).

    vs the round-1 dq+dkv kernel pair this halves the softmax recompute
    (the dominant VPU cost: ds is shared by dk AND dq), reads each
    lse/delta element once instead of once per kv block, and needs no
    extra matmul for dq beyond ds_t @ k (ds is already in registers).
    Everything runs in the transposed (bk, bq) orientation so no
    (bq, bk) block ever needs a transpose.
    """
    bq, d = q_ref.shape[2], q_ref.shape[3]
    kv_pad = k_ref.shape[2]
    jq = pl.program_id(2)

    @pl.when(jq == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    qj = q_ref[0, 0] * jnp.asarray(sm_scale * _LOG2E, q_ref.dtype)  # (bq,d)
    doj = do_ref[0, 0]                                              # (bq,d)
    lse_t = lse_ref[0, 0, :1, :]                                    # (1,bq)
    # delta = sum(do * o) computed IN-REGISTER from the streamed o
    # block: the old materialized delta was a (b, h, sq, 8) f32 array
    # whose (8,128) tile padding made its broadcast write run at
    # ~33 GB/s — 4.3 ms/step of pure layout waste (r4 trace)
    delta_t = jnp.sum(doj.astype(jnp.float32)
                      * o_ref[0, 0].astype(jnp.float32),
                      axis=-1)[None, :]                             # (1,bq)
    prec = _prec(q_ref.dtype)

    start_g = jq * bq                    # global row (q_valid mask)
    start = start_g % seg_len if seg_len is not None else start_g
    nk_total = kv_pad // block_k
    if causal:
        nk = jnp.minimum((start + bq + block_k - 1) // block_k, nk_total)
        n_full = jnp.minimum(start // block_k, kv_valid // block_k)
    else:
        nk = nk_total
        n_full = kv_valid // block_k
    # rows past q_valid must not contribute to dk/dv: no mask-free blocks
    # unless every row of this q-block is valid
    n_full = jnp.where((jq + 1) * bq <= q_valid, n_full, 0)

    # see _fwd_kernel: on fully-aligned shapes the masked block is the
    # diagonal one with a STATIC (transposed) triangular mask
    static_tri = (causal and bq == block_k and kv_valid % block_k == 0
                  and q_valid % bq == 0
                  and (seg_len is None or seg_len % block_k == 0))

    def body(j, dq_acc, masked=True):
        kj = k_ref[0, 0, pl.ds(j * block_k, block_k), :]   # (bk, d)
        vj = v_ref[0, 0, pl.ds(j * block_k, block_k), :]   # (bk, d)
        s_t = jax.lax.dot_general(
            kj, qj, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32, precision=prec)  # (bk,bq)
        if masked and static_tri:
            tri_t = (jax.lax.broadcasted_iota(jnp.int32, (block_k, bq), 0)
                     <= jax.lax.broadcasted_iota(jnp.int32, (block_k, bq),
                                                 1))
            s_t = jnp.where(tri_t, s_t, _NEG_INF)
        elif masked:
            col = jax.lax.broadcasted_iota(
                jnp.int32, (block_k, bq), 0) + j * block_k
            row_g = jax.lax.broadcasted_iota(
                jnp.int32, (block_k, bq), 1) + start_g
            valid = jnp.logical_and(col < kv_valid, row_g < q_valid)
            if causal:
                row_c = jax.lax.broadcasted_iota(
                    jnp.int32, (block_k, bq), 1) + start
                valid = jnp.logical_and(valid, col <= row_c)
            s_t = jnp.where(valid, s_t, _NEG_INF)
        p_t = _exp2(s_t - lse_t)                                 # (bk,bq)
        dv_scr[pl.ds(j * block_k, block_k)] += jax.lax.dot_general(
            p_t.astype(doj.dtype), doj, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=prec)  # (bk,d)
        dp_t = jax.lax.dot_general(
            vj, doj, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32, precision=prec)  # (bk,bq)
        ds_t = p_t * (dp_t - delta_t) * sm_scale                 # true ds^T
        ds_lp = ds_t.astype(qj.dtype)
        dk_scr[pl.ds(j * block_k, block_k)] += jax.lax.dot_general(
            ds_lp, qj, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=prec)  # (bk,d)
        return dq_acc + jax.lax.dot_general(
            ds_lp, kj, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=prec)  # (bq,d)

    dq0 = jnp.zeros((bq, d), jnp.float32)
    dq_acc = jax.lax.fori_loop(0, n_full,
                               functools.partial(body, masked=False), dq0)
    dq_acc = jax.lax.fori_loop(n_full, nk, body, dq_acc)
    dq_ref[0, 0] = dq_acc.astype(dq_ref.dtype)

    @pl.when(jq == nq_total - 1)
    def _store():
        # dk accumulated against the log2e/sm_scale-prescaled q; undo it
        dk_ref[0, 0] = (dk_scr[...] / (sm_scale * _LOG2E)).astype(
            dk_ref.dtype)
        dv_ref[0, 0] = dv_scr[...].astype(dv_ref.dtype)


# fused single-kernel backward needs k+v resident AND full-kv f32 dk/dv
# scratch (2x k+v bytes in f32) in VMEM; above this k+v byte budget fall
# back to the round-1 dq + dkv kernel pair. 1MB measured safe on v5e
# (16MB scoped vmem); 2MB compiled standalone but blew the scoped limit
# inside the full train step at S=8k (co-scheduled ops share VMEM).
_FUSED_KV_BYTES = int(_os.environ.get("PADDLE_TPU_FLASH_FUSED_KV",
                                      1024 * 1024))


def _flash_bwd_pallas(q, k, v, o, lse, g, causal, sm_scale,
                      block_q=None, block_k=None, interpret=False,
                      seg_len=None, stream_kv=None, fused=None):
    """FA2 backward. q,k,v,o,g: (B,H,S,D); lse: (B,H,rows,Sq_pad) f32
    TRANSPOSED layout (full (8,128) tiles — see the fwd kernel note)."""
    b, h, sq, d = q.shape
    sk = k.shape[2]
    will_stream = (stream_kv if stream_kv is not None
                   else _auto_stream_kv(sk, d, k.dtype.itemsize))
    if block_q is None and block_k is None and not will_stream:
        tq, tk = _tuned_blocks("flash_bwd", b, h, sq, sk, d, q.dtype,
                               causal, seg_len)
    else:
        tq, tk = block_q or _BLOCK_Q_BWD, block_k or _BLOCK_K_BWD
    bq = min(tq, sq)
    bk = min(tk, sk)
    if seg_len is not None:
        assert sq % seg_len == 0 and seg_len % bq == 0, (sq, seg_len, bq)
    sq_p = (sq + bq - 1) // bq * bq
    sk_p = (sk + bk - 1) // bk * bk

    # lse was padded with the FORWARD block size; reconcile to ours
    # (padded rows are masked in dkv and sliced off dq, values don't matter)
    if lse.shape[3] > sq_p:
        lse = lse[..., :sq_p]
    elif lse.shape[3] < sq_p:
        lse = jnp.pad(lse, ((0, 0), (0, 0), (0, 0),
                            (0, sq_p - lse.shape[3])))
    if sq_p != sq:
        pad = ((0, 0), (0, 0), (0, sq_p - sq), (0, 0))
        q = jnp.pad(q, pad)
        g = jnp.pad(g, pad)
        o = jnp.pad(o, pad)
    if sk_p != sk:
        pad = ((0, 0), (0, 0), (0, sk_p - sk), (0, 0))
        k = jnp.pad(k, pad)
        v = jnp.pad(v, pad)

    if stream_kv is None:
        stream_kv = _auto_stream_kv(sk_p, d, k.dtype.itemsize)
    if stream_kv and block_k is None:
        bk2 = _stream_block_k(sk, d, k.dtype.itemsize, k.dtype)
        if bk2 > bk:
            bk = bk2
            sk_p = (sk + bk - 1) // bk * bk
            if k.shape[2] != sk_p:     # re-pad from the valid prefix
                pad = ((0, 0), (0, 0), (0, sk_p - sk), (0, 0))
                k = jnp.pad(k[:, :, :sk], pad)
                v = jnp.pad(v[:, :, :sk], pad)
    if fused is None:
        fused = (not stream_kv
                 and sk_p * d * 2 * k.dtype.itemsize <= _FUSED_KV_BYTES)
    elif fused and stream_kv:
        raise ValueError(
            "fused=True requires the whole-kv layout but stream_kv "
            "resolved True for this kv size; pass stream_kv=False or "
            "raise PADDLE_TPU_FLASH_KV_VMEM")

    if fused:
        qspec = pl.BlockSpec((1, 1, bq, d),
                             lambda bi, hi, qi: (bi, hi, qi, 0))
        kres = pl.BlockSpec((1, 1, sk_p, d),
                            lambda bi, hi, qi: (bi, hi, 0, 0))
        # lse/delta stream per q-block: each block is read exactly once
        # per (b, h) sweep, so streaming costs the same HBM bytes as
        # whole-resident, without dynamic sublane slicing in-kernel
        lres = pl.BlockSpec((1, 1, _LSE_ROWS, bq),
                            lambda bi, hi, qi: (bi, hi, 0, qi))
        dq, dk, dv = pl.pallas_call(
            functools.partial(_bwd_fused_kernel, sm_scale=sm_scale,
                              causal=causal, block_k=bk, q_valid=sq,
                              kv_valid=sk, nq_total=sq_p // bq,
                              seg_len=seg_len),
            grid=(b, h, sq_p // bq),
            in_specs=[qspec, kres, kres, qspec, qspec, lres],
            out_specs=[qspec, kres, kres],
            out_shape=[jax.ShapeDtypeStruct((b, h, sq_p, d), q.dtype),
                       jax.ShapeDtypeStruct((b, h, sk_p, d), k.dtype),
                       jax.ShapeDtypeStruct((b, h, sk_p, d), v.dtype)],
            scratch_shapes=[pltpu.VMEM((sk_p, d), jnp.float32),
                            pltpu.VMEM((sk_p, d), jnp.float32)],
            interpret=interpret,
        )(q, k, v, g, o, lse)
        return (dq[:, :, :sq, :], dk[:, :, :sk, :], dv[:, :, :sk, :])

    # non-fused paths (streamed / dq+dkv pair) still consume the
    # materialized lane-broadcast delta (their kernels read it per
    # (q-block, kv-block) pair, where recomputing from o would re-read
    # o once per kv block)
    delta = jnp.sum(g.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    delta = jnp.broadcast_to(delta[:, :, None, :],
                             delta.shape[:2] + (_LSE_ROWS,)
                             + delta.shape[2:])

    if stream_kv:
        clamp = _ki_clamp(bq, bk, causal, seg_len)
        qspec4q = pl.BlockSpec((1, 1, bq, d),
                               lambda bi, hi, qi, ki: (bi, hi, qi, 0))
        kspec4q = pl.BlockSpec((1, 1, bk, d),
                               lambda bi, hi, qi, ki: (bi, hi,
                                                       clamp(qi, ki), 0))
        lspec4q = pl.BlockSpec((1, 1, _LSE_ROWS, bq),
                               lambda bi, hi, qi, ki: (bi, hi, 0, qi))
        dq = pl.pallas_call(
            functools.partial(_bwd_dq_kernel_stream, sm_scale=sm_scale,
                              causal=causal, kv_valid=sk,
                              nk_total=sk_p // bk, seg_len=seg_len),
            grid=(b, h, sq_p // bq, sk_p // bk),
            in_specs=[qspec4q, kspec4q, kspec4q, qspec4q, lspec4q, lspec4q],
            out_specs=qspec4q,
            out_shape=jax.ShapeDtypeStruct((b, h, sq_p, d), q.dtype),
            scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
            interpret=interpret,
        )(q, k, v, g, lse, delta)
    else:
        qspec = pl.BlockSpec((1, 1, bq, d),
                             lambda bi, hi, qi: (bi, hi, qi, 0))
        kfull = pl.BlockSpec((1, 1, sk_p, d),
                             lambda bi, hi, qi: (bi, hi, 0, 0))
        lspec = pl.BlockSpec((1, 1, _LSE_ROWS, bq),
                             lambda bi, hi, qi: (bi, hi, 0, qi))
        dq = pl.pallas_call(
            functools.partial(_bwd_dq_kernel, sm_scale=sm_scale,
                              causal=causal, block_k=bk, kv_valid=sk,
                              seg_len=seg_len),
            grid=(b, h, sq_p // bq),
            in_specs=[qspec, kfull, kfull, qspec, lspec, lspec],
            out_specs=qspec,
            out_shape=jax.ShapeDtypeStruct((b, h, sq_p, d), q.dtype),
            interpret=interpret,
        )(q, k, v, g, lse, delta)

    nq_total = sq_p // bq
    kspec4 = pl.BlockSpec((1, 1, bk, d),
                          lambda bi, hi, ki, qi: (bi, hi, ki, 0))
    qspec4 = pl.BlockSpec((1, 1, bq, d),
                          lambda bi, hi, ki, qi: (bi, hi, qi, 0))
    lspec4 = pl.BlockSpec((1, 1, _LSE_ROWS, bq),
                          lambda bi, hi, ki, qi: (bi, hi, 0, qi))
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, sm_scale=sm_scale, causal=causal,
                          nq_total=nq_total, q_valid=sq, kv_valid=sk,
                          seg_len=seg_len),
        grid=(b, h, sk_p // bk, nq_total),
        in_specs=[qspec4, kspec4, kspec4, qspec4, lspec4, lspec4],
        out_specs=[kspec4, kspec4],
        out_shape=[jax.ShapeDtypeStruct((b, h, sk_p, d), k.dtype),
                   jax.ShapeDtypeStruct((b, h, sk_p, d), v.dtype)],
        scratch_shapes=[pltpu.VMEM((bk, d), jnp.float32),
                        pltpu.VMEM((bk, d), jnp.float32)],
        interpret=interpret,
    )(q, k, v, g, lse, delta)

    return (dq[:, :, :sq, :], dk[:, :, :sk, :], dv[:, :, :sk, :])


# ---------------------------------------------------------------------------
# Chunked (blockwise) attention in pure jax — CPU fallback path
# ---------------------------------------------------------------------------

def _chunked_attention(q, k, v, causal, sm_scale, block_q=512, block_k=512):
    """(B,H,S,D) exact attention via online softmax over k/v blocks.
    jax.checkpoint per block => O(S·D) residuals under AD."""
    b, h, sq, d = q.shape
    sk = k.shape[2]
    bq = min(block_q, sq)
    bk = min(block_k, sk)
    sq_p = (sq + bq - 1) // bq * bq
    sk_p = (sk + bk - 1) // bk * bk
    qp = jnp.pad(q, ((0, 0), (0, 0), (0, sq_p - sq), (0, 0)))
    kp = jnp.pad(k, ((0, 0), (0, 0), (0, sk_p - sk), (0, 0)))
    vp = jnp.pad(v, ((0, 0), (0, 0), (0, sk_p - sk), (0, 0)))
    nq, nk = sq_p // bq, sk_p // bk

    qb = qp.reshape(b, h, nq, bq, d)
    kb = kp.reshape(b, h, nk, bk, d)
    vb = vp.reshape(b, h, nk, bk, d)

    @jax.checkpoint
    def block(qi, kj, vj, iq, jk):
        prec = _prec(qi.dtype)
        qf = qi * jnp.asarray(sm_scale, qi.dtype)
        s = jnp.einsum("...qd,...kd->...qk", qf, kj,
                       preferred_element_type=jnp.float32,
                       precision=prec)
        col = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1) + jk * bk
        valid = col < sk
        if causal:
            row = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0) + iq * bq
            valid = jnp.logical_and(valid, col <= row)
        s = jnp.where(valid, s, _NEG_INF)
        m = jnp.max(s, axis=-1, keepdims=True)
        p = jnp.exp(s - m)
        l = jnp.sum(p, axis=-1, keepdims=True)
        o = jnp.einsum("...qk,...kd->...qd", p.astype(vj.dtype), vj,
                       preferred_element_type=jnp.float32,
                       precision=prec)
        return m, l, o

    def q_block(iq, qi):
        def kv_step(carry, jk):
            m, l, acc = carry
            mj, lj, oj = block(qi, kb[:, :, jk], vb[:, :, jk], iq, jk)
            m_new = jnp.maximum(m, mj)
            alpha = jnp.exp(m - m_new)
            beta = jnp.exp(mj - m_new)
            l_new = l * alpha + lj * beta
            acc_new = acc * alpha + oj * beta
            return (m_new, l_new, acc_new), None

        m0 = jnp.full((b, h, bq, 1), _NEG_INF, jnp.float32)
        l0 = jnp.zeros((b, h, bq, 1), jnp.float32)
        a0 = jnp.zeros((b, h, bq, d), jnp.float32)
        (m, l, acc), _ = jax.lax.scan(kv_step, (m0, l0, a0),
                                      jnp.arange(nk))
        return (acc / jnp.maximum(l, 1e-30)).astype(q.dtype)

    outs = jax.lax.map(lambda i: q_block(i, qb[:, :, i]), jnp.arange(nq))
    out = jnp.moveaxis(outs, 0, 2).reshape(b, h, sq_p, d)
    return out[:, :, :sq, :]


# ---------------------------------------------------------------------------
# custom_vjp glue
# ---------------------------------------------------------------------------

from paddle_tpu.core.jax_compat import on_tpu as _on_tpu  # noqa: E402


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash(q, k, v, causal, sm_scale, seg_len):
    if _on_tpu():
        return _flash_fwd_pallas(q, k, v, causal, sm_scale,
                                 save_lse=False, seg_len=seg_len)[0]
    assert seg_len is None  # the GQA fold is only taken on the TPU path
    return _chunked_attention(q, k, v, causal, sm_scale)


def _flash_fwd_rule(q, k, v, causal, sm_scale, seg_len):
    if _on_tpu():
        out, lse = _flash_fwd_pallas(q, k, v, causal, sm_scale,
                                     seg_len=seg_len)
        return out, (q, k, v, out, lse)
    assert seg_len is None
    return _chunked_attention(q, k, v, causal, sm_scale), (q, k, v, None,
                                                          None)


def _flash_bwd_rule(causal, sm_scale, seg_len, res, g):
    q, k, v, o, lse = res
    if lse is not None:
        return _flash_bwd_pallas(q, k, v, o, lse, g, causal, sm_scale,
                                 seg_len=seg_len)
    _, vjp = jax.vjp(
        lambda q_, k_, v_: _chunked_attention(q_, k_, v_, causal, sm_scale),
        q, k, v)
    return vjp(g)


_flash.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def flash_attention_bhsd(q, k, v, causal=False, sm_scale=None):
    """(B, H, S, D) entry. GQA: kv head count may divide q head count.

    On TPU, GQA takes the fold path: q (B, G*Hk, S, D) is bitcast to
    (B, Hk, G*S, D) — adjacent q-heads share a kv head — so the kernels
    stream each kv head once instead of G repeated copies, and dk/dv come
    out per-kv-head directly (no XLA group-reduction). Requires the
    segment length S to align with the q block sizes; otherwise falls
    back to jnp.repeat of k/v.
    """
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    hq, hk = q.shape[1], k.shape[1]
    if hk != hq:
        rep = hq // hk
        b, _, s, d = q.shape
        bq_f = min(_BLOCK_Q, rep * s)
        bq_b = min(_BLOCK_Q_BWD, rep * s)
        if _on_tpu() and hq % hk == 0 and s % bq_f == 0 and s % bq_b == 0:
            qf = q.reshape(b, hk, rep * s, d)
            out = _flash(qf, k, v, causal, sm_scale, s)
            return out.reshape(b, hq, s, d)
        k = jnp.repeat(k, rep, axis=1)
        v = jnp.repeat(v, rep, axis=1)
    return _flash(q, k, v, causal, sm_scale, None)


def flash_attention_bshd(q, k, v, causal=False, sm_scale=None):
    """Paddle layout (B, S, H, D) (reference flash_attention surface)."""
    out = flash_attention_bhsd(
        jnp.swapaxes(q, 1, 2), jnp.swapaxes(k, 1, 2),
        jnp.swapaxes(v, 1, 2), causal=causal, sm_scale=sm_scale)
    return jnp.swapaxes(out, 1, 2)
