"""Pallas paged-attention decode kernel (TPU), with int8 KV dequant.

The serving hot path: PagedKVEngine's decode tick attends ONE query row
per slot over that slot's whole paged KV window. The jnp path in
inference/paged.py gathers every slot's full page window into a dense
(b, hk, L, d) array, repeats it across query heads for GQA, and runs a
dense masked softmax — O(window) HBM gather traffic plus hq/hk x
materialization per layer per decode step. This kernel is the
vLLM-PagedAttention-style replacement (Kwon et al., SOSP'23; same
capability as the reference's block_multi_head_attention_kernel.cu
decode branch):

- the page pools (num_pages, hk, page_size, d) stay in HBM; the grid is
  (slot, kv_head, page) and the k/v BlockSpec index_map reads the
  BLOCK TABLE (a scalar-prefetch operand, SMEM-resident before the body
  runs) to DMA exactly the pages the slot owns — no dense gather, no
  copy of anyone else's pages;
- GQA is handled by the same head-fold trick as flash_attention.py:
  the g = hq//hk query heads sharing a kv head ride ONE (g, d) q tile,
  so k/v pages are streamed once per kv head instead of materializing
  jnp.repeat'ed copies;
- softmax is the online accumulator from the flash kernels (base-2
  exponentials, log2e folded into the q scale once), carried in VMEM
  scratch across the page axis; pages past the slot's length are
  skipped via pl.when AND their DMA is elided by clamping the index
  map to the last needed page (the _ki_clamp trick);
- int8 KV pools dequantize INSIDE the K-loop: scores/values are
  computed from the int8 page block and scaled by the per-page-per-head
  f32 scale AFTER the dot (scalar multiply), so the bf16/f32 pool is
  never materialized in HBM — the quant_matmul.py lesson applied to KV.

Masking contract: query position per slot is `lens[i]` (the new token's
k/v is already scattered at that position), so column c is visible iff
c <= lens[i]. Unallocated / partial pages therefore never contribute.

Runs under `interpret=True` on CPU (tier-1 exercises exact greedy
parity vs the jnp path this way); on real TPUs the compiled kernel is
the decode hot loop.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from paddle_tpu.core.jax_compat import tpu_compiler_params

__all__ = ["paged_decode_attention", "decode_shape_problems",
           "check_decode_shapes"]

_NEG_INF = -1e30
_LOG2E = 1.4426950408889634


def _prec(dtype):
    return (jax.lax.Precision.DEFAULT
            if dtype in (jnp.bfloat16, jnp.float16)
            else jax.lax.Precision.HIGHEST)


# Mosaic minimum sublane tile by element size: int8 (32, 128),
# bf16/f16 (16, 128), f32 (8, 128) — the (page_size, d) k/v block's
# sublane dim must tile it when compiled for a real TPU
_MIN_SUBLANE = {1: 32, 2: 16, 4: 8}


def decode_shape_problems(hq, hk, d, page_size, interpret=False,
                          kv_dtype=None):
    """Reasons this (hq, hk, d, page_size) geometry cannot take the
    Pallas decode kernel; empty list = supported. Mirrors
    `_ring_flash_plan`'s role for ring attention: the AUTO path gates
    on this, the forced path turns the reasons into a ValueError.
    `kv_dtype` is the POOL dtype (the sublane tile is dtype-dependent:
    int8 pools need page_size % 32, bf16 % 16, f32 % 8)."""
    problems = []
    if hk <= 0 or hq % hk != 0:
        problems.append(f"q heads must be a multiple of kv heads "
                        f"(hq={hq}, hk={hk})")
    if not interpret:
        # compiled Mosaic wants tileable (page_size, d) k/v blocks;
        # interpret mode (CPU tier-1) has no tiling constraint
        dt = jnp.dtype(kv_dtype if kv_dtype is not None
                       else jnp.float32)
        sub = _MIN_SUBLANE.get(dt.itemsize, 8)
        if d % 8 != 0:
            problems.append(f"head_dim % 8 == 0 required on TPU "
                            f"(got d={d})")
        if page_size % sub != 0:
            problems.append(f"page_size % {sub} == 0 required on TPU "
                            f"for {dt.name} pools (got "
                            f"page_size={page_size})")
    return problems


def check_decode_shapes(hq, hk, d, page_size, interpret=False,
                        kv_dtype=None):
    """Raise a descriptive ValueError naming every misaligned dim when
    the kernel cannot run (same contract as
    `ring_attention_local(use_flash=True)`); no-op when supported."""
    problems = decode_shape_problems(hq, hk, d, page_size, interpret,
                                     kv_dtype)
    if problems:
        raise ValueError(
            "paged_decode_attention: shapes cannot take the Pallas "
            "decode kernel — " + "; ".join(problems)
            + '; use kernel="jnp" for the gather/softmax fallback')


def _decode_kernel(bt_ref, lens_ref, kscale_ref, vscale_ref,
                   q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr, *,
                   page_size, sm_scale, quantized):
    """Grid (b, hk, max_pages). Scalar-prefetch refs: block tables
    (b, mp) i32, lens (b,) i32, and — quantized pools only — the
    PER-SLOT gathered f32 scales (b, mp, hk) in SMEM (gathered from
    the (num_pages, hk) planes outside the kernel so SMEM use scales
    with the batch, not the pool). k_ref/v_ref are ONE page block
    (1, 1, page_size, d), DMA'd by the index_map through the block
    table."""
    bi = pl.program_id(0)
    hi = pl.program_id(1)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    pos = lens_ref[bi]                   # query position of this slot
    last = pos // page_size              # last page the window touches
    gp, d = q_ref.shape[2], q_ref.shape[3]
    prec = _prec(q_ref.dtype)

    @pl.when(j <= last)
    def _compute():
        # log2e folded into the (gp, d) q tile once; exponentials below
        # are exp2 (flash_attention.py convention)
        q = q_ref[0, 0] * jnp.asarray(sm_scale * _LOG2E, q_ref.dtype)
        kj = k_ref[0, 0]                              # (ps, d)
        vj = v_ref[0, 0]
        if quantized:
            # fuse-the-convert: int8 -> f32 in REGISTER, dot, then one
            # scalar multiply per page block (the per-page-per-head
            # scale) — the dequantized page never exists in HBM
            kj = kj.astype(jnp.float32)
            vj = vj.astype(jnp.float32)
            q = q.astype(jnp.float32)
            s = jax.lax.dot_general(
                q, kj, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
                precision=prec) * kscale_ref[bi, j, hi]  # (gp, ps)
        else:
            s = jax.lax.dot_general(
                q, kj, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
                precision=prec)                          # (gp, ps)
        col = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1) \
            + j * page_size
        s = jnp.where(col <= pos, s, _NEG_INF)
        m = m_scr[:, :1]
        l = l_scr[:, :1]
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp2(s - m_new)
        alpha = jnp.exp2(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True,
                                    dtype=jnp.float32)
        pv = jax.lax.dot_general(
            p.astype(vj.dtype), vj, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=prec)
        if quantized:
            pv = pv * vscale_ref[bi, j, hi]
        acc_scr[...] = acc_scr[...] * alpha + pv
        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(j == pl.num_programs(2) - 1)
    def _store():
        o_ref[0, 0] = (acc_scr[...]
                       / jnp.maximum(l_scr[:, :1], 1e-30))


def _decode_kernel_noquant(bt_ref, lens_ref, *rest, **kw):
    """Unquantized pools carry no scale operands: splice None refs into
    _decode_kernel's scale slots."""
    return _decode_kernel(bt_ref, lens_ref, None, None, *rest,
                          quantized=False, **kw)


def paged_decode_attention(q, k_pool, v_pool, block_tables, lens, *,
                           k_scale=None, v_scale=None, sm_scale=None,
                           interpret=False):
    """One decode step of paged attention for every slot.

    q: (b, hq, d) — one (position-encoded) query row per slot.
    k_pool/v_pool: (num_pages, hk, page_size, d), bf16/f32, or int8
        with `k_scale`/`v_scale` (num_pages, hk) f32 such that
        k ~= k_pool * k_scale[page, head, None, None].
    block_tables: (b, max_pages) int32 — physical page of each logical
        page per slot (engine convention: 0 = never-written trash page
        for unallocated entries; those columns are masked anyway).
    lens: (b,) int32 — this query's position (its k/v must already be
        scattered there); columns c <= lens[i] are attended.

    Returns (b, hq, d) f32. Shapes must pass `check_decode_shapes`
    (call it, or gate on `decode_shape_problems`, before forcing this
    path — same contract as ring_attention_local(use_flash=True)).
    """
    b, hq, d = q.shape
    num_pages, hk, page_size, _ = k_pool.shape
    mp = block_tables.shape[1]
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    quantized = k_pool.dtype == jnp.int8
    if quantized and (k_scale is None or v_scale is None):
        raise ValueError("int8 pools require k_scale and v_scale "
                         "(num_pages, hk) f32")
    check_decode_shapes(hq, hk, d, page_size, interpret,
                        kv_dtype=k_pool.dtype)

    g = hq // hk
    # fold query heads sharing a kv head into the q tile's rows, padded
    # to a full sublane tile so the compiled kernel never sees a g < 8
    # second-minor dim (padded rows are zeros; their output is sliced
    # off — they cost nothing real at these sizes)
    gp = max(8, -(-g // 8) * 8) if not interpret else g
    qf = q.reshape(b, hk, g, d)
    if gp != g:
        qf = jnp.pad(qf, ((0, 0), (0, 0), (0, gp - g), (0, 0)))

    bt = block_tables.astype(jnp.int32)
    lens = lens.astype(jnp.int32)

    def clamp(j, bt_sp, lens_sp, bi):
        # revisit the last needed page above the window: a repeated
        # block index elides the DMA (flash _ki_clamp trick), and the
        # clamped entry is always an ALLOCATED page of this slot
        return bt_sp[bi, jnp.minimum(j, lens_sp[bi] // page_size)]

    kv_spec = pl.BlockSpec(
        (1, 1, page_size, d),
        lambda bi, hi, j, bt_sp, lens_sp, *_sc: (
            clamp(j, bt_sp, lens_sp, bi), hi, 0, 0))
    q_spec = pl.BlockSpec(
        (1, 1, gp, d),
        lambda bi, hi, j, *_sp: (bi, hi, 0, 0))

    scalar_args = [bt, lens]
    if quantized:
        # gather scales per SLOT here (tiny: (b, mp, hk)) so the SMEM
        # footprint follows the batch, not the pool — pool-wide scale
        # planes would outgrow SMEM at production page counts
        scalar_args += [k_scale[bt].astype(jnp.float32),
                        v_scale[bt].astype(jnp.float32)]
        kernel = functools.partial(_decode_kernel, page_size=page_size,
                                   sm_scale=sm_scale, quantized=True)
    else:
        kernel = functools.partial(_decode_kernel_noquant,
                                   page_size=page_size,
                                   sm_scale=sm_scale)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=len(scalar_args),
        grid=(b, hk, mp),
        in_specs=[q_spec, kv_spec, kv_spec],
        out_specs=q_spec,
        scratch_shapes=[pltpu.VMEM((gp, 8), jnp.float32),
                        pltpu.VMEM((gp, 8), jnp.float32),
                        pltpu.VMEM((gp, d), jnp.float32)],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, hk, gp, d), jnp.float32),
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(*scalar_args, qf, k_pool, v_pool)
    return out[:, :, :g, :].reshape(b, hq, d)
