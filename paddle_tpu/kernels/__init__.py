"""Pallas TPU kernels — the rebuild's equivalent of the reference's
hand-written CUDA fusion library (reference: paddle/phi/kernels/fusion/gpu/,
third_party/flashattn, paddle/cinn codegen). Only ops XLA cannot fuse well
live here; everything else rides XLA fusion (SURVEY.md §2.4 "TPU
equivalent: XLA itself").
"""
from paddle_tpu.kernels import blockwise_ce     # noqa: F401
from paddle_tpu.kernels import flash_attention  # noqa: F401
from paddle_tpu.kernels import fused_norm       # noqa: F401
from paddle_tpu.kernels import paged_attention  # noqa: F401
from paddle_tpu.kernels import quant_matmul     # noqa: F401
