"""`paddle.sparse.nn` — sparse NN layers (reference:
python/paddle/sparse/nn/).

ReLU/ReLU6/LeakyReLU act on values; Softmax is a per-row segment softmax
over the CSR pattern (the attention-mask use-case); BatchNorm normalizes
values per channel; sparse convs densify per-block (XLA conv is dense —
submanifold sparse conv is a gather/scatter program that only pays off at
extreme sparsity; the dense path is the TPU-fast one at typical densities).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.core.tensor import Tensor
from paddle_tpu.nn.layer.layers import Layer
from paddle_tpu.sparse import (SparseCooTensor, SparseCsrTensor, _is_sparse,
                               _vop)
from paddle_tpu.sparse import functional  # noqa: F401

__all__ = ['ReLU', 'ReLU6', 'LeakyReLU', 'Softmax', 'BatchNorm',
           'SyncBatchNorm', 'Conv2D', 'Conv3D', 'SubmConv2D', 'SubmConv3D',
           'MaxPool3D']


class ReLU(Layer):
    def forward(self, x):
        return functional.relu(x)


class ReLU6(Layer):
    def forward(self, x):
        return functional.relu6(x)


class LeakyReLU(Layer):
    def __init__(self, negative_slope=0.01):
        super().__init__()
        self.negative_slope = negative_slope

    def forward(self, x):
        return functional.leaky_relu(x, self.negative_slope)


class Softmax(Layer):
    def __init__(self, axis=-1):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return functional.softmax(x, self.axis)


class BatchNorm(Layer):
    """BatchNorm over the channel (last) dim of COO values (reference:
    sparse/nn/layer/norm.py — normalizes nnz x C values like dense BN over
    the flattened spatial dims)."""

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format='NDHWC',
                 use_global_stats=None, name=None):
        super().__init__()
        from paddle_tpu.nn.layer.norm import BatchNorm1D
        self._bn = BatchNorm1D(num_features, momentum=momentum,
                               epsilon=epsilon)

    def forward(self, x):
        vals = x.values()
        out_vals = self._bn(vals)
        return SparseCooTensor(x._indices, out_vals, x._shape, x._coalesced)


class SyncBatchNorm(BatchNorm):
    """On TPU, batch stats sync falls out of GSPMD when values are sharded;
    the layer is identical to BatchNorm (reference needs a NCCL allreduce)."""


class _DenseConvWrapper(Layer):
    """Sparse conv via densify -> XLA conv -> re-sparsify. Submanifold
    variants preserve the input pattern (reference:
    sparse/nn/layer/conv.py SubmConv3D)."""

    def __init__(self, conv, subm):
        super().__init__()
        self._conv = conv
        self._subm = subm

    def forward(self, x):
        # values layout (reference): indices (ndim, nnz) over N,*spatial;
        # values (nnz, C); dense layout channels-last
        dense = x.to_dense()  # (N, *spatial, C)
        from paddle_tpu import tensor as T
        perm_in = [0, dense.ndim - 1] + list(range(1, dense.ndim - 1))
        out = self._conv(T.transpose(dense, perm_in))  # NC* conv
        perm_out = [0] + list(range(2, out.ndim)) + [1]
        out = T.transpose(out, perm_out)               # back to N*...C
        if not self._subm:
            return _dense_to_coo(out)
        # submanifold: output must keep the input geometry — enforce it
        # (same-padding, stride 1); a silent clamp-gather would corrupt
        # border activations
        if tuple(out.shape[:-1]) != tuple(x.shape[:-1]):
            raise ValueError(
                f"SubmConv requires output spatial shape == input shape; "
                f"got {list(out.shape)} vs {x.shape}. Use stride=1 and "
                f"'same' padding (padding=(k-1)//2*dilation).")
        idx = tuple(x._indices[d] for d in range(x._indices.shape[0]))
        vals = _vop("subm_gather", lambda o: o[idx], out)
        return SparseCooTensor(x._indices, vals, tuple(out.shape),
                               coalesced=x._coalesced)


def _dense_to_coo(dense_t, sparse_dim=None):
    arr = dense_t._value if isinstance(dense_t, Tensor) else dense_t
    ndim_sp = (arr.ndim - 1) if sparse_dim is None else sparse_dim
    mask = jnp.any(arr != 0, axis=tuple(range(ndim_sp, arr.ndim)))
    nz = jnp.nonzero(mask)
    idx = jnp.stack(nz).astype(jnp.int32)
    vals = _vop("dense_to_coo", lambda a: a[nz], dense_t)
    return SparseCooTensor(idx, vals, tuple(arr.shape))


def _same_padding(kernel_size, dilation, n):
    ks = kernel_size if isinstance(kernel_size, (list, tuple)) else \
        [kernel_size] * n
    dl = dilation if isinstance(dilation, (list, tuple)) else [dilation] * n
    return [((k - 1) // 2) * d for k, d in zip(ks, dl)]


def _check_subm(kernel_size, stride, n):
    ks = kernel_size if isinstance(kernel_size, (list, tuple)) else \
        [kernel_size] * n
    st = stride if isinstance(stride, (list, tuple)) else [stride] * n
    if any(s != 1 for s in st):
        raise ValueError(
            "SubmConv preserves the input sparsity pattern and therefore "
            f"requires stride=1, got stride={stride}")
    if any(k % 2 == 0 for k in ks):
        raise ValueError(
            "SubmConv requires odd kernel sizes (same-padding must keep "
            f"the spatial shape), got kernel_size={kernel_size}")


def Conv2D(in_channels, out_channels, kernel_size, stride=1, padding=0,
           dilation=1, groups=1, subm=False, key=None, weight_attr=None,
           bias_attr=None, data_format="NHWC"):
    from paddle_tpu.nn import Conv2D as DenseConv2D
    if subm:
        _check_subm(kernel_size, stride, 2)
        stride, padding = 1, _same_padding(kernel_size, dilation, 2)
    return _DenseConvWrapper(
        DenseConv2D(in_channels, out_channels, kernel_size, stride=stride,
                    padding=padding, dilation=dilation, groups=groups), subm)


def Conv3D(in_channels, out_channels, kernel_size, stride=1, padding=0,
           dilation=1, groups=1, subm=False, key=None, weight_attr=None,
           bias_attr=None, data_format="NDHWC"):
    from paddle_tpu.nn import Conv3D as DenseConv3D
    if subm:
        _check_subm(kernel_size, stride, 3)
        stride, padding = 1, _same_padding(kernel_size, dilation, 3)
    return _DenseConvWrapper(
        DenseConv3D(in_channels, out_channels, kernel_size, stride=stride,
                    padding=padding, dilation=dilation, groups=groups), subm)


def SubmConv2D(*args, **kwargs):
    kwargs["subm"] = True
    return Conv2D(*args, **kwargs)


def SubmConv3D(*args, **kwargs):
    kwargs["subm"] = True
    return Conv3D(*args, **kwargs)


class MaxPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NDHWC", name=None):
        super().__init__()
        from paddle_tpu.nn import MaxPool3D as DenseMaxPool3D
        self._pool = DenseMaxPool3D(kernel_size, stride=stride,
                                    padding=padding)

    def forward(self, x):
        dense = x.to_dense()
        from paddle_tpu import tensor as T
        perm_in = [0, dense.ndim - 1] + list(range(1, dense.ndim - 1))
        out = self._pool(T.transpose(dense, perm_in))
        perm_out = [0] + list(range(2, out.ndim)) + [1]
        return _dense_to_coo(T.transpose(out, perm_out))
