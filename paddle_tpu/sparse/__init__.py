"""`paddle.sparse` — COO/CSR sparse tensors (reference: python/paddle/sparse/,
C++ types paddle/phi/core/sparse_coo_tensor.h, sparse_csr_tensor.h, kernels
paddle/phi/kernels/sparse/).

TPU-native design: XLA has no sparse buffer type, and TPU sparse compute is
idiomatically expressed as gather / scatter-add / segment-sum over dense
index+value arrays — which is exactly the COO/CSR decomposition. So a sparse
tensor here is a pair of arrays (indices + values) where the VALUES live on
the autograd tape (a paddle Tensor) and the indices are static jax arrays:
every op below is a defop over the values (and any dense operand), so
gradients flow exactly like the reference's sparse autograd, and everything
jits. matmul lowers to one gather + one segment-sum — the XLA-friendly spmv.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu.core.dispatch import defop, dispatch, OpDef
from paddle_tpu.core.tensor import Tensor

__all__ = [
    'sparse_coo_tensor', 'sparse_csr_tensor',
    'sin', 'tan', 'asin', 'atan', 'sinh', 'tanh', 'asinh', 'atanh',
    'sqrt', 'square', 'log1p', 'abs', 'pow', 'cast', 'neg', 'deg2rad',
    'rad2deg', 'expm1',
    'mv', 'matmul', 'masked_matmul', 'addmm',
    'add', 'subtract', 'multiply', 'divide',
    'transpose', 'sum', 'coalesce', 'is_same_shape', 'reshape', 'isnan',
    'slice',
]


def _values_tensor(v):
    return v if isinstance(v, Tensor) else Tensor(jnp.asarray(v))


def _idx_arr(x):
    if isinstance(x, Tensor):
        x = x._value
    return jnp.asarray(x).astype(jnp.int32)


def _vop(name, fn, *tensors, **kw):
    """Run fn through the eager dispatcher so values stay on the tape."""
    return dispatch(OpDef("sparse." + name, fn), tensors, kw)


class SparseCooTensor:
    """COO sparse tensor: indices (ndim, nnz) int32 + values (nnz, ...)
    (reference: paddle/phi/core/sparse_coo_tensor.h)."""

    def __init__(self, indices, values, shape, coalesced=False):
        self._indices = _idx_arr(indices)
        self._values = _values_tensor(values)
        self._shape = tuple(int(s) for s in shape)
        self._coalesced = bool(coalesced)

    # -- paddle Tensor-protocol surface ------------------------------------
    @property
    def shape(self):
        return list(self._shape)

    @property
    def ndim(self):
        return len(self._shape)

    @property
    def dtype(self):
        return self._values.dtype

    @property
    def nnz(self):
        return int(self._indices.shape[1])

    @property
    def sparse_dim(self):
        # hybrid COO: index rows may cover only the leading dims, with the
        # rest carried as trailing dense dims of the values
        return int(self._indices.shape[0])

    @property
    def stop_gradient(self):
        return self._values.stop_gradient

    @stop_gradient.setter
    def stop_gradient(self, v):
        self._values.stop_gradient = v

    @property
    def grad(self):
        return self._values.grad

    def indices(self):
        return Tensor(self._indices)

    def values(self):
        return self._values

    def is_sparse_coo(self):
        return True

    def is_sparse_csr(self):
        return False

    def to_dense(self):
        idx = tuple(self._indices[d] for d in range(self._indices.shape[0]))
        shape = self._shape

        def f(v):
            dense = jnp.zeros(shape, v.dtype)
            return dense.at[idx].add(v)
        return _vop("coo_to_dense", f, self._values)

    def to_sparse_csr(self):
        coo = self.coalesce() if not self._coalesced else self
        if coo.ndim != 2:
            raise ValueError("to_sparse_csr requires a 2-D sparse tensor")
        rows, cols = coo._indices[0], coo._indices[1]
        nrows = coo._shape[0]
        crows = jnp.cumsum(jnp.bincount(rows, length=nrows))
        crows = jnp.concatenate([jnp.zeros((1,), crows.dtype), crows])
        return SparseCsrTensor(crows, cols, coo._values, coo._shape)

    def to_sparse_coo(self, sparse_dim=None):
        return self

    def coalesce(self):
        """Sum duplicate indices (reference: sparse/unary.py coalesce op)."""
        # int32 linear ids: fine for any shape XLA can index on TPU
        sd = self.sparse_dim
        lin = jnp.zeros((self.nnz,), jnp.int32)
        for d in range(sd):
            lin = lin * self._shape[d] + self._indices[d]
        uniq, inv = jnp.unique(lin, return_inverse=True, size=self.nnz,
                               fill_value=-1)
        n_uniq = int(jnp.sum(uniq >= 0))
        # positions of unique linear ids, decomposed back to nd indices
        uu = uniq[:n_uniq]
        nd = []
        rem = uu
        for d in reversed(range(sd)):
            nd.append(rem % self._shape[d])
            rem = rem // self._shape[d]
        new_idx = jnp.stack(list(reversed(nd))).astype(jnp.int32)
        # jnp.unique(size=...) pads with fill_value at the END, so inverse
        # ids already index uniq[:n_uniq] directly

        def f(v):
            out = jnp.zeros((n_uniq,) + v.shape[1:], v.dtype)
            return out.at[inv.reshape(-1)].add(v)
        vals = _vop("coo_coalesce", f, self._values)
        return SparseCooTensor(new_idx, vals, self._shape, coalesced=True)

    def t(self):
        return transpose(self, [1, 0])

    def numpy(self):
        return np.asarray(self.to_dense().numpy())

    def __repr__(self):
        return (f"SparseCooTensor(shape={self.shape}, nnz={self.nnz}, "
                f"dtype={self.dtype})")

    def backward(self, *a, **k):
        return self._values.backward(*a, **k)


class SparseCsrTensor:
    """CSR sparse matrix: crows (rows+1), cols (nnz), values (nnz)
    (reference: paddle/phi/core/sparse_csr_tensor.h)."""

    def __init__(self, crows, cols, values, shape):
        self._crows = _idx_arr(crows)
        self._cols = _idx_arr(cols)
        self._values = _values_tensor(values)
        self._shape = tuple(int(s) for s in shape)

    @property
    def shape(self):
        return list(self._shape)

    @property
    def ndim(self):
        return len(self._shape)

    @property
    def dtype(self):
        return self._values.dtype

    @property
    def nnz(self):
        return int(self._cols.shape[0])

    @property
    def stop_gradient(self):
        return self._values.stop_gradient

    @stop_gradient.setter
    def stop_gradient(self, v):
        self._values.stop_gradient = v

    @property
    def grad(self):
        return self._values.grad

    def crows(self):
        return Tensor(self._crows)

    def cols(self):
        return Tensor(self._cols)

    def values(self):
        return self._values

    def is_sparse_coo(self):
        return False

    def is_sparse_csr(self):
        return True

    def _row_indices(self):
        counts = jnp.diff(self._crows)
        return jnp.repeat(jnp.arange(self._shape[0], dtype=jnp.int32),
                          counts, total_repeat_length=self.nnz)

    def to_sparse_coo(self, sparse_dim=None):
        idx = jnp.stack([self._row_indices(), self._cols])
        return SparseCooTensor(idx, self._values, self._shape,
                               coalesced=True)

    def to_sparse_csr(self):
        return self

    def to_dense(self):
        return self.to_sparse_coo().to_dense()

    def numpy(self):
        return np.asarray(self.to_dense().numpy())

    def __repr__(self):
        return (f"SparseCsrTensor(shape={self.shape}, nnz={self.nnz}, "
                f"dtype={self.dtype})")

    def backward(self, *a, **k):
        return self._values.backward(*a, **k)


def _is_sparse(x):
    return isinstance(x, (SparseCooTensor, SparseCsrTensor))


# -- creation ---------------------------------------------------------------

def sparse_coo_tensor(indices, values, shape=None, dtype=None,
                      place=None, stop_gradient=True):
    """Build a COO tensor (reference: python/paddle/sparse/creation.py)."""
    idx = _idx_arr(indices)
    vals = _values_tensor(values)
    if dtype is not None:
        vals = Tensor(vals._value.astype(dtype), stop_gradient=vals.stop_gradient)
    if shape is None:
        shape = tuple(int(jnp.max(idx[d])) + 1 for d in range(idx.shape[0]))
    # fresh leaf wrapper: creation copies (reference semantics) so flipping
    # stop_gradient here must not detach the caller's Tensor elsewhere
    vals = Tensor(vals._value, stop_gradient=stop_gradient)
    return SparseCooTensor(idx, vals, shape)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None,
                      place=None, stop_gradient=True):
    """Build a CSR matrix (reference: python/paddle/sparse/creation.py)."""
    vals = _values_tensor(values)
    if dtype is not None:
        vals = Tensor(vals._value.astype(dtype), stop_gradient=vals.stop_gradient)
    vals = Tensor(vals._value, stop_gradient=stop_gradient)
    return SparseCsrTensor(crows, cols, vals, shape)


# -- unary (zero-preserving ops apply to values only) -----------------------

def _unary(op_name, fn):
    def op(x, name=None):
        if not _is_sparse(x):
            raise TypeError(
                f"paddle.sparse.{op_name} expects a sparse tensor")
        vals = _vop(op_name, fn, x._values)
        if x.is_sparse_coo():
            return SparseCooTensor(x._indices, vals, x._shape, x._coalesced)
        return SparseCsrTensor(x._crows, x._cols, vals, x._shape)
    op.__name__ = op_name
    return op


sin = _unary("sin", jnp.sin)
tan = _unary("tan", jnp.tan)
asin = _unary("asin", jnp.arcsin)
atan = _unary("atan", jnp.arctan)
sinh = _unary("sinh", jnp.sinh)
tanh = _unary("tanh", jnp.tanh)
asinh = _unary("asinh", jnp.arcsinh)
atanh = _unary("atanh", jnp.arctanh)
sqrt = _unary("sqrt", jnp.sqrt)
square = _unary("square", jnp.square)
log1p = _unary("log1p", jnp.log1p)
abs = _unary("abs", jnp.abs)
neg = _unary("neg", jnp.negative)
deg2rad = _unary("deg2rad", jnp.deg2rad)
rad2deg = _unary("rad2deg", jnp.rad2deg)
expm1 = _unary("expm1", jnp.expm1)
isnan = _unary("isnan", jnp.isnan)


def pow(x, factor, name=None):
    return _unary("pow", lambda v: jnp.power(v, factor))(x)


def cast(x, index_dtype=None, value_dtype=None, name=None):
    vals = x._values
    if value_dtype is not None:
        vals = _vop("cast", lambda v: v.astype(value_dtype), vals)
    if x.is_sparse_coo():
        out = SparseCooTensor(x._indices, vals, x._shape, x._coalesced)
        if index_dtype is not None:
            # bypass the constructor's int32 normalization; whether int64
            # actually sticks follows jax's x64 policy like every other
            # dtype in the framework
            out._indices = x._indices.astype(index_dtype)
        return out
    out = SparseCsrTensor(x._crows, x._cols, vals, x._shape)
    if index_dtype is not None:
        out._crows = x._crows.astype(index_dtype)
        out._cols = x._cols.astype(index_dtype)
    return out


# -- binary -----------------------------------------------------------------

def _coo_binary(name, fn, x, y):
    """Elementwise sparse-sparse op via union of patterns (both operands'
    values stay on the tape)."""
    xc = x.to_sparse_coo().coalesce()
    yc = y.to_sparse_coo().coalesce()
    if xc._shape != yc._shape:
        raise ValueError("sparse binary op requires equal shapes")
    idx = jnp.concatenate([xc._indices, yc._indices], axis=1)

    def f(xv, yv):
        zeros_y = jnp.zeros(yv.shape, yv.dtype)
        zeros_x = jnp.zeros(xv.shape, xv.dtype)
        left = jnp.concatenate([xv, zeros_y])
        right = jnp.concatenate([zeros_x, yv])
        return fn(left, right)
    vals = _vop(name, f, xc._values, yc._values)
    out = SparseCooTensor(idx, vals, xc._shape).coalesce()
    # divide/multiply across the union pattern must still be computed on
    # summed duplicates — fn is applied pre-coalesce which is only valid
    # for add/subtract; multiply/divide go through aligned patterns below.
    return out


def _linear_ids(indices, shape, sparse_dim):
    lin = jnp.zeros((indices.shape[1],), jnp.int32)
    for d in range(sparse_dim):
        lin = lin * shape[d] + indices[d]
    return lin


def _aligned_binary(name, fn, x, y):
    """multiply/divide need value alignment, not union accumulate: scatter
    each side's values onto the union-pattern slots (searchsorted over
    linear ids — O(nnz), never densified), then apply fn slotwise."""
    xc = x.to_sparse_coo().coalesce()
    yc = y.to_sparse_coo().coalesce()
    if xc._shape != yc._shape:
        raise ValueError("sparse binary op requires equal shapes")
    union = SparseCooTensor(
        jnp.concatenate([xc._indices, yc._indices], axis=1),
        jnp.concatenate([jnp.ones((xc.nnz,), xc._values._value.dtype),
                         jnp.ones((yc.nnz,), yc._values._value.dtype)]),
        xc._shape).coalesce()
    # coalesce() emits indices in ascending linear-id order, so the union
    # ids are sorted and each side's slot is found by searchsorted
    u_lin = _linear_ids(union._indices, union._shape, union.sparse_dim)
    x_pos = jnp.searchsorted(u_lin, _linear_ids(xc._indices, xc._shape,
                                                xc.sparse_dim))
    y_pos = jnp.searchsorted(u_lin, _linear_ids(yc._indices, yc._shape,
                                                yc.sparse_dim))
    n_union = union.nnz
    trail = xc._values._value.shape[1:]

    def f(xv, yv):
        dx = jnp.zeros((n_union,) + trail, xv.dtype).at[x_pos].set(xv)
        dy = jnp.zeros((n_union,) + trail, yv.dtype).at[y_pos].set(yv)
        return fn(dx, dy)
    vals = _vop(name, f, xc._values, yc._values)
    return SparseCooTensor(union._indices, vals, union._shape,
                           coalesced=True)


def _keep_format(out, x, y):
    # reference returns CSR when both operands are CSR
    if x.is_sparse_csr() and y.is_sparse_csr():
        return out.to_sparse_csr()
    return out


def add(x, y, name=None):
    return _keep_format(_coo_binary("add", jnp.add, x, y), x, y)


def subtract(x, y, name=None):
    return _keep_format(_coo_binary("subtract", jnp.subtract, x, y), x, y)


def multiply(x, y, name=None):
    return _keep_format(_aligned_binary("multiply", jnp.multiply, x, y),
                        x, y)


def divide(x, y, name=None):
    return _keep_format(_aligned_binary("divide", jnp.divide, x, y), x, y)


# -- matmul family ----------------------------------------------------------

def _spmm(sp, dense_t, name):
    """sparse (M,K) @ dense (K,N) -> dense (M,N): gather rows of the dense
    operand at the sparse column ids, scale by values, segment-sum into
    output rows. One gather + one scatter-add — the XLA/TPU-canonical spmv
    (reference kernel: paddle/phi/kernels/sparse/gpu/matmul_kernel.cu via
    cusparse; ours is the gather/scatter formulation XLA tiles natively)."""
    coo = sp.to_sparse_coo()
    if coo.ndim != 2 or coo.sparse_dim != 2:
        raise ValueError(
            f"sparse matmul requires a 2-D sparse operand, got shape "
            f"{coo.shape} with {coo.sparse_dim} sparse dims")
    rows, cols = coo._indices[0], coo._indices[1]
    M = coo._shape[0]

    def f(v, d):
        gathered = v[:, None] * d[cols]          # (nnz, N)
        return jax.ops.segment_sum(gathered, rows, num_segments=M)
    return _vop(name, f, coo._values, dense_t)


def matmul(x, y, name=None):
    if _is_sparse(x) and not _is_sparse(y):
        return _spmm(x, y, "spmm")
    if _is_sparse(x) and _is_sparse(y):
        # sparse @ sparse -> dense of x @ dense(y) kept sparse-free
        return _spmm(x, y.to_dense(), "spspmm")
    raise TypeError("paddle.sparse.matmul: first operand must be sparse")


def mv(x, vec, name=None):
    coo = x.to_sparse_coo()
    if coo.ndim != 2 or coo.sparse_dim != 2:
        raise ValueError("sparse mv requires a 2-D sparse operand")
    rows, cols = coo._indices[0], coo._indices[1]
    M = coo._shape[0]

    def f(v, d):
        return jax.ops.segment_sum(v * d[cols], rows, num_segments=M)
    return _vop("spmv", f, coo._values, vec)


def masked_matmul(x, y, mask, name=None):
    """dense @ dense evaluated only at `mask`'s sparsity pattern
    (reference: sparse/binary.py masked_matmul, SDDMM)."""
    coo = mask.to_sparse_coo()
    rows, cols = coo._indices[0], coo._indices[1]

    def f(xv, yv):
        return jnp.sum(xv[rows] * yv[:, cols].T, axis=-1)
    vals = _vop("sddmm", f, x, y)
    if mask.is_sparse_csr():
        return SparseCsrTensor(mask._crows, mask._cols, vals, mask._shape)
    return SparseCooTensor(coo._indices, vals, coo._shape, coo._coalesced)


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    """beta*input + alpha*(x@y) with sparse x (reference: sparse/multiary.py)."""
    from paddle_tpu import tensor as T
    prod = matmul(x, y)
    return T.add(T.scale(input, beta), T.scale(prod, alpha))


# -- shape ops --------------------------------------------------------------

def transpose(x, perm, name=None):
    coo = x.to_sparse_coo()
    if len(perm) != coo.sparse_dim:
        raise NotImplementedError(
            "sparse transpose only permutes the sparse dims")
    idx = jnp.stack([coo._indices[p] for p in perm])
    shape = tuple(coo._shape[p] for p in perm)
    out = SparseCooTensor(idx, coo._values, shape)
    return out.to_sparse_csr() if x.is_sparse_csr() else out


def reshape(x, shape, name=None):
    coo = x.to_sparse_coo().coalesce()
    if coo.sparse_dim != coo.ndim:
        raise NotImplementedError(
            "sparse reshape of hybrid COO (trailing dense dims) is not "
            "supported")
    shape = tuple(int(s) for s in shape)
    n_old = int(np.prod(coo._shape))
    # resolve a single -1
    if -1 in shape:
        known = int(np.prod([s for s in shape if s != -1]))
        shape = tuple(n_old // known if s == -1 else s for s in shape)
    if int(np.prod(shape)) != n_old:
        raise ValueError(
            f"sparse reshape: cannot reshape {coo.shape} ({n_old} elements) "
            f"to {list(shape)}")
    lin = jnp.zeros((coo.nnz,), jnp.int32)
    for d in range(coo.ndim):
        lin = lin * coo._shape[d] + coo._indices[d]
    nd = []
    rem = lin
    for d in reversed(range(len(shape))):
        nd.append(rem % shape[d])
        rem = rem // shape[d]
    idx = jnp.stack(list(reversed(nd))).astype(jnp.int32)
    out = SparseCooTensor(idx, coo._values, shape, coalesced=True)
    return out.to_sparse_csr() if x.is_sparse_csr() else out


def sum(x, axis=None, dtype=None, keepdim=False, name=None):
    """Reduce to dense (reference returns sparse for axis reductions of coo;
    the dense result is the useful one on TPU and feeds straight into XLA)."""
    dense = x.to_dense()
    from paddle_tpu import tensor as T
    return T.sum(dense, axis=axis, dtype=dtype, keepdim=keepdim)


def coalesce(x, name=None):
    return x.coalesce()


def is_same_shape(x, y):
    return list(x.shape) == list(y.shape)


def slice(x, axes, starts, ends, name=None):
    from paddle_tpu import tensor as T
    return T.slice(x.to_dense(), axes, starts, ends)


from paddle_tpu.sparse import nn  # noqa: E402,F401
