"""`paddle.sparse.nn.functional` (reference:
python/paddle/sparse/nn/functional/).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ['relu', 'relu6', 'leaky_relu', 'softmax', 'attention']


def relu(x, name=None):
    from paddle_tpu.sparse import _unary
    return _unary("relu", jax.nn.relu)(x)


def relu6(x, name=None):
    from paddle_tpu.sparse import _unary
    return _unary("relu6", lambda v: jnp.clip(v, 0.0, 6.0))(x)


def leaky_relu(x, negative_slope=0.01, name=None):
    from paddle_tpu.sparse import _unary
    return _unary("leaky_relu",
                  lambda v: jax.nn.leaky_relu(v, negative_slope))(x)


def softmax(x, axis=-1, name=None):
    """Per-row softmax over the sparsity pattern (reference:
    sparse/nn/functional/activation.py softmax — only supports the last
    axis, which is the attention-logits use-case). Segment-max/sum over the
    CSR row ids — the XLA-native masked softmax."""
    from paddle_tpu.sparse import SparseCooTensor, SparseCsrTensor, _vop
    if axis not in (-1, x.ndim - 1):
        raise ValueError("sparse softmax supports the last axis only")
    csr = x if x.is_sparse_csr() else x.to_sparse_csr()
    rows = csr._row_indices()
    nrows = csr._shape[0]

    def f(v):
        row_max = jax.ops.segment_max(v, rows, num_segments=nrows)
        e = jnp.exp(v - row_max[rows])
        denom = jax.ops.segment_sum(e, rows, num_segments=nrows)
        return e / denom[rows]
    vals = _vop("csr_softmax", f, csr._values)
    out = SparseCsrTensor(csr._crows, csr._cols, vals, csr._shape)
    return out if x.is_sparse_csr() else out.to_sparse_coo()


def attention(query, key, value, sparse_mask, key_padding_mask=None,
              attn_mask=None, name=None):
    """Sparse-pattern attention: QK^T evaluated only on sparse_mask's
    pattern, softmax per row, then spmv against V (reference:
    sparse/nn/functional/transformer.py attention over SparseCsrTensor).
    key_padding_mask (keys,) and attn_mask (queries, keys) are additive
    masks gathered at the sparse pattern positions before the softmax."""
    from paddle_tpu import tensor as T
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.sparse import (SparseCooTensor, SparseCsrTensor,
                                   masked_matmul, matmul, _vop)
    import math
    d = query.shape[-1]
    scores = masked_matmul(T.scale(query, 1.0 / math.sqrt(d)),
                           T.transpose(key, [1, 0]), sparse_mask)
    if key_padding_mask is not None or attn_mask is not None:
        coo = scores.to_sparse_coo()
        rows, cols = coo._indices[0], coo._indices[1]

        def add_masks(v, *masks):
            i = 0
            if key_padding_mask is not None:
                v = v + masks[i][cols]
                i += 1
            if attn_mask is not None:
                v = v + masks[i][rows, cols]
            return v
        margs = [m for m in (key_padding_mask, attn_mask) if m is not None]
        vals = _vop("sp_attn_mask", add_masks, coo._values, *margs)
        coo = SparseCooTensor(coo._indices, vals, coo._shape, coo._coalesced)
        scores = coo if scores.is_sparse_coo() else coo.to_sparse_csr()
    probs = softmax(scores)
    return matmul(probs, value)
