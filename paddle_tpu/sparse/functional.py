"""`paddle.sparse.nn.functional` (reference:
python/paddle/sparse/nn/functional/).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ['relu', 'relu6', 'leaky_relu', 'softmax', 'attention',
           'conv3d', 'subm_conv3d']


def relu(x, name=None):
    from paddle_tpu.sparse import _unary
    return _unary("relu", jax.nn.relu)(x)


def relu6(x, name=None):
    from paddle_tpu.sparse import _unary
    return _unary("relu6", lambda v: jnp.clip(v, 0.0, 6.0))(x)


def leaky_relu(x, negative_slope=0.01, name=None):
    from paddle_tpu.sparse import _unary
    return _unary("leaky_relu",
                  lambda v: jax.nn.leaky_relu(v, negative_slope))(x)


def softmax(x, axis=-1, name=None):
    """Per-row softmax over the sparsity pattern (reference:
    sparse/nn/functional/activation.py softmax — only supports the last
    axis, which is the attention-logits use-case). Segment-max/sum over the
    CSR row ids — the XLA-native masked softmax."""
    from paddle_tpu.sparse import SparseCooTensor, SparseCsrTensor, _vop
    if axis not in (-1, x.ndim - 1):
        raise ValueError("sparse softmax supports the last axis only")
    csr = x if x.is_sparse_csr() else x.to_sparse_csr()
    rows = csr._row_indices()
    nrows = csr._shape[0]

    def f(v):
        row_max = jax.ops.segment_max(v, rows, num_segments=nrows)
        e = jnp.exp(v - row_max[rows])
        denom = jax.ops.segment_sum(e, rows, num_segments=nrows)
        return e / denom[rows]
    vals = _vop("csr_softmax", f, csr._values)
    out = SparseCsrTensor(csr._crows, csr._cols, vals, csr._shape)
    return out if x.is_sparse_csr() else out.to_sparse_coo()


def attention(query, key, value, sparse_mask, key_padding_mask=None,
              attn_mask=None, name=None):
    """Sparse-pattern attention: QK^T evaluated only on sparse_mask's
    pattern, softmax per row, then spmv against V (reference:
    sparse/nn/functional/transformer.py attention over SparseCsrTensor).
    key_padding_mask (keys,) and attn_mask (queries, keys) are additive
    masks gathered at the sparse pattern positions before the softmax."""
    from paddle_tpu import tensor as T
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.sparse import (SparseCooTensor, SparseCsrTensor,
                                   masked_matmul, matmul, _vop)
    import math
    d = query.shape[-1]
    scores = masked_matmul(T.scale(query, 1.0 / math.sqrt(d)),
                           T.transpose(key, [1, 0]), sparse_mask)
    if key_padding_mask is not None or attn_mask is not None:
        coo = scores.to_sparse_coo()
        rows, cols = coo._indices[0], coo._indices[1]

        def add_masks(v, *masks):
            i = 0
            if key_padding_mask is not None:
                v = v + masks[i][cols]
                i += 1
            if attn_mask is not None:
                v = v + masks[i][rows, cols]
            return v
        margs = [m for m in (key_padding_mask, attn_mask) if m is not None]
        vals = _vop("sp_attn_mask", add_masks, coo._values, *margs)
        coo = SparseCooTensor(coo._indices, vals, coo._shape, coo._coalesced)
        scores = coo if scores.is_sparse_coo() else coo.to_sparse_csr()
    probs = softmax(scores)
    return matmul(probs, value)


# ---------------------------------------------------------------------------
# sparse 3-D convolution (gather-scatter-matmul)
# ---------------------------------------------------------------------------

def _triple(v):
    return (v, v, v) if isinstance(v, int) else tuple(v)


def _conv3d_gather_scatter(x, weight, bias, stride, padding, dilation,
                           groups, subm, name):
    """True sparse conv (reference kernels:
    paddle/phi/kernels/sparse/gpu/conv_kernel.cu — the rulebook
    gather/GEMM/scatter pipeline). TPU-native formulation: per kernel
    offset, contributing nnz entries are GATHERED from the value rows,
    hit with that offset's (C, M) weight slice (MXU matmuls), and
    SEGMENT-SUMMED into the output rows. The index rulebook is computed
    host-side once per call (eager contract, like the reference's
    rulebook build); the value path is pure jax, so forward AND backward
    (grads to values, weight, bias) ride the tape.

    x: SparseCooTensor, shape (N, D, H, W, C), sparse_dim 4, values
    (nnz, C). weight: (KD, KH, KW, C, M) — the reference's DHWCM filter
    layout. subm=True keeps the input's sparsity pattern (submanifold,
    https://arxiv.org/abs/1706.01307)."""
    import numpy as np
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.sparse import SparseCooTensor, _vop

    if groups != 1:
        raise NotImplementedError("sparse conv3d: groups > 1")
    if x.sparse_dim != 4 or x.ndim != 5:
        raise ValueError(
            "sparse conv3d expects a (N, D, H, W, C) SparseCooTensor "
            f"with sparse_dim 4, got shape {x.shape} sparse_dim "
            f"{x.sparse_dim}")
    wt = weight._value if isinstance(weight, Tensor) else jnp.asarray(weight)
    kd, kh, kw, c, m = wt.shape
    st, pd, dl = _triple(stride), _triple(padding), _triple(dilation)
    if subm and st != (1, 1, 1):
        raise ValueError("subm_conv3d requires stride 1 (the output "
                         "pattern equals the input pattern)")
    n_, d_, h_, w_, _ = x.shape
    if subm:
        od, oh, ow = d_, h_, w_
    else:
        od = (d_ + 2 * pd[0] - dl[0] * (kd - 1) - 1) // st[0] + 1
        oh = (h_ + 2 * pd[1] - dl[1] * (kh - 1) - 1) // st[1] + 1
        ow = (w_ + 2 * pd[2] - dl[2] * (kw - 1) - 1) // st[2] + 1

    idx = np.asarray(x._indices)                 # (4, nnz) host
    nnz = idx.shape[1]
    # -- host rulebook: (kernel offset, src row, out coordinate) --------
    src_rows, off_ids, out_coords = [], [], []
    for ko in range(kd * kh * kw):
        k0, rem = divmod(ko, kh * kw)
        k1, k2 = divmod(rem, kw)
        num = (idx[1] + pd[0] - k0 * dl[0],
               idx[2] + pd[1] - k1 * dl[1],
               idx[3] + pd[2] - k2 * dl[2])
        ok = np.ones(nnz, bool)
        outs = []
        for a in range(3):
            q, r = np.divmod(num[a], st[a])
            ok &= (r == 0) & (q >= 0) & (q < (od, oh, ow)[a])
            outs.append(q)
        rows = np.nonzero(ok)[0]
        if rows.size == 0:
            continue
        src_rows.append(rows)
        off_ids.append(np.full(rows.size, ko, np.int64))
        out_coords.append(np.stack(
            [idx[0][rows], outs[0][rows], outs[1][rows], outs[2][rows]]))
    out_shape = (n_, od, oh, ow, m)
    if not src_rows:
        return SparseCooTensor(np.zeros((4, 0), np.int32),
                               jnp.zeros((0, m), wt.dtype), out_shape)
    src_rows = np.concatenate(src_rows)
    off_ids = np.concatenate(off_ids)
    out_coords = np.concatenate(out_coords, axis=1)   # (4, R)

    if subm:
        # output pattern == input pattern: map contributions onto the
        # existing rows, drop any that fall outside the pattern
        lin_in = np.ravel_multi_index(tuple(idx), (n_, d_, h_, w_))
        lin_out = np.ravel_multi_index(tuple(out_coords),
                                       (n_, d_, h_, w_))
        order = np.argsort(lin_in)
        pos = np.searchsorted(lin_in[order], lin_out)
        pos = np.clip(pos, 0, lin_in.size - 1)
        hit = lin_in[order][pos] == lin_out
        seg = order[pos][hit]
        src_rows, off_ids = src_rows[hit], off_ids[hit]
        out_idx = idx
        n_out = nnz
    else:
        lin_out = np.ravel_multi_index(tuple(out_coords),
                                       (n_, od, oh, ow))
        uniq, seg = np.unique(lin_out, return_inverse=True)
        out_idx = np.stack(np.unravel_index(uniq, (n_, od, oh, ow)))
        n_out = uniq.size

    srt = np.argsort(off_ids, kind="stable")     # group rows by offset
    src_rows, seg, off_srt = src_rows[srt], seg[srt], off_ids[srt]
    counts = np.bincount(off_srt, minlength=kd * kh * kw)
    bounds = np.concatenate([[0], np.cumsum(counts)])

    def f(vals, w, *maybe_bias):
        w2 = w.reshape(kd * kh * kw, c, m)
        parts = []
        for ko in range(kd * kh * kw):
            lo, hi = int(bounds[ko]), int(bounds[ko + 1])
            if hi == lo:
                continue
            parts.append(jnp.take(vals, src_rows[lo:hi], axis=0)
                         @ w2[ko].astype(vals.dtype))
        contrib = jnp.concatenate(parts, axis=0)
        out = jax.ops.segment_sum(contrib, jnp.asarray(seg),
                                  num_segments=n_out)
        if maybe_bias:
            out = out + maybe_bias[0].astype(out.dtype)
        return out

    from paddle_tpu.sparse import _vop as vop
    args = (x._values, weight) if bias is None else (x._values, weight,
                                                     bias)
    out_vals = vop("subm_conv3d" if subm else "conv3d", f, *args)
    return SparseCooTensor(out_idx.astype(np.int32), out_vals, out_shape,
                           coalesced=True)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1,
           groups=1, data_format="NDHWC", name=None):
    """Sparse conv3d (reference: sparse/nn/functional/conv.py:199)."""
    if data_format != "NDHWC":
        raise ValueError("sparse conv3d supports NDHWC only (reference "
                         "contract)")
    return _conv3d_gather_scatter(x, weight, bias, stride, padding,
                                  dilation, groups, False, name)


def subm_conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                groups=1, data_format="NDHWC", key=None, name=None):
    """Submanifold sparse conv3d (reference:
    sparse/nn/functional/conv.py:305; output keeps the input pattern)."""
    if data_format != "NDHWC":
        raise ValueError("sparse subm_conv3d supports NDHWC only")
    return _conv3d_gather_scatter(x, weight, bias, stride, padding,
                                  dilation, groups, True, name)
