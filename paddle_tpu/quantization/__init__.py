"""`paddle.quantization` — QAT/PTQ framework (reference:
python/paddle/quantization/: config.py, qat.py, ptq.py, quanters/abs_max.py,
observers/abs_max.py, wrapper.py).

TPU-native: fake-quant is a pure elementwise round/clip program with a
straight-through estimator (custom STE composed as
x + stop_gradient(q(x) - x)), which XLA fuses into the surrounding matmul —
no custom kernels needed. int8 matmul execution at inference rides XLA's
native int8 MXU path when exported.
"""
from __future__ import annotations

import copy

import jax
import jax.numpy as jnp

from paddle_tpu.core.dispatch import dispatch, OpDef
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.nn.layer.layers import Layer

__all__ = ["QuantConfig", "BaseQuanter", "BaseObserver", "quanter",
           "QAT", "PTQ", "HistObserver", "KLObserver", "AbsmaxObserver",
           "AbsMaxChannelWiseWeightObserver", "FrozenFakeQuanter"]


def _op(name, fn, *tensors):
    return dispatch(OpDef("quant." + name, fn), tensors, {})


def _fake_quant_ste(x, scale, bit_length=8, quant_axis=-1):
    """Simulated quantization with straight-through gradients. `scale`
    may be a scalar (per-tensor) or a vector broadcast on `quant_axis`
    (per-channel weight quant, reference quanters/abs_max.py
    quant_axis)."""
    bnd = float(2 ** (bit_length - 1) - 1)

    def f(xv, sv):
        if sv.ndim == 1 and xv.ndim > 1:
            shape = [1] * xv.ndim
            shape[quant_axis] = sv.shape[0]
            sv = sv.reshape(shape)
        s = jnp.maximum(sv, 1e-9)
        q = jnp.clip(jnp.round(xv / s * bnd), -bnd, bnd) * s / bnd
        # STE: identity gradient within range
        return xv + jax.lax.stop_gradient(q - xv)
    return _op("fake_quant", f, x, scale)


# -- base types (reference: base_quanter.py / base_observer.py) -------------

class BaseQuanter(Layer):
    def scales(self):
        raise NotImplementedError

    def zero_points(self):
        return None

    def bit_length(self):
        return 8

    def quant_axis(self):
        return -1


class BaseObserver(BaseQuanter):
    pass


class QuanterFactory:
    """Partial-application factory so one config object can instantiate a
    fresh quanter per layer (reference: factory.py)."""

    def __init__(self, cls, *args, **kwargs):
        self._cls, self._args, self._kwargs = cls, args, kwargs

    def _instance(self, layer=None):
        return self._cls(*self._args, **self._kwargs)


QUANTER_REGISTRY = {}


def quanter(name):
    """Decorator registering a quanter layer under a factory name
    (reference: factory.py quanter). The factory is available as
    QUANTER_REGISTRY[name]."""
    def deco(cls):
        def factory(*args, **kwargs):
            return QuanterFactory(cls, *args, **kwargs)
        factory.__name__ = name
        QUANTER_REGISTRY[name] = factory
        return cls
    return deco


# -- quanters / observers ---------------------------------------------------

class FakeQuanterWithAbsMaxObserverLayer(BaseQuanter):
    """Moving-average absmax fake quanter (reference:
    quanters/abs_max.py:96 — dynamic_forward updates state, static_forward
    uses accumulated scale)."""

    def __init__(self, moving_rate=0.9, bit_length=8, dtype="float32",
                 name=None):
        super().__init__()
        self._moving_rate = moving_rate
        self._bit_length = bit_length
        self.register_buffer("scale", Tensor(jnp.ones((), jnp.float32)))
        self.register_buffer("state", Tensor(jnp.ones((), jnp.float32)))
        self.register_buffer("accum", Tensor(jnp.ones((), jnp.float32)))

    def forward(self, x):
        if self.training:
            # dynamic_forward: update running absmax. Eager-only — under
            # any jit/vjp tracing (input OR buffers abstract) the
            # accumulated scale is used instead, matching the reference's
            # static_forward (quanters/abs_max.py:180).
            try:
                absmax = float(jnp.max(jnp.abs(x._value)))
                r = self._moving_rate
                state = float(self.state._value) * r + 1.0
                accum = float(self.accum._value) * r + absmax
                self.state._value = jnp.asarray(state, jnp.float32)
                self.accum._value = jnp.asarray(accum, jnp.float32)
                self.scale._value = jnp.asarray(accum / state, jnp.float32)
            except jax.errors.ConcretizationTypeError:
                pass
        return _fake_quant_ste(x, self.scale, self._bit_length)

    def scales(self):
        return self.scale

    def bit_length(self):
        return self._bit_length


def FakeQuanterWithAbsMaxObserver(moving_rate=0.9, bit_length=8,
                                  dtype="float32", name=None):
    return QuanterFactory(FakeQuanterWithAbsMaxObserverLayer,
                          moving_rate=moving_rate, bit_length=bit_length)


class AbsmaxObserverLayer(BaseObserver):
    """PTQ absmax observer: tracks the max |x| seen, no fake-quant during
    calibration (reference: observers/abs_max.py)."""

    def __init__(self, quant_bits=8):
        super().__init__()
        self._bit_length = quant_bits
        self.register_buffer("max_value", Tensor(jnp.zeros((), jnp.float32)))

    def forward(self, x):
        try:
            m = float(jnp.max(jnp.abs(x._value)))
            if m > float(self.max_value._value):
                self.max_value._value = jnp.asarray(m, jnp.float32)
        except jax.errors.ConcretizationTypeError:
            pass  # under tracing: calibration is an eager-mode activity
        return x

    def scales(self):
        return self.max_value

    def bit_length(self):
        return self._bit_length


def AbsmaxObserver(quant_bits=8):
    return QuanterFactory(AbsmaxObserverLayer, quant_bits=quant_bits)


class HistObserverLayer(BaseObserver):
    """Histogram percentile observer (reference: observers/hist.py
    PercentHistObserver): accumulates an |x| histogram over calibration
    batches — re-binning when the range grows — and calibrates the scale
    at the `percent` quantile instead of the raw absmax, which clips
    outliers that would otherwise waste the int8 range."""

    def __init__(self, quant_bits=8, bins=2048, percent=0.99999):
        super().__init__()
        import numpy as np
        self._bit_length = quant_bits
        self._bins = bins
        self._percent = percent
        self._hist = np.zeros(bins, np.float64)
        self._max = 0.0

    def forward(self, x):
        import numpy as np
        try:
            a = np.abs(np.asarray(x._value, np.float32)).ravel()
        except Exception:
            return x        # under tracing: calibration is eager-only
        m = float(a.max()) if a.size else 0.0
        if m > self._max:
            if self._max > 0.0:   # re-bin old counts into the new range
                old = self._hist
                self._hist = np.zeros(self._bins, np.float64)
                centers = (np.arange(self._bins) + 0.5) * (
                    self._max / self._bins)
                idx = np.minimum(
                    (centers / m * self._bins).astype(int),
                    self._bins - 1)
                np.add.at(self._hist, idx, old)
            self._max = m
        if self._max > 0.0:
            h, _ = np.histogram(a, bins=self._bins,
                                range=(0.0, self._max))
            self._hist += h
        return x

    def scales(self):
        import numpy as np
        if self._max == 0.0 or self._hist.sum() == 0:
            return Tensor(jnp.zeros((), jnp.float32))
        c = np.cumsum(self._hist) / self._hist.sum()
        i = int(np.searchsorted(c, self._percent))
        t = (i + 1) / self._bins * self._max
        return Tensor(jnp.asarray(t, jnp.float32))

    def bit_length(self):
        return self._bit_length


def HistObserver(quant_bits=8, bins_count=2048, percent=0.99999):
    return QuanterFactory(HistObserverLayer, quant_bits=quant_bits,
                          bins=bins_count, percent=percent)


class KLObserverLayer(HistObserverLayer):
    """KL-divergence calibration (reference: observers/kl.py): choose the
    clip threshold whose int8-quantized distribution has minimal KL
    divergence from the observed one (the TensorRT calibration recipe)."""

    def __init__(self, quant_bits=8, bins=2048):
        super().__init__(quant_bits=quant_bits, bins=bins)

    def scales(self):
        import numpy as np
        hist = self._hist
        if self._max == 0.0 or hist.sum() == 0:
            return Tensor(jnp.zeros((), jnp.float32))
        levels = 2 ** (self._bit_length - 1)   # 128 for int8
        best_i, best_kl = self._bins, float("inf")
        total = hist.sum()
        for i in range(levels, self._bins + 1, max(1, self._bins // 256)):
            p = hist[:i].copy()
            p[-1] += hist[i:].sum()            # clip tail into last bin
            if p.sum() == 0:
                continue
            # quantize p into `levels` buckets, expand back uniformly
            chunks = np.array_split(p, levels)
            q = np.concatenate([
                np.full(len(ch), ch.sum() / max((ch > 0).sum(), 1))
                * (ch > 0) for ch in chunks])
            pn = p / total
            qn = q / max(q.sum(), 1e-12)
            mask = pn > 0
            kl = float(np.sum(pn[mask] * np.log(
                pn[mask] / np.maximum(qn[mask], 1e-12))))
            if kl < best_kl:
                best_kl, best_i = kl, i
        t = (best_i + 0.5) / self._bins * self._max
        return Tensor(jnp.asarray(min(t, self._max), jnp.float32))


def KLObserver(quant_bits=8, bins_count=2048):
    return QuanterFactory(KLObserverLayer, quant_bits=quant_bits,
                          bins=bins_count)


class AbsMaxChannelWiseWeightObserverLayer(BaseObserver):
    """Per-channel weight observer (reference:
    observers/abs_max_weight.py AbsMaxChannelWiseWeightObserver): one
    scale per output channel along `quant_axis` (paddle layouts: 1 for
    Linear's (in, out) weight, 0 for Conv2D's (out, in, kh, kw))."""

    def __init__(self, quant_bits=8, quant_axis=None):
        super().__init__()
        self._bit_length = quant_bits
        self._axis = quant_axis
        self._scales = None

    def forward(self, x):
        v = x._value if isinstance(x, Tensor) else x
        axis = self._axis
        if axis is None:
            axis = 1 if v.ndim == 2 else 0
        self._resolved_axis = axis
        red = tuple(i for i in range(v.ndim) if i != axis)
        if isinstance(v, jax.core.Tracer):
            return x      # calibration is an eager-mode activity
        self._scales = jnp.max(jnp.abs(v), axis=red)
        return x

    def scales(self):
        return Tensor(self._scales)

    def quant_axis(self):
        return getattr(self, "_resolved_axis", self._axis or 0)

    def bit_length(self):
        return self._bit_length


def AbsMaxChannelWiseWeightObserver(quant_bits=8, quant_axis=None):
    return QuanterFactory(AbsMaxChannelWiseWeightObserverLayer,
                          quant_bits=quant_bits, quant_axis=quant_axis)


class FrozenFakeQuanter(BaseQuanter):
    """Calibrated scales frozen into a fake q/dq op — what PTQ.convert
    installs; exportable (jit.save lowers the round/clip/scale program
    into the StableHLO module the Predictor then serves)."""

    def __init__(self, scale, bit_length=8, quant_axis=-1):
        super().__init__()
        self.register_buffer("scale", scale if isinstance(scale, Tensor)
                             else Tensor(jnp.asarray(scale, jnp.float32)))
        self._bit_length = bit_length
        self._axis = quant_axis

    def forward(self, x):
        return _fake_quant_ste(x, self.scale, self._bit_length,
                               self._axis)

    def scales(self):
        return self.scale

    def bit_length(self):
        return self._bit_length

    def quant_axis(self):
        return self._axis


# -- quanted layer wrappers (reference: nn/quant/ + wrapper.py) -------------

class QuantedLinear(Layer):
    def __init__(self, layer, q_config):
        super().__init__()
        self._layer = layer
        self.weight_quanter = (q_config.weight._instance(layer)
                               if q_config.weight else None)
        self.activation_quanter = (q_config.activation._instance(layer)
                                   if q_config.activation else None)

    def forward(self, x):
        from paddle_tpu.nn import functional as F
        if self.activation_quanter is not None:
            x = self.activation_quanter(x)
        w = self._layer.weight
        if self.weight_quanter is not None:
            w = self.weight_quanter(w)
        return F.linear(x, w, self._layer.bias)


class QuantedConv2D(Layer):
    def __init__(self, layer, q_config):
        super().__init__()
        self._layer = layer
        self.weight_quanter = (q_config.weight._instance(layer)
                               if q_config.weight else None)
        self.activation_quanter = (q_config.activation._instance(layer)
                                   if q_config.activation else None)

    def forward(self, x):
        from paddle_tpu.nn import functional as F
        if self.activation_quanter is not None:
            x = self.activation_quanter(x)
        w = self._layer.weight
        if self.weight_quanter is not None:
            w = self.weight_quanter(w)
        lay = self._layer
        return F.conv2d(x, w, lay.bias, stride=lay._stride,
                        padding=lay._padding, dilation=lay._dilation,
                        groups=lay._groups, data_format=lay._data_format)


class ObserveWrapper(Layer):
    """Observer around a leaf layer's output (reference: wrapper.py)."""

    def __init__(self, observer, observed):
        super().__init__()
        self._observer = observer
        self._observed = observed

    def forward(self, *a, **k):
        out = self._observed(*a, **k)
        return self._observer(out)


# -- config -----------------------------------------------------------------

class SingleLayerConfig:
    def __init__(self, activation, weight):
        self.activation = activation
        self.weight = weight


class QuantConfig:
    """Maps layers -> quanter factories (reference: config.py:60; priority
    layer > name > type > global)."""

    def __init__(self, activation, weight):
        self._global = SingleLayerConfig(activation, weight)
        self._layer_configs = []   # (layer_instance, cfg)
        self._name_configs = []    # (name, cfg)
        self._type_configs = []    # (type, cfg)
        self.qat_layer_mappings = dict(DEFAULT_QAT_LAYER_MAPPINGS)

    def add_layer_config(self, layer, activation=None, weight=None):
        layers = layer if isinstance(layer, (list, tuple)) else [layer]
        for l in layers:
            self._layer_configs.append(
                (l, SingleLayerConfig(activation, weight)))

    def add_name_config(self, layer_name, activation=None, weight=None):
        names = (layer_name if isinstance(layer_name, (list, tuple))
                 else [layer_name])
        for n in names:
            self._name_configs.append(
                (n, SingleLayerConfig(activation, weight)))

    def add_type_config(self, layer_type, activation=None, weight=None):
        types = (layer_type if isinstance(layer_type, (list, tuple))
                 else [layer_type])
        for t in types:
            self._type_configs.append(
                (t, SingleLayerConfig(activation, weight)))

    def add_qat_layer_mapping(self, source, target):
        self.qat_layer_mappings[source] = target

    def _config_for(self, name, layer):
        for l, cfg in self._layer_configs:
            if l is layer:
                return cfg
        for n, cfg in self._name_configs:
            if n == name:
                return cfg
        for t, cfg in self._type_configs:
            if isinstance(layer, t):
                return cfg
        return self._global


def _default_mappings():
    from paddle_tpu import nn
    return {nn.Linear: QuantedLinear, nn.Conv2D: QuantedConv2D}


DEFAULT_QAT_LAYER_MAPPINGS = None  # filled lazily below


class _Quantization:
    def __init__(self, config):
        self._config = config

    def _transform(self, model, make_wrapper):
        for name, child in list(model.named_children()):
            cfg = self._config._config_for(name, child)
            wrapper = make_wrapper(name, child, cfg)
            if wrapper is not None:
                model.add_sublayer(name, wrapper)
            else:
                self._transform(child, make_wrapper)
        return model


class QAT(_Quantization):
    """Insert fake quanters for quantization-aware training (reference:
    qat.py:23)."""

    def quantize(self, model, inplace=False):
        if not inplace:
            model = copy.deepcopy(model)

        def make(name, child, cfg):
            for src, dst in self._config.qat_layer_mappings.items():
                if type(child) is src:
                    return dst(child, cfg)
            return None
        return self._transform(model, make)


class PTQ(_Quantization):
    """Post-training quantization: insert observers, calibrate by running
    batches, then convert (reference: ptq.py)."""

    def quantize(self, model, inplace=False):
        if not inplace:
            model = copy.deepcopy(model)

        def make(name, child, cfg):
            for src, dst in self._config.qat_layer_mappings.items():
                if type(child) is src:
                    obs_cfg = SingleLayerConfig(
                        cfg.activation or QuanterFactory(AbsmaxObserverLayer),
                        cfg.weight or QuanterFactory(AbsmaxObserverLayer))
                    return dst(child, obs_cfg)
            if cfg.activation is not None and not list(child.children()):
                # observe outputs of non-quantized leaf layers so their
                # ranges are available at export (reference: ptq.py wraps
                # them in ObserveWrapper)
                return ObserveWrapper(cfg.activation._instance(child), child)
            return None
        return self._transform(model, make)

    def convert(self, model, inplace=False):
        """Freeze observed scales into fake-quant layers."""
        if not inplace:
            model = copy.deepcopy(model)
        def unwrap(parent):
            for name, child in list(parent.named_children()):
                if isinstance(child, ObserveWrapper):
                    parent.add_sublayer(name, child._observed)
                else:
                    unwrap(child)
        unwrap(model)
        for lay in model.sublayers(include_self=True):
            if isinstance(lay, (QuantedLinear, QuantedConv2D)):
                for attr in ("weight_quanter", "activation_quanter"):
                    q = getattr(lay, attr)
                    if isinstance(q, BaseObserver):
                        fq = FrozenFakeQuanter(q.scales(),
                                               q.bit_length(),
                                               q.quant_axis())
                        fq.eval()
                        setattr(lay, attr, fq)
        return model


DEFAULT_QAT_LAYER_MAPPINGS = _default_mappings()
