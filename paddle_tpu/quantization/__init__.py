"""`paddle.quantization` — QAT/PTQ framework (reference:
python/paddle/quantization/: config.py, qat.py, ptq.py, quanters/abs_max.py,
observers/abs_max.py, wrapper.py).

TPU-native: fake-quant is a pure elementwise round/clip program with a
straight-through estimator (custom STE composed as
x + stop_gradient(q(x) - x)), which XLA fuses into the surrounding matmul —
no custom kernels needed. int8 matmul execution at inference rides XLA's
native int8 MXU path when exported.
"""
from __future__ import annotations

import copy

import numpy as np

import jax
import jax.numpy as jnp

from paddle_tpu.core.dispatch import dispatch, OpDef
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.nn.layer.layers import Layer

__all__ = ["QuantConfig", "BaseQuanter", "BaseObserver", "quanter",
           "QAT", "PTQ", "HistObserver", "KLObserver", "AbsmaxObserver",
           "AbsMaxChannelWiseWeightObserver", "FrozenFakeQuanter",
           "QuantizedLinear", "QuantizedConv2D", "layer_error_report"]


def _op(name, fn, *tensors):
    return dispatch(OpDef("quant." + name, fn), tensors, {})


def _fake_quant_ste(x, scale, bit_length=8, quant_axis=-1):
    """Simulated quantization with straight-through gradients. `scale`
    may be a scalar (per-tensor) or a vector broadcast on `quant_axis`
    (per-channel weight quant, reference quanters/abs_max.py
    quant_axis)."""
    bnd = float(2 ** (bit_length - 1) - 1)

    def f(xv, sv):
        if sv.ndim == 1 and xv.ndim > 1:
            shape = [1] * xv.ndim
            shape[quant_axis] = sv.shape[0]
            sv = sv.reshape(shape)
        s = jnp.maximum(sv, 1e-9)
        q = jnp.clip(jnp.round(xv / s * bnd), -bnd, bnd) * s / bnd
        # scale<=0 means the observer never saw non-zero data: no range
        # info, so pass through rather than saturate everything to ~0
        q = jnp.where(sv > 0, q, xv)
        # STE: identity gradient within range
        return xv + jax.lax.stop_gradient(q - xv)
    return _op("fake_quant", f, x, scale)


# -- base types (reference: base_quanter.py / base_observer.py) -------------

class BaseQuanter(Layer):
    def scales(self):
        raise NotImplementedError

    def zero_points(self):
        return None

    def bit_length(self):
        return 8

    def quant_axis(self):
        return -1


class BaseObserver(BaseQuanter):
    pass


class QuanterFactory:
    """Partial-application factory so one config object can instantiate a
    fresh quanter per layer (reference: factory.py)."""

    def __init__(self, cls, *args, **kwargs):
        self._cls, self._args, self._kwargs = cls, args, kwargs

    def _instance(self, layer=None):
        return self._cls(*self._args, **self._kwargs)


QUANTER_REGISTRY = {}


def quanter(class_name):
    """Decorator registering a quanter layer under a factory name
    (reference: factory.py quanter). The factory is available as
    QUANTER_REGISTRY[class_name]."""
    name = class_name
    def deco(cls):
        def factory(*args, **kwargs):
            return QuanterFactory(cls, *args, **kwargs)
        factory.__name__ = name
        QUANTER_REGISTRY[name] = factory
        return cls
    return deco


# -- quanters / observers ---------------------------------------------------

class FakeQuanterWithAbsMaxObserverLayer(BaseQuanter):
    """Moving-average absmax fake quanter (reference:
    quanters/abs_max.py:96 — dynamic_forward updates state, static_forward
    uses accumulated scale)."""

    def __init__(self, moving_rate=0.9, bit_length=8, dtype="float32",
                 name=None):
        super().__init__()
        self._moving_rate = moving_rate
        self._bit_length = bit_length
        self.register_buffer("scale", Tensor(jnp.ones((), jnp.float32)))
        self.register_buffer("state", Tensor(jnp.ones((), jnp.float32)))
        self.register_buffer("accum", Tensor(jnp.ones((), jnp.float32)))

    def forward(self, x):
        if self.training:
            # dynamic_forward: update running absmax. Eager-only — under
            # any jit/vjp tracing (input OR buffers abstract) the
            # accumulated scale is used instead, matching the reference's
            # static_forward (quanters/abs_max.py:180).
            try:
                absmax = float(jnp.max(jnp.abs(x._value)))
                r = self._moving_rate
                state = float(self.state._value) * r + 1.0
                accum = float(self.accum._value) * r + absmax
                self.state._value = jnp.asarray(state, jnp.float32)
                self.accum._value = jnp.asarray(accum, jnp.float32)
                self.scale._value = jnp.asarray(accum / state, jnp.float32)
            except jax.errors.ConcretizationTypeError:
                pass
        return _fake_quant_ste(x, self.scale, self._bit_length)

    def scales(self):
        return self.scale

    def bit_length(self):
        return self._bit_length


def FakeQuanterWithAbsMaxObserver(moving_rate=0.9, bit_length=8,
                                  dtype="float32", name=None):
    return QuanterFactory(FakeQuanterWithAbsMaxObserverLayer,
                          moving_rate=moving_rate, bit_length=bit_length)


class AbsmaxObserverLayer(BaseObserver):
    """PTQ absmax observer: tracks the max |x| seen, no fake-quant during
    calibration (reference: observers/abs_max.py)."""

    def __init__(self, quant_bits=8):
        super().__init__()
        self._bit_length = quant_bits
        self.register_buffer("max_value", Tensor(jnp.zeros((), jnp.float32)))

    def forward(self, x):
        try:
            m = float(jnp.max(jnp.abs(x._value)))
            if m > float(self.max_value._value):
                self.max_value._value = jnp.asarray(m, jnp.float32)
        except jax.errors.ConcretizationTypeError:
            pass  # under tracing: calibration is an eager-mode activity
        return x

    def scales(self):
        return self.max_value

    def bit_length(self):
        return self._bit_length


def AbsmaxObserver(quant_bits=8):
    return QuanterFactory(AbsmaxObserverLayer, quant_bits=quant_bits)


class HistObserverLayer(BaseObserver):
    """Histogram percentile observer (reference: observers/hist.py
    PercentHistObserver): accumulates an |x| histogram over calibration
    batches — re-binning when the range grows — and calibrates the scale
    at the `percent` quantile instead of the raw absmax, which clips
    outliers that would otherwise waste the int8 range."""

    def __init__(self, quant_bits=8, bins=2048, percent=0.99999):
        super().__init__()
        import numpy as np
        self._bit_length = quant_bits
        self._bins = bins
        self._percent = percent
        self._hist = np.zeros(bins, np.float64)
        self._max = 0.0

    def forward(self, x):
        import numpy as np
        try:
            a = np.abs(np.asarray(x._value, np.float32)).ravel()
        except Exception:
            return x        # under tracing: calibration is eager-only
        m = float(a.max()) if a.size else 0.0
        if m > self._max:
            if self._max > 0.0:   # re-bin old counts into the new range
                old = self._hist
                self._hist = np.zeros(self._bins, np.float64)
                centers = (np.arange(self._bins) + 0.5) * (
                    self._max / self._bins)
                idx = np.minimum(
                    (centers / m * self._bins).astype(int),
                    self._bins - 1)
                np.add.at(self._hist, idx, old)
            self._max = m
        if self._max > 0.0:
            h, _ = np.histogram(a, bins=self._bins,
                                range=(0.0, self._max))
            self._hist += h
        return x

    def scales(self):
        import numpy as np
        if self._max == 0.0 or self._hist.sum() == 0:
            return Tensor(jnp.zeros((), jnp.float32))
        c = np.cumsum(self._hist) / self._hist.sum()
        i = int(np.searchsorted(c, self._percent))
        t = (i + 1) / self._bins * self._max
        return Tensor(jnp.asarray(t, jnp.float32))

    def bit_length(self):
        return self._bit_length


def HistObserver(quant_bits=8, bins_count=2048, percent=0.99999):
    return QuanterFactory(HistObserverLayer, quant_bits=quant_bits,
                          bins=bins_count, percent=percent)


class KLObserverLayer(HistObserverLayer):
    """KL-divergence calibration (reference: observers/kl.py): choose the
    clip threshold whose int8-quantized distribution has minimal KL
    divergence from the observed one (the TensorRT calibration recipe)."""

    def __init__(self, quant_bits=8, bins=2048):
        super().__init__(quant_bits=quant_bits, bins=bins)

    def scales(self):
        import numpy as np
        hist = self._hist
        if self._max == 0.0 or hist.sum() == 0:
            return Tensor(jnp.zeros((), jnp.float32))
        levels = 2 ** (self._bit_length - 1)   # 128 for int8
        best_i, best_kl = self._bins, float("inf")
        total = hist.sum()
        for i in range(levels, self._bins + 1, max(1, self._bins // 256)):
            p = hist[:i].copy()
            p[-1] += hist[i:].sum()            # clip tail into last bin
            if p.sum() == 0:
                continue
            # quantize p into `levels` buckets, expand back uniformly
            chunks = np.array_split(p, levels)
            q = np.concatenate([
                np.full(len(ch), ch.sum() / max((ch > 0).sum(), 1))
                * (ch > 0) for ch in chunks])
            pn = p / total
            qn = q / max(q.sum(), 1e-12)
            mask = pn > 0
            kl = float(np.sum(pn[mask] * np.log(
                pn[mask] / np.maximum(qn[mask], 1e-12))))
            if kl < best_kl:
                best_kl, best_i = kl, i
        t = (best_i + 0.5) / self._bins * self._max
        return Tensor(jnp.asarray(min(t, self._max), jnp.float32))


def KLObserver(quant_bits=8, bins_count=2048):
    return QuanterFactory(KLObserverLayer, quant_bits=quant_bits,
                          bins=bins_count)


class AbsMaxChannelWiseWeightObserverLayer(BaseObserver):
    """Per-channel weight observer (reference:
    observers/abs_max_weight.py AbsMaxChannelWiseWeightObserver): one
    scale per output channel along `quant_axis` (paddle layouts: 1 for
    Linear's (in, out) weight, 0 for Conv2D's (out, in, kh, kw))."""

    def __init__(self, quant_bits=8, quant_axis=None):
        super().__init__()
        self._bit_length = quant_bits
        self._axis = quant_axis
        self._scales = None

    def forward(self, x):
        v = x._value if isinstance(x, Tensor) else x
        axis = self._axis
        if axis is None:
            axis = 1 if v.ndim == 2 else 0
        self._resolved_axis = axis
        red = tuple(i for i in range(v.ndim) if i != axis)
        if isinstance(v, jax.core.Tracer):
            return x      # calibration is an eager-mode activity
        self._scales = jnp.max(jnp.abs(v), axis=red)
        return x

    def scales(self):
        return Tensor(self._scales)

    def quant_axis(self):
        return getattr(self, "_resolved_axis", self._axis or 0)

    def bit_length(self):
        return self._bit_length


def AbsMaxChannelWiseWeightObserver(quant_bits=8, quant_axis=None):
    return QuanterFactory(AbsMaxChannelWiseWeightObserverLayer,
                          quant_bits=quant_bits, quant_axis=quant_axis)


class FrozenFakeQuanter(BaseQuanter):
    """Calibrated scales frozen into a fake q/dq op — what PTQ.convert
    installs; exportable (jit.save lowers the round/clip/scale program
    into the StableHLO module the Predictor then serves)."""

    def __init__(self, scale, bit_length=8, quant_axis=-1):
        super().__init__()
        self.register_buffer("scale", scale if isinstance(scale, Tensor)
                             else Tensor(jnp.asarray(scale, jnp.float32)))
        self._bit_length = bit_length
        self._axis = quant_axis

    def forward(self, x):
        return _fake_quant_ste(x, self.scale, self._bit_length,
                               self._axis)

    def scales(self):
        return self.scale

    def bit_length(self):
        return self._bit_length

    def quant_axis(self):
        return self._axis


# -- native int8 execution (reference: phi/kernels/quantize_linear_kernel.h,
# weight_quantize_kernel.h — real quant kernels, not simulation) ------------

def _round_clip_i8(x, scale, bnd):
    """x (float) -> int8 codes with the SAME rounding/clip grid the fake
    quanters use (round-half-even, symmetric +-bnd)."""
    s = jnp.maximum(scale, 1e-9)
    return jnp.clip(jnp.round(x / s * bnd), -bnd, bnd).astype(jnp.int8)


def _weight_only_matmul(xv, qwv, eff_scale):
    """W8A16 matmul. On TPU with tile-able shapes this is the fused
    Pallas kernel (dequant inside the K-loop, 1 byte/weight of HBM
    traffic); otherwise the XLA fallback (which materializes the bf16
    weight — correct, but no bandwidth win)."""
    K, N = qwv.shape
    if (jax.default_backend() == "tpu" and eff_scale.ndim == 1
            and xv.dtype in (jnp.bfloat16, jnp.float32)):
        from paddle_tpu.kernels.quant_matmul import (
            pick_block_m, weight_only_int8_matmul)
        M = 1
        for d in xv.shape[:-1]:
            M *= d
        for blk in (512, 256, 128):
            if K % blk == 0 and N % blk == 0 \
                    and pick_block_m(M) is not None:
                return weight_only_int8_matmul(
                    xv, qwv, eff_scale.astype(jnp.float32),
                    block_n=blk, block_k=blk,
                    out_dtype=xv.dtype).astype(xv.dtype)
    w = qwv.astype(xv.dtype) * eff_scale.astype(xv.dtype)
    return jnp.matmul(xv, w)


class _QuantizedExec(Layer):
    """Shared plumbing for the real-int8 execution layers: mode
    validation, one-time weight quantization on the calibrated grid
    (same rounding as the fake quanters), scale/act-scale buffers.
    Subclasses differ only in which weight axis is the OUT-channel axis
    and in the compute op they dispatch."""

    def _init_quant(self, layer, w_scale, act_scale, bit_length, mode,
                    quant_axis, out_axes, axis_error,
                    per_tensor_act=False):
        if mode not in ("int8", "weight_only_int8"):
            raise ValueError(f"unknown quantized execution mode {mode!r}")
        if mode == "int8" and act_scale is None:
            raise ValueError(
                "mode='int8' needs a calibrated activation scale; "
                "re-run PTQ with an activation observer or use "
                "mode='weight_only_int8'")
        self._mode = mode
        self._bnd = float(2 ** (bit_length - 1) - 1)
        w = layer.weight._value.astype(jnp.float32)
        ws = jnp.asarray(
            w_scale._value if isinstance(w_scale, Tensor) else w_scale,
            jnp.float32)
        if ws.ndim == 1:
            quant_axis = quant_axis % w.ndim
            if quant_axis not in out_axes(w.ndim):
                # the dequant epilogue multiplies AFTER the contraction
                # over the in dims, so per-channel scales must live on
                # the out dim; per-in-channel scales cannot be factored
                raise ValueError(axis_error.format(axis=quant_axis))
            shape = [1] * w.ndim
            shape[quant_axis] = ws.shape[0]
            ws_b = ws.reshape(shape)
        else:
            ws_b = ws
        self.register_buffer(
            "qweight", Tensor(_round_clip_i8(w, ws_b, self._bnd)))
        self.register_buffer("w_scale", Tensor(ws))
        self._quant_axis = quant_axis
        if act_scale is not None:
            a = jnp.asarray(
                act_scale._value if isinstance(act_scale, Tensor)
                else act_scale, jnp.float32)
            if per_tensor_act and a.size != 1:
                raise ValueError(
                    "int8 conv execution needs a per-tensor activation "
                    f"scale, got shape {a.shape}")
            self.register_buffer(
                "act_scale", Tensor(a.reshape(()) if per_tensor_act
                                    else a))
        else:
            self.act_scale = None
        self.bias = layer.bias


class QuantizedLinear(_QuantizedExec):
    """Linear with REAL int8 execution — the deployment path the
    reference implements in quantize_linear_kernel.h / llm.int8-style
    weight_only kernels, built TPU-native:

    - mode='int8' (W8A8): both operands int8, ONE lax.dot_general with
      preferred_element_type=int32 — this is the MXU's native int8 path
      (2x the bf16 peak on v5e) — then a float dequant epilogue
      out = acc_i32 * (s_x*s_w/bnd^2) + bias that XLA fuses.
    - mode='weight_only_int8' (W8A16): weights stored int8 (half the HBM
      of bf16 — decode is weight-bandwidth-bound), dequantized on the fly
      into a bf16 matmul.

    Weights are quantized ONCE at construction (per-out-channel scales
    from the calibration observer); activations use the frozen
    calibration scale. Inference-only: gradients do not flow (use
    QAT/fake-quant for training)."""

    def __init__(self, layer, w_scale, act_scale=None, bit_length=8,
                 quant_axis=1, mode="int8"):
        super().__init__()
        self._init_quant(
            layer, w_scale, act_scale, bit_length, mode, quant_axis,
            out_axes=lambda nd: (1, nd - 1),      # -1 == out dim for 2D
            axis_error=("int8 execution needs per-OUT-channel "
                        "(quant_axis=1) or per-tensor scales, got "
                        "quant_axis={axis}"))

    def forward(self, x):
        qw = self.qweight._value
        ws = self.w_scale._value
        bias = None if self.bias is None else self.bias._value
        bnd = self._bnd
        if self._mode == "weight_only_int8":
            def f(xv, qwv, wsv, *b):
                out = _weight_only_matmul(xv, qwv, wsv / bnd)
                return out + b[0].astype(out.dtype) if b else out
        else:
            def f(xv, qwv, wsv, sav, *b):
                xq = _round_clip_i8(xv.astype(jnp.float32), sav, bnd)
                acc = jax.lax.dot_general(
                    xq, qwv,
                    (((xv.ndim - 1,), (0,)), ((), ())),
                    preferred_element_type=jnp.int32)
                out = acc.astype(jnp.float32) * (sav * wsv / (bnd * bnd))
                if b:
                    out = out + b[0].astype(jnp.float32)
                return out.astype(xv.dtype)
        args = [x, Tensor(qw, stop_gradient=True),
                Tensor(ws, stop_gradient=True)]
        if self._mode == "int8":
            args.append(Tensor(self.act_scale._value, stop_gradient=True))
        if bias is not None:
            args.append(Tensor(bias, stop_gradient=True))
        return _op(self._mode + "_linear", f, *args)


class QuantizedConv2D(_QuantizedExec):
    """Conv2D with REAL int8 execution (reference:
    phi/kernels/quantize_linear_kernel.h + the cuDNN int8 conv path the
    reference reaches through quantized inference passes), TPU-native:

    - mode='int8' (W8A8): both operands int8, ONE
      lax.conv_general_dilated with preferred_element_type=int32 — the
      MXU's native int8 conv path — then a float dequant epilogue
      out = acc_i32 * (s_x*s_w/bnd^2) broadcast over the out-channel
      axis, fused by XLA.
    - mode='weight_only_int8' (W8A16): weights stored int8 (half the
      HBM), dequantized on the fly into a float conv. Conv weights are
      small relative to activations, so the XLA materialize-and-conv
      form is fine here (no Pallas K-loop kernel like linear needs).

    Weight layout is paddle OIHW; per-channel scales must live on the
    OUT-channel axis (quant_axis=0) — the epilogue multiplies after the
    contraction over in*kh*kw, so per-in-channel scales cannot be
    factored out. Activation scale must be per-tensor for the same
    reason. Inference-only.

    Measured (v5e, r3, tools/quant_bench.py conv): end-to-end W8A8 conv
    stack is throughput PARITY with bf16 (8x Conv256@56^2: 7.6 ms both);
    a raw s8 conv micro is 0.76x of bf16 — unlike dot_general, XLA has
    no native int8 conv lowering on this generation. Use this path for
    memory (int8 weights) and numerics-faithful deployment, not speed;
    the int8 *matmul* path (QuantizedLinear) is where the MXU win is."""

    def __init__(self, layer, w_scale, act_scale=None, bit_length=8,
                 quant_axis=0, mode="int8"):
        super().__init__()
        self._init_quant(
            layer, w_scale, act_scale, bit_length, mode, quant_axis,
            out_axes=lambda nd: (0,),             # OIHW out channels
            axis_error=("int8 conv execution needs per-OUT-channel "
                        "(quant_axis=0, OIHW) or per-tensor scales, got "
                        "quant_axis={axis}"),
            per_tensor_act=True)
        self._stride = layer._stride
        self._padding = layer._padding
        self._dilation = layer._dilation
        self._groups = layer._groups
        self._data_format = layer._data_format

    def forward(self, x):
        from paddle_tpu.nn.functional.conv import (_conv_nd, _padding
                                                   as _norm_pad, _tuple
                                                   as _norm_tuple)
        qw = self.qweight._value
        ws = self.w_scale._value
        bias = None if self.bias is None else self.bias._value
        bnd = self._bnd
        channel_last = self._data_format == "NHWC"
        stride = _norm_tuple(self._stride, 2)
        dilation = _norm_tuple(self._dilation, 2)
        pad = _norm_pad(self._padding, 2, stride, None, dilation)
        groups = self._groups

        def conv(xv, wv, preferred=None):
            # same lowering as the float path (bias applied in the
            # dequant epilogue below, not here)
            return _conv_nd(xv, wv, None, stride, pad, dilation, groups,
                            2, channel_last,
                            preferred_element_type=preferred)

        def chan_shape(ndim):
            s = [1] * ndim
            s[-1 if channel_last else 1] = -1
            return tuple(s)

        if self._mode == "weight_only_int8":
            def f(xv, qwv, wsv, *b):
                scale = (wsv / bnd).reshape((-1,) + (1,) * (qwv.ndim - 1)) \
                    if wsv.ndim == 1 else wsv / bnd
                out = conv(xv, qwv.astype(xv.dtype)
                           * scale.astype(xv.dtype))
                if b:
                    out = out + b[0].astype(out.dtype).reshape(
                        chan_shape(out.ndim))
                return out
        else:
            def f(xv, qwv, wsv, sav, *b):
                xq = _round_clip_i8(xv.astype(jnp.float32), sav, bnd)
                acc = conv(xq, qwv, preferred=jnp.int32)
                scale = sav * wsv / (bnd * bnd)
                if scale.ndim == 1:
                    scale = scale.reshape(chan_shape(acc.ndim))
                out = acc.astype(jnp.float32) * scale
                if b:
                    out = out + b[0].astype(jnp.float32).reshape(
                        chan_shape(out.ndim))
                return out.astype(xv.dtype)
        args = [x, Tensor(qw, stop_gradient=True),
                Tensor(ws, stop_gradient=True)]
        if self._mode == "int8":
            args.append(Tensor(self.act_scale._value, stop_gradient=True))
        if bias is not None:
            args.append(Tensor(bias, stop_gradient=True))
        return _op(self._mode + "_conv2d", f, *args)


def layer_error_report(float_model, quant_model, *inputs):
    """Per-layer output error between a float model and its quantized
    counterpart (reference: the per-op error dump of
    analysis/quantization passes). Runs both models on `inputs`, matches
    quantized layers to their float originals by qualified name, and
    returns {name: {'mse':, 'max_abs':, 'rel':, 'mode':}} — the per-layer
    acceptance evidence top-1 agreement can't give."""
    targets = (QuantizedLinear, QuantizedConv2D, QuantedLinear,
               QuantedConv2D)

    def capture(model, pick):
        outs, handles = {}, []
        for name, sub in model.named_sublayers():
            if pick(sub):
                def hook(layer, inp, out, _n=name):
                    outs[_n] = (out[0] if isinstance(out, (tuple, list))
                                else out)
                handles.append(sub.register_forward_post_hook(hook))
        model(*inputs)
        for h in handles:
            h.remove()
        return outs

    from paddle_tpu.nn import Linear, Conv2D
    f_outs = capture(float_model,
                     lambda l: isinstance(l, (Linear, Conv2D)))
    q_outs = capture(quant_model, lambda l: isinstance(l, targets))
    report = {}
    subs = dict(quant_model.named_sublayers())
    for name, q in q_outs.items():
        ref = f_outs.get(name)
        if ref is None:
            continue
        r = np.asarray(ref.numpy(), np.float32)
        v = np.asarray(q.numpy(), np.float32)
        err = v - r
        denom = float(np.abs(r).mean()) or 1.0
        sub = subs[name]
        report[name] = {
            "mse": float((err ** 2).mean()),
            "max_abs": float(np.abs(err).max()),
            "rel": float(np.abs(err).mean() / denom),
            "mode": getattr(sub, "_mode", "fake"),
        }
    return report


# -- quanted layer wrappers (reference: nn/quant/ + wrapper.py) -------------

class QuantedLinear(Layer):
    def __init__(self, layer, q_config):
        super().__init__()
        self._layer = layer
        self.weight_quanter = (q_config.weight._instance(layer)
                               if q_config.weight else None)
        self.activation_quanter = (q_config.activation._instance(layer)
                                   if q_config.activation else None)

    def forward(self, x):
        from paddle_tpu.nn import functional as F
        if self.activation_quanter is not None:
            x = self.activation_quanter(x)
        w = self._layer.weight
        if self.weight_quanter is not None:
            w = self.weight_quanter(w)
        return F.linear(x, w, self._layer.bias)


class QuantedConv2D(Layer):
    def __init__(self, layer, q_config):
        super().__init__()
        self._layer = layer
        self.weight_quanter = (q_config.weight._instance(layer)
                               if q_config.weight else None)
        self.activation_quanter = (q_config.activation._instance(layer)
                                   if q_config.activation else None)

    def forward(self, x):
        from paddle_tpu.nn import functional as F
        if self.activation_quanter is not None:
            x = self.activation_quanter(x)
        w = self._layer.weight
        if self.weight_quanter is not None:
            w = self.weight_quanter(w)
        lay = self._layer
        return F.conv2d(x, w, lay.bias, stride=lay._stride,
                        padding=lay._padding, dilation=lay._dilation,
                        groups=lay._groups, data_format=lay._data_format)


class ObserveWrapper(Layer):
    """Observer around a leaf layer's output (reference: wrapper.py)."""

    def __init__(self, observer, observed):
        super().__init__()
        self._observer = observer
        self._observed = observed

    def forward(self, *a, **k):
        out = self._observed(*a, **k)
        return self._observer(out)


# -- config -----------------------------------------------------------------

class SingleLayerConfig:
    def __init__(self, activation, weight):
        self.activation = activation
        self.weight = weight


class QuantConfig:
    """Maps layers -> quanter factories (reference: config.py:60; priority
    layer > name > type > global)."""

    def __init__(self, activation, weight):
        self._global = SingleLayerConfig(activation, weight)
        self._layer_configs = []   # (layer_instance, cfg)
        self._name_configs = []    # (name, cfg)
        self._type_configs = []    # (type, cfg)
        self.qat_layer_mappings = dict(DEFAULT_QAT_LAYER_MAPPINGS)

    def add_layer_config(self, layer, activation=None, weight=None):
        layers = layer if isinstance(layer, (list, tuple)) else [layer]
        for l in layers:
            self._layer_configs.append(
                (l, SingleLayerConfig(activation, weight)))

    def add_name_config(self, layer_name, activation=None, weight=None):
        names = (layer_name if isinstance(layer_name, (list, tuple))
                 else [layer_name])
        for n in names:
            self._name_configs.append(
                (n, SingleLayerConfig(activation, weight)))

    def add_type_config(self, layer_type, activation=None, weight=None):
        types = (layer_type if isinstance(layer_type, (list, tuple))
                 else [layer_type])
        for t in types:
            self._type_configs.append(
                (t, SingleLayerConfig(activation, weight)))

    def add_qat_layer_mapping(self, source, target):
        self.qat_layer_mappings[source] = target

    def _config_for(self, name, layer):
        for l, cfg in self._layer_configs:
            if l is layer:
                return cfg
        for n, cfg in self._name_configs:
            if n == name:
                return cfg
        for t, cfg in self._type_configs:
            if isinstance(layer, t):
                return cfg
        return self._global


def _default_mappings():
    from paddle_tpu import nn
    return {nn.Linear: QuantedLinear, nn.Conv2D: QuantedConv2D}


DEFAULT_QAT_LAYER_MAPPINGS = None  # filled lazily below


class _Quantization:
    def __init__(self, config):
        self._config = config

    def _transform(self, model, make_wrapper):
        for name, child in list(model.named_children()):
            cfg = self._config._config_for(name, child)
            wrapper = make_wrapper(name, child, cfg)
            if wrapper is not None:
                model.add_sublayer(name, wrapper)
            else:
                self._transform(child, make_wrapper)
        return model


class QAT(_Quantization):
    """Insert fake quanters for quantization-aware training (reference:
    qat.py:23)."""

    def quantize(self, model, inplace=False):
        if not inplace:
            model = copy.deepcopy(model)

        def make(name, child, cfg):
            for src, dst in self._config.qat_layer_mappings.items():
                if type(child) is src:
                    return dst(child, cfg)
            return None
        return self._transform(model, make)


class PTQ(_Quantization):
    """Post-training quantization: insert observers, calibrate by running
    batches, then convert (reference: ptq.py)."""

    def quantize(self, model, inplace=False):
        if not inplace:
            model = copy.deepcopy(model)

        def make(name, child, cfg):
            for src, dst in self._config.qat_layer_mappings.items():
                if type(child) is src:
                    obs_cfg = SingleLayerConfig(
                        cfg.activation or QuanterFactory(AbsmaxObserverLayer),
                        cfg.weight or QuanterFactory(AbsmaxObserverLayer))
                    return dst(child, obs_cfg)
            if cfg.activation is not None and not list(child.children()):
                # observe outputs of non-quantized leaf layers so their
                # ranges are available at export (reference: ptq.py wraps
                # them in ObserveWrapper)
                return ObserveWrapper(cfg.activation._instance(child), child)
            return None
        return self._transform(model, make)

    def convert(self, model, inplace=False, execute="fake"):
        """Freeze observed scales. execute='fake' (default) keeps the
        simulated q/dq program; execute='int8' / 'weight_only_int8'
        installs QuantizedLinear / QuantizedConv2D layers that run REAL
        int8 matmuls / convs (reference: quantize_linear_kernel.h).
        Layers whose calibrated scales cannot feed the real path (e.g.
        int8 without an activation range) freeze to fake-quant; the
        error report flags them with mode='fake'."""
        if execute not in ("fake", "int8", "weight_only_int8"):
            raise ValueError(f"unknown execute mode {execute!r}")
        if not inplace:
            model = copy.deepcopy(model)
        def unwrap(parent):
            for name, child in list(parent.named_children()):
                if isinstance(child, ObserveWrapper):
                    parent.add_sublayer(name, child._observed)
                else:
                    unwrap(child)
        unwrap(model)

        def freeze(lay):
            for attr in ("weight_quanter", "activation_quanter"):
                q = getattr(lay, attr)
                if isinstance(q, BaseObserver):
                    fq = FrozenFakeQuanter(q.scales(), q.bit_length(),
                                           q.quant_axis())
                    fq.eval()
                    setattr(lay, attr, fq)

        def usable_act_scale(aq, per_tensor=False):
            """Calibrated activation scale, or None when the real-int8
            path can't use it (no observer, per-channel when per-tensor
            is required, or a degenerate range — an observer that never
            saw non-zero data reports scale 0, which would saturate
            every activation to +-bnd and dequant to ~0)."""
            if not isinstance(aq, (BaseObserver, FrozenFakeQuanter)):
                return None
            s = aq.scales()
            sv = np.asarray(s._value if isinstance(s, Tensor) else s,
                            np.float32)
            if per_tensor and sv.size != 1:
                return None
            if not np.all(np.isfinite(sv)) or not np.all(sv > 0):
                return None
            return s

        def convert_one(child):
            """Replacement layer for `child`, or None (child frozen or
            handled in place)."""
            if isinstance(child, QuantedLinear) and execute != "fake":
                wq = child.weight_quanter
                act_scale = (usable_act_scale(child.activation_quanter)
                             if execute == "int8" else None)
                if execute == "int8" and act_scale is None:
                    freeze(child)   # no usable act range calibrated
                    return None
                return QuantizedLinear(
                    child._layer, wq.scales(), act_scale,
                    bit_length=wq.bit_length(),
                    quant_axis=(wq.quant_axis()
                                if wq.quant_axis() not in (None, -1)
                                else 1),
                    mode=execute)
            if isinstance(child, QuantedConv2D) and execute != "fake":
                wq = child.weight_quanter
                act_scale = (usable_act_scale(child.activation_quanter,
                                              per_tensor=True)
                             if execute == "int8" else None)
                if execute == "int8" and act_scale is None:
                    freeze(child)   # no usable act range calibrated
                    return None
                try:
                    return QuantizedConv2D(
                        child._layer, wq.scales(), act_scale,
                        bit_length=wq.bit_length(),
                        quant_axis=(wq.quant_axis()
                                    if wq.quant_axis() is not None else 0),
                        mode=execute)
                except ValueError:
                    freeze(child)   # e.g. per-in-channel weight scales
                    return None
            if isinstance(child, (QuantedLinear, QuantedConv2D)):
                freeze(child)
            return None

        def walk(parent):
            for name, child in list(parent.named_children()):
                if isinstance(child, (QuantedLinear, QuantedConv2D)):
                    repl = convert_one(child)
                    if repl is not None:
                        parent.add_sublayer(name, repl)
                else:
                    walk(child)

        if isinstance(model, (QuantedLinear, QuantedConv2D)):
            # a bare quanted layer passed directly (the old
            # include_self=True path): convert/freeze the root itself
            return convert_one(model) or model
        walk(model)
        return model


DEFAULT_QAT_LAYER_MAPPINGS = _default_mappings()
