"""Llama-3 model family, TPU-native.

The reference trains Llama via PaddleNLP's llm/ recipes on top of
paddle.nn + incubate fused ops (fused_rms_norm, fused_rotary_position_
embedding, swiglu, fused attention) and fleet hybrid parallel; this module
is the in-tree equivalent the BASELINE.json north-star config
("Llama-3-8B pretrain, DP+TP, >=40% MFU on v5p") trains.

Design notes (TPU-first):
- All matmuls are (B*S, D) x (D, F) shaped — large, static, bf16-friendly —
  so XLA tiles them onto the MXU.
- Attention goes through nn.functional.scaled_dot_product_attention, which
  routes to the Pallas flash kernel for long sequences.
- The decoder stack iterates Python-side (unrolled under jit). The parallel
  trainer (paddle_tpu.parallel) optionally rewrites the stack into a
  lax.scan over stacked layer params for fast compiles + pipeline parallel.
- Sharding is NOT baked into the model: paddle_tpu.parallel.plan attaches a
  GSPMD sharding plan (param-name -> PartitionSpec) for dp/fsdp/mp/sp axes,
  replacing the reference's ColumnParallelLinear/RowParallelLinear split
  classes (fleet/layers/mpu/mp_layers.py:335,542) with plain Linears +
  shardings.
"""
from __future__ import annotations

from dataclasses import dataclass, field

import jax.numpy as jnp

import paddle_tpu
from paddle_tpu import nn
from paddle_tpu import tensor as T
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.nn import functional as F
from paddle_tpu.nn.layer.norm import RMSNorm
from paddle_tpu.incubate.nn.functional import (
    fused_rotary_position_embedding, swiglu,
)


@dataclass
class LlamaConfig:
    """Mirror of PaddleNLP's LlamaConfig fields that matter for pretrain."""
    vocab_size: int = 128256
    hidden_size: int = 4096
    intermediate_size: int = 14336
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 8
    max_position_embeddings: int = 8192
    rms_norm_eps: float = 1e-5
    rope_theta: float = 500000.0
    tie_word_embeddings: bool = False
    initializer_range: float = 0.02
    use_flash_attention: bool = False
    # single (d, d + 2*kv) qkv matmul / single (d, 2*f) gate-up matmul
    # (PaddleNLP LlamaConfig.fuse_attention_qkv / fuse_attention_ffn):
    # fewer, larger MXU matmuls and one fused dW in the backward.
    # CAVEAT under tensor parallel: the fused output dim is sharded
    # contiguously over 'mp', so the q/k/v (or gate/up) split boundaries
    # cut mid-shard and GSPMD inserts a reshard per layer — prefer the
    # unfused projections on mp>1 meshes until a per-rank-interleaved
    # fused layout exists (PaddleNLP interleaves the fused weight).
    fuse_attention_qkv: bool = False
    fuse_attention_ffn: bool = False
    # rerun each decoder layer's forward during backward instead of saving
    # activations (fleet.utils.recompute equivalent -> jax.checkpoint)
    recompute: bool = False
    # sequence length used by helpers that need one (bench, example inputs)
    seq_length: int = 4096
    # -- fused train-path kernels (ISSUE 14; kernels/blockwise_ce.py +
    # kernels/fused_norm.py) ------------------------------------------
    # loss_chunk > 0: next_token_loss streams the hidden->vocab
    # projection + softmax-CE in `loss_chunk`-row blocks so the
    # [B*S, vocab] logits tensor NEVER materializes (fwd or bwd) — at
    # Llama-3 vocab that tensor dwarfs every activation and caps batch
    # size. 0 = the old dense path (logits returned as before; the
    # blockwise path returns (loss, None)).
    loss_chunk: int = 0
    # optional vocab streaming inside each row block (0 = whole vocab
    # per chunk): peak logits-shaped intermediate is
    # (loss_chunk, loss_vocab_block or vocab)
    loss_vocab_block: int = 0
    # route the decoder's RMSNorms through the fused norm(+residual)
    # custom_vjp op (one read of x, residual written in the same pass,
    # closed-form backward); numerics identical to rms_norm_ref
    fused_norm: bool = False
    # route RoPE through the fused apply (mul/lane-roll/mul/add, no
    # slice/concat transpose chain; inverse-rotation backward)
    fused_rope: bool = False
    # -- decomposed FSDP collectives (ISSUE 19; parallel/overlap.py) --
    # overlap_fsdp: route the FSDP-critical projections (q/k/v/o,
    # gate/up/down and their fused variants) through the chunked
    # ppermute rings so the weight all-gather streams under the matmul
    # instead of ahead of it. overlap_chunks: sub-chunks per resident
    # shard (finer pipelining); 0 disables the rewrite even when
    # overlap_fsdp is set — both knobs off = byte-identical jaxpr to
    # the propagated path. The trainer's overlap_fsdp_guard activates
    # the same rewrite without touching the model config.
    overlap_fsdp: bool = False
    overlap_chunks: int = 0

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads


def next_token_loss(logits, labels, vocab_size):
    """Shifted next-token cross entropy: position t scores labels[t+1].
    Shifts the LABELS (tiny) and marks the final position ignore_index
    instead of slicing logits[:, :-1] — at (B*S, vocab) that slice is a
    multi-hundred-MB copy XLA materializes before the loss.
    cross_entropy's mean already excludes ignored positions (and any
    user-supplied -100 padding)."""
    b = labels.shape[0]
    shifted = T.concat(
        [labels[:, 1:], T.full([b, 1], -100, labels.dtype)], axis=1)
    return F.cross_entropy(
        T.reshape(logits, [-1, vocab_size]),
        T.reshape(shifted, [-1]),
        ignore_index=-100, reduction="mean")


def next_token_loss_blockwise(hidden, weight, labels, config,
                              transpose_w=False):
    """Shifted next-token CE straight from the FINAL HIDDEN states —
    the lm_head projection is fused into the blockwise loss
    (kernels/blockwise_ce.py), so the [B*S, vocab] tensor never
    exists. `weight` is the lm_head weight (D, V); pass
    transpose_w=True for the tied-embedding (V, D) layout — the CALLER
    states the layout explicitly (shape-sniffing it would silently
    skip the transpose when vocab == hidden). Same label shift +
    ignore_index semantics as `next_token_loss`."""
    b = labels.shape[0]
    d = hidden.shape[-1]
    shifted = T.concat(
        [labels[:, 1:], T.full([b, 1], -100, labels.dtype)], axis=1)
    return F.blockwise_cross_entropy(
        T.reshape(hidden, [-1, d]), weight, T.reshape(shifted, [-1]),
        chunk=config.loss_chunk, vocab_block=config.loss_vocab_block,
        ignore_index=-100, transpose_w=transpose_w)


def llama3_8b_config(**overrides) -> LlamaConfig:
    return LlamaConfig(**overrides)


def tiny_llama_config(**overrides) -> LlamaConfig:
    """4-layer toy config for tests / CPU dryruns."""
    base = dict(vocab_size=256, hidden_size=64, intermediate_size=128,
                num_hidden_layers=4, num_attention_heads=4,
                num_key_value_heads=2, max_position_embeddings=256,
                rope_theta=10000.0, seq_length=32)
    base.update(overrides)
    return LlamaConfig(**base)


def _maybe_overlap_linear(layer, x, name, cfg):
    """Route one FSDP-critical projection through the decomposed
    ppermute ring (parallel/overlap.py) when the model config or the
    trainer's overlap_fsdp_guard asks for it. Every other case (guard
    off + knobs off, chunks < 1, no mesh, mesh without the axis, plan
    leaves the param off 'fsdp') falls back to the plain Linear call —
    the disabled path traces a byte-identical jaxpr."""
    from paddle_tpu.parallel import overlap as _ov
    ov = _ov.current_overlap()
    if ov is None and not (cfg.overlap_fsdp and cfg.overlap_chunks > 0):
        return layer(x)
    axis = ov["axis"] if ov else "fsdp"
    chunks = ov["chunks"] if ov else cfg.overlap_chunks
    if chunks < 1:
        return layer(x)
    mesh = _ov.resolve_overlap_mesh(ov["mesh"] if ov else None)
    if mesh is None or axis not in mesh.axis_names:
        return layer(x)
    from paddle_tpu.parallel.plan import fsdp_partition, llama_sharding_plan
    sd = fsdp_partition(llama_sharding_plan(mesh.axis_names),
                        name + ".weight", axis)
    if sd is None:
        return layer(x)
    return _ov.overlap_linear(x, layer.weight, axis=axis, chunks=chunks,
                              shard_dim=sd)


class LlamaAttention(nn.Layer):
    """GQA attention with RoPE (PaddleNLP LlamaAttention equivalent;
    reference fused path: incubate fused_rope + flash_attention kernels
    phi/kernels/gpu/flash_attn_kernel.cu)."""

    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        d, hd = config.hidden_size, config.head_dim
        kv_out = config.num_key_value_heads * hd
        init = nn.initializer.Normal(0.0, config.initializer_range)
        attr = paddle_tpu.nn.ParamAttr(initializer=init)
        if config.fuse_attention_qkv:
            self.qkv_proj = nn.Linear(d, d + 2 * kv_out, weight_attr=attr,
                                      bias_attr=False)
        else:
            self.q_proj = nn.Linear(d, d, weight_attr=attr, bias_attr=False)
            self.k_proj = nn.Linear(d, kv_out, weight_attr=attr,
                                    bias_attr=False)
            self.v_proj = nn.Linear(d, kv_out, weight_attr=attr,
                                    bias_attr=False)
        self.o_proj = nn.Linear(d, d, weight_attr=attr, bias_attr=False)

    def forward(self, hidden_states, position_ids=None, attn_mask=None,
                cache=None, cache_index=None):
        cfg = self.config
        b, s = hidden_states.shape[0], hidden_states.shape[1]
        if cfg.fuse_attention_qkv:
            kv_out = cfg.num_key_value_heads * cfg.head_dim
            qkv = _maybe_overlap_linear(self.qkv_proj, hidden_states,
                                        "qkv_proj", cfg)
            q, k, v = T.split(qkv, [cfg.hidden_size, kv_out, kv_out],
                              axis=-1)
        else:
            q = _maybe_overlap_linear(self.q_proj, hidden_states,
                                      "q_proj", cfg)
            k = _maybe_overlap_linear(self.k_proj, hidden_states,
                                      "k_proj", cfg)
            v = _maybe_overlap_linear(self.v_proj, hidden_states,
                                      "v_proj", cfg)
        q = T.reshape(q, [b, s, cfg.num_attention_heads, cfg.head_dim])
        k = T.reshape(k, [b, s, cfg.num_key_value_heads, cfg.head_dim])
        v = T.reshape(v, [b, s, cfg.num_key_value_heads, cfg.head_dim])
        if cfg.fused_rope:
            # fused train-path apply (kernels/fused_norm.py): identical
            # rotation, one pass, inverse-rotation backward
            from paddle_tpu.incubate.nn.functional import fused_rope_apply
            q, k = fused_rope_apply(q, k, position_ids=position_ids,
                                    rotary_emb_base=cfg.rope_theta)
        else:
            q, k, _ = fused_rotary_position_embedding(
                q, k, None, position_ids=position_ids,
                rotary_emb_base=cfg.rope_theta)
        if cache is not None:
            from paddle_tpu.inference.paged import (PagedState,
                                                    paged_attention_update)
            if isinstance(cache_index, PagedState):
                # paged (block) KV serving: cache is a (k_pool, v_pool)
                # page-pool pair, cache_index carries the block tables +
                # per-slot lengths (inference/paged.py; reference serving
                # path: block_multi_head_attention_kernel.cu)
                out, new_cache = paged_attention_update(
                    q, k, v, cache, cache_index)
                return self.o_proj(out), new_cache
            # incremental decode (models/generation.py): write this
            # step's k/v into the fixed-size buffer at cache_index,
            # then attend over the whole buffer under a position mask
            # (key j visible to query i iff j <= cache_index + i)
            from paddle_tpu.models.generation import kv_cache_update
            k_buf = kv_cache_update(cache[0], k, cache_index)
            v_buf = kv_cache_update(cache[1], v, cache_index)
            kl = k_buf.shape[1]
            k_pos = T.arange(0, kl, dtype="int32")
            q_pos = T.reshape(
                cache_index + T.arange(0, s, dtype="int32"), [s, 1])
            mask = T.unsqueeze(
                T.unsqueeze(T.unsqueeze(k_pos, 0) <= q_pos, 0), 0)
            if attn_mask is not None:
                # combine a user padding mask (bool keep-mask or
                # additive float, broadcastable over (b, h, s, kl))
                # with the position mask instead of dropping it
                if "bool" in str(attn_mask.dtype):
                    mask = T.logical_and(mask, attn_mask)
                else:
                    # -inf (not a large-negative) so SDPA's
                    # fully-masked-row guard (isneginf in _sdpa_ref)
                    # fires for rows a float mask hides entirely;
                    # no +inf exists here, so the sum never NaNs
                    fmask = T.cast(mask, "float32")
                    mask = T.where(
                        mask, T.zeros_like(fmask),
                        T.full_like(fmask, float("-inf"))) + attn_mask
            out = F.scaled_dot_product_attention(
                q, k_buf, v_buf, attn_mask=mask)
            out = T.reshape(out, [b, s, cfg.hidden_size])
            return self.o_proj(out), (k_buf, v_buf)
        if cfg.use_flash_attention and attn_mask is None:
            out, _ = F.flash_attention(q, k, v, causal=True)
        else:
            out = F.scaled_dot_product_attention(
                q, k, v, attn_mask=attn_mask, is_causal=attn_mask is None)
        out = T.reshape(out, [b, s, cfg.hidden_size])
        return _maybe_overlap_linear(self.o_proj, out, "o_proj", cfg)


class LlamaMLP(nn.Layer):
    """SwiGLU MLP (PaddleNLP LlamaMLP; fused path incubate swiglu)."""

    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        d, f = config.hidden_size, config.intermediate_size
        init = nn.initializer.Normal(0.0, config.initializer_range)
        attr = paddle_tpu.nn.ParamAttr(initializer=init)
        self.fuse_ffn = config.fuse_attention_ffn
        if self.fuse_ffn:
            self.gate_up_fused_proj = nn.Linear(d, 2 * f, weight_attr=attr,
                                                bias_attr=False)
        else:
            self.gate_proj = nn.Linear(d, f, weight_attr=attr,
                                       bias_attr=False)
            self.up_proj = nn.Linear(d, f, weight_attr=attr,
                                     bias_attr=False)
        self.down_proj = nn.Linear(f, d, weight_attr=attr, bias_attr=False)

    def forward(self, x):
        cfg = self.config
        if self.fuse_ffn:
            # swiglu(x) splits the fused gate-up output in half (phi
            # SwiGLU kernel semantics)
            h = swiglu(_maybe_overlap_linear(
                self.gate_up_fused_proj, x, "gate_up_fused_proj", cfg))
        else:
            h = swiglu(
                _maybe_overlap_linear(self.gate_proj, x, "gate_proj", cfg),
                _maybe_overlap_linear(self.up_proj, x, "up_proj", cfg))
        return _maybe_overlap_linear(self.down_proj, h, "down_proj", cfg)


class LlamaDecoderLayer(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.self_attn = LlamaAttention(config)
        self.mlp = LlamaMLP(config)
        self.input_layernorm = RMSNorm(config.hidden_size,
                                       epsilon=config.rms_norm_eps)
        self.post_attention_layernorm = RMSNorm(config.hidden_size,
                                                epsilon=config.rms_norm_eps)

    def forward(self, hidden_states, position_ids=None, attn_mask=None,
                cache=None, cache_index=None):
        fused = self.config.fused_norm
        eps = self.config.rms_norm_eps
        residual = hidden_states
        if fused:
            # fused train-path norms (kernels/fused_norm.py): norm1 as
            # one custom_vjp op; norm2 fuses the attention residual add
            # into the same pass (one read of attn_out, h written once)
            h, _ = F.rms_norm_fused(hidden_states,
                                    self.input_layernorm.weight, eps)
        else:
            h = self.input_layernorm(hidden_states)
        new_cache = None
        if cache is not None:
            h, new_cache = self.self_attn(
                h, position_ids=position_ids, attn_mask=attn_mask,
                cache=cache, cache_index=cache_index)
        else:
            h = self.self_attn(h, position_ids=position_ids,
                               attn_mask=attn_mask)
        if fused:
            h2, residual = F.rms_norm_fused(
                h, self.post_attention_layernorm.weight, eps,
                residual=residual)
        else:
            h = residual + h
            residual = h
            h2 = self.post_attention_layernorm(h)
        h2 = self.mlp(h2)
        out = residual + h2
        return out if cache is None else (out, new_cache)


class LlamaModel(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        init = nn.initializer.Normal(0.0, config.initializer_range)
        self.embed_tokens = nn.Embedding(
            config.vocab_size, config.hidden_size,
            weight_attr=paddle_tpu.nn.ParamAttr(initializer=init))
        self.layers = nn.LayerList(
            [LlamaDecoderLayer(config)
             for _ in range(config.num_hidden_layers)])
        self.norm = RMSNorm(config.hidden_size, epsilon=config.rms_norm_eps)

    def _final_norm(self, h):
        if self.config.fused_norm:
            out, _ = F.rms_norm_fused(h, self.norm.weight,
                                      self.config.rms_norm_eps)
            return out
        return self.norm(h)

    def forward(self, input_ids, position_ids=None, attn_mask=None,
                caches=None, cache_index=None):
        from paddle_tpu.distributed.recompute import recompute
        h = self.embed_tokens(input_ids)
        if caches is not None:
            new_caches = []
            for layer, cache in zip(self.layers, caches):
                h, c = layer(h, position_ids=position_ids,
                             attn_mask=attn_mask, cache=cache,
                             cache_index=cache_index)
                new_caches.append(c)
            return self._final_norm(h), new_caches
        for layer in self.layers:
            if self.config.recompute and self.training:
                h = recompute(layer, h, position_ids=position_ids,
                              attn_mask=attn_mask)
            else:
                h = layer(h, position_ids=position_ids, attn_mask=attn_mask)
        return self._final_norm(h)


class LlamaForCausalLM(nn.Layer):
    """Causal LM head + shifted cross-entropy loss (PaddleNLP
    LlamaForCausalLM + LlamaPretrainingCriterion equivalent)."""

    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.model = LlamaModel(config)
        if config.tie_word_embeddings:
            self.lm_head = None
        else:
            init = nn.initializer.Normal(0.0, config.initializer_range)
            self.lm_head = nn.Linear(
                config.hidden_size, config.vocab_size,
                weight_attr=paddle_tpu.nn.ParamAttr(initializer=init),
                bias_attr=False)

    def logits(self, hidden):
        if self.lm_head is None:
            w = self.model.embed_tokens.weight
            return T.matmul(hidden, T.transpose(w, [1, 0]))
        return self.lm_head(hidden)

    def forward(self, input_ids, labels=None, position_ids=None,
                attn_mask=None, caches=None, cache_index=None):
        if caches is not None:
            if labels is not None:
                raise ValueError("KV-cache decode is inference-only; "
                                 "drop labels or caches")
            h, caches = self.model(input_ids, position_ids=position_ids,
                                   attn_mask=attn_mask, caches=caches,
                                   cache_index=cache_index)
            return self.logits(h), caches
        h = self.model(input_ids, position_ids=position_ids,
                       attn_mask=attn_mask)
        if labels is not None and self.config.loss_chunk:
            # blockwise fused loss: the lm_head matmul streams inside
            # the CE (kernels/blockwise_ce.py) — no [B*S, vocab] logits
            # exists to return, hence (loss, None)
            w = (self.model.embed_tokens.weight if self.lm_head is None
                 else self.lm_head.weight)
            loss = next_token_loss_blockwise(
                h, w, labels, self.config,
                transpose_w=self.lm_head is None)
            return loss, None
        logits = self.logits(h)
        if labels is None:
            return logits
        loss = next_token_loss(logits, labels, self.config.vocab_size)
        return loss, logits

    def generate(self, input_ids, max_new_tokens=32, **kwargs):
        """KV-cache autoregressive generation (PaddleNLP
        GenerationMixin.generate equivalent; see models/generation.py)."""
        from paddle_tpu.models.generation import generate
        return generate(self, input_ids, max_new_tokens, **kwargs)


def param_count(config: LlamaConfig) -> int:
    """Analytic parameter count (for MFU math in bench.py)."""
    d, f, v = config.hidden_size, config.intermediate_size, config.vocab_size
    hd = config.head_dim
    per_layer = (d * d + 2 * d * config.num_key_value_heads * hd + d * d
                 + 3 * d * f + 2 * d)
    head = 0 if config.tie_word_embeddings else d * v
    return v * d + config.num_hidden_layers * per_layer + d + head


def flops_per_token(config: LlamaConfig, seq_len: int) -> float:
    """Training FLOPs/token ~= 6*N + attention term (for MFU)."""
    n = param_count(config) - config.vocab_size * config.hidden_size * (
        1 if config.tie_word_embeddings else 2)
    attn = (12 * config.num_hidden_layers * config.hidden_size * seq_len)
    return 6.0 * n + attn
