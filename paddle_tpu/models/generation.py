"""Autoregressive generation with a static KV cache, TPU-first.

Reference surface: PaddleNLP's GenerationMixin (generation/utils.py —
greedy_search / sample with temperature, top-k, top-p, eos handling,
use_cache) and the reference's fused decode loops. The TPU design
differs from the reference's dynamically-growing cache:

- The KV cache is a FIXED-SIZE buffer `(batch, max_len, kv_heads,
  head_dim)` per layer, written in place with
  `lax.dynamic_update_slice` at a TRACED position index. Static shapes
  mean exactly TWO compiles per (batch, prompt_len): one prefill step
  and one single-token decode step reused for every generated token.
- Sampling uses the Gumbel-max trick with HOST-generated noise passed
  into the jitted step as data. Under `jit` a traced-in PRNG key would
  be baked at trace time (every step would sample identically); noise
  as an input keeps the step compiled once and the randomness fresh
  and seedable.
- The decode loop runs host-side, one jitted step per token. That is a
  deliberate serving-first choice: each step's token id is fetched to
  the host anyway (streaming + eos early-exit), so a device-side
  `lax.while_loop` over the whole sequence would buy nothing and lose
  the streaming surface.

Models opt in by accepting `caches=`/`cache_index=` in forward and
returning `(logits, caches)` (LlamaForCausalLM does; see
models/llama.py). Models without cache support still generate through
the full-recompute fallback (`use_cache=False`), which re-runs the
whole prefix per token — fine for tests/small models, quadratic for
real serving.
"""
from __future__ import annotations

import inspect
import os

import numpy as np
import jax
import jax.numpy as jnp

import paddle_tpu
from paddle_tpu import tensor as T
from paddle_tpu.core.dispatch import defop
from paddle_tpu.core.tensor import Tensor

__all__ = ["init_kv_cache", "kv_cache_update", "process_logits",
           "generate", "generate_stream", "generate_speculative"]


@defop("kv_cache_update", differentiable=False,
       spmd_note="cache batch dim shards with dp; kv-head dim with mp")
def kv_cache_update(buf, new, index):
    """Write `new` (b, s, h, d) into the fixed cache buffer at sequence
    position `index` (traced scalar). lax.dynamic_update_slice keeps the
    buffer shape static so the decode step compiles once."""
    index = jnp.asarray(index, jnp.int32).reshape(())
    zero = jnp.zeros((), jnp.int32)
    return jax.lax.dynamic_update_slice(
        buf, new.astype(buf.dtype), (zero, index, zero, zero))


def init_kv_cache(model, batch_size, max_len, dtype=None):
    """Per-layer (k, v) buffers for `model` (a CausalLM exposing
    .config with num_hidden_layers / num_key_value_heads / head_dim).
    dtype defaults to the model's parameter dtype."""
    cfg = model.config
    n_kv = getattr(cfg, "num_key_value_heads", None) \
        or cfg.num_attention_heads
    hd = getattr(cfg, "head_dim", None) \
        or cfg.hidden_size // cfg.num_attention_heads
    if dtype is None:
        dtype = next(iter(model.parameters())).dtype
    shape = [batch_size, max_len, n_kv, hd]
    return [(T.zeros(shape, dtype=dtype), T.zeros(shape, dtype=dtype))
            for _ in range(cfg.num_hidden_layers)]


def process_logits(logits, temperature=1.0, top_k=0, top_p=1.0):
    """Standard logits pipeline (reference: generation/logits_process.py
    TemperatureLogitsWarper, TopKProcess, TopPProcess). logits: (b, v).
    Filtered-out entries are set to -1e9 so Gumbel-max never picks
    them. Pure tensor ops — safe under jit."""
    if temperature != 1.0:
        if temperature <= 0:
            raise ValueError(f"temperature must be > 0, got {temperature}")
        logits = logits / float(temperature)
    v = logits.shape[-1]
    if top_k and 0 < top_k < v:
        kth = T.topk(logits, top_k, axis=-1)[0][:, -1:]      # (b, 1)
        logits = T.where(logits < kth,
                         T.full_like(logits, -1e9), logits)
    if top_p < 1.0:
        sorted_logits = T.sort(logits, axis=-1, descending=True)
        probs = paddle_tpu.nn.functional.softmax(sorted_logits, axis=-1)
        cum = T.cumsum(probs, axis=-1)
        # keep the smallest prefix with cumulative prob >= top_p
        # (always keep the top-1 token)
        keep_sorted = cum - probs < top_p
        # threshold logit = smallest kept logit per row
        thresh = T.min(
            T.where(keep_sorted, sorted_logits,
                    T.full_like(sorted_logits, float("inf"))),
            axis=-1, keepdim=True)
        logits = T.where(logits < thresh,
                         T.full_like(logits, -1e9), logits)
    return logits


def _select_token(logits, do_sample, temperature, top_k, top_p, noise):
    """(b, v) logits -> (b,) int32 next ids. Sampling = Gumbel-max over
    the processed logits with host-supplied noise (see module doc)."""
    if do_sample:
        logits = process_logits(logits, temperature, top_k, top_p)
        logits = logits + noise
    return T.cast(T.argmax(logits, axis=-1), "int32")


def _model_supports_cache(model):
    try:
        sig = inspect.signature(type(model).forward)
    except (TypeError, ValueError):
        return False
    return "caches" in sig.parameters


def _gumbel(rng, shape):
    u = rng.uniform(1e-9, 1.0, size=shape).astype("float32")
    return -np.log(-np.log(u))


def generate_stream(model, input_ids, max_new_tokens=32, *,
                    eos_token_id=None, pad_token_id=0, do_sample=False,
                    temperature=1.0, top_k=0, top_p=1.0, use_cache=True,
                    seed=None):
    """Yield one (batch,) numpy int32 array of token ids per generated
    position. Sequences that hit `eos_token_id` keep yielding
    `pad_token_id`; the stream ends early once ALL sequences finished.
    This iterator is the serving streaming surface (PredictorServer
    SSE / C API callback ride on it)."""
    ids = input_ids if isinstance(input_ids, Tensor) \
        else paddle_tpu.to_tensor(np.asarray(input_ids, "int32"))
    if ids.dtype not in ("int32", "int64"):
        raise ValueError(f"input_ids must be integer ids, got {ids.dtype}")
    b, s = ids.shape[0], ids.shape[1]
    if max_new_tokens <= 0:
        return                      # a 0-token request streams nothing
    rng = np.random.RandomState(seed)
    use_cache = use_cache and _model_supports_cache(model)

    was_training = getattr(model, "training", False)
    model.eval()
    try:
        with paddle_tpu.no_grad():
            if use_cache:
                yield from _stream_cached(
                    model, ids, b, s, max_new_tokens, eos_token_id,
                    pad_token_id, do_sample, temperature, top_k, top_p,
                    rng)
            else:
                yield from _stream_recompute(
                    model, ids, b, s, max_new_tokens, eos_token_id,
                    pad_token_id, do_sample, temperature, top_k, top_p,
                    rng)
    finally:
        if was_training:
            model.train()


def _finish_step(tok, finished, eos_token_id, pad_token_id):
    """Host-side eos bookkeeping: returns (emitted tokens, finished)."""
    if eos_token_id is None:
        return tok, finished
    tok = np.where(finished, pad_token_id, tok)
    finished = finished | (tok == eos_token_id)
    return tok, finished


# compiled prefill/decode step pairs, memoized ON the model instance: a
# serving process pays the XLA trace+compile ONCE per
# (batch, prompt_len, sampling config), not once per request
# (StaticFunction._jit_cache is per-instance). Stored in the model's
# __dict__ (not a global map) so the cache — whose closures capture the
# model strongly — dies with the model instead of leaking it.

def _compiled_steps(model, b, s, do_sample, temperature, top_k, top_p):
    per_model = model.__dict__.setdefault("_gen_step_cache", {})
    key = (b, s, do_sample, temperature, top_k, top_p)
    if key not in per_model:
        def prefill(ids_t, caches):
            pos = T.unsqueeze(T.arange(0, s, dtype="int32"), 0)
            logits, caches = model(
                ids_t, position_ids=pos, caches=caches,
                cache_index=paddle_tpu.to_tensor(0, dtype="int32"))
            return logits[:, -1], caches

        def decode(tok_t, index_t, caches, noise_t):
            pos = T.reshape(index_t, [1, 1])
            logits, caches = model(T.reshape(tok_t, [b, 1]),
                                   position_ids=pos, caches=caches,
                                   cache_index=index_t)
            nxt = _select_token(logits[:, -1], do_sample, temperature,
                                top_k, top_p, noise_t)
            return nxt, caches

        per_model[key] = (paddle_tpu.jit.to_static(prefill),
                          paddle_tpu.jit.to_static(decode))
    return per_model[key]


def _stream_cached(model, ids, b, s, max_new_tokens, eos_token_id,
                   pad_token_id, do_sample, temperature, top_k, top_p,
                   rng):
    max_len = s + max_new_tokens
    caches = init_kv_cache(model, b, max_len)
    sf_prefill, sf_decode = _compiled_steps(
        model, b, s, do_sample, temperature, top_k, top_p)

    def noise_for(vocab):
        # greedy ignores the noise: feed a scalar zero instead of
        # generating + transferring a (b, vocab) array per token
        if not do_sample:
            return paddle_tpu.to_tensor(np.zeros((), "float32"))
        return paddle_tpu.to_tensor(_gumbel(rng, (b, vocab)))

    last_logits, caches = sf_prefill(ids, caches)
    vocab = last_logits.shape[-1]
    tok_t = _select_token(last_logits, do_sample, temperature, top_k,
                          top_p, noise_for(vocab))
    finished = np.zeros((b,), bool)
    tok = np.asarray(tok_t.numpy(), "int32").reshape(b)
    tok, finished = _finish_step(tok, finished, eos_token_id,
                                 pad_token_id)
    yield tok
    for step in range(1, max_new_tokens):
        if finished.all():
            return
        index_t = paddle_tpu.to_tensor(s + step - 1, dtype="int32")
        tok_t, caches = sf_decode(
            paddle_tpu.to_tensor(tok.astype("int32")), index_t, caches,
            noise_for(vocab))
        tok = np.asarray(tok_t.numpy(), "int32").reshape(b)
        tok, finished = _finish_step(tok, finished, eos_token_id,
                                     pad_token_id)
        yield tok


def _stream_recompute(model, ids, b, s, max_new_tokens, eos_token_id,
                      pad_token_id, do_sample, temperature, top_k, top_p,
                      rng):
    """Cache-less fallback: re-run the full prefix per token. Works with
    ANY CausalLM forward(input_ids)->logits; each step recompiles (the
    prefix grows), so this is the correctness/compat path, not the
    serving path."""
    cur = ids
    finished = np.zeros((b,), bool)
    for _ in range(max_new_tokens):
        if finished.all():
            return
        logits = model(cur)
        if isinstance(logits, tuple):
            logits = logits[-1]
        last = logits[:, -1]
        noise = paddle_tpu.to_tensor(_gumbel(rng, tuple(last.shape)))
        tok_t = _select_token(last, do_sample, temperature, top_k, top_p,
                              noise)
        tok = np.asarray(tok_t.numpy(), "int32").reshape(b)
        tok, finished = _finish_step(tok, finished, eos_token_id,
                                     pad_token_id)
        yield tok
        cur = T.concat(
            [cur, paddle_tpu.to_tensor(
                tok.reshape(b, 1).astype(str(cur.dtype)))], axis=1)


def generate(model, input_ids, max_new_tokens=32, **kwargs):
    """Batch generation: returns an int32 Tensor
    (batch, prompt_len + n_generated) of prompt + generated ids
    (n_generated <= max_new_tokens when every sequence hits eos early).
    Keyword args as in generate_stream."""
    ids = input_ids if isinstance(input_ids, Tensor) \
        else paddle_tpu.to_tensor(np.asarray(input_ids, "int32"))
    steps = list(generate_stream(model, ids, max_new_tokens, **kwargs))
    prompt = np.asarray(ids.numpy(), "int32")
    if not steps:
        return paddle_tpu.to_tensor(prompt)
    gen = np.stack(steps, axis=1).astype("int32")
    return paddle_tpu.to_tensor(np.concatenate([prompt, gen], axis=1))


# -- speculative decoding ----------------------------------------------------

def generate_speculative(target, draft, input_ids, max_new_tokens=32, *,
                         num_speculative_tokens=4, eos_token_id=None,
                         stats=None):
    """Greedy speculative decoding (reference ecosystem: PaddleNLP's
    inference 'speculate_method' draft-model path; Leviathan et al.):
    a cheap DRAFT model proposes `num_speculative_tokens` tokens
    autoregressively; the TARGET model scores the whole block in ONE
    cache-aware forward and accepts the longest matching prefix plus
    one corrected/bonus token. Greedy acceptance makes the output
    EXACTLY the target's own greedy continuation — the draft only
    changes how many target forwards it takes.

    TPU shape: the verify step is a width-g decode (static shape, one
    compile) — g tokens enter the MXU together, so acceptance rate
    directly converts sequential decode steps into one batched-matmul
    step. Stale cache slots from rejected proposals are safe: the
    position mask hides them until the next write overwrites the slot.

    batch must be 1 (rows would diverge in acceptance length).
    Returns int32 ids (1, prompt + generated). Pass a dict as `stats`
    to receive {'target_forwards', 'generated', 'accepted_drafts'}."""
    ids = input_ids if isinstance(input_ids, Tensor) \
        else paddle_tpu.to_tensor(np.asarray(input_ids, "int32"))
    b, s = ids.shape[0], ids.shape[1]
    if b != 1:
        raise ValueError("speculative decoding is batch-1 "
                         f"(got batch {b}); rows diverge in acceptance")
    g = int(num_speculative_tokens)
    if g < 1:
        raise ValueError("num_speculative_tokens must be >= 1")
    if not (_model_supports_cache(target) and _model_supports_cache(draft)):
        raise ValueError("both target and draft need KV-cache support")
    prompt = np.asarray(ids.numpy(), "int32")
    if max_new_tokens <= 0:
        return paddle_tpu.to_tensor(prompt)

    was_t, was_d = getattr(target, "training", False), \
        getattr(draft, "training", False)
    target.eval()
    draft.eval()
    n_target_fwd = 0
    try:
        with paddle_tpu.no_grad():
            max_len = s + max_new_tokens + g
            t_caches = init_kv_cache(target, 1, max_len)
            d_caches = init_kv_cache(draft, 1, max_len)
            t_prefill, t_decode = _compiled_steps(
                target, 1, s, False, 1.0, 0, 1.0)
            d_prefill, d_decode = _compiled_steps(
                draft, 1, s, False, 1.0, 0, 1.0)
            t_verify = _compiled_verify(target, 1, g)
            zero = paddle_tpu.to_tensor(np.zeros((), "float32"))

            last, t_caches = t_prefill(ids, t_caches)
            n_target_fwd += 1
            _, d_caches = d_prefill(ids, d_caches)
            pending = int(np.asarray(last.numpy()).argmax(-1).ravel()[0])
            out = [pending]
            p = s                       # both caches hold positions < p
            accepted_total = 0
            while len(out) < max_new_tokens and \
                    (eos_token_id is None or pending != eos_token_id):
                # draft consumes block[i] at position p+i and proposes
                # block[i+1]; the final feed (i = g-1) discards its
                # proposal but is REQUIRED: it writes d_{g-1}'s k/v
                # into slot p+g-1, which the next round attends when
                # every proposal gets accepted
                block = [pending]
                for i in range(g):
                    tok_t, d_caches = d_decode(
                        paddle_tpu.to_tensor(
                            np.array([block[i]], "int32")),
                        paddle_tpu.to_tensor(p + i, dtype="int32"),
                        d_caches, zero)
                    if i < g - 1:
                        block.append(
                            int(np.asarray(tok_t.numpy()).ravel()[0]))
                # ONE target forward scores the whole block;
                # preds[i] = target's greedy token AFTER block[:i+1]
                preds_t, t_caches = t_verify(
                    paddle_tpu.to_tensor(
                        np.array([block], "int32")),
                    paddle_tpu.to_tensor(p, dtype="int32"), t_caches)
                n_target_fwd += 1
                preds = np.asarray(preds_t.numpy()).ravel()
                # accept the longest prefix of proposals the target
                # agrees with, then emit the target's own next token
                # (correction on mismatch, bonus when all accepted)
                n_acc = 0
                while n_acc < g - 1 and block[n_acc + 1] == int(preds[n_acc]):
                    n_acc += 1
                emitted = block[1:1 + n_acc] + [int(preds[n_acc])]
                accepted_total += n_acc
                # caches: target holds block[0..g-1] at p..p+g-1, draft
                # the same — the accepted prefix occupies p..p+n_acc
                # correctly; stale slots beyond are position-masked
                # until overwritten. `pending` (the emitted correction/
                # bonus) enters both caches next round at index p.
                p += n_acc + 1
                pending = emitted[-1]
                out.extend(emitted)
                if eos_token_id is not None and eos_token_id in emitted:
                    out = out[:out.index(eos_token_id) + 1]
                    break
            out = out[:max_new_tokens]
    finally:
        if was_t:
            target.train()
        if was_d:
            draft.train()
    if stats is not None:
        stats.update(target_forwards=n_target_fwd,
                     generated=len(out),
                     accepted_drafts=accepted_total)
    return paddle_tpu.to_tensor(
        np.concatenate([prompt, np.array([out], "int32")], axis=1))


def _compiled_verify(model, b, g):
    """Width-g greedy verify step: feed g tokens at cache position
    `index`, return the argmax token after EACH of them (b, g)."""
    per_model = model.__dict__.setdefault("_gen_step_cache", {})
    key = ("verify", b, g)
    if key not in per_model:
        def verify(block_t, index_t, caches):
            pos = T.reshape(index_t + T.arange(0, g, dtype="int32"),
                            [1, g])
            logits, caches = model(block_t, position_ids=pos,
                                   caches=caches, cache_index=index_t)
            return T.cast(T.argmax(logits, axis=-1), "int32"), caches

        per_model[key] = paddle_tpu.jit.to_static(verify)
    return per_model[key]


# -- deployment bundle: exported prefill + decode programs -------------------
#
# jit.save exports ONE program; generation needs TWO (prefill fills the
# cache from the prompt, the decode step advances one token). The bundle
# is the serving artifact the PredictorServer /generate endpoint and the
# C API PT_Generator* surface load — StableHLO + params + a meta json,
# the same philosophy as the .pdmodel/.pdiparams pair (reference: the
# inference programs PaddleNLP exports for its fused decode).

def _np_process_logits(logits, temperature, top_k, top_p):
    """numpy twin of process_logits for loaded-bundle hosts (no model,
    no tape — the exported programs return raw logits)."""
    x = np.asarray(logits, "float32")
    if temperature != 1.0:
        if temperature <= 0:
            raise ValueError(f"temperature must be > 0, got {temperature}")
        x = x / float(temperature)
    v = x.shape[-1]
    if top_k and 0 < top_k < v:
        kth = np.sort(x, axis=-1)[:, -top_k][:, None]
        x = np.where(x < kth, -1e9, x)
    if top_p < 1.0:
        s = np.sort(x, axis=-1)[:, ::-1]
        e = np.exp(s - s.max(-1, keepdims=True))
        probs = e / e.sum(-1, keepdims=True)
        cum = np.cumsum(probs, axis=-1)
        keep = cum - probs < top_p
        masked = np.where(keep, s, np.inf)
        thresh = masked.min(-1, keepdims=True)
        x = np.where(x < thresh, -1e9, x)
    return x


def _np_select_token(logits, do_sample, temperature, top_k, top_p, rng):
    x = np.asarray(logits, "float32")
    if do_sample:
        x = _np_process_logits(x, temperature, top_k, top_p)
        x = x + _gumbel(rng, x.shape)
    return x.argmax(-1).astype("int32")


def export_generation_bundle(model, path, batch_size, prompt_len,
                             max_new_tokens):
    """Export `model` (cache-capable CausalLM) as a generation bundle:
    `path.prefill.pdmodel` + `path.decode.pdmodel` (StableHLO via
    jax.export), `path.pdiparams` (params), `path.genmeta` (shape/config
    json). Shapes are static: (batch_size, prompt_len) prompts,
    prompt_len + max_new_tokens cache slots."""
    import json

    import jax

    from paddle_tpu.core.tape import no_grad
    from paddle_tpu.jit.functional import _swapped, state_arrays

    if not _model_supports_cache(model):
        raise ValueError(f"{type(model).__name__} has no caches= support; "
                         "the bundle needs the KV-cache decode path")
    cfg = model.config
    b, s = batch_size, prompt_len
    max_len = s + max_new_tokens
    state = state_arrays(model)
    caches = init_kv_cache(model, b, max_len)
    cache_avals = [jax.ShapeDtypeStruct(tuple(c._value.shape),
                                        c._value.dtype)
                   for kv in caches for c in kv]
    n_layers = len(caches)

    def pack(flat):
        return [(Tensor(flat[2 * i]), Tensor(flat[2 * i + 1]))
                for i in range(n_layers)]

    def prefill_pure(state_, ids, *cache_flat):
        pos = T.unsqueeze(T.arange(0, s, dtype="int32"), 0)
        with no_grad(), _swapped(model, state_):
            logits, new_caches = model(
                Tensor(ids), position_ids=pos, caches=pack(cache_flat),
                cache_index=Tensor(jnp.zeros((), jnp.int32)))
        flat = [c._value for kv in new_caches for c in kv]
        return (logits[:, -1]._value, *flat)

    def decode_pure(state_, tok, index, *cache_flat):
        pos = T.reshape(Tensor(index), [1, 1])
        with no_grad(), _swapped(model, state_):
            logits, new_caches = model(
                Tensor(tok), position_ids=pos, caches=pack(cache_flat),
                cache_index=Tensor(index))
        flat = [c._value for kv in new_caches for c in kv]
        return (logits[:, -1]._value, *flat)

    ids_aval = jax.ShapeDtypeStruct((b, s), jnp.int32)
    tok_aval = jax.ShapeDtypeStruct((b, 1), jnp.int32)
    idx_aval = jax.ShapeDtypeStruct((), jnp.int32)
    exp_prefill = jax.export.export(jax.jit(prefill_pure))(
        state, ids_aval, *cache_avals)
    exp_decode = jax.export.export(jax.jit(decode_pure))(
        state, tok_aval, idx_aval, *cache_avals)

    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path + ".prefill.pdmodel", "wb") as f:
        f.write(exp_prefill.serialize())
    with open(path + ".decode.pdmodel", "wb") as f:
        f.write(exp_decode.serialize())
    from paddle_tpu.framework.io_utils import save as _save
    _save(model.state_dict(), path + ".pdiparams")
    with open(path + ".genmeta", "w") as f:
        json.dump({"batch_size": b, "prompt_len": s,
                   "max_new_tokens": max_new_tokens,
                   "num_layers": n_layers,
                   "cache_shape": list(cache_avals[0].shape),
                   "cache_dtype": str(cache_avals[0].dtype),
                   "vocab_size": cfg.vocab_size}, f)
    return path


class GenerationPredictor:
    """Load + drive an exported generation bundle: the serving twin of
    inference.Predictor for autoregressive decode. stream() yields one
    (batch,) int32 array per token — the surface the HTTP /generate
    endpoint and the C API callback ride."""

    def __init__(self, path):
        import json

        import jax

        with open(path + ".prefill.pdmodel", "rb") as f:
            self._prefill = jax.export.deserialize(f.read())
        with open(path + ".decode.pdmodel", "rb") as f:
            self._decode = jax.export.deserialize(f.read())
        with open(path + ".genmeta") as f:
            self.meta = json.load(f)
        from paddle_tpu.framework.io_utils import load as _load
        sd = _load(path + ".pdiparams")
        self._state = {k: (v._value if isinstance(v, Tensor)
                           else np.asarray(v)) for k, v in sd.items()}

    def stream(self, input_ids, max_new_tokens=None, *, eos_token_id=None,
               pad_token_id=0, do_sample=False, temperature=1.0, top_k=0,
               top_p=1.0, seed=None):
        m = self.meta
        ids = np.asarray(input_ids, "int32")
        if ids.shape != (m["batch_size"], m["prompt_len"]):
            raise ValueError(
                f"bundle expects prompt shape "
                f"({m['batch_size']}, {m['prompt_len']}), got {ids.shape}"
                " — pad/trim client-side (exported programs are "
                "shape-monomorphic)")
        steps = (m["max_new_tokens"] if max_new_tokens is None
                 else max_new_tokens)
        if steps > m["max_new_tokens"]:
            raise ValueError(
                f"bundle cache holds {m['max_new_tokens']} new tokens, "
                f"asked for {steps}")
        if steps <= 0:
            return                  # a 0-token request streams nothing
        rng = np.random.RandomState(seed)
        b, s = ids.shape
        caches = [np.zeros(m["cache_shape"], m["cache_dtype"])
                  for _ in range(2 * m["num_layers"])]
        out = self._prefill.call(self._state, ids, *caches)
        logits, caches = np.asarray(out[0]), list(out[1:])
        tok = _np_select_token(logits, do_sample, temperature, top_k,
                               top_p, rng)
        finished = np.zeros((b,), bool)
        tok, finished = _finish_step(tok, finished, eos_token_id,
                                     pad_token_id)
        yield tok
        for step in range(1, steps):
            if finished.all():
                return
            out = self._decode.call(
                self._state, tok.reshape(b, 1).astype("int32"),
                np.int32(s + step - 1), *caches)
            logits, caches = np.asarray(out[0]), list(out[1:])
            tok = _np_select_token(logits, do_sample, temperature, top_k,
                                   top_p, rng)
            tok, finished = _finish_step(tok, finished, eos_token_id,
                                         pad_token_id)
            yield tok

    def generate(self, input_ids, max_new_tokens=None, **kwargs):
        steps = list(self.stream(input_ids, max_new_tokens, **kwargs))
        prompt = np.asarray(input_ids, "int32")
        if not steps:
            return prompt
        return np.concatenate([prompt, np.stack(steps, 1)], axis=1)
