"""Autoregressive generation with a static KV cache, TPU-first.

Reference surface: PaddleNLP's GenerationMixin (generation/utils.py —
greedy_search / sample with temperature, top-k, top-p, eos handling,
use_cache, attention_mask threading) and the reference's fused decode
loops. The TPU design differs from the reference's dynamically-growing
cache:

- The KV cache is a FIXED-SIZE buffer `(batch, max_len, kv_heads,
  head_dim)` per layer, written in place with
  `lax.dynamic_update_slice` at a TRACED position index. Static shapes
  mean exactly TWO compiles per (batch, prompt_len): one prefill step
  and one single-token decode step reused for every generated token.
- Sampling parameters (temperature / top_k / top_p) enter the compiled
  steps as TRACED scalars, so a serving process compiles per
  (batch, prompt_len, do_sample) — NOT per sampling config (every novel
  temperature used to cost a full XLA retrace). Noise for the
  Gumbel-max sample is HOST-generated and passed in as data: a
  traced-in PRNG key would be baked at trace time; noise as an input
  keeps the step compiled once and the randomness fresh and seedable.
- Prompt padding: `attention_mask` (batch, prompt_len), 1 = real
  token, 0 = pad (use LEFT padding so all rows end at the same slot).
  The mask is threaded into every compiled step; RoPE position ids are
  derived from it in-graph (cumsum - 1), so a padded batch generates
  exactly what each row would generate unpadded.
- The decode loop runs host-side by default, one jitted step per token
  (each token id is fetched for streaming + eos early-exit anyway).
  `tokens_per_fetch=N` switches to a DEVICE-SIDE `lax.while_loop` that
  emits up to N tokens per host round-trip — the shape real serving
  wants when host<->device latency dominates (and the only way to
  measure decode throughput through a high-RTT tunnel).

Models opt in by accepting `caches=`/`cache_index=` in forward and
returning `(logits, caches)` (LlamaForCausalLM does; see
models/llama.py). Models without cache support still generate through
the full-recompute fallback (`use_cache=False`), which re-runs the
whole prefix per token — fine for tests/small models, quadratic for
real serving.
"""
from __future__ import annotations

import inspect
import os
from collections import OrderedDict

import numpy as np
import jax
import jax.numpy as jnp

import paddle_tpu
from paddle_tpu import tensor as T
from paddle_tpu.core.dispatch import defop
from paddle_tpu.core.tensor import Tensor

__all__ = ["init_kv_cache", "kv_cache_update", "process_logits",
           "generate", "generate_stream", "generate_speculative"]


@defop("kv_cache_update", differentiable=False,
       spmd_note="cache batch dim shards with dp; kv-head dim with mp")
def kv_cache_update(buf, new, index):
    """Write `new` (b, s, h, d) into the fixed cache buffer at sequence
    position `index` (traced scalar). lax.dynamic_update_slice keeps the
    buffer shape static so the decode step compiles once."""
    index = jnp.asarray(index, jnp.int32).reshape(())
    zero = jnp.zeros((), jnp.int32)
    return jax.lax.dynamic_update_slice(
        buf, new.astype(buf.dtype), (zero, index, zero, zero))


def init_kv_cache(model, batch_size, max_len, dtype=None):
    """Per-layer (k, v) buffers for `model` (a CausalLM exposing
    .config with num_hidden_layers / num_key_value_heads / head_dim).
    dtype defaults to the model's parameter dtype."""
    cfg = model.config
    n_kv = getattr(cfg, "num_key_value_heads", None) \
        or cfg.num_attention_heads
    hd = getattr(cfg, "head_dim", None) \
        or cfg.hidden_size // cfg.num_attention_heads
    if dtype is None:
        dtype = next(iter(model.parameters())).dtype
    shape = [batch_size, max_len, n_kv, hd]
    return [(T.zeros(shape, dtype=dtype), T.zeros(shape, dtype=dtype))
            for _ in range(cfg.num_hidden_layers)]


def process_logits(logits, temperature=1.0, top_k=0, top_p=1.0):
    """Standard logits pipeline (reference: generation/logits_process.py
    TemperatureLogitsWarper, TopKProcess, TopPProcess). logits: (b, v).
    Filtered-out entries are set to -1e9 so Gumbel-max never picks
    them. Pure tensor ops — safe under jit. This is the STATIC-parameter
    form (python scalars); the compiled decode steps use
    _process_logits_traced so sampling configs don't multiply compiles."""
    if temperature != 1.0:
        if temperature <= 0:
            raise ValueError(f"temperature must be > 0, got {temperature}")
        logits = logits / float(temperature)
    v = logits.shape[-1]
    if top_k and 0 < top_k < v:
        kth = T.topk(logits, top_k, axis=-1)[0][:, -1:]      # (b, 1)
        logits = T.where(logits < kth,
                         T.full_like(logits, -1e9), logits)
    if top_p < 1.0:
        sorted_logits = T.sort(logits, axis=-1, descending=True)
        probs = paddle_tpu.nn.functional.softmax(sorted_logits, axis=-1)
        cum = T.cumsum(probs, axis=-1)
        # keep the smallest prefix with cumulative prob >= top_p
        # (always keep the top-1 token)
        keep_sorted = cum - probs < top_p
        # threshold logit = smallest kept logit per row
        thresh = T.min(
            T.where(keep_sorted, sorted_logits,
                    T.full_like(sorted_logits, float("inf"))),
            axis=-1, keepdim=True)
        logits = T.where(logits < thresh,
                         T.full_like(logits, -1e9), logits)
    return logits


def _process_logits_traced(logits, temperature, top_k, top_p):
    """Traced twin of process_logits: temperature/top_k/top_p are TRACED
    scalar Tensors, so one compiled step serves every sampling config
    (ADVICE r3: float-keyed compile cache). Each filter disables itself
    in-graph: top_k <= 0 or >= v -> no-op, top_p >= 1 -> no-op. The
    top-k threshold (k-th largest, k traced) is a one-hot row-select
    off the sorted logits — no dynamic-shape gather."""
    x = T.cast(logits, "float32") / temperature
    v = x.shape[-1]
    # top-k
    sorted_desc = T.sort(x, axis=-1, descending=True)
    kk = T.clip(T.cast(top_k, "int32"), 1, v)
    onehot = T.cast(T.equal(T.arange(0, v, dtype="int32"), kk - 1),
                    "float32")
    kth = T.matmul(sorted_desc, T.reshape(onehot, [v, 1]))       # (b, 1)
    use_k = T.logical_and(top_k > 0, top_k < v)
    kth = T.where(use_k, kth, T.full_like(kth, float("-inf")))
    x = T.where(x < kth, T.full_like(x, -1e9), x)
    # top-p over the (possibly top-k-masked) logits — same order as
    # process_logits / _np_process_logits
    sorted_p = T.sort(x, axis=-1, descending=True)
    probs = paddle_tpu.nn.functional.softmax(sorted_p, axis=-1)
    cum = T.cumsum(probs, axis=-1)
    keep_sorted = cum - probs < top_p
    thresh = T.min(T.where(keep_sorted, sorted_p,
                           T.full_like(sorted_p, float("inf"))),
                   axis=-1, keepdim=True)
    use_p = top_p < 1.0
    thresh = T.where(use_p, thresh, T.full_like(thresh, float("-inf")))
    return T.where(x < thresh, T.full_like(x, -1e9), x)


def _select_token(logits, do_sample, temperature, top_k, top_p, noise):
    """(b, v) logits -> (b,) int32 next ids, STATIC sampling params
    (recompute-fallback path). Sampling = Gumbel-max over the processed
    logits with host-supplied noise (see module doc)."""
    if do_sample:
        logits = process_logits(logits, temperature, top_k, top_p)
        logits = logits + noise
    return T.cast(T.argmax(logits, axis=-1), "int32")


def _select_traced(logits, do_sample, samp):
    """In-graph token selection. samp = () for greedy, else
    (noise_t, temp_t, topk_t, topp_t) traced Tensors."""
    if not do_sample:
        return T.cast(T.argmax(logits, axis=-1), "int32")
    noise_t, temp_t, topk_t, topp_t = samp
    x = _process_logits_traced(logits, temp_t, topk_t, topp_t)
    return T.cast(T.argmax(x + noise_t, axis=-1), "int32")


def _accepts(model, name):
    try:
        sig = inspect.signature(type(model).forward)
    except (TypeError, ValueError):
        return False
    return name in sig.parameters


def _model_supports_cache(model):
    return _accepts(model, "caches")


def _gumbel(rng, shape):
    u = rng.uniform(1e-9, 1.0, size=shape).astype("float32")
    return -np.log(-np.log(u))


def _norm_attention_mask(attention_mask, b, s):
    """-> np bool (b, s) keep-mask, or None when no mask was given.
    Accepts Tensor / array-like of 1/0 or bool (HF/PaddleNLP
    attention_mask convention). LEFT padding is the supported layout
    for cached decode (all rows then end at the same cache slot)."""
    if attention_mask is None:
        return None
    m = attention_mask.numpy() if isinstance(attention_mask, Tensor) \
        else np.asarray(attention_mask)
    if m.shape != (b, s):
        raise ValueError(f"attention_mask must be (batch, prompt_len) = "
                         f"({b}, {s}), got {m.shape}")
    m = m.astype(bool)
    if not m[:, -1].all():
        raise ValueError(
            "attention_mask must be LEFT-padded (every row's last prompt "
            "position real): decode positions and the final-logit select "
            "assume rows end at the same slot. Right-padded rows would "
            "generate from a pad embedding. Re-pad on the left.")
    return m


def _graph_mask(keep_t, max_len):
    """In-graph mask expansion: (b, s) bool keep ->
    (attn (b, 1, 1, max_len) bool over cache slots, n_real (b,) int32).
    Generated positions (slots >= s) are always real."""
    b, s = keep_t.shape[0], keep_t.shape[1]
    if max_len > s:
        pad = T.cast(T.ones([b, max_len - s], dtype="int32"), "bool")
        keep_full = T.concat([keep_t, pad], axis=1)
    else:
        keep_full = keep_t
    attn = T.reshape(keep_full, [b, 1, 1, max_len])
    n_real = T.sum(T.cast(keep_t, "int32"), axis=1)
    return attn, n_real


def generate_stream(model, input_ids, max_new_tokens=32, *,
                    attention_mask=None, eos_token_id=None, pad_token_id=0,
                    do_sample=False, temperature=1.0, top_k=0, top_p=1.0,
                    use_cache=True, seed=None, tokens_per_fetch=1):
    """Yield one (batch,) numpy int32 array of token ids per generated
    position. Sequences that hit `eos_token_id` keep yielding
    `pad_token_id`; the stream ends early once ALL sequences finished.
    This iterator is the serving streaming surface (PredictorServer
    SSE / C API callback ride on it).

    attention_mask: (batch, prompt_len) 1/0 prompt padding mask (LEFT
    padding). tokens_per_fetch>1 runs that many decode steps inside one
    XLA program (lax.while_loop) per host round-trip — tokens then
    arrive in bursts of up to that size, but the per-token host<->device
    latency disappears from the decode path. Greedy block decode emits
    the exact per-token stream; SAMPLED block decode draws its Gumbel
    noise on device from a seed-derived PRNG key (shipping host noise
    would cost block*batch*vocab floats per fetch), so it is
    seed-deterministic but a different stream than tokens_per_fetch=1."""
    ids = input_ids if isinstance(input_ids, Tensor) \
        else paddle_tpu.to_tensor(np.asarray(input_ids, "int32"))
    if ids.dtype not in ("int32", "int64"):
        raise ValueError(f"input_ids must be integer ids, got {ids.dtype}")
    b, s = ids.shape[0], ids.shape[1]
    if max_new_tokens <= 0:
        return                      # a 0-token request streams nothing
    if do_sample and temperature <= 0:
        raise ValueError(f"temperature must be > 0, got {temperature}")
    keep_np = _norm_attention_mask(attention_mask, b, s)
    rng = np.random.RandomState(seed)
    use_cache = use_cache and _model_supports_cache(model)

    was_training = getattr(model, "training", False)
    model.eval()
    try:
        with paddle_tpu.no_grad():
            if use_cache:
                yield from _stream_cached(
                    model, ids, b, s, max_new_tokens, eos_token_id,
                    pad_token_id, do_sample, temperature, top_k, top_p,
                    rng, keep_np, tokens_per_fetch)
            else:
                yield from _stream_recompute(
                    model, ids, b, s, max_new_tokens, eos_token_id,
                    pad_token_id, do_sample, temperature, top_k, top_p,
                    rng, keep_np)
    finally:
        if was_training:
            model.train()


def _finish_step(tok, finished, eos_token_id, pad_token_id):
    """Host-side eos bookkeeping: returns (emitted tokens, finished)."""
    if eos_token_id is None:
        return tok, finished
    tok = np.where(finished, pad_token_id, tok)
    finished = finished | (tok == eos_token_id)
    return tok, finished


# compiled prefill/decode step pairs, memoized ON the model instance: a
# serving process pays the XLA trace+compile ONCE per
# (batch, prompt_len, do_sample), not once per request or per sampling
# config (StaticFunction._jit_cache is per-instance; sampling params are
# traced inputs). Stored in the model's __dict__ (not a global map) so
# the cache — whose closures capture the model strongly — dies with the
# model instead of leaking it. The cache is LRU-bounded: each novel
# (batch, prompt_len) still costs a compile (static shapes), so servers
# should pad prompts to a few canonical lengths.

_GEN_CACHE_CAP = int(os.environ.get("PADDLE_TPU_GEN_STEP_CACHE", "32"))


def _gen_cache_get(model, key, build):
    cache = model.__dict__.setdefault("_gen_step_cache", OrderedDict())
    if key in cache:
        cache.move_to_end(key)
        return cache[key]
    val = build()
    cache[key] = val
    while len(cache) > _GEN_CACHE_CAP:
        cache.popitem(last=False)
    return val


def _mask_capable(model):
    return _accepts(model, "attn_mask") and _accepts(model, "position_ids")


def _compiled_steps(model, b, s, do_sample):
    """-> (prefill, decode) compiled steps.

    prefill(ids, keep, caches, *samp)           -> (tok, caches)
    decode(tok, index, keep, caches, *samp)     -> (tok, caches)
    samp = () greedy, else (noise, temp, topk, topp) traced Tensors.
    keep: (b, s) bool prompt mask (all-True when unpadded); RoPE
    positions derive from it in-graph, so padded rows decode at their
    own positions."""
    masked = _mask_capable(model)

    def build():
        # two body sets (masked / legacy): the dy2static scan dislikes
        # branch-local assignments, and a model without attn_mask
        # support must not receive the kwarg at all
        if masked:
            def prefill(ids_t, keep_t, caches, *samp):
                max_len = caches[0][0].shape[1]
                attn, n_real = _graph_mask(keep_t, max_len)
                posids = T.clip(
                    T.cumsum(T.cast(keep_t, "int32"), axis=1) - 1, 0, s)
                logits, new_caches = model(
                    ids_t, caches=caches, attn_mask=attn,
                    position_ids=posids,
                    cache_index=paddle_tpu.to_tensor(0, dtype="int32"))
                return _select_traced(logits[:, -1], do_sample, samp), \
                    new_caches

            def decode(tok_t, index_t, keep_t, caches, *samp):
                max_len = caches[0][0].shape[1]
                attn, n_real = _graph_mask(keep_t, max_len)
                pos = T.reshape(n_real + (index_t - s), [b, 1])
                logits, new_caches = model(
                    T.reshape(tok_t, [b, 1]), caches=caches,
                    attn_mask=attn, position_ids=pos,
                    cache_index=index_t)
                return _select_traced(logits[:, -1], do_sample, samp), \
                    new_caches
        else:
            def prefill(ids_t, keep_t, caches, *samp):
                posids = T.unsqueeze(T.arange(0, s, dtype="int32"), 0)
                logits, new_caches = model(
                    ids_t, caches=caches, position_ids=posids,
                    cache_index=paddle_tpu.to_tensor(0, dtype="int32"))
                return _select_traced(logits[:, -1], do_sample, samp), \
                    new_caches

            def decode(tok_t, index_t, keep_t, caches, *samp):
                pos = T.reshape(index_t, [1, 1])
                logits, new_caches = model(
                    T.reshape(tok_t, [b, 1]), caches=caches,
                    position_ids=pos, cache_index=index_t)
                return _select_traced(logits[:, -1], do_sample, samp), \
                    new_caches

        return (paddle_tpu.jit.to_static(prefill),
                paddle_tpu.jit.to_static(decode))

    return _gen_cache_get(model, (b, s, do_sample), build)


def _compiled_block(model, b, s, n_steps, do_sample):
    """Device-side decode loop: up to `limit` (<= n_steps) decode steps
    inside ONE XLA program (lax.while_loop with eos early-exit), so one
    host round-trip fetches a whole block of tokens (VERDICT r3 item 3;
    reference analog: the fused decode loop in
    paddle/phi/kernels/fusion/gpu/masked_multihead_attention_kernel.cu).

    block(tok, index, limit, keep, caches, fin, eos, pad, *samp)
      -> (out (b, n_steps) int32, n_done (), finished (b,), tok (b,),
          caches)
    eos < 0 means "no eos". All of limit/eos/pad are traced scalars, so
    tail blocks and different eos ids reuse the one compile."""
    def build():
        def block(tok_t, index_t, limit_t, keep_t, caches, fin_t, eos_t,
                  pad_t, *samp):
            return _block_impl(model, b, s, n_steps, do_sample, tok_t,
                               index_t, limit_t, keep_t, caches, fin_t,
                               eos_t, pad_t, samp)

        return paddle_tpu.jit.to_static(block)

    return _gen_cache_get(model, ("block", b, s, n_steps, do_sample),
                          build)


def _block_impl(model, b, s, n_steps, do_sample, tok_t, index_t, limit_t,
                keep_t, caches, fin_t, eos_t, pad_t, samp):
    """Body of the compiled block-decode program. Lives OUTSIDE the
    to_static-wrapped function so the dy2static AST pass never rewrites
    it — the lax.while_loop here is hand-built (the python `if`s branch
    on build-time constants only).

    Sampling noise is generated ON DEVICE from a traced PRNG key
    (fold_in(key, absolute position) per step): shipping host Gumbel
    noise would cost n_steps*b*vocab floats per fetch — the exact
    host<->device traffic tokens_per_fetch exists to eliminate."""
    masked = _mask_capable(model)
    nl = len(caches)
    if masked:
        attn, n_real = _graph_mask(keep_t, caches[0][0].shape[1])
        attn_v, nreal_v = attn._value, n_real._value
    idx0 = index_t._value
    limit_v = limit_t._value
    eos_v, pad_v = eos_t._value, pad_t._value
    if do_sample:
        key_v = samp[0]._value          # (2,) uint32 raw PRNG key data
        temp_t, topk_t, topp_t = samp[1:]
    cflat = [c._value for kv in caches for c in kv]

    def body(carry):
        i, tok, fin, out = carry[0], carry[1], carry[2], carry[3]
        cf = carry[4:]
        ci = [(Tensor(cf[2 * j]), Tensor(cf[2 * j + 1]))
              for j in range(nl)]
        index = Tensor(idx0 + i)
        if masked:
            pos = T.reshape(Tensor(nreal_v) + (index - s), [b, 1])
            kw = dict(attn_mask=Tensor(attn_v), position_ids=pos)
        else:
            kw = dict(position_ids=T.reshape(index, [1, 1]))
        logits, ci = model(T.reshape(Tensor(tok), [b, 1]),
                           caches=ci, cache_index=index, **kw)
        last = logits[:, -1]
        if do_sample:
            step_key = jax.random.fold_in(
                jax.random.wrap_key_data(key_v), idx0 + i)
            ni = Tensor(jax.random.gumbel(
                step_key, (b, last.shape[-1]), jnp.float32))
            x = _process_logits_traced(last, temp_t, topk_t, topp_t)
            nxt = T.cast(T.argmax(x + ni, axis=-1), "int32")
        else:
            nxt = T.cast(T.argmax(last, axis=-1), "int32")
        finT = Tensor(fin)
        nxt = T.where(finT, T.zeros_like(nxt) + Tensor(pad_v), nxt)
        has_eos = Tensor(eos_v) >= 0
        newfin = T.logical_or(
            finT, T.logical_and(has_eos, T.equal(nxt, Tensor(eos_v))))
        out = jax.lax.dynamic_update_slice(
            out, jnp.reshape(nxt._value, (b, 1)),
            (jnp.zeros((), jnp.int32), i))
        new_cf = [c._value for kv in ci for c in kv]
        return (i + 1, nxt._value, newfin._value, out, *new_cf)

    def cond(carry):
        i, fin = carry[0], carry[2]
        return jnp.logical_and(i < limit_v,
                               jnp.logical_not(jnp.all(fin)))

    init = (jnp.zeros((), jnp.int32),
            tok_t._value.astype(jnp.int32),
            fin_t._value,
            jnp.broadcast_to(pad_v, (b, n_steps)).astype(jnp.int32),
            *cflat)
    final = jax.lax.while_loop(cond, body, init)
    n_done, tok_f, fin_f, out_buf = final[0], final[1], final[2], final[3]
    cf = final[4:]
    new_caches = [(Tensor(cf[2 * j]), Tensor(cf[2 * j + 1]))
                  for j in range(nl)]
    return (Tensor(out_buf), Tensor(n_done), Tensor(fin_f),
            Tensor(tok_f), new_caches)


def _stream_cached(model, ids, b, s, max_new_tokens, eos_token_id,
                   pad_token_id, do_sample, temperature, top_k, top_p,
                   rng, keep_np, tokens_per_fetch):
    if keep_np is not None and keep_np.all():
        keep_np = None              # an all-ones mask is no mask
    if keep_np is not None and not _mask_capable(model):
        raise ValueError(
            f"{type(model).__name__} accepts caches= but not attn_mask=/"
            "position_ids=; attention_mask needs both (or use "
            "use_cache=False)")
    max_len = s + max_new_tokens
    caches = init_kv_cache(model, b, max_len)
    sf_prefill, sf_decode = _compiled_steps(model, b, s, do_sample)
    keep_t = paddle_tpu.to_tensor(
        keep_np if keep_np is not None else np.ones((b, s), bool))
    vocab = model.config.vocab_size

    # the sampling-config tensors are loop constants; only the gumbel
    # noise is fresh per step
    const_samp = () if not do_sample else (
        paddle_tpu.to_tensor(float(temperature)),
        paddle_tpu.to_tensor(int(top_k), dtype="int32"),
        paddle_tpu.to_tensor(float(top_p)))

    def samp_args(n=None):
        if not do_sample:
            return ()
        shape = (b, vocab) if n is None else (n, b, vocab)
        return (paddle_tpu.to_tensor(_gumbel(rng, shape)), *const_samp)

    tok_t, caches = sf_prefill(ids, keep_t, caches, *samp_args())
    finished = np.zeros((b,), bool)
    tok = np.asarray(tok_t.numpy(), "int32").reshape(b)
    tok, finished = _finish_step(tok, finished, eos_token_id,
                                 pad_token_id)
    yield tok

    block = int(tokens_per_fetch or 1)
    if block > 1:
        sf_block = _compiled_block(model, b, s, block, do_sample)
        eos_t = paddle_tpu.to_tensor(
            -1 if eos_token_id is None else int(eos_token_id),
            dtype="int32")
        pad_t = paddle_tpu.to_tensor(int(pad_token_id), dtype="int32")
        # block noise is device-generated from ONE key (2 words instead
        # of block*b*vocab floats per fetch); fold_in by absolute
        # position keeps every step's draw distinct and seed-stable
        block_samp = ()
        if do_sample:
            block_seed = int(rng.randint(0, 2 ** 31 - 1))
            block_samp = (Tensor(jax.random.key_data(
                jax.random.key(block_seed))), *const_samp)
        produced = 1
        while produced < max_new_tokens and not finished.all():
            limit = min(block, max_new_tokens - produced)
            out_t, n_t, fin_t, tok_t, caches = sf_block(
                paddle_tpu.to_tensor(tok.astype("int32")),
                paddle_tpu.to_tensor(s + produced - 1, dtype="int32"),
                paddle_tpu.to_tensor(limit, dtype="int32"),
                keep_t, caches, paddle_tpu.to_tensor(finished),
                eos_t, pad_t, *block_samp)
            n_done = int(np.asarray(n_t.numpy()))
            outb = np.asarray(out_t.numpy(), "int32")
            finished = np.asarray(fin_t.numpy(), bool)
            for j in range(n_done):
                yield outb[:, j]
            produced += n_done
            tok = np.asarray(tok_t.numpy(), "int32").reshape(b)
            if n_done == 0:     # all rows were already finished
                return
        return

    for step in range(1, max_new_tokens):
        if finished.all():
            return
        index_t = paddle_tpu.to_tensor(s + step - 1, dtype="int32")
        tok_t, caches = sf_decode(
            paddle_tpu.to_tensor(tok.astype("int32")), index_t, keep_t,
            caches, *samp_args())
        tok = np.asarray(tok_t.numpy(), "int32").reshape(b)
        tok, finished = _finish_step(tok, finished, eos_token_id,
                                     pad_token_id)
        yield tok


def _stream_recompute(model, ids, b, s, max_new_tokens, eos_token_id,
                      pad_token_id, do_sample, temperature, top_k, top_p,
                      rng, keep_np):
    """Cache-less fallback: re-run the full prefix per token. Works with
    ANY CausalLM forward(input_ids)->logits; each step recompiles (the
    prefix grows), so this is the correctness/compat path, not the
    serving path. attention_mask requires the model to accept
    attn_mask= (a combined causal+padding keep-mask is passed)."""
    masked = keep_np is not None and not keep_np.all()
    if masked and not _accepts(model, "attn_mask"):
        raise ValueError(
            f"{type(model).__name__} does not accept attn_mask=; "
            "cannot honor attention_mask on the recompute path")
    cur = ids
    finished = np.zeros((b,), bool)
    for _ in range(max_new_tokens):
        if finished.all():
            return
        kwargs = {}
        if masked:
            cl = cur.shape[1]
            kf = np.concatenate(
                [keep_np, np.ones((b, cl - s), bool)], axis=1)
            causal = np.tril(np.ones((cl, cl), bool))
            m = causal[None, None] & kf[:, None, None, :]
            kwargs["attn_mask"] = paddle_tpu.to_tensor(m)
            if _accepts(model, "position_ids"):
                kwargs["position_ids"] = paddle_tpu.to_tensor(
                    np.maximum(np.cumsum(kf, 1) - 1, 0).astype("int32"))
        logits = model(cur, **kwargs)
        if isinstance(logits, tuple):
            logits = logits[-1]
        last = logits[:, -1]
        noise = paddle_tpu.to_tensor(_gumbel(rng, tuple(last.shape)))
        tok_t = _select_token(last, do_sample, temperature, top_k, top_p,
                              noise)
        tok = np.asarray(tok_t.numpy(), "int32").reshape(b)
        tok, finished = _finish_step(tok, finished, eos_token_id,
                                     pad_token_id)
        yield tok
        cur = T.concat(
            [cur, paddle_tpu.to_tensor(
                tok.reshape(b, 1).astype(str(cur.dtype)))], axis=1)


def generate(model, input_ids, max_new_tokens=32, **kwargs):
    """Batch generation: returns an int32 Tensor
    (batch, prompt_len + n_generated) of prompt + generated ids
    (n_generated <= max_new_tokens when every sequence hits eos early).
    Keyword args as in generate_stream (attention_mask for padded
    prompts, tokens_per_fetch for device-side block decode)."""
    ids = input_ids if isinstance(input_ids, Tensor) \
        else paddle_tpu.to_tensor(np.asarray(input_ids, "int32"))
    steps = list(generate_stream(model, ids, max_new_tokens, **kwargs))
    prompt = np.asarray(ids.numpy(), "int32")
    if not steps:
        return paddle_tpu.to_tensor(prompt)
    gen = np.stack(steps, axis=1).astype("int32")
    return paddle_tpu.to_tensor(np.concatenate([prompt, gen], axis=1))


# -- speculative decoding ----------------------------------------------------

def generate_speculative(target, draft, input_ids, max_new_tokens=32, *,
                         num_speculative_tokens=4, eos_token_id=None,
                         do_sample=False, temperature=1.0, top_k=0,
                         top_p=1.0, seed=None, stats=None):
    """Speculative decoding (reference ecosystem: PaddleNLP's inference
    'speculate_method' draft-model path; Leviathan et al. 2211.17192):
    a cheap DRAFT model proposes `num_speculative_tokens` tokens
    autoregressively; the TARGET model scores the whole block in ONE
    cache-aware forward and accepts a prefix.

    Greedy (do_sample=False): accept the longest prefix matching the
    target's own argmax, then emit the target's correction/bonus token —
    the output EXACTLY equals the target's greedy continuation.

    Sampling (do_sample=True): standard REJECTION SAMPLING — proposal
    x_i ~ q_i (the draft's processed distribution) is accepted with
    prob min(1, p_i(x_i)/q_i(x_i)); on first rejection the emitted
    token is resampled from the residual norm(max(p_i - q_i, 0)); if
    everything is accepted, a bonus token is sampled from p_g. The
    emitted sequence is distributed EXACTLY as plain sampling from the
    target under the same temperature/top_k/top_p (the acceptance test
    and residual sample run ON DEVICE in the verify program; only two
    scalars are fetched per round).

    TPU shape: the verify step is a width-g decode (static shape, one
    compile) — g tokens enter the MXU together, so acceptance rate
    directly converts sequential decode steps into one batched-matmul
    step. Stale cache slots from rejected proposals are safe: the
    position mask hides them until the next write overwrites the slot.

    batch must be 1 (rows would diverge in acceptance length).
    Returns int32 ids (1, prompt + generated). Pass a dict as `stats`
    to receive {'target_forwards', 'generated', 'accepted_drafts'}."""
    ids = input_ids if isinstance(input_ids, Tensor) \
        else paddle_tpu.to_tensor(np.asarray(input_ids, "int32"))
    b, s = ids.shape[0], ids.shape[1]
    if b != 1:
        raise ValueError("speculative decoding is batch-1 "
                         f"(got batch {b}); rows diverge in acceptance")
    g = int(num_speculative_tokens)
    if g < 1:
        raise ValueError("num_speculative_tokens must be >= 1")
    if not (_model_supports_cache(target) and _model_supports_cache(draft)):
        raise ValueError("both target and draft need KV-cache support")
    if do_sample and temperature <= 0:
        raise ValueError(f"temperature must be > 0, got {temperature}")
    prompt = np.asarray(ids.numpy(), "int32")
    if max_new_tokens <= 0:
        return paddle_tpu.to_tensor(prompt)
    rng = np.random.RandomState(seed)

    was_t, was_d = getattr(target, "training", False), \
        getattr(draft, "training", False)
    target.eval()
    draft.eval()
    n_target_fwd = 0
    vocab = target.config.vocab_size
    keep1 = paddle_tpu.to_tensor(np.ones((1, s), bool))

    def samp_tensors():
        return (paddle_tpu.to_tensor(float(temperature)),
                paddle_tpu.to_tensor(int(top_k), dtype="int32"),
                paddle_tpu.to_tensor(float(top_p)))

    try:
        with paddle_tpu.no_grad():
            max_len = s + max_new_tokens + g
            t_caches = init_kv_cache(target, 1, max_len)
            d_caches = init_kv_cache(draft, 1, max_len)
            t_prefill, _ = _compiled_steps(target, 1, s, do_sample)
            d_prefill, d_decode = _compiled_steps(draft, 1, s, False)
            if do_sample:
                d_spec = _compiled_spec_draft(draft)
                t_verify = _compiled_spec_verify(target, g)
                tk = samp_tensors()
            else:
                t_verify = _compiled_verify(target, 1, g)

            pre_samp = ()
            if do_sample:
                pre_samp = (paddle_tpu.to_tensor(_gumbel(rng, (1, vocab))),
                            *tk)
            tok_t, t_caches = t_prefill(ids, keep1, t_caches, *pre_samp)
            n_target_fwd += 1
            _, d_caches = d_prefill(ids, keep1, d_caches)
            pending = int(np.asarray(tok_t.numpy()).ravel()[0])
            out = [pending]
            p = s                       # both caches hold positions < p
            accepted_total = 0
            while len(out) < max_new_tokens and \
                    (eos_token_id is None or pending != eos_token_id):
                # draft consumes block[i] at position p+i and proposes
                # block[i+1]; the final feed (i = g-1) discards its
                # proposal but is REQUIRED: it writes d_{g-1}'s k/v
                # into slot p+g-1, which the next round attends when
                # every proposal gets accepted
                block = [pending]
                q_rows = []
                for i in range(g):
                    if do_sample:
                        tok_t, q_t, d_caches = d_spec(
                            paddle_tpu.to_tensor(
                                np.array([block[i]], "int32")),
                            paddle_tpu.to_tensor(p + i, dtype="int32"),
                            d_caches,
                            paddle_tpu.to_tensor(
                                _gumbel(rng, (1, vocab))), *tk)
                    else:
                        tok_t, d_caches = d_decode(
                            paddle_tpu.to_tensor(
                                np.array([block[i]], "int32")),
                            paddle_tpu.to_tensor(p + i, dtype="int32"),
                            keep1, d_caches)
                    if i < g - 1:
                        block.append(
                            int(np.asarray(tok_t.numpy()).ravel()[0]))
                        if do_sample:
                            q_rows.append(q_t)
                block_t = paddle_tpu.to_tensor(np.array([block], "int32"))
                p_t = paddle_tpu.to_tensor(p, dtype="int32")
                if do_sample:
                    q_stack = (T.concat(q_rows, axis=0) if q_rows
                               else T.zeros([0, vocab], dtype="float32"))
                    u_t = paddle_tpu.to_tensor(
                        rng.uniform(size=(g - 1,)).astype("float32"))
                    gn_t = paddle_tpu.to_tensor(_gumbel(rng, (vocab,)))
                    nacc_t, emit_t, t_caches = t_verify(
                        block_t, q_stack, u_t, gn_t, p_t, t_caches, *tk)
                    n_target_fwd += 1
                    n_acc = int(np.asarray(nacc_t.numpy()))
                    emitted = block[1:1 + n_acc] + \
                        [int(np.asarray(emit_t.numpy()))]
                else:
                    preds_t, t_caches = t_verify(block_t, p_t, t_caches)
                    n_target_fwd += 1
                    preds = np.asarray(preds_t.numpy()).ravel()
                    # accept the longest prefix of proposals the target
                    # agrees with, then emit the target's own next token
                    # (correction on mismatch, bonus when all accepted)
                    n_acc = 0
                    while n_acc < g - 1 and \
                            block[n_acc + 1] == int(preds[n_acc]):
                        n_acc += 1
                    emitted = block[1:1 + n_acc] + [int(preds[n_acc])]
                accepted_total += n_acc
                # caches: target holds block[0..g-1] at p..p+g-1, draft
                # the same — the accepted prefix occupies p..p+n_acc
                # correctly; stale slots beyond are position-masked
                # until overwritten. `pending` (the emitted correction/
                # bonus/resample) enters both caches next round at p.
                p += n_acc + 1
                pending = emitted[-1]
                out.extend(emitted)
                if eos_token_id is not None and eos_token_id in emitted:
                    out = out[:out.index(eos_token_id) + 1]
                    break
            out = out[:max_new_tokens]
    finally:
        if was_t:
            target.train()
        if was_d:
            draft.train()
    if stats is not None:
        stats.update(target_forwards=n_target_fwd,
                     generated=len(out),
                     accepted_drafts=accepted_total)
    return paddle_tpu.to_tensor(
        np.concatenate([prompt, np.array([out], "int32")], axis=1))


def _compiled_verify(model, b, g):
    """Width-g greedy verify step: feed g tokens at cache position
    `index`, return the argmax token after EACH of them (b, g)."""
    def build():
        def verify(block_t, index_t, caches):
            pos = T.reshape(index_t + T.arange(0, g, dtype="int32"),
                            [1, g])
            logits, caches = model(block_t, position_ids=pos,
                                   caches=caches, cache_index=index_t)
            return T.cast(T.argmax(logits, axis=-1), "int32"), caches

        return paddle_tpu.jit.to_static(verify)

    return _gen_cache_get(model, ("verify", b, g), build)


def _compiled_spec_draft(model):
    """Sampling draft step: decode one token AND return the processed
    draft distribution q it was sampled from (needed by the rejection
    test). -> (tok (1,), q (1, v) float32, caches)."""
    def build():
        def spec_draft(tok_t, index_t, caches, noise_t, temp_t, topk_t,
                       topp_t):
            logits, caches = model(
                T.reshape(tok_t, [1, 1]),
                position_ids=T.reshape(index_t, [1, 1]),
                caches=caches, cache_index=index_t)
            x = _process_logits_traced(logits[:, -1], temp_t, topk_t,
                                       topp_t)
            q = paddle_tpu.nn.functional.softmax(x, axis=-1)
            tok = T.cast(T.argmax(x + noise_t, axis=-1), "int32")
            return tok, q, caches

        return paddle_tpu.jit.to_static(spec_draft)

    return _gen_cache_get(model, ("spec_draft",), build)


def _compiled_spec_verify(model, g):
    """Rejection-sampling verify: ONE target forward over the block,
    accept/resample ON DEVICE (only n_acc + the emitted token leave the
    chip).

    verify(block (1,g), q (g-1,v), u (g-1,), gumbel (v,), index,
           caches, temp, topk, topp) -> (n_acc (), emitted (), caches)

    p_i = target's processed distribution after block[:i+1]. Proposal
    x_i = block[i+1] accepted iff u_i * q_i(x_i) < p_i(x_i). The
    emitted token samples from max(p_row - q_row, 0) renormalized at
    row n_acc, where q is zero-padded with a bonus row — so the
    all-accepted case reduces to sampling the bonus from p_{g-1}."""
    def build():
        def spec_verify(block_t, q_t, u_t, gnoise_t, index_t, caches,
                        temp_t, topk_t, topp_t):
            v = q_t.shape[-1]
            pos = T.reshape(index_t + T.arange(0, g, dtype="int32"),
                            [1, g])
            logits, caches = model(block_t, position_ids=pos,
                                   caches=caches, cache_index=index_t)
            lg = _process_logits_traced(
                T.reshape(logits, [g, v]), temp_t, topk_t, topp_t)
            p = paddle_tpu.nn.functional.softmax(lg, axis=-1)  # (g, v)
            props = block_t[0, 1:]                             # (g-1,)
            oh = T.cast(T.equal(T.unsqueeze(props, 1),
                                T.arange(0, v, dtype="int32")),
                        "float32")                             # (g-1, v)
            pi = T.sum(p[:g - 1] * oh, axis=-1)                # (g-1,)
            qi = T.sum(q_t * oh, axis=-1)
            accept = T.cast(u_t * qi < pi, "int32")
            # leading run of accepts: positions where no rejection yet
            n_acc = T.sum(T.cast(
                T.equal(T.cumsum(1 - accept, axis=0), 0), "int32"))
            ohrow = T.cast(T.equal(T.arange(0, g, dtype="int32"), n_acc),
                           "float32")                          # (g,)
            p_row = T.matmul(T.reshape(ohrow, [1, g]), p)[0]   # (v,)
            qpad = T.concat(
                [q_t, T.zeros([1, v], dtype="float32")], axis=0)
            q_row = T.matmul(T.reshape(ohrow, [1, g]), qpad)[0]
            r = T.maximum(p_row - q_row, T.zeros_like(p_row))
            # numerically-degenerate guard: p == q at the rejected row
            # makes the residual all-zero (rejection there has measure
            # zero); fall back to p_row
            r = T.where(T.sum(r) > 0, r, p_row)
            emitted = T.cast(
                T.argmax(T.log(r + 1e-20) + gnoise_t, axis=-1), "int32")
            return n_acc, emitted, caches

        return paddle_tpu.jit.to_static(spec_verify)

    return _gen_cache_get(model, ("spec_verify", g), build)


# -- deployment bundle: exported prefill + decode programs -------------------
#
# jit.save exports ONE program; generation needs TWO (prefill fills the
# cache from the prompt, the decode step advances one token). The bundle
# is the serving artifact the PredictorServer /generate endpoint and the
# C API PT_Generator* surface load — StableHLO + params + a meta json,
# the same philosophy as the .pdmodel/.pdiparams pair (reference: the
# inference programs PaddleNLP exports for its fused decode).

def _np_process_logits(logits, temperature, top_k, top_p):
    """numpy twin of process_logits for loaded-bundle hosts (no model,
    no tape — the exported programs return raw logits)."""
    x = np.asarray(logits, "float32")
    if temperature != 1.0:
        if temperature <= 0:
            raise ValueError(f"temperature must be > 0, got {temperature}")
        x = x / float(temperature)
    v = x.shape[-1]
    if top_k and 0 < top_k < v:
        kth = np.sort(x, axis=-1)[:, -top_k][:, None]
        x = np.where(x < kth, -1e9, x)
    if top_p < 1.0:
        s = np.sort(x, axis=-1)[:, ::-1]
        e = np.exp(s - s.max(-1, keepdims=True))
        probs = e / e.sum(-1, keepdims=True)
        cum = np.cumsum(probs, axis=-1)
        keep = cum - probs < top_p
        masked = np.where(keep, s, np.inf)
        thresh = masked.min(-1, keepdims=True)
        x = np.where(x < thresh, -1e9, x)
    return x


def _np_select_token(logits, do_sample, temperature, top_k, top_p, rng):
    x = np.asarray(logits, "float32")
    if do_sample:
        x = _np_process_logits(x, temperature, top_k, top_p)
        x = x + _gumbel(rng, x.shape)
    return x.argmax(-1).astype("int32")


def export_generation_bundle(model, path, batch_size, prompt_len,
                             max_new_tokens):
    """Export `model` (cache-capable CausalLM) as a generation bundle:
    `path.prefill.pdmodel` + `path.decode.pdmodel` (StableHLO via
    jax.export), `path.pdiparams` (params), `path.genmeta` (shape/config
    json). Shapes are static: (batch_size, prompt_len) prompts,
    prompt_len + max_new_tokens cache slots. Bundles (format 2) take a
    (batch, prompt_len) bool keep-mask input, so left-padded ragged
    prompts generate exactly their unpadded continuations."""
    import json

    import jax

    from paddle_tpu.core.tape import no_grad
    from paddle_tpu.jit.functional import _swapped, state_arrays

    if not _model_supports_cache(model):
        raise ValueError(f"{type(model).__name__} has no caches= support; "
                         "the bundle needs the KV-cache decode path")
    masked = _mask_capable(model)
    cfg = model.config
    b, s = batch_size, prompt_len
    max_len = s + max_new_tokens
    state = state_arrays(model)
    caches = init_kv_cache(model, b, max_len)
    cache_avals = [jax.ShapeDtypeStruct(tuple(c._value.shape),
                                        c._value.dtype)
                   for kv in caches for c in kv]
    n_layers = len(caches)

    def pack(flat):
        return [(Tensor(flat[2 * i]), Tensor(flat[2 * i + 1]))
                for i in range(n_layers)]

    def mask_kw(keep, index=None):
        if not masked:
            if index is None:
                return dict(position_ids=T.unsqueeze(
                    T.arange(0, s, dtype="int32"), 0))
            return dict(position_ids=T.reshape(Tensor(index), [1, 1]))
        kt = Tensor(keep)
        attn, n_real = _graph_mask(kt, max_len)
        if index is None:
            posids = T.clip(
                T.cumsum(T.cast(kt, "int32"), axis=1) - 1, 0, s)
        else:
            posids = T.reshape(n_real + (Tensor(index) - s), [b, 1])
        return dict(attn_mask=attn, position_ids=posids)

    def prefill_pure(state_, ids, keep, *cache_flat):
        with no_grad(), _swapped(model, state_):
            logits, new_caches = model(
                Tensor(ids), caches=pack(cache_flat),
                cache_index=Tensor(jnp.zeros((), jnp.int32)),
                **mask_kw(keep))
        flat = [c._value for kv in new_caches for c in kv]
        return (logits[:, -1]._value, *flat)

    def decode_pure(state_, tok, index, keep, *cache_flat):
        with no_grad(), _swapped(model, state_):
            logits, new_caches = model(
                Tensor(tok), caches=pack(cache_flat),
                cache_index=Tensor(index), **mask_kw(keep, index))
        flat = [c._value for kv in new_caches for c in kv]
        return (logits[:, -1]._value, *flat)

    ids_aval = jax.ShapeDtypeStruct((b, s), jnp.int32)
    keep_aval = jax.ShapeDtypeStruct((b, s), jnp.bool_)
    tok_aval = jax.ShapeDtypeStruct((b, 1), jnp.int32)
    idx_aval = jax.ShapeDtypeStruct((), jnp.int32)
    exp_prefill = jax.export.export(jax.jit(prefill_pure))(
        state, ids_aval, keep_aval, *cache_avals)
    exp_decode = jax.export.export(jax.jit(decode_pure))(
        state, tok_aval, idx_aval, keep_aval, *cache_avals)

    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path + ".prefill.pdmodel", "wb") as f:
        f.write(exp_prefill.serialize())
    with open(path + ".decode.pdmodel", "wb") as f:
        f.write(exp_decode.serialize())
    from paddle_tpu.framework.io_utils import save as _save
    _save(model.state_dict(), path + ".pdiparams")
    with open(path + ".genmeta", "w") as f:
        json.dump({"format": 2, "mask_input": True,
                   "mask_honored": masked,
                   "batch_size": b, "prompt_len": s,
                   "max_new_tokens": max_new_tokens,
                   "num_layers": n_layers,
                   "cache_shape": list(cache_avals[0].shape),
                   "cache_dtype": str(cache_avals[0].dtype),
                   "vocab_size": cfg.vocab_size}, f)
    return path


class GenerationPredictor:
    """Load + drive an exported generation bundle: the serving twin of
    inference.Predictor for autoregressive decode. stream() yields one
    (batch,) int32 array per token — the surface the HTTP /generate
    endpoint and the C API callback ride."""

    def __init__(self, path):
        import json

        import jax

        with open(path + ".prefill.pdmodel", "rb") as f:
            self._prefill = jax.export.deserialize(f.read())
        with open(path + ".decode.pdmodel", "rb") as f:
            self._decode = jax.export.deserialize(f.read())
        with open(path + ".genmeta") as f:
            self.meta = json.load(f)
        from paddle_tpu.framework.io_utils import load as _load
        sd = _load(path + ".pdiparams")
        self._state = {k: (v._value if isinstance(v, Tensor)
                           else np.asarray(v)) for k, v in sd.items()}

    def stream(self, input_ids, max_new_tokens=None, *,
               attention_mask=None, eos_token_id=None,
               pad_token_id=0, do_sample=False, temperature=1.0, top_k=0,
               top_p=1.0, seed=None):
        m = self.meta
        ids = np.asarray(input_ids, "int32")
        if ids.shape != (m["batch_size"], m["prompt_len"]):
            raise ValueError(
                f"bundle expects prompt shape "
                f"({m['batch_size']}, {m['prompt_len']}), got {ids.shape}"
                " — left-pad/trim client-side (exported programs are "
                "shape-monomorphic); pass attention_mask to mark pads")
        has_mask = m.get("mask_input", False)
        honored = m.get("mask_honored", has_mask)
        keep = _norm_attention_mask(attention_mask, *ids.shape)
        if keep is None:
            keep = np.ones(ids.shape, bool)
        elif not (has_mask and honored):
            raise ValueError("this bundle cannot honor attention_mask "
                             "(exported pre-format-2 or from a model "
                             "without attn_mask support); re-export")
        steps = (m["max_new_tokens"] if max_new_tokens is None
                 else max_new_tokens)
        if steps > m["max_new_tokens"]:
            raise ValueError(
                f"bundle cache holds {m['max_new_tokens']} new tokens, "
                f"asked for {steps}")
        if steps <= 0:
            return                  # a 0-token request streams nothing
        rng = np.random.RandomState(seed)
        b, s = ids.shape
        mask_args = (keep,) if has_mask else ()
        caches = [np.zeros(m["cache_shape"], m["cache_dtype"])
                  for _ in range(2 * m["num_layers"])]
        out = self._prefill.call(self._state, ids, *mask_args, *caches)
        logits, caches = np.asarray(out[0]), list(out[1:])
        tok = _np_select_token(logits, do_sample, temperature, top_k,
                               top_p, rng)
        finished = np.zeros((b,), bool)
        tok, finished = _finish_step(tok, finished, eos_token_id,
                                     pad_token_id)
        yield tok
        for step in range(1, steps):
            if finished.all():
                return
            out = self._decode.call(
                self._state, tok.reshape(b, 1).astype("int32"),
                np.int32(s + step - 1), *mask_args, *caches)
            logits, caches = np.asarray(out[0]), list(out[1:])
            tok = _np_select_token(logits, do_sample, temperature, top_k,
                                   top_p, rng)
            tok, finished = _finish_step(tok, finished, eos_token_id,
                                         pad_token_id)
            yield tok

    def generate(self, input_ids, max_new_tokens=None, **kwargs):
        steps = list(self.stream(input_ids, max_new_tokens, **kwargs))
        prompt = np.asarray(input_ids, "int32")
        if not steps:
            return prompt
        return np.concatenate([prompt, np.stack(steps, 1)], axis=1)
