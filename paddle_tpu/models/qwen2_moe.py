"""Qwen2-MoE-class model family (BASELINE.json config #5:
"Qwen2-MoE / DeepSeekMoE with fleet expert-parallel").

The reference trains this through PaddleNLP with
incubate.distributed.models.moe.MoELayer + fleet's expert-parallel groups;
here the decoder reuses the Llama attention stack with the expert-parallel
MoEMLP (paddle_tpu.nn.layer.moe), plus the Qwen2-MoE shared expert with a
sigmoid gate. Expert weights shard over the mesh's 'ep' axis via
paddle_tpu.parallel.plan.
"""
from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp

import paddle_tpu
from paddle_tpu import nn
from paddle_tpu import tensor as T
from paddle_tpu.nn import functional as F
from paddle_tpu.nn.layer.norm import RMSNorm
from paddle_tpu.nn.layer.moe import MoEMLP
from paddle_tpu.models.llama import (LlamaAttention, LlamaMLP, LlamaConfig)


@dataclass
class Qwen2MoeConfig(LlamaConfig):
    num_experts: int = 60
    num_experts_per_tok: int = 2
    moe_intermediate_size: int = 1408
    shared_expert_intermediate_size: int = 5632
    capacity_factor: float = 1.25
    router_aux_loss_coef: float = 0.001
    # dropless dMoE (ragged grouped matmul) instead of GShard capacity
    # dispatch — zero dropped tokens (nn/layer/moe.py _moe_mlp_dropless)
    moe_dropless: bool = False


def tiny_qwen2_moe_config(**overrides) -> Qwen2MoeConfig:
    base = dict(vocab_size=256, hidden_size=64, intermediate_size=128,
                num_hidden_layers=2, num_attention_heads=4,
                num_key_value_heads=2, max_position_embeddings=256,
                rope_theta=10000.0, seq_length=32, num_experts=4,
                num_experts_per_tok=2, moe_intermediate_size=32,
                shared_expert_intermediate_size=64)
    base.update(overrides)
    return Qwen2MoeConfig(**base)


class Qwen2MoeSparseBlock(nn.Layer):
    """MoE experts + always-on shared expert with sigmoid gate
    (Qwen2-MoE architecture)."""

    def __init__(self, config: Qwen2MoeConfig):
        super().__init__()
        self.moe = MoEMLP(
            config.hidden_size, config.moe_intermediate_size,
            config.num_experts, top_k=config.num_experts_per_tok,
            capacity_factor=config.capacity_factor,
            initializer_range=config.initializer_range,
            dropless=config.moe_dropless)
        shared_cfg = LlamaConfig(
            hidden_size=config.hidden_size,
            intermediate_size=config.shared_expert_intermediate_size,
            initializer_range=config.initializer_range)
        self.shared_expert = LlamaMLP(shared_cfg)
        init = nn.initializer.Normal(0.0, config.initializer_range)
        self.shared_expert_gate = nn.Linear(
            config.hidden_size, 1,
            weight_attr=paddle_tpu.nn.ParamAttr(initializer=init),
            bias_attr=False)

    def forward(self, x):
        moe_out = self.moe(x)
        shared = self.shared_expert(x)
        g = F.sigmoid(self.shared_expert_gate(x))
        return moe_out + g * shared

    @property
    def aux_loss(self):
        return self.moe.aux_loss


class Qwen2MoeDecoderLayer(nn.Layer):
    def __init__(self, config: Qwen2MoeConfig):
        super().__init__()
        self.self_attn = LlamaAttention(config)
        self.mlp = Qwen2MoeSparseBlock(config)
        self.input_layernorm = RMSNorm(config.hidden_size,
                                       epsilon=config.rms_norm_eps)
        self.post_attention_layernorm = RMSNorm(config.hidden_size,
                                                epsilon=config.rms_norm_eps)

    def forward(self, h, position_ids=None, attn_mask=None, cache=None,
                cache_index=None):
        res = h
        h = self.input_layernorm(h)
        new_cache = None
        if cache is not None:
            h, new_cache = self.self_attn(
                h, position_ids=position_ids, attn_mask=attn_mask,
                cache=cache, cache_index=cache_index)
        else:
            h = self.self_attn(h, position_ids=position_ids,
                               attn_mask=attn_mask)
        h = res + h
        res = h
        h2 = self.post_attention_layernorm(h)
        h2 = self.mlp(h2)
        out = res + h2
        return out if cache is None else (out, new_cache)


class Qwen2MoeModel(nn.Layer):
    def __init__(self, config: Qwen2MoeConfig):
        super().__init__()
        self.config = config
        init = nn.initializer.Normal(0.0, config.initializer_range)
        self.embed_tokens = nn.Embedding(
            config.vocab_size, config.hidden_size,
            weight_attr=paddle_tpu.nn.ParamAttr(initializer=init))
        self.layers = nn.LayerList(
            [Qwen2MoeDecoderLayer(config)
             for _ in range(config.num_hidden_layers)])
        self.norm = RMSNorm(config.hidden_size, epsilon=config.rms_norm_eps)

    def forward(self, input_ids, position_ids=None, attn_mask=None,
                caches=None, cache_index=None):
        from paddle_tpu.distributed.recompute import recompute
        h = self.embed_tokens(input_ids)
        if caches is not None:
            new_caches = []
            for layer, cache in zip(self.layers, caches):
                h, c = layer(h, position_ids=position_ids,
                             attn_mask=attn_mask, cache=cache,
                             cache_index=cache_index)
                new_caches.append(c)
            return self.norm(h), new_caches
        for layer in self.layers:
            if self.config.recompute and self.training:
                h = recompute(layer, h, position_ids=position_ids,
                              attn_mask=attn_mask)
            else:
                h = layer(h, position_ids=position_ids,
                          attn_mask=attn_mask)
        return self.norm(h)

    def aux_losses(self):
        return [l.mlp.aux_loss for l in self.layers
                if l.mlp.aux_loss is not None]


class Qwen2MoeForCausalLM(nn.Layer):
    def __init__(self, config: Qwen2MoeConfig):
        super().__init__()
        self.config = config
        self.model = Qwen2MoeModel(config)
        init = nn.initializer.Normal(0.0, config.initializer_range)
        self.lm_head = nn.Linear(
            config.hidden_size, config.vocab_size,
            weight_attr=paddle_tpu.nn.ParamAttr(initializer=init),
            bias_attr=False)

    def forward(self, input_ids, labels=None, position_ids=None,
                attn_mask=None, caches=None, cache_index=None):
        if caches is not None:
            if labels is not None:
                raise ValueError("KV-cache decode is inference-only; "
                                 "drop labels or caches")
            h, caches = self.model(input_ids, position_ids=position_ids,
                                   attn_mask=attn_mask, caches=caches,
                                   cache_index=cache_index)
            return self.lm_head(h), caches
        h = self.model(input_ids, position_ids=position_ids,
                       attn_mask=attn_mask)
        logits = self.lm_head(h)
        if labels is None:
            return logits
        from paddle_tpu.models.llama import next_token_loss
        loss = next_token_loss(logits, labels, self.config.vocab_size)
        auxes = self.model.aux_losses()
        if auxes:
            total_aux = auxes[0]
            for a in auxes[1:]:
                total_aux = total_aux + a
            loss = loss + self.config.router_aux_loss_coef * total_aux
        return loss, logits

    def generate(self, input_ids, max_new_tokens=32, **kwargs):
        """KV-cache autoregressive generation (models/generation.py)."""
        from paddle_tpu.models.generation import generate
        return generate(self, input_ids, max_new_tokens, **kwargs)
