"""DiT — Diffusion Transformer (PaddleMIX ppdiffusers DiTTransformer2DModel
equivalent; SURVEY.md §7 M5 "DiT/SD3 conv+attention config").

Patchify conv -> N DiT blocks with adaLN-Zero conditioning on (timestep,
class label) -> unpatchify. Attention + large matmuls dominate, so the
whole model rides the MXU; timestep embedding is the standard sinusoidal
MLP.
"""
from __future__ import annotations

import math
from dataclasses import dataclass

import numpy as np

import paddle_tpu
from paddle_tpu import nn
from paddle_tpu import tensor as T
from paddle_tpu.core.tensor import Tensor


@dataclass
class DiTConfig:
    input_size: int = 32          # latent spatial size
    patch_size: int = 2
    in_channels: int = 4
    hidden_size: int = 1152
    num_layers: int = 28
    num_attention_heads: int = 16
    mlp_ratio: float = 4.0
    num_classes: int = 1000
    learn_sigma: bool = True

    @property
    def out_channels(self):
        return self.in_channels * (2 if self.learn_sigma else 1)

    @property
    def num_patches(self):
        return (self.input_size // self.patch_size) ** 2


def dit_xl_2_config(**overrides) -> DiTConfig:
    return DiTConfig(**overrides)


def tiny_dit_config(**overrides) -> DiTConfig:
    kw = dict(input_size=8, patch_size=2, in_channels=4, hidden_size=64,
              num_layers=2, num_attention_heads=4, num_classes=10)
    kw.update(overrides)
    return DiTConfig(**kw)


def timestep_embedding(t, dim, max_period=10000):
    """Sinusoidal timestep features (DiT paper eq.; ppdiffusers
    TimestepEmbedding)."""
    half = dim // 2
    freqs = T.exp(T.arange(0, half, dtype="float32")
                  * (-math.log(max_period) / half))
    args = T.unsqueeze(T.cast(t, "float32"), -1) * T.unsqueeze(freqs, 0)
    return T.concat([T.cos(args), T.sin(args)], axis=-1)


class TimestepEmbedder(nn.Layer):
    def __init__(self, hidden_size, freq_dim=256):
        super().__init__()
        self.freq_dim = freq_dim
        self.mlp = nn.Sequential(
            nn.Linear(freq_dim, hidden_size), nn.Silu(),
            nn.Linear(hidden_size, hidden_size))

    def forward(self, t):
        return self.mlp(timestep_embedding(t, self.freq_dim))


class LabelEmbedder(nn.Layer):
    def __init__(self, num_classes, hidden_size):
        super().__init__()
        # +1 slot: the null (unconditional) class for CFG
        self.embedding_table = nn.Embedding(num_classes + 1, hidden_size)
        self.num_classes = num_classes

    def forward(self, labels):
        return self.embedding_table(labels)


def modulate(x, shift, scale):
    return x * (1 + T.unsqueeze(scale, 1)) + T.unsqueeze(shift, 1)


class DiTBlock(nn.Layer):
    """Transformer block with adaLN-Zero conditioning (DiT paper §3)."""

    def __init__(self, cfg: DiTConfig):
        super().__init__()
        d = cfg.hidden_size
        self.norm1 = nn.LayerNorm(d, epsilon=1e-6, weight_attr=False,
                                  bias_attr=False)
        self.attn = nn.MultiHeadAttention(d, cfg.num_attention_heads, 0.0)
        self.norm2 = nn.LayerNorm(d, epsilon=1e-6, weight_attr=False,
                                  bias_attr=False)
        f = int(d * cfg.mlp_ratio)
        self.mlp = nn.Sequential(nn.Linear(d, f), nn.GELU(approximate=True),
                                 nn.Linear(f, d))
        # adaLN-zero: 6 modulation params, zero-init so blocks start as
        # identity (DiT paper: stabilizes large-depth training)
        zero = paddle_tpu.nn.ParamAttr(
            initializer=nn.initializer.Constant(0.0))
        self.adaLN_modulation = nn.Sequential(
            nn.Silu(), nn.Linear(d, 6 * d, weight_attr=zero,
                                 bias_attr=zero))

    def forward(self, x, c):
        mod = self.adaLN_modulation(c)
        (shift_msa, scale_msa, gate_msa, shift_mlp, scale_mlp,
         gate_mlp) = tuple(T.split(mod, 6, axis=-1))
        h = modulate(self.norm1(x), shift_msa, scale_msa)
        x = x + T.unsqueeze(gate_msa, 1) * self.attn(h, h, h)
        h = modulate(self.norm2(x), shift_mlp, scale_mlp)
        x = x + T.unsqueeze(gate_mlp, 1) * self.mlp(h)
        return x


class FinalLayer(nn.Layer):
    def __init__(self, cfg: DiTConfig):
        super().__init__()
        d = cfg.hidden_size
        self.norm_final = nn.LayerNorm(d, epsilon=1e-6, weight_attr=False,
                                       bias_attr=False)
        zero = paddle_tpu.nn.ParamAttr(
            initializer=nn.initializer.Constant(0.0))
        self.adaLN_modulation = nn.Sequential(
            nn.Silu(), nn.Linear(d, 2 * d, weight_attr=zero,
                                 bias_attr=zero))
        self.linear = nn.Linear(
            d, cfg.patch_size * cfg.patch_size * cfg.out_channels,
            weight_attr=zero, bias_attr=zero)

    def forward(self, x, c):
        shift, scale = tuple(T.split(self.adaLN_modulation(c), 2, axis=-1))
        return self.linear(modulate(self.norm_final(x), shift, scale))


class DiT(nn.Layer):
    """Latent-space diffusion transformer: forward(x, t, y) -> noise
    prediction with the same spatial shape (+sigma channels)."""

    def __init__(self, cfg: DiTConfig):
        super().__init__()
        self.config = cfg
        p, d = cfg.patch_size, cfg.hidden_size
        self.x_embedder = nn.Conv2D(cfg.in_channels, d, p, stride=p)
        self.t_embedder = TimestepEmbedder(d)
        self.y_embedder = LabelEmbedder(cfg.num_classes, d)
        # fixed sin-cos 2D position table (DiT uses non-learned)
        grid = cfg.input_size // p
        self.register_buffer(
            "pos_embed",
            Tensor(_sincos_2d(d, grid)[None].astype(np.float32)),
            persistable=False)
        self.blocks = nn.LayerList([DiTBlock(cfg)
                                    for _ in range(cfg.num_layers)])
        self.final_layer = FinalLayer(cfg)

    def unpatchify(self, x):
        cfg = self.config
        p, c = cfg.patch_size, cfg.out_channels
        g = cfg.input_size // p
        b = x.shape[0]
        x = T.reshape(x, [b, g, g, p, p, c])
        x = T.transpose(x, [0, 5, 1, 3, 2, 4])  # b c gh p gw p
        return T.reshape(x, [b, c, g * p, g * p])

    def forward(self, x, t, y):
        # x: (b, c, h, w) latents; t: (b,) timesteps; y: (b,) labels
        x = self.x_embedder(x)                      # (b, d, g, g)
        b, d = x.shape[0], x.shape[1]
        x = T.reshape(x, [b, d, -1])
        x = T.transpose(x, [0, 2, 1]) + self.pos_embed
        c = self.t_embedder(t) + self.y_embedder(y)
        for block in self.blocks:
            x = block(x, c)
        x = self.final_layer(x, c)
        return self.unpatchify(x)


def _sincos_2d(dim, grid_size):
    """2D sin-cos position embedding (DiT repo get_2d_sincos_pos_embed)."""
    def _1d(d, pos):
        omega = np.arange(d // 2, dtype=np.float64) / (d / 2.0)
        omega = 1.0 / 10000 ** omega
        out = np.einsum("m,d->md", pos.reshape(-1), omega)
        return np.concatenate([np.sin(out), np.cos(out)], axis=1)

    grid_h = np.arange(grid_size, dtype=np.float64)
    grid_w = np.arange(grid_size, dtype=np.float64)
    grid = np.meshgrid(grid_w, grid_h)  # w goes first
    grid = np.stack(grid, axis=0).reshape([2, 1, grid_size, grid_size])
    emb_h = _1d(dim // 2, grid[0])
    emb_w = _1d(dim // 2, grid[1])
    return np.concatenate([emb_h, emb_w], axis=1)
