"""BERT family (PaddleNLP transformers/bert equivalent; M2 milestone
BERT-SST2 finetune per SURVEY.md §7). Built from paddle_tpu.nn blocks —
post-LN encoder, learned positions, GELU FFN, pooler.
"""
from __future__ import annotations

from dataclasses import dataclass

import numpy as np

import paddle_tpu
from paddle_tpu import nn
from paddle_tpu import tensor as T
from paddle_tpu.core.tensor import Tensor


@dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    layer_norm_eps: float = 1e-12
    initializer_range: float = 0.02
    num_labels: int = 2


def bert_base_config(**overrides) -> BertConfig:
    return BertConfig(**overrides)


def tiny_bert_config(**overrides) -> BertConfig:
    kw = dict(vocab_size=1024, hidden_size=64, num_hidden_layers=2,
              num_attention_heads=4, intermediate_size=128,
              max_position_embeddings=128, hidden_dropout_prob=0.0,
              attention_probs_dropout_prob=0.0)
    kw.update(overrides)
    return BertConfig(**kw)


class BertEmbeddings(nn.Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.word_embeddings = nn.Embedding(cfg.vocab_size, cfg.hidden_size)
        self.position_embeddings = nn.Embedding(cfg.max_position_embeddings,
                                                cfg.hidden_size)
        self.token_type_embeddings = nn.Embedding(cfg.type_vocab_size,
                                                  cfg.hidden_size)
        self.layer_norm = nn.LayerNorm(cfg.hidden_size,
                                       epsilon=cfg.layer_norm_eps)
        self.dropout = nn.Dropout(cfg.hidden_dropout_prob)

    def forward(self, input_ids, token_type_ids=None, position_ids=None):
        s = input_ids.shape[1]
        if position_ids is None:
            position_ids = T.arange(0, s, dtype="int32")
            position_ids = T.unsqueeze(position_ids, 0)
        if token_type_ids is None:
            token_type_ids = T.zeros_like(input_ids)
        emb = (self.word_embeddings(input_ids)
               + self.position_embeddings(position_ids)
               + self.token_type_embeddings(token_type_ids))
        return self.dropout(self.layer_norm(emb))


class BertModel(nn.Layer):
    """Encoder stack (PaddleNLP BertModel). attention_mask: (b, s) with 1
    for real tokens, 0 for padding."""

    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.config = cfg
        self.embeddings = BertEmbeddings(cfg)
        layer = nn.TransformerEncoderLayer(
            cfg.hidden_size, cfg.num_attention_heads, cfg.intermediate_size,
            dropout=cfg.hidden_dropout_prob, activation="gelu",
            attn_dropout=cfg.attention_probs_dropout_prob,
            act_dropout=0.0, normalize_before=False)
        self.encoder = nn.TransformerEncoder(layer, cfg.num_hidden_layers)
        self.pooler = nn.Linear(cfg.hidden_size, cfg.hidden_size)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None):
        x = self.embeddings(input_ids, token_type_ids, position_ids)
        mask = None
        if attention_mask is not None:
            # (b, s) keep-mask -> additive (b, 1, 1, s)
            m = T.cast(attention_mask, "float32")
            mask = T.unsqueeze(T.unsqueeze((m - 1.0) * 1e9, 1), 1)
        seq = self.encoder(x, src_mask=mask)
        pooled = T.tanh(self.pooler(seq[:, 0]))
        return seq, pooled


class BertForSequenceClassification(nn.Layer):
    """(PaddleNLP BertForSequenceClassification — the BERT-SST2 finetune
    head, SURVEY.md §7 M2)."""

    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.bert = BertModel(cfg)
        self.dropout = nn.Dropout(cfg.hidden_dropout_prob)
        self.classifier = nn.Linear(cfg.hidden_size, cfg.num_labels)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None,
                labels=None):
        _, pooled = self.bert(input_ids, token_type_ids,
                              attention_mask=attention_mask)
        logits = self.classifier(self.dropout(pooled))
        if labels is not None:
            loss = nn.functional.cross_entropy(logits, labels)
            return loss, logits
        return logits


class BertForMaskedLM(nn.Layer):
    """MLM head with the decoder weight TIED to the word embedding
    (PaddleNLP BertLMPredictionHead ties the same way)."""

    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.bert = BertModel(cfg)
        self.transform = nn.Linear(cfg.hidden_size, cfg.hidden_size)
        self.layer_norm = nn.LayerNorm(cfg.hidden_size,
                                       epsilon=cfg.layer_norm_eps)
        self.decoder_bias = self.create_parameter(
            [cfg.vocab_size], is_bias=True)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None,
                labels=None):
        seq, _ = self.bert(input_ids, token_type_ids,
                           attention_mask=attention_mask)
        h = self.layer_norm(nn.functional.gelu(self.transform(seq)))
        emb_w = self.bert.embeddings.word_embeddings.weight  # (vocab, d)
        logits = T.matmul(h, emb_w, transpose_y=True) + self.decoder_bias
        if labels is not None:
            loss = nn.functional.cross_entropy(
                T.reshape(logits, [-1, logits.shape[-1]]),
                T.reshape(labels, [-1]), ignore_index=-100)
            return loss, logits
        return logits
