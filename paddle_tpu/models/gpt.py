"""GPT-2/3 family (PaddleNLP transformers/gpt equivalent; PaddleFleetX's
classic pretrain config). Pre-LN decoder-only transformer with learned
positions and tied input/output embedding.
"""
from __future__ import annotations

from dataclasses import dataclass

import paddle_tpu
from paddle_tpu import nn
from paddle_tpu import tensor as T


@dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 1024
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    layer_norm_eps: float = 1e-5
    initializer_range: float = 0.02


def gpt2_small_config(**overrides) -> GPTConfig:
    return GPTConfig(**overrides)


def tiny_gpt_config(**overrides) -> GPTConfig:
    kw = dict(vocab_size=512, hidden_size=64, num_hidden_layers=2,
              num_attention_heads=4, intermediate_size=128,
              max_position_embeddings=128, hidden_dropout_prob=0.0,
              attention_probs_dropout_prob=0.0)
    kw.update(overrides)
    return GPTConfig(**kw)


class GPTDecoderLayer(nn.Layer):
    """Pre-LN causal block (PaddleNLP GPTDecoderLayer)."""

    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.norm1 = nn.LayerNorm(cfg.hidden_size,
                                  epsilon=cfg.layer_norm_eps)
        self.self_attn = nn.MultiHeadAttention(
            cfg.hidden_size, cfg.num_attention_heads,
            cfg.attention_probs_dropout_prob)
        self.norm2 = nn.LayerNorm(cfg.hidden_size,
                                  epsilon=cfg.layer_norm_eps)
        self.linear1 = nn.Linear(cfg.hidden_size, cfg.intermediate_size)
        self.linear2 = nn.Linear(cfg.intermediate_size, cfg.hidden_size)
        self.dropout = nn.Dropout(cfg.hidden_dropout_prob)

    def forward(self, x, attn_mask=None):
        h = self.norm1(x)
        x = x + self.dropout(self.self_attn(h, h, h, attn_mask))
        h = self.norm2(x)
        x = x + self.dropout(
            self.linear2(nn.functional.gelu(self.linear1(h))))
        return x


class GPTModel(nn.Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.config = cfg
        self.word_embeddings = nn.Embedding(cfg.vocab_size, cfg.hidden_size)
        self.position_embeddings = nn.Embedding(
            cfg.max_position_embeddings, cfg.hidden_size)
        self.dropout = nn.Dropout(cfg.hidden_dropout_prob)
        self.layers = nn.LayerList(
            [GPTDecoderLayer(cfg) for _ in range(cfg.num_hidden_layers)])
        self.final_norm = nn.LayerNorm(cfg.hidden_size,
                                       epsilon=cfg.layer_norm_eps)

    def forward(self, input_ids, position_ids=None, attn_mask=None):
        b, s = input_ids.shape[0], input_ids.shape[1]
        if position_ids is None:
            position_ids = T.unsqueeze(T.arange(0, s, dtype="int32"), 0)
        x = self.dropout(self.word_embeddings(input_ids)
                         + self.position_embeddings(position_ids))
        # causal additive mask (b-agnostic, (1, 1, s, s)) — ALWAYS applied;
        # a user mask (e.g. padding) is combined with it, never replaces it
        causal = T.triu(T.full([s, s], -1e9, dtype="float32"), 1)
        causal = T.unsqueeze(T.unsqueeze(causal, 0), 0)
        if attn_mask is None:
            attn_mask = causal
        else:
            if "bool" in str(attn_mask.dtype):
                # keep-mask -> additive before combining with the causal mask
                attn_mask = (T.cast(attn_mask, "float32") - 1.0) * 1e9
            attn_mask = causal + attn_mask
        for layer in self.layers:
            x = layer(x, attn_mask)
        return self.final_norm(x)


class GPTForCausalLM(nn.Layer):
    """LM head tied to the input embedding (PaddleNLP
    GPTForCausalLM/GPTLMHeadModel)."""

    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.gpt = GPTModel(cfg)

    def logits(self, hidden):
        w = self.gpt.word_embeddings.weight  # (vocab, d) — tied
        return T.matmul(hidden, w, transpose_y=True)

    def forward(self, input_ids, labels=None, position_ids=None,
                attn_mask=None):
        hidden = self.gpt(input_ids, position_ids, attn_mask)
        logits = self.logits(hidden)
        if labels is None:
            return logits
        from paddle_tpu.models.llama import next_token_loss
        loss = next_token_loss(logits, labels, logits.shape[-1])
        return loss, logits
