"""Model zoo: the flagship model families the reference's ecosystem trains
(PaddleNLP llm/ recipes — Llama-3, Qwen2/Qwen2-MoE; PaddleMIX — DiT), built
natively on paddle_tpu layers.

The reference keeps models out-of-tree (PaddleNLP/PaddleMIX); we ship them
in-tree because BASELINE.json's north-star configs are model-level
(Llama-3-8B pretrain, Qwen2-MoE, DiT) and the parallel plans in
paddle_tpu.parallel are keyed to these architectures.
"""
from paddle_tpu.models.llama import (  # noqa: F401
    LlamaConfig, LlamaForCausalLM, LlamaModel, RMSNorm,
    llama3_8b_config, tiny_llama_config,
)
