"""Model zoo: the flagship model families the reference's ecosystem trains
(PaddleNLP llm/ recipes — Llama-3, Qwen2/Qwen2-MoE; PaddleMIX — DiT), built
natively on paddle_tpu layers.

The reference keeps models out-of-tree (PaddleNLP/PaddleMIX); we ship them
in-tree because BASELINE.json's north-star configs are model-level
(Llama-3-8B pretrain, Qwen2-MoE, DiT) and the parallel plans in
paddle_tpu.parallel are keyed to these architectures.
"""
from paddle_tpu.models.llama import (  # noqa: F401
    LlamaConfig, LlamaForCausalLM, LlamaModel, RMSNorm,
    llama3_8b_config, tiny_llama_config,
)
from paddle_tpu.models.qwen2_moe import (  # noqa: F401
    Qwen2MoeConfig, Qwen2MoeForCausalLM, tiny_qwen2_moe_config,
)
from paddle_tpu.models.bert import (  # noqa: F401
    BertConfig, BertModel, BertForSequenceClassification, BertForMaskedLM,
    bert_base_config, tiny_bert_config,
)
from paddle_tpu.models.gpt import (  # noqa: F401
    GPTConfig, GPTModel, GPTForCausalLM, gpt2_small_config, tiny_gpt_config,
)
from paddle_tpu.models.dit import (  # noqa: F401
    DiTConfig, DiT, dit_xl_2_config, tiny_dit_config,
)
from paddle_tpu.models.generation import (  # noqa: F401
    generate, generate_speculative, generate_stream, init_kv_cache,
    process_logits,
)
