"""Search / sort / index ops (reference: python/paddle/tensor/search.py)."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu.core.dispatch import defop
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.core import dtype as dtypes


@defop("argmax", differentiable=False)
def _argmax(x, axis=None, keepdim=False, dtype="int64"):
    out = jnp.argmax(x, axis=axis, keepdims=keepdim if axis is not None else False)
    return out.astype(dtypes.convert_dtype(dtype))


def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    return _argmax(x, axis=axis, keepdim=keepdim, dtype=dtype)


@defop("argmin", differentiable=False)
def _argmin(x, axis=None, keepdim=False, dtype="int64"):
    out = jnp.argmin(x, axis=axis, keepdims=keepdim if axis is not None else False)
    return out.astype(dtypes.convert_dtype(dtype))


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    return _argmin(x, axis=axis, keepdim=keepdim, dtype=dtype)


@defop("argsort", differentiable=False)
def argsort(x, axis=-1, descending=False, stable=True, name=None):
    out = jnp.argsort(x, axis=axis, stable=stable,
                      descending=descending)
    return out


@defop("sort_op")
def _sort(x, axis=-1, descending=False, stable=True):
    out = jnp.sort(x, axis=axis, stable=stable, descending=descending)
    return out


def sort(x, axis=-1, descending=False, stable=True, name=None):
    return _sort(x, axis=axis, descending=descending, stable=stable)


@defop("topk")
def _topk(x, k, axis=-1, largest=True, sorted=True):
    if largest:
        vals, idx = jax.lax.top_k(jnp.moveaxis(x, axis, -1), k)
    else:
        vals, idx = jax.lax.top_k(-jnp.moveaxis(x, axis, -1), k)
        vals = -vals
    return jnp.moveaxis(vals, -1, axis), jnp.moveaxis(idx, -1, axis).astype(jnp.int64)


def topk(x, k, axis=None, largest=True, sorted=True, name=None):
    k = int(k.item()) if isinstance(k, Tensor) else int(k)
    if axis is None:        # reference: axis=None means the last axis
        axis = -1
    return _topk(x, k=k, axis=axis, largest=largest, sorted=sorted)


@defop("kthvalue")
def kthvalue(x, k, axis=-1, keepdim=False):
    sorted_v = jnp.sort(x, axis=axis)
    idx_v = jnp.argsort(x, axis=axis)
    vals = jnp.take(sorted_v, k - 1, axis=axis)
    idxs = jnp.take(idx_v, k - 1, axis=axis)
    if keepdim:
        vals = jnp.expand_dims(vals, axis)
        idxs = jnp.expand_dims(idxs, axis)
    return vals, idxs.astype(jnp.int64)


@defop("mode_op", differentiable=False)
def _mode(x, axis=-1, keepdim=False):
    n = x.shape[axis]
    moved = jnp.moveaxis(jnp.sort(x, axis=axis), axis, -1)
    # run lengths over the sorted axis; the position with the longest run
    # ending there holds the mode
    lens = jnp.ones_like(moved, jnp.int32)

    def body(i, l):
        prev = jnp.where(moved[..., i] == moved[..., i - 1], l[..., i - 1], 0)
        return l.at[..., i].set(prev + 1)

    lens = jax.lax.fori_loop(1, n, body, lens)
    best = jnp.argmax(lens, axis=-1)
    vals = jnp.take_along_axis(moved, best[..., None], axis=-1)[..., 0]
    orig_idx = jnp.argsort(jnp.moveaxis(x, axis, -1), axis=-1)
    mode_idx = jnp.take_along_axis(orig_idx, best[..., None], axis=-1)[..., 0]
    if keepdim:
        vals = jnp.expand_dims(vals, axis)
        mode_idx = jnp.expand_dims(mode_idx, axis)
    return vals, mode_idx.astype(jnp.int64)


def mode(x, axis=-1, keepdim=False, name=None):
    return _mode(x, axis=axis, keepdim=keepdim)


def nonzero(x, as_tuple=False):
    xv = np.asarray(x._value)
    nz = np.nonzero(xv)
    if as_tuple:
        return tuple(Tensor(jnp.asarray(a.reshape(-1, 1))) for a in nz)
    return Tensor(jnp.asarray(np.stack(nz, axis=1).astype(np.int64)))


@defop("searchsorted", differentiable=False)
def searchsorted(sorted_sequence, values, out_int32=False, right=False,
                 name=None):
    out = jnp.searchsorted(sorted_sequence, values,
                           side="right" if right else "left")
    return out.astype(jnp.int32 if out_int32 else jnp.int64)


@defop("bucketize", differentiable=False)
def bucketize(x, sorted_sequence, out_int32=False, right=False,
              name=None):
    out = jnp.searchsorted(sorted_sequence, x,
                           side="right" if right else "left")
    return out.astype(jnp.int32 if out_int32 else jnp.int64)


def masked_select(x, mask, name=None):
    from paddle_tpu.tensor.manipulation import masked_select as _ms
    return _ms(x, mask)


def index_sample(x, index):
    from paddle_tpu.tensor.manipulation import index_sample as _is
    return _is(x, index)
