"""Linear algebra ops (reference: python/paddle/tensor/linalg.py).

matmul rides the MXU — it is the single most important op for TPU perf;
everything here lowers to XLA dot_general / LAPACK-on-host fallbacks.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu.core.dispatch import defop
from paddle_tpu.core.tensor import Tensor


@defop("matmul", amp_policy="white",
       spmd_note="contracting dims reduce over mesh axes; see MatmulInferSpmd "
                 "(reference: phi/infermeta/spmd_rules/matmul.cc)")
def _matmul(x, y, transpose_x=False, transpose_y=False):
    if transpose_x:
        x = jnp.swapaxes(x, -1, -2) if x.ndim >= 2 else x
    if transpose_y:
        y = jnp.swapaxes(y, -1, -2) if y.ndim >= 2 else y
    return jnp.matmul(x, y)


def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    return _matmul(x, y, transpose_x=transpose_x, transpose_y=transpose_y)


@defop("mm", amp_policy="white")
def mm(input, mat2, name=None):
    return jnp.matmul(input, mat2)


@defop("bmm", amp_policy="white")
def bmm(x, y, name=None):
    return jnp.matmul(x, y)


@defop("dot")
def dot(x, y, name=None):
    return jnp.sum(x * y, axis=-1)


@defop("mv", amp_policy="white")
def mv(x, vec, name=None):
    return jnp.matmul(x, vec)


@defop("t_op")
def _t(x):
    return x.T if x.ndim >= 2 else x


def t(input, name=None):
    return _t(input)


@defop("cross")
def cross(x, y, axis=9, name=None):
    ax = axis if axis != 9 else next(
        (i for i, s in enumerate(x.shape) if s == 3), -1)
    return jnp.cross(x, y, axis=ax)


@defop("norm", amp_policy="black")
def _norm(x, p=2.0, axis=None, keepdim=False):
    if p == "fro" or (p == 2.0 and axis is None):
        return jnp.sqrt(jnp.sum(jnp.square(jnp.abs(x)), axis=axis, keepdims=keepdim))
    if p == "nuc":
        s = jnp.linalg.svd(x, compute_uv=False)
        return jnp.sum(s, axis=-1, keepdims=keepdim)
    if p == float("inf"):
        return jnp.max(jnp.abs(x), axis=axis, keepdims=keepdim)
    if p == float("-inf"):
        return jnp.min(jnp.abs(x), axis=axis, keepdims=keepdim)
    if p == 0:
        return jnp.sum((x != 0).astype(x.dtype), axis=axis, keepdims=keepdim)
    return jnp.power(jnp.sum(jnp.power(jnp.abs(x), p), axis=axis,
                             keepdims=keepdim), 1.0 / p)


def norm(x, p=None, axis=None, keepdim=False, name=None):
    if isinstance(axis, (list, tuple)):
        axis = tuple(int(a) for a in axis)
        if p is None:
            p = "fro"
    if p is None:
        p = 2.0
    return _norm(x, p=p, axis=axis, keepdim=keepdim)


def p_norm(x, p=2.0, axis=None, keepdim=False):
    return _norm(x, p=p, axis=axis, keepdim=keepdim)


@defop("dist", amp_policy="black")
def dist(x, y, p=2, name=None):
    d = x - y
    if p == float("inf"):
        return jnp.max(jnp.abs(d))
    if p == float("-inf"):
        return jnp.min(jnp.abs(d))
    if p == 0:
        return jnp.sum((d != 0).astype(d.dtype))
    return jnp.power(jnp.sum(jnp.power(jnp.abs(d), p)), 1.0 / p)


@defop("cholesky")
def cholesky(x, upper=False, name=None):
    L = jnp.linalg.cholesky(x)
    return jnp.swapaxes(L, -1, -2) if upper else L


@defop("cholesky_solve")
def cholesky_solve(x, y, upper=False, name=None):
    L = jnp.swapaxes(y, -1, -2) if upper else y
    z = jax.scipy.linalg.solve_triangular(L, x, lower=True)
    return jax.scipy.linalg.solve_triangular(
        jnp.swapaxes(L, -1, -2), z, lower=False)


@defop("triangular_solve")
def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False, name=None):
    return jax.scipy.linalg.solve_triangular(
        x, y, lower=not upper, trans=1 if transpose else 0,
        unit_diagonal=unitriangular)


@defop("inverse")
def inverse(x, name=None):
    return jnp.linalg.inv(x)


inv = inverse


@defop("pinv")
def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return jnp.linalg.pinv(x, rtol=rcond, hermitian=hermitian)


@defop("solve")
def solve(x, y, name=None):
    return jnp.linalg.solve(x, y)


@defop("lstsq", differentiable=False)
def _lstsq(x, y, rcond=None):
    sol, res, rank, sv = jnp.linalg.lstsq(x, y, rcond=rcond)
    return sol, res, rank, sv


def lstsq(x, y, rcond=None, driver=None, name=None):
    return _lstsq(x, y, rcond=rcond)


@defop("det")
def det(x, name=None):
    return jnp.linalg.det(x)


@defop("slogdet")
def slogdet(x, name=None):
    sign, logdet = jnp.linalg.slogdet(x)
    return jnp.stack([sign, logdet]) if sign.ndim == 0 else (sign, logdet)


@defop("matrix_power")
def matrix_power(x, n, name=None):
    return jnp.linalg.matrix_power(x, n)


@defop("matrix_rank", differentiable=False)
def matrix_rank(x, tol=None, hermitian=False, name=None):
    return jnp.linalg.matrix_rank(x, rtol=tol)


@defop("svd", differentiable=False)
def _svd(x, full_matrices=False):
    return jnp.linalg.svd(x, full_matrices=full_matrices)


def svd(x, full_matrices=False, name=None):
    u, s, vh = _svd(x, full_matrices=full_matrices)
    from paddle_tpu.tensor.manipulation import swapaxes
    return u, s, swapaxes(vh, -1, -2)  # paddle returns V not V^H


@defop("qr", differentiable=False)
def _qr(x, mode="reduced"):
    return jnp.linalg.qr(x, mode=mode)


def qr(x, mode="reduced", name=None):
    return _qr(x, mode=mode)


@defop("eig", differentiable=False)
def eig(x, name=None):
    # jax.numpy.linalg.eig is CPU-only; pull to host
    w, v = np.linalg.eig(np.asarray(x))
    return jnp.asarray(w), jnp.asarray(v)


@defop("eigh", differentiable=False)
def _eigh(x, UPLO="L"):
    return jnp.linalg.eigh(x, UPLO=UPLO)


def eigh(x, UPLO="L", name=None):
    return _eigh(x, UPLO=UPLO)


@defop("eigvals", differentiable=False)
def eigvals(x, name=None):
    return jnp.asarray(np.linalg.eigvals(np.asarray(x)))


@defop("eigvalsh", differentiable=False)
def _eigvalsh(x, UPLO="L"):
    return jnp.linalg.eigvalsh(x, UPLO=UPLO)


def eigvalsh(x, UPLO="L", name=None):
    return _eigvalsh(x, UPLO=UPLO)


@defop("lu", differentiable=False)
def _lu(x):
    lu_, piv = jax.scipy.linalg.lu_factor(x)
    return lu_, piv + 1  # paddle pivots are 1-based

def lu(x, pivot=True, get_infos=False, name=None):
    lu_, piv = _lu(x)
    from paddle_tpu.tensor.creation import zeros
    if get_infos:
        return lu_, piv, zeros([1], dtype="int32")
    return lu_, piv


@defop("matrix_exp")
def matrix_exp(x, name=None):
    return jax.scipy.linalg.expm(x)


@defop("cond_op", differentiable=False)
def _cond(x, p=None):
    return jnp.linalg.cond(x, p=p)


def cond(x, p=None, name=None):
    return _cond(x, p=p)


@defop("householder_product")
def householder_product(x, tau, name=None):
    m, n = x.shape[-2], x.shape[-1]
    Q = jnp.eye(m, dtype=x.dtype)
    for i in range(n):
        v = jnp.where(jnp.arange(m) == i, 1.0,
                      jnp.where(jnp.arange(m) > i, x[..., :, i], 0.0))
        H = jnp.eye(m, dtype=x.dtype) - tau[..., i] * jnp.outer(v, v)
        Q = Q @ H
    return Q[..., :, :n]


def tensordot(x, y, axes=2, name=None):
    xv = x._value if isinstance(x, Tensor) else jnp.asarray(x)
    yv = y._value if isinstance(y, Tensor) else jnp.asarray(y)
    if isinstance(axes, Tensor):
        axes = np.asarray(axes._value).tolist()
    if isinstance(axes, (list, tuple)):
        axes = tuple(tuple(a) if isinstance(a, (list, tuple)) else a for a in axes)
    return Tensor(jnp.tensordot(xv, yv, axes=axes))


def multi_dot(x, name=None):
    return Tensor(jnp.linalg.multi_dot([t._value for t in x]))


@defop("corrcoef")
def corrcoef(x, rowvar=True, name=None):
    return jnp.corrcoef(x, rowvar=rowvar)


@defop("cov")
def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    return jnp.cov(x, rowvar=rowvar, ddof=1 if ddof else 0,
                   fweights=fweights, aweights=aweights)


@defop("matrix_norm", amp_policy="black")
def _matrix_norm(x, p="fro", axis=(-2, -1), keepdim=False):
    return jnp.linalg.norm(x, ord=p, axis=tuple(axis), keepdims=keepdim)


def matrix_norm(x, p="fro", axis=[-2, -1], keepdim=False, name=None):
    return _matrix_norm(x, p=p, axis=axis, keepdim=keepdim)


@defop("vector_norm", amp_policy="black")
def _vector_norm(x, p=2.0, axis=None, keepdim=False):
    if axis is None:
        # reduce over ALL axes; keepdim must preserve rank (reference
        # sets axis=list(range(x.ndim)) when axis is None)
        out = jnp.linalg.norm(x.reshape(-1), ord=p, axis=0)
        return out.reshape((1,) * x.ndim) if keepdim else out
    return jnp.linalg.norm(x, ord=p, axis=axis, keepdims=keepdim)


def vector_norm(x, p=2.0, axis=None, keepdim=False, name=None):
    return _vector_norm(x, p=p, axis=axis, keepdim=keepdim)


@defop("lu_unpack_l_u", differentiable=False)
def _lu_unpack_l_u(lu_data):
    m, n = lu_data.shape[-2], lu_data.shape[-1]
    k = min(m, n)
    L = jnp.tril(lu_data[..., :, :k], k=-1) + jnp.eye(m, k, dtype=lu_data.dtype)
    U = jnp.triu(lu_data[..., :k, :])
    return L, U


@defop("lu_unpack_p", differentiable=False)
def _lu_unpack_p(lu_data, lu_pivots):
    # pivots (1-based sequential swaps) -> permutation matrix; batched
    m = lu_data.shape[-2]
    piv = lu_pivots - 1                       # (..., k)
    batch = piv.shape[:-1]
    perm = jnp.broadcast_to(jnp.arange(m), batch + (m,))
    for i in range(piv.shape[-1]):
        j = piv[..., i]                        # (...,)
        pi = perm[..., i]
        pj = jnp.take_along_axis(perm, j[..., None], axis=-1)[..., 0]
        perm = perm.at[..., i].set(pj)
        perm = jnp.where(jnp.arange(m) == j[..., None], pi[..., None], perm)
    P = jnp.take(jnp.eye(m, dtype=lu_data.dtype), perm, axis=0)  # (...,m,m)
    return jnp.swapaxes(P, -1, -2)


def lu_unpack(x, y, unpack_ludata=True, unpack_pivots=True, name=None):
    P = _lu_unpack_p(x, y) if unpack_pivots else None
    if unpack_ludata:
        L, U = _lu_unpack_l_u(x)
    else:
        L = U = None
    return P, L, U


@defop("pca_lowrank", differentiable=False)
def _pca_lowrank(x, omega, center=True, niter=2):
    if center:
        x = x - jnp.mean(x, axis=-2, keepdims=True)
    # randomized range finder with power iterations
    Y = x @ omega
    Q_, _ = jnp.linalg.qr(Y)
    for _ in range(niter):
        Z = jnp.swapaxes(x, -1, -2) @ Q_
        Qz, _ = jnp.linalg.qr(Z)
        Y = x @ Qz
        Q_, _ = jnp.linalg.qr(Y)
    B = jnp.swapaxes(Q_, -1, -2) @ x
    u, s, vh = jnp.linalg.svd(B, full_matrices=False)
    return Q_ @ u, s, jnp.swapaxes(vh, -1, -2)


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    """Low-rank PCA via randomized SVD (reference:
    python/paddle/tensor/linalg.py pca_lowrank). Non-differentiable like
    svd; the projection basis omega is drawn from the global Generator
    outside the op so jit tracing stays pure."""
    from paddle_tpu.core.random import next_key
    shape = tuple(x.shape)
    m, n = shape[-2], shape[-1]
    if q is None:
        q = min(6, m, n)
    dt = x.dtype if not isinstance(x, Tensor) else x._value.dtype
    omega = Tensor(jax.random.normal(next_key(), shape[:-2] + (n, q),
                                     dtype=dt))
    return _pca_lowrank(x, omega, center=center, niter=niter)
