"""Random sampling ops (reference: python/paddle/tensor/random.py).

All ops draw explicit subkeys from the global Generator
(paddle_tpu.core.random) — deterministic and jit-safe, unlike the reference's
stateful Philox offset bookkeeping (paddle/phi/core/generator.h).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu.core import dtype as dtypes
from paddle_tpu.core.random import next_key
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.tensor.creation import _dt, _shape


def rand(shape, dtype=None, name=None):
    return Tensor(jax.random.uniform(next_key(), _shape(shape),
                                     _dt(dtype)))


def randn(shape, dtype=None, name=None):
    return Tensor(jax.random.normal(next_key(), _shape(shape),
                                    _dt(dtype)))


def standard_normal(shape, dtype=None, name=None):
    return randn(shape, dtype)


def normal(mean=0.0, std=1.0, shape=None, name=None):
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m = mean._value if isinstance(mean, Tensor) else mean
        s = std._value if isinstance(std, Tensor) else std
        shp = jnp.broadcast_shapes(
            jnp.shape(m), jnp.shape(s)) if shape is None else _shape(shape)
        return Tensor(jax.random.normal(next_key(), shp) * s + m)
    shp = _shape(shape) if shape is not None else ()
    return Tensor(jax.random.normal(next_key(), shp) * std + mean)


def normal_(x, mean=0.0, std=1.0, name=None):
    x._value = jax.random.normal(next_key(), tuple(x.shape),
                                 x._value.dtype) * std + mean
    x._version += 1
    return x


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None):
    key = jax.random.key(seed) if seed else next_key()
    return Tensor(jax.random.uniform(
        key, _shape(shape), _dt(dtype), minval=min, maxval=max))


def uniform_(x, min=-1.0, max=1.0, seed=0, name=None):
    key = jax.random.key(seed) if seed else next_key()
    x._value = jax.random.uniform(key, tuple(x.shape), x._value.dtype,
                                  minval=min, maxval=max)
    x._version += 1
    return x


def randint(low=0, high=None, shape=[1], dtype=None, name=None):
    if high is None:
        low, high = 0, low
    return Tensor(jax.random.randint(
        next_key(), _shape(shape), low, high,
        dtypes.convert_dtype(dtype) or jnp.int64))


def randint_like(x, low=0, high=None, dtype=None, name=None):
    if high is None:
        low, high = 0, low
    dt = dtypes.convert_dtype(dtype) or x.dtype
    return Tensor(jax.random.randint(next_key(), tuple(x.shape), low, high)
                  .astype(dt))


def randperm(n, dtype="int64", name=None):
    return Tensor(jax.random.permutation(next_key(), n)
                  .astype(dtypes.convert_dtype(dtype)))


def bernoulli(x, name=None):
    return Tensor(jax.random.bernoulli(
        next_key(), x._value.astype(jnp.float32)).astype(x._value.dtype))


def bernoulli_(x, p=0.5, name=None):
    x._value = jax.random.bernoulli(next_key(), p, tuple(x.shape)) \
        .astype(x._value.dtype)
    x._version += 1
    return x


def poisson(x, name=None):
    return Tensor(jax.random.poisson(
        next_key(), x._value).astype(x._value.dtype))


def multinomial(x, num_samples=1, replacement=False, name=None):
    v = x._value
    if v.ndim == 1:
        v = v[None]
        squeeze = True
    else:
        squeeze = False
    p = v / jnp.sum(v, -1, keepdims=True)
    outs = []
    for row in range(p.shape[0]):
        outs.append(jax.random.choice(
            next_key(), p.shape[1], (num_samples,), replace=replacement,
            p=p[row]))
    out = jnp.stack(outs).astype(jnp.int64)
    return Tensor(out[0] if squeeze else out)


def exponential_(x, lam=1.0, name=None):
    x._value = (jax.random.exponential(next_key(), tuple(x.shape),
                                       x._value.dtype) / lam)
    x._version += 1
    return x


def rand_like(x, dtype=None, name=None):
    dt = dtypes.convert_dtype(dtype) or x._value.dtype
    return Tensor(jax.random.uniform(next_key(), tuple(x.shape), dt))


def randn_like(x, dtype=None, name=None):
    dt = dtypes.convert_dtype(dtype) or x._value.dtype
    return Tensor(jax.random.normal(next_key(), tuple(x.shape), dt))


def gaussian(shape, mean=0.0, std=1.0, seed=0, dtype="float32", name=None):
    key = jax.random.key(seed) if seed else next_key()
    return Tensor(jax.random.normal(
        key, _shape(shape), dtypes.convert_dtype(dtype)) * std + mean)


def binomial(count, prob, name=None):
    c = count._value if isinstance(count, Tensor) else count
    p = prob._value if isinstance(prob, Tensor) else prob
    return Tensor(jax.random.binomial(next_key(), c, p).astype(jnp.int64))
