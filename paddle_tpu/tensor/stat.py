"""Statistics ops (reference: python/paddle/tensor/stat.py)."""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from paddle_tpu.core.dispatch import defop
from paddle_tpu.core.tensor import Tensor


def _axis(axis):
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return axis


@defop("std", amp_policy="black")
def _std(x, axis=None, unbiased=True, keepdim=False):
    return jnp.std(x, axis=axis, ddof=1 if unbiased else 0, keepdims=keepdim)


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    return _std(x, axis=_axis(axis), unbiased=unbiased, keepdim=keepdim)


@defop("var", amp_policy="black")
def _var(x, axis=None, unbiased=True, keepdim=False):
    return jnp.var(x, axis=axis, ddof=1 if unbiased else 0, keepdims=keepdim)


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    return _var(x, axis=_axis(axis), unbiased=unbiased, keepdim=keepdim)


@defop("median")
def _median(x, axis=None, keepdim=False, mode="avg"):
    if mode == "avg":
        return jnp.median(x, axis=axis, keepdims=keepdim)
    # 'min' mode: lower of the two middle values
    n = x.size if axis is None else x.shape[axis]
    s = jnp.sort(x.reshape(-1) if axis is None else x, axis=0 if axis is None else axis)
    k = (n - 1) // 2
    out = jnp.take(s, k, axis=0 if axis is None else axis)
    if keepdim and axis is not None:
        out = jnp.expand_dims(out, axis)
    return out


def median(x, axis=None, keepdim=False, mode="avg", name=None):
    return _median(x, axis=axis, keepdim=keepdim, mode=mode)


@defop("nanmedian")
def nanmedian(x, axis=None, keepdim=False):
    return jnp.nanmedian(x, axis=_axis(axis), keepdims=keepdim)


@defop("quantile")
def _quantile(x, q, axis=None, keepdim=False, interpolation="linear"):
    return jnp.quantile(x, jnp.asarray(q), axis=axis, keepdims=keepdim,
                        method=interpolation)


def quantile(x, q, axis=None, keepdim=False, interpolation="linear", name=None):
    return _quantile(x, q, axis=_axis(axis), keepdim=keepdim,
                     interpolation=interpolation)


@defop("nanquantile")
def nanquantile(x, q, axis=None, keepdim=False, interpolation="linear"):
    return jnp.nanquantile(x, jnp.asarray(q), axis=_axis(axis),
                           keepdims=keepdim, method=interpolation)


@defop("histogram", differentiable=False)
def histogram(input, bins=100, min=0, max=0, weight=None,
              density=False, name=None):
    if min == 0 and max == 0:
        lo, hi = jnp.min(input), jnp.max(input)
    else:
        lo, hi = min, max
    hist, _ = jnp.histogram(input.reshape(-1), bins=bins, range=(lo, hi),
                            weights=None if weight is None else weight.reshape(-1),
                            density=density)
    return hist if density or weight is not None else hist.astype(jnp.int64)


def histogramdd(x, bins=10, ranges=None, density=False, weights=None, name=None):
    xv = np.asarray(x._value)
    hist, edges = np.histogramdd(
        xv, bins=bins, range=ranges, density=density,
        weights=None if weights is None else np.asarray(weights._value))
    return Tensor(jnp.asarray(hist)), [Tensor(jnp.asarray(e)) for e in edges]


@defop("bincount", differentiable=False)
def _bincount(x, weights=None, minlength=0):
    length = max(int(minlength), int(np.asarray(x).max(initial=-1)) + 1) \
        if not hasattr(x, "aval") else minlength
    return jnp.bincount(x, weights=weights, minlength=length)


def bincount(x, weights=None, minlength=0, name=None):
    xv = np.asarray(x._value)
    length = max(int(minlength), (int(xv.max()) + 1) if xv.size else 0)
    out = jnp.bincount(x._value, length=length,
                       weights=None if weights is None else weights._value)
    return Tensor(out if weights is not None else out.astype(jnp.int64))
