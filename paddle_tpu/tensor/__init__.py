"""Functional tensor API + Tensor method patching.

Reference: python/paddle/tensor/__init__.py, which monkey-patches the
generated pybind Tensor with python methods (monkey_patch_tensor). We do the
same: every functional op in the submodules is also attached as a Tensor
method, and the arithmetic dunders route to the defop'd functions so that
`x + y` records on the autograd tape exactly like paddle's `add` ad_func.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu.core.tensor import Tensor, Parameter, to_tensor, is_tensor
from paddle_tpu.core.dispatch import defop

from paddle_tpu.tensor.creation import *  # noqa: F401,F403
from paddle_tpu.tensor.math import *  # noqa: F401,F403
from paddle_tpu.tensor.manipulation import *  # noqa: F401,F403
from paddle_tpu.tensor.linalg import *  # noqa: F401,F403
from paddle_tpu.tensor.logic import *  # noqa: F401,F403
from paddle_tpu.tensor.search import *  # noqa: F401,F403
from paddle_tpu.tensor.stat import *  # noqa: F401,F403
from paddle_tpu.tensor.random import *  # noqa: F401,F403
from paddle_tpu.tensor.extras import *  # noqa: F401,F403
from paddle_tpu.tensor.einsum import einsum  # noqa: F401
from paddle_tpu.tensor import attribute  # noqa: F401
from paddle_tpu.tensor.attribute import shape, shape as shape_op  # noqa: F401
from paddle_tpu.tensor.attribute import numel, rank  # noqa: F401

from paddle_tpu.tensor import (creation, extras, math, manipulation,
                               linalg, logic, search, stat)
from paddle_tpu.tensor import random as random_mod


# ---------------------------------------------------------------------------
# Indexing
# ---------------------------------------------------------------------------
@defop("getitem")
def _getitem(x, idx):
    return x[idx]


@defop("setitem_value")
def _set_value_at(x, idx, value):
    v = value
    return x.at[idx].set(v)


def _normalize_index(idx):
    """Convert Tensor indices to arrays; detect bool-mask (dynamic shape)."""
    has_bool = [False]

    def conv(i):
        if isinstance(i, Tensor):
            if i.dtype == np.dtype(bool):
                has_bool[0] = True
            return i
        if isinstance(i, np.ndarray) and i.dtype == bool:
            has_bool[0] = True
        return i

    if isinstance(idx, tuple):
        out = tuple(conv(i) for i in idx)
    else:
        out = conv(idx)
    return out, has_bool[0]


def _tensor_getitem(self, idx):
    idx, has_bool = _normalize_index(idx)
    if has_bool:
        # dynamic output shape: host fallback, non-differentiable
        np_idx = jax.tree.map(
            lambda i: np.asarray(i._value) if isinstance(i, Tensor) else i,
            idx, is_leaf=lambda i: isinstance(i, Tensor))
        return Tensor(jnp.asarray(np.asarray(self._value)[np_idx]))
    return _getitem(self, idx)


def _tensor_setitem(self, idx, value):
    idx, has_bool = _normalize_index(idx)
    if not isinstance(value, Tensor):
        value = Tensor(jnp.asarray(value, dtype=self._value.dtype))
    if value.dtype != self.dtype:
        value = value.astype(self.dtype)
    if has_bool:
        # numpy semantics (compacted value arrays, masks inside tuples)
        # need dynamic shapes -> host fallback; non-differentiable
        np_x = np.asarray(self._value).copy()
        np_idx = jax.tree.map(
            lambda i: np.asarray(i._value) if isinstance(i, Tensor) else i,
            idx, is_leaf=lambda i: isinstance(i, Tensor))
        np_x[np_idx] = np.asarray(value._value)
        new = Tensor(jnp.asarray(np_x))
    else:
        new = _set_value_at(self, idx, value)
    self._inplace_assign(new)


# ---------------------------------------------------------------------------
# Operator dunders
# ---------------------------------------------------------------------------
def _patch():
    T = Tensor
    T.__getitem__ = _tensor_getitem
    T.__setitem__ = _tensor_setitem

    T.__add__ = lambda s, o: math.add(s, o)
    T.__radd__ = lambda s, o: math.add(s, o)
    T.__sub__ = lambda s, o: math.subtract(s, o)
    T.__rsub__ = lambda s, o: math.subtract(_as_t(o, s), s)
    T.__mul__ = lambda s, o: math.multiply(s, o)
    T.__rmul__ = lambda s, o: math.multiply(s, o)
    T.__truediv__ = lambda s, o: math.divide(s, o)
    T.__rtruediv__ = lambda s, o: math.divide(_as_t(o, s), s)
    T.__floordiv__ = lambda s, o: math.floor_divide(s, o)
    T.__rfloordiv__ = lambda s, o: math.floor_divide(_as_t(o, s), s)
    T.__mod__ = lambda s, o: math.mod(s, o)
    T.__rmod__ = lambda s, o: math.mod(_as_t(o, s), s)
    T.__pow__ = lambda s, o: math.pow(s, o)
    T.__rpow__ = lambda s, o: math.pow(_as_t(o, s), s)
    T.__matmul__ = lambda s, o: linalg.matmul(s, o)
    T.__rmatmul__ = lambda s, o: linalg.matmul(_as_t(o, s), s)
    T.__neg__ = lambda s: math.neg(s)
    T.__abs__ = lambda s: math.abs(s)
    T.__pos__ = lambda s: s

    T.__eq__ = lambda s, o: logic.equal(s, o)
    T.__ne__ = lambda s, o: logic.not_equal(s, o)
    T.__lt__ = lambda s, o: logic.less_than(s, o)
    T.__le__ = lambda s, o: logic.less_equal(s, o)
    T.__gt__ = lambda s, o: logic.greater_than(s, o)
    T.__ge__ = lambda s, o: logic.greater_equal(s, o)

    T.__and__ = lambda s, o: logic.logical_and(s, o) \
        if s.dtype == np.dtype(bool) else logic.bitwise_and(s, o)
    T.__or__ = lambda s, o: logic.logical_or(s, o) \
        if s.dtype == np.dtype(bool) else logic.bitwise_or(s, o)
    T.__xor__ = lambda s, o: logic.logical_xor(s, o) \
        if s.dtype == np.dtype(bool) else logic.bitwise_xor(s, o)
    T.__invert__ = lambda s: logic.logical_not(s) \
        if s.dtype == np.dtype(bool) else logic.bitwise_not(s)
    T.__lshift__ = lambda s, o: logic.bitwise_left_shift(s, o)
    T.__rshift__ = lambda s, o: logic.bitwise_right_shift(s, o)

    # in-place arithmetic (paddle: add_, etc.)
    def _inplace(fn):
        def m(self, *a, **k):
            return self._inplace_assign(fn(self, *a, **k))
        return m

    methods = {
        # math
        "add": math.add, "subtract": math.subtract, "multiply": math.multiply,
        "divide": math.divide, "floor_divide": math.floor_divide,
        "mod": math.mod, "remainder": math.mod, "pow": math.pow,
        "maximum": math.maximum, "minimum": math.minimum,
        "fmax": math.fmax, "fmin": math.fmin,
        "abs": math.abs, "neg": math.neg, "sign": math.sign,
        "exp": math.exp, "expm1": math.expm1, "log": math.log,
        "log2": math.log2, "log10": math.log10, "log1p": math.log1p,
        "sqrt": math.sqrt, "rsqrt": math.rsqrt, "square": math.square,
        "reciprocal": math.reciprocal, "sin": math.sin, "cos": math.cos,
        "tan": math.tan, "asin": math.asin, "acos": math.acos,
        "atan": math.atan, "sinh": math.sinh, "cosh": math.cosh,
        "tanh": math.tanh, "asinh": math.asinh, "acosh": math.acosh,
        "atanh": math.atanh, "erf": math.erf, "erfinv": math.erfinv,
        "sigmoid": math.sigmoid, "floor": math.floor, "ceil": math.ceil,
        "round": math.round, "trunc": math.trunc, "frac": math.frac,
        "conj": math.conj, "real": math.real, "imag": math.imag,
        "angle": math.angle, "lgamma": math.lgamma, "digamma": math.digamma,
        "isfinite": math.isfinite, "isinf": math.isinf, "isnan": math.isnan,
        "sum": math.sum, "mean": math.mean, "max": math.max, "min": math.min,
        "amax": math.amax, "amin": math.amin, "prod": math.prod,
        "logsumexp": math.logsumexp, "all": math.all, "any": math.any,
        "cumsum": math.cumsum, "cumprod": math.cumprod,
        "clip": math.clip, "scale": math.scale, "lerp": math.lerp,
        "trace": math.trace, "diagonal": math.diagonal, "diff": math.diff,
        "nan_to_num": math.nan_to_num, "count_nonzero": math.count_nonzero,
        "atan2": math.atan2, "outer": math.outer, "inner": math.inner,
        "addmm": math.addmm, "logit": math.logit, "heaviside": math.heaviside,
        # stat
        "std": stat.std, "var": stat.var, "median": stat.median,
        "quantile": stat.quantile, "nanquantile": stat.nanquantile,
        "nanmedian": stat.nanmedian, "histogram": stat.histogram,
        "bincount": stat.bincount,
        # manipulation
        "reshape": manipulation.reshape, "reshape_": manipulation.reshape_,
        "transpose": manipulation.transpose, "squeeze": manipulation.squeeze,
        "squeeze_": manipulation.squeeze_, "unsqueeze": manipulation.unsqueeze,
        "unsqueeze_": manipulation.unsqueeze_, "flatten": manipulation.flatten,
        "flatten_": manipulation.flatten_, "tile": manipulation.tile,
        "expand": manipulation.expand, "expand_as": manipulation.expand_as,
        "broadcast_to": manipulation.broadcast_to, "flip": manipulation.flip,
        "roll": manipulation.roll, "gather": manipulation.gather,
        "gather_nd": manipulation.gather_nd, "scatter": manipulation.scatter,
        "scatter_": manipulation.scatter_,
        "scatter_nd_add": manipulation.scatter_nd_add,
        "index_select": manipulation.index_select,
        "index_sample": manipulation.index_sample,
        "index_add": manipulation.index_add,
        "masked_select": manipulation.masked_select,
        "masked_fill": manipulation.masked_fill,
        "take_along_axis": manipulation.take_along_axis,
        "put_along_axis": manipulation.put_along_axis,
        "split": manipulation.split, "chunk": manipulation.chunk,
        "unbind": manipulation.unbind, "repeat_interleave":
            manipulation.repeat_interleave, "where": None,
        "moveaxis": manipulation.moveaxis, "swapaxes": manipulation.swapaxes,
        "unique": manipulation.unique, "pad": manipulation.pad,
        "slice": manipulation.slice, "unfold": manipulation.unfold,
        "view": manipulation.view, "view_as": manipulation.view_as,
        "as_strided": manipulation.as_strided,
        "tensor_split": manipulation.tensor_split,
        # linalg
        "matmul": linalg.matmul, "mm": linalg.mm, "bmm": linalg.bmm,
        "dot": linalg.dot, "mv": linalg.mv, "t": linalg.t,
        "norm": linalg.norm, "dist": linalg.dist, "cross": linalg.cross,
        "cholesky": linalg.cholesky, "inverse": linalg.inverse,
        "matrix_power": linalg.matrix_power, "det": linalg.det,
        "tensordot": linalg.tensordot, "kron": math.kron,
        # logic
        "equal": logic.equal, "not_equal": logic.not_equal,
        "greater_than": logic.greater_than, "greater_equal":
            logic.greater_equal, "less_than": logic.less_than,
        "less_equal": logic.less_equal, "logical_and": logic.logical_and,
        "logical_or": logic.logical_or, "logical_xor": logic.logical_xor,
        "logical_not": logic.logical_not, "bitwise_and": logic.bitwise_and,
        "bitwise_or": logic.bitwise_or, "bitwise_xor": logic.bitwise_xor,
        "bitwise_not": logic.bitwise_not, "isclose": logic.isclose,
        "allclose": logic.allclose, "equal_all": logic.equal_all,
        "is_empty": logic.is_empty,
        # search
        "argmax": search.argmax, "argmin": search.argmin,
        "argsort": search.argsort, "sort": search.sort, "topk": search.topk,
        "kthvalue": search.kthvalue, "mode": search.mode,
        "nonzero": search.nonzero, "searchsorted": search.searchsorted,
        "bucketize": search.bucketize,
        # creation-ish
        "diag": creation.diag, "tril": creation.tril, "triu": creation.triu,
        # random
        "normal_": random_mod.normal_, "uniform_": random_mod.uniform_,
        "exponential_": random_mod.exponential_,
        "bernoulli_": random_mod.bernoulli_,
        # attribute
        "numel": numel, "rank_fn": rank,
    }
    for name, fn in methods.items():
        if fn is None:
            continue
        setattr(T, name, _method(fn))

    T.where = lambda s, x=None, y=None, name=None: manipulation.where(s, x, y)
    # inplace arithmetic variants
    for nm, fn in [("add_", math.add), ("subtract_", math.subtract),
                   ("multiply_", math.multiply), ("divide_", math.divide),
                   ("scale_", math.scale), ("clip_", math.clip),
                   ("floor_", math.floor), ("ceil_", math.ceil),
                   ("exp_", math.exp), ("sqrt_", math.sqrt),
                   ("rsqrt_", math.rsqrt), ("reciprocal_", math.reciprocal),
                   ("round_", math.round), ("abs_", math.abs),
                   ("tanh_", math.tanh), ("pow_", math.pow),
                   ("remainder_", math.mod), ("lerp_", math.lerp),
                   ("masked_fill_", manipulation.masked_fill)]:
        setattr(T, nm, _inplace(fn))

    # remaining reference inplace variants, generated from their base ops
    # (reference: tensor/__init__.py *_ entries; on the immutable substrate
    # inplace = compute + rebind _value + bump the version counter)
    _extra_inplace = [
        "acos", "acosh", "asin", "asinh", "atan", "atanh", "cast",
        "copysign", "cos", "cosh", "cumprod", "cumsum", "digamma",
        "erfinv", "floor_divide", "frac", "gammainc", "gammaincc",
        "gammaln", "gcd", "hypot", "i0", "lcm", "lgamma", "log", "log10",
        "log1p", "log2", "logit", "mod", "nan_to_num", "neg", "polygamma",
        "sigmoid", "sin", "sinh", "sqrt", "tan", "trunc", "tril", "triu",
        "erf", "expm1", "square", "t",
        "equal", "not_equal", "greater_equal", "greater_than",
        "less_equal", "less_than", "logical_and", "logical_not",
        "logical_or", "logical_xor", "bitwise_and", "bitwise_not",
        "bitwise_or", "bitwise_xor", "bitwise_left_shift",
        "bitwise_right_shift", "multigammaln", "addmm", "index_fill",
        "index_put", "masked_scatter", "put_along_axis", "renorm",
        "ldexp", "divide", "multiply", "subtract", "add",
        "scale", "clip", "floor", "ceil", "exp", "rsqrt", "reciprocal",
        "round", "abs", "tanh", "pow", "lerp", "masked_fill",
    ]
    import sys as _sys
    _mod = _sys.modules[__name__]
    for _base in _extra_inplace:
        _fn = getattr(_mod, _base, None)
        if _fn is None or not callable(_fn):
            continue
        _nm = _base + "_"
        if not hasattr(T, _nm):
            setattr(T, _nm, _inplace(_fn))
        if not hasattr(_mod, _nm):
            def _make_free(fn):
                def free(x, *a, **k):
                    return x._inplace_assign(fn(x, *a, **k))
                return free
            setattr(_mod, _nm, _make_free(_fn))

    # aliases + in-place random fills (reference: random.py cauchy_/
    # geometric_ fill the tensor from the distribution)
    # where_ mutates X (reference: search.py:743), not the condition
    def _where_(cond, x, y, name=None):
        return x._inplace_assign(manipulation.where(cond, x, y))

    _mod.where_ = _where_
    T.where_ = lambda self, x, y, name=None: _where_(self, x, y)

    T.floor_mod_ = T.mod_
    T.remainder_ = T.mod_
    _mod.floor_mod_ = _mod.mod_
    _mod.remainder_ = _mod.mod_

    def _cauchy_(self, loc=0, scale=1, name=None):
        from paddle_tpu.core.random import next_key
        u = jax.random.uniform(next_key(), self._value.shape,
                               jnp.float32, 1e-6, 1 - 1e-6)
        vals = loc + scale * jnp.tan(jnp.pi * (u - 0.5))
        return self._inplace_assign(Tensor(vals.astype(self._value.dtype)))

    def _geometric_(self, probs, name=None):
        from paddle_tpu.core.random import next_key
        p = probs._value if isinstance(probs, Tensor) else jnp.asarray(probs)
        u = jax.random.uniform(next_key(), self._value.shape,
                               jnp.float32, 1e-6, 1 - 1e-6)
        vals = jnp.ceil(jnp.log(u) / jnp.log1p(-p))
        return self._inplace_assign(Tensor(vals.astype(self._value.dtype)))

    T.cauchy_ = _cauchy_
    T.geometric_ = _geometric_
    _mod.cauchy_ = lambda x, *a, **k: _cauchy_(x, *a, **k)
    _mod.geometric_ = lambda x, *a, **k: _geometric_(x, *a, **k)

    # paddle: x.cuda()/cpu()/to() are placement ops; PjRt owns placement.
    T.cuda = lambda s, *a, **k: s
    T.cpu = lambda s: Tensor(np.asarray(s._value), stop_gradient=s.stop_gradient)
    T.pin_memory = lambda s: s
    T.to = _tensor_to


def _tensor_to(self, *args, **kwargs):
    dtype = kwargs.get("dtype")
    for a in args:
        if isinstance(a, (str, np.dtype)) and str(a) not in ("cpu", "gpu", "tpu"):
            try:
                from paddle_tpu.core.dtype import convert_dtype
                dtype = convert_dtype(a)
            except (ValueError, TypeError):
                pass
        elif isinstance(a, Tensor):
            dtype = a.dtype
    if dtype is not None and np.dtype(dtype) != self.dtype:
        return self.astype(dtype)
    return self


def _as_t(o, like):
    if isinstance(o, Tensor):
        return o
    return Tensor(jnp.asarray(o, dtype=like._value.dtype
                              if isinstance(o, (int, float, bool)) else None))


def _method(fn):
    def m(self, *args, **kwargs):
        return fn(self, *args, **kwargs)
    m.__name__ = getattr(fn, "__name__", "method")
    return m


_patch()
