"""Einsum (reference: python/paddle/tensor/einsum.py — 1k-LoC planner).

The reference hand-builds a contraction plan over matmul/transpose ops; on
TPU we delegate straight to jnp.einsum, which lowers to XLA dot_general and
rides the MXU with optimal contraction ordering from opt_einsum.
"""
from __future__ import annotations

from paddle_tpu.core.dispatch import defop
import jax.numpy as jnp


@defop("einsum", amp_policy="white")
def _einsum(equation, *operands):
    return jnp.einsum(equation, *operands)


def einsum(equation, *operands):
    return _einsum(equation, *operands)
