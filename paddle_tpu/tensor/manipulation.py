"""Shape / layout manipulation ops (reference: python/paddle/tensor/manipulation.py)."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu.core import dtype as dtypes
from paddle_tpu.core.dispatch import defop

# the public op `slice` (API parity) shadows the builtin at
# module scope; internal code must use this alias
import builtins as _builtins
_pyslice = _builtins.slice
from paddle_tpu.core.tensor import Tensor


def _ints(v):
    if isinstance(v, Tensor):
        return tuple(int(i) for i in np.asarray(v._value))
    if isinstance(v, (int, np.integer)):
        return (int(v),)
    return tuple(int(i.item()) if isinstance(i, Tensor) else int(i) for i in v)


@defop("cast")
def _cast(x, dtype):
    return x.astype(dtypes.convert_dtype(dtype))


def cast(x, dtype):
    return _cast(x, dtype=dtypes.convert_dtype(dtype))


@defop("clone")
def clone(x):
    return jnp.asarray(x).copy() if isinstance(x, np.ndarray) else x + 0


@defop("reshape")
def _reshape(x, shape):
    return jnp.reshape(x, shape)


def reshape(x, shape, name=None):
    shape = tuple(int(s.item()) if isinstance(s, Tensor) else int(s)
                  for s in (shape if isinstance(shape, (list, tuple)) else _ints(shape)))
    return _reshape(x, shape=shape)


def reshape_(x, shape, name=None):
    return x._inplace_assign(reshape(x, shape))


@defop("transpose")
def _transpose(x, perm):
    return jnp.transpose(x, perm)


def transpose(x, perm, name=None):
    return _transpose(x, perm=tuple(int(p) for p in perm))


@defop("moveaxis")
def moveaxis(x, source, destination):
    return jnp.moveaxis(x, source, destination)


@defop("swapaxes")
def swapaxes(x, axis0, axis1):
    return jnp.swapaxes(x, axis0, axis1)


transpose_ = None  # not supported (layout is XLA's concern)


@defop("squeeze")
def _squeeze(x, axis=None):
    if axis is None:
        return jnp.squeeze(x)
    axes = axis if isinstance(axis, tuple) else (axis,)
    axes = tuple(a for a in axes if x.shape[a] == 1)
    return jnp.squeeze(x, axis=axes) if axes else x


def squeeze(x, axis=None, name=None):
    if isinstance(axis, (list, tuple)):
        axis = tuple(int(a) for a in axis)
    return _squeeze(x, axis=axis)


def squeeze_(x, axis=None, name=None):
    return x._inplace_assign(squeeze(x, axis))


@defop("unsqueeze")
def _unsqueeze(x, axis):
    axes = axis if isinstance(axis, tuple) else (axis,)
    out = x
    for a in sorted(a if a >= 0 else a + out.ndim + 1 for a in axes):
        out = jnp.expand_dims(out, a)
    return out


def unsqueeze(x, axis, name=None):
    if isinstance(axis, Tensor):
        axis = _ints(axis)
    if isinstance(axis, (list, tuple)):
        axis = tuple(int(a) for a in axis)
    return _unsqueeze(x, axis=axis)


def unsqueeze_(x, axis, name=None):
    return x._inplace_assign(unsqueeze(x, axis))


@defop("concat")
def _concat(xs, axis=0):
    return jnp.concatenate(xs, axis=axis)


def concat(x, axis=0, name=None):
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    return _concat(list(x), axis=axis)


@defop("stack")
def _stack(xs, axis=0):
    return jnp.stack(xs, axis=axis)


def stack(x, axis=0, name=None):
    return _stack(list(x), axis=axis)


@defop("split_op")
def _split(x, sections, axis):
    if isinstance(sections, int):
        return tuple(jnp.split(x, sections, axis=axis))
    # list of sizes, possibly with one -1
    sizes = list(sections)
    if -1 in sizes:
        known = sum(s for s in sizes if s != -1)
        sizes[sizes.index(-1)] = x.shape[axis] - known
    offsets = np.cumsum(sizes)[:-1].tolist()
    return tuple(jnp.split(x, offsets, axis=axis))


def split(x, num_or_sections, axis=0, name=None):
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    if isinstance(num_or_sections, (list, tuple)):
        num_or_sections = [int(s.item()) if isinstance(s, Tensor) else int(s)
                           for s in num_or_sections]
    return list(_split(x, sections=num_or_sections, axis=int(axis)))


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis)


@defop("unbind")
def _unbind(x, axis=0):
    return tuple(jnp.moveaxis(x, axis, 0))


def unbind(x, axis=0):
    return list(_unbind(x, axis=axis))


@defop("flatten_op")
def _flatten(x, start_axis=0, stop_axis=-1):
    nd = x.ndim
    s = start_axis % nd if nd else 0
    e = stop_axis % nd if nd else 0
    shape = x.shape[:s] + (-1,) + x.shape[e + 1:]
    return jnp.reshape(x, shape)


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    return _flatten(x, start_axis=start_axis, stop_axis=stop_axis)


def flatten_(x, start_axis=0, stop_axis=-1, name=None):
    return x._inplace_assign(flatten(x, start_axis, stop_axis))


@defop("tile")
def _tile(x, repeat_times):
    return jnp.tile(x, repeat_times)


def tile(x, repeat_times, name=None):
    return _tile(x, repeat_times=_ints(repeat_times))


@defop("expand")
def _expand(x, shape):
    shape = tuple(x.shape[i - (len(shape) - x.ndim)] if s in (-1,) else s
                  for i, s in enumerate(shape))
    return jnp.broadcast_to(x, shape)


def expand(x, shape, name=None):
    return _expand(x, shape=_ints(shape))


def broadcast_to(x, shape, name=None):
    return expand(x, shape)


def expand_as(x, y, name=None):
    return expand(x, y.shape)


def broadcast_tensors(inputs, name=None):
    arrays = jnp.broadcast_arrays(*[t._value for t in inputs])
    return [Tensor(a) for a in arrays]


def broadcast_shape(x_shape, y_shape):
    return list(np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


@defop("flip")
def _flip(x, axis):
    return jnp.flip(x, axis=axis)


def flip(x, axis, name=None):
    if isinstance(axis, (list, tuple)):
        axis = tuple(int(a) for a in axis)
    return _flip(x, axis=axis)


def rot90(x, k=1, axes=(0, 1), name=None):
    return Tensor(jnp.rot90(x._value, k=k, axes=tuple(axes)))


@defop("roll")
def _roll(x, shifts, axis=None):
    return jnp.roll(x, shifts, axis=axis)


def roll(x, shifts, axis=None, name=None):
    if isinstance(shifts, (list, tuple)):
        shifts = tuple(int(s) for s in shifts)
    if isinstance(axis, (list, tuple)):
        axis = tuple(int(a) for a in axis)
    return _roll(x, shifts=shifts, axis=axis)


@defop("pad_op")
def _pad(x, pad, mode="constant", value=0.0, data_format="NCHW"):
    if len(pad) == x.ndim * 2:
        cfg = [(pad[2 * i], pad[2 * i + 1]) for i in range(x.ndim)]
    else:
        # paddle semantics: pad applies to the last len(pad)//2 dims,
        # innermost dim first in the pad list (NCHW pad=[l,r,t,b] -> W gets
        # (l,r), H gets (t,b)).
        n = len(pad) // 2
        cfg = [(0, 0)] * (x.ndim - n) + \
            [(pad[2 * i], pad[2 * i + 1]) for i in range(n - 1, -1, -1)]
    jmode = {"constant": "constant", "reflect": "reflect",
             "replicate": "edge", "circular": "wrap"}[mode]
    if jmode == "constant":
        return jnp.pad(x, cfg, mode=jmode, constant_values=value)
    return jnp.pad(x, cfg, mode=jmode)


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    return _pad(x, pad=_ints(pad), mode=mode, value=value,
                data_format=data_format)


@defop("slice_op")
def _slice(x, axes, starts, ends):
    idx = [_pyslice(None)] * x.ndim
    for a, s, e in zip(axes, starts, ends):
        idx[a] = _pyslice(s, e)
    return x[tuple(idx)]


def slice(x, axes, starts, ends):
    return _slice(x, axes=_ints(axes), starts=_ints(starts), ends=_ints(ends))


@defop("strided_slice_op")
def _strided_slice(x, axes, starts, ends, strides):
    idx = [_pyslice(None)] * x.ndim
    for a, s, e, st in zip(axes, starts, ends, strides):
        idx[a] = _pyslice(s, e, st)
    return x[tuple(idx)]


def strided_slice(x, axes, starts, ends, strides, name=None):
    return _strided_slice(x, axes=_ints(axes), starts=_ints(starts),
                          ends=_ints(ends), strides=_ints(strides))


@defop("gather")
def _gather(x, index, axis=0):
    return jnp.take(x, index, axis=axis)


def gather(x, index, axis=None, name=None):
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    if axis is None:        # reference: axis=None means axis 0
        axis = 0
    idx = index
    if isinstance(index, Tensor) and index.ndim == 2 and index.shape[1] == 1:
        idx = index.reshape([-1])
    return _gather(x, idx, axis=axis)


@defop("gather_nd")
def gather_nd(x, index):
    idx = tuple(jnp.moveaxis(index, -1, 0))
    return x[idx]


@defop("take_along_axis")
def take_along_axis(arr, indices, axis, broadcast=True):
    return jnp.take_along_axis(arr, indices, axis=axis)


@defop("put_along_axis")
def put_along_axis(arr, indices, values, axis, reduce="assign",
                   include_self=True, broadcast=True):
    values = jnp.broadcast_to(values, indices.shape) \
        if not hasattr(values, "shape") or values.shape != indices.shape else values
    dims = list(range(arr.ndim))
    idx = [jnp.broadcast_to(
        jnp.arange(indices.shape[d]).reshape(
            [-1 if i == d else 1 for i in range(arr.ndim)]), indices.shape)
        for d in dims]
    idx[axis] = indices
    at = arr.at[tuple(idx)]
    if reduce == "assign":
        return at.set(values)
    if reduce in ("add", "sum"):
        return at.add(values)
    if reduce in ("mul", "multiply"):
        return at.multiply(values)
    if reduce == "amax":
        return at.max(values)
    if reduce == "amin":
        return at.min(values)
    raise ValueError(f"Unsupported reduce: {reduce}")


@defop("scatter")
def _scatter(x, index, updates, overwrite=True):
    if index.ndim == 2 and index.shape[1] == 1:
        index = index.reshape(-1)
    if overwrite:
        return x.at[index].set(updates)
    return x.at[index].add(updates)


def scatter(x, index, updates, overwrite=True, name=None):
    return _scatter(x, index, updates, overwrite=overwrite)


def scatter_(x, index, updates, overwrite=True, name=None):
    return x._inplace_assign(scatter(x, index, updates, overwrite))


@defop("scatter_nd_add")
def scatter_nd_add(x, index, updates):
    idx = tuple(jnp.moveaxis(index, -1, 0))
    return x.at[idx].add(updates)


def scatter_nd(index, updates, shape, name=None):
    from paddle_tpu.tensor.creation import zeros
    z = zeros(shape, dtype=updates.dtype)
    return scatter_nd_add(z, index, updates)


@defop("index_select")
def _index_select(x, index, axis=0):
    return jnp.take(x, index, axis=axis)


def index_select(x, index, axis=0, name=None):
    return _index_select(x, index, axis=axis)


@defop("index_sample")
def index_sample(x, index):
    return jnp.take_along_axis(x, index, axis=1)


@defop("index_add")
def index_add(x, index, axis, value):
    x_m = jnp.moveaxis(x, axis, 0)
    v_m = jnp.moveaxis(value, axis, 0)
    out = x_m.at[index].add(v_m)
    return jnp.moveaxis(out, 0, axis)


@defop("index_put")
def index_put(x, indices, value, accumulate=False):
    idx = tuple(indices)
    return x.at[idx].add(value) if accumulate else x.at[idx].set(value)


@defop("masked_select", differentiable=False)
def masked_select(x, mask):
    # dynamic-shape op: falls back to host (XLA needs static shapes)
    xv = np.asarray(x)
    mv = np.asarray(mask)
    return jnp.asarray(xv[np.broadcast_to(mv, xv.shape)])


@defop("masked_fill")
def masked_fill(x, mask, value):
    return jnp.where(mask, value, x)


@defop("masked_scatter")
def masked_scatter(x, mask, value):
    mask_b = jnp.broadcast_to(mask, x.shape)
    flat_mask = mask_b.reshape(-1)
    pos = jnp.cumsum(flat_mask.astype(jnp.int32)) - 1
    vals = value.reshape(-1)[jnp.clip(pos, 0, value.size - 1)]
    return jnp.where(flat_mask, vals, x.reshape(-1)).reshape(x.shape)


@defop("where")
def _where(condition, x, y):
    return jnp.where(condition, x, y)


def where(condition, x=None, y=None, name=None):
    if x is None and y is None:
        from paddle_tpu.tensor.search import nonzero
        return nonzero(condition, as_tuple=True)
    return _where(condition, x, y)


@defop("repeat_interleave")
def _repeat_interleave(x, repeats, axis=None):
    return jnp.repeat(x, repeats, axis=axis)


def repeat_interleave(x, repeats, axis=None, name=None):
    if isinstance(repeats, Tensor):
        repeats = np.asarray(repeats._value)
        total = int(repeats.sum())
        return Tensor(jnp.repeat(x._value, jnp.asarray(repeats), axis=axis,
                                 total_repeat_length=total))
    return _repeat_interleave(x, repeats, axis=axis)


@defop("as_strided")
def as_strided(x, shape, stride, offset=0):
    flat = x.reshape(-1)
    idx = jnp.full(tuple(shape), offset)
    for d, (s, st) in enumerate(zip(shape, stride)):
        r = jnp.arange(s).reshape([-1 if i == d else 1 for i in range(len(shape))])
        idx = idx + r * st
    return flat[idx]


def view(x, shape_or_dtype, name=None):
    if isinstance(shape_or_dtype, (list, tuple)):
        return reshape(x, shape_or_dtype)
    return Tensor(x._value.view(dtypes.convert_dtype(shape_or_dtype)))


def view_as(x, other, name=None):
    return reshape(x, other.shape)


def as_real(x, name=None):
    v = x._value
    return Tensor(jnp.stack([jnp.real(v), jnp.imag(v)], axis=-1))


def as_complex(x, name=None):
    v = x._value
    return Tensor(jax.lax.complex(v[..., 0], v[..., 1]))


@defop("unfold")
def unfold(x, axis, size, step):
    n = (x.shape[axis] - size) // step + 1
    starts = jnp.arange(n) * step
    idx = starts[:, None] + jnp.arange(size)[None, :]
    moved = jnp.moveaxis(x, axis, 0)
    out = moved[idx]  # (n, size, ...)
    return jnp.moveaxis(out, (0, 1), (axis, x.ndim))


def unique(x, return_index=False, return_inverse=False, return_counts=False,
           axis=None, dtype="int64", name=None):
    xv = np.asarray(x._value)
    res = np.unique(xv, return_index=return_index,
                    return_inverse=return_inverse,
                    return_counts=return_counts, axis=axis)
    if not isinstance(res, tuple):
        return Tensor(jnp.asarray(res))
    out = [Tensor(jnp.asarray(r)) for r in res]
    # paddle's return order: out, index, inverse, counts
    return tuple(out)


def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None,
                       dtype="int64", name=None):
    xv = np.asarray(x._value)
    if axis is None:
        xv = xv.reshape(-1)
        change = np.concatenate([[True], xv[1:] != xv[:-1]])
    else:
        raise NotImplementedError("unique_consecutive with axis")
    out = xv[change]
    rets = [Tensor(jnp.asarray(out))]
    if return_inverse:
        rets.append(Tensor(jnp.asarray(np.cumsum(change) - 1)))
    if return_counts:
        idx = np.flatnonzero(change)
        counts = np.diff(np.append(idx, len(xv)))
        rets.append(Tensor(jnp.asarray(counts)))
    return rets[0] if len(rets) == 1 else tuple(rets)


@defop("crop")
def crop(x, shape=None, offsets=None):
    offsets = offsets or [0] * x.ndim
    idx = tuple(_pyslice(o, o + s)
                for o, s in zip(offsets, shape))
    return x[idx]


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    size = index_num // nshards
    v = input._value
    in_shard = (v // size) == shard_id
    return Tensor(jnp.where(in_shard, v % size, ignore_value))


def tensordot(x, y, axes=2, name=None):
    from paddle_tpu.tensor.linalg import tensordot as _td
    return _td(x, y, axes)


@defop("atleast_1d")
def atleast_1d(x):
    return jnp.atleast_1d(x)


@defop("atleast_2d")
def atleast_2d(x):
    return jnp.atleast_2d(x)


@defop("atleast_3d")
def atleast_3d(x):
    return jnp.atleast_3d(x)


def vstack(x, name=None):
    return Tensor(jnp.vstack([t._value for t in x]))


def hstack(x, name=None):
    return Tensor(jnp.hstack([t._value for t in x]))


def dstack(x, name=None):
    return Tensor(jnp.dstack([t._value for t in x]))


def column_stack(x, name=None):
    return Tensor(jnp.column_stack([t._value for t in x]))


def row_stack(x, name=None):
    return vstack(x)


def dsplit(x, num_or_indices, name=None):
    return [Tensor(a) for a in jnp.dsplit(x._value, num_or_indices)]


def hsplit(x, num_or_indices, name=None):
    return [Tensor(a) for a in jnp.hsplit(x._value, num_or_indices)]


def vsplit(x, num_or_indices, name=None):
    return [Tensor(a) for a in jnp.vsplit(x._value, num_or_indices)]


def tensor_split(x, num_or_indices, axis=0, name=None):
    return [Tensor(a) for a in jnp.array_split(
        x._value, num_or_indices, axis=axis)]
