"""Tensor attribute helpers (reference: python/paddle/tensor/attribute.py)."""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from paddle_tpu.core.tensor import Tensor


def shape(x):
    """Returns the shape as an int32 tensor (paddle.shape semantics)."""
    return Tensor(jnp.asarray(x.shape, dtype=jnp.int32))


def rank(x):
    return Tensor(jnp.asarray(x.ndim, dtype=jnp.int32))


def numel(x, name=None):
    return Tensor(jnp.asarray(x.size, dtype=jnp.int64))


def is_complex(x):
    return np.issubdtype(x.dtype, np.complexfloating)


def is_integer(x):
    return np.issubdtype(x.dtype, np.integer)


def is_floating_point(x):
    return np.issubdtype(np.dtype(x.dtype), np.floating) or \
        str(x.dtype) == "bfloat16"


def real(x, name=None):
    from paddle_tpu.tensor.math import real as _real
    return _real(x)


def imag(x, name=None):
    from paddle_tpu.tensor.math import imag as _imag
    return _imag(x)
