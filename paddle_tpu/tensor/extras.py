"""Long-tail tensor ops (reference: python/paddle/tensor/{math,
manipulation,creation,search}.py entries not covered by the core modules).
"""
from __future__ import annotations

import math as _math

import numpy as np
import jax
import jax.numpy as jnp
from jax.scipy import special as jsp

from paddle_tpu.core.dispatch import defop
from paddle_tpu.core.tensor import Tensor

__all__ = [
    'take', 'add_n', 'cdist', 'diag_embed', 'diagonal_scatter',
    'select_scatter', 'slice_scatter', 'frexp', 'ldexp', 'gammainc',
    'gammaincc', 'multigammaln', 'multiplex', 'renorm', 'reverse',
    'signbit', 'trapezoid', 'cumulative_trapezoid', 'unflatten', 'unstack',
    'vander', 'top_p_sampling', 'set_printoptions', 'index_fill',
]


def _arr(x):
    return x._value if isinstance(x, Tensor) else jnp.asarray(x)


@defop("take")
def _take(x, index, mode="raise"):
    idx = index.astype(jnp.int32)
    flat = x.reshape(-1)
    n = flat.shape[0]
    if mode == "wrap":
        idx = jnp.mod(idx, n)
    elif mode == "clip":
        # reference disables negative indexing in clip mode: [0, n-1]
        idx = jnp.clip(idx, 0, n - 1)
    idx = jnp.where(idx < 0, idx + n, idx)
    return flat[idx]


def take(x, index, mode="raise", name=None):
    """Flat-index gather (reference: tensor/math.py take)."""
    return _take(x, _arr(index), mode=mode)


def add_n(inputs, name=None):
    """Sum a list of tensors (reference: tensor/math.py add_n)."""
    from paddle_tpu import tensor as T
    if isinstance(inputs, Tensor):
        return inputs
    out = inputs[0]
    for t in inputs[1:]:
        out = T.add(out, t)
    return out


@defop("cdist", amp_policy="black")
def _cdist(x, y, p=2.0):
    diff = x[..., :, None, :] - y[..., None, :, :]
    if p == 2.0:
        return jnp.sqrt(jnp.sum(diff * diff, axis=-1) + 1e-30)
    if p == float("inf"):
        return jnp.max(jnp.abs(diff), axis=-1)
    return jnp.power(jnp.sum(jnp.power(jnp.abs(diff), p), axis=-1), 1.0 / p)


def cdist(x, y, p=2.0, compute_mode="use_mm_for_euclid_dist_if_necessary",
          name=None):
    return _cdist(x, y, p=p)


@defop("diag_embed")
def _diag_embed(input, offset=0, dim1=-2, dim2=-1):
    last = input.shape[-1]
    n = last + abs(offset)
    out = jnp.zeros(input.shape[:-1] + (n, n), input.dtype)
    rows = jnp.arange(last) + max(-offset, 0)
    cols = jnp.arange(last) + max(offset, 0)
    out = out.at[..., rows, cols].set(input)
    # move the two new axes to dim1/dim2
    nd = out.ndim
    d1, d2 = dim1 % nd, dim2 % nd
    if (d1, d2) != (nd - 2, nd - 1):
        out = jnp.moveaxis(out, (nd - 2, nd - 1), (d1, d2))
    return out


def diag_embed(input, offset=0, dim1=-2, dim2=-1, name=None):
    return _diag_embed(input, offset=offset, dim1=dim1, dim2=dim2)


@defop("diagonal_scatter")
def _diagonal_scatter(x, y, offset=0, axis1=0, axis2=1):
    nd = x.ndim
    a1, a2 = axis1 % nd, axis2 % nd
    # bring target plane to the back
    perm = [i for i in range(nd) if i not in (a1, a2)] + [a1, a2]
    xt = jnp.transpose(x, perm)
    k = y.shape[-1] if y.ndim else 1
    rows = jnp.arange(k) + max(-offset, 0)
    cols = jnp.arange(k) + max(offset, 0)
    xt = xt.at[..., rows, cols].set(y)
    inv = np.argsort(perm)
    return jnp.transpose(xt, inv)


def diagonal_scatter(x, y, offset=0, axis1=0, axis2=1, name=None):
    return _diagonal_scatter(x, y, offset=offset, axis1=axis1, axis2=axis2)


@defop("select_scatter")
def _select_scatter(x, values, axis, index):
    idx = [slice(None)] * x.ndim
    idx[axis] = index
    return x.at[tuple(idx)].set(values)


def select_scatter(x, values, axis, index, name=None):
    return _select_scatter(x, values, axis, index)


@defop("slice_scatter")
def _slice_scatter(x, value, axes, starts, ends, strides):
    idx = [slice(None)] * x.ndim
    for ax, st, en, sr in zip(axes, starts, ends, strides):
        idx[ax] = slice(st, en, sr)
    return x.at[tuple(idx)].set(value)


def slice_scatter(x, value, axes=(0,), starts=(0,), ends=(1,),
                  strides=(1,), name=None):
    return _slice_scatter(x, value, tuple(axes), tuple(starts),
                          tuple(ends), tuple(strides))


def frexp(x, name=None):
    """mantissa, exponent with x = m * 2**e (reference: math.py frexp)."""
    m, e = jnp.frexp(_arr(x))
    return Tensor(m), Tensor(e.astype(jnp.int32))


@defop("ldexp")
def _ldexp(x, y):
    return x * jnp.power(jnp.asarray(2.0, x.dtype if
                                     jnp.issubdtype(x.dtype, jnp.floating)
                                     else jnp.float32), y.astype(jnp.float32))


def ldexp(x, y, name=None):
    return _ldexp(x, _arr(y))


@defop("gammainc")
def gammainc(x, y):
    return jsp.gammainc(x, y)


@defop("gammaincc")
def gammaincc(x, y):
    return jsp.gammaincc(x, y)


@defop("multigammaln")
def _multigammaln(x, p):
    out = jnp.asarray(p * (p - 1) / 4.0 * _math.log(_math.pi), x.dtype)
    for i in range(p):
        out = out + jsp.gammaln(x - i / 2.0)
    return out


def multigammaln(x, p, name=None):
    return _multigammaln(x, int(p))


@defop("multiplex")
def _multiplex(index, *inputs):
    stacked = jnp.stack(inputs)                # (n, batch, ...)
    idx = index.reshape(-1).astype(jnp.int32)
    return stacked[idx, jnp.arange(stacked.shape[1])]


def multiplex(inputs, index, name=None):
    """Row-wise select among tensors (reference: math.py multiplex)."""
    return _multiplex(_arr(index), *inputs)


@defop("renorm")
def _renorm(x, p, axis, max_norm):
    moved = jnp.moveaxis(x, axis, 0)
    flat = moved.reshape(moved.shape[0], -1)
    norms = jnp.power(jnp.sum(jnp.power(jnp.abs(flat), p), axis=1),
                      1.0 / p)
    factor = jnp.where(norms > max_norm, max_norm / (norms + 1e-7), 1.0)
    out = flat * factor[:, None]
    return jnp.moveaxis(out.reshape(moved.shape), 0, axis)


def renorm(x, p, axis, max_norm, name=None):
    return _renorm(x, float(p), int(axis), float(max_norm))


def reverse(x, axis, name=None):
    from paddle_tpu import tensor as T
    return T.flip(x, axis)


@defop("signbit", differentiable=False)
def signbit(x):
    return jnp.signbit(x)


@defop("trapezoid", amp_policy="black")
def _trapezoid(y, x=None, dx=None, axis=-1):
    if x is not None:
        return jnp.trapezoid(y, x=x, axis=axis)
    return jnp.trapezoid(y, dx=1.0 if dx is None else dx, axis=axis)


def trapezoid(y, x=None, dx=None, axis=-1, name=None):
    if x is not None:
        return _trapezoid(y, _arr(x), axis=axis)
    return _trapezoid(y, dx=dx, axis=axis)


@defop("cumulative_trapezoid", amp_policy="black")
def _cumtrapz(y, x=None, dx=None, axis=-1):
    y1 = jax.lax.slice_in_dim(y, 1, y.shape[axis], axis=axis)
    y0 = jax.lax.slice_in_dim(y, 0, y.shape[axis] - 1, axis=axis)
    if x is not None:
        x1 = jax.lax.slice_in_dim(x, 1, x.shape[axis], axis=axis)
        x0 = jax.lax.slice_in_dim(x, 0, x.shape[axis] - 1, axis=axis)
        d = x1 - x0
    else:
        d = 1.0 if dx is None else dx
    return jnp.cumsum((y0 + y1) / 2.0 * d, axis=axis)


def cumulative_trapezoid(y, x=None, dx=None, axis=-1, name=None):
    if x is not None:
        return _cumtrapz(y, _arr(x), axis=axis)
    return _cumtrapz(y, dx=dx, axis=axis)


def unflatten(x, axis, shape, name=None):
    from paddle_tpu import tensor as T
    xs = list(x.shape)
    ax = axis % len(xs)
    shape = list(shape)
    if -1 in shape:
        known = int(np.prod([s for s in shape if s != -1]))
        shape[shape.index(-1)] = xs[ax] // known
    return T.reshape(x, xs[:ax] + shape + xs[ax + 1:])


def unstack(x, axis=0, num=None, name=None):
    from paddle_tpu import tensor as T
    n = num if num is not None else x.shape[axis]
    parts = T.split(x, n, axis)
    return [T.squeeze(p, axis) for p in parts]


@defop("vander")
def _vander(x, n, increasing):
    return jnp.vander(x, N=n, increasing=increasing)


def vander(x, n=None, increasing=False, name=None):
    nn = n if n is not None else x.shape[0]
    return _vander(x, int(nn), bool(increasing))


def top_p_sampling(x, ps, threshold=None, seed=None, name=None):
    """Nucleus sampling over the last axis (reference: math.py
    top_p_sampling; CUDA kernel phi/kernels/gpu/top_p_sampling_kernel.cu).
    x: (batch, vocab) logits; ps: (batch,) cumulative-probability cutoffs.
    Returns (scores, ids)."""
    from paddle_tpu.core.random import next_key
    logits = _arr(x)
    p_arr = _arr(ps).reshape(-1)
    probs = jax.nn.softmax(logits, axis=-1)
    sort_idx = jnp.argsort(-probs, axis=-1)
    sorted_probs = jnp.take_along_axis(probs, sort_idx, axis=-1)
    cum = jnp.cumsum(sorted_probs, axis=-1)
    keep = cum - sorted_probs < p_arr[:, None]    # always keep top-1
    filt = jnp.where(keep, sorted_probs, 0.0)
    filt = filt / jnp.sum(filt, axis=-1, keepdims=True)
    key = next_key()
    pick = jax.random.categorical(key, jnp.log(jnp.maximum(filt, 1e-30)),
                                  axis=-1)
    ids = jnp.take_along_axis(sort_idx, pick[:, None], axis=-1)
    scores = jnp.take_along_axis(probs, ids, axis=-1)
    return Tensor(scores), Tensor(ids.astype(jnp.int32))


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    """(reference: tensor/to_string.py set_printoptions) — numpy-backed."""
    kw = {}
    if precision is not None:
        kw["precision"] = precision
    if threshold is not None:
        kw["threshold"] = threshold
    if edgeitems is not None:
        kw["edgeitems"] = edgeitems
    if linewidth is not None:
        kw["linewidth"] = linewidth
    if sci_mode is not None:
        kw["suppress"] = not sci_mode
    np.set_printoptions(**kw)


@defop("index_fill")
def _index_fill(x, index, axis, value):
    idx = [slice(None)] * x.ndim
    idx[axis] = index
    return x.at[tuple(idx)].set(value)


def index_fill(x, index, axis, value, name=None):
    return _index_fill(x, _arr(index).astype(jnp.int32), axis % x.ndim,
                       value)


