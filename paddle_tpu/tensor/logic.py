"""Comparison & logical ops (reference: python/paddle/tensor/logic.py)."""
from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.core.dispatch import defop
from paddle_tpu.core.tensor import Tensor


@defop("equal", differentiable=False)
def equal(x, y):
    return jnp.equal(x, y)


@defop("not_equal", differentiable=False)
def not_equal(x, y):
    return jnp.not_equal(x, y)


@defop("greater_than", differentiable=False)
def greater_than(x, y):
    return jnp.greater(x, y)


@defop("greater_equal", differentiable=False)
def greater_equal(x, y):
    return jnp.greater_equal(x, y)


@defop("less_than", differentiable=False)
def less_than(x, y):
    return jnp.less(x, y)


@defop("less_equal", differentiable=False)
def less_equal(x, y):
    return jnp.less_equal(x, y)


@defop("logical_and", differentiable=False)
def logical_and(x, y, out=None):
    return jnp.logical_and(x, y)


@defop("logical_or", differentiable=False)
def logical_or(x, y, out=None):
    return jnp.logical_or(x, y)


@defop("logical_xor", differentiable=False)
def logical_xor(x, y, out=None):
    return jnp.logical_xor(x, y)


@defop("logical_not", differentiable=False)
def logical_not(x, out=None):
    return jnp.logical_not(x)


@defop("bitwise_and", differentiable=False)
def bitwise_and(x, y, out=None):
    return jnp.bitwise_and(x, y)


@defop("bitwise_or", differentiable=False)
def bitwise_or(x, y, out=None):
    return jnp.bitwise_or(x, y)


@defop("bitwise_xor", differentiable=False)
def bitwise_xor(x, y, out=None):
    return jnp.bitwise_xor(x, y)


@defop("bitwise_not", differentiable=False)
def bitwise_not(x, out=None):
    return jnp.bitwise_not(x)


@defop("bitwise_left_shift", differentiable=False)
def bitwise_left_shift(x, y, is_arithmetic=True, out=None):
    return jnp.left_shift(x, y)


@defop("bitwise_right_shift", differentiable=False)
def bitwise_right_shift(x, y, is_arithmetic=True, out=None):
    return jnp.right_shift(x, y)


@defop("isclose", differentiable=False)
def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False):
    return jnp.isclose(x, y, rtol=rtol, atol=atol, equal_nan=equal_nan)


@defop("allclose", differentiable=False)
def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False):
    return jnp.allclose(x, y, rtol=rtol, atol=atol, equal_nan=equal_nan)


def equal_all(x, y, name=None):
    if tuple(x.shape) != tuple(y.shape):
        return Tensor(jnp.asarray(False))
    return Tensor(jnp.all(x._value == y._value))


def is_empty(x, name=None):
    return Tensor(jnp.asarray(x.size == 0))


@defop("isreal", differentiable=False)
def isreal(x):
    return jnp.isreal(x)


def is_complex(x):
    import numpy as np
    return np.issubdtype(x.dtype, np.complexfloating)


def is_integer(x):
    import numpy as np
    return np.issubdtype(x.dtype, np.integer)


def is_floating_point(x):
    import numpy as np
    return np.issubdtype(x.dtype, np.floating) or str(x.dtype) == "bfloat16"
