"""Tensor creation ops (reference: python/paddle/tensor/creation.py)."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu.core import dtype as dtypes
from paddle_tpu.core.dispatch import defop
from paddle_tpu.core.tensor import Tensor, to_tensor  # re-export to_tensor


def _mk(arr, dtype=None) -> Tensor:
    return Tensor(arr if dtype is None else arr.astype(dtypes.convert_dtype(dtype)))


def _dt(dtype):
    """dtype=None resolves to paddle.get_default_dtype() (reference
    contract: creation ops honor set_default_dtype)."""
    if dtype is None:
        from paddle_tpu.framework import _default_dtype
        return dtypes.convert_dtype(_default_dtype[0])
    return dtypes.convert_dtype(dtype)



def zeros(shape, dtype=None, name=None):
    return Tensor(jnp.zeros(_shape(shape), _dt(dtype)))


def ones(shape, dtype=None, name=None):
    return Tensor(jnp.ones(_shape(shape), _dt(dtype)))


def full(shape, fill_value, dtype=None, name=None):
    fv = fill_value.item() if isinstance(fill_value, Tensor) else fill_value
    return Tensor(jnp.full(_shape(shape), fv, _dt(dtype)))


def empty(shape, dtype=None, name=None):
    return zeros(shape, dtype)


@defop("zeros_like", differentiable=False)
def _zeros_like(x, dtype=None):
    return jnp.zeros_like(x, dtype=dtypes.convert_dtype(dtype))


def zeros_like(x, dtype=None, name=None):
    return _zeros_like(x, dtype=dtype)


@defop("ones_like", differentiable=False)
def _ones_like(x, dtype=None):
    return jnp.ones_like(x, dtype=dtypes.convert_dtype(dtype))


def ones_like(x, dtype=None, name=None):
    return _ones_like(x, dtype=dtype)


@defop("full_like", differentiable=False)
def _full_like(x, fill_value, dtype=None):
    return jnp.full_like(x, fill_value, dtype=dtypes.convert_dtype(dtype))


def full_like(x, fill_value, dtype=None, name=None):
    fv = fill_value.item() if isinstance(fill_value, Tensor) else fill_value
    return _full_like(x, fv, dtype=dtype)


def empty_like(x, dtype=None, name=None):
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None, name=None):
    start = start.item() if isinstance(start, Tensor) else start
    end = end.item() if isinstance(end, Tensor) else end
    step = step.item() if isinstance(step, Tensor) else step
    if end is None:
        start, end = 0, start
    dt = dtypes.convert_dtype(dtype)
    if dt is None:
        dt = (np.dtype("int64") if all(
            isinstance(v, (int, np.integer)) for v in (start, end, step))
            else np.dtype("float32"))
    return Tensor(jnp.arange(start, end, step, dtype=dt))


def linspace(start, stop, num, dtype=None, name=None):
    start = start.item() if isinstance(start, Tensor) else start
    stop = stop.item() if isinstance(stop, Tensor) else stop
    num = int(num.item() if isinstance(num, Tensor) else num)
    return Tensor(jnp.linspace(start, stop, num, dtype=_dt(dtype)))


def logspace(start, stop, num, base=10.0, dtype=None, name=None):
    return Tensor(jnp.logspace(float(start), float(stop), int(num), base=base,
                               dtype=_dt(dtype)))


def eye(num_rows, num_columns=None, dtype=None, name=None):
    return Tensor(jnp.eye(num_rows, num_columns, dtype=_dt(dtype)))


@defop("diag")
def _diag(x, offset=0):
    return jnp.diag(x, k=offset)


def diag(x, offset=0, padding_value=0, name=None):
    if padding_value != 0:
        base = _diag(x, offset=offset)
        from paddle_tpu.tensor.logic import equal
        mask = Tensor(jnp.eye(*base._value.shape, k=offset, dtype=bool)
                      if base.ndim == 2 else jnp.ones_like(base._value, bool))
        return Tensor(jnp.where(mask._value, base._value, padding_value))
    return _diag(x, offset=offset)


@defop("diagflat")
def diagflat(x, offset=0):
    return jnp.diagflat(x, k=offset)


@defop("tril")
def tril(x, diagonal=0, name=None):
    return jnp.tril(x, k=diagonal)


@defop("triu")
def triu(x, diagonal=0, name=None):
    return jnp.triu(x, k=diagonal)


def tril_indices(row, col, offset=0, dtype="int64"):
    r, c = np.tril_indices(row, offset, col)
    return Tensor(jnp.asarray(np.stack([r, c]), dtype=dtypes.convert_dtype(dtype)))


def triu_indices(row, col=None, offset=0, dtype="int64"):
    r, c = np.triu_indices(row, offset, col if col is not None else row)
    return Tensor(jnp.asarray(np.stack([r, c]), dtype=dtypes.convert_dtype(dtype)))


def meshgrid(*args, **kwargs):
    arrays = [a._value if isinstance(a, Tensor) else jnp.asarray(a)
              for a in (args[0] if len(args) == 1 and isinstance(args[0], (list, tuple)) else args)]
    return [Tensor(g) for g in jnp.meshgrid(*arrays, indexing="ij")]


@defop("assign")
def _assign(x):
    return jnp.asarray(x)


def assign(x, output=None):
    x = x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))
    out = _assign(x)
    if output is not None:
        output._inplace_assign(out)
        return output
    return out


def clone(x, name=None):
    from paddle_tpu.tensor.manipulation import clone as _clone
    return _clone(x)


def complex(real, imag, name=None):
    return Tensor(jax.lax.complex(real._value, imag._value))


def polar(abs, angle, name=None):
    return Tensor(jax.lax.complex(abs._value * jnp.cos(angle._value),
                                  abs._value * jnp.sin(angle._value)))


def _shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in np.asarray(shape._value))
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(s.item()) if isinstance(s, Tensor) else int(s) for s in shape)
