"""Elementwise & reduction math ops (reference: python/paddle/tensor/math.py).

Every op is a pure jax.numpy composition registered via defop — XLA fuses the
elementwise chains; there is no per-op kernel to write (reference analog: the
~950 CPU/GPU kernel files under paddle/phi/kernels/).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu.core.dispatch import defop
from paddle_tpu.core import dtype as dtypes
from paddle_tpu.core.tensor import Tensor


def _unary(name, fn, amp="promote", diff=True):
    op = defop(name, differentiable=diff, amp_policy=amp)(fn)
    return op


# ---- binary arithmetic -------------------------------------------------
@defop("add")
def add(x, y, name=None):
    return jnp.add(x, y)


@defop("subtract")
def subtract(x, y, name=None):
    return jnp.subtract(x, y)


@defop("multiply")
def multiply(x, y, name=None):
    return jnp.multiply(x, y)


@defop("divide")
def divide(x, y, name=None):
    return jnp.true_divide(x, y)


@defop("floor_divide", differentiable=False)
def floor_divide(x, y):
    return jnp.floor_divide(x, y)


@defop("mod", differentiable=False)
def mod(x, y):
    return jnp.mod(x, y)


remainder = mod
floor_mod = mod


@defop("pow", amp_policy="black")
def pow(x, y, name=None):
    return jnp.power(x, y)


@defop("maximum")
def maximum(x, y):
    return jnp.maximum(x, y)


@defop("minimum")
def minimum(x, y):
    return jnp.minimum(x, y)


@defop("fmax")
def fmax(x, y):
    return jnp.fmax(x, y)


@defop("fmin")
def fmin(x, y):
    return jnp.fmin(x, y)


@defop("atan2")
def atan2(x, y):
    return jnp.arctan2(x, y)


@defop("hypot")
def hypot(x, y):
    return jnp.hypot(x, y)


@defop("logaddexp", amp_policy="black")
def logaddexp(x, y):
    return jnp.logaddexp(x, y)


@defop("nextafter", differentiable=False)
def nextafter(x, y):
    return jnp.nextafter(x, y)


@defop("copysign")
def copysign(x, y):
    return jnp.copysign(x, y)


@defop("heaviside", differentiable=False)
def heaviside(x, y):
    return jnp.heaviside(x, y)


@defop("gcd", differentiable=False)
def gcd(x, y):
    return jnp.gcd(x, y)


@defop("lcm", differentiable=False)
def lcm(x, y):
    return jnp.lcm(x, y)


@defop("inner")
def inner(x, y):
    return jnp.inner(x, y)


@defop("outer")
def outer(x, y):
    return jnp.outer(x, y)


@defop("kron")
def kron(x, y, name=None):
    return jnp.kron(x, y)


# ---- scalar-arg ops ----------------------------------------------------
@defop("scale")
def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None):
    out = x * scale + bias if bias_after_scale else (x + bias) * scale
    return out


@defop("clip")
def clip(x, min=None, max=None, name=None):
    return jnp.clip(x, min, max)


@defop("lerp")
def lerp(x, y, weight):
    return x + weight * (y - x)


@defop("stanh")
def stanh(x, scale_a=0.67, scale_b=1.7159):
    return scale_b * jnp.tanh(scale_a * x)


# ---- unary -------------------------------------------------------------
@defop("abs")
def abs(x, name=None):
    return jnp.abs(x)


@defop("neg")
def neg(x):
    return jnp.negative(x)


@defop("sign", differentiable=False)
def sign(x):
    return jnp.sign(x)


@defop("sgn", differentiable=False)
def sgn(x):
    return jnp.sign(x)


@defop("exp", amp_policy="black")
def exp(x, name=None):
    return jnp.exp(x)


@defop("expm1", amp_policy="black")
def expm1(x):
    return jnp.expm1(x)


@defop("log", amp_policy="black")
def log(x, name=None):
    return jnp.log(x)


@defop("log2", amp_policy="black")
def log2(x):
    return jnp.log2(x)


@defop("log10", amp_policy="black")
def log10(x):
    return jnp.log10(x)


@defop("log1p", amp_policy="black")
def log1p(x):
    return jnp.log1p(x)


@defop("sqrt")
def sqrt(x, name=None):
    return jnp.sqrt(x)


@defop("rsqrt")
def rsqrt(x):
    return jax.lax.rsqrt(x)


@defop("square")
def square(x):
    return jnp.square(x)


@defop("reciprocal")
def reciprocal(x):
    return jnp.reciprocal(x)


@defop("sin")
def sin(x):
    return jnp.sin(x)


@defop("cos")
def cos(x):
    return jnp.cos(x)


@defop("tan")
def tan(x):
    return jnp.tan(x)


@defop("asin")
def asin(x):
    return jnp.arcsin(x)


@defop("acos")
def acos(x):
    return jnp.arccos(x)


@defop("atan")
def atan(x):
    return jnp.arctan(x)


@defop("sinh")
def sinh(x):
    return jnp.sinh(x)


@defop("cosh")
def cosh(x):
    return jnp.cosh(x)


@defop("tanh")
def tanh(x):
    return jnp.tanh(x)


@defop("asinh")
def asinh(x):
    return jnp.arcsinh(x)


@defop("acosh")
def acosh(x):
    return jnp.arccosh(x)


@defop("atanh")
def atanh(x):
    return jnp.arctanh(x)


@defop("erf")
def erf(x):
    return jax.scipy.special.erf(x)


@defop("erfinv")
def erfinv(x):
    return jax.scipy.special.erfinv(x)


@defop("sigmoid")
def sigmoid(x):
    return jax.nn.sigmoid(x)


@defop("logit", amp_policy="black")
def logit(x, eps=None):
    if eps is not None:
        x = jnp.clip(x, eps, 1.0 - eps)
    return jnp.log(x / (1.0 - x))


@defop("floor", differentiable=False)
def floor(x, name=None):
    return jnp.floor(x)


@defop("ceil", differentiable=False)
def ceil(x, name=None):
    return jnp.ceil(x)


@defop("round", differentiable=False)
def round(x, decimals=0):
    return jnp.round(x, decimals)


@defop("trunc", differentiable=False)
def trunc(x):
    return jnp.trunc(x)


@defop("frac")
def frac(x):
    return x - jnp.trunc(x)


@defop("angle")
def angle(x):
    return jnp.angle(x)


@defop("conj")
def conj(x):
    return jnp.conj(x)


@defop("real")
def real(x):
    return jnp.real(x)


@defop("imag")
def imag(x):
    return jnp.imag(x)


@defop("deg2rad")
def deg2rad(x):
    return jnp.deg2rad(x)


@defop("rad2deg")
def rad2deg(x):
    return jnp.rad2deg(x)


@defop("digamma")
def digamma(x):
    return jax.scipy.special.digamma(x)


@defop("lgamma")
def lgamma(x):
    return jax.scipy.special.gammaln(x)


@defop("gammaln")
def gammaln(x):
    return jax.scipy.special.gammaln(x)


@defop("polygamma")
def polygamma(x, n=0):
    return jax.scipy.special.polygamma(n, x)


@defop("i0")
def i0(x):
    return jax.scipy.special.i0(x)


@defop("i0e")
def i0e(x):
    return jax.scipy.special.i0e(x)


@defop("i1")
def i1(x):
    return jax.scipy.special.i1(x)


@defop("i1e")
def i1e(x):
    return jax.scipy.special.i1e(x)


@defop("isfinite", differentiable=False)
def isfinite(x):
    return jnp.isfinite(x)


@defop("isinf", differentiable=False)
def isinf(x):
    return jnp.isinf(x)


@defop("isnan", differentiable=False)
def isnan(x):
    return jnp.isnan(x)


# ---- reductions --------------------------------------------------------
def _axis(axis):
    if axis is None:
        return None
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


@defop("sum", amp_policy="black")
def sum(x, axis=None, dtype=None, keepdim=False, name=None):
    return jnp.sum(x, axis=_axis(axis), dtype=dtypes.convert_dtype(dtype),
                   keepdims=keepdim)


@defop("mean", amp_policy="black")
def mean(x, axis=None, keepdim=False, name=None):
    return jnp.mean(x, axis=_axis(axis), keepdims=keepdim)


@defop("max")
def max(x, axis=None, keepdim=False, name=None):
    return jnp.max(x, axis=_axis(axis), keepdims=keepdim)


@defop("min")
def min(x, axis=None, keepdim=False, name=None):
    return jnp.min(x, axis=_axis(axis), keepdims=keepdim)


@defop("amax")
def amax(x, axis=None, keepdim=False):
    return jnp.max(x, axis=_axis(axis), keepdims=keepdim)


@defop("amin")
def amin(x, axis=None, keepdim=False):
    return jnp.min(x, axis=_axis(axis), keepdims=keepdim)


@defop("prod")
def prod(x, axis=None, keepdim=False, dtype=None):
    return jnp.prod(x, axis=_axis(axis), keepdims=keepdim,
                    dtype=dtypes.convert_dtype(dtype))


@defop("logsumexp", amp_policy="black")
def logsumexp(x, axis=None, keepdim=False, name=None):
    return jax.scipy.special.logsumexp(x, axis=_axis(axis), keepdims=keepdim)


@defop("all", differentiable=False)
def all(x, axis=None, keepdim=False):
    return jnp.all(x, axis=_axis(axis), keepdims=keepdim)


@defop("any", differentiable=False)
def any(x, axis=None, keepdim=False):
    return jnp.any(x, axis=_axis(axis), keepdims=keepdim)


@defop("count_nonzero", differentiable=False)
def count_nonzero(x, axis=None, keepdim=False):
    return jnp.count_nonzero(x, axis=_axis(axis), keepdims=keepdim)


@defop("nansum", amp_policy="black")
def nansum(x, axis=None, dtype=None, keepdim=False):
    return jnp.nansum(x, axis=_axis(axis), dtype=dtypes.convert_dtype(dtype),
                      keepdims=keepdim)


@defop("nanmean", amp_policy="black")
def nanmean(x, axis=None, keepdim=False, name=None):
    return jnp.nanmean(x, axis=_axis(axis), keepdims=keepdim)


# ---- cumulative --------------------------------------------------------
@defop("cumsum", amp_policy="black")
def cumsum(x, axis=None, dtype=None, name=None):
    return jnp.cumsum(x, axis=axis, dtype=dtypes.convert_dtype(dtype))


@defop("cumprod")
def cumprod(x, dim=None, dtype=None):
    return jnp.cumprod(x, axis=dim, dtype=dtypes.convert_dtype(dtype))


@defop("cummax", differentiable=False)
def cummax(x, axis=None):
    if axis is None:
        x = x.reshape(-1)
        axis = 0
    vals = jax.lax.cummax(x, axis=axis)
    n = x.shape[axis]
    idx = jnp.arange(n).reshape([-1 if i == axis % x.ndim else 1
                                 for i in range(x.ndim)])
    idx = jnp.broadcast_to(idx, x.shape)
    eq = x == vals
    ids = jnp.where(eq, idx, -1)
    return vals, jax.lax.cummax(ids, axis=axis).astype(jnp.int32)


@defop("cummin", differentiable=False)
def cummin(x, axis=None):
    if axis is None:
        x = x.reshape(-1)
        axis = 0
    vals = jax.lax.cummin(x, axis=axis)
    n = x.shape[axis]
    idx = jnp.arange(n).reshape([-1 if i == axis % x.ndim else 1
                                 for i in range(x.ndim)])
    idx = jnp.broadcast_to(idx, x.shape)
    eq = x == vals
    ids = jnp.where(eq, idx, -1)
    return vals, jax.lax.cummax(ids, axis=axis).astype(jnp.int32)


@defop("logcumsumexp", amp_policy="black")
def logcumsumexp(x, axis=None):
    if axis is None:
        x = x.reshape(-1)
        axis = 0
    return jax.lax.associative_scan(jnp.logaddexp, x, axis=axis)


# ---- misc --------------------------------------------------------------
@defop("trace")
def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return jnp.trace(x, offset=offset, axis1=axis1, axis2=axis2)


@defop("diagonal")
def diagonal(x, offset=0, axis1=0, axis2=1):
    return jnp.diagonal(x, offset=offset, axis1=axis1, axis2=axis2)


@defop("diff")
def diff(x, n=1, axis=-1):
    return jnp.diff(x, n=n, axis=axis)


@defop("multiply_add")
def multiply_add(x, y, z):
    return x * y + z


@defop("addmm")
def addmm(input, x, y, beta=1.0, alpha=1.0):
    return beta * input + alpha * (x @ y)


@defop("nan_to_num")
def nan_to_num(x, nan=0.0, posinf=None, neginf=None):
    return jnp.nan_to_num(x, nan=nan, posinf=posinf, neginf=neginf)


@defop("broadcast_add")
def broadcast_add(x, y):
    return x + y


def increment(x, value=1.0):
    x._value = x._value + value
    x._version += 1
    return x


def accuracy_op(pred, label, k=1):
    from paddle_tpu.metric import accuracy as _acc
    return _acc(pred, label, k)
