"""Keras-like high-level API (reference: python/paddle/hapi/)."""
from paddle_tpu.hapi.model import Model  # noqa: F401
from paddle_tpu.hapi import callbacks  # noqa: F401
from paddle_tpu.hapi.callbacks import (  # noqa: F401
    Callback, ProgBarLogger, ModelCheckpoint, EarlyStopping, LRScheduler,
    History,
)
from paddle_tpu.hapi.summary import summary  # noqa: F401
