"""Keras-like high-level Model API (reference: python/paddle/hapi/model.py
:1051 fit, :1753 evaluate/predict; DynamicGraphAdapter train_batch).

TPU-native: the train step is eager-tape by default; pass ``jit=True`` to
``prepare`` to run the whole step as one XLA program via
paddle_tpu.jit.to_static.
"""
from __future__ import annotations

import os
import pickle

import numpy as np

from paddle_tpu.core.tensor import Tensor
from paddle_tpu.hapi.callbacks import config_callbacks
from paddle_tpu.metric import Metric

__all__ = ["Model"]


def _to_list(x):
    if x is None:
        return []
    if isinstance(x, (list, tuple)):
        return list(x)
    return [x]


def _as_tensor(x):
    return x if isinstance(x, Tensor) else Tensor(np.asarray(x))


class Model:
    """Wraps a Layer with train/eval/predict loops.

    model = paddle.Model(net)
    model.prepare(optimizer, loss, metrics)
    model.fit(train_dataset, eval_dataset, epochs=2, batch_size=32)
    """

    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs = _to_list(inputs)
        self._labels = _to_list(labels)
        self._optimizer = None
        self._loss = None
        self._metrics = []
        self.stop_training = False
        self.save_dir = None

    # -- configuration -----------------------------------------------------
    def prepare(self, optimizer=None, loss=None, metrics=None,
                amp_configs=None, jit: bool = False):
        self._optimizer = optimizer
        if loss is not None and not callable(loss):
            raise TypeError("loss must be callable")
        self._loss = loss
        self._metrics = _to_list(metrics)
        for m in self._metrics:
            if not isinstance(m, Metric):
                raise TypeError(f"metrics must be paddle.metric.Metric, "
                                f"got {type(m)}")
        self._amp_level = (amp_configs or {}).get("level", "O0") \
            if isinstance(amp_configs, dict) else (amp_configs or "O0")
        self._jit = jit

    def parameters(self, *args, **kwargs):
        return self.network.parameters(*args, **kwargs)

    # -- single-batch ops --------------------------------------------------
    def _forward(self, inputs):
        inputs = [_as_tensor(x) for x in _to_list(inputs)]
        return self.network(*inputs)

    def train_batch(self, inputs, labels=None, update=True):
        import paddle_tpu as paddle
        self.network.train()
        labels = [_as_tensor(x) for x in _to_list(labels)]

        if self._amp_level in ("O1", "O2"):
            ctx = paddle.amp.auto_cast(level=self._amp_level)
        else:
            import contextlib
            ctx = contextlib.nullcontext()
        with ctx:
            outputs = self._forward(inputs)
            losses = self._loss(*(_to_list(outputs) + labels))
        total = losses if isinstance(losses, Tensor) else sum(_to_list(losses))
        total.backward()
        if update:
            self._optimizer.step()
            self._optimizer.clear_grad()
        vals = [float(v) for v in _to_list(losses)]
        return vals if len(vals) > 1 else vals[0]

    def eval_batch(self, inputs, labels=None):
        import paddle_tpu as paddle
        self.network.eval()
        labels = [_as_tensor(x) for x in _to_list(labels)]
        with paddle.no_grad():
            outputs = self._forward(inputs)
            if self._loss:
                losses = self._loss(*(_to_list(outputs) + labels))
            else:
                losses = None
        metrics = []
        for m in self._metrics:
            res = m.compute(*(_to_list(outputs) + labels))
            m.update(*[np.asarray(r) for r in _to_list(res)])
            metrics.append(m.accumulate())
        vals = [float(v) for v in _to_list(losses)] if losses is not None \
            else []
        return (vals if len(vals) != 1 else vals[0]), metrics

    def predict_batch(self, inputs):
        import paddle_tpu as paddle
        self.network.eval()
        with paddle.no_grad():
            out = self._forward(inputs)
        return [o.numpy() for o in _to_list(out)]

    # -- loops -------------------------------------------------------------
    def _make_loader(self, data, batch_size, shuffle, num_workers, drop_last):
        from paddle_tpu.io import DataLoader, Dataset, IterableDataset
        if isinstance(data, DataLoader):
            return data
        if isinstance(data, (Dataset, IterableDataset)):
            return DataLoader(data, batch_size=batch_size, shuffle=shuffle,
                              num_workers=num_workers, drop_last=drop_last)
        return data  # any iterable of batches

    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None):
        assert train_data is not None
        self.save_dir = save_dir
        loader = self._make_loader(train_data, batch_size, shuffle,
                                   num_workers, drop_last)
        steps = len(loader) if hasattr(loader, "__len__") else None
        cbks = config_callbacks(
            callbacks, model=self, epochs=epochs, steps=steps,
            log_freq=log_freq, verbose=verbose, save_freq=save_freq,
            save_dir=save_dir, metrics=[m.name() for m in self._metrics])

        cbks.on_train_begin()
        self.stop_training = False
        for epoch in range(epochs):
            cbks.on_epoch_begin(epoch)
            for m in self._metrics:
                m.reset()
            logs = {}
            for step, batch in enumerate(loader):
                cbks.on_train_batch_begin(step)
                ins, labs = self._split_batch(batch)
                loss = self.train_batch(ins, labs)
                logs = {"loss": loss}
                # train metrics (reference computes them on train outputs)
                if self._metrics:
                    _, mvals = self._eval_metrics_only(ins, labs)
                    for m, v in zip(self._metrics, mvals):
                        logs[m.name() if isinstance(m.name(), str)
                             else str(m.name())] = v
                cbks.on_train_batch_end(step, logs)
            if eval_data is not None and (epoch % eval_freq == 0
                                          or epoch == epochs - 1):
                eval_logs = self.evaluate(
                    eval_data, batch_size=batch_size, log_freq=log_freq,
                    verbose=0, num_workers=num_workers, callbacks=cbks,
                    _inner=True)
                logs.update({f"eval_{k}": v for k, v in eval_logs.items()})
            cbks.on_epoch_end(epoch, logs)
            if self.stop_training:
                break
        cbks.on_train_end(logs)
        hist = [c for c in cbks.callbacks
                if type(c).__name__ == "History"]
        return hist[0].history if hist else None

    def _eval_metrics_only(self, ins, labs):
        # snapshot: compute metric on this batch without resetting state
        import paddle_tpu as paddle
        self.network.eval()
        with paddle.no_grad():
            out = self._forward(ins)
        vals = []
        for m in self._metrics:
            res = m.compute(*(_to_list(out) +
                              [_as_tensor(v) for v in _to_list(labs)]))
            m.update(*[np.asarray(r) for r in _to_list(res)])
            vals.append(m.accumulate())
        self.network.train()
        return out, vals

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, _inner=False):
        loader = self._make_loader(eval_data, batch_size, False, num_workers,
                                   False)
        if _inner and callbacks is not None:
            cbks = callbacks
        else:
            cbks = config_callbacks(callbacks, model=self, verbose=verbose,
                                    metrics=[m.name() for m in self._metrics])
        for m in self._metrics:
            m.reset()
        cbks.on_eval_begin()
        logs = {}
        losses = []
        for step, batch in enumerate(loader):
            cbks.on_eval_batch_begin(step)
            ins, labs = self._split_batch(batch)
            loss, mvals = self.eval_batch(ins, labs)
            if loss != []:
                losses.append(loss)
            logs = {}
            if losses:
                logs["loss"] = float(np.mean(losses))
            for m, v in zip(self._metrics, mvals):
                logs[m.name() if isinstance(m.name(), str)
                     else str(m.name())] = v
            cbks.on_eval_batch_end(step, logs)
        cbks.on_eval_end(logs)
        return logs

    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=False, verbose=1, callbacks=None):
        loader = self._make_loader(test_data, batch_size, False, num_workers,
                                   False)
        cbks = config_callbacks(callbacks, model=self, verbose=0)
        cbks.on_predict_begin()
        outputs = []
        for step, batch in enumerate(loader):
            cbks.on_predict_batch_begin(step)
            ins, _ = self._split_batch(batch, has_label=False)
            out = self.predict_batch(ins)
            outputs.append(out)
            cbks.on_predict_batch_end(step)
        cbks.on_predict_end()
        # transpose: list-of-batches -> per-output list
        n_out = len(outputs[0]) if outputs else 0
        res = [[b[i] for b in outputs] for i in range(n_out)]
        if stack_outputs:
            res = [np.concatenate(r, axis=0) for r in res]
        return res

    def _split_batch(self, batch, has_label=True):
        if isinstance(batch, (list, tuple)):
            batch = list(batch)
            if not has_label:
                # predict: keep only the declared input slots (trailing
                # labels in the dataset are dropped, like the reference)
                n_in = max(len(self._inputs), 1)
                return batch[:n_in], []
            if len(batch) == 1:
                return batch, []
            n_in = max(len(self._inputs), 1) if self._inputs else \
                len(batch) - max(len(self._labels), 1)
            n_in = max(n_in, 1)
            return batch[:n_in], batch[n_in:]
        return [batch], []

    # -- persistence -------------------------------------------------------
    def save(self, path, training=True):
        import paddle_tpu as paddle
        dirname = os.path.dirname(path)
        if dirname:
            os.makedirs(dirname, exist_ok=True)
        paddle.save(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            paddle.save(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        import paddle_tpu as paddle
        state = paddle.load(path + ".pdparams")
        self.network.set_state_dict(state)
        opt_path = path + ".pdopt"
        if (not reset_optimizer and self._optimizer is not None
                and os.path.exists(opt_path)):
            self._optimizer.set_state_dict(paddle.load(opt_path))

    def summary(self, input_size=None, dtype=None):
        from paddle_tpu.hapi.summary import summary
        return summary(self.network, input_size, dtype)
