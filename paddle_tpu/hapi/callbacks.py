"""hapi callbacks (reference: python/paddle/hapi/callbacks.py —
Callback/CallbackList, ProgBarLogger, ModelCheckpoint, EarlyStopping,
LRScheduler)."""
from __future__ import annotations

import numbers
import os
import time

import numpy as np

__all__ = ["Callback", "ProgBarLogger", "ModelCheckpoint", "EarlyStopping",
           "LRScheduler", "History", "config_callbacks"]


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_params(self, params):
        self.params = params or {}

    def set_model(self, model):
        self.model = model

    # lifecycle hooks — all optional overrides
    def on_train_begin(self, logs=None): ...
    def on_train_end(self, logs=None): ...
    def on_eval_begin(self, logs=None): ...
    def on_eval_end(self, logs=None): ...
    def on_predict_begin(self, logs=None): ...
    def on_predict_end(self, logs=None): ...
    def on_epoch_begin(self, epoch, logs=None): ...
    def on_epoch_end(self, epoch, logs=None): ...
    def on_train_batch_begin(self, step, logs=None): ...
    def on_train_batch_end(self, step, logs=None): ...
    def on_eval_batch_begin(self, step, logs=None): ...
    def on_eval_batch_end(self, step, logs=None): ...
    def on_predict_batch_begin(self, step, logs=None): ...
    def on_predict_batch_end(self, step, logs=None): ...


class CallbackList:
    def __init__(self, callbacks):
        self.callbacks = list(callbacks)

    def set_params(self, params):
        for c in self.callbacks:
            c.set_params(params)

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def __getattr__(self, name):
        if not name.startswith("on_"):
            raise AttributeError(name)

        def call(*args, **kwargs):
            for c in self.callbacks:
                getattr(c, name)(*args, **kwargs)
        return call


def _fmt_logs(logs):
    parts = []
    for k, v in (logs or {}).items():
        if isinstance(v, (list, tuple, np.ndarray)):
            v = v[0] if len(v) else v
        if isinstance(v, numbers.Number):
            parts.append(f"{k}: {v:.4f}")
        else:
            parts.append(f"{k}: {v}")
    return " - ".join(parts)


class ProgBarLogger(Callback):
    """Per-epoch textual progress (reference ProgBarLogger; verbose 0/1/2)."""

    def __init__(self, log_freq: int = 1, verbose: int = 2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_train_begin(self, logs=None):
        self.epochs = self.params.get("epochs")

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.steps = self.params.get("steps")
        self._t0 = time.time()
        if self.verbose and self.epochs:
            print(f"Epoch {epoch + 1}/{self.epochs}")

    def on_train_batch_end(self, step, logs=None):
        if self.verbose == 2 and step % self.log_freq == 0:
            steps = f"/{self.steps}" if self.steps else ""
            print(f"step {step + 1}{steps} - {_fmt_logs(logs)}")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            dt = time.time() - self._t0
            print(f"Epoch {epoch + 1} done in {dt:.1f}s - {_fmt_logs(logs)}")

    def on_eval_begin(self, logs=None):
        self._eval_t0 = time.time()
        if self.verbose:
            print("Eval begin...")

    def on_eval_end(self, logs=None):
        if self.verbose:
            dt = time.time() - self._eval_t0
            print(f"Eval done in {dt:.1f}s - {_fmt_logs(logs)}")


class History(Callback):
    """Records per-epoch logs; attached automatically, returned by fit."""

    def on_train_begin(self, logs=None):
        self.history = {}

    def on_epoch_end(self, epoch, logs=None):
        for k, v in (logs or {}).items():
            self.history.setdefault(k, []).append(v)


class ModelCheckpoint(Callback):
    def __init__(self, save_freq: int = 1, save_dir: str = "checkpoint"):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.model is not None and epoch % self.save_freq == 0:
            path = os.path.join(self.save_dir, str(epoch))
            self.model.save(path)

    def on_train_end(self, logs=None):
        if self.model is not None:
            self.model.save(os.path.join(self.save_dir, "final"))


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.verbose = verbose
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.save_best_model = save_best_model
        self.stopped_epoch = 0
        if mode == "auto":
            mode = "min" if "loss" in monitor or "err" in monitor else "max"
        self.mode = mode

    def on_train_begin(self, logs=None):
        self.wait = 0
        self.best = self.baseline if self.baseline is not None else (
            np.inf if self.mode == "min" else -np.inf)
        self.model.stop_training = False

    def _better(self, cur):
        if self.mode == "min":
            return cur < self.best - self.min_delta
        return cur > self.best + self.min_delta

    def on_eval_end(self, logs=None):
        cur = (logs or {}).get(self.monitor)
        if cur is None:
            return
        if isinstance(cur, (list, tuple, np.ndarray)):
            cur = cur[0]
        if self._better(cur):
            self.best = cur
            self.wait = 0
            if self.save_best_model and getattr(self.model, "save_dir", None):
                self.model.save(os.path.join(self.model.save_dir, "best_model"))
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.model.stop_training = True
                if self.verbose:
                    print(f"Early stopping: {self.monitor} did not improve "
                          f"for {self.patience} evals (best {self.best:.5f})")


class LRScheduler(Callback):
    """Steps the optimizer's LRScheduler (reference LRScheduler callback)."""

    def __init__(self, by_step: bool = True, by_epoch: bool = False):
        super().__init__()
        assert by_step ^ by_epoch, "exactly one of by_step/by_epoch"
        self.by_step = by_step

    def _sched(self):
        from paddle_tpu.optimizer.lr import LRScheduler as Sched
        opt = getattr(self.model, "_optimizer", None)
        lr = getattr(opt, "_learning_rate", None)
        return lr if isinstance(lr, Sched) else None

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if self.by_step and s is not None:
            s.step()

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if not self.by_step and s is not None:
            s.step()


def config_callbacks(callbacks=None, model=None, epochs=None, steps=None,
                     log_freq=2, verbose=2, save_freq=1, save_dir=None,
                     metrics=None, mode="train"):
    cbks = list(callbacks or [])
    if not any(isinstance(c, ProgBarLogger) for c in cbks) and verbose:
        cbks = [ProgBarLogger(log_freq, verbose=verbose)] + cbks
    if not any(isinstance(c, LRScheduler) for c in cbks):
        cbks = cbks + [LRScheduler()]
    if save_dir and not any(isinstance(c, ModelCheckpoint) for c in cbks):
        cbks = cbks + [ModelCheckpoint(save_freq, save_dir)]
    if not any(isinstance(c, History) for c in cbks):
        cbks = cbks + [History()]
    lst = CallbackList(cbks)
    lst.set_model(model)
    lst.set_params({"epochs": epochs, "steps": steps, "verbose": verbose,
                    "metrics": metrics or []})
    return lst
