"""paddle.summary (reference: python/paddle/hapi/model_summary.py) —
layer-by-layer output shapes and parameter counts via forward hooks."""
from __future__ import annotations

import numpy as np

from paddle_tpu.core.tensor import Tensor

__all__ = ["summary"]


def _num_params(layer):
    own = list(layer._parameters.values()) if hasattr(layer, "_parameters") \
        else []
    return sum(int(np.prod(p.shape)) for p in own if p is not None)


def summary(net, input_size=None, dtypes=None, input=None):
    """Prints the table; returns {'total_params': N, 'trainable_params': N}."""
    records = []
    hooks = []

    def make_hook(name):
        def hook(layer, inputs, outputs):
            out = outputs[0] if isinstance(outputs, (list, tuple)) else outputs
            shape = list(out.shape) if isinstance(out, Tensor) else None
            records.append((name, type(layer).__name__, shape,
                            _num_params(layer)))
        return hook

    for name, sub in net.named_sublayers():
        if not list(sub.children()):  # leaves only, like the reference table
            hooks.append(sub.register_forward_post_hook(make_hook(name)))

    try:
        if input is not None:
            x = input if isinstance(input, (list, tuple)) else [input]
            x = [v if isinstance(v, Tensor) else Tensor(np.asarray(v))
                 for v in x]
        else:
            if input_size is None:
                raise ValueError("summary needs input_size or input")
            sizes = input_size if isinstance(input_size, list) else [input_size]
            if sizes and isinstance(sizes[0], int):
                sizes = [tuple(sizes)]
            dts = dtypes if isinstance(dtypes, (list, tuple)) else \
                [dtypes] * len(sizes)
            x = []
            for s, dt in zip(sizes, dts):
                s = tuple(1 if (d is None or d == -1) else d for d in s)
                x.append(Tensor(np.zeros(s, dtype=dt or "float32")))
        was_training = net.training
        net.eval()
        net(*x)
        if was_training:
            net.train()
    finally:
        for h in hooks:
            h.remove()

    total = sum(int(np.prod(p.shape)) for p in net.parameters())
    trainable = sum(int(np.prod(p.shape)) for p in net.parameters()
                    if not p.stop_gradient)

    width = 76
    print("-" * width)
    print(f"{'Layer (type)':<32}{'Output Shape':<26}{'Param #':>12}")
    print("=" * width)
    for name, cls, shape, n in records:
        print(f"{(name + ' (' + cls + ')')[:31]:<32}"
              f"{str(shape)[:25]:<26}{n:>12,}")
    print("=" * width)
    print(f"Total params: {total:,}")
    print(f"Trainable params: {trainable:,}")
    print(f"Non-trainable params: {total - trainable:,}")
    print("-" * width)
    return {"total_params": total, "trainable_params": trainable}
