"""Global flag registry.

TPU-native replacement for the reference's gflags-based flag system
(reference: paddle/common/flags.h:38, paddle/phi/core/flags.cc,
python exported via paddle.set_flags/get_flags). One typed Python registry
with env-var overlay (FLAGS_* envvars honoured at definition time), per
SURVEY.md §5 "Config / flag system".
"""
from __future__ import annotations

import os
import threading
from typing import Any, Callable

_lock = threading.Lock()
_FLAGS: dict[str, "_Flag"] = {}


class _Flag:
    __slots__ = ("name", "value", "default", "type", "help")

    def __init__(self, name, default, type_, help_):
        self.name = name
        self.default = default
        self.type = type_
        self.help = help_
        env = os.environ.get(name)
        self.value = _parse(env, type_) if env is not None else default


def _parse(text: str, type_: type):
    if type_ is bool:
        return text.lower() in ("1", "true", "yes", "on")
    return type_(text)


def define_flag(name: str, default: Any, help: str = "", type: type | None = None):
    """Define a flag; FLAGS_<name> env var overrides the default."""
    if not name.startswith("FLAGS_"):
        name = "FLAGS_" + name
    with _lock:
        if name not in _FLAGS:
            _FLAGS[name] = _Flag(name, default, type or type_of(default), help)
    return _FLAGS[name].value


def type_of(v):
    return bool if isinstance(v, bool) else (type(v) if v is not None else str)


def get_flags(flags=None) -> dict:
    with _lock:
        names = (
            list(_FLAGS) if flags is None
            else [f if f.startswith("FLAGS_") else "FLAGS_" + f
                  for f in ([flags] if isinstance(flags, str) else flags)]
        )
        return {n: _FLAGS[n].value for n in names if n in _FLAGS}


def get_flag(name: str, default=None):
    if not name.startswith("FLAGS_"):
        name = "FLAGS_" + name
    with _lock:
        return _FLAGS[name].value if name in _FLAGS else default


def set_flags(flags: dict):
    with _lock:
        for name, v in flags.items():
            if not name.startswith("FLAGS_"):
                name = "FLAGS_" + name
            if name not in _FLAGS:
                _FLAGS[name] = _Flag(name, v, type_of(v), "")
            else:
                _FLAGS[name].value = v


# Core flags (mirroring the reference's most-used runtime toggles).
define_flag("FLAGS_check_nan_inf", False, "Check every op output for NaN/Inf")
define_flag("FLAGS_matmul_precision", "highest",
            "XLA matmul precision for f32 operands: 'default' allows the "
            "MXU's bf16 passes (fast, ~1e-2 rel err), 'highest' gives true "
            "f32 accumulation. bf16 inputs are unaffected. Mirrors the "
            "reference's TF32 toggle (FLAGS_allow_tf32_cublas semantics).")
define_flag("FLAGS_check_nan_inf_level", 0, "0: fail on nan/inf; >0: log only")
define_flag("FLAGS_eager_op_jit", True, "Cache-jit eager per-op executables")
define_flag("FLAGS_log_level", 0, "VLOG-style verbosity (0=off)")
