"""Dtype system for paddle_tpu.

TPU-native rebuild of the reference dtype enum (reference:
paddle/phi/common/data_type.h, python/paddle/framework/dtype.py). Instead of a
C++ enum bridged through pybind, dtypes are thin aliases over numpy/jax dtypes
so that every value is directly consumable by jax.numpy without translation.

Note: TPUs have no native float64 path and JAX runs with x64 disabled by
default; int64/float64 requests are honoured at the API level but map to the
widest enabled type (int32/float32) unless jax x64 is enabled.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp
import ml_dtypes

# Canonical dtype objects (numpy dtype instances — hashable, comparable).
float16 = np.dtype("float16")
bfloat16 = np.dtype(ml_dtypes.bfloat16)
float32 = np.dtype("float32")
float64 = np.dtype("float64")
int8 = np.dtype("int8")
int16 = np.dtype("int16")
int32 = np.dtype("int32")
int64 = np.dtype("int64")
uint8 = np.dtype("uint8")
uint16 = np.dtype("uint16")
uint32 = np.dtype("uint32")
uint64 = np.dtype("uint64")
bool_ = np.dtype("bool")
complex64 = np.dtype("complex64")
complex128 = np.dtype("complex128")
float8_e4m3fn = np.dtype(ml_dtypes.float8_e4m3fn)
float8_e5m2 = np.dtype(ml_dtypes.float8_e5m2)

_NAME_TO_DTYPE = {
    "float16": float16, "fp16": float16, "half": float16,
    "bfloat16": bfloat16, "bf16": bfloat16,
    "float32": float32, "fp32": float32, "float": float32,
    "float64": float64, "fp64": float64, "double": float64,
    "int8": int8, "int16": int16, "int32": int32, "int64": int64,
    "uint8": uint8, "uint16": uint16, "uint32": uint32, "uint64": uint64,
    "bool": bool_,
    "complex64": complex64, "complex128": complex128,
    "float8_e4m3fn": float8_e4m3fn, "float8_e5m2": float8_e5m2,
}

FLOATING = {float16, bfloat16, float32, float64, float8_e4m3fn, float8_e5m2}
INTEGER = {int8, int16, int32, int64, uint8, uint16, uint32, uint64}
COMPLEX = {complex64, complex128}


def convert_dtype(dtype) -> np.dtype:
    """Normalize any user-provided dtype spec to a numpy dtype."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        try:
            return _NAME_TO_DTYPE[dtype]
        except KeyError:
            raise ValueError(f"Unknown dtype name: {dtype!r}")
    if isinstance(dtype, np.dtype):
        return dtype
    # jnp.float32-style / python types / ml_dtypes classes
    return np.dtype(dtype)


def dtype_name(dtype) -> str:
    d = convert_dtype(dtype)
    if d == bfloat16:
        return "bfloat16"
    if d == float8_e4m3fn:
        return "float8_e4m3fn"
    if d == float8_e5m2:
        return "float8_e5m2"
    return d.name


def is_floating_point(dtype) -> bool:
    return convert_dtype(dtype) in FLOATING


def is_integer(dtype) -> bool:
    return convert_dtype(dtype) in INTEGER


def is_complex(dtype) -> bool:
    return convert_dtype(dtype) in COMPLEX


def promote_types(a, b) -> np.dtype:
    """Binary dtype promotion following jax's lattice (TPU-friendly)."""
    return np.dtype(jnp.promote_types(convert_dtype(a), convert_dtype(b)))
