"""Version-fragile jax API surface, centralized.

`shard_map` has moved twice: `jax.experimental.shard_map.shard_map`
(<= 0.4.x), then promoted to `jax.shard_map` (>= 0.6), with the
`check_rep` kwarg renamed to `check_vma` along the way. A bare
`from jax import shard_map` therefore breaks every importing module on
the 0.4.x line (10 test files failed collection on 0.4.37). All
paddle_tpu code imports `shard_map` from HERE; tools/check_jax_compat.py
fails CI when a bare import sneaks back in.

Pallas TPU compiler params renamed too: `pltpu.TPUCompilerParams`
(0.4.x) became `pltpu.CompilerParams` (newer lines). Kernels build
theirs through `tpu_compiler_params(...)` here.
"""
from __future__ import annotations

import inspect

import jax

__all__ = ["shard_map", "axis_size", "tpu_compiler_params"]


def on_tpu() -> bool:
    """True when the default jax backend is a real TPU — the shared
    auto-dispatch gate for the Pallas kernel modules (kernels/
    flash_attention, blockwise_ce, fused_norm); one probe, one
    behavior."""
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


def tpu_compiler_params(**kwargs):
    """`pltpu.CompilerParams(**kwargs)` under whichever name the
    installed jax line exports (`TPUCompilerParams` on 0.4.x)."""
    from jax.experimental.pallas import tpu as pltpu
    cls = getattr(pltpu, "CompilerParams", None) \
        or getattr(pltpu, "TPUCompilerParams")
    return cls(**kwargs)

try:                                   # jax >= 0.6: promoted to top level
    from jax import shard_map as _shard_map
except ImportError:                    # jax 0.4.x
    from jax.experimental.shard_map import shard_map as _shard_map

if hasattr(jax.lax, "axis_size"):      # added ~0.5
    axis_size = jax.lax.axis_size
else:
    def axis_size(axis_name):
        """Size of a named mesh axis inside shard_map: psum of 1 folds
        to the constant at compile time on the 0.4.x line."""
        return jax.lax.psum(1, axis_name)

_HAS_VMA = "check_vma" in inspect.signature(_shard_map).parameters


def shard_map(f, mesh=None, in_specs=None, out_specs=None,
              check_vma=None, **kw):
    """`jax.shard_map` with the replication-check kwarg translated for
    whichever jax line is installed (`check_vma` new / `check_rep` old)."""
    if check_vma is not None:
        kw["check_vma" if _HAS_VMA else "check_rep"] = check_vma
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, **kw)
