"""RNG state management.

TPU-native rebuild of the reference's Generator (reference:
paddle/phi/core/generator.h:32; python/paddle/framework/random.py). Instead of
stateful Philox engines per device, we keep a counter-advanced root
`jax.random` key: every random op folds a fresh subkey out of the global (or a
local) Generator. This is deterministic, replayable, and safe under jit
(keys are explicit values, never hidden state inside a traced program).

RNGStatesTracker mirrors fleet/layers/mpu/random.py:34 — named parallel RNG
streams so e.g. tensor-parallel ranks can draw identical ("global") or
distinct ("local") dropout masks.
"""
from __future__ import annotations

import contextlib
import threading

import jax


class Generator:
    """Counter-based key generator over a root jax PRNG key."""

    def __init__(self, seed: int = 0):
        self._lock = threading.Lock()
        self.manual_seed(seed)

    def manual_seed(self, seed: int):
        with getattr(self, "_lock", contextlib.nullcontext()):
            self._seed = int(seed)
            # LAZY: materializing the key runs a jax computation, which
            # initializes the XLA backend — import paddle_tpu must stay
            # backend-free or jax.distributed.initialize (which must run
            # before ANY backend touch) breaks under the launcher
            self._key = None
            self._counter = 0
        return self

    def _root_key(self):
        if self._key is None:
            self._key = jax.random.key(self._seed)
        return self._key

    def seed(self, seed: int):
        return self.manual_seed(seed)

    @property
    def initial_seed(self) -> int:
        return self._seed

    def next_key(self):
        with self._lock:
            c = self._counter
            self._counter += 1
        return jax.random.fold_in(self._root_key(), c)

    def get_state(self):
        return (self._seed, self._counter)

    def set_state(self, state):
        self._seed, self._counter = int(state[0]), int(state[1])
        self._key = None


_default_generator = Generator(0)


def default_generator() -> Generator:
    return _default_generator


def seed(s: int) -> Generator:
    """paddle.seed equivalent: reseed the global generator."""
    _default_generator.manual_seed(s)
    return _default_generator


def get_rng_state():
    return _default_generator.get_state()


def set_rng_state(state):
    _default_generator.set_state(state)


def next_key(generator: Generator | None = None):
    return (generator or _default_generator).next_key()


class RNGStatesTracker:
    """Named RNG streams for parallel-consistent randomness
    (reference: fleet/layers/mpu/random.py:34)."""

    def __init__(self):
        self._states: dict[str, Generator] = {}

    def add(self, name: str, seed: int):
        if name in self._states:
            raise ValueError(f"RNG state {name!r} already exists")
        self._states[name] = Generator(seed)

    def reset(self):
        self._states.clear()

    @contextlib.contextmanager
    def rng_state(self, name: str):
        global _default_generator
        if name not in self._states:
            raise ValueError(f"RNG state {name!r} not registered")
        prev = _default_generator
        _default_generator = self._states[name]
        try:
            yield
        finally:
            _default_generator = prev


_rng_tracker = RNGStatesTracker()


def get_rng_state_tracker() -> RNGStatesTracker:
    return _rng_tracker
