"""Eager autograd tape.

TPU-native replacement for the reference's eager autograd engine
(reference: paddle/fluid/eager/grad_node_info.h:197 GradNodeBase,
paddle/fluid/eager/backward.cc:429 egr::Backward, grad_tensor_holder.h:27).

Design (SURVEY.md §3.1-3.2 "TPU lesson"): instead of generated per-op
GradNodes, each eager op that needs grad is run through `jax.vjp`, and the
returned vjp closure IS the grad node. The tape is an append-only list; eager
execution order is a topological order of the graph, so backward is simply a
reverse sweep — no in-degree bookkeeping needed (the reference's queue +
DuplicateCheckedGraphInfo exists because its graph is built from C++ nodes
with multi-threaded hooks; ours is single-threaded by construction).

Gradient accumulation across fan-out (the reference's GradTensorHolder) is a
dict keyed by tensor id, summed with jnp.add.
"""
from __future__ import annotations

import threading
from typing import Callable, Sequence

import numpy as np
import jax
import jax.numpy as jnp


import weakref


class TapeNode:
    """One recorded differentiable op: inputs -> vjp_fn -> outputs.

    Outputs are held weakly (keyed by tensor uid): when every output of a
    node is garbage-collected, no future backward can reach it, so the tape
    prunes it — the analog of the reference freeing GradNodes when their
    forward tensors die (eager autograd_meta shared_ptr ownership)."""

    __slots__ = ("inputs", "out_refs", "out_uids", "vjp_fn", "out_avals",
                 "name", "replay_fn")

    def __init__(self, name, inputs, outputs, vjp_fn, out_avals,
                 replay_fn=None):
        self.name = name
        self.inputs = inputs      # list[Tensor] (only those requiring grad)
        self.out_refs = [weakref.ref(o) for o in outputs]
        self.out_uids = [o._uid for o in outputs]
        self.vjp_fn = vjp_fn      # callable(cotangents tuple) -> input grads
        self.out_avals = out_avals  # [(shape, dtype)] to build zero cotangents
        # pure function(*input_arrays) -> flat outputs, same args as
        # `inputs`: lets create_graph=True re-linearize the op so the vjp
        # REPLAY is recorded on the tape (vjp-of-vjp; reference
        # backward.cc:440 create_graph / general_grad.h)
        self.replay_fn = replay_fn

    def alive(self):
        return any(r() is not None for r in self.out_refs)


_PRUNE_EVERY = 256


class Tape:
    def __init__(self):
        self.nodes: list[TapeNode] = []
        self._since_prune = 0

    def record(self, node: TapeNode):
        self.nodes.append(node)
        self._since_prune += 1
        if self._since_prune >= _PRUNE_EVERY:
            self.prune()

    def prune(self):
        """Drop nodes whose outputs are all dead — unreachable for any
        future backward (downstream nodes hold their inputs strongly, so a
        node with live consumers always has a live output)."""
        self.nodes = [n for n in self.nodes if n.alive()]
        self._since_prune = 0

    def remove(self, visited: set):
        self.nodes = [n for n in self.nodes if id(n) not in visited]

    def clear(self):
        self.nodes.clear()
        self._since_prune = 0


_state = threading.local()


def _get_state():
    if not hasattr(_state, "tape"):
        _state.tape = Tape()
        _state.grad_enabled = True
    return _state


def current_tape() -> Tape:
    return _get_state().tape


def push_tape() -> Tape:
    """Install a fresh tape (used while jit-tracing so tracer-valued nodes
    never leak onto the eager tape); returns the previous tape."""
    st = _get_state()
    prev = st.tape
    st.tape = Tape()
    return prev


def pop_tape(prev: Tape):
    _get_state().tape = prev


def grad_enabled() -> bool:
    return _get_state().grad_enabled


def set_grad_enabled(flag: bool) -> bool:
    st = _get_state()
    prev = st.grad_enabled
    st.grad_enabled = flag
    return prev


class no_grad:
    """Context manager / decorator disabling tape recording
    (reference: python/paddle/base/dygraph/base.py no_grad_)."""

    def __enter__(self):
        self._prev = set_grad_enabled(False)
        return self

    def __exit__(self, *exc):
        set_grad_enabled(self._prev)
        return False

    def __call__(self, fn):
        import functools

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with no_grad():
                return fn(*args, **kwargs)

        return wrapper


class enable_grad:
    def __enter__(self):
        self._prev = set_grad_enabled(True)
        return self

    def __exit__(self, *exc):
        set_grad_enabled(self._prev)
        return False


def _zero_cotangent(shape, dtype):
    if jnp.issubdtype(dtype, jnp.inexact):
        return jnp.zeros(shape, dtype)
    # integer/bool outputs take float0 cotangents in jax
    return np.zeros(shape, dtype=jax.dtypes.float0)


def backward(tensors, grad_tensors=None, retain_graph=False):
    """Run reverse-mode accumulation from `tensors`; set .grad on leaves.

    Mirrors egr::Backward (reference: paddle/fluid/eager/backward.cc:429):
    seeds output grads (default ones), sweeps the graph in reverse
    topological order, sums fan-in, applies registered tensor hooks, and
    accumulates into `.grad` of leaf tensors (reference:
    accumulation/accumulation_node.h:24).
    """
    grads = _seed_grads(tensors, grad_tensors)
    tape = current_tape()
    visited = set()
    _sweep(tape, grads, accumulate_leaves=True, visited=visited)
    if not retain_graph:
        # free only the swept subgraph; other live graphs (e.g. a second
        # loss over shared inputs) keep their nodes
        tape.remove(visited)


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, only_inputs=True, allow_unused=False,
         no_grad_vars=None):
    """paddle.grad equivalent (reference: backward.cc:440 egr::Grad /
    GeneralGrad subgraph). Returns grads of `inputs` without touching .grad.

    only_inputs=False (compute .grad for the whole subgraph too) is
    deprecated in the reference and unsupported here; no_grad_vars
    excludes tensors from the sweep (their grads become None/zero
    contributions, matching reference semantics).

    create_graph=True records the backward sweep ITSELF on the tape
    (each node's vjp is re-linearized via its replay_fn and recorded as
    a new node), so the returned grads are differentiable — enough for
    gradient-penalty training (WGAN-GP). Higher-order beyond that works
    the same way, recursively."""
    outputs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    if not only_inputs:
        raise NotImplementedError(
            "only_inputs=False is deprecated in the reference (always "
            "behaves as True there too) and is not supported")
    blocked = None
    if no_grad_vars:
        ng = (list(no_grad_vars)
              if isinstance(no_grad_vars, (list, tuple, set))
              else [no_grad_vars])
        blocked = {t._uid for t in ng}
        if blocked & {t._uid for t in inputs}:
            raise ValueError("no_grad_vars overlaps inputs")
        if create_graph:
            raise NotImplementedError(
                "no_grad_vars with create_graph=True is not supported")
    tape = current_tape()
    wanted = {t._uid for t in inputs}
    if create_graph:
        if retain_graph is None:
            retain_graph = True
        result_map = _sweep_create_graph(
            tape, _seed_grad_tensors(outputs, grad_outputs), wanted)
        out = []
        for t in inputs:
            g = result_map.get(t._uid)
            if g is None and not allow_unused:
                raise RuntimeError(
                    "One of the differentiated tensors appears to not "
                    "have been used in the graph (set allow_unused=True "
                    "to allow this).")
            out.append(g)
        return out
    grads = _seed_grads(outputs, grad_outputs)
    visited = set()
    result_map = _sweep(tape, grads, accumulate_leaves=False, wanted=wanted,
                        visited=visited, blocked=blocked)
    if not retain_graph:
        tape.remove(visited)
    out = []
    for t in inputs:
        g = result_map.get(t._uid)
        if g is None and not allow_unused:
            raise RuntimeError(
                "One of the differentiated tensors appears to not have been "
                "used in the graph (set allow_unused=True to allow this).")
        out.append(None if g is None else _wrap(g))
    return out


def _seed_grad_tensors(tensors, grad_tensors):
    """Seeds as Tensors (create_graph mode: the whole sweep stays on
    Tensors so every step is recordable)."""
    from paddle_tpu.core.tensor import Tensor
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    elif not isinstance(grad_tensors, (list, tuple)):
        grad_tensors = [grad_tensors]
    grads = {}
    for t, g in zip(tensors, grad_tensors):
        if g is None:
            g = Tensor(jnp.ones(t.shape, t._value.dtype),
                       stop_gradient=True)
        elif not isinstance(g, Tensor):
            g = Tensor(jnp.asarray(g), stop_gradient=True)
        prev = grads.get(t._uid)
        grads[t._uid] = g if prev is None else prev + g
    return grads


def _record_replay(node, cot_tensors, cot_consts):
    """Apply `node`'s vjp as a RECORDED op: re-linearize replay_fn at the
    node's saved inputs and vjp with the (Tensor) cotangents, so the
    result is itself differentiable wrt both the forward inputs (through
    the re-linearization residuals) and the cotangent chain."""
    from paddle_tpu.core.tensor import Tensor
    n_in = len(node.inputs)
    in_ts = list(node.inputs) + list(cot_tensors)
    arrays = [t._value for t in in_ts]

    def f(*arrs):
        _, vjp2 = jax.vjp(node.replay_fn, *arrs[:n_in])
        cots = []
        it = iter(arrs[n_in:])
        for c in cot_consts:
            cots.append(next(it) if c is None else c)
        return tuple(vjp2(tuple(cots)))

    diff_pos = [i for i, t in enumerate(in_ts)
                if not t.stop_gradient
                and jnp.issubdtype(t._value.dtype, jnp.inexact)]

    def f_diff(*diff_arrays):
        av = list(arrays)
        for i, a in zip(diff_pos, diff_arrays):
            av[i] = a
        return f(*av)

    out_flat, vjp3 = jax.vjp(f_diff, *[arrays[i] for i in diff_pos])
    wrapped = [Tensor(a, stop_gradient=not diff_pos) for a in out_flat]
    if diff_pos:
        node2 = TapeNode(
            "grad:" + node.name,
            inputs=[in_ts[i] for i in diff_pos],
            outputs=wrapped, vjp_fn=vjp3,
            out_avals=[(a.shape, a.dtype) for a in out_flat],
            replay_fn=f_diff)     # third-and-higher order recurse
        current_tape().record(node2)
    return wrapped


def _sweep_create_graph(tape, grads, wanted):
    """Reverse sweep where every vjp application is RECORDED (grads are
    Tensors). Mirrors _sweep; nodes lacking a replay_fn (recompute /
    to_static regions) cannot contribute re-differentiable grads and
    raise rather than silently returning wrong second derivatives."""
    from paddle_tpu.core.tensor import Tensor

    result: dict[int, Tensor] = {}
    nodes = list(tape.nodes)   # replay RECORDS new nodes; fixed snapshot
    for node in reversed(nodes):
        if not any(uid in grads for uid in node.out_uids):
            continue
        if node.replay_fn is None:
            raise NotImplementedError(
                f"create_graph=True cannot differentiate through the "
                f"'{node.name}' region (no replay function recorded); "
                "compute the gradient penalty outside recompute/"
                "to_static wrappers or via jax.grad composition.")
        cot_tensors, cot_consts = [], []
        for uid, (shape, dtype) in zip(node.out_uids, node.out_avals):
            g = grads.get(uid)
            if not jnp.issubdtype(dtype, jnp.inexact):
                cot_consts.append(_zero_cotangent(shape, dtype))
                continue
            if g is None:
                g = Tensor(jnp.zeros(shape, dtype), stop_gradient=True)
            cot_tensors.append(g)
            cot_consts.append(None)
        in_grads = _record_replay(node, cot_tensors, cot_consts)
        for t, g in zip(node.inputs, in_grads):
            if g is None:
                continue
            for hook in getattr(t, "_grad_hooks", ()):
                res = hook(g)
                if res is not None:
                    g = res if isinstance(res, Tensor) else Tensor(
                        jnp.asarray(res), stop_gradient=False)
            if t._uid in grads:
                grads[t._uid] = grads[t._uid] + g
            else:
                grads[t._uid] = g
            if t._uid in wanted:
                result[t._uid] = grads[t._uid]
    return result


def _seed_grads(tensors, grad_tensors):
    tensors = tensors if isinstance(tensors, (list, tuple)) else [tensors]
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    elif not isinstance(grad_tensors, (list, tuple)):
        grad_tensors = [grad_tensors]
    grads: dict[int, jax.Array] = {}
    for t, g in zip(tensors, grad_tensors):
        if g is None:
            g_arr = jnp.ones(t.shape, t._value.dtype)
        else:
            g_arr = g._value if hasattr(g, "_value") else jnp.asarray(g)
        grads[t._uid] = grads.get(t._uid, 0) + g_arr
    return grads


def _sweep(tape, grads, accumulate_leaves, wanted=None, visited=None,
           blocked=None):
    """Reverse sweep over tape nodes, returning the final grad map.
    Grad bookkeeping is keyed by tensor uid (monotonic, never reused — id()
    can be recycled by the allocator mid-training-loop)."""
    from paddle_tpu.core.tensor import Tensor

    produced = {uid: n for n in tape.nodes for uid in n.out_uids}
    result: dict[int, jax.Array] = {}
    for node in reversed(tape.nodes):
        if not any(uid in grads for uid in node.out_uids):
            continue
        if visited is not None:
            visited.add(id(node))
        cotangents = []
        for uid, (shape, dtype) in zip(node.out_uids, node.out_avals):
            g = grads.get(uid)
            if g is None:
                g = _zero_cotangent(shape, dtype)
            else:
                g = jnp.asarray(g, dtype) if jnp.issubdtype(
                    dtype, jnp.inexact) else g
            cotangents.append(g)
        in_grads = node.vjp_fn(tuple(cotangents))
        for t, g in zip(node.inputs, in_grads):
            if g is None:
                continue
            if blocked is not None and t._uid in blocked:
                continue           # grad(no_grad_vars=...): cut the edge
            for hook in getattr(t, "_grad_hooks", ()):
                res = hook(_wrap(g))
                if res is not None:
                    g = res._value if isinstance(res, Tensor) else jnp.asarray(res)
            if t._uid in grads:
                grads[t._uid] = grads[t._uid] + g
            else:
                grads[t._uid] = g
            is_leaf = t._uid not in produced
            if wanted is not None and t._uid in wanted:
                result[t._uid] = grads[t._uid]
            if accumulate_leaves and is_leaf and not t.stop_gradient:
                if t.grad is None:
                    t._grad = _wrap(grads[t._uid])
                else:
                    t._grad._value = t._grad._value + g
    if wanted is None:
        return grads
    return result


def _wrap(arr):
    from paddle_tpu.core.tensor import Tensor
    t = Tensor(arr, stop_gradient=True)
    return t
