"""Eager op dispatch: pure jax functions -> Tensor-level ops with autograd.

This is the TPU-native collapse of the reference's entire per-op pipeline
(reference: generated `*_ad_func` from eager_gen.py:251 — record-event, AMP
cast, autograd-meta collection, grad-node creation — then
paddle::experimental::* kernel dispatch in phi/api/lib/kernel_dispatch.cc and
KernelFactory::SelectKernelOrThrowError, phi/core/kernel_factory.cc:215).

Per SURVEY.md §3.1 the whole stack collapses to `tape.record(prim, *args)`:
- kernel selection/codegen        -> XLA (jnp ops are compiled per-shape)
- generated autograd node         -> `jax.vjp` closure captured on the tape
- AMP cast insertion              -> paddle_tpu.amp consults one hook here
- NaN/Inf guard (nan_inf_utils.cc)-> optional check behind FLAGS_check_nan_inf

`defop(name)(fn)` wraps a pure jax-array function into an eager op. A single
registry entry per op (OpDef) replaces the reference's YAML schema + four
code generators (SURVEY.md §1 "single most important design idea").
"""
from __future__ import annotations

import functools
from dataclasses import dataclass, field
from typing import Callable

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu.core import flags
from paddle_tpu.core.tape import TapeNode, current_tape, grad_enabled


@dataclass
class OpDef:
    """One op schema — the registry row that replaces the reference's YAML
    entry (paddle/phi/api/yaml/ops.yaml) feeding four generators."""
    name: str
    fn: Callable                 # pure jax function
    differentiable: bool = True
    amp_policy: str = "promote"  # 'white' (fp16-friendly), 'black', 'promote'
    spmd_note: str = ""          # documentation of sharding behaviour
    custom: bool = False         # user-registered (utils.cpp_extension):
    #                              exempt from the op-harness coverage gate


OP_REGISTRY: dict[str, OpDef] = {}

# amp.debugging installs a callable(op_name, out_arrays) here to count
# executed ops by output dtype (reference: debugging.py operator stats)
OP_STATS_HOOK = None

# static-graph capture (paddle_tpu.static.graph) installs a
# callable(op, args, kwargs) here while a program_guard is active; it
# returns NotImplemented for all-concrete calls (which then execute
# eagerly as usual) and a recorded placeholder result otherwise —
# the deferred-op analog of the reference's static op append
# (python/paddle/base/framework.py append_op)
STATIC_GRAPH_HOOK = None

# amp.debugging installs a callable(op_name)->bool here to narrow the
# NaN/Inf check to TensorCheckerConfig's checked/skipped op lists
NAN_CHECK_FILTER = None


def _is_tensor(x):
    from paddle_tpu.core.tensor import Tensor
    return isinstance(x, Tensor)


def _check_nan_inf(name, arrays):
    if NAN_CHECK_FILTER is not None and not NAN_CHECK_FILTER(name):
        return
    for a in arrays:
        if isinstance(a, jax.core.Tracer):
            # can't concretize under jit tracing; the fused program is
            # checked by the caller on concrete outputs instead
            continue
        if isinstance(a, jax.Array) and jnp.issubdtype(a.dtype, jnp.inexact):
            if bool(jnp.any(~jnp.isfinite(a))):
                msg = f"NaN/Inf detected in output of op '{name}'"
                if flags.get_flag("FLAGS_check_nan_inf_level", 0) > 0:
                    print("WARNING:", msg)
                else:
                    raise FloatingPointError(msg)


def defop(name: str, differentiable: bool = True, amp_policy: str = "promote",
          spmd_note: str = ""):
    """Register + wrap a pure jax function as an eager Tensor op."""

    def deco(fn):
        OP_REGISTRY[name] = OpDef(name, fn, differentiable, amp_policy, spmd_note)

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            return dispatch(OP_REGISTRY[name], args, kwargs)

        wrapper.op_name = name
        wrapper.raw_fn = fn
        return wrapper

    return deco


def dispatch(op: OpDef, args, kwargs):
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu import amp as amp_mod

    if STATIC_GRAPH_HOOK is not None:
        out = STATIC_GRAPH_HOOK(op, args, kwargs)
        if out is not NotImplemented:
            return out

    # AMP autocast hook (reference: eager_gen.py:515 AMP logic in every
    # generated forward).
    if amp_mod.state.enabled():
        args, kwargs = amp_mod.state.cast_args(op, args, kwargs)

    # Flatten (args, kwargs), pulling out Tensor leaves.
    leaves, treedef = jax.tree.flatten((args, kwargs), is_leaf=_is_tensor)
    tensor_idx = [i for i, l in enumerate(leaves) if _is_tensor(l)]
    tensors = [leaves[i] for i in tensor_idx]

    def call_with(arrays):
        lv = list(leaves)
        for i, a in zip(tensor_idx, arrays):
            lv[i] = a
        a2, k2 = jax.tree.unflatten(treedef, lv)
        return op.fn(*a2, **k2)

    need_grad = (
        op.differentiable
        and grad_enabled()
        and any(not t.stop_gradient for t in tensors)
    )

    if not need_grad:
        out = call_with([t._value for t in tensors])
        if OP_STATS_HOOK is not None:
            OP_STATS_HOOK(op.name, jax.tree.flatten(out)[0])
        return _wrap_outputs(op, out, stop_gradient=True)

    diff_pos = [j for j, t in enumerate(tensors)
                if not t.stop_gradient and _is_diff_dtype(t._value.dtype)]
    arrays = [t._value for t in tensors]
    out_treedef = None

    def g(*diff_arrays):
        nonlocal out_treedef
        av = list(arrays)
        for j, a in zip(diff_pos, diff_arrays):
            av[j] = a
        out = call_with(av)
        flat, out_treedef = jax.tree.flatten(out)
        return tuple(flat)

    out_flat, vjp_fn = jax.vjp(g, *[arrays[j] for j in diff_pos])
    result = jax.tree.unflatten(out_treedef, list(out_flat))
    outputs, wrapped = _wrap_outputs(op, result, stop_gradient=False,
                                     return_list=True)
    node = TapeNode(
        op.name,
        inputs=[tensors[j] for j in diff_pos],
        outputs=wrapped,
        vjp_fn=vjp_fn,
        out_avals=[(o.shape, o.dtype) for o in out_flat],
        replay_fn=g,   # re-linearization hook for create_graph=True
    )
    current_tape().record(node)
    if OP_STATS_HOOK is not None:
        OP_STATS_HOOK(op.name, list(out_flat))
    if flags.get_flag("FLAGS_check_nan_inf"):
        _check_nan_inf(op.name, out_flat)
    return outputs


def _is_diff_dtype(dtype) -> bool:
    return jnp.issubdtype(dtype, jnp.inexact)


def _wrap_outputs(op, out, stop_gradient, return_list=False):
    from paddle_tpu.core.tensor import Tensor

    flat, treedef = jax.tree.flatten(out)
    wrapped = []
    for a in flat:
        sg = stop_gradient or not _is_diff_dtype(a.dtype)
        wrapped.append(Tensor(a, stop_gradient=sg))
    result = jax.tree.unflatten(treedef, wrapped)
    if flags.get_flag("FLAGS_check_nan_inf") and stop_gradient:
        _check_nan_inf(op.name, flat)
    if return_list:
        return result, wrapped
    return result
