"""Runtime kernel autotune cache.

Reference: paddle/phi/kernels/autotune/cache.h:97 (AlgorithmsCache — a
per-op hash map from a parameter signature to the measured-best
algorithm) + switch_autotune.cc (step-gated measuring). The TPU-native
version picks Pallas block configurations instead of cuDNN algorithms:

- `choose(kernel, key, candidates, measure, default)` returns the cached
  pick for (kernel, key) if present; otherwise, when measuring is
  possible (real TPU backend, measuring enabled), it times each
  candidate ONCE via the caller-supplied `measure` callback, caches the
  winner, and persists the cache to disk — the next process skips the
  sweep entirely. Off-TPU (or with autotune disabled) it returns
  `default` — the hand-swept constants that were the only option before.
- The on-disk cache (JSON, atomic replace) ships SEEDED with the round-2
  v5e sweep results, so bench-shape calls never pay a sweep.

Env:
  PADDLE_TPU_AUTOTUNE=0/1    enable measuring (default 1 on TPU)
  PADDLE_TPU_AUTOTUNE_CACHE  cache file path
                             (default ~/.cache/paddle_tpu/autotune.json)
"""
from __future__ import annotations

import json
import os
import tempfile
import threading

__all__ = ["choose", "get", "put", "cache_path", "clear_memory",
           "time_fn"]


def time_fn(fn, iters: int = 6) -> float:
    """Median-free simple timer for candidate measurement. Syncs by
    FETCHING a reduced scalar — through the axon dispatch tunnel
    jax.block_until_ready returns before execution finishes
    (BASELINE.md round-3 note), so a value fetch is the only real
    sync."""
    import time as _time

    import jax.numpy as jnp

    def _sync(out):
        leaf = out[0] if isinstance(out, (tuple, list)) else out
        float(jnp.sum(leaf.astype(jnp.float32)))

    _sync(fn())                    # compile + warm
    t0 = _time.perf_counter()
    out = None
    for _ in range(iters):
        out = fn()
    _sync(out)
    return (_time.perf_counter() - t0) / iters

_lock = threading.Lock()
_mem: dict | None = None      # {"kernel|key": config}
_dirty = False

# Round-2 v5e sweep results (BASELINE.md / NOTES_r2.md): these keys use
# the same signature format the kernels generate, so the shipped cache
# covers the bench shapes without a first-run sweep.
_SEED = {
    # flash fwd/bwd short-seq: (512, 512) won IN THE FULL TRAIN STEP
    # (larger q-blocks win in kernel isolation but lose in context).
    # Keys cover the bench family: 400M llama (20 q-heads / 4 kv -> GQA
    # fold rep=5, q=5*2048) and 1b (32/4 -> q=8*2048), plus the plain
    # unfolded shapes.
    "flash_fwd|q10240_s2048_d64_bf16_c1_g": [512, 512],
    "flash_bwd|q10240_s2048_d64_bf16_c1_g": [512, 512],
    "flash_fwd|q16384_s2048_d64_bf16_c1_g": [512, 512],
    "flash_bwd|q16384_s2048_d64_bf16_c1_g": [512, 512],
    "flash_fwd|q40960_s8192_d64_bf16_c1_g": [512, 512],
    "flash_bwd|q40960_s8192_d64_bf16_c1_g": [512, 512],
    "flash_fwd|q2048_s2048_d64_bf16_c1": [512, 512],
    "flash_bwd|q2048_s2048_d64_bf16_c1": [512, 512],
    "flash_fwd|q1024_s1024_d64_bf16_c1": [512, 512],
    "flash_bwd|q1024_s1024_d64_bf16_c1": [512, 512],
    # streamed-kv long-seq kernels want WIDE k blocks (16k: 9.2k->13.9k
    # tok/s; 32k: 5.0k->8.5k); the VMEM cap in _stream_block_k still
    # applies on top of this target
    "flash_stream_bk|s8192_bf16": 2048,
    "flash_stream_bk|s16384_bf16": 2048,
    "flash_stream_bk|s32768_bf16": 2048,
}


def cache_path() -> str:
    p = os.environ.get("PADDLE_TPU_AUTOTUNE_CACHE")
    if p:
        return p
    return os.path.join(os.path.expanduser("~"), ".cache", "paddle_tpu",
                        "autotune.json")


def _load() -> dict:
    global _mem
    if _mem is not None:
        return _mem
    data = dict(_SEED)
    try:
        with open(cache_path()) as f:
            data.update(json.load(f))
    except (FileNotFoundError, json.JSONDecodeError, OSError):
        pass
    _mem = data
    return _mem


def _persist() -> None:
    global _dirty
    if not _dirty:
        return
    path = cache_path()
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path),
                                   prefix=".autotune_")
        # persist only entries that DIFFER from the shipped seeds —
        # dumping seeds would permanently shadow improved seeds from a
        # future package version
        data = {k: v for k, v in _mem.items() if _SEED.get(k) != v}
        with os.fdopen(fd, "w") as f:
            json.dump(data, f, indent=1, sort_keys=True)
        os.replace(tmp, path)          # atomic vs concurrent processes
        _dirty = False
    except OSError:
        pass                           # read-only FS: stay in-memory


def clear_memory() -> None:
    """Drop the in-process cache (tests)."""
    global _mem, _dirty
    with _lock:
        _mem = None
        _dirty = False


def get(kernel: str, key: str):
    with _lock:
        v = _load().get(f"{kernel}|{key}")
        return tuple(v) if isinstance(v, list) else v


def put(kernel: str, key: str, config) -> None:
    global _dirty
    with _lock:
        _load()[f"{kernel}|{key}"] = (list(config)
                                      if isinstance(config, (tuple, list))
                                      else config)
        _dirty = True
        _persist()


def _measuring_enabled() -> bool:
    flag = os.environ.get("PADDLE_TPU_AUTOTUNE")
    if flag is not None:
        return flag not in ("0", "false", "False")
    try:
        import jax
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def choose(kernel: str, key: str, candidates, measure, default):
    """Cached pick for (kernel, key); sweep-once via `measure(cfg) ->
    seconds` when measuring is possible, else `default`.

    `measure` runs each candidate standalone on concrete data of the
    call's shapes — it is invoked OUTSIDE any trace, so callers may use
    choose() at trace time (block sizes are static). A candidate that
    raises is skipped (e.g. a block config Mosaic rejects for this
    shape)."""
    cached = get(kernel, key)
    if cached is not None:
        return cached
    if not _measuring_enabled() or measure is None:
        return default
    best, best_t = None, float("inf")
    for cfg in candidates:
        try:
            t = measure(cfg)
        except Exception:  # lint: disable=silent-swallow -- a candidate config the compiler rejects for this shape is skipped by design (see docstring)
            continue
        if t < best_t:
            best, best_t = cfg, t
    if best is None:
        # cache the default so an all-candidates-fail shape is not
        # re-swept on every trace and every process
        best = default
    put(kernel, key, best)
    return best
