"""The paddle_tpu Tensor: a paddle-flavoured eager handle over jax.Array.

TPU-native rebuild of the reference's eager Tensor (reference:
paddle/fluid/pybind/eager.cc Tensor type + eager_method.cc tensor methods;
phi::DenseTensor paddle/phi/core/dense_tensor.h:37). Instead of a C++ tensor
with allocations and a pybind bridge, this wraps an immutable `jax.Array`
(device memory managed by PjRt) plus the eager-mode bookkeeping the array
itself cannot carry: stop_gradient, accumulated .grad, hooks, name, and an
inplace version counter (reference: tensor_wrapper.h inplace version checks).

Tensor is registered as a jax pytree node so `jax.jit`-traced functions can
take and return Tensors directly (the to_static bridge, SURVEY.md §3.3).

Most numeric methods (reshape/sum/matmul/...) are monkey-patched onto this
class by paddle_tpu.tensor (mirroring the reference's monkey_patch_math_tensor
pattern in python/paddle/tensor/__init__.py) to keep this module cycle-free.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu.core import dtype as dtypes
from paddle_tpu.core.tape import backward as _tape_backward

_tensor_counter = [0]


class Tensor:
    __slots__ = ("_value", "_stop_gradient", "_grad", "_grad_hooks", "name",
                 "_version", "persistable", "_uid", "__weakref__")

    def __init__(self, data, dtype=None, stop_gradient=True, name=None):
        dt = dtypes.convert_dtype(dtype)
        if isinstance(data, Tensor):
            arr = data._value
            if dt is not None and arr.dtype != dt:
                arr = arr.astype(dt)
        elif isinstance(data, jax.Array) or isinstance(data, jax.core.Tracer):
            arr = data if dt is None or data.dtype == dt else data.astype(dt)
        else:
            arr = jnp.asarray(data, dtype=dt)
        self._value = arr
        self._stop_gradient = bool(stop_gradient)
        self._grad = None
        self._grad_hooks = []
        self._version = 0
        self.persistable = False
        _tensor_counter[0] += 1
        self._uid = _tensor_counter[0]
        if name is None:
            name = f"generated_tensor_{self._uid}"
        self.name = name

    # -- basic properties --------------------------------------------------
    @property
    def value(self):
        return self._value

    @property
    def shape(self):
        return list(self._value.shape)

    @property
    def ndim(self):
        return self._value.ndim

    # paddle calls this .rank in places
    @property
    def rank(self):
        return self._value.ndim

    @property
    def size(self):
        return int(np.prod(self._value.shape)) if self._value.shape else 1

    @property
    def dtype(self):
        return np.dtype(self._value.dtype)

    @property
    def place(self):
        try:
            devs = self._value.devices()
            return next(iter(devs))
        except Exception:
            return None

    @property
    def stop_gradient(self):
        return self._stop_gradient

    @stop_gradient.setter
    def stop_gradient(self, v):
        self._stop_gradient = bool(v)

    @property
    def grad(self):
        return self._grad

    @grad.setter
    def grad(self, g):
        self._grad = g

    @property
    def is_leaf(self):
        return True  # refined by tape bookkeeping; leaves are the common case

    @property
    def T(self):
        from paddle_tpu import tensor as T
        return T.transpose(self, list(range(self.ndim))[::-1])

    # -- conversion --------------------------------------------------------
    def numpy(self):
        return np.asarray(self._value)

    def item(self, *args):
        if args:
            return np.asarray(self._value).item(*args)
        return np.asarray(self._value).item()

    def tolist(self):
        return np.asarray(self._value).tolist()

    def astype(self, dtype):
        from paddle_tpu.tensor.manipulation import cast
        return cast(self, dtype)

    def cast(self, dtype):
        return self.astype(dtype)

    def __dlpack__(self, *a, **k):
        return self._value.__dlpack__(*a, **k)

    # -- autograd ----------------------------------------------------------
    def backward(self, grad_tensor=None, retain_graph=False):
        _tape_backward([self], [grad_tensor] if grad_tensor is not None else None,
                       retain_graph=retain_graph)

    def clear_grad(self):
        self._grad = None

    def clear_gradient(self, set_to_zero=False):
        if set_to_zero and self._grad is not None:
            self._grad._value = jnp.zeros_like(self._grad._value)
        else:
            self._grad = None

    def register_hook(self, hook):
        self._grad_hooks.append(hook)

        class _Removable:
            def remove(_s):
                try:
                    self._grad_hooks.remove(hook)
                except ValueError:
                    pass
        return _Removable()

    def detach(self):
        t = Tensor(self._value, stop_gradient=True, name=self.name + "_detached")
        return t

    def detach_(self):
        self._stop_gradient = True
        return self

    def clone(self):
        from paddle_tpu.tensor.manipulation import clone
        return clone(self)

    # -- mutation (eager-only; bumps version counter) ----------------------
    def set_value(self, value):
        """Replace the underlying buffer in place (reference:
        eager_method.cc set_value). Allowed on leaves / under no_grad."""
        arr = value._value if isinstance(value, Tensor) else jnp.asarray(
            value, dtype=self._value.dtype)
        if tuple(arr.shape) != tuple(self._value.shape):
            raise ValueError(
                f"set_value shape mismatch: {arr.shape} vs {self._value.shape}")
        self._value = arr.astype(self._value.dtype)
        self._version += 1

    def _inplace_assign(self, new_value_tensor):
        from paddle_tpu.core.tape import grad_enabled
        if grad_enabled() and (not self._stop_gradient
                               or not new_value_tensor.stop_gradient):
            # Rebinding the buffer would detach this tensor from the tape
            # node that produced new_value, silently dropping gradients
            # (reference guards this with inplace version checks,
            # tensor_wrapper.h). Fail loudly instead.
            raise RuntimeError(
                "in-place operation on a tensor that requires grad is not "
                "supported on the eager tape; use the out-of-place variant "
                "or wrap the mutation in paddle_tpu.no_grad()")
        self._value = new_value_tensor._value
        self._version += 1
        return self

    def copy_(self, other):
        self.set_value(other)
        return self

    def zero_(self):
        self._value = jnp.zeros_like(self._value)
        self._version += 1
        return self

    def fill_(self, v):
        self._value = jnp.full_like(self._value, v)
        self._version += 1
        return self

    # -- python protocol ---------------------------------------------------
    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-d tensor")
        return self._value.shape[0]

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def _concretize(self, caster, what):
        import jax
        try:
            return caster(np.asarray(self._value))
        except (jax.errors.ConcretizationTypeError,
                jax.errors.TracerArrayConversionError,
                jax.errors.TracerBoolConversionError) as e:
            raise TypeError(
                f"{what} of a traced Tensor inside @to_static/jit: the "
                "value is only known at run time. For data-dependent "
                "control flow use paddle_tpu.jit.cond / "
                "paddle_tpu.jit.while_loop (or let to_static's AST "
                "rewrite handle plain `if`/`while` on Tensor "
                "predicates); for host access move the read outside "
                "the compiled function.") from e

    def __float__(self):
        return self._concretize(float, "float()")

    def __int__(self):
        return self._concretize(int, "int()")

    def __bool__(self):
        return self._concretize(bool, "bool()")

    def __index__(self):
        return self._concretize(int, "index use")

    def __format__(self, spec):
        if self.ndim == 0:
            return format(self.item(), spec)
        return format(str(self), spec)

    def __hash__(self):
        return id(self)

    def __repr__(self):
        sg = self._stop_gradient
        try:
            data = np.asarray(self._value)
            body = np.array2string(data, precision=6, separator=", ")
        except Exception:
            body = f"<traced {self._value}>"
        return (f"Tensor(shape={self.shape}, dtype={dtypes.dtype_name(self.dtype)}, "
                f"stop_gradient={sg},\n       {body})")

    __str__ = __repr__

    # numpy interop
    def __array__(self, dtype=None):
        a = np.asarray(self._value)
        return a.astype(dtype) if dtype is not None else a


class Parameter(Tensor):
    """Trainable tensor (reference: python/paddle/base/framework.py
    EagerParamBase). stop_gradient defaults to False; `trainable` mirrors it."""

    def __init__(self, data, dtype=None, name=None, trainable=True):
        super().__init__(data, dtype=dtype, stop_gradient=not trainable,
                         name=name)
        self.persistable = True

    @property
    def trainable(self):
        return not self._stop_gradient

    @trainable.setter
    def trainable(self, v):
        self._stop_gradient = not v

    def __repr__(self):
        return "Parameter containing:\n" + super().__repr__()


def _tensor_flatten(t: Tensor):
    return (t._value,), (t._stop_gradient, t.name)


def _tensor_unflatten(aux, children, cls=None):
    sg, name = aux
    t = (cls or Tensor).__new__(cls or Tensor)
    t._value = children[0]
    t._stop_gradient = sg
    t._grad = None
    t._grad_hooks = []
    t._version = 0
    t.persistable = cls is Parameter
    _tensor_counter[0] += 1
    t._uid = _tensor_counter[0]
    t.name = name
    return t


jax.tree_util.register_pytree_node(Tensor, _tensor_flatten, _tensor_unflatten)
jax.tree_util.register_pytree_node(
    Parameter,
    lambda p: ((p._value,), (p._stop_gradient, p.name)),
    lambda aux, ch: _tensor_unflatten(aux, ch, cls=Parameter),
)


def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    """paddle.to_tensor equivalent (reference:
    python/paddle/tensor/creation.py to_tensor)."""
    return Tensor(data, dtype=dtype, stop_gradient=stop_gradient)


def is_tensor(x):
    return isinstance(x, Tensor)
