"""Optimizers (reference: python/paddle/optimizer/optimizer.py + adam.py,
adamw.py, sgd.py, momentum.py, rmsprop.py, adagrad.py, lamb.py).

Two execution paths share ONE update rule per optimizer:
- eager `opt.step()`: per-parameter jitted rule application (the reference's
  C++ adam kernels become one XLA executable per shape, cached);
- functional `opt.init_state_arrays()` / `opt.apply_gradients_arrays()`:
  pure pytree->pytree update used inside fused jit train steps and under
  shard_map for ZeRO-style sharded updates (SURVEY.md §2.5 sharding).

Master weights (`multi_precision`) follow the reference's AMP-O2 contract:
state keeps an fp32 copy for low-precision params.
"""
from __future__ import annotations

import functools
from typing import Callable

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu.core.tensor import Tensor, Parameter
from paddle_tpu.core.tape import no_grad
# NOTE: ZeRO-style sharded-update composition (module docstring) should
# import shard_map from paddle_tpu.core.jax_compat — the bare jax
# spellings are version-fragile (tools/check_jax_compat.py enforces it)
from paddle_tpu.optimizer import lr as lr_mod
from paddle_tpu.optimizer.lr import LRScheduler


def _global_norm_clip(grads, clip_norm):
    flat = [g for g in grads if g is not None]
    if not flat:
        return grads
    gn = jnp.sqrt(sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                      for g in flat))
    scale = jnp.minimum(1.0, clip_norm / jnp.maximum(gn, 1e-12))
    return [None if g is None else (g * scale).astype(g.dtype)
            for g in grads]


class Optimizer:
    def __init__(self, learning_rate=0.001, parameters=None,
                 weight_decay=None, grad_clip=None, multi_precision=False,
                 name=None):
        if parameters is None:
            raise ValueError(
                "paddle_tpu optimizers require an explicit parameter list")
        self._parameter_list = list(parameters)
        self._learning_rate = learning_rate
        self._grad_clip = grad_clip
        self._multi_precision = multi_precision
        if isinstance(weight_decay, (int, float)) or weight_decay is None:
            self._weight_decay = float(weight_decay or 0.0)
            self._decay_mode = "l2"
        else:  # L1Decay/L2Decay objects
            self._weight_decay = float(getattr(weight_decay, "_coeff",
                                               getattr(weight_decay,
                                                       "coeff", 0.0)))
            self._decay_mode = "l1" if type(weight_decay).__name__ == \
                "L1Decay" else "l2"
        self._states: dict[int, dict] = {}
        self._step_count = 0
        self._rule_jit = jax.jit(self._rule_with_state)

    # ---- subclass API ----------------------------------------------------
    def _init_state(self, p_arr) -> dict:
        return {}

    def _rule(self, p, g, state, lr, wd):
        """Return (new_p, new_state). `wd` is the weight-decay coefficient
        for THIS parameter (0.0 when excluded by apply_decay_param_fun)."""
        raise NotImplementedError

    def _decay_term(self, p, wd):
        """L2 adds wd*p to the grad; L1 adds wd*sign(p) (reference:
        python/paddle/regularizer.py L1Decay/L2Decay)."""
        if self._decay_mode == "l1":
            return wd * jnp.sign(p)
        return wd * p

    def _wd_for(self, p):
        fn = getattr(self, "_apply_decay_fun", None)
        if fn is not None and not fn(p.name):
            return 0.0
        return self._weight_decay

    # ---- helpers ---------------------------------------------------------
    def _rule_with_state(self, p, g, state, lr, wd):
        master = state.get("master") if self._multi_precision else None
        new_p, new_state = self._rule(
            master if master is not None else p, g, state, lr, wd)
        if master is not None:
            new_state = dict(new_state)
            new_state["master"] = new_p
            new_p = new_p.astype(p.dtype)
        return new_p, new_state

    def _lr_value(self):
        if isinstance(self._learning_rate, LRScheduler):
            return float(self._learning_rate())
        return float(self._learning_rate)

    def get_lr(self):
        return self._lr_value()

    def set_lr(self, value):
        if isinstance(self._learning_rate, LRScheduler):
            raise RuntimeError("optimizer's learning rate is a scheduler; "
                               "call scheduler.step() instead")
        self._learning_rate = float(value)

    def set_lr_scheduler(self, scheduler):
        self._learning_rate = scheduler

    # ---- eager step ------------------------------------------------------
    @no_grad()
    def step(self):
        self._step_count += 1
        lr = jnp.asarray(self._lr_value(), jnp.float32)
        params = [p for p in self._parameter_list
                  if (not p.stop_gradient) and p.grad is not None]
        grads = [p.grad._value for p in params]
        if self._grad_clip is not None:
            cn = getattr(self._grad_clip, "clip_norm", None)
            if cn is not None and type(self._grad_clip).__name__ == \
                    "ClipGradByGlobalNorm":
                grads = _global_norm_clip(grads, cn)
            elif type(self._grad_clip).__name__ == "ClipGradByNorm":
                grads = [g if g is None else _global_norm_clip([g], cn)[0]
                         for g in grads]
            elif type(self._grad_clip).__name__ == "ClipGradByValue":
                grads = [jnp.clip(g, self._grad_clip.min,
                                  self._grad_clip.max) for g in grads]
        for p, g in zip(params, grads):
            sid = id(p)
            if sid not in self._states:
                st = self._init_state(p._value)
                if self._multi_precision and p._value.dtype != jnp.float32:
                    st["master"] = p._value.astype(jnp.float32)
                self._states[sid] = st
            new_p, new_state = self._rule_jit(
                p._value, g, self._states[sid], lr,
                jnp.asarray(self._wd_for(p), jnp.float32))
            p._value = new_p
            self._states[sid] = new_state

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        from paddle_tpu.static.graph import _StaticVar, current_program
        if isinstance(loss, _StaticVar):
            # static mode (reference: optimizer.minimize appends the
            # backward + update ops): register the training directive;
            # Executor.run computes grads in the jitted replay and
            # drives this optimizer's eager step()
            prog = current_program()
            if prog is None:
                raise RuntimeError(
                    "minimize(static loss) outside a program_guard")
            prog.minimizers.append((self, loss))
            return None, None
        loss.backward()
        self.step()
        self.clear_grad()
        return None, None

    def clear_grad(self, set_to_zero=False):
        for p in self._parameter_list:
            p.clear_gradient(set_to_zero)

    clear_gradients = clear_grad

    # ---- state dict ------------------------------------------------------
    def state_dict(self):
        out = {"_step_count": self._step_count}
        for i, p in enumerate(self._parameter_list):
            st = self._states.get(id(p))
            if st:
                for k, v in st.items():
                    out[f"param{i}.{k}"] = Tensor(v) if isinstance(
                        v, jax.Array) else v
        if isinstance(self._learning_rate, LRScheduler):
            out["LR_Scheduler"] = self._learning_rate.state_dict()
        return out

    def set_state_dict(self, sd):
        self._step_count = sd.get("_step_count", 0)
        if "LR_Scheduler" in sd and isinstance(self._learning_rate,
                                               LRScheduler):
            self._learning_rate.set_state_dict(sd["LR_Scheduler"])
        for i, p in enumerate(self._parameter_list):
            st = {}
            prefix = f"param{i}."
            for k, v in sd.items():
                if isinstance(k, str) and k.startswith(prefix):
                    st[k[len(prefix):]] = v._value if isinstance(
                        v, Tensor) else v
            if st:
                self._states[id(p)] = st

    # ---- functional path (for jit train steps / sharded updates) --------
    def init_state_arrays(self, params: dict):
        state = {}
        for name, arr in params.items():
            st = self._init_state(arr)
            if self._multi_precision and arr.dtype != jnp.float32:
                st["master"] = arr.astype(jnp.float32)
            state[name] = st
        return state

    def apply_gradients_arrays(self, params: dict, grads: dict, state: dict,
                               lr):
        """Pure: returns (new_params, new_state). Used inside jit."""
        if self._grad_clip is not None and type(
                self._grad_clip).__name__ == "ClipGradByGlobalNorm":
            names = list(grads)
            clipped = _global_norm_clip([grads[n] for n in names],
                                        self._grad_clip.clip_norm)
            grads = dict(zip(names, clipped))
        new_params, new_state = {}, {}
        for name, p in params.items():
            g = grads.get(name)
            if g is None:
                new_params[name] = p
                new_state[name] = state[name]
                continue
            wd = self._weight_decay
            fn = getattr(self, "_apply_decay_fun", None)
            if fn is not None and not fn(name):
                wd = 0.0
            np_, ns = self._rule_with_state(p, g, state[name], lr, wd)
            new_params[name] = np_
            new_state[name] = ns
        return new_params, new_state


class SGD(Optimizer):
    """Reference: python/paddle/optimizer/sgd.py."""

    def _rule(self, p, g, state, lr, wd):
        g = g.astype(p.dtype)
        g = g + self._decay_term(p, wd).astype(p.dtype)
        return p - lr.astype(p.dtype) * g, {k: v for k, v in state.items()
                                            if k == "master"}


class Momentum(Optimizer):
    """Reference: python/paddle/optimizer/momentum.py."""

    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 multi_precision=False, rescale_grad=1.0,
                 use_multi_tensor=False, name=None):
        self._momentum = momentum
        self._nesterov = use_nesterov
        # rescale_grad multiplies incoming grads (reference momentum.py);
        # use_multi_tensor is implicit under XLA fusion
        self._rescale_grad = float(rescale_grad)
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, name)

    def _init_state(self, p):
        return {"velocity": jnp.zeros(p.shape, jnp.float32)}

    def _rule(self, p, g, state, lr, wd):
        g = g.astype(jnp.float32)
        if self._rescale_grad != 1.0:
            g = g * self._rescale_grad
        g = g + self._decay_term(p.astype(jnp.float32), wd)
        v = self._momentum * state["velocity"] + g
        if self._nesterov:
            upd = g + self._momentum * v
        else:
            upd = v
        new_p = p - (lr * upd).astype(p.dtype)
        return new_p, {"velocity": v}


class Adam(Optimizer):
    """Reference: python/paddle/optimizer/adam.py."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, lazy_mode=False, multi_precision=False,
                 use_multi_tensor=False, amsgrad=False, name=None):
        # use_multi_tensor: accepted for reference parity; XLA fuses the
        # whole update program, so multi-tensor batching is implicit
        self._beta1 = beta1
        self._beta2 = beta2
        self._eps = epsilon
        self._amsgrad = amsgrad
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, name)

    def _init_state(self, p):
        st = {"moment1": jnp.zeros(p.shape, jnp.float32),
              "moment2": jnp.zeros(p.shape, jnp.float32),
              "beta1_pow": jnp.ones((), jnp.float32),
              "beta2_pow": jnp.ones((), jnp.float32)}
        if self._amsgrad:
            st["moment2_max"] = jnp.zeros(p.shape, jnp.float32)
        return st

    def _decoupled(self):
        return False

    def _rule(self, p, g, state, lr, wd):
        pf = p.astype(jnp.float32)
        g = g.astype(jnp.float32)
        if not self._decoupled():
            g = g + self._decay_term(pf, wd)
        b1p = state["beta1_pow"] * self._beta1
        b2p = state["beta2_pow"] * self._beta2
        m1 = self._beta1 * state["moment1"] + (1 - self._beta1) * g
        m2 = self._beta2 * state["moment2"] + (1 - self._beta2) * g * g
        new_state = {"moment1": m1, "moment2": m2, "beta1_pow": b1p,
                     "beta2_pow": b2p}
        if self._amsgrad:
            m2h = jnp.maximum(state["moment2_max"], m2)
            new_state["moment2_max"] = m2h
        else:
            m2h = m2
        m1_hat = m1 / (1 - b1p)
        m2_hat = m2h / (1 - b2p)
        upd = m1_hat / (jnp.sqrt(m2_hat) + self._eps)
        if self._decoupled():
            upd = upd + wd * pf
        new_p = (pf - lr * upd).astype(p.dtype)
        return new_p, new_state


class AdamW(Adam):
    """Decoupled weight decay (reference: python/paddle/optimizer/adamw.py)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=0.01,
                 lr_ratio=None, apply_decay_param_fun=None, grad_clip=None,
                 lazy_mode=False, multi_precision=False, amsgrad=False,
                 name=None):
        self._apply_decay_fun = apply_decay_param_fun
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         weight_decay, grad_clip, lazy_mode, multi_precision,
                         amsgrad, name)

    def _decoupled(self):
        return True


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None, name=None,
                 initial_accumulator_value=0.0, multi_precision=False):
        self._eps = epsilon
        self._init_acc = initial_accumulator_value
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, name)

    def _init_state(self, p):
        return {"moment": jnp.full(p.shape, self._init_acc, jnp.float32)}

    def _rule(self, p, g, state, lr, wd):
        g = g.astype(jnp.float32)
        g = g + self._decay_term(p.astype(jnp.float32), wd)
        acc = state["moment"] + g * g
        new_p = (p.astype(jnp.float32) -
                 lr * g / (jnp.sqrt(acc) + self._eps)).astype(p.dtype)
        return new_p, {"moment": acc}


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=False, name=None):
        self._rho = rho
        self._eps = epsilon
        self._momentum = momentum
        self._centered = centered
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, name)

    def _init_state(self, p):
        return {"mean_square": jnp.zeros(p.shape, jnp.float32),
                "mean_grad": jnp.zeros(p.shape, jnp.float32),
                "momentum_acc": jnp.zeros(p.shape, jnp.float32)}

    def _rule(self, p, g, state, lr, wd):
        g = g.astype(jnp.float32)
        g = g + self._decay_term(p.astype(jnp.float32), wd)
        ms = self._rho * state["mean_square"] + (1 - self._rho) * g * g
        if self._centered:
            mg = self._rho * state["mean_grad"] + (1 - self._rho) * g
            denom = jnp.sqrt(ms - mg * mg + self._eps)
        else:
            mg = state["mean_grad"]
            denom = jnp.sqrt(ms + self._eps)
        mom = self._momentum * state["momentum_acc"] + lr * g / denom
        new_p = (p.astype(jnp.float32) - mom).astype(p.dtype)
        return new_p, {"mean_square": ms, "mean_grad": mg,
                       "momentum_acc": mom}


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         False, name)

    def _init_state(self, p):
        return {"moment": jnp.zeros(p.shape, jnp.float32),
                "inf_norm": jnp.zeros(p.shape, jnp.float32),
                "beta1_pow": jnp.ones((), jnp.float32)}

    def _rule(self, p, g, state, lr, wd):
        g = g.astype(jnp.float32)
        g = g + self._decay_term(p.astype(jnp.float32), wd)
        b1p = state["beta1_pow"] * self._beta1
        m = self._beta1 * state["moment"] + (1 - self._beta1) * g
        u = jnp.maximum(self._beta2 * state["inf_norm"], jnp.abs(g))
        new_p = (p.astype(jnp.float32) -
                 lr / (1 - b1p) * m / (u + self._eps)).astype(p.dtype)
        return new_p, {"moment": m, "inf_norm": u, "beta1_pow": b1p}


class Lamb(Optimizer):
    """Reference: python/paddle/optimizer/lamb.py."""

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-6, parameters=None,
                 grad_clip=None, exclude_from_weight_decay_fn=None,
                 multi_precision=False, always_adapt=False, name=None):
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon
        self._exclude_fn = exclude_from_weight_decay_fn
        super().__init__(learning_rate, parameters, lamb_weight_decay,
                         grad_clip, multi_precision, name)

    def _wd_for(self, p):
        # Lamb's exclude hook receives the parameter itself (reference:
        # optimizer/lamb.py exclude_from_weight_decay_fn)
        if self._exclude_fn is not None and self._exclude_fn(p):
            return 0.0
        return self._weight_decay

    def _init_state(self, p):
        return {"moment1": jnp.zeros(p.shape, jnp.float32),
                "moment2": jnp.zeros(p.shape, jnp.float32),
                "beta1_pow": jnp.ones((), jnp.float32),
                "beta2_pow": jnp.ones((), jnp.float32)}

    def _rule(self, p, g, state, lr, wd):
        pf = p.astype(jnp.float32)
        g = g.astype(jnp.float32)
        b1p = state["beta1_pow"] * self._beta1
        b2p = state["beta2_pow"] * self._beta2
        m1 = self._beta1 * state["moment1"] + (1 - self._beta1) * g
        m2 = self._beta2 * state["moment2"] + (1 - self._beta2) * g * g
        r = m1 / (1 - b1p) / (jnp.sqrt(m2 / (1 - b2p)) + self._eps)
        r = r + wd * pf
        w_norm = jnp.linalg.norm(pf)
        r_norm = jnp.linalg.norm(r)
        trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        new_p = (pf - lr * trust * r).astype(p.dtype)
        return new_p, {"moment1": m1, "moment2": m2, "beta1_pow": b1p,
                       "beta2_pow": b2p}


class L1Decay:
    def __init__(self, coeff=0.0):
        self._coeff = coeff


class L2Decay:
    def __init__(self, coeff=0.0):
        self._coeff = coeff


class Adadelta(Optimizer):
    """(reference: python/paddle/optimizer/adadelta.py)."""

    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None,
                 multi_precision=False, name=None):
        self._rho = rho
        self._eps = epsilon
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, name)

    def _init_state(self, p):
        return {"avg_squared_grad": jnp.zeros(p.shape, jnp.float32),
                "avg_squared_update": jnp.zeros(p.shape, jnp.float32)}

    def _rule(self, p, g, state, lr, wd):
        g = g.astype(jnp.float32)
        g = g + self._decay_term(p.astype(jnp.float32), wd)
        asg = self._rho * state["avg_squared_grad"] + (1 - self._rho) * g * g
        update = (g * jnp.sqrt(state["avg_squared_update"] + self._eps)
                  / jnp.sqrt(asg + self._eps))
        asu = (self._rho * state["avg_squared_update"]
               + (1 - self._rho) * update * update)
        new_p = (p.astype(jnp.float32) - lr * update).astype(p.dtype)
        return new_p, {"avg_squared_grad": asg, "avg_squared_update": asu}


class Rprop(Optimizer):
    """Resilient backprop — sign-based per-weight step sizes
    (reference: python/paddle/optimizer/rprop.py)."""

    def __init__(self, learning_rate=0.001, learning_rate_range=(1e-05, 50),
                 parameters=None, etas=(0.5, 1.2), grad_clip=None,
                 multi_precision=False, name=None):
        self._lr_min, self._lr_max = learning_rate_range
        self._eta_neg, self._eta_pos = etas
        self._init_lr = learning_rate
        super().__init__(learning_rate, parameters, None, grad_clip,
                         multi_precision, name)

    def _init_state(self, p):
        return {"prev_grad": jnp.zeros(p.shape, jnp.float32),
                "step_size": jnp.full(p.shape, self._init_lr, jnp.float32)}

    def _rule(self, p, g, state, lr, wd):
        g = g.astype(jnp.float32)
        sign = jnp.sign(g * state["prev_grad"])
        factor = jnp.where(sign > 0, self._eta_pos,
                           jnp.where(sign < 0, self._eta_neg, 1.0))
        step = jnp.clip(state["step_size"] * factor, self._lr_min,
                        self._lr_max)
        # on sign change, grad is zeroed (no step) per classic Rprop-
        g_eff = jnp.where(sign < 0, 0.0, g)
        new_p = (p.astype(jnp.float32)
                 - jnp.sign(g_eff) * step).astype(p.dtype)
        return new_p, {"prev_grad": g_eff, "step_size": step}


class ASGD(Optimizer):
    """Averaged SGD (reference: python/paddle/optimizer/asgd.py — running
    average of iterates over a window)."""

    def __init__(self, learning_rate=0.001, batch_num=1, parameters=None,
                 weight_decay=None, grad_clip=None, multi_precision=False,
                 name=None):
        self._batch_num = batch_num
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, name)

    def _init_state(self, p):
        return {"d": jnp.zeros(p.shape, jnp.float32),
                "ys": jnp.zeros((self._batch_num,) + tuple(p.shape),
                                jnp.float32),
                "step": jnp.zeros((), jnp.float32)}

    def _rule(self, p, g, state, lr, wd):
        g = g.astype(jnp.float32)
        g = g + self._decay_term(p.astype(jnp.float32), wd)
        idx = (state["step"] % self._batch_num).astype(jnp.int32)
        old_y = state["ys"][idx]
        d = state["d"] - old_y + g
        ys = state["ys"].at[idx].set(g)
        n = jnp.minimum(state["step"] + 1, float(self._batch_num))
        new_p = (p.astype(jnp.float32) - lr * d / n).astype(p.dtype)
        return new_p, {"d": d, "ys": ys, "step": state["step"] + 1}


class LBFGS(Optimizer):
    """Limited-memory BFGS (reference: python/paddle/optimizer/lbfgs.py).

    Unlike the per-parameter rule optimizers, LBFGS needs the closure
    re-evaluating the loss; `step(closure)` runs strong-Wolfe-free
    backtracking line search over the two-loop-recursion direction on the
    CONCATENATED parameter vector (the reference flattens the same way).
    """

    def __init__(self, learning_rate=1.0, max_iter=20, max_eval=None,
                 tolerance_grad=1e-07, tolerance_change=1e-09,
                 history_size=100,
                 line_search_fn=None, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         False, name)
        self._max_iter = max_iter
        self._max_eval = max_eval
        self._tol_grad = tolerance_grad
        self._tol_change = tolerance_change
        self._hist = history_size
        self._line_search_fn = line_search_fn
        self._s, self._y = [], []

    def _flat(self):
        return jnp.concatenate([p._value.astype(jnp.float32).ravel()
                                for p in self._parameter_list])

    def _unflat_set(self, vec):
        off = 0
        for p in self._parameter_list:
            n = int(np.prod(p._value.shape)) if p._value.shape else 1
            p._value = vec[off:off + n].reshape(p._value.shape).astype(
                p._value.dtype)
            off += n

    def _grad_flat(self):
        gs = []
        for p in self._parameter_list:
            g = p.grad._value if p.grad is not None else jnp.zeros_like(
                p._value)
            gs.append(g.astype(jnp.float32).ravel())
        return jnp.concatenate(gs)

    def step(self, closure=None):
        """(torch/paddle LBFGS semantics: with line_search_fn=None, take
        fixed lr-sized quasi-Newton steps — first iteration scaled by
        min(1, 1/|g|_1); with 'strong_wolfe', a sufficient-decrease
        backtracking search that REVERTS when no decrease is found.)"""
        if closure is None:
            raise ValueError("LBFGS.step requires a closure returning the "
                             "loss (reference lbfgs.py same contract)")

        def eval_closure():
            self.clear_grad()
            loss = closure()
            g = self._grad_flat()
            if self._weight_decay:
                g = g + self._weight_decay * self._flat()
            return loss, g

        loss, g = eval_closure()
        f_prev = float(loss.numpy() if hasattr(loss, "numpy") else loss)
        n_evals = 0
        max_eval = self._max_eval or self._max_iter * 5 // 4
        for it in range(self._max_iter):
            if float(jnp.max(jnp.abs(g))) <= self._tol_grad:
                break
            # two-loop recursion
            q = g
            alphas = []
            for s_v, y_v in zip(reversed(self._s), reversed(self._y)):
                rho = 1.0 / (float(jnp.dot(y_v, s_v)) + 1e-20)
                a = rho * float(jnp.dot(s_v, q))
                alphas.append((a, rho, s_v, y_v))
                q = q - a * y_v
            if self._y:
                y_last, s_last = self._y[-1], self._s[-1]
                gamma = (float(jnp.dot(s_last, y_last))
                         / (float(jnp.dot(y_last, y_last)) + 1e-20))
                q = q * gamma
            for a, rho, s_v, y_v in reversed(alphas):
                b = rho * float(jnp.dot(y_v, q))
                q = q + (a - b) * s_v
            d = -q
            gtd = float(jnp.dot(g, d))
            if gtd > -self._tol_change:
                break
            lr = float(self._lr_value())
            t = (min(1.0, 1.0 / (float(jnp.sum(jnp.abs(g))) + 1e-20)) * lr
                 if it == 0 and not self._s else lr)
            x0 = self._flat()
            if self._line_search_fn is None:
                self._unflat_set(x0 + t * d)
                f_new, g_new = eval_closure()
                n_evals += 1
            else:   # 'strong_wolfe' -> sufficient-decrease backtracking
                ok = False
                for _ls in range(12):
                    self._unflat_set(x0 + t * d)
                    f_new, g_new = eval_closure()
                    n_evals += 1
                    fv = float(f_new.numpy() if hasattr(f_new, "numpy")
                               else f_new)
                    if fv <= f_prev + 1e-4 * t * gtd:
                        ok = True
                        break
                    t *= 0.5
                if not ok:
                    # never commit a step that failed the decrease test
                    self._unflat_set(x0)
                    loss, g = eval_closure()
                    break
            s_vec = t * d
            y_vec = g_new - g
            if float(jnp.dot(s_vec, y_vec)) > 1e-10:
                self._s.append(s_vec)
                self._y.append(y_vec)
                if len(self._s) > self._hist:
                    self._s.pop(0)
                    self._y.pop(0)
            loss, g = f_new, g_new
            f_new_val = float(loss.numpy() if hasattr(loss, "numpy")
                              else loss)
            if (float(jnp.max(jnp.abs(s_vec))) <= self._tol_change
                    or abs(f_new_val - f_prev) < self._tol_change
                    or n_evals >= max_eval):
                f_prev = f_new_val
                break
            f_prev = f_new_val
        return loss
