"""Metrics (reference: python/paddle/metric/metrics.py)."""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from paddle_tpu.core.tensor import Tensor


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    """Top-k accuracy (reference: metric/metrics.py accuracy)."""
    pred = input._value
    lab = label._value
    if lab.ndim == pred.ndim and lab.shape[-1] == 1:
        lab = lab[..., 0]
    topk_idx = jnp.argsort(pred, axis=-1)[..., ::-1][..., :k]
    hit = jnp.any(topk_idx == lab[..., None], axis=-1)
    return Tensor(jnp.mean(hit.astype(jnp.float32)))


class Metric:
    """Base metric (reference: metric/metrics.py:Metric)."""

    def __init__(self):
        pass

    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        return self.__class__.__name__

    def compute(self, *args):
        return args


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None):
        super().__init__()
        self.topk = topk if isinstance(topk, (list, tuple)) else (topk,)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def compute(self, pred, label, *args):
        p = pred._value if isinstance(pred, Tensor) else jnp.asarray(pred)
        l = label._value if isinstance(label, Tensor) else jnp.asarray(label)
        if l.ndim == p.ndim and l.shape[-1] == 1:
            l = l[..., 0]
        if l.ndim == p.ndim:  # one-hot
            l = jnp.argmax(l, axis=-1)
        idx = jnp.argsort(p, axis=-1)[..., ::-1][..., :self.maxk]
        correct = idx == l[..., None]
        return Tensor(correct.astype(jnp.float32))

    def update(self, correct, *args):
        c = np.asarray(correct._value if isinstance(correct, Tensor) else correct)
        accs = []
        for i, k in enumerate(self.topk):
            num = c[..., :k].sum()
            n = int(np.prod(c.shape[:-1]))
            self.total[i] += float(num)
            self.count[i] += n
            accs.append(float(num) / max(n, 1))
        return accs[0] if len(accs) == 1 else accs

    def accumulate(self):
        res = [t / max(c, 1) for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res

    def name(self):
        return self._name


class Precision(Metric):
    """Binary precision (reference: metric/metrics.py:Precision)."""

    def __init__(self, name="precision"):
        super().__init__()
        self._name = name
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        p = np.asarray(preds._value if isinstance(preds, Tensor) else preds)
        l = np.asarray(labels._value if isinstance(labels, Tensor) else labels)
        pred_pos = (p.reshape(-1) > 0.5).astype(np.int64)
        lab = l.reshape(-1).astype(np.int64)
        self.tp += int(((pred_pos == 1) & (lab == 1)).sum())
        self.fp += int(((pred_pos == 1) & (lab == 0)).sum())

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name="recall"):
        super().__init__()
        self._name = name
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        p = np.asarray(preds._value if isinstance(preds, Tensor) else preds)
        l = np.asarray(labels._value if isinstance(labels, Tensor) else labels)
        pred_pos = (p.reshape(-1) > 0.5).astype(np.int64)
        lab = l.reshape(-1).astype(np.int64)
        self.tp += int(((pred_pos == 1) & (lab == 1)).sum())
        self.fn += int(((pred_pos == 0) & (lab == 1)).sum())

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    """ROC AUC via thresholded confusion bins (reference: metrics.py:Auc)."""

    def __init__(self, curve="ROC", num_thresholds=4095, name="auc"):
        super().__init__()
        self._name = name
        self.num_thresholds = num_thresholds
        self.reset()

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1)
        self._stat_neg = np.zeros(self.num_thresholds + 1)

    def update(self, preds, labels):
        p = np.asarray(preds._value if isinstance(preds, Tensor) else preds)
        l = np.asarray(labels._value if isinstance(labels, Tensor) else labels)
        if p.ndim == 2 and p.shape[1] == 2:
            p = p[:, 1]
        p = p.reshape(-1)
        l = l.reshape(-1)
        bins = np.minimum((p * self.num_thresholds).astype(np.int64),
                          self.num_thresholds)
        for b, y in zip(bins, l):
            if y:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def accumulate(self):
        tot_pos = 0.0
        tot_neg = 0.0
        auc = 0.0
        for i in range(self.num_thresholds, -1, -1):
            new_pos = tot_pos + self._stat_pos[i]
            new_neg = tot_neg + self._stat_neg[i]
            auc += (new_neg - tot_neg) * (tot_pos + new_pos) / 2.0
            tot_pos, tot_neg = new_pos, new_neg
        return auc / (tot_pos * tot_neg) if tot_pos and tot_neg else 0.0

    def name(self):
        return self._name
