"""`paddle.fft` — discrete Fourier transforms (reference: python/paddle/fft.py).

The reference routes these to pocketfft (CPU) / cuFFT (GPU) kernels; here
every transform lowers to XLA's FFT HLO via jnp.fft, which TPU executes
natively. Normalization-mode semantics ('forward' | 'backward' | 'ortho')
match the reference (`fft.py:_check_normalization`).
"""
from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.core.dispatch import defop
from paddle_tpu.core.tensor import Tensor

__all__ = [
    'fft', 'ifft', 'rfft', 'irfft', 'hfft', 'ihfft',
    'fft2', 'ifft2', 'rfft2', 'irfft2', 'hfft2', 'ihfft2',
    'fftn', 'ifftn', 'rfftn', 'irfftn', 'hfftn', 'ihfftn',
    'fftfreq', 'rfftfreq', 'fftshift', 'ifftshift',
]


def _norm(norm):
    if norm not in ('forward', 'backward', 'ortho'):
        raise ValueError(
            f"Unexpected norm: {norm}. Norm should be forward, backward or ortho")
    return norm


def _mk1d(jnp_fn, opname):
    @defop(opname)
    def op(x, n=None, axis=-1, norm="backward"):
        return jnp_fn(x, n=n, axis=axis, norm=_norm(norm))

    def api(x, n=None, axis=-1, norm="backward", name=None):
        return op(x, n=n, axis=axis, norm=norm)

    api.__name__ = opname
    return api


def _mknd(jnp_fn, opname, default_axes):
    @defop(opname)
    def op(x, s=None, axes=default_axes, norm="backward"):
        return jnp_fn(x, s=s, axes=axes, norm=_norm(norm))

    def api(x, s=None, axes=default_axes, norm="backward", name=None):
        if axes is not None:
            axes = tuple(axes)
        return op(x, s=tuple(s) if s is not None else None, axes=axes,
                  norm=norm)

    api.__name__ = opname
    return api


fft = _mk1d(jnp.fft.fft, "fft")
ifft = _mk1d(jnp.fft.ifft, "ifft")
rfft = _mk1d(jnp.fft.rfft, "rfft")
irfft = _mk1d(jnp.fft.irfft, "irfft")
hfft = _mk1d(jnp.fft.hfft, "hfft")
ihfft = _mk1d(jnp.fft.ihfft, "ihfft")

fft2 = _mknd(jnp.fft.fft2, "fft2", (-2, -1))
ifft2 = _mknd(jnp.fft.ifft2, "ifft2", (-2, -1))
rfft2 = _mknd(jnp.fft.rfft2, "rfft2", (-2, -1))
irfft2 = _mknd(jnp.fft.irfft2, "irfft2", (-2, -1))
fftn = _mknd(jnp.fft.fftn, "fftn", None)
ifftn = _mknd(jnp.fft.ifftn, "ifftn", None)
rfftn = _mknd(jnp.fft.rfftn, "rfftn", None)
irfftn = _mknd(jnp.fft.irfftn, "irfftn", None)


def hfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return hfftn(x, s=s, axes=axes, norm=norm)


def ihfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return ihfftn(x, s=s, axes=axes, norm=norm)


@defop("hfftn")
def _hfftn(x, s=None, axes=None, norm="backward"):
    # hermitian-input FFT: forward fftn over leading axes, hfft over the
    # last (matches scipy.fft.hfftn == irfftn(conj(x)) up to scale)
    _norm(norm)
    axes = tuple(range(-x.ndim, 0)) if axes is None else tuple(axes)
    last = axes[-1]
    n_last = None if s is None else s[-1]
    if len(axes) > 1:
        pre_s = None if s is None else tuple(s[:-1])
        x = jnp.fft.fftn(x, s=pre_s, axes=axes[:-1], norm=norm)
    return jnp.fft.hfft(x, n=n_last, axis=last, norm=norm)


def hfftn(x, s=None, axes=None, norm="backward", name=None):
    return _hfftn(x, s=tuple(s) if s is not None else None,
                  axes=tuple(axes) if axes is not None else None, norm=norm)


@defop("ihfftn")
def _ihfftn(x, s=None, axes=None, norm="backward"):
    _norm(norm)
    axes = tuple(range(-x.ndim, 0)) if axes is None else tuple(axes)
    last = axes[-1]
    n_last = None if s is None else s[-1]
    out = jnp.fft.ihfft(x, n=n_last, axis=last, norm=norm)
    if len(axes) > 1:
        pre_s = None if s is None else tuple(s[:-1])
        out = jnp.fft.ifftn(out, s=pre_s, axes=axes[:-1], norm=norm)
    return out


def ihfftn(x, s=None, axes=None, norm="backward", name=None):
    return _ihfftn(x, s=tuple(s) if s is not None else None,
                   axes=tuple(axes) if axes is not None else None, norm=norm)


def fftfreq(n, d=1.0, dtype=None, name=None):
    return Tensor(jnp.fft.fftfreq(n, d=d).astype(dtype or jnp.float32))


def rfftfreq(n, d=1.0, dtype=None, name=None):
    return Tensor(jnp.fft.rfftfreq(n, d=d).astype(dtype or jnp.float32))


@defop("fftshift")
def _fftshift(x, axes=None):
    return jnp.fft.fftshift(x, axes=axes)


def fftshift(x, axes=None, name=None):
    return _fftshift(x, axes=tuple(axes) if axes is not None else None)


@defop("ifftshift")
def _ifftshift(x, axes=None):
    return jnp.fft.ifftshift(x, axes=axes)


def ifftshift(x, axes=None, name=None):
    return _ifftshift(x, axes=tuple(axes) if axes is not None else None)
