"""`paddle.audio` — audio feature toolkit (reference: python/paddle/audio/:
functional/{functional,window}.py, features/layers.py, datasets, backends).

Feature extraction composes paddle_tpu.signal.stft with mel filterbanks —
all static-shape jnp, so a whole MelSpectrogram/MFCC frontend jits into
one XLA program on TPU.
"""
from paddle_tpu.audio import functional  # noqa: F401
from paddle_tpu.audio import features  # noqa: F401
from paddle_tpu.audio import datasets  # noqa: F401

__all__ = ["functional", "features", "datasets"]
