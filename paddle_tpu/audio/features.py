"""`paddle.audio.features` — feature-extraction layers (reference:
python/paddle/audio/features/layers.py: Spectrogram, MelSpectrogram,
LogMelSpectrogram, MFCC).
"""
from __future__ import annotations

from paddle_tpu import nn
from paddle_tpu import tensor as T
from paddle_tpu import signal
from paddle_tpu.audio import functional as AF

__all__ = ['Spectrogram', 'MelSpectrogram', 'LogMelSpectrogram', 'MFCC']


class Spectrogram(nn.Layer):
    def __init__(self, n_fft=512, hop_length=512, win_length=None,
                 window="hann", power=1.0, center=True, pad_mode="reflect",
                 dtype="float32"):
        super().__init__()
        self.n_fft = n_fft
        self.hop_length = hop_length or n_fft // 4
        self.win_length = win_length or n_fft
        self.power = power
        self.center = center
        self.pad_mode = pad_mode
        self.register_buffer(
            "window", AF.get_window(window, self.win_length, dtype=dtype),
            persistable=False)

    def forward(self, x):
        spec = signal.stft(x, self.n_fft, self.hop_length, self.win_length,
                           window=self.window, center=self.center,
                           pad_mode=self.pad_mode)
        mag = spec.abs()
        return mag ** self.power if self.power != 1.0 else mag


class MelSpectrogram(nn.Layer):
    def __init__(self, sr=22050, n_fft=2048, hop_length=512,
                 win_length=None, window="hann", power=2.0, center=True,
                 pad_mode="reflect", n_mels=64, f_min=50.0, f_max=None,
                 htk=False, norm="slaney", dtype="float32"):
        super().__init__()
        self._spectrogram = Spectrogram(n_fft, hop_length, win_length,
                                        window, power, center, pad_mode,
                                        dtype)
        self.register_buffer(
            "fbank_matrix",
            AF.compute_fbank_matrix(sr, n_fft, n_mels, f_min, f_max, htk,
                                    norm, dtype),
            persistable=False)

    def forward(self, x):
        spec = self._spectrogram(x)       # (..., freq, time)
        return T.matmul(self.fbank_matrix, spec)


class LogMelSpectrogram(nn.Layer):
    def __init__(self, sr=22050, n_fft=512, hop_length=None,
                 win_length=None, window="hann", power=2.0, center=True,
                 pad_mode="reflect", n_mels=64, f_min=50.0, f_max=None,
                 htk=False, norm="slaney", ref_value=1.0, amin=1e-10,
                 top_db=None, dtype="float32"):
        super().__init__()
        self._melspectrogram = MelSpectrogram(
            sr, n_fft, hop_length, win_length, window, power, center,
            pad_mode, n_mels, f_min, f_max, htk, norm, dtype)
        self.ref_value, self.amin, self.top_db = ref_value, amin, top_db

    def forward(self, x):
        mel = self._melspectrogram(x)
        return AF.power_to_db(mel, self.ref_value, self.amin, self.top_db)


class MFCC(nn.Layer):
    def __init__(self, sr=22050, n_mfcc=40, n_fft=512, hop_length=None,
                 win_length=None, window="hann", power=2.0, center=True,
                 pad_mode="reflect", n_mels=64, f_min=50.0, f_max=None,
                 htk=False, norm="slaney", ref_value=1.0, amin=1e-10,
                 top_db=None, dtype="float32"):
        super().__init__()
        self._log_melspectrogram = LogMelSpectrogram(
            sr, n_fft, hop_length, win_length, window, power, center,
            pad_mode, n_mels, f_min, f_max, htk, norm, ref_value, amin,
            top_db, dtype)
        self.register_buffer(
            "dct_matrix", AF.create_dct(n_mfcc, n_mels, dtype=dtype),
            persistable=False)

    def forward(self, x):
        logmel = self._log_melspectrogram(x)       # (..., n_mels, time)
        return T.matmul(T.transpose(self.dct_matrix, [1, 0]), logmel)
