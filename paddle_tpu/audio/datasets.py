"""`paddle.audio.datasets` (reference: python/paddle/audio/datasets/
TESS, ESC50 — downloadable corpora).

This build runs with zero network egress, so the downloadable datasets
raise a clear error; AudioFolderDataset covers the local-files workflow.
"""
from __future__ import annotations

import os

import numpy as np

from paddle_tpu.io import Dataset

__all__ = ["TESS", "ESC50", "AudioFolderDataset"]


class _Downloadable(Dataset):
    _NAME = "?"

    def __init__(self, *a, **k):
        raise RuntimeError(
            f"paddle_tpu.audio.datasets.{self._NAME} downloads its corpus "
            f"from the internet, which this environment does not allow. "
            f"Fetch the archive yourself and use AudioFolderDataset over "
            f"the extracted directory.")


class TESS(_Downloadable):
    _NAME = "TESS"


class ESC50(_Downloadable):
    _NAME = "ESC50"


class AudioFolderDataset(Dataset):
    """label-per-subdirectory layout of .npy waveform files."""

    def __init__(self, root):
        self.samples = []
        self.labels = sorted(
            d for d in os.listdir(root)
            if os.path.isdir(os.path.join(root, d)))
        for li, lab in enumerate(self.labels):
            for f in sorted(os.listdir(os.path.join(root, lab))):
                if f.endswith(".npy"):
                    self.samples.append(
                        (os.path.join(root, lab, f), li))

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, idx):
        path, label = self.samples[idx]
        return np.load(path), label
