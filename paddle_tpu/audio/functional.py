"""`paddle.audio.functional` (reference:
python/paddle/audio/functional/functional.py — mel scale conversions,
fbank matrix, dct; window.py — get_window).
"""
from __future__ import annotations

import math

import numpy as np
import jax.numpy as jnp

from paddle_tpu.core.tensor import Tensor

__all__ = ['compute_fbank_matrix', 'create_dct', 'fft_frequencies',
           'hz_to_mel', 'mel_frequencies', 'mel_to_hz', 'power_to_db',
           'get_window']


def _arr(x):
    return x._value if isinstance(x, Tensor) else jnp.asarray(x)


def hz_to_mel(freq, htk=False):
    """(reference: functional.py hz_to_mel — slaney by default)."""
    scalar = not isinstance(freq, (Tensor, jnp.ndarray, np.ndarray))
    f = _arr(np.asarray(freq, np.float32))
    if htk:
        mel = 2595.0 * jnp.log10(1.0 + f / 700.0)
    else:
        f_min, f_sp = 0.0, 200.0 / 3
        mel = (f - f_min) / f_sp
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        mel = jnp.where(f >= min_log_hz,
                        min_log_mel + jnp.log(jnp.maximum(f, 1e-10)
                                              / min_log_hz) / logstep,
                        mel)
    return float(mel) if scalar else Tensor(mel)


def mel_to_hz(mel, htk=False):
    scalar = not isinstance(mel, (Tensor, jnp.ndarray, np.ndarray))
    m = _arr(np.asarray(mel, np.float32))
    if htk:
        hz = 700.0 * (10.0 ** (m / 2595.0) - 1.0)
    else:
        f_min, f_sp = 0.0, 200.0 / 3
        hz = f_min + f_sp * m
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        hz = jnp.where(m >= min_log_mel,
                       min_log_hz * jnp.exp(logstep * (m - min_log_mel)),
                       hz)
    return float(hz) if scalar else Tensor(hz)


def mel_frequencies(n_mels=64, f_min=0.0, f_max=11025.0, htk=False,
                    dtype="float32"):
    low = hz_to_mel(f_min, htk)
    high = hz_to_mel(f_max, htk)
    mels = jnp.linspace(low, high, n_mels).astype(dtype)
    return mel_to_hz(Tensor(mels), htk)


def fft_frequencies(sr, n_fft, dtype="float32"):
    return Tensor(jnp.linspace(0, sr / 2, n_fft // 2 + 1).astype(dtype))


def compute_fbank_matrix(sr, n_fft, n_mels=64, f_min=0.0, f_max=None,
                         htk=False, norm="slaney", dtype="float32"):
    """Triangular mel filterbank (n_mels, n_fft//2+1) (reference:
    functional.py compute_fbank_matrix)."""
    if f_max is None:
        f_max = sr / 2.0
    fftfreqs = fft_frequencies(sr, n_fft)._value
    mel_f = mel_frequencies(n_mels + 2, f_min, f_max, htk)._value
    fdiff = jnp.diff(mel_f)
    ramps = mel_f[:, None] - fftfreqs[None, :]
    lower = -ramps[:-2] / fdiff[:-1, None]
    upper = ramps[2:] / fdiff[1:, None]
    weights = jnp.maximum(0, jnp.minimum(lower, upper))
    if norm == "slaney":
        enorm = 2.0 / (mel_f[2:n_mels + 2] - mel_f[:n_mels])
        weights = weights * enorm[:, None]
    return Tensor(weights.astype(dtype))


def create_dct(n_mfcc, n_mels, norm="ortho", dtype="float32"):
    """DCT-II matrix (n_mels, n_mfcc) (reference: functional.py create_dct)."""
    n = jnp.arange(n_mels, dtype=jnp.float32)
    k = jnp.arange(n_mfcc, dtype=jnp.float32)[None, :]
    dct = jnp.cos(math.pi / n_mels * (n[:, None] + 0.5) * k)
    if norm == "ortho":
        dct = dct * math.sqrt(2.0 / n_mels)
        dct = dct.at[:, 0].set(dct[:, 0] / math.sqrt(2.0))
    else:
        dct = dct * 2.0
    return Tensor(dct.astype(dtype))


def power_to_db(spect, ref_value=1.0, amin=1e-10, top_db=80.0):
    """10*log10(S/ref) with clipping (reference: functional.py power_to_db)."""
    s = _arr(spect)
    log_spec = 10.0 * jnp.log10(jnp.maximum(s, amin))
    log_spec = log_spec - 10.0 * math.log10(max(ref_value, amin))
    if top_db is not None:
        log_spec = jnp.maximum(log_spec, jnp.max(log_spec) - top_db)
    return Tensor(log_spec)


def get_window(window, win_length, fftbins=True, dtype="float32"):
    """Window functions (reference: window.py get_window)."""
    if isinstance(window, tuple):
        name, *params = window
    else:
        name, params = window, []
    n = win_length
    sym = not fftbins

    def periodic(fn_n):
        # scipy convention: fftbins=True -> periodic window
        if sym:
            return fn_n(n)
        w = fn_n(n + 1)
        return w[:-1]

    if name in ("hann", "hanning"):
        w = periodic(lambda k: 0.5 - 0.5 * np.cos(
            2 * np.pi * np.arange(k) / (k - 1)))
    elif name == "hamming":
        w = periodic(lambda k: 0.54 - 0.46 * np.cos(
            2 * np.pi * np.arange(k) / (k - 1)))
    elif name == "blackman":
        w = periodic(lambda k: 0.42 - 0.5 * np.cos(
            2 * np.pi * np.arange(k) / (k - 1))
            + 0.08 * np.cos(4 * np.pi * np.arange(k) / (k - 1)))
    elif name in ("rect", "rectangular", "boxcar", "ones"):
        w = np.ones(n)
    elif name == "triang":
        w = periodic(lambda k: 1 - np.abs(
            (np.arange(k) - (k - 1) / 2) / ((k - 1) / 2)))
    elif name == "bartlett":
        w = periodic(lambda k: np.bartlett(k))
    elif name == "gaussian":
        std = params[0] if params else 7.0

        def gauss(k):
            idx = np.arange(k) - (k - 1) / 2
            return np.exp(-0.5 * (idx / std) ** 2)
        w = periodic(gauss)
    elif name == "kaiser":
        beta = params[0] if params else 12.0
        w = periodic(lambda k: np.kaiser(k, beta))
    else:
        raise ValueError(f"unknown window {window!r}")
    return Tensor(jnp.asarray(w.astype(dtype)))
