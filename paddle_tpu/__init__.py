"""paddle_tpu: a TPU-native deep learning framework.

A ground-up rebuild of the capabilities of the reference framework
(feifei-111/Paddle, i.e. PaddlePaddle ~2.6) designed TPU-first on
JAX/XLA/Pallas: eager mode is a thin autograd tape over XLA-compiled ops,
static mode is `jax.jit` tracing, distribution is GSPMD mesh-and-sharding
over ICI, and hot kernels are Pallas. See SURVEY.md at the repo root for the
full component mapping to the reference.

Public API mirrors `import paddle` (reference: python/paddle/__init__.py).
"""
from __future__ import annotations

__version__ = "0.1.0"

# f32 matmuls default to full precision so eager/grad numerics match the
# reference's CUDA fp32 path; training runs in bf16 where this has no cost.
import jax as _jax  # noqa: E402
from paddle_tpu.core.flags import get_flag as _get_flag  # noqa: E402
_jax.config.update("jax_default_matmul_precision",
                   _get_flag("FLAGS_matmul_precision", "highest"))

# core types
from paddle_tpu.core.tensor import Tensor, Parameter, to_tensor, is_tensor
from paddle_tpu.core.tape import no_grad, enable_grad, set_grad_enabled, grad
from paddle_tpu.core import dtype as _dtype_mod
from paddle_tpu.core.dtype import (
    float16, bfloat16, float32, float64, int8, int16, int32, int64,
    uint8, bool_, complex64, complex128, float8_e4m3fn, float8_e5m2,
)
from paddle_tpu.core.random import seed, get_rng_state, set_rng_state
from paddle_tpu.core.flags import set_flags, get_flags

bool = bool_  # paddle.bool

# functional tensor API (creation/math/manipulation/linalg/...)
from paddle_tpu.tensor import *  # noqa: F401,F403
from paddle_tpu.tensor import einsum  # noqa: F401
# the star import binds `linalg` to paddle_tpu.tensor.linalg; rebind the
# public `paddle.linalg` namespace module over it
from paddle_tpu import linalg  # noqa: F401,E402
from paddle_tpu.signal import stft, istft  # noqa: F401,E402


def create_parameter(shape, dtype="float32", name=None, attr=None,
                     is_bias=False, default_initializer=None):
    """(reference: tensor/creation.py create_parameter)."""
    from paddle_tpu.core.tensor import Parameter as _Param
    from paddle_tpu.nn import initializer as _I
    init = default_initializer or _I.XavierNormal()
    arr = init(tuple(shape), dtype)
    t = _Param(arr)
    t.stop_gradient = False
    return t


def create_tensor(dtype="float32", name=None, persistable=False):
    import jax.numpy as _jnp
    from paddle_tpu.core.tensor import Tensor as _T
    return _T(_jnp.zeros((), dtype))

# subpackages (paddle.nn, paddle.optimizer, ...)
from paddle_tpu import nn  # noqa: F401
from paddle_tpu import optimizer  # noqa: F401
from paddle_tpu import amp  # noqa: F401
from paddle_tpu import autograd  # noqa: F401
from paddle_tpu import io  # noqa: F401
from paddle_tpu import jit  # noqa: F401
from paddle_tpu import metric  # noqa: F401
from paddle_tpu import device  # noqa: F401
from paddle_tpu.framework.io_utils import save, load  # noqa: F401
from paddle_tpu.jit.api import to_static  # noqa: F401
from paddle_tpu.device import (  # noqa: F401
    set_device, get_device, is_compiled_with_cuda, is_compiled_with_xpu,
    is_compiled_with_rocm, is_compiled_with_custom_device,
)


def __getattr__(name):
    # heavy subpackages loaded lazily to keep import fast
    import importlib
    if name in ("distributed", "vision", "distribution", "profiler",
                "incubate", "sparse", "static", "hapi", "models", "fft",
                "signal", "linalg", "quantization", "geometric", "text",
                "audio", "onnx", "utils", "inference", "sysconfig", "version"):
        try:
            mod = importlib.import_module(f"paddle_tpu.{name}")
        except ModuleNotFoundError as e:
            if e.name != f"paddle_tpu.{name}":
                raise  # real dependency failure inside an existing submodule
            # keep hasattr()/getattr() semantics for not-yet-built submodules
            raise AttributeError(
                f"module 'paddle_tpu' has no attribute {name!r}") from e
        globals()[name] = mod
        return mod
    if name == "Model":
        from paddle_tpu.hapi import Model
        globals()["Model"] = Model
        return Model
    if name == "callbacks":
        from paddle_tpu.hapi import callbacks
        globals()["callbacks"] = callbacks
        return callbacks
    raise AttributeError(f"module 'paddle_tpu' has no attribute {name!r}")


def in_dynamic_mode():
    from paddle_tpu.jit.api import _in_tracing
    return not _in_tracing()


def disable_static(place=None):
    return None


def enable_static():
    raise NotImplementedError(
        "paddle_tpu has no separate static graph mode: use paddle_tpu.jit."
        "to_static / paddle_tpu.static for program-capture workflows.")


def get_default_dtype():
    from paddle_tpu.framework import _default_dtype
    return _default_dtype[0]


def set_default_dtype(d):
    from paddle_tpu.framework import _default_dtype
    from paddle_tpu.core.dtype import convert_dtype
    _default_dtype[0] = convert_dtype(d)


def summary(net, input_size=None, dtypes=None, input=None):
    from paddle_tpu.hapi.summary import summary as _summary
    return _summary(net, input_size, dtypes, input)


def flops(net, input_size, custom_ops=None, print_detail=False):
    return 0
