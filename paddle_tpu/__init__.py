"""paddle_tpu: a TPU-native deep learning framework.

A ground-up rebuild of the capabilities of the reference framework
(feifei-111/Paddle, i.e. PaddlePaddle ~2.6) designed TPU-first on
JAX/XLA/Pallas: eager mode is a thin autograd tape over XLA-compiled ops,
static mode is `jax.jit` tracing, distribution is GSPMD mesh-and-sharding
over ICI, and hot kernels are Pallas. See SURVEY.md at the repo root for the
full component mapping to the reference.

Public API mirrors `import paddle` (reference: python/paddle/__init__.py).
"""
from __future__ import annotations

__version__ = "0.1.0"

# f32 matmuls default to full precision so eager/grad numerics match the
# reference's CUDA fp32 path; training runs in bf16 where this has no cost.
import jax as _jax  # noqa: E402
from paddle_tpu.core.flags import get_flag as _get_flag  # noqa: E402
_jax.config.update("jax_default_matmul_precision",
                   _get_flag("FLAGS_matmul_precision", "highest"))

# core types
from paddle_tpu.core.tensor import Tensor, Parameter, to_tensor, is_tensor
from paddle_tpu.core.tape import no_grad, enable_grad, set_grad_enabled, grad
from paddle_tpu.core import dtype as _dtype_mod
from paddle_tpu.core.dtype import (
    float16, bfloat16, float32, float64, int8, int16, int32, int64,
    uint8, bool_, complex64, complex128, float8_e4m3fn, float8_e5m2,
)
from paddle_tpu.core.random import seed, get_rng_state, set_rng_state
from paddle_tpu.core.flags import set_flags, get_flags

bool = bool_  # paddle.bool

# functional tensor API (creation/math/manipulation/linalg/...)
from paddle_tpu.tensor import *  # noqa: F401,F403
from paddle_tpu.tensor import einsum  # noqa: F401
# the star import binds `linalg` to paddle_tpu.tensor.linalg; rebind the
# public `paddle.linalg` namespace module over it
from paddle_tpu import linalg  # noqa: F401,E402
from paddle_tpu.signal import stft, istft  # noqa: F401,E402


def create_parameter(shape, dtype="float32", name=None, attr=None,
                     is_bias=False, default_initializer=None):
    """(reference: tensor/creation.py create_parameter)."""
    from paddle_tpu.core.tensor import Parameter as _Param
    from paddle_tpu.nn import initializer as _I
    init = default_initializer or _I.XavierNormal()
    arr = init(tuple(shape), dtype)
    t = _Param(arr)
    t.stop_gradient = False
    return t


def create_tensor(dtype="float32", name=None, persistable=False):
    import jax.numpy as _jnp
    from paddle_tpu.core.tensor import Tensor as _T
    return _T(_jnp.zeros((), dtype))

# subpackages (paddle.nn, paddle.optimizer, ...)
from paddle_tpu import nn  # noqa: F401
from paddle_tpu import optimizer  # noqa: F401
from paddle_tpu import amp  # noqa: F401
from paddle_tpu import autograd  # noqa: F401
from paddle_tpu import io  # noqa: F401
from paddle_tpu import jit  # noqa: F401
from paddle_tpu import metric  # noqa: F401
from paddle_tpu import device  # noqa: F401
from paddle_tpu import strings  # noqa: F401
from paddle_tpu.framework.io_utils import save, load  # noqa: F401
from paddle_tpu.jit.api import to_static  # noqa: F401
from paddle_tpu.device import (  # noqa: F401
    set_device, get_device, is_compiled_with_cuda, is_compiled_with_xpu,
    is_compiled_with_rocm, is_compiled_with_custom_device,
)
from paddle_tpu.nn import ParamAttr  # noqa: F401
import numpy as _np
dtype = _np.dtype  # paddle.dtype: dtypes are numpy dtypes in this build


def __getattr__(name):
    # heavy subpackages loaded lazily to keep import fast
    import importlib
    if name in ("distributed", "vision", "distribution", "profiler",
                "incubate", "sparse", "static", "hapi", "models", "fft",
                "signal", "linalg", "quantization", "geometric", "text",
                "audio", "onnx", "utils", "inference", "sysconfig",
                "version", "observability"):
        try:
            mod = importlib.import_module(f"paddle_tpu.{name}")
        except ModuleNotFoundError as e:
            if e.name != f"paddle_tpu.{name}":
                raise  # real dependency failure inside an existing submodule
            # keep hasattr()/getattr() semantics for not-yet-built submodules
            raise AttributeError(
                f"module 'paddle_tpu' has no attribute {name!r}") from e
        globals()[name] = mod
        return mod
    if name == "DataParallel":
        from paddle_tpu.distributed.parallel import DataParallel
        globals()["DataParallel"] = DataParallel
        return DataParallel
    if name == "Model":
        from paddle_tpu.hapi import Model
        globals()["Model"] = Model
        return Model
    if name == "callbacks":
        from paddle_tpu.hapi import callbacks
        globals()["callbacks"] = callbacks
        return callbacks
    raise AttributeError(f"module 'paddle_tpu' has no attribute {name!r}")


# -- remaining top-level reference surface ---------------------------------

from paddle_tpu.device import _Place as _PlaceBase  # noqa: E402


class CPUPlace(_PlaceBase):
    def __init__(self):
        super().__init__("cpu")


class CUDAPlace(_PlaceBase):
    def __init__(self, dev_id=0):
        super().__init__("gpu", dev_id)


class CUDAPinnedPlace(_PlaceBase):
    def __init__(self):
        super().__init__("gpu_pinned")


class LazyGuard:
    """(reference: python/paddle/nn/initializer/lazy_init.py LazyGuard) —
    eager-initialized parameters make lazy init a no-op context."""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


def finfo(dtype):
    import jax.numpy as _jnp
    from paddle_tpu.core.dtype import convert_dtype
    return _jnp.finfo(convert_dtype(dtype))


def iinfo(dtype):
    import jax.numpy as _jnp
    from paddle_tpu.core.dtype import convert_dtype
    return _jnp.iinfo(convert_dtype(dtype))


def is_grad_enabled():
    from paddle_tpu.core.tape import grad_enabled
    return grad_enabled()


def tolist(x):
    return x.numpy().tolist()


def batch(reader, batch_size, drop_last=False):
    """Legacy reader combinator (reference: python/paddle/batch.py)."""
    def batched():
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf
    return batched


def pdist(x, p=2.0, name=None):
    """Condensed pairwise distances (reference: tensor/linalg.py pdist)."""
    import numpy as _np
    from paddle_tpu import tensor as _T
    full = cdist(x, x, p=p)
    n = x.shape[0]
    iu = _np.triu_indices(n, 1)
    return _T.gather_nd(full, _T.to_tensor(
        _np.stack(iu, axis=1).astype(_np.int32)))


def combinations(x, r=2, with_replacement=False, name=None):
    """(reference: tensor/math.py combinations)."""
    import itertools as _it
    import numpy as _np
    from paddle_tpu import tensor as _T
    n = x.shape[0]
    idx = (_it.combinations_with_replacement(range(n), r)
           if with_replacement else _it.combinations(range(n), r))
    idx = _np.asarray(list(idx), _np.int32).reshape(-1, r)
    cols = [index_select(x, _T.to_tensor(idx[:, j]), axis=0)
            for j in range(r)]
    return _T.stack(cols, axis=1)


def standard_gamma(x, name=None):
    """Sample Gamma(alpha=x, 1) (reference: tensor/random.py
    standard_gamma)."""
    import jax as _jax
    from paddle_tpu.core.random import next_key
    from paddle_tpu.core.tensor import Tensor as _T
    arr = x._value if isinstance(x, _T) else x
    return _T(_jax.random.gamma(next_key(), arr))


def check_shape(x):
    return list(x.shape)


def disable_signal_handler():
    return None


def get_cuda_rng_state():
    from paddle_tpu.core.random import get_rng_state
    return get_rng_state()


def set_cuda_rng_state(state):
    from paddle_tpu.core.random import set_rng_state
    return set_rng_state(state)


def in_dynamic_mode():
    from paddle_tpu.jit.api import _in_tracing
    return not _in_tracing()


def disable_static(place=None):
    return None


def enable_static():
    raise NotImplementedError(
        "paddle_tpu has no separate static graph mode: use paddle_tpu.jit."
        "to_static / paddle_tpu.static for program-capture workflows.")


def get_default_dtype():
    from paddle_tpu.framework import _default_dtype
    return _default_dtype[0]


def set_default_dtype(d):
    from paddle_tpu.framework import _default_dtype
    from paddle_tpu.core.dtype import convert_dtype
    _default_dtype[0] = convert_dtype(d)


def summary(net, input_size=None, dtypes=None, input=None):
    from paddle_tpu.hapi.summary import summary as _summary
    return _summary(net, input_size, dtypes, input)


def flops(net, input_size, custom_ops=None, print_detail=False):
    return 0
