"""`paddle.geometric` — graph message passing (reference:
python/paddle/geometric/: message_passing/send_recv.py, math.py,
reindex.py, sampling/neighbors.py; GPU kernels
paddle/phi/kernels/gpu/graph_send_recv_kernel.cu).

TPU-native: gather + jax.ops.segment_{sum,max,min} ARE the message-passing
primitives — XLA lowers them to the same scatter-reduce the reference's
CUDA kernels hand-roll, and they fuse with surrounding elementwise work.
Sampling/reindex are host-side graph-prep utilities (numpy), matching the
reference's CPU path; they feed static-shape device batches.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu.core.dispatch import dispatch, OpDef
from paddle_tpu.core.tensor import Tensor

__all__ = [
    'send_u_recv', 'send_ue_recv', 'send_uv',
    'segment_sum', 'segment_mean', 'segment_min', 'segment_max',
    'reindex_graph', 'reindex_heter_graph',
    'sample_neighbors', 'weighted_sample_neighbors',
]


def _op(name, fn, *tensors):
    return dispatch(OpDef("geometric." + name, fn), tensors, {})


def _idx(x):
    a = x._value if isinstance(x, Tensor) else jnp.asarray(x)
    return a.astype(jnp.int32)


_MSG = {
    "add": jnp.add, "sub": jnp.subtract, "mul": jnp.multiply,
    "div": jnp.divide,
}


def _segment_reduce(msgs, dst, num_segments, reduce_op):
    if reduce_op == "sum":
        return jax.ops.segment_sum(msgs, dst, num_segments=num_segments)
    if reduce_op == "mean":
        s = jax.ops.segment_sum(msgs, dst, num_segments=num_segments)
        cnt = jax.ops.segment_sum(jnp.ones_like(dst, msgs.dtype), dst,
                                  num_segments=num_segments)
        cnt = jnp.maximum(cnt, 1.0)
        return s / cnt.reshape((-1,) + (1,) * (msgs.ndim - 1))
    if reduce_op in ("max", "min"):
        seg = (jax.ops.segment_max if reduce_op == "max"
               else jax.ops.segment_min)
        out = seg(msgs, dst, num_segments=num_segments)
        # empty segments: identity is +/-inf for floats, INT_MIN/MAX for
        # ints; fill with a dtype-matched 0 like the reference kernels
        cnt = jax.ops.segment_sum(jnp.ones_like(dst), dst,
                                  num_segments=num_segments)
        nonempty = (cnt > 0).reshape((-1,) + (1,) * (msgs.ndim - 1))
        return jnp.where(nonempty, out, jnp.zeros((), msgs.dtype))
    raise ValueError(f"unknown reduce_op {reduce_op!r}")


def send_u_recv(x, src_index, dst_index, reduce_op="sum", out_size=None,
                name=None):
    """Gather x at src, reduce onto dst (reference:
    message_passing/send_recv.py:36)."""
    src, dst = _idx(src_index), _idx(dst_index)
    n_out = int(out_size) if out_size is not None else int(x.shape[0])

    def f(xv):
        return _segment_reduce(xv[src], dst, n_out, reduce_op)
    return _op("send_u_recv", f, x)


def send_ue_recv(x, y, src_index, dst_index, message_op="add",
                 reduce_op="sum", out_size=None, name=None):
    """Gather x at src, combine with edge feature y, reduce onto dst
    (reference: message_passing/send_recv.py:187)."""
    if message_op not in _MSG:
        raise ValueError(f"unknown message_op {message_op!r}")
    src, dst = _idx(src_index), _idx(dst_index)
    n_out = int(out_size) if out_size is not None else int(x.shape[0])

    def f(xv, yv):
        return _segment_reduce(_MSG[message_op](xv[src], yv), dst, n_out,
                               reduce_op)
    return _op("send_ue_recv", f, x, y)


def send_uv(x, y, src_index, dst_index, message_op="add", name=None):
    """Per-edge message from src-node and dst-node features (reference:
    message_passing/send_recv.py:392)."""
    if message_op not in _MSG:
        raise ValueError(f"unknown message_op {message_op!r}")
    src, dst = _idx(src_index), _idx(dst_index)

    def f(xv, yv):
        return _MSG[message_op](xv[src], yv[dst])
    return _op("send_uv", f, x, y)


def _segment(op_name, reduce_op):
    def api(data, segment_ids, name=None):
        seg = _idx(segment_ids)
        n = int(jnp.max(seg)) + 1 if seg.size else 0

        def f(d):
            return _segment_reduce(d, seg, n, reduce_op)
        return _op(op_name, f, data)
    api.__name__ = op_name
    return api


segment_sum = _segment("segment_sum", "sum")
segment_mean = _segment("segment_mean", "mean")
segment_min = _segment("segment_min", "min")
segment_max = _segment("segment_max", "max")


def reindex_graph(x, neighbors, count, value_buffer=None, index_buffer=None,
                  name=None):
    """Compact global node ids to local ids (reference: reindex.py:25).
    Host-side graph prep: returns (reindex_src, reindex_dst, out_nodes)
    where out_nodes = unique nodes in [x, neighbors] with x first."""
    xv = np.asarray(x._value if isinstance(x, Tensor) else x).ravel()
    nb = np.asarray(
        neighbors._value if isinstance(neighbors, Tensor) else neighbors
    ).ravel()
    cnt = np.asarray(count._value if isinstance(count, Tensor) else count
                     ).ravel()
    seen = dict((int(n), i) for i, n in enumerate(xv))
    out_nodes = list(xv)
    for n in nb:
        n = int(n)
        if n not in seen:
            seen[n] = len(out_nodes)
            out_nodes.append(n)
    reindex_src = np.array([seen[int(n)] for n in nb], np.int32)
    reindex_dst = np.repeat(np.arange(len(xv), dtype=np.int32), cnt)
    return (Tensor(jnp.asarray(reindex_src)),
            Tensor(jnp.asarray(reindex_dst)),
            Tensor(jnp.asarray(np.array(out_nodes, np.int32))))


def reindex_heter_graph(x, neighbors, count, value_buffer=None,
                        index_buffer=None, name=None):
    """Heterogeneous variant: neighbors per edge type share one id space
    (reference: reindex.py reindex_heter_graph)."""
    xv = np.asarray(x._value if isinstance(x, Tensor) else x).ravel()
    nbs = [np.asarray(n._value if isinstance(n, Tensor) else n).ravel()
           for n in neighbors]
    cnts = [np.asarray(c._value if isinstance(c, Tensor) else c).ravel()
            for c in count]
    seen = dict((int(n), i) for i, n in enumerate(xv))
    out_nodes = list(xv)
    srcs, dsts = [], []
    for nb, cnt in zip(nbs, cnts):
        for n in nb:
            n = int(n)
            if n not in seen:
                seen[n] = len(out_nodes)
                out_nodes.append(n)
        srcs.append(np.array([seen[int(n)] for n in nb], np.int32))
        dsts.append(np.repeat(np.arange(len(xv), dtype=np.int32), cnt))
    return (Tensor(jnp.asarray(np.concatenate(srcs))),
            Tensor(jnp.asarray(np.concatenate(dsts))),
            Tensor(jnp.asarray(np.array(out_nodes, np.int32))))


def sample_neighbors(row, colptr, input_nodes, sample_size=-1, eids=None,
                     return_eids=False, perm_buffer=None, name=None):
    """Uniform neighbor sampling on a CSC graph (reference:
    sampling/neighbors.py:23). Host-side; returns (out_neighbors,
    out_count[, out_eids])."""
    rv = np.asarray(row._value if isinstance(row, Tensor) else row).ravel()
    cp = np.asarray(colptr._value if isinstance(colptr, Tensor) else colptr
                    ).ravel()
    nodes = np.asarray(
        input_nodes._value if isinstance(input_nodes, Tensor)
        else input_nodes).ravel()
    ev = (np.asarray(eids._value if isinstance(eids, Tensor) else eids
                     ).ravel() if eids is not None else None)
    rng = np.random.RandomState()
    out_n, out_c, out_e = [], [], []
    for v in nodes:
        beg, end = int(cp[v]), int(cp[v + 1])
        neigh = rv[beg:end]
        ids = np.arange(beg, end)
        if sample_size != -1 and len(neigh) > sample_size:
            pick = rng.choice(len(neigh), size=sample_size, replace=False)
            neigh, ids = neigh[pick], ids[pick]
        out_n.append(neigh)
        out_c.append(len(neigh))
        if ev is not None:
            out_e.append(ev[ids])
    res = (Tensor(jnp.asarray(np.concatenate(out_n) if out_n else
                              np.zeros(0, np.int32), jnp.int32)),
           Tensor(jnp.asarray(np.array(out_c, np.int32))))
    if return_eids:
        if ev is None:
            raise ValueError("return_eids=True requires eids")
        return res + (Tensor(jnp.asarray(np.concatenate(out_e))),)
    return res


def weighted_sample_neighbors(row, colptr, edge_weight, input_nodes,
                              sample_size=-1, eids=None, return_eids=False,
                              name=None):
    """Weighted-without-replacement neighbor sampling (reference:
    sampling/neighbors.py weighted_sample_neighbors; uses the A-ExpJ
    reservoir method — here numpy Gumbel top-k, same distribution)."""
    rv = np.asarray(row._value if isinstance(row, Tensor) else row).ravel()
    cp = np.asarray(colptr._value if isinstance(colptr, Tensor) else colptr
                    ).ravel()
    wv = np.asarray(edge_weight._value if isinstance(edge_weight, Tensor)
                    else edge_weight).ravel()
    nodes = np.asarray(
        input_nodes._value if isinstance(input_nodes, Tensor)
        else input_nodes).ravel()
    ev = (np.asarray(eids._value if isinstance(eids, Tensor) else eids
                     ).ravel() if eids is not None else None)
    rng = np.random.RandomState()
    out_n, out_c, out_e = [], [], []
    for v in nodes:
        beg, end = int(cp[v]), int(cp[v + 1])
        neigh, w = rv[beg:end], wv[beg:end]
        ids = np.arange(beg, end)
        if sample_size != -1 and len(neigh) > sample_size:
            # Gumbel top-k == weighted sampling without replacement
            keys = np.log(np.maximum(w, 1e-30)) + rng.gumbel(size=len(w))
            pick = np.argsort(-keys)[:sample_size]
            neigh, ids = neigh[pick], ids[pick]
        out_n.append(neigh)
        out_c.append(len(neigh))
        if ev is not None:
            out_e.append(ev[ids])
    res = (Tensor(jnp.asarray(np.concatenate(out_n) if out_n else
                              np.zeros(0, np.int32), jnp.int32)),
           Tensor(jnp.asarray(np.array(out_c, np.int32))))
    if return_eids:
        if ev is None:
            raise ValueError("return_eids=True requires eids")
        return res + (Tensor(jnp.asarray(np.concatenate(out_e))),)
    return res
