"""Device management (reference: python/paddle/device/__init__.py).

Placement is owned by PjRt/XLA; these APIs report the TPU topology instead
of steering allocations. CUDA/XPU/custom-device predicates exist for API
parity and report False — there is exactly one backend family here: XLA
(tpu on hardware, cpu for tests).
"""
from __future__ import annotations

import jax

_current_device = [None]


def get_all_devices():
    return jax.devices()


def device_count():
    return jax.device_count()


def local_device_count():
    return jax.local_device_count()


def set_device(device):
    _current_device[0] = device
    return device


def get_device():
    if _current_device[0] is not None:
        return _current_device[0]
    d = jax.devices()[0]
    return f"{d.platform}:{d.id}"


def is_compiled_with_cuda():
    return False


def is_compiled_with_rocm():
    return False


def is_compiled_with_xpu():
    return False


def is_compiled_with_cinn():
    return False


def is_compiled_with_custom_device(name=None):
    return False


def is_compiled_with_distribute():
    return True


def is_compiled_with_tpu():
    return any(d.platform in ("tpu", "axon") for d in jax.devices())


class cuda:
    """Namespace shim for paddle.device.cuda."""

    @staticmethod
    def device_count():
        return 0

    @staticmethod
    def is_available():
        return False


def synchronize(device=None):
    import jax
    (jax.device_put(0) + 0).block_until_ready()


class Stream:
    """No-op stream shim: XLA orders execution itself; exposed for API
    parity with paddle.device.Stream."""

    def __init__(self, device=None, priority=2):
        self.device = device

    def synchronize(self):
        synchronize()


class Event:
    def __init__(self, enable_timing=False):
        pass

    def record(self, stream=None):
        pass

    def synchronize(self):
        synchronize()
