"""Device management (reference: python/paddle/device/__init__.py).

Placement is owned by PjRt/XLA; these APIs report the TPU topology instead
of steering allocations. CUDA/XPU/custom-device predicates exist for API
parity and report False — there is exactly one backend family here: XLA
(tpu on hardware, cpu for tests).
"""
from __future__ import annotations

import jax

_current_device = [None]


def get_all_devices():
    return jax.devices()


def device_count():
    return jax.device_count()


def local_device_count():
    return jax.local_device_count()


def set_device(device):
    _current_device[0] = device
    return device


def get_device():
    if _current_device[0] is not None:
        return _current_device[0]
    d = jax.devices()[0]
    return f"{d.platform}:{d.id}"


def is_compiled_with_cuda():
    return False


def is_compiled_with_rocm():
    return False


def is_compiled_with_xpu():
    return False


def is_compiled_with_cinn():
    return False


def is_compiled_with_custom_device(name=None):
    return False


def is_compiled_with_distribute():
    return True


def is_compiled_with_tpu():
    return any(d.platform in ("tpu", "axon") for d in jax.devices())


class cuda:
    """Namespace shim for paddle.device.cuda."""

    @staticmethod
    def device_count():
        return 0

    @staticmethod
    def is_available():
        return False


def synchronize(device=None):
    import jax
    (jax.device_put(0) + 0).block_until_ready()


class Stream:
    """No-op stream shim: XLA orders execution itself; exposed for API
    parity with paddle.device.Stream."""

    def __init__(self, device=None, priority=2):
        self.device = device

    def synchronize(self):
        synchronize()


class Event:
    def __init__(self, enable_timing=False):
        pass

    def record(self, stream=None):
        pass

    def synchronize(self):
        synchronize()


# -- remaining reference surface (reference: python/paddle/device/__init__)

class _Place:
    def __init__(self, kind, dev_id=0):
        self._kind, self._dev_id = kind, dev_id

    def __repr__(self):
        return f"Place({self._kind}:{self._dev_id})"


class XPUPlace(_Place):
    def __init__(self, dev_id=0):
        super().__init__("xpu", dev_id)


class IPUPlace(_Place):
    def __init__(self, dev_id=0):
        super().__init__("ipu", dev_id)


def get_all_device_type():
    import jax
    return sorted({d.platform for d in jax.devices()})


def get_all_custom_device_type():
    return []


def get_available_device():
    import jax
    return [f"{d.platform}:{d.id}" for d in jax.devices()]


def get_available_custom_device():
    return []


def get_cudnn_version():
    return None  # no cuDNN on the TPU backend


_current_stream = Stream()


def current_stream(device=None):
    return _current_stream


def set_stream(stream):
    global _current_stream
    prev = _current_stream
    _current_stream = stream
    return prev


class stream_guard:
    """(reference: device/__init__.py stream_guard) — XLA owns ordering;
    the guard swaps the bookkeeping object only."""

    def __init__(self, stream):
        self._stream = stream
        self._prev = None

    def __enter__(self):
        self._prev = set_stream(self._stream)
        return self._stream

    def __exit__(self, *exc):
        set_stream(self._prev)
        return False


def is_compiled_with_ipu():
    return False
