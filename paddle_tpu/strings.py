"""String tensors and string ops.

Reference: paddle/phi/kernels/strings/ — strings_empty_kernel.h,
strings_copy_kernel.h, strings_lower_upper_kernel.h (+ case_utils.h /
unicode.h for the utf8 path). The reference stores pstring arrays on
CPU/GPU; TPU has no string support at all, so the TPU-native design
keeps StringTensor a HOST container (numpy object array of python str)
with the same op surface. Anything numeric derived from strings
(lengths, hashes, token ids) crosses to device as int arrays.
"""
from __future__ import annotations

import numpy as np

__all__ = ["StringTensor", "empty", "empty_like", "copy", "lower",
           "upper", "to_string_tensor"]


class StringTensor:
    """Host-resident string array (reference: phi::StringTensor,
    paddle/phi/core/string_tensor.h)."""

    def __init__(self, data, name=None):
        arr = np.asarray(data, dtype=object)
        flat = [("" if s is None else str(s)) for s in arr.ravel()]
        self._data = np.asarray(flat, dtype=object).reshape(arr.shape)
        self.name = name

    @property
    def shape(self):
        return list(self._data.shape)

    def numpy(self):
        return self._data

    def tolist(self):
        return self._data.tolist()

    def __getitem__(self, idx):
        out = self._data[idx]
        if isinstance(out, str):
            return out
        return StringTensor(out)

    def __len__(self):
        return len(self._data)

    def __eq__(self, other):
        o = other._data if isinstance(other, StringTensor) else other
        return np.asarray(self._data == np.asarray(o, dtype=object))

    def __repr__(self):
        return f"StringTensor(shape={self.shape}, {self._data!r})"

    # numeric bridges (lengths/bytes go to device as ints)
    def lengths(self):
        """Per-string character counts as an int32 numpy array."""
        return np.vectorize(len, otypes=[np.int32])(self._data)


def to_string_tensor(data, name=None) -> StringTensor:
    return StringTensor(data, name=name)


def empty(shape) -> StringTensor:
    """reference: strings_empty_kernel.h EmptyKernel."""
    return StringTensor(np.full(tuple(shape), "", dtype=object))


def empty_like(x: StringTensor) -> StringTensor:
    """reference: strings_empty_kernel.h EmptyLikeKernel."""
    return empty(x.shape)


def copy(x: StringTensor) -> StringTensor:
    """Deep copy (reference: strings_copy_kernel.h Copy)."""
    return StringTensor(x.numpy().copy())


def _case_map(x, fn, utf8):
    if not utf8:
        # ascii-only transform: the reference's non-utf8 kernel touches
        # only [A-Za-z] bytes (case_utils.h AsciiCaseConverter)
        def conv(s):
            return "".join(fn(c) if c.isascii() else c for c in s)
    else:
        conv = fn
    out = np.vectorize(conv, otypes=[object])(x.numpy())
    return StringTensor(out)


def lower(x: StringTensor, use_utf8_encoding: bool = False):
    """reference: strings_lower_upper_kernel.h StringLowerKernel —
    ascii byte-wise by default, full unicode when use_utf8_encoding."""
    return _case_map(x, str.lower, use_utf8_encoding)


def upper(x: StringTensor, use_utf8_encoding: bool = False):
    """reference: strings_lower_upper_kernel.h StringUpperKernel."""
    return _case_map(x, str.upper, use_utf8_encoding)
