"""Fleet telemetry plane: cross-rank heartbeats, straggler detection,
and a crash flight recorder.

Everything the PR 3/7 observability plane records is process-local: a
rank that stalls, wedges, or silently slows down is invisible to its
peers until the watchdog kills the job — and the evidence (metrics,
spans, in-flight requests) dies with the process. Production TPU
stacks (MegaScale-line systems, PAPERS.md) treat the *cross-rank* view
as the primary health signal: per-rank progress published to a shared
store, an aggregator computing step skew and straggler flags, and a
crash dump rich enough to debug post-mortem. This module is that layer
for paddle_tpu, built on the pieces already here:

    FleetHeartbeat     each rank periodically publishes a compact
                       bounded JSON snapshot (step, tokens/sec, MFU,
                       recompiles, pending async saves, serving queue
                       depth, wall time) into the rendezvous TCPStore
                       under ``fleet/hb/{rank}`` — a daemon thread,
                       writes via the distributed/retries.py policy on
                       its own cloned client connection so a blocking
                       wait() on the shared socket can never starve
                       the beat
    FleetAggregator    rank 0 (or the serving process, behind
                       ``GET /debug/fleet``) scans every rank's key
                       into one view: step skew (max-min), slowest-
                       rank lag vs the fleet median, stale-rank count,
                       fleet-summed tokens/sec — published as the
                       catalogued ``fleet.*`` instruments — plus a
                       straggler detector flagging any rank whose step
                       lags the median by more than ``straggler_steps``
                       or whose heartbeat age exceeds ``stale_after_s``
    flight recorder    ``record_crash(reason, exc=...)`` atomically
                       dumps a self-contained bundle directory —
                       metrics JSON snapshot, span-ring chrome trace,
                       /debug/requests-shape registry rows, the
                       last-seen fleet view, exception + traceback +
                       all-thread stacks — with bounded retention.
                       Wired to watchdog aborts and restartable faults
                       in elastic.run_resilient() and to the serving
                       SIGTERM drain; ``tools/obs_dump.py``
                       pretty-prints a bundle.

Chaos sites ``fleet.heartbeat.delay`` (the beat is stamped BEFORE the
injected delay, so the published snapshot ages — the heartbeat-age
straggler lever) and ``fleet.heartbeat.drop`` (the publish is skipped,
so the rank's last beat goes stale) drive the detector
deterministically in tests.

Contract with the hot path — the same one distributed/chaos.py set:
disabled (the default), the whole plane is one module-attribute check
at each wiring site (`Trainer.fleet_heartbeat`, serving's drain dump,
elastic's fault dump all gate on ``observability.ENABLED``): no
threads, no store traffic, no bundle directories. The flight recorder
additionally no-ops until a bundle directory is configured
(``configure_flight_recorder(dir=...)`` or ``PADDLE_TPU_FLIGHT_DIR``).

Importing this module never touches jax; chaos and the retry policy
import lazily on the (cold, already-enabled) publish path.
"""
from __future__ import annotations

import itertools
import json
import os
import shutil
import socket
import sys
import threading
import time
import traceback

from paddle_tpu.observability.metrics import REGISTRY
from paddle_tpu.observability import trace
from paddle_tpu.observability import requests as _requests

__all__ = [
    "HEARTBEAT_PREFIX", "FleetHeartbeat", "FleetAggregator",
    "registry_sample", "last_view", "clear",
    "FLIGHT", "configure_flight_recorder", "record_crash",
    "flight_records",
]

#: store key namespace one rank's heartbeat lives under: f"fleet/hb/{rank}"
HEARTBEAT_PREFIX = "fleet/hb/"

#: a heartbeat snapshot is COMPACT and BOUNDED: at most this many
#: fields survive (sorted; identity fields always kept), floats are
#: rounded — the store is a rendezvous service, not a time-series DB.
_MAX_FIELDS = 24

_HOST = socket.gethostname()

_view_lock = threading.Lock()
_LAST_VIEW: dict | None = None


def last_view():
    """The most recent FleetAggregator view scanned in this process
    (None before any scan) — the flight recorder ships it so a crash
    bundle carries the last cross-rank picture, not just local state."""
    with _view_lock:
        return _LAST_VIEW


def _remember(view):
    global _LAST_VIEW
    with _view_lock:
        _LAST_VIEW = view


def clear():
    """Drop the cached fleet view (tests / observability.enable(reset))."""
    global _LAST_VIEW
    with _view_lock:
        _LAST_VIEW = None


def registry_sample(registry=None) -> dict:
    """The default per-rank heartbeat payload, read from the shared
    metrics registry: only instruments that have actually recorded
    appear, so an inference-only process ships queue depth without
    fake training fields and vice versa."""
    reg = registry if registry is not None else REGISTRY
    names = reg.names()
    out = {}
    if "train.steps" in names:
        out["step"] = int(reg.counter("train.steps").value())
    if "train.tokens_per_sec" in names:
        v = reg.gauge("train.tokens_per_sec").value()
        if v is not None:
            out["tokens_per_sec"] = float(v)
    if "train.mfu" in names:
        v = reg.gauge("train.mfu").value()
        if v is not None:
            out["mfu"] = float(v)
    if "train.recompiles" in names:
        # summed across the per-shape label cells (trainer labels each
        # recompile with its triggering batch-shape signature)
        out["recompiles"] = int(sum(
            reg.counter("train.recompiles").labeled().values()))
    if "checkpoint.async.pending" in names:
        v = reg.gauge("checkpoint.async.pending").value()
        if v is not None:
            out["ckpt_async_pending"] = float(v)
    if "train.sentry.steps_since_good" in names:
        # a rank whose training is numerically degrading shows up here
        # (climbing steps-since-promoted-checkpoint, mounting trigger
        # count) BEFORE its sentry quarantines it
        v = reg.gauge("train.sentry.steps_since_good").value()
        if v is not None:
            out["steps_since_good"] = float(v)
    if "train.sentry.triggers" in names:
        out["sentry_triggers"] = int(sum(
            reg.counter("train.sentry.triggers").labeled().values()))
    return out


def _json_value(v):
    """A JSON-serializable scalar for one snapshot field. sample_fn /
    extra_fn values in this codebase commonly come off numpy/jax
    (np.int64 queue depths, np.float32 gauges) — json.dumps rejects
    those, and a publisher that raises on EVERY beat makes the rank
    look stale with no visible error. Numbers coerce through float
    (integral values stay integers), everything else stringifies."""
    if v is None or isinstance(v, (bool, str, int)):
        return v
    try:
        f = float(v)
    except (TypeError, ValueError):
        return str(v)[:64]
    if f != f or f in (float("inf"), float("-inf")):
        return str(f)
    if f.is_integer() and abs(f) < 2 ** 53:
        return int(f)
    return round(f, 4)


def _clone_store(store):
    """A private client connection for the publisher thread when the
    store can provide one (TCPStore.clone): a blocking wait() on the
    shared client's socket must never starve the heartbeat."""
    clone = getattr(store, "clone", None)
    if clone is not None:
        try:
            return clone()
        except Exception:  # lint: disable=silent-swallow -- clone is an optimization; fall back to the shared client
            pass
    return store


class FleetHeartbeat:
    """One rank's heartbeat publisher.

    ``sample_fn() -> dict`` overrides the registry-derived payload
    (tests drive the detector with synthetic steps this way);
    ``extra_fn() -> dict`` merges on top (serving attaches its queue
    depth). `start()` publishes one beat synchronously — the rank is
    rendezvous-visible immediately — then a daemon thread re-publishes
    every `interval` seconds through the retry policy. A store that
    stays down for `max_consecutive_errors` beats ends the loop: the
    job is ending anyway, and a daemon thread hammering a dead socket
    helps nobody.
    """

    def __init__(self, store, rank, world_size, *, interval=2.0,
                 sample_fn=None, extra_fn=None, prefix=HEARTBEAT_PREFIX,
                 retry_policy=None, max_consecutive_errors=8):
        self.store = store
        self.rank = int(rank)
        self.world_size = int(world_size)
        self.interval = float(interval)
        self.sample_fn = sample_fn
        self.extra_fn = extra_fn
        self.key = f"{prefix}{self.rank}"
        self.max_consecutive_errors = int(max_consecutive_errors)
        self.beats = 0              # publishes that landed in the store
        self._seq = 0               # publish attempts (snapshot field)
        self._consecutive_errors = 0
        self._stop_ev = threading.Event()
        self._thread = None
        self._pub_store = _clone_store(store)
        if retry_policy is not None:
            self._retry = retry_policy
        else:
            from paddle_tpu.distributed.retries import default_policy
            self._retry = default_policy(retryable=(ConnectionError,))

    # -- sampling -----------------------------------------------------
    def sample(self) -> dict:
        """The snapshot one publish ships: identity + wall-time stamp +
        the registry-derived (or sample_fn-provided) payload, bounded
        to _MAX_FIELDS fields with rounded floats."""
        snap = {"rank": self.rank, "world_size": self.world_size,
                "seq": self._seq, "time": time.time(),
                "pid": os.getpid(), "host": _HOST}
        body = (self.sample_fn() if self.sample_fn is not None
                else registry_sample())
        if self.extra_fn is not None:
            body = {**body, **self.extra_fn()}
        for k in sorted(body):
            if len(snap) >= _MAX_FIELDS:
                break
            snap[str(k)] = _json_value(body[k])
        return snap

    # -- publishing ---------------------------------------------------
    def publish(self) -> bool:
        """One beat: sample, stamp, chaos gate, store.set through the
        retry policy. Returns True when the beat landed. The snapshot
        is stamped BEFORE the chaos delay so an injected slow publish
        ages the beat the aggregator reads."""
        snap = self.sample()
        self._seq += 1
        payload = json.dumps(snap, separators=(",", ":")).encode()
        from paddle_tpu.distributed import chaos
        if chaos.ENABLED:
            chaos.maybe_delay("fleet.heartbeat.delay")
            if chaos.should_fire("fleet.heartbeat.drop"):
                return False
        self._retry.run(self._pub_store.set, self.key, payload,
                        desc=f"fleet.heartbeat({self.key})")
        self.beats += 1
        REGISTRY.inc("fleet.heartbeats")
        return True

    def _loop(self):
        while not self._stop_ev.wait(self.interval):
            try:
                self.publish()
            except Exception:   # noqa: BLE001 — the plane must outlive a flaky store
                REGISTRY.inc("fleet.heartbeat.errors")
                self._consecutive_errors += 1
                if self._consecutive_errors >= self.max_consecutive_errors:
                    return      # store is gone: the job is ending anyway
            else:
                self._consecutive_errors = 0

    def start(self):
        self._stop_ev.clear()
        try:
            self.publish()
        except Exception:   # noqa: BLE001 — a slow rendezvous must not block training start
            REGISTRY.inc("fleet.heartbeat.errors")
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"fleet-heartbeat-{self.rank}")
        self._thread.start()
        return self

    def stop(self, join_timeout=5.0):
        self._stop_ev.set()
        t = self._thread
        if t is not None:
            t.join(timeout=join_timeout)
            self._thread = None
        if self._pub_store is not self.store:
            try:
                self._pub_store.close()
            except Exception:  # lint: disable=silent-swallow -- best-effort close of the private publisher connection
                pass


class FleetAggregator:
    """The cross-rank reader: scan every rank's heartbeat key into one
    view, publish the ``fleet.*`` gauges, and flag stragglers.

    A rank is STALE when its heartbeat is missing or older than
    ``stale_after_s``; a rank is a STRAGGLER when it is stale or its
    step lags the median of the fresh ranks' steps by more than
    ``straggler_steps``. `scan()` is a plain synchronous call (the
    serving ``GET /debug/fleet`` path, and what tests drive
    deterministically); `start()` wraps it in a rank-0 daemon thread.
    Every scan is cached process-wide (`last_view`) so a crash bundle
    carries the final cross-rank picture.
    """

    def __init__(self, store, world_size, *, stale_after_s=10.0,
                 straggler_steps=100, prefix=HEARTBEAT_PREFIX,
                 publish=True):
        self.store = store
        self.world_size = int(world_size)
        self.stale_after_s = float(stale_after_s)
        self.straggler_steps = int(straggler_steps)
        self.prefix = prefix
        self.publish = publish
        self._last = None           # this aggregator's newest view
        self._stop_ev = threading.Event()
        self._thread = None

    # -- reading ------------------------------------------------------
    def read(self, rank):
        """One rank's parsed snapshot, or None when the key is missing
        or unreadable (a read error is a liveness unknown, not a
        crash)."""
        key = f"{self.prefix}{rank}"
        try:
            # check() first: a blind get() on a missing key blocks for
            # the store's full timeout waiting for it to appear
            if hasattr(self.store, "check") and not self.store.check(key):
                return None
            snap = json.loads(self.store.get(key).decode())
        except Exception:   # noqa: BLE001 — an unreadable beat counts as missing
            REGISTRY.inc("fleet.heartbeat.errors")
            return None
        return snap if isinstance(snap, dict) else None

    def scan(self, now=None, max_age_s=None) -> dict:
        """One aggregation pass -> the fleet view dict (also cached via
        `last_view` and, with publish=True, mirrored into the
        catalogued fleet.* instruments). With `max_age_s`, a cached
        view at most that old is returned WITHOUT touching the store —
        the GET /debug/fleet path uses this so a router polling every
        replica does not multiply into world_size store RPCs per poll
        against the one rendezvous service."""
        now = time.time() if now is None else now
        if max_age_s is not None and self._last is not None \
                and now - self._last["time"] <= max_age_s:
            return self._last
        rows = []
        for r in range(self.world_size):
            snap = self.read(r)
            if snap is None:
                rows.append({"rank": r, "present": False, "stale": True,
                             "age_s": None, "step": None})
                continue
            age = max(0.0, now - float(snap.get("time", 0.0)))
            row = dict(snap)
            row.update(rank=r, present=True,
                       age_s=round(age, 4),
                       stale=age > self.stale_after_s)
            rows.append(row)
        fresh_steps = [r["step"] for r in rows
                       if not r["stale"] and isinstance(
                           r.get("step"), (int, float))]
        median = _median(fresh_steps)
        for row in rows:
            step = row.get("step")
            lag = (float(median) - float(step)
                   if median is not None
                   and isinstance(step, (int, float)) else None)
            row["lag"] = lag
            row["straggler"] = bool(
                row["stale"]
                or (lag is not None and lag > self.straggler_steps))
        all_steps = [r["step"] for r in rows
                     if isinstance(r.get("step"), (int, float))]
        stragglers = [r["rank"] for r in rows if r["straggler"]]
        summary = {
            "present": sum(1 for r in rows if r["present"]),
            "stale_ranks": sum(1 for r in rows if r["stale"]),
            "stragglers": stragglers,
            "median_step": median,
            "step_skew": (float(max(all_steps) - min(all_steps))
                          if all_steps else 0.0),
            "step_lag": (max(0.0, float(median) - float(min(all_steps)))
                         if median is not None and all_steps else 0.0),
            "fleet_tokens_per_sec": round(sum(
                float(r.get("tokens_per_sec") or 0.0)
                for r in rows if r["present"]), 4),
        }
        view = {"time": now, "world_size": self.world_size,
                "stale_after_s": self.stale_after_s,
                "straggler_steps": self.straggler_steps,
                "ranks": rows, "summary": summary}
        if self.publish:
            self._publish(view)
        self._last = view
        _remember(view)
        return view

    def _publish(self, view):
        s = view["summary"]
        REGISTRY.set_gauge("fleet.step.skew", s["step_skew"])
        REGISTRY.set_gauge("fleet.step.lag", s["step_lag"])
        REGISTRY.set_gauge("fleet.stale_ranks", s["stale_ranks"])
        REGISTRY.set_gauge("fleet.stragglers", len(s["stragglers"]))
        REGISTRY.set_gauge("fleet.tokens_per_sec",
                           s["fleet_tokens_per_sec"])
        for row in view["ranks"]:
            # per-rank flag gauge: cardinality bounded by world size
            REGISTRY.set_gauge("fleet.straggler",
                               1.0 if row["straggler"] else 0.0,
                               rank=row["rank"])

    # -- background form (rank 0) ------------------------------------
    def _loop(self, interval):
        while not self._stop_ev.wait(interval):
            try:
                self.scan()
            except Exception:   # noqa: BLE001 — the monitor must outlive a flaky store
                REGISTRY.inc("fleet.heartbeat.errors")

    def start(self, interval=2.0):
        self._stop_ev.clear()
        self._thread = threading.Thread(
            target=self._loop, args=(float(interval),), daemon=True,
            name="fleet-aggregator")
        self._thread.start()
        return self

    def stop(self, join_timeout=5.0):
        self._stop_ev.set()
        t = self._thread
        if t is not None:
            t.join(timeout=join_timeout)
            self._thread = None


def _median(values):
    if not values:
        return None
    vs = sorted(values)
    n = len(vs)
    mid = n // 2
    if n % 2:
        return float(vs[mid])
    return (float(vs[mid - 1]) + float(vs[mid])) / 2.0


# ---------------------------------------------------------------------------
# crash flight recorder
# ---------------------------------------------------------------------------


class _FlightConfig:
    """Flight-recorder knobs (module-global; set via
    configure_flight_recorder or PADDLE_TPU_FLIGHT_DIR /
    PADDLE_TPU_FLIGHT_KEEP, read once at import)."""

    __slots__ = ("dir", "max_keep")

    def __init__(self):
        self.dir = os.environ.get("PADDLE_TPU_FLIGHT_DIR") or None
        try:
            self.max_keep = int(os.environ.get(
                "PADDLE_TPU_FLIGHT_KEEP", "5"))
        except ValueError:
            # a typo'd ops knob must not make `import paddle_tpu` raise
            self.max_keep = 5


FLIGHT = _FlightConfig()


def configure_flight_recorder(dir="unset", max_keep=None):
    """Arm (or with dir=None disarm) the crash flight recorder and/or
    set how many bundles are retained. Omitted arguments keep their
    current value."""
    if dir != "unset":
        FLIGHT.dir = dir
    if max_keep is not None:
        FLIGHT.max_keep = int(max_keep)


def flight_records(dir=None) -> list:
    """Bundle directories under `dir` (default: the configured one),
    oldest first — names embed a millisecond timestamp + sequence so
    lexicographic order IS recency order."""
    d = dir if dir is not None else FLIGHT.dir
    if d is None or not os.path.isdir(d):
        return []
    return sorted(os.path.join(d, n) for n in os.listdir(d)
                  if n.startswith("flight-"))


_flight_lock = threading.Lock()
_flight_seq = itertools.count(1)

#: every bundle carries exactly these artifacts (manifest.json lists
#: them too; tools/obs_dump.py renders them)
BUNDLE_FILES = ("manifest.json", "metrics.json", "trace.json",
                "requests.json", "fleet.json", "traceback.txt")


def record_crash(reason, exc=None, extra=None, view=None,
                 dir=None) -> str | None:
    """Atomically dump a self-contained diagnostic bundle directory and
    enforce retention; returns the bundle path, or None when no bundle
    directory is configured (the disarmed default — callers gate on
    ``observability.ENABLED`` so the disabled plane never reaches
    here).

    Bundle layout (BUNDLE_FILES):
        manifest.json   reason, wall time, pid/host, exception summary,
                        caller `extra`, artifact list
        metrics.json    full metrics-registry snapshot
        trace.json      span ring as a chrome-trace document
        requests.json   /debug/requests-shape rows of in-flight requests
        fleet.json      `view` or the last-seen aggregator view
        traceback.txt   the exception's traceback + ALL thread stacks
                        (the watchdog-abort case is usually a hang:
                        where every thread is stuck IS the diagnosis)

    The bundle is written into a hidden ``.tmp`` directory and renamed
    into place, so a crash *during* the dump never leaves a
    half-bundle that obs_dump would trip over.
    """
    d = dir if dir is not None else FLIGHT.dir
    if d is None:
        return None
    slug = "".join(c if c.isalnum() or c in "-_" else "_"
                   for c in str(reason))[:48] or "crash"
    with _flight_lock:
        t = time.time()
        # pid in the NAME, not just the manifest: a fleet-wide abort
        # dumps every rank in the same millisecond into a shared dir,
        # and the per-process sequence alone would collide (the loser's
        # bundle — the artifact this feature exists for — would be lost)
        name = (f"flight-{int(t * 1000):014d}-p{os.getpid()}-"
                f"{next(_flight_seq):04d}-{slug}")
        os.makedirs(d, exist_ok=True)
        tmp = os.path.join(d, "." + name + ".tmp")
        final = os.path.join(d, name)
        os.makedirs(tmp)
        _dump_json(os.path.join(tmp, "metrics.json"), REGISTRY.snapshot)
        _dump_json(os.path.join(tmp, "trace.json"),
                   trace.export_chrome_trace)
        _dump_json(os.path.join(tmp, "requests.json"), _snapshot_requests)
        _dump_json(os.path.join(tmp, "fleet.json"),
                   lambda: _snapshot_fleet(view))
        with open(os.path.join(tmp, "traceback.txt"), "w") as f:
            f.write(_format_failure(exc))
        manifest = {
            "version": 1, "reason": str(reason), "time": t,
            "iso_time": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                      time.gmtime(t)),
            "pid": os.getpid(), "host": _HOST,
            "exception": None if exc is None else {
                "type": type(exc).__name__, "message": str(exc)},
            "extra": extra or {},
            "files": list(BUNDLE_FILES),
        }
        _dump_json(os.path.join(tmp, "manifest.json"), lambda: manifest)
        os.replace(tmp, final)
        recs = flight_records(d)
        for old in recs[:max(0, len(recs) - FLIGHT.max_keep)]:
            shutil.rmtree(old, ignore_errors=True)
    REGISTRY.inc("fleet.flight.records", reason=slug)
    return final


def _dump_json(path, builder):
    """Write builder() as JSON; one broken artifact records its error
    in place instead of sinking the whole bundle."""
    try:
        data = builder()
    except Exception as e:      # noqa: BLE001 — see docstring
        data = {"error": repr(e)}
    with open(path, "w") as f:
        json.dump(data, f, indent=1, sort_keys=True, default=str)


def _snapshot_requests():
    rows = _requests.live_requests()
    return {"count": len(rows), "requests": rows}


def _snapshot_fleet(view):
    v = view if view is not None else last_view()
    if v is None:
        return {"available": False}
    return {"available": True, "view": v}


def _format_failure(exc):
    parts = []
    if exc is not None:
        parts.append("== exception ==\n" + "".join(
            traceback.format_exception(type(exc), exc,
                                       exc.__traceback__)))
    parts.append("== all thread stacks ==\n" + _thread_stacks())
    return "\n".join(parts)


def _thread_stacks() -> str:
    names = {t.ident: t.name for t in threading.enumerate()}
    parts = []
    for ident, frame in sys._current_frames().items():
        parts.append(f"-- thread {names.get(ident, '?')} "
                     f"(ident={ident}) --\n"
                     + "".join(traceback.format_stack(frame)))
    return "\n".join(parts)
