"""Unified observability: metrics registry, span tracing, training
telemetry (reference: python/paddle/profiler is the reference's only
telemetry layer; production TPU stacks — MegaScale et al. — credit
per-step tokens/sec + MFU, RPC/collective counters and restart
accounting for keeping large runs healthy. This package is that plane
for paddle_tpu).

Three pieces, one switch:

    metrics.py    thread-safe MetricsRegistry of Counter/Gauge/
                  Histogram with a closed name catalogue (METRICS),
                  JSON snapshot + Prometheus text exposition (served
                  at GET /metrics by inference/serving.PredictorServer)
    trace.py      span(name, **attrs) -> bounded ring buffer ->
                  chrome-trace JSON, mergeable with the profiler's
                  HostTracer events
    telemetry.py  per-step training reporter: tokens/sec/chip + MFU
                  (the bench.py math, in-framework), lagged loss,
                  driven by parallel/trainer.py
    fleet.py      the cross-rank layer: per-rank heartbeats into the
                  rendezvous TCPStore, an aggregator computing step
                  skew + straggler flags (fleet.* instruments, served
                  at GET /debug/fleet), and the crash flight recorder
                  (atomic diagnostic bundles, tools/obs_dump.py)

Contract with the hot path — the same one distributed/chaos.py set:
when observability is disabled (the default), every instrumentation
point is a single module-attribute load + falsy branch:

    if observability.ENABLED:
        observability.inc("store.rpc.retries")

No dict lookup, no allocation, no lock. Enabling is explicit —
`observability.enable()` in-process, or PADDLE_TPU_OBS=1 in the
environment (read once at import). The serving stack's own request
counters are the exception: they are always on because they REPLACE
the /stats bookkeeping PredictorServer already paid for (per-server
registries, not this module's global one).

Metric names at instrumentation sites must be string literals from
the metrics.METRICS catalogue; tools/check_metric_names.py (tier-1
wired) fails the build otherwise.

Importing this package never touches jax.
"""
from __future__ import annotations

import os

from paddle_tpu.observability import metrics as metrics  # noqa: PLC0414
from paddle_tpu.observability import trace as trace      # noqa: PLC0414
from paddle_tpu.observability import requests as requests  # noqa: PLC0414
from paddle_tpu.observability import fleet as fleet      # noqa: PLC0414
from paddle_tpu.observability.metrics import (
    METRICS, MetricsRegistry, REGISTRY)
from paddle_tpu.observability.trace import Span, export_chrome_trace
from paddle_tpu.observability.requests import RequestContext

__all__ = [
    "ENABLED", "enable", "disable", "scoped", "inc", "observe",
    "set_gauge", "span", "METRICS", "MetricsRegistry", "REGISTRY",
    "Span", "export_chrome_trace", "metrics", "trace", "requests",
    "RequestContext", "fleet",
]

# the ONE attribute hot paths branch on
ENABLED = False


def enable(reset=False):
    """Turn instrumentation on process-wide. `reset=True` also clears
    the global registry and span ring (test harness form)."""
    global ENABLED
    if reset:
        REGISTRY.reset()
        trace.clear()
        requests.clear()
        fleet.clear()
    ENABLED = True


def disable():
    """Back to the zero-cost default; recorded data is kept."""
    global ENABLED
    ENABLED = False


class _Scoped:
    def __init__(self, reset):
        self._reset = reset

    def __enter__(self):
        self._prev = ENABLED
        enable(reset=self._reset)
        return REGISTRY

    def __exit__(self, *exc):
        global ENABLED
        ENABLED = self._prev
        return False


def scoped(reset=True):
    """`with observability.scoped() as registry:` — enable for a block,
    restoring the previous state (including disabled) on exit."""
    return _Scoped(reset)


# -- instrumentation surface ------------------------------------------------
# Call sites gate with `if observability.ENABLED:` so the disabled cost
# is one attribute check; these helpers themselves always record (into
# the global REGISTRY), which is what tests and scoped() rely on.

def inc(name, n=1, **labels):
    REGISTRY.inc(name, n, **labels)


def observe(name, v, **labels):
    REGISTRY.observe(name, v, **labels)


def set_gauge(name, v, **labels):
    REGISTRY.set_gauge(name, v, **labels)


class _NoopSpan:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NOOP_SPAN = _NoopSpan()


def span(name, **attrs):
    """Timed scope -> the trace ring. Cheap when disabled: returns a
    shared no-op context manager without allocating."""
    if not ENABLED:
        return _NOOP_SPAN
    return Span(name, attrs)


# -- env bootstrap (read once at import) ------------------------------------

if os.environ.get("PADDLE_TPU_OBS") == "1":
    enable()
