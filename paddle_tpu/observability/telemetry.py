"""Per-step training telemetry: tokens/sec/chip, MFU, loss, skips.

The north star (Llama-3-8B-class MFU on v5p) previously had no
in-framework measurement — the MFU math lived only in bench.py. This
module is that math as a runtime reporter: `TrainingTelemetry` turns
(tokens, step wall time) into tokens/sec and an MFU estimate using the
SAME flops-per-token helper bench.py uses (models/llama.py
`flops_per_token`, including the 8/6 recompute replay factor) and the
same per-chip peak-FLOPs table, publishing gauges/histograms into the
shared metrics registry. `parallel/trainer.py` drives it when
observability is enabled; the cost when disabled is one attribute
check in Trainer.step.

Two measurement caveats, both deliberate:
  - step time is the interval between consecutive step() dispatches.
    Dispatch is async, but donated buffers backpressure the host, so
    in steady state the interval converges to device step time (the
    same quantity bench.py measures over a synced window).
  - the loss gauge lags `loss_lag` steps: a loss read that young would
    force a host sync and stall the dispatch pipeline; by the time a
    step is `loss_lag` old its value is already on host and float() is
    free.

Importing this module never touches jax; model-specific helpers import
lazily inside functions.
"""
from __future__ import annotations

import collections

from paddle_tpu.observability import metrics as _metrics

__all__ = ["PEAK_FLOPS", "peak_flops_for_kind", "detect_peak_flops",
           "flops_per_token_for", "TrainingTelemetry"]

# bf16 peak FLOP/s per chip by device kind (public TPU specs) — kept in
# lockstep with bench.py's _PEAK table; tests cross-check the two.
PEAK_FLOPS = {
    "TPU v4": 275e12,
    "TPU v5": 459e12,        # v5p
    "TPU v5 lite": 197e12,   # v5e
    "TPU v5e": 197e12,
    "TPU v6 lite": 918e12,   # v6e / Trillium
    "TPU v6e": 918e12,
}


def peak_flops_for_kind(kind: str) -> float:
    """Longest-key-first match (bench.py learned this the hard way:
    'TPU v5 lite' must win over 'TPU v5'). Unknown kinds assume v5p,
    the north-star part."""
    kind = kind or ""
    for k in sorted(PEAK_FLOPS, key=len, reverse=True):
        if kind.startswith(k) or k in kind:
            return PEAK_FLOPS[k]
    return 459e12


def detect_peak_flops():
    """Peak FLOP/s of device 0, or None off-TPU (MFU reads 0 there —
    a CPU-emulation 'MFU' would be noise)."""
    try:
        import jax
        dev = jax.devices()[0]
        if dev.platform != "tpu":
            return None
        return peak_flops_for_kind(getattr(dev, "device_kind", ""))
    except Exception:
        return None


def flops_per_token_for(model, seq_len: int) -> float:
    """Training FLOPs/token for `model`: the shared analytic helper
    (models/llama.py flops_per_token — 6N + attention term, x8/6 when
    the config says recompute) when the config quacks like a llama;
    otherwise the generic 6 x trainable-param-count estimate."""
    cfg = getattr(model, "config", None)
    ftok = None
    if cfg is not None:
        try:
            from paddle_tpu.models.llama import flops_per_token
            ftok = flops_per_token(cfg, seq_len)
        except Exception:
            ftok = None
    if ftok is None:
        n = 0
        for p in getattr(model, "parameters", lambda: [])():
            if not getattr(p, "stop_gradient", False):
                n += int(getattr(p, "size", 0) or 0)
        ftok = 6.0 * n
    if cfg is not None and getattr(cfg, "recompute", False):
        # remat replays each layer's forward once: ~8N/token not 6N
        ftok = ftok * 8.0 / 6.0
    return float(ftok)


class TrainingTelemetry:
    """Per-step reporter publishing into a metrics registry.

    flops_per_token: float, or a callable seq_len -> float (so the
    attention term can track the batch's actual sequence length).
    peak_flops: per-chip peak FLOP/s; None disables MFU (reports 0).
    """

    def __init__(self, flops_per_token=None, peak_flops=None,
                 registry=None, loss_lag=8):
        self._fpt = flops_per_token
        self.peak_flops = peak_flops
        self.registry = registry if registry is not None \
            else _metrics.REGISTRY
        self.loss_lag = max(0, int(loss_lag))
        self._loss_buf: collections.deque = collections.deque()
        self.steps = 0
        self.last_tokens_per_sec = 0.0
        self.last_mfu = 0.0
        self.last_loss = None

    @classmethod
    def for_model(cls, model, registry=None, peak_flops=None, **kw):
        """Reporter bound to `model`'s analytic flops-per-token and the
        detected chip peak."""
        if peak_flops is None:
            peak_flops = detect_peak_flops()
        return cls(
            flops_per_token=lambda seq: flops_per_token_for(model, seq),
            peak_flops=peak_flops, registry=registry, **kw)

    def flops_per_token(self, seq_len) -> float:
        if callable(self._fpt):
            return float(self._fpt(seq_len))
        return float(self._fpt or 0.0)

    def mfu(self, tokens_per_sec, seq_len) -> float:
        """tokens/sec/chip x FLOPs/token / chip peak — identically
        bench.py's formula (tests cross-check)."""
        if not self.peak_flops:
            return 0.0
        return tokens_per_sec * self.flops_per_token(seq_len) \
            / self.peak_flops

    def step(self, tokens, step_time_s, seq_len=None, loss=None,
             grad_norm=None):
        """Report one completed step. `loss` may be lazy (a jax array /
        Tensor); it is buffered and materialized `loss_lag` steps
        later, never blocking the current dispatch."""
        reg = self.registry
        self.steps += 1
        reg.inc("train.steps")
        if step_time_s and step_time_s > 0:
            reg.observe("train.step.seconds", step_time_s)
            tps = tokens / step_time_s
            self.last_tokens_per_sec = tps
            reg.set_gauge("train.tokens_per_sec", tps)
            seq = seq_len if seq_len is not None else tokens
            self.last_mfu = self.mfu(tps, seq)
            reg.set_gauge("train.mfu", self.last_mfu)
        if grad_norm is not None:
            reg.set_gauge("train.grad_norm", float(grad_norm))
        if loss is not None:
            self._loss_buf.append(loss)
            while len(self._loss_buf) > self.loss_lag:
                self._publish_loss(self._loss_buf.popleft())

    def _publish_loss(self, loss):
        try:
            val = float(loss)
        except Exception:
            return              # non-scalar / dead array: drop silently
        self.last_loss = val
        self.registry.set_gauge("train.loss", val)

    def flush(self):
        """Materialize every buffered loss (end of run / snapshot)."""
        while self._loss_buf:
            self._publish_loss(self._loss_buf.popleft())

    def snapshot(self) -> dict:
        self.flush()
        return {"steps": self.steps,
                "tokens_per_sec": round(self.last_tokens_per_sec, 2),
                "mfu": round(self.last_mfu, 4),
                "loss": self.last_loss}
