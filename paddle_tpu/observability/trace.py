"""Lightweight span tracing into a bounded ring buffer.

`observability.span(name, **attrs)` (the gated entry point — see
observability/__init__.py) wraps a host-side scope; completed spans
land in a process-wide ring buffer (oldest evicted first, so a
long-running job's memory is bounded) and export as chrome-trace JSON
that loads in chrome://tracing / perfetto. `export_chrome_trace`
merges the native profiler's HostTracer events on request so one
timeline shows both the coarse runtime spans recorded here (steps,
checkpoint saves, RPC retries) and the fine per-op scopes from
paddle_tpu/_native — and, side by side in perfetto, the XLA device
trace `jax.profiler` writes under its logdir.

Spans nest naturally: chrome-trace "X" (complete) events reconstruct
the stack from ts/dur containment per thread; `depth` is also recorded
explicitly in args for programmatic consumers.

Stdlib-only; importing this module never touches jax.
"""
from __future__ import annotations

import collections
import json
import os
import threading
import time

__all__ = ["Span", "record_span", "set_ring_capacity", "ring_capacity",
           "spans", "clear", "export_chrome_trace", "chrome_events"]

_DEFAULT_CAPACITY = 4096

_lock = threading.Lock()
_ring: collections.deque = collections.deque(maxlen=_DEFAULT_CAPACITY)
_tls = threading.local()


def set_ring_capacity(n: int):
    """Resize the span ring (keeps the newest spans)."""
    global _ring
    with _lock:
        _ring = collections.deque(_ring, maxlen=int(n))


def ring_capacity() -> int:
    return _ring.maxlen


def clear():
    with _lock:
        _ring.clear()


class Span:
    """One timed scope. Use through observability.span(...) so the
    disabled path stays a single attribute check; constructing a Span
    directly always records."""

    __slots__ = ("name", "attrs", "t0", "dur_us", "depth", "tid")

    def __init__(self, name, attrs=None):
        self.name = name
        self.attrs = attrs or {}
        self.t0 = 0.0
        self.dur_us = 0.0
        self.depth = 0
        self.tid = 0

    def __enter__(self):
        depth = getattr(_tls, "depth", 0)
        _tls.depth = depth + 1
        self.depth = depth
        self.tid = threading.get_ident()
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        self.dur_us = (time.perf_counter() - self.t0) * 1e6
        _tls.depth = self.depth
        if exc_type is not None:
            self.attrs = {**self.attrs, "error": exc_type.__name__}
        with _lock:
            _ring.append(self)
        return False


def record_span(name, t0, dur_us, *, depth=0, tid=None, attrs=None):
    """Append an externally-timed completed span to the ring. The
    slow-request exemplar path (observability/requests.py) rebuilds a
    request's lifecycle from its recorded timeline after the fact
    rather than timing a live scope; `t0` must be a
    time.perf_counter() value so the span lands on the same timeline
    as live span() scopes."""
    s = Span(name, attrs or {})
    s.t0 = float(t0)
    s.dur_us = float(dur_us)
    s.depth = int(depth)
    s.tid = int(tid) if tid is not None else threading.get_ident()
    with _lock:
        _ring.append(s)
    return s


def spans() -> list:
    """Snapshot of the ring, oldest first."""
    with _lock:
        return list(_ring)


def chrome_events() -> list:
    """Ring contents as chrome-trace event dicts. perf_counter has an
    arbitrary epoch; events are self-consistent with each other and
    with the HostTracer events merged by export_chrome_trace (both
    clocks are monotonic-since-boot on Linux)."""
    evs = []
    pid = os.getpid()
    for s in spans():
        args = {"depth": s.depth}
        args.update({str(k): v for k, v in s.attrs.items()})
        evs.append({"name": s.name, "ph": "X", "pid": pid,
                    "tid": s.tid, "ts": s.t0 * 1e6,
                    "dur": s.dur_us, "cat": "observability",
                    "args": args})
    return evs


def export_chrome_trace(path=None, merge_host_tracer=False) -> dict:
    """Chrome-trace document of the recorded spans; with
    `merge_host_tracer` the native profiler HostTracer's events (the
    per-op scopes the Profiler records) join the same timeline. Writes
    to `path` when given; always returns the document."""
    events = chrome_events()
    if merge_host_tracer:
        try:
            from paddle_tpu.profiler import utils as _utils
            events = events + list(_utils.host_chrome_events())
        except Exception:  # lint: disable=silent-swallow -- profiler backend unavailable: export spans alone
            pass
    doc = {"traceEvents": events, "displayTimeUnit": "ms",
           "metadata": {"producer": "paddle_tpu.observability"}}
    if path is not None:
        with open(path, "w") as f:
            json.dump(doc, f)
    return doc
