"""Per-request tracing & serving SLO telemetry.

The PR 3 observability plane sees the *process* (RPC counts, step MFU,
scrape-time gauges) but is blind to the *request*: nothing records what
one generation request experienced between admission and its last
streamed token. Continuous-batching servers (Orca / vLLM line of work,
PAPERS.md) treat time-to-first-token and inter-token latency as *the*
user-felt SLOs; the fleet router and multi-tenant QoS items in
ROADMAP.md route, shed, and enforce on exactly those signals. This
module is the request-level nervous system:

    RequestContext    one request's identity (request id + W3C trace
                      context) and its typed event timeline (admitted,
                      queued, scheduled, prefill start/end, first
                      token, every decode tick, finished / shed /
                      expired / cancelled / disconnected / error)
    contextvar        `set_current` / `current` propagate the context
                      from the HTTP handler thread into whatever layer
                      touches the request next (DynamicBatcher.submit,
                      PagedKVEngine.submit); serving copies the
                      contextvars context into its producer thread so
                      the engine sees the same request
    in-flight registry  bounded map of live contexts behind serving's
                      GET /debug/requests (stage + age per request:
                      the router's machine-readable signal)
    SLO instruments   request.ttft.seconds / request.itl.seconds /
                      request.queue_wait.seconds / request.prefill.
                      seconds / request.tokens histograms and the
                      request.outcome counter, recorded into the
                      process-wide metrics REGISTRY as the timeline
                      unfolds (catalogued in metrics.METRICS, audited
                      by tools/check_metric_names.py)
    exemplar sampler  a finished request that breached the configured
                      TTFT / total-latency threshold dumps its whole
                      lifecycle into the PR 3 span ring as nested
                      spans, so trace.export_chrome_trace shows what a
                      slow request actually waited on

Contract with the hot path — the same one distributed/chaos.py and the
package __init__ set: when observability is disabled (the default), no
context is ever created and every instrumentation site in serving /
batcher / engine is a single module-attribute load + falsy branch (or
one `is not None` check on a request that never got a context). Layers
below the HTTP server guard on the context handle itself, so a request
admitted while disabled stays zero-cost for its whole life even if
observability is enabled mid-flight.

Event timestamps use time.perf_counter() — the span ring's clock — so
exemplar spans land on the same timeline as live `span()` scopes.

Stdlib-only; importing this module never touches jax.
"""
from __future__ import annotations

import contextvars
import itertools
import os
import secrets
import threading
import time
import zlib

from paddle_tpu.observability.metrics import REGISTRY
from paddle_tpu.observability import trace

__all__ = [
    "EVENTS", "RequestContext", "parse_traceparent", "safe_request_id",
    "current", "set_current", "reset_current", "register",
    "live_requests", "configure", "clear",
]

#: the closed event-name catalogue (the metrics.METRICS pattern):
#: record() raises on anything else, so the timeline stays typed and
#: /debug/requests consumers can switch on `stage` exhaustively.
EVENTS = frozenset({
    "admitted",         # passed the server's admission gate
    "queued",           # waiting for a batch slot / engine slot
    "scheduled",        # slot assigned (batch formed / engine slot)
    "prefill_start",    # prompt prefill program dispatched
    "prefill_end",      # prompt prefill finished
    "first_token",      # first generated token accepted
    "tokens",           # a decode tick emitted tokens (attrs: n)
    # terminal events (exactly one per request, written by finish())
    "finished", "shed", "expired", "cancelled", "disconnected", "error",
})

#: finish(reason) outcome -> terminal timeline event. Reasons are the
#: serving /stats outcome keys plus the engine's terminal states; the
#: request.outcome counter keeps the RAW reason as its label.
_TERMINAL = {
    "ok": "finished", "finished": "finished",
    "expired": "expired", "deadline_exceeded": "expired",
    "cancelled": "cancelled", "disconnected": "disconnected",
}


def _terminal_event(reason: str) -> str:
    if reason.startswith("shed"):
        return "shed"
    return _TERMINAL.get(reason, "error")


# -- configuration ----------------------------------------------------------

class _Config:
    """Slow-request exemplar thresholds + bounds (module-global; set
    via configure())."""

    __slots__ = ("slow_ttft_s", "slow_total_s", "live_capacity",
                 "max_events")

    def __init__(self):
        def _env_f(name):
            v = os.environ.get(name)
            if not v:
                return None
            try:
                return float(v)
            except ValueError:
                # a typo'd ops knob must not make `import paddle_tpu`
                # raise; the threshold is simply not armed
                return None
        self.slow_ttft_s = _env_f("PADDLE_TPU_SLOW_TTFT_S")
        self.slow_total_s = _env_f("PADDLE_TPU_SLOW_TOTAL_S")
        self.live_capacity = 1024
        self.max_events = 256


CONFIG = _Config()


def configure(slow_ttft_s="unset", slow_total_s="unset",
              live_capacity=None, max_events=None):
    """Tune the slow-request exemplar thresholds (seconds; None
    disables that trigger) and the in-flight / timeline bounds.
    Omitted arguments keep their current value."""
    # coerce NOW: a bad value must raise here, on the caller's thread —
    # stored raw, the first comparison happens inside finish(), which
    # on the engine path runs on the ticker thread and would kill it
    if slow_ttft_s != "unset":
        CONFIG.slow_ttft_s = (None if slow_ttft_s is None
                              else float(slow_ttft_s))
    if slow_total_s != "unset":
        CONFIG.slow_total_s = (None if slow_total_s is None
                               else float(slow_total_s))
    if live_capacity is not None:
        CONFIG.live_capacity = int(live_capacity)
    if max_events is not None:
        CONFIG.max_events = int(max_events)


# -- W3C trace context ------------------------------------------------------

def _is_hex(s: str) -> bool:
    return all(c in "0123456789abcdef" for c in s)


# adopted X-Request-Id values are echoed back through send_header();
# http.server's email parser hands obs-folded request headers over WITH
# their CR/LF intact, so an unvalidated id is a response-header
# injection vector. RFC 7230 token chars only, bounded length.
_RID_CHARS = frozenset(
    "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789"
    "!#$%&'*+-.^_`|~")
_RID_MAX = 128


def _safe_request_id(rid):
    """The inbound `X-Request-Id` if it is safe to echo, else None
    (the caller then generates one)."""
    if not rid or not isinstance(rid, str) or len(rid) > _RID_MAX:
        return None
    if not all(c in _RID_CHARS for c in rid):
        return None
    return rid


def safe_request_id(rid):
    """Public form of the echo-safety check: any layer that echoes an
    inbound `X-Request-Id` (the replica router, a future gateway) must
    apply the SAME injection rules as the serving layer, or the hop
    becomes the header-injection vector the serving layer closed."""
    return _safe_request_id(rid)


def parse_traceparent(header):
    """Parse a W3C `traceparent` header -> (trace_id, parent_id,
    flags) or None when absent/malformed (the caller then starts a
    fresh trace — per spec, an invalid header is ignored, not an
    error)."""
    if not header or not isinstance(header, str):
        return None
    # no case folding: the spec requires lowercase hex and says a
    # non-conforming header MUST be ignored — uppercase ids start a
    # fresh trace rather than silently joining
    parts = header.strip().split("-")
    if len(parts) < 4:
        return None
    version, trace_id, parent_id, flags = parts[:4]
    if len(version) != 2 or not _is_hex(version) or version == "ff":
        return None
    if version == "00" and len(parts) != 4:
        # version 00 defines EXACTLY four fields; trailing data is
        # invalid there (later versions may append fields)
        return None
    if len(trace_id) != 32 or not _is_hex(trace_id) \
            or trace_id == "0" * 32:
        return None
    if len(parent_id) != 16 or not _is_hex(parent_id) \
            or parent_id == "0" * 16:
        return None
    if len(flags) != 2 or not _is_hex(flags):
        return None
    return trace_id, parent_id, int(flags, 16)


# -- the per-request context -------------------------------------------------

_seq = itertools.count(1)


class RequestContext:
    """One request's identity + typed event timeline.

    Construct through `from_headers` (serving) or `new` (anything that
    originates a request without HTTP headers, e.g. a direct
    PagedKVEngine.submit). Thread-safe: the HTTP handler, the stream
    producer thread, and the engine ticker all record into the same
    context."""

    __slots__ = ("request_id", "trace_id", "parent_id", "span_id",
                 "flags", "tenant", "tenant_key", "t0", "events",
                 "tokens", "dropped_events", "tokens_claimed",
                 "outcome", "finish_t", "_lock", "_queued_t",
                 "_prefill_t", "_last_emit", "_live_key",
                 "_engine_refs", "_engine_reason")

    def __init__(self, request_id=None, trace_id=None, parent_id=None,
                 flags=1, tenant=None):
        self.request_id = request_id or "req-" + secrets.token_hex(8)
        # tenant attribution (inference/tenancy.py): sanitized
        # X-Tenant-Id, or None for unlabeled traffic. Serving may
        # override after chaos-storm stamping (tenancy.resolve_tenant).
        # `tenant_key` is the TenantTable accounting key a tenancy-
        # configured layer sets beside it: the outcome METRIC labels
        # with the key (bounded by the configured tenant set) while
        # /debug/requests keeps the raw id — 64 junk header values
        # must not exhaust request.outcome's label budget and fold
        # real tenants into "_other" forever.
        self.tenant = tenant
        self.tenant_key = None
        self.trace_id = trace_id or secrets.token_hex(16)
        self.parent_id = parent_id          # inbound caller's span id
        self.span_id = secrets.token_hex(8)  # OUR span within the trace
        self.flags = int(flags)
        self.t0 = time.perf_counter()
        self.events: list = []              # (name, t, attrs|None)
        self.tokens = 0                     # generated tokens accepted
        self.dropped_events = 0
        # an engine claiming token accounting stops the serving layer
        # double-recording the same emissions (serving.generate_steps)
        self.tokens_claimed = False
        self.outcome = None                 # set once by finish()
        self.finish_t = None
        self._lock = threading.Lock()
        self._queued_t: dict = {}   # per-stream queued time (rid key)
        self._prefill_t: dict = {}  # per-stream prefill start (rid key)
        self._last_emit: dict = {}      # per-stream last emission time
        self._live_key = None
        self._engine_refs = 0       # engine rows sharing this context
        self._engine_reason = None  # first abnormal row outcome

    # -- constructors ---------------------------------------------------
    @classmethod
    def new(cls, request_id=None):
        return cls(request_id=request_id)

    @classmethod
    def from_headers(cls, headers):
        """Build from inbound HTTP headers: `traceparent` joins the
        caller's trace (malformed -> fresh trace), `X-Request-Id` is
        adopted when it is safe to echo — RFC 7230 token chars,
        bounded length — else generated (the id comes back on the
        response verbatim, so CR/LF or oversized values would be a
        header-injection vector)."""
        get = headers.get if headers is not None else (lambda k: None)
        parsed = parse_traceparent(get("traceparent"))
        rid = _safe_request_id(get("X-Request-Id"))
        # same sanitization rules as the request id: the tenant id is
        # echoed on replies and rides the router hop as a header
        tenant = _safe_request_id(get("X-Tenant-Id"))
        if parsed is None:
            return cls(request_id=rid, tenant=tenant)
        trace_id, parent_id, flags = parsed
        return cls(request_id=rid, trace_id=trace_id,
                   parent_id=parent_id, flags=flags, tenant=tenant)

    def traceparent(self) -> str:
        """The outbound `traceparent` header value: same trace id, OUR
        span id as the new parent (W3C propagation contract)."""
        return f"00-{self.trace_id}-{self.span_id}-{self.flags:02x}"

    # -- timeline -------------------------------------------------------
    def record(self, event, **attrs):
        """Append a typed event (EVENTS catalogue; unknown names raise)
        and derive phase instruments as the boundaries pass."""
        if event not in EVENTS:
            raise KeyError(
                f"request event {event!r} is not in the EVENTS "
                "catalogue (observability/requests.py) — register it "
                "there")
        t = time.perf_counter()
        with self._lock:
            if self.outcome is not None:
                # a layer still holding a finished context (the batcher
                # scheduling a deadline-expired request, a late row of
                # a multi-row generate) must not grow the timeline or
                # skew the phase SLOs past the terminal event
                return t
            self._append_locked(event, t, attrs or None)
            if event == "queued":
                # keyed by the caller's rid (None for single-stream
                # callers like the batcher): a multi-row request queues
                # each row at its own time, and each row's wait must be
                # measured against ITS queued instant, not whichever
                # sibling queued last
                self._queued_t[attrs.get("rid")] = t
            elif event == "scheduled":
                qt = self._queued_t.pop(attrs.get("rid"), None)
                if qt is not None:
                    REGISTRY.observe("request.queue_wait.seconds",
                                     t - qt)
            elif event == "prefill_start":
                self._prefill_t[attrs.get("rid")] = t
            elif event == "prefill_end":
                pt = self._prefill_t.pop(attrs.get("rid"), None)
                if pt is not None:
                    REGISTRY.observe("request.prefill.seconds", t - pt)
        return t

    def _append_locked(self, event, t, attrs):
        if len(self.events) >= CONFIG.max_events:
            self.dropped_events += 1
            return
        self.events.append((event, t, attrs))

    def record_tokens(self, n, stream=None):
        """One decode emission of `n` accepted tokens. The first call
        overall records `first_token` (-> request.ttft.seconds,
        measured from context creation — the user-felt clock); later
        calls record a `tokens` tick event and observe
        request.itl.seconds once per emission with the per-token mean
        gap (tokens inside one fused tick are indistinguishable
        host-side). `stream` keys the gap clock: a multi-row request
        shares one context across engine rows, and each row's ITL must
        be measured against ITS previous emission, not whichever
        sibling emitted microseconds ago in the same tick — a row's
        own first emission contributes no gap."""
        n = int(n)
        if n <= 0:
            return
        t = time.perf_counter()
        with self._lock:
            if self.outcome is not None:
                return      # post-terminal emission: drop, don't skew
            self.tokens += n
            if not self._last_emit:
                self._append_locked("first_token", t, None)
                REGISTRY.observe("request.ttft.seconds", t - self.t0)
                if n > 1:
                    # a tick can carry the first token AND successors
                    self._append_locked("tokens", t, {"n": n - 1})
            else:
                prev = self._last_emit.get(stream)
                self._append_locked("tokens", t, {"n": n})
                if prev is not None:
                    REGISTRY.observe("request.itl.seconds",
                                     (t - prev) / n)
            self._last_emit[stream] = t

    def claim_tokens(self):
        """An engine that records emissions itself (PagedKVEngine)
        claims token accounting so the serving consumer loop doesn't
        double-record the same tokens."""
        self.tokens_claimed = True

    def adopt_engine(self):
        """One engine request (one row of a possibly multi-row serving
        request) adopted this context. Pairs with engine_finish(): the
        context only reaches its terminal state when the LAST adopted
        row does, so a two-prompt /generate stays live in
        /debug/requests — and keeps recording tokens — until every row
        retires."""
        with self._lock:
            self._engine_refs += 1

    def engine_finish(self, reason):
        """Terminal transition for ONE adopted engine row. Finishes
        the whole context only on the last release; the first abnormal
        reason (anything but "finished") wins over rows that completed
        normally."""
        with self._lock:
            if self.outcome is not None:
                return False
            if reason != "finished" and self._engine_reason is None:
                self._engine_reason = reason
            self._engine_refs -= 1
            if self._engine_refs > 0:
                return False
            final = self._engine_reason or reason
        return self.finish(final)

    # -- finish ---------------------------------------------------------
    def finish(self, reason):
        """Terminal transition — idempotent, first reason wins (the
        engine retiring a request and the HTTP layer unwinding both
        call this; whoever saw the outcome first owns it). Records the
        terminal event, the request.tokens / request.outcome
        instruments, runs the slow-request exemplar check, and drops
        the context from the in-flight registry."""
        t = time.perf_counter()
        with self._lock:
            if self.outcome is not None:
                return False
            self.outcome = str(reason)
            self.finish_t = t
            # bypass the max_events cap: a long generation can fill the
            # timeline with tokens ticks, but the exactly-one-terminal-
            # event contract must hold — the exemplar dump and stage()
            # need it, and it is one element past the bound
            self.events.append((_terminal_event(self.outcome), t, None))
        REGISTRY.observe("request.tokens", self.tokens)
        if self.tenant_key is not None:
            # tenant-labeled outcome ONLY via the ACCOUNTING KEY a
            # tenancy-configured layer assigned (bounded by the
            # configured tenant set). The raw header id is never a
            # label: in attribution-only mode (no TenantTable) 64
            # junk ids would otherwise exhaust this instrument's
            # label budget and fold every real tenant into "_other"
            # forever — raw ids stay on the echo and /debug/requests.
            REGISTRY.inc("request.outcome", reason=self.outcome,
                         tenant=self.tenant_key)
        else:
            REGISTRY.inc("request.outcome", reason=self.outcome)
        self._maybe_dump_exemplar()
        _unregister(self)
        return True

    @property
    def finished(self):
        return self.outcome is not None

    # -- introspection --------------------------------------------------
    def stage(self):
        """Name of the most recent event ("created" before any)."""
        with self._lock:
            return self.events[-1][0] if self.events else "created"

    def age_s(self):
        end = self.finish_t if self.finish_t is not None \
            else time.perf_counter()
        return end - self.t0

    def snapshot(self):
        """The /debug/requests row: identity + stage + age — the
        machine-readable signal a fleet router keys on."""
        with self._lock:
            stage = self.events[-1][0] if self.events else "created"
        return {"request_id": self.request_id,
                "trace_id": self.trace_id,
                "tenant": self.tenant,
                "stage": stage,
                "age_s": round(self.age_s(), 6),
                "tokens": self.tokens}

    def timeline(self):
        """[(event, t, attrs)] copy, oldest first."""
        with self._lock:
            return list(self.events)

    # -- slow-request exemplar ------------------------------------------
    def _ttft_s(self):
        for name, t, _ in self.events:
            if name == "first_token":
                return t - self.t0
        return None

    def _maybe_dump_exemplar(self):
        ttft = self._ttft_s()
        total = (self.finish_t - self.t0) if self.finish_t else None
        slow = ((CONFIG.slow_ttft_s is not None and ttft is not None
                 and ttft > CONFIG.slow_ttft_s)
                or (CONFIG.slow_total_s is not None and total is not None
                    and total > CONFIG.slow_total_s))
        if not slow:
            return
        self.dump_spans()
        REGISTRY.inc("request.slow_exemplars")

    def dump_spans(self):
        """Reconstruct this request's lifecycle as nested spans in the
        trace ring, so export_chrome_trace shows it alongside live
        span() scopes: one root `request` span, phase spans
        (queue_wait / prefill / decode) at depth 1, and every timeline
        event as a zero-duration mark at depth 2. All spans share a
        tid derived from the request id, giving the request its own
        track in chrome://tracing / perfetto."""
        with self._lock:
            events = list(self.events)
            t_end = self.finish_t or time.perf_counter()
        tid = zlib.crc32(self.request_id.encode()) & 0x7FFFFFFF
        ident = {"request_id": self.request_id,
                 "trace_id": self.trace_id, "span_id": self.span_id}
        trace.record_span(
            "request", self.t0, (t_end - self.t0) * 1e6, depth=0,
            tid=tid, attrs={**ident, "outcome": self.outcome,
                            "tokens": self.tokens,
                            "dropped_events": self.dropped_events})
        at: dict = {}                              # first occurrence
        for name, t, _ in events:
            at.setdefault(name, t)
        phases = (("queue_wait", at.get("queued"), at.get("scheduled")),
                  ("prefill", at.get("prefill_start"),
                   at.get("prefill_end")),
                  ("decode", at.get("first_token"), t_end))
        for name, p0, p1 in phases:
            if p0 is not None and p1 is not None and p1 >= p0:
                trace.record_span(name, p0, (p1 - p0) * 1e6, depth=1,
                                  tid=tid, attrs=dict(ident))
        for name, t, attrs in events:
            trace.record_span(f"ev.{name}", t, 0.0, depth=2, tid=tid,
                              attrs={**ident, **(attrs or {})})


# -- contextvar propagation --------------------------------------------------

_current: contextvars.ContextVar = contextvars.ContextVar(
    "paddle_tpu_request_context", default=None)


def current():
    """The RequestContext bound to this execution context (None when
    observability is disabled or nothing set one)."""
    return _current.get()


def set_current(ctx):
    """Bind `ctx`; returns a token for reset_current(). Serving copies
    the whole contextvars context into its stream-producer thread
    (contextvars.copy_context().run), so the engine's submit() sees
    the same binding."""
    return _current.set(ctx)


def reset_current(token):
    _current.reset(token)


# -- bounded in-flight registry ----------------------------------------------

_live_lock = threading.Lock()
_live: dict = {}                # insertion-ordered (py3.7+): seq -> ctx


def register(ctx: RequestContext):
    """Track a live request for /debug/requests. Bounded: past
    CONFIG.live_capacity the oldest entry is evicted (a leaked or
    abandoned context must not grow the registry forever)."""
    with _live_lock:
        key = next(_seq)
        ctx._live_key = key
        _live[key] = ctx
        while len(_live) > CONFIG.live_capacity:
            _live.pop(next(iter(_live)))
    return ctx


def _unregister(ctx: RequestContext):
    with _live_lock:
        _live.pop(ctx._live_key, None)


def live_requests():
    """Snapshots of every live (registered, unfinished) request,
    oldest first — the GET /debug/requests body."""
    with _live_lock:
        ctxs = list(_live.values())
    return [c.snapshot() for c in ctxs]


def live_count() -> int:
    with _live_lock:
        return len(_live)


def clear():
    """Drop every tracked context (tests)."""
    with _live_lock:
        _live.clear()
