"""Process-wide metrics: a thread-safe registry of Counter / Gauge /
Histogram instruments with label support, a JSON snapshot, and
Prometheus text exposition (served by PredictorServer's /metrics).

The reference ships a whole profiler layer but no *metrics* plane:
retries, breaker trips, checkpoint fallbacks and elastic restarts in
this tree previously left no durable signal. This module is the
substrate: every runtime instrumentation site increments a named
instrument here, and any exporter (the serving /metrics endpoint, a
test, a notebook) reads one consistent snapshot.

Metric NAMES are a closed catalogue (`METRICS` below), exactly like
chaos.POINTS: an instrumentation call with a name that is not
catalogued raises at runtime, and tools/check_metric_names.py (tier-1
wired via tests/test_metric_names_tool.py) fails the build on any
non-literal or unregistered name at a call site — so the README's
metric table can never silently drift from the code.

Everything is stdlib-only; importing this module never touches jax
(tools/check_metric_names.py loads it standalone for the catalogue).
"""
from __future__ import annotations

import json
import threading

__all__ = ["METRICS", "Counter", "Gauge", "Histogram",
           "MetricsRegistry", "REGISTRY", "DEFAULT_BUCKETS_MS",
           "DEFAULT_BUCKETS_S", "DEFAULT_MAX_LABEL_VALUES"]

# latency-ish defaults; histograms may override via the catalogue
DEFAULT_BUCKETS_MS = (1.0, 2.0, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0,
                      500.0, 1000.0, 2500.0, 5000.0, 10000.0)
DEFAULT_BUCKETS_S = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0,
                     10.0, 30.0, 60.0, 300.0)

#: The metric-name catalogue: every literal name passed to
#: inc/observe/set_gauge anywhere in the package MUST have an entry
#: here — (kind, help[, buckets]). tools/check_metric_names.py fails
#: the build otherwise. Keep names dotted + lowercase; the Prometheus
#: exposition converts to `paddle_tpu_<name with _>` and appends
#: `_total` to counters.
METRICS = {
    # -- store RPC / rendezvous --------------------------------------
    "store.rpc.total": ("counter", "store RPC ops issued (label: op)"),
    "store.rpc.latency_ms": ("histogram",
                             "store RPC round-trip latency (label: op)",
                             DEFAULT_BUCKETS_MS),
    "store.rpc.reconnects": ("counter",
                             "store client reconnects between retries"),
    "store.barrier.rounds": ("counter",
                             "store barrier rounds completed"),
    # -- generic retry policy ----------------------------------------
    "retry.attempts": ("counter",
                       "retry attempts across all RetryPolicy objects"),
    "retry.exhausted": ("counter",
                        "RetryBudgetExceeded raises (op gave up)"),
    # -- checkpoint ---------------------------------------------------
    "ckpt.saves": ("counter", "checkpoint saves completed"),
    "ckpt.loads": ("counter", "checkpoint loads completed"),
    "ckpt.save.seconds": ("histogram", "checkpoint save wall time",
                          DEFAULT_BUCKETS_S),
    "ckpt.load.seconds": ("histogram", "checkpoint load wall time",
                          DEFAULT_BUCKETS_S),
    "ckpt.quarantined_files": ("counter",
                               "corrupt files moved to .quarantine"),
    "ckpt.fallbacks": ("counter",
                       "loads that fell back past a corrupt newest "
                       "checkpoint"),
    "checkpoint.async.pending": ("gauge",
                                 "async saves snapshotted but not yet "
                                 "durably committed (queued + in "
                                 "flight)"),
    "checkpoint.snapshot.seconds": ("histogram",
                                    "device->host snapshot time — the "
                                    "only save stall the TRAINING "
                                    "thread pays on the async path",
                                    DEFAULT_BUCKETS_S),
    "checkpoint.write.seconds": ("histogram",
                                 "background writer time per async "
                                 "save (hash + files + barrier + "
                                 "marker), overlapped with training",
                                 DEFAULT_BUCKETS_S),
    # -- elastic ------------------------------------------------------
    "elastic.restarts": ("counter",
                         "elastic restarts (in-process resume loops + "
                         "supervisor relaunches)"),
    "elastic.preemptions": ("counter",
                            "preemption signals observed"),
    "elastic.store.read_errors": ("counter",
                                  "supervisor heartbeat-key store reads "
                                  "that failed (N consecutive failures "
                                  "presume the rank stale — a down "
                                  "store must not make every rank look "
                                  "healthy forever)"),
    # -- chaos --------------------------------------------------------
    "chaos.injections": ("counter",
                         "chaos faults fired (label: site)"),
    # -- training telemetry -------------------------------------------
    "train.steps": ("counter", "optimizer steps dispatched"),
    "train.step.seconds": ("histogram",
                           "inter-step wall time (dispatch pipelined: "
                           "converges to device step time)",
                           DEFAULT_BUCKETS_S),
    "train.tokens_per_sec": ("gauge",
                             "tokens/sec/chip over the last step"),
    "train.mfu": ("gauge",
                  "model FLOPs utilization estimate (flops-per-token "
                  "x tokens/sec / chip peak)"),
    "train.loss": ("gauge",
                   "loss of a recent step (lagged a few steps so the "
                   "read never blocks dispatch)"),
    "train.grad_norm": ("gauge", "global grad norm, when reported"),
    "train.nonfinite_skips": ("counter",
                              "steps skipped for non-finite grads"),
    # -- training anomaly sentry (distributed/sentry.py) --------------
    "train.sentry.triggers": ("counter",
                              "sentry anomaly triggers (label: reason "
                              "= loss_spike | nonfinite_grad | "
                              "sentry_quarantine)"),
    "train.sentry.skips": ("counter",
                           "updates discarded by the sentry skip "
                           "policy (data cursor still advanced)"),
    "train.sentry.rollbacks": ("counter",
                               "restores onto the last promoted "
                               "known-good checkpoint"),
    "train.sentry.steps_since_good": ("gauge",
                                      "steps since the newest "
                                      "PROMOTED (rollback-eligible) "
                                      "checkpoint — a climbing value "
                                      "on one rank is numeric "
                                      "degradation before quarantine"),
    "train.sentry.probe.seconds": ("histogram",
                                   "host-side sentry overhead per "
                                   "step (probe read + EWMA update + "
                                   "policy decision) — the <1% "
                                   "probe-overhead acceptance is "
                                   "benched in extra.sentry",
                                   DEFAULT_BUCKETS_S),
    "train.recompiles": ("counter",
                         "train-step program (re)builds (label: shape "
                         "= the triggering batch-shape signature — the "
                         "bucket-autotune feed)"),
    "train.phase.seconds": ("histogram",
                            "phase-attributed step wall time (label: "
                            "phase = fwd | bwd | optimizer), from "
                            "Trainer.measure_phase_seconds timing the "
                            "step's own loss machinery fwd-only / "
                            "fwd+bwd / full — the bench evidence for "
                            "WHY MFU moved, not just that it did",
                            DEFAULT_BUCKETS_S),
    "train.loss.logits_bytes_saved": ("gauge",
                                      "per-chip bytes of the [B*S, "
                                      "vocab] logits tensor the "
                                      "blockwise-CE loss path avoids "
                                      "materializing per step (0 / "
                                      "absent on the dense path)"),
    "train.overlap.comm.seconds": ("histogram",
                                   "weight-movement collective seconds "
                                   "per phase (label: phase = fwd | "
                                   "bwd): propagated-twin minus "
                                   "nocomm-twin wall time from "
                                   "measure_phase_seconds — the "
                                   "overlap-fraction denominator",
                                   DEFAULT_BUCKETS_S),
    "train.overlap.fraction": ("gauge",
                               "share of FSDP weight-movement comm "
                               "hidden under compute by the decomposed "
                               "ppermute rings (parallel/overlap.py), "
                               "from the train.overlap.phase trace "
                               "spans: (propagated − overlapped) / "
                               "(propagated − nocomm) over fwd+bwd"),
    # -- input pipeline -----------------------------------------------
    "io.prefetch.queue_depth": ("gauge",
                                "batches already on device, waiting "
                                "for the consumer"),
    "io.prefetch.batches": ("counter",
                            "batches placed on device by prefetch "
                            "workers"),
    "io.h2d.seconds": ("histogram",
                       "host->device batch placement time on the "
                       "prefetch thread (dispatch + ready)",
                       DEFAULT_BUCKETS_S),
    # -- serving ------------------------------------------------------
    "serving.requests": ("counter",
                         "HTTP requests by outcome (label: outcome)"),
    "serving.request.latency_ms": ("histogram",
                                   "successful request latency",
                                   DEFAULT_BUCKETS_MS),
    "serving.in_flight": ("gauge", "admitted requests in flight"),
    "serving.capacity": ("gauge", "admission capacity"),
    "serving.draining": ("gauge", "1 while draining"),
    "serving.warming": ("gauge", "1 while the cold-start readiness "
                                 "gate holds (/readyz 503 \"warming\": "
                                 "model built, first compile not yet "
                                 "paid)"),
    "serving.admission.admitted": ("gauge",
                                   "lifetime admitted (scraped)"),
    "serving.admission.rejected": ("gauge",
                                   "lifetime admission rejections "
                                   "(scraped)"),
    "serving.breaker.state": ("gauge",
                              "circuit breaker state (0 closed, "
                              "1 half-open, 2 open)"),
    "serving.breaker.consecutive_failures": ("gauge",
                                             "consecutive backend "
                                             "failures"),
    "serving.breaker.opens": ("gauge", "lifetime breaker trips"),
    "serving.breaker.recloses": ("gauge", "lifetime breaker recloses"),
    "serving.batcher.queued": ("gauge", "requests buffered for a batch"),
    "serving.batcher.batches_run": ("gauge", "batches executed"),
    "serving.batcher.requests_served": ("gauge",
                                        "requests served via batches"),
    "serving.batcher.expired_in_queue": ("gauge",
                                         "requests expired while "
                                         "buffered"),
    "serving.batcher.shed_full": ("gauge",
                                  "requests shed on a full buffer"),
    "serving.batcher.shed_tenant": ("gauge",
                                    "requests shed on a per-tenant "
                                    "buffer quota (scraped)"),
    # -- multi-tenant QoS (inference/tenancy.py) ----------------------
    "tenant.requests": ("counter",
                        "served-layer requests by tenant and outcome "
                        "(labels: tenant, outcome — the serving /stats "
                        "outcome keys)"),
    "tenant.shed": ("counter",
                    "tenant-quota sheds (labels: tenant, reason = "
                    "admission | queue | engine | rate)"),
    "tenant.admitted": ("counter",
                        "engine slot admissions by tenant (label: "
                        "tenant)"),
    "tenant.decode.slots": ("counter",
                            "decode slot-ticks by tenant — one count "
                            "per live slot per scheduler tick, the "
                            "weighted-fair share evidence (label: "
                            "tenant)"),
    "tenant.queue_wait.seconds": ("histogram",
                                  "engine admission queue wait by "
                                  "tenant (label: tenant) — the "
                                  "starvation-soak SLO",
                                  DEFAULT_BUCKETS_S),
    "tenant.in_flight": ("gauge",
                         "admitted requests in flight by tenant "
                         "(label: tenant, scraped)"),
    # -- registry self-protection -------------------------------------
    "metrics.labels.dropped": ("counter",
                               "label values folded into the literal "
                               "\"_other\" cell because an instrument "
                               "hit its distinct-label-value bound "
                               "(label: metric) — a tenant-id flood "
                               "must not grow the registry without "
                               "bound"),
    # -- per-request serving SLOs (observability/requests.py) ---------
    "request.ttft.seconds": ("histogram",
                             "time to first generated token, from "
                             "request-context creation (HTTP arrival "
                             "or engine submit) — the user-felt SLO",
                             DEFAULT_BUCKETS_S),
    "request.itl.seconds": ("histogram",
                            "inter-token latency: per-token mean gap "
                            "between successive decode emissions "
                            "(one observation per fused tick)",
                            DEFAULT_BUCKETS_S),
    "request.queue_wait.seconds": ("histogram",
                                   "wait between queued and scheduled "
                                   "(batch formed / engine slot "
                                   "assigned)", DEFAULT_BUCKETS_S),
    "request.prefill.seconds": ("histogram",
                                "prompt prefill wall time per request",
                                DEFAULT_BUCKETS_S),
    "request.tokens": ("histogram",
                       "generated tokens per finished request",
                       (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0,
                        256.0, 512.0, 1024.0, 2048.0, 4096.0)),
    "request.outcome": ("counter",
                        "finished requests by outcome (label: reason "
                        "= finished | ok | shed_* | deadline_exceeded "
                        "| expired | cancelled | disconnected | "
                        "client_error | server_error | error)"),
    "request.slow_exemplars": ("counter",
                               "requests breaching the slow-request "
                               "threshold whose lifecycle was dumped "
                               "into the span ring"),
    # -- fleet telemetry plane (observability/fleet.py) ---------------
    "fleet.heartbeats": ("counter",
                         "heartbeat snapshots this rank published into "
                         "the store"),
    "fleet.heartbeat.errors": ("counter",
                               "heartbeat publishes/reads that failed "
                               "(after retries)"),
    "fleet.step.skew": ("gauge",
                        "max-min training step across ranks reporting "
                        "a step"),
    "fleet.step.lag": ("gauge",
                       "slowest rank's step lag vs the fleet median"),
    "fleet.stale_ranks": ("gauge",
                          "ranks whose heartbeat is missing or older "
                          "than stale_after_s"),
    "fleet.stragglers": ("gauge",
                         "ranks currently flagged as stragglers (stale "
                         "or step-lagged past straggler_steps)"),
    "fleet.straggler": ("gauge",
                        "1 while the labeled rank is flagged as a "
                        "straggler (label: rank)"),
    "fleet.tokens_per_sec": ("gauge",
                             "fleet-summed tokens/sec across live "
                             "ranks"),
    "fleet.flight.records": ("counter",
                             "flight-recorder bundles dumped (label: "
                             "reason)"),
    # -- replica fleet router (inference/router.py) -------------------
    "router.requests": ("counter",
                        "routed requests by outcome (label: outcome = "
                        "ok | shed_upstream | shed_tenant | "
                        "no_replicas | failed | deadline_exceeded | "
                        "client_error | server_error | stream_error | "
                        "disconnected)"),
    "router.retries": ("counter",
                       "failover retries (label: kind = shed | "
                       "connect | stream)"),
    "router.probes": ("counter",
                      "replica health probes (label: result = ready | "
                      "saturated | draining | warming | breaker | "
                      "failed | flap)"),
    "router.ejections": ("counter",
                         "replicas ejected from rotation (label: "
                         "reason = draining | warming | probe_failed | "
                         "replica_breaker | breaker_open | "
                         "connect_failed)"),
    "router.reentries": ("counter",
                         "ejected replicas re-admitted after K "
                         "consecutive clean probes"),
    "router.affinity.rebinds": ("counter",
                                "sessions re-pinned after their "
                                "affine replica left rotation"),
    "router.prefix.pins": ("counter",
                           "prefix-hash -> replica pins created or "
                           "re-pointed (one per chain key)"),
    "router.prefix.hits": ("counter",
                           "requests routed to the replica their "
                           "prefix hash is pinned to (KV locality "
                           "preserved)"),
    "router.prefix.rebinds": ("counter",
                              "prefix pins re-bound after every "
                              "pinned replica for the chain left "
                              "rotation"),
    "router.disagg.handoffs": ("counter",
                               "requests routed through the "
                               "disaggregated two-hop path (prefill "
                               "pool, then decode pool with a KV "
                               "page handoff)"),
    "router.disagg.fallbacks": ("counter",
                                "two-hop candidates degraded to "
                                "single-replica decode (label: "
                                "reason = prefill_failed | "
                                "transfer_fail)"),
    "router.replicas.in_rotation": ("gauge",
                                    "replicas currently routable"),
    "router.replicas.ejected": ("gauge",
                                "replicas currently out of rotation"),
    "router.forward.seconds": ("histogram",
                               "router-side request wall time incl. "
                               "failover retries (the added-hop "
                               "budget)", DEFAULT_BUCKETS_S),
    # -- fleet autopilot (inference/autopilot.py) ---------------------
    "autopilot.restarts": ("counter",
                           "replica restarts attempted by the "
                           "supervisor (label: rid)"),
    "autopilot.restart.seconds": ("histogram",
                                  "dead-replica detection to back-in-"
                                  "rotation wall time (the restart-to-"
                                  "ready availability number)",
                                  DEFAULT_BUCKETS_S),
    "autopilot.launch.failures": ("counter",
                                  "replica spawn attempts that raised "
                                  "or never became ready (label: rid)"),
    "autopilot.quarantines": ("counter",
                              "supervised slots quarantined after K "
                              "restarts inside the crash-loop window "
                              "(label: rid)"),
    "autopilot.replicas.quarantined": ("gauge",
                                       "supervised slots currently "
                                       "quarantined (not restarted "
                                       "until released)"),
    "autopilot.replicas.desired": ("gauge",
                                   "autoscaler's current desired "
                                   "replica count"),
    "autopilot.scale.events": ("counter",
                               "autoscaler resizes applied (label: "
                               "direction = out | in)"),
    "autopilot.rollouts": ("counter",
                           "weight rollouts finished (label: outcome "
                           "= completed | aborted)"),
    "autopilot.rollout.steps": ("counter",
                                "per-replica rollout steps (label: "
                                "result = swapped | rolled_back)"),
    # -- paged KV engine ----------------------------------------------
    "inference.decode.kernel": ("counter",
                                "decode ticks by attend path (label: "
                                "path = pallas | jnp)"),
    "inference.kv.bytes_per_slot": ("gauge",
                                    "KV-pool HBM bytes one fully-grown "
                                    "slot pins (all layers, real "
                                    "buffer dtypes incl. int8 scale "
                                    "planes)"),
    "inference.prefix.hits": ("counter",
                              "admissions that shared cached prompt "
                              "prefix pages (prefill ran only the "
                              "tail)"),
    "inference.prefix.misses": ("counter",
                                "admissions of shareable-length "
                                "prompts that found no cached "
                                "prefix"),
    "inference.prefix.hit_tokens": ("counter",
                                    "prompt tokens served from shared "
                                    "prefix pages instead of "
                                    "prefill"),
    "inference.prefix.pages_shared": ("counter",
                                      "prefix-cache pages pointed "
                                      "into admitted slots' block "
                                      "tables"),
    "inference.prefix.evictions": ("counter",
                                   "prefix-cache entries evicted "
                                   "(LRU budget or on-demand when "
                                   "decode needed the page back)"),
    "inference.kvtier.spilled_pages": ("counter",
                                       "KV pages spilled to the "
                                       "host-RAM tier at eviction "
                                       "(D2H)"),
    "inference.kvtier.restored_pages": ("counter",
                                        "host-tier pages uploaded "
                                        "back into device pools on a "
                                        "restore hit (H2D)"),
    "inference.kvtier.spill_bytes": ("counter",
                                     "bytes moved device -> host by "
                                     "spills (int8 pools move ~0.52x "
                                     "the bf16 volume)"),
    "inference.kvtier.restore_bytes": ("counter",
                                       "bytes moved host -> device "
                                       "by restore hits"),
    "inference.kvtier.host_pages": ("gauge",
                                    "KV pages currently resident in "
                                    "the host-RAM tier"),
    "inference.kvtier.suspends": ("counter",
                                  "idle sessions suspended (KV "
                                  "spilled to host, HBM pages "
                                  "freed)"),
    "inference.kvtier.resumes": ("counter",
                                 "suspended sessions resumed on "
                                 "their next turn"),
    # -- disaggregated prefill/decode handoff (inference/disagg.py) ---
    "inference.disagg.handoff_pages": ("counter",
                                       "committed KV pages served to "
                                       "decode-pool pulls (/kv/pull, "
                                       "prefill side)"),
    "inference.disagg.handoff_bytes": ("counter",
                                       "wire bytes of packed page "
                                       "bundles served to pulls "
                                       "(int8 + dedup keep this "
                                       "~2x+ under naive bf16)"),
    "inference.disagg.imported_pages": ("counter",
                                        "pulled pages committed into "
                                        "a decode replica's pools "
                                        "(batched H2D scatter)"),
    "inference.disagg.imported_bytes": ("counter",
                                        "host bytes of pulled pages "
                                        "committed into device "
                                        "pools"),
    "inference.disagg.dedup_skipped_pages": ("counter",
                                             "handoff pages skipped "
                                             "because the chain key "
                                             "was already resident on "
                                             "the decode replica (a "
                                             "warm replica transfers "
                                             "nothing)"),
    "inference.disagg.transfer_seconds": ("histogram",
                                          "decode-side /kv/pull wall "
                                          "time, fetch through "
                                          "unpack (the handoff tax "
                                          "on TTFT)", DEFAULT_BUCKETS_S),
    "inference.disagg.pull_failures": ("counter",
                                       "failed /kv/pull fetches — the "
                                       "request falls back to a cold "
                                       "local prefill, never an "
                                       "error"),
    "engine.ticks": ("gauge", "scheduler ticks run"),
    "engine.prefills": ("gauge", "prompts prefilled"),
    "engine.tokens_out": ("gauge", "tokens emitted"),
    "engine.admitted": ("gauge", "requests admitted to slots"),
    "engine.finished": ("gauge", "requests finished"),
    "engine.cancelled": ("gauge", "requests cancelled"),
    "engine.expired": ("gauge", "requests expired before admission"),
    "engine.overloaded": ("gauge", "submits shed with EngineOverloaded"),
    "engine.pending": ("gauge", "requests queued for admission"),
}


def _label_key(labels: dict) -> tuple:
    return tuple(sorted(labels.items()))


#: default bound on DISTINCT values per label key per instrument; the
#: overflow folds into the literal "_other" cell (guard rationale in
#: _Instrument._norm_record_locked)
DEFAULT_MAX_LABEL_VALUES = 64


def _note_dropped(name, n):
    """Count label-value folds into the process registry. The guard's
    own counter is exempt (its `metric` label is bounded by the
    catalogue, and exempting it breaks the recursion by construction)."""
    if name == "metrics.labels.dropped":
        return
    REGISTRY.inc("metrics.labels.dropped", n, metric=name)


class _Instrument:
    """Base: per-label-set cells guarded by one lock. Label VALUES are
    free-form but BOUNDED: past `max_label_values` distinct values per
    label key, new values fold into the literal "_other" cell and the
    `metrics.labels.dropped` counter records the fold — an unbounded
    id flood (e.g. 10k distinct tenant ids) must not grow the registry
    (and every /metrics scrape body) without bound. Label keys+values
    are stringified at record time."""

    kind = "untyped"

    def __init__(self, name, help="",
                 max_label_values=DEFAULT_MAX_LABEL_VALUES):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._cells: dict = {}
        self._max_label_values = int(max_label_values)
        self._label_vals: dict = {}         # label key -> seen values

    def _norm(self, labels):
        """READ-side normalization: no guard, no mutation — a lookup
        of a never-recorded value must not consume cardinality budget
        (it just misses, or hits "_other" if writes folded)."""
        return _label_key({str(k): str(v) for k, v in labels.items()})

    def _norm_record_locked(self, labels):
        """WRITE-side normalization (caller holds self._lock): returns
        (cell key, values folded). A label value past the per-key
        distinct bound becomes "_other"."""
        dropped = 0
        out = {}
        for k, v in labels.items():
            k, v = str(k), str(v)
            vals = self._label_vals.setdefault(k, set())
            if v not in vals:
                if len(vals) >= self._max_label_values:
                    dropped += 1
                    v = "_other"
                else:
                    vals.add(v)
            out[k] = v
        return _label_key(out), dropped

    def labeled(self) -> dict:
        """{label_key_tuple: value} snapshot."""
        with self._lock:
            return dict(self._cells)


class Counter(_Instrument):
    kind = "counter"

    def inc(self, n=1, **labels):
        if n < 0:
            raise ValueError(f"counter {self.name} cannot decrease")
        with self._lock:
            key, dropped = self._norm_record_locked(labels)
            self._cells[key] = self._cells.get(key, 0) + n
        if dropped:
            _note_dropped(self.name, dropped)

    def value(self, **labels):
        with self._lock:
            return self._cells.get(self._norm(labels), 0)


class Gauge(_Instrument):
    kind = "gauge"

    def set(self, v, **labels):
        with self._lock:
            key, dropped = self._norm_record_locked(labels)
            self._cells[key] = float(v)
        if dropped:
            _note_dropped(self.name, dropped)

    def value(self, **labels):
        with self._lock:
            return self._cells.get(self._norm(labels))


class _HistCell:
    __slots__ = ("counts", "sum", "count", "ring", "ring_idx")

    def __init__(self, n_buckets, ring_cap):
        self.counts = [0] * (n_buckets + 1)     # +inf bucket last
        self.sum = 0.0
        self.count = 0
        # bounded reservoir of recent raw values, for percentiles
        # (bucket counts alone only bound a percentile to a bucket)
        self.ring = [0.0] * ring_cap
        self.ring_idx = 0


class Histogram(_Instrument):
    """Fixed-bucket histogram (cumulative `le` semantics on export)
    plus a bounded ring of recent raw observations so `percentile()`
    answers exactly over the recent window."""

    kind = "histogram"

    def __init__(self, name, help="", buckets=DEFAULT_BUCKETS_MS,
                 ring_capacity=512,
                 max_label_values=DEFAULT_MAX_LABEL_VALUES):
        super().__init__(name, help, max_label_values=max_label_values)
        self.buckets = tuple(sorted(float(b) for b in buckets))
        self.ring_capacity = int(ring_capacity)

    def observe(self, v, **labels):
        v = float(v)
        with self._lock:
            key, dropped = self._norm_record_locked(labels)
            cell = self._cells.get(key)
            if cell is None:
                cell = self._cells[key] = _HistCell(
                    len(self.buckets), self.ring_capacity)
            i = 0
            for b in self.buckets:
                if v <= b:
                    break
                i += 1
            cell.counts[i] += 1
            cell.sum += v
            cell.count += 1
            cell.ring[cell.ring_idx % self.ring_capacity] = v
            cell.ring_idx += 1
        if dropped:
            _note_dropped(self.name, dropped)

    def labeled(self) -> dict:
        """Consistent per-cell copies: exporters read counts/sum/count
        of a cell outside the lock, and a concurrent observe() must
        not let the +Inf cumulative bucket disagree with _count (the
        Prometheus invariant strict parsers check)."""
        with self._lock:
            out = {}
            for key, cell in self._cells.items():
                c = _HistCell(len(self.buckets), 1)
                c.counts = list(cell.counts)
                c.sum = cell.sum
                c.count = cell.count
                out[key] = c
            return out

    def count(self, **labels):
        with self._lock:
            cell = self._cells.get(self._norm(labels))
            return cell.count if cell else 0

    def percentile(self, p, **labels):
        """Nearest-rank percentile over the recent window (None when
        nothing recorded)."""
        with self._lock:
            cell = self._cells.get(self._norm(labels))
            if cell is None or cell.count == 0:
                return None
            n = min(cell.count, self.ring_capacity)
            win = sorted(cell.ring[:n])
        rank = min(n - 1, max(0, int(round(p / 100.0 * (n - 1)))))
        return win[rank]


class MetricsRegistry:
    """Thread-safe, catalogue-validated instrument registry.

    `inc` / `observe` / `set_gauge` are the instrumentation surface
    (audited by tools/check_metric_names.py); `counter` / `gauge` /
    `histogram` hand back the instrument object for readers. Unknown
    names raise — the catalogue, not the call site, is the source of
    truth for what exists."""

    def __init__(self, catalogue=None,
                 max_label_values=DEFAULT_MAX_LABEL_VALUES):
        self._catalogue = catalogue if catalogue is not None else METRICS
        self._lock = threading.Lock()
        self._metrics: dict = {}
        self._max_label_values = int(max_label_values)

    # -- acquisition --------------------------------------------------
    def _get(self, name, expect_kind):
        spec = self._catalogue.get(name)
        if spec is None:
            raise KeyError(
                f"metric {name!r} is not in the METRICS catalogue "
                "(observability/metrics.py) — register it there")
        kind = spec[0]
        if kind != expect_kind:
            raise TypeError(
                f"metric {name!r} is a {kind}, not a {expect_kind}")
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                help_ = spec[1] if len(spec) > 1 else ""
                mlv = self._max_label_values
                if kind == "counter":
                    m = Counter(name, help_, max_label_values=mlv)
                elif kind == "gauge":
                    m = Gauge(name, help_, max_label_values=mlv)
                else:
                    buckets = (spec[2] if len(spec) > 2
                               else DEFAULT_BUCKETS_MS)
                    m = Histogram(name, help_, buckets,
                                  max_label_values=mlv)
                self._metrics[name] = m
            return m

    def counter(self, name) -> Counter:
        return self._get(name, "counter")

    def gauge(self, name) -> Gauge:
        return self._get(name, "gauge")

    def histogram(self, name) -> Histogram:
        return self._get(name, "histogram")

    # -- instrumentation surface (audited; names must be literal) -----
    def inc(self, name, n=1, **labels):
        self._get(name, "counter").inc(n, **labels)

    def observe(self, name, v, **labels):
        self._get(name, "histogram").observe(v, **labels)

    def set_gauge(self, name, v, **labels):
        self._get(name, "gauge").set(v, **labels)

    # -- readers ------------------------------------------------------
    def snapshot(self) -> dict:
        """JSON-able {name: {kind, help, series: [{labels, ...}]}}."""
        with self._lock:
            metrics = list(self._metrics.values())
        out = {}
        for m in sorted(metrics, key=lambda m: m.name):
            series = []
            for key, val in sorted(m.labeled().items()):
                entry = {"labels": dict(key)}
                if isinstance(val, _HistCell):
                    entry.update(count=val.count, sum=val.sum,
                                 buckets=dict(zip(
                                     [*map(str, m.buckets), "+Inf"],
                                     _cumulate(val.counts))))
                else:
                    entry["value"] = val
                series.append(entry)
            out[m.name] = {"kind": m.kind, "help": m.help,
                           "series": series}
        return out

    def to_json(self) -> str:
        return json.dumps(self.snapshot(), indent=1, sort_keys=True)

    def names(self) -> set:
        """Names of the instruments recorded so far."""
        with self._lock:
            return set(self._metrics)

    def prometheus_text(self, exclude=()) -> str:
        """Prometheus text exposition format 0.0.4. `exclude` skips
        metric names another exposition already emitted — a family
        must not appear twice in one scrape body (serving.metrics_text
        concatenates the per-server and global registries)."""
        with self._lock:
            metrics = [m for m in self._metrics.values()
                       if m.name not in exclude]
        lines = []
        for m in sorted(metrics, key=lambda m: m.name):
            pname = _prom_name(m.name, m.kind)
            if m.help:
                lines.append(f"# HELP {pname} {_prom_escape_help(m.help)}")
            lines.append(f"# TYPE {pname} {m.kind}")
            for key, val in sorted(m.labeled().items()):
                labels = dict(key)
                if isinstance(val, _HistCell):
                    cum = _cumulate(val.counts)
                    for b, c in zip([*m.buckets, "+Inf"], cum):
                        le = _prom_float(b) if b != "+Inf" else "+Inf"
                        lines.append(
                            f"{pname}_bucket"
                            f"{_prom_labels({**labels, 'le': le})} {c}")
                    lines.append(f"{pname}_sum{_prom_labels(labels)} "
                                 f"{_prom_float(val.sum)}")
                    lines.append(f"{pname}_count{_prom_labels(labels)} "
                                 f"{val.count}")
                else:
                    lines.append(f"{pname}{_prom_labels(labels)} "
                                 f"{_prom_float(val)}")
        return "\n".join(lines) + ("\n" if lines else "")

    def reset(self):
        """Drop every instrument (tests)."""
        with self._lock:
            self._metrics.clear()


def _cumulate(counts):
    out, acc = [], 0
    for c in counts:
        acc += c
        out.append(acc)
    return out


def _prom_name(name: str, kind: str) -> str:
    base = "paddle_tpu_" + name.replace(".", "_").replace("-", "_")
    if kind == "counter" and not base.endswith("_total"):
        base += "_total"
    return base


def _prom_escape_help(s: str) -> str:
    return s.replace("\\", "\\\\").replace("\n", "\\n")


def _prom_escape_label(s: str) -> str:
    return (s.replace("\\", "\\\\").replace("\n", "\\n")
            .replace('"', '\\"'))


def _prom_labels(labels: dict) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_prom_escape_label(str(v))}"'
                     for k, v in sorted(labels.items()))
    return "{" + inner + "}"


def _prom_float(v) -> str:
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


#: the process-wide default registry every `observability.inc(...)`
#: helper writes to; serving creates per-server registries besides
REGISTRY = MetricsRegistry()
