"""paddle.profiler equivalent (reference: python/paddle/profiler/).

Host scopes → native C++ HostTracer (paddle_tpu/_native); device timeline →
XLA profiler (xplane under logdir, viewable in xprof/tensorboard/perfetto);
chrome-trace JSON export merges host events.
"""
from paddle_tpu.profiler.profiler import (  # noqa: F401
    Profiler, ProfilerState, ProfilerTarget, make_scheduler,
    export_chrome_tracing, export_protobuf,
)
from paddle_tpu.profiler.utils import (  # noqa: F401
    RecordEvent, in_profiler_mode, wrap_optimizers,
)
from paddle_tpu.profiler.timer import Benchmark, benchmark  # noqa: F401
