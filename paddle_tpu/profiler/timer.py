"""Throughput timer (reference: python/paddle/profiler/timer.py — the
Benchmark/TimerHook that feeds fleet "ips" logs)."""
from __future__ import annotations

import time

__all__ = ["Benchmark", "benchmark"]


class _Stat:
    def __init__(self):
        self.reset()

    def reset(self):
        self.count = 0
        self.total = 0.0
        self.samples = 0
        self._t0 = None

    def start(self):
        self._t0 = time.perf_counter()

    def stop(self, num_samples):
        if self._t0 is None:
            return
        self.total += time.perf_counter() - self._t0
        self.count += 1
        self.samples += num_samples or 0
        self._t0 = None

    @property
    def steps_per_sec(self):
        return self.count / self.total if self.total else 0.0

    @property
    def ips(self):
        return self.samples / self.total if self.total else 0.0


class Benchmark:
    def __init__(self):
        self._stat = _Stat()
        self.current_event = self._stat

    def begin(self):
        self._stat.reset()
        self._stat.start()

    def step(self, num_samples=None):
        self._stat.stop(num_samples)
        self._stat.start()

    def end(self):
        self._stat._t0 = None

    def step_info(self, unit=None):
        unit = unit or "samples"
        return (f"avg_steps/sec: {self._stat.steps_per_sec:.3f}, "
                f"ips: {self._stat.ips:.2f} {unit}/s")


_global_benchmark = Benchmark()


def benchmark() -> Benchmark:
    return _global_benchmark
