"""RecordEvent instrumentation scopes (reference:
python/paddle/profiler/utils.py:38 RecordEvent;
paddle/fluid/platform/profiler/event_tracing.h RecordEvent;
host_tracer.h:26 HostTracer).

Events are recorded into the native C++ host tracer
(paddle_tpu/_native/src/native.cc HostTracer — thread-local buffers,
steady-clock ns) and additionally annotated into the XLA device trace via
jax.profiler.TraceAnnotation so host scopes line up with device ops in
xprof/perfetto. A pure-Python recorder is the fallback.
"""
from __future__ import annotations

import functools
import threading
import time

from paddle_tpu import _native

__all__ = ["RecordEvent", "in_profiler_mode", "wrap_optimizers"]

_py_events = []  # fallback recorder: (name, t0_ns, t1_ns, tid, kind, value)
_py_lock = threading.Lock()
_py_enabled = [False]


def _tracer_enabled() -> bool:
    lib = _native.load()
    if lib is not None:
        return bool(lib.pt_tracer_enabled())
    return _py_enabled[0]


def in_profiler_mode() -> bool:
    return _tracer_enabled()


def enable_host_tracer(on: bool) -> None:
    lib = _native.load()
    if lib is not None:
        lib.pt_tracer_enable(1 if on else 0)
    else:
        _py_enabled[0] = bool(on)


def clear_host_events() -> None:
    lib = _native.load()
    if lib is not None:
        lib.pt_tracer_clear()
    else:
        with _py_lock:
            _py_events.clear()


def host_chrome_events() -> list:
    """Collected host events as chrome-trace event dicts."""
    lib = _native.load()
    if lib is not None:
        import ctypes
        import json
        out = ctypes.POINTER(ctypes.c_uint8)()
        n = ctypes.c_int64()
        lib.pt_tracer_export_chrome(ctypes.byref(out), ctypes.byref(n))
        return json.loads(_native._take_bytes(lib, out, n) or b"[]")
    with _py_lock:
        evs = []
        for name, t0, t1, tid, kind, value in _py_events:
            e = {"name": name, "ph": {0: "X", 1: "i", 2: "C"}[kind],
                 "pid": 0, "tid": tid, "ts": t0 / 1000.0}
            if kind == 0:
                e["dur"] = (t1 - t0) / 1000.0
            elif kind == 2:
                e["args"] = {"value": value}
            evs.append(e)
        return evs


def record_counter(name: str, value: float) -> None:
    lib = _native.load()
    if lib is not None:
        lib.pt_tracer_counter(name.encode(), float(value))
    elif _py_enabled[0]:
        t = time.perf_counter_ns()
        with _py_lock:
            _py_events.append((name, t, t, threading.get_ident(), 2,
                               float(value)))


class RecordEvent:
    """Context manager / decorator marking a named host scope.

    Mirrors paddle.profiler.RecordEvent (reference utils.py:38): usable as
    `with RecordEvent("forward"):` or `.begin()`/`.end()` pairs.
    """

    def __init__(self, name: str, event_type=None):
        self.name = name
        self.event_type = event_type
        self._annotation = None
        self._t0 = None

    def begin(self):
        if not _tracer_enabled():
            return
        lib = _native.load()
        if lib is not None:
            lib.pt_tracer_push(self.name.encode())
        else:
            self._t0 = time.perf_counter_ns()
        try:
            import jax.profiler
            self._annotation = jax.profiler.TraceAnnotation(self.name)
            self._annotation.__enter__()
        except Exception:
            self._annotation = None

    def end(self):
        if self._annotation is not None:
            self._annotation.__exit__(None, None, None)
            self._annotation = None
        lib = _native.load()
        if lib is not None:
            if _tracer_enabled():
                lib.pt_tracer_pop()
        elif self._t0 is not None:
            t1 = time.perf_counter_ns()
            with _py_lock:
                _py_events.append((self.name, self._t0, t1,
                                   threading.get_ident(), 0, 0.0))
            self._t0 = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False

    def __call__(self, fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with RecordEvent(self.name):
                return fn(*args, **kwargs)
        return wrapper


def wrap_optimizers():
    """Reference wraps Optimizer.step in RecordEvent scopes
    (python/paddle/profiler/utils.py wrap_optimizers); ours instruments
    paddle_tpu.optimizer.Optimizer.step once."""
    from paddle_tpu.optimizer import Optimizer
    if getattr(Optimizer.step, "_profiled", False):
        return
    orig = Optimizer.step

    @functools.wraps(orig)
    def step(self, *a, **k):
        with RecordEvent(f"{type(self).__name__}.step"):
            return orig(self, *a, **k)

    step._profiled = True
    Optimizer.step = step
