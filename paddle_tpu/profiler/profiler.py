"""Profiler with scheduler states and chrome-trace export (reference:
python/paddle/profiler/profiler.py:79 ProfilerState, :346 class Profiler;
chrome export chrometracing_logger.cc).

TPU-native split of responsibilities: device-side tracing is delegated to
XLA's profiler (jax.profiler.start_trace → xplane/perfetto artifacts under
`logdir`), host-side scopes come from the native HostTracer
(paddle_tpu/_native) and are exported as a chrome-trace JSON that can be
loaded in chrome://tracing or perfetto alongside the device trace.
"""
from __future__ import annotations

import enum
import json
import os
import socket
import time

from paddle_tpu.profiler import utils as _utils

__all__ = ["ProfilerState", "ProfilerTarget", "Profiler", "make_scheduler",
           "export_chrome_tracing", "export_protobuf"]


class ProfilerState(enum.Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


class ProfilerTarget(enum.Enum):
    CPU = 0
    GPU = 1
    XPU = 2
    CUSTOM_DEVICE = 3
    TPU = 4


def make_scheduler(*, closed: int, ready: int, record: int, repeat: int = 0,
                   skip_first: int = 0):
    """Step-indexed state machine (reference profiler.py make_scheduler)."""
    cycle = closed + ready + record

    def scheduler(step: int) -> ProfilerState:
        if step < skip_first:
            return ProfilerState.CLOSED
        step -= skip_first
        if repeat and step >= repeat * cycle:
            return ProfilerState.CLOSED
        pos = step % cycle
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == cycle - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return scheduler


def _default_scheduler(step: int) -> ProfilerState:
    return ProfilerState.RECORD


def export_chrome_tracing(dir_name: str, worker_name: str | None = None):
    """on_trace_ready callback writing chrome trace json."""
    seq = [0]

    def handler(prof: "Profiler"):
        os.makedirs(dir_name, exist_ok=True)
        worker = worker_name or f"host_{socket.gethostname()}_pid{os.getpid()}"
        seq[0] += 1
        # monotonic sequence: repeated record cycles within one second must
        # not clobber each other
        path = os.path.join(
            dir_name,
            f"{worker}_time_{int(time.time())}_{seq[0]}.paddle_trace.json")
        prof._export_chrome(path)
        prof.last_export_path = path
    return handler


def export_protobuf(dir_name: str, worker_name: str | None = None):
    # the XLA trace under logdir IS the protobuf artifact; host json besides
    return export_chrome_tracing(dir_name, worker_name)


class Profiler:
    """paddle.profiler.Profiler equivalent.

    with Profiler(scheduler=(2, 5), on_trace_ready=...) as p:
        for batch in loader:
            train_step(batch)
            p.step()
    """

    def __init__(self, *, targets=None, scheduler=None, on_trace_ready=None,
                 record_shapes: bool = False, profile_memory: bool = False,
                 timer_only: bool = False, emit_nvtx: bool = False,
                 custom_device_types=None, with_flops: bool = False,
                 logdir: str | None = None):
        if scheduler is None:
            self._scheduler = _default_scheduler
        elif isinstance(scheduler, (tuple, list)):
            start, end = scheduler
            self._scheduler = make_scheduler(
                closed=max(start, 0), ready=0, record=end - start, repeat=1)
        else:
            self._scheduler = scheduler
        self._on_trace_ready = on_trace_ready
        self._timer_only = timer_only
        self._logdir = logdir or os.environ.get(
            "PADDLE_TPU_PROFILE_DIR", "profiler_log")
        self.current_state = ProfilerState.CLOSED
        self._step = 0
        self._device_tracing = False
        self.last_export_path = None
        self._benchmark = None
        if timer_only:
            from paddle_tpu.profiler.timer import Benchmark
            self._benchmark = Benchmark()

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        self.current_state = self._scheduler(self._step)
        if self._benchmark is not None:
            self._benchmark.begin()
        if self._timer_only:
            return
        self._transit(ProfilerState.CLOSED, self.current_state)

    def stop(self):
        if self._benchmark is not None:
            self._benchmark.end()
        if self._timer_only:
            return
        if self.current_state in (ProfilerState.RECORD,
                                  ProfilerState.RECORD_AND_RETURN):
            self._stop_tracing()
            if self._on_trace_ready:
                self._on_trace_ready(self)
        self.current_state = ProfilerState.CLOSED

    def step(self, num_samples: int | None = None):
        if self._benchmark is not None:
            self._benchmark.step(num_samples)
        self._step += 1
        if self._timer_only:
            return
        old = self.current_state
        new = self._scheduler(self._step)
        self.current_state = new
        self._transit(old, new)

    def step_info(self, unit=None):
        if self._benchmark is None:
            return ""
        return self._benchmark.step_info(unit)

    def _transit(self, old: ProfilerState, new: ProfilerState):
        was_rec = old in (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN)
        is_rec = new in (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN)
        if not was_rec and is_rec:
            self._start_tracing()
        elif was_rec and (not is_rec or old == ProfilerState.RECORD_AND_RETURN):
            self._stop_tracing()
            if self._on_trace_ready:
                self._on_trace_ready(self)
            if is_rec:
                self._start_tracing()

    def _start_tracing(self):
        _utils.clear_host_events()
        _utils.enable_host_tracer(True)
        try:
            import jax.profiler
            jax.profiler.start_trace(self._logdir)
            self._device_tracing = True
        except Exception:
            self._device_tracing = False

    def _stop_tracing(self):
        _utils.enable_host_tracer(False)
        if self._device_tracing:
            try:
                import jax.profiler
                jax.profiler.stop_trace()
            except Exception:  # lint: disable=silent-swallow -- stop_trace after a backend that never started; host events still export
                pass
            self._device_tracing = False

    # -- export / summary --------------------------------------------------
    def _export_chrome(self, path: str):
        events = _utils.host_chrome_events()
        with open(path, "w") as f:
            json.dump({"traceEvents": events,
                       "displayTimeUnit": "ms",
                       "metadata": {"producer": "paddle_tpu.profiler",
                                    "xla_trace_logdir": self._logdir}}, f)

    def export(self, path: str, format: str = "json"):
        self._export_chrome(path)

    def summary(self, sorted_by=None, op_detail=True, thread_sep=False,
                time_unit="ms"):
        events = _utils.host_chrome_events()
        stats = {}
        for e in events:
            if e.get("ph") != "X":
                continue
            s = stats.setdefault(e["name"], [0, 0.0, 0.0])
            s[0] += 1
            s[1] += e.get("dur", 0.0)
            s[2] = max(s[2], e.get("dur", 0.0))
        lines = [f"{'Name':<40}{'Calls':>8}{'Total(ms)':>12}{'Max(ms)':>12}"]
        for name, (calls, total, mx) in sorted(
                stats.items(), key=lambda kv: -kv[1][1]):
            lines.append(
                f"{name[:39]:<40}{calls:>8}{total / 1000:>12.3f}"
                f"{mx / 1000:>12.3f}")
        report = "\n".join(lines)
        print(report)
        return report

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False
