"""`paddle.incubate.optimizer` — LookAhead, ModelAverage (reference:
python/paddle/incubate/optimizer/lookahead.py:30, modelaverage.py).
"""
from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.optimizer import Optimizer

__all__ = ["LookAhead", "ModelAverage"]


class LookAhead:
    """k steps forward, 1 step back (reference: lookahead.py LookAhead —
    wraps an inner optimizer; slow weights pulled toward fast weights
    every k steps)."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        if not isinstance(inner_optimizer, Optimizer):
            raise TypeError("inner_optimizer must be a paddle_tpu Optimizer")
        self.inner_optimizer = inner_optimizer
        self.alpha = float(alpha)
        self.k = int(k)
        self._step_num = 0
        self._slow = {id(p): jnp.asarray(p._value)
                      for p in inner_optimizer._parameter_list}

    @property
    def _parameter_list(self):
        return self.inner_optimizer._parameter_list

    def step(self):
        self.inner_optimizer.step()
        self._step_num += 1
        if self._step_num % self.k == 0:
            for p in self.inner_optimizer._parameter_list:
                slow = self._slow[id(p)]
                slow = slow + self.alpha * (
                    p._value.astype(slow.dtype) - slow)
                self._slow[id(p)] = slow
                p._value = slow.astype(p._value.dtype)

    def clear_grad(self, *a, **k):
        return self.inner_optimizer.clear_grad(*a, **k)

    def minimize(self, loss):
        loss.backward()
        self.step()
        self.clear_grad()

    def state_dict(self):
        sd = self.inner_optimizer.state_dict()
        sd["lookahead_step"] = self._step_num
        return sd


class ModelAverage:
    """Maintains an exponential/window average of parameters for eval
    (reference: modelaverage.py ModelAverage; apply()/restore())."""

    def __init__(self, average_window_rate=0.15, parameters=None,
                 min_average_window=10000, max_average_window=10000,
                 name=None):
        if parameters is None:
            raise ValueError("ModelAverage requires the parameter list")
        self._params = list(parameters)
        self._rate = average_window_rate
        self._min_window = int(min_average_window)
        self._max_window = int(max_average_window)
        self._sums = {id(p): jnp.zeros_like(p._value.astype(jnp.float32))
                      for p in self._params}
        self._count = 0
        self._total_steps = 0
        self._backup = None

    def step(self):
        self._total_steps += 1
        # window restart (reference modelaverage.py: the accumulator is
        # restarted so at most ~max_average_window recent snapshots — and
        # no more than rate*num_updates once past min_average_window —
        # contribute to the average)
        if (self._count >= self._max_window
                or (self._total_steps > self._min_window
                    and self._count >= max(
                        1, int(self._rate * self._total_steps)))):
            for p in self._params:
                self._sums[id(p)] = jnp.zeros_like(
                    p._value.astype(jnp.float32))
            self._count = 0
        for p in self._params:
            self._sums[id(p)] = (self._sums[id(p)]
                                 + p._value.astype(jnp.float32))
        self._count += 1

    def apply(self, executor=None, need_restore=True):
        """Swap averaged weights in (context-manager style also works)."""
        self._backup = {id(p): p._value for p in self._params}
        for p in self._params:
            if self._count:
                p._value = (self._sums[id(p)] / self._count).astype(
                    p._value.dtype)
        if not need_restore:
            self._backup = None
        return self

    def restore(self, executor=None):
        if self._backup is not None:
            for p in self._params:
                p._value = self._backup[id(p)]
            self._backup = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.restore()
        return False
