"""paddle.incubate parity namespace (reference: python/paddle/incubate/).

Hosts the fused-op functional API the reference's LLM recipes call
(fused_rms_norm, fused_rotary_position_embedding, swiglu, ...). On TPU
"fused" means: expressed so XLA fuses it into one kernel, or routed to a
Pallas kernel where XLA's fusion is insufficient (paddle_tpu.kernels).
"""
from paddle_tpu.incubate import nn  # noqa: F401
from paddle_tpu.incubate import asp  # noqa: F401
from paddle_tpu.incubate import optimizer  # noqa: F401
from paddle_tpu.incubate.optimizer import LookAhead, ModelAverage  # noqa: F401
