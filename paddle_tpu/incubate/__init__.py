"""paddle.incubate parity namespace (reference: python/paddle/incubate/).

Hosts the fused-op functional API the reference's LLM recipes call
(fused_rms_norm, fused_rotary_position_embedding, swiglu, ...). On TPU
"fused" means: expressed so XLA fuses it into one kernel, or routed to a
Pallas kernel where XLA's fusion is insufficient (paddle_tpu.kernels).
"""
from paddle_tpu.incubate import nn  # noqa: F401
from paddle_tpu.incubate import asp  # noqa: F401
from paddle_tpu.incubate import optimizer  # noqa: F401
from paddle_tpu.incubate.optimizer import LookAhead, ModelAverage  # noqa: F401


# legacy graph-op aliases (reference: incubate/__init__.py re-exports of
# the pre-paddle.geometric API)
from paddle_tpu.geometric import (  # noqa: F401,E402
    segment_sum, segment_mean, segment_min, segment_max)


def graph_send_recv(x, src_index, dst_index, pool_type="sum",
                    out_size=None, name=None):
    from paddle_tpu.geometric import send_u_recv
    return send_u_recv(x, src_index, dst_index, reduce_op=pool_type,
                       out_size=out_size)


def graph_reindex(x, neighbors, count, value_buffer=None,
                  index_buffer=None, flag_buffer_hashtable=False,
                  name=None):
    from paddle_tpu.geometric import reindex_graph
    return reindex_graph(x, neighbors, count)


def graph_sample_neighbors(row, colptr, input_nodes, eids=None,
                           perm_buffer=None, sample_size=-1,
                           return_eids=False, flag_perm_buffer=False,
                           name=None):
    from paddle_tpu.geometric import sample_neighbors
    return sample_neighbors(row, colptr, input_nodes,
                            sample_size=sample_size, eids=eids,
                            return_eids=return_eids)


def graph_khop_sampler(row, colptr, input_nodes, sample_sizes,
                       sorted_eids=None, return_eids=False, name=None):
    """Multi-hop neighbor sampling (reference:
    incubate/operators/graph_khop_sampler.py:123 — returns
    (edge_src, edge_dst, sample_index, reindex_nodes): locally-reindexed
    edges over the union subgraph, the union's global node ids, and the
    local ids of the seed nodes)."""
    if return_eids:
        raise NotImplementedError("return_eids unsupported in khop sampler")
    from paddle_tpu.geometric import sample_neighbors
    import numpy as np
    import jax.numpy as jnp
    from paddle_tpu.core.tensor import Tensor

    seeds = np.asarray(input_nodes._value
                       if isinstance(input_nodes, Tensor)
                       else input_nodes).ravel()
    seen = dict((int(n), i) for i, n in enumerate(seeds))
    union = list(seeds)
    frontier = seeds
    src_g, dst_g = [], []
    for k in sample_sizes:
        if len(frontier) == 0:
            break
        nb, cnt = sample_neighbors(row, colptr,
                                   Tensor(jnp.asarray(frontier,
                                                      jnp.int32)),
                                   sample_size=k)
        nb_np = np.asarray(nb._value)
        cnt_np = np.asarray(cnt._value)
        dst_np = np.repeat(frontier, cnt_np)
        src_g.append(nb_np)
        dst_g.append(dst_np)
        nxt = []
        for n in nb_np:
            n = int(n)
            if n not in seen:
                seen[n] = len(union)
                union.append(n)
                nxt.append(n)
        # next frontier: only NEW nodes (reference khop semantics —
        # already-visited nodes are not re-expanded)
        frontier = np.asarray(nxt, seeds.dtype)
    all_src = (np.concatenate(src_g) if src_g
               else np.zeros(0, np.int64))
    all_dst = (np.concatenate(dst_g) if dst_g
               else np.zeros(0, np.int64))
    edge_src = np.asarray([seen[int(n)] for n in all_src], np.int32)
    edge_dst = np.asarray([seen[int(n)] for n in all_dst], np.int32)
    sample_index = np.asarray(union, np.int32)
    reindex_nodes = np.arange(len(seeds), dtype=np.int32)
    return (Tensor(jnp.asarray(edge_src)), Tensor(jnp.asarray(edge_dst)),
            Tensor(jnp.asarray(sample_index)),
            Tensor(jnp.asarray(reindex_nodes)))


def identity_loss(x, reduction="none"):
    """(reference: incubate/nn/functional/identity_loss.py — marks a
    tensor as the loss for IPU; on TPU it is reduce-or-pass-through)."""
    from paddle_tpu import tensor as T
    if reduction in ("none", 2):
        return x
    if reduction in ("mean", 1):
        return T.mean(x)
    if reduction in ("sum", 0):
        return T.sum(x)
    raise ValueError(f"unknown reduction {reduction!r}: expected "
                     f"sum/mean/none (0/1/2)")


def softmax_mask_fuse(x, mask, name=None):
    """(reference: incubate/operators/softmax_mask_fuse.py — fused
    softmax(x + mask); XLA fuses the composition)."""
    from paddle_tpu.core.dispatch import dispatch, OpDef
    import jax
    return dispatch(OpDef("softmax_mask_fuse",
                          lambda a, m: jax.nn.softmax(a + m, axis=-1)),
                    (x, mask), {})


def softmax_mask_fuse_upper_triangle(x):
    """(reference: softmax_mask_fuse_upper_triangle — causal-masked
    softmax without an explicit mask tensor)."""
    from paddle_tpu.core.dispatch import dispatch, OpDef
    import jax
    import jax.numpy as jnp

    def f(a):
        s = a.shape[-1]
        causal = jnp.tril(jnp.ones((s, s), bool))
        return jax.nn.softmax(jnp.where(causal, a, -1e9), axis=-1)
    return dispatch(OpDef("softmax_mask_fuse_upper_triangle", f), (x,), {})
