from paddle_tpu.incubate.nn import functional  # noqa: F401
