from paddle_tpu.incubate.nn import functional  # noqa: F401

from paddle_tpu.incubate.nn.layer import *  # noqa: F401,F403
