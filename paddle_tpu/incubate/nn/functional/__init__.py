"""Fused-op functional API (reference: python/paddle/incubate/nn/functional/
fused_rms_norm.py, fused_rotary_position_embedding.py, swiglu.py,
fused_layer_norm.py — CUDA kernels under paddle/phi/kernels/fusion/gpu/).

TPU-native: each op is ONE traced jax expression, so XLA's fusion pass emits
a single kernel — the hand-written CUDA fusion the reference needs is the
compiler's job here. Ops that XLA fuses poorly (blockwise attention) live in
paddle_tpu.kernels as Pallas kernels instead.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.core.dispatch import defop


# ---------------------------------------------------------------------------
# rms / layer norm
# ---------------------------------------------------------------------------

def _rms_norm_raw(x, weight, bias, epsilon, begin_norm_axis):
    axes = tuple(range(begin_norm_axis, x.ndim))
    xf = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(xf), axis=axes, keepdims=True)
    y = xf * jax.lax.rsqrt(var + epsilon)
    y = y.astype(x.dtype) * weight
    if bias is not None:
        y = y + bias
    return y


@defop("fused_rms_norm", amp_policy="black",
       spmd_note="norm axis must be replicated; batch/seq axes free")
def _fused_rms_norm(x, norm_weight, norm_bias=None, epsilon=1e-6,
                    begin_norm_axis=-1):
    ax = begin_norm_axis if begin_norm_axis >= 0 else x.ndim + begin_norm_axis
    return _rms_norm_raw(x, norm_weight, norm_bias, epsilon, ax)


def fused_rms_norm(x, norm_weight, norm_bias=None, epsilon=1e-6,
                   begin_norm_axis=-1, **kwargs):
    """Reference: incubate/nn/functional/fused_rms_norm.py (kernel
    phi/kernels/fusion/gpu/fused_layernorm_kernel.cu rmsnorm branch).
    Returns (out, invvar-placeholder) pair like the reference."""
    out = _fused_rms_norm(x, norm_weight, norm_bias, epsilon=epsilon,
                          begin_norm_axis=begin_norm_axis)
    return out, None


def fused_layer_norm(x, norm_weight, norm_bias, epsilon=1e-5,
                     begin_norm_axis=-1, **kwargs):
    from paddle_tpu.nn import functional as F
    return F.layer_norm(x, x.shape[begin_norm_axis:], norm_weight,
                        norm_bias, epsilon), None


# ---------------------------------------------------------------------------
# rotary position embedding
# ---------------------------------------------------------------------------

def _rope_cos_sin(seq_len, head_dim, theta, dtype, position_ids=None):
    inv_freq = 1.0 / (theta ** (
        jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    if position_ids is None:
        t = jnp.arange(seq_len, dtype=jnp.float32)
        freqs = jnp.outer(t, inv_freq)            # (S, D/2)
    else:
        freqs = position_ids[..., None].astype(jnp.float32) * inv_freq
    return jnp.cos(freqs), jnp.sin(freqs)


def _apply_rope_neox(x, cos, sin):
    """NeoX/Llama style: rotate [first half | second half]. x: (B,S,H,D);
    cos/sin broadcastable (S, D/2) or (B,S,D/2)."""
    d2 = x.shape[-1] // 2
    x1, x2 = x[..., :d2], x[..., d2:]
    if cos.ndim == 2:
        cos = cos[None, :, None, :]
        sin = sin[None, :, None, :]
    else:
        cos = cos[:, :, None, :]
        sin = sin[:, :, None, :]
    cos = cos.astype(jnp.float32)
    sin = sin.astype(jnp.float32)
    xf1, xf2 = x1.astype(jnp.float32), x2.astype(jnp.float32)
    o1 = xf1 * cos - xf2 * sin
    o2 = xf2 * cos + xf1 * sin
    return jnp.concatenate([o1, o2], axis=-1).astype(x.dtype)


def _apply_rope_interleaved(x, cos, sin):
    """GPT-J style: rotate even/odd interleaved pairs."""
    x1, x2 = x[..., 0::2], x[..., 1::2]
    if cos.ndim == 2:
        cos = cos[None, :, None, :]
        sin = sin[None, :, None, :]
    else:
        cos = cos[:, :, None, :]
        sin = sin[:, :, None, :]
    o1 = x1 * cos - x2 * sin
    o2 = x2 * cos + x1 * sin
    out = jnp.stack([o1, o2], axis=-1)
    return out.reshape(x.shape).astype(x.dtype)


@defop("fused_rope", amp_policy="white",
       spmd_note="heads axis shards over 'mp'; seq sharding composes with "
                 "position_ids offsets (context parallel)")
def _fused_rope(q, k, v, sin, cos, position_ids, use_neox_rotary_style,
                theta):
    seq_len, head_dim = q.shape[1], q.shape[-1]
    if cos is None or sin is None:
        cos, sin = _rope_cos_sin(seq_len, head_dim, theta, q.dtype,
                                 position_ids)
    else:
        # reference passes (1, S, 1, D) duplicated-half tables; reduce to D/2
        cos = jnp.squeeze(cos)[..., : head_dim // 2]
        sin = jnp.squeeze(sin)[..., : head_dim // 2]
        if position_ids is not None:
            cos = jnp.take(cos, position_ids, axis=0)
            sin = jnp.take(sin, position_ids, axis=0)
    apply = (_apply_rope_neox if use_neox_rotary_style
             else _apply_rope_interleaved)
    outs = tuple(apply(t, cos, sin) if t is not None else None
                 for t in (q, k, v))
    return tuple(o for o in outs if o is not None)


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None,
                                    use_neox_rotary_style=True,
                                    rotary_emb_base=10000.0, **kwargs):
    """Reference: incubate/nn/functional/fused_rotary_position_embedding.py
    (kernel phi/kernels/fusion/gpu/fused_rope). Layout (B, S, H, D)."""
    outs = _fused_rope(q, k, v, sin, cos, position_ids,
                       use_neox_rotary_style=use_neox_rotary_style,
                       theta=rotary_emb_base)
    if not isinstance(outs, tuple):
        outs = (outs,)
    res = list(outs) + [None] * (3 - len(outs))
    return tuple(res[:3])


# ---------------------------------------------------------------------------
# activations / gemm epilogues
# ---------------------------------------------------------------------------

@defop("swiglu", amp_policy="white")
def _swiglu(x, y):
    if y is None:
        x, y = jnp.split(x, 2, axis=-1)
    return jax.nn.silu(x.astype(jnp.float32)).astype(x.dtype) * y


def swiglu(x, y=None, name=None):
    """Reference: incubate/nn/functional/swiglu.py — silu(x) * y, or split
    x in half when y is None (phi SwiGLU kernel)."""
    return _swiglu(x, y)


@defop("fused_bias_act", amp_policy="white")
def _fused_bias_act(x, bias, act_method):
    if bias is not None:
        x = x + bias
    if act_method in ("gelu", "geglu"):
        return jax.nn.gelu(x)
    if act_method in ("swiglu",):
        a, b = jnp.split(x, 2, axis=-1)
        return jax.nn.silu(a) * b
    if act_method == "relu":
        return jax.nn.relu(x)
    return x


def fused_bias_act(x, bias=None, act_method="gelu", **kwargs):
    """Reference: fused_bias_act_kernel.cu — bias + activation in one pass;
    one XLA fusion here."""
    return _fused_bias_act(x, bias, act_method)


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    """Reference: incubate/nn/functional/fused_linear (cublasLt gemm
    epilogue). XLA fuses bias-add into the MXU matmul."""
    from paddle_tpu.nn import functional as F
    if transpose_weight:
        from paddle_tpu import tensor as T
        weight = T.transpose(weight, [1, 0])
    return F.linear(x, weight, bias)


def fused_linear_activation(x, y, bias=None, trans_x=False, trans_y=False,
                            activation="gelu"):
    out = fused_linear(x, y, bias, transpose_weight=trans_y)
    return fused_bias_act(out, None, act_method=activation)
