"""Fused-op functional API (reference: python/paddle/incubate/nn/functional/
fused_rms_norm.py, fused_rotary_position_embedding.py, swiglu.py,
fused_layer_norm.py — CUDA kernels under paddle/phi/kernels/fusion/gpu/).

TPU-native: each op is ONE traced jax expression, so XLA's fusion pass emits
a single kernel — the hand-written CUDA fusion the reference needs is the
compiler's job here. Ops that XLA fuses poorly (blockwise attention) live in
paddle_tpu.kernels as Pallas kernels instead.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.core.dispatch import defop


# ---------------------------------------------------------------------------
# rms / layer norm
# ---------------------------------------------------------------------------

def _rms_norm_raw(x, weight, bias, epsilon, begin_norm_axis):
    axes = tuple(range(begin_norm_axis, x.ndim))
    xf = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(xf), axis=axes, keepdims=True)
    y = xf * jax.lax.rsqrt(var + epsilon)
    y = y.astype(x.dtype) * weight
    if bias is not None:
        y = y + bias
    return y


@defop("fused_rms_norm", amp_policy="black",
       spmd_note="norm axis must be replicated; batch/seq axes free")
def _fused_rms_norm(x, norm_weight, norm_bias=None, epsilon=1e-6,
                    begin_norm_axis=-1):
    ax = begin_norm_axis if begin_norm_axis >= 0 else x.ndim + begin_norm_axis
    return _rms_norm_raw(x, norm_weight, norm_bias, epsilon, ax)


def fused_rms_norm(x, norm_weight, norm_bias=None, epsilon=1e-6,
                   begin_norm_axis=-1, **kwargs):
    """Reference: incubate/nn/functional/fused_rms_norm.py (kernel
    phi/kernels/fusion/gpu/fused_layernorm_kernel.cu rmsnorm branch).
    Returns (out, invvar-placeholder) pair like the reference."""
    out = _fused_rms_norm(x, norm_weight, norm_bias, epsilon=epsilon,
                          begin_norm_axis=begin_norm_axis)
    return out, None


def fused_layer_norm(x, norm_weight, norm_bias, epsilon=1e-5,
                     begin_norm_axis=1, **kwargs):
    from paddle_tpu.nn import functional as F
    return F.layer_norm(x, x.shape[begin_norm_axis:], norm_weight,
                        norm_bias, epsilon), None


# ---------------------------------------------------------------------------
# rotary position embedding
# ---------------------------------------------------------------------------

def _rope_cos_sin(seq_len, head_dim, theta, dtype, position_ids=None):
    inv_freq = 1.0 / (theta ** (
        jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    if position_ids is None:
        t = jnp.arange(seq_len, dtype=jnp.float32)
        freqs = jnp.outer(t, inv_freq)            # (S, D/2)
    else:
        freqs = position_ids[..., None].astype(jnp.float32) * inv_freq
    return jnp.cos(freqs), jnp.sin(freqs)


def _rope_neox_raw(x, cos, sin):
    d2 = x.shape[-1] // 2
    x1, x2 = x[..., :d2], x[..., d2:]
    if cos.ndim == 2:
        cos = cos[None, :, None, :]
        sin = sin[None, :, None, :]
    else:
        cos = cos[:, :, None, :]
        sin = sin[:, :, None, :]
    cos = cos.astype(jnp.float32)
    sin = sin.astype(jnp.float32)
    xf1, xf2 = x1.astype(jnp.float32), x2.astype(jnp.float32)
    o1 = xf1 * cos - xf2 * sin
    o2 = xf2 * cos + xf1 * sin
    return jnp.concatenate([o1, o2], axis=-1).astype(x.dtype)


@jax.custom_vjp
def _apply_rope_neox(x, cos, sin):
    """NeoX/Llama style: rotate [first half | second half]. x: (B,S,H,D);
    cos/sin broadcastable (S, D/2) or (B,S,D/2).

    Custom vjp: the backward of a rotation is the INVERSE rotation —
    the same forward-shaped code on the cotangent with -sin — which
    avoids the layout-hostile slice/concat transpose chain jax AD
    generates for the half-split formulation (measured as relayout
    copies in the step trace)."""
    return _rope_neox_raw(x, cos, sin)


def _rope_fwd(x, cos, sin):
    return _rope_neox_raw(x, cos, sin), (cos, sin)


def _rope_bwd(res, g):
    cos, sin = res
    return (_rope_neox_raw(g, cos, -sin), jnp.zeros_like(cos),
            jnp.zeros_like(sin))


_apply_rope_neox.defvjp(_rope_fwd, _rope_bwd)


def _apply_rope_interleaved(x, cos, sin):
    """GPT-J style: rotate even/odd interleaved pairs."""
    x1, x2 = x[..., 0::2], x[..., 1::2]
    if cos.ndim == 2:
        cos = cos[None, :, None, :]
        sin = sin[None, :, None, :]
    else:
        cos = cos[:, :, None, :]
        sin = sin[:, :, None, :]
    o1 = x1 * cos - x2 * sin
    o2 = x2 * cos + x1 * sin
    out = jnp.stack([o1, o2], axis=-1)
    return out.reshape(x.shape).astype(x.dtype)


@defop("fused_rope", amp_policy="white",
       spmd_note="heads axis shards over 'mp'; seq sharding composes with "
                 "position_ids offsets (context parallel)")
def _fused_rope(q, k, v, sin, cos, position_ids, use_neox_rotary_style,
                theta):
    seq_len, head_dim = q.shape[1], q.shape[-1]
    if cos is None or sin is None:
        cos, sin = _rope_cos_sin(seq_len, head_dim, theta, q.dtype,
                                 position_ids)
    else:
        # reference passes (1, S, 1, D) duplicated-half tables; reduce to D/2
        cos = jnp.squeeze(cos)[..., : head_dim // 2]
        sin = jnp.squeeze(sin)[..., : head_dim // 2]
        if position_ids is not None:
            cos = jnp.take(cos, position_ids, axis=0)
            sin = jnp.take(sin, position_ids, axis=0)
    apply = (_apply_rope_neox if use_neox_rotary_style
             else _apply_rope_interleaved)
    outs = tuple(apply(t, cos, sin) if t is not None else None
                 for t in (q, k, v))
    return tuple(o for o in outs if o is not None)


@defop("fused_rope_kernel", amp_policy="white",
       spmd_note="heads axis shards over 'mp'; seq sharding composes "
                 "with explicit positions (context parallel)")
def _fused_rope_kernel_op(q, k=None, positions=None, theta=10000.0,
                          kernel=None):
    """Train-path fused RoPE (kernels/fused_norm.py `rope_apply`):
    full-width cos + sign-folded sin tables built once, the apply is
    mul/lane-roll/mul/add in one pass (Pallas on TPU, fused jnp
    elsewhere), backward = the inverse rotation. Same math as
    `_apply_rope_neox`, without its slice/concat transpose chain."""
    from paddle_tpu.kernels.fused_norm import rope_apply
    out_q = rope_apply(q, positions=positions, theta=theta,
                       kernel=kernel)
    if k is None:
        return out_q
    return out_q, rope_apply(k, positions=positions, theta=theta,
                             kernel=kernel)


def fused_rope_apply(q, k=None, position_ids=None, rotary_emb_base=10000.0,
                     kernel=None, name=None):
    """Fused-kernel twin of `fused_rotary_position_embedding` for the
    NeoX/Llama train path: applies RoPE to q (and k) in layout
    (B, S, H, D). Returns q or (q, k)."""
    return _fused_rope_kernel_op(q, k, positions=position_ids,
                                 theta=rotary_emb_base, kernel=kernel)


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None,
                                    use_neox_rotary_style=True,
                                    rotary_emb_base=10000.0, **kwargs):
    """Reference: incubate/nn/functional/fused_rotary_position_embedding.py
    (kernel phi/kernels/fusion/gpu/fused_rope). Layout (B, S, H, D)."""
    outs = _fused_rope(q, k, v, sin, cos, position_ids,
                       use_neox_rotary_style=use_neox_rotary_style,
                       theta=rotary_emb_base)
    if not isinstance(outs, tuple):
        outs = (outs,)
    res = list(outs) + [None] * (3 - len(outs))
    return tuple(res[:3])


# ---------------------------------------------------------------------------
# activations / gemm epilogues
# ---------------------------------------------------------------------------

@defop("swiglu", amp_policy="white")
def _swiglu(x, y):
    if y is None:
        x, y = jnp.split(x, 2, axis=-1)
    return jax.nn.silu(x.astype(jnp.float32)).astype(x.dtype) * y


def swiglu(x, y=None, name=None):
    """Reference: incubate/nn/functional/swiglu.py — silu(x) * y, or split
    x in half when y is None (phi SwiGLU kernel)."""
    return _swiglu(x, y)


@defop("fused_bias_act", amp_policy="white")
def _fused_bias_act(x, bias, act_method):
    if bias is not None:
        x = x + bias
    if act_method in ("gelu", "geglu"):
        return jax.nn.gelu(x)
    if act_method in ("swiglu",):
        a, b = jnp.split(x, 2, axis=-1)
        return jax.nn.silu(a) * b
    if act_method == "relu":
        return jax.nn.relu(x)
    return x


def fused_bias_act(x, bias=None, act_method="gelu", **kwargs):
    """Reference: fused_bias_act_kernel.cu — bias + activation in one pass;
    one XLA fusion here."""
    return _fused_bias_act(x, bias, act_method)


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    """Reference: incubate/nn/functional/fused_linear (cublasLt gemm
    epilogue). XLA fuses bias-add into the MXU matmul."""
    from paddle_tpu.nn import functional as F
    if transpose_weight:
        from paddle_tpu import tensor as T
        weight = T.transpose(weight, [1, 0])
    return F.linear(x, weight, bias)


def fused_linear_activation(x, y, bias=None, trans_x=False, trans_y=False,
                            activation=None):
    out = fused_linear(x, y, bias, transpose_weight=trans_y)
    if activation is None:      # reference default: plain biased linear
        return out
    return fused_bias_act(out, None, act_method=activation)


# ---------------------------------------------------------------------------
# fused attention family (reference: incubate/nn/functional/
# fused_dot_product_attention.py, block_multihead_attention.py,
# masked_multihead_attention.py, variable_length_memory_efficient_attention
# .py — CUDA kernels fused_multi_transformer / block_multi_head_attention)
# ---------------------------------------------------------------------------

def fused_dot_product_attention(q, k, v, mask=None, scaling_factor=None,
                                dropout_prob=0.0, is_training=True,
                                is_causal_masking=False,
                                use_workspace_opt=None,
                                return_softmax=False, *, attn_mask=None,
                                dropout=None, causal=None, training=None,
                                name=None):
    """(reference: fused_dot_product_attention.py:22 — cuDNN fused MHA;
    positional params match). Routes to the flash kernel when unmasked,
    the fused SDPA otherwise; layout (batch, seq, heads, head_dim).
    The trailing keyword aliases (attn_mask/dropout/causal/training) are
    the pre-r5 names, kept for compatibility."""
    from paddle_tpu.nn import functional as F
    mask = attn_mask if attn_mask is not None else mask
    dropout_prob = dropout if dropout is not None else dropout_prob
    is_causal_masking = causal if causal is not None else is_causal_masking
    is_training = training if training is not None else is_training
    if scaling_factor is not None:
        d = q.shape[-1]
        import math
        if abs(float(scaling_factor) - 1.0 / math.sqrt(d)) > 1e-9:
            raise NotImplementedError(
                "non-default scaling_factor is not supported; scale q "
                "before the call")
    if mask is None and not (dropout_prob and is_training):
        out, _ = F.flash_attention(q, k, v, causal=is_causal_masking,
                                   training=is_training)
        return out
    # dropout (or a mask) needs the SDPA path — the flash kernel has no
    # dropout support, and silently dropping it would change training
    return F.scaled_dot_product_attention(
        q, k, v, attn_mask=mask,
        dropout_p=dropout_prob if is_training else 0.0,
        is_causal=is_causal_masking)


@defop("varlen_attn_mask", differentiable=False)
def _varlen_attn_mask_op(q_lens, kv_lens, sq, sk, causal=False):
    """Additive (0 / -1e9) ragged-batch attention mask from per-example
    lengths (reference: the cutlass varlen kernel's implicit masking)."""
    b = q_lens.shape[0]
    col = jnp.arange(sk)[None, None, None, :]
    row = jnp.arange(sq)[None, None, :, None]
    valid = col < kv_lens.reshape(b, 1, 1, 1)
    valid = jnp.logical_and(valid, row < q_lens.reshape(b, 1, 1, 1))
    if causal:
        valid = jnp.logical_and(valid, col <= row)
    return jnp.where(valid, 0.0, -1e9).astype(jnp.float32)


_varlen_attn_mask = _varlen_attn_mask_op


def variable_length_memory_efficient_attention(
        query, key, value, seq_lens, kv_seq_lens, mask=None, scale=None,
        causal=False, pre_cache_length=0):
    """(reference: variable_length_memory_efficient_attention.py — cutlass
    memory-efficient attention over ragged batches). TPU-native: lengths
    become an additive mask; compute stays dense/static-shape (padded),
    which is how TPU serving batches anyway. Layout (b, heads, seq, dim)."""
    from paddle_tpu.nn import functional as F
    from paddle_tpu import tensor as T
    import numpy as np  # noqa: F811
    if pre_cache_length:
        raise NotImplementedError(
            "pre_cache_length is a CUDA-cache detail; prepend the cache to "
            "key/value instead")
    sq = query.shape[2]
    sk = key.shape[2]
    kv_lens = kv_seq_lens if kv_seq_lens is not None else seq_lens
    amask = _varlen_attn_mask(seq_lens, kv_lens, sq=sq, sk=sk,
                              causal=causal)
    if mask is not None:
        amask = amask + mask
    # (b, h, s, d) -> (b, s, h, d) for the sdpa surface
    qs = T.transpose(query, [0, 2, 1, 3])
    ks = T.transpose(key, [0, 2, 1, 3])
    vs = T.transpose(value, [0, 2, 1, 3])
    if scale is not None:
        # SDPA applies 1/sqrt(d); fold the requested scale into q
        import math as _math
        qs = qs * float(scale) * _math.sqrt(query.shape[-1])
    out = F.scaled_dot_product_attention(qs, ks, vs, attn_mask=amask,
                                         is_causal=False)
    return T.transpose(out, [0, 2, 1, 3])


def masked_multihead_attention(x, cache_kv=None, bias=None,
                               src_mask=None, sequence_lengths=None,
                               rotary_tensor=None, beam_cache_offset=None,
                               qkv_out_scale=None, out_shift=None, **kw):
    """Single-step decode attention with KV cache (reference:
    masked_multihead_attention.py — the reference's fused decode kernel).
    x: (b, 3*h*d) packed qkv for ONE new token; cache_kv: (2, b, heads,
    max_seq, d). Returns (out, cache_kv) like the reference."""
    from paddle_tpu.core.tensor import Tensor
    import math
    if cache_kv is None:
        raise ValueError("masked_multihead_attention requires cache_kv")
    unsupported = {"rotary_tensor": rotary_tensor, "bias": bias,
                   "src_mask": src_mask,
                   "beam_cache_offset": beam_cache_offset,
                   "qkv_out_scale": qkv_out_scale, "out_shift": out_shift}
    bad = [k for k, v in unsupported.items() if v is not None]
    if bad:
        raise NotImplementedError(
            f"masked_multihead_attention: {bad} not supported here — apply "
            f"RoPE/bias to qkv before the call (incubate."
            f"fused_rotary_position_embedding)")
    cache = cache_kv._value if isinstance(cache_kv, Tensor) else cache_kv
    xv = x._value if isinstance(x, Tensor) else x
    b = xv.shape[0]
    _, _, h, max_seq, d = cache.shape
    q, k, v = jnp.split(xv.reshape(b, 3, h, d), 3, axis=1)
    q, k, v = q[:, 0], k[:, 0], v[:, 0]        # (b, h, d)
    if sequence_lengths is not None:
        sl = (sequence_lengths._value
              if isinstance(sequence_lengths, Tensor) else sequence_lengths)
        pos = sl.reshape(b).astype(jnp.int32)
    else:
        pos = jnp.zeros((b,), jnp.int32)

    # write k,v at pos
    bidx = jnp.arange(b)
    new_k = cache[0].at[bidx, :, pos, :].set(k)
    new_v = cache[1].at[bidx, :, pos, :].set(v)
    cache_new = jnp.stack([new_k, new_v])

    scores = jnp.einsum("bhd,bhsd->bhs", q.astype(jnp.float32),
                        new_k.astype(jnp.float32)) / math.sqrt(d)
    col = jnp.arange(max_seq)[None, None, :]
    valid = col <= pos.reshape(b, 1, 1)
    scores = jnp.where(valid, scores, -1e9)
    p = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhs,bhsd->bhd", p, new_v.astype(jnp.float32))
    out = out.reshape(b, h * d).astype(xv.dtype)
    from paddle_tpu.core.tensor import Tensor as _T
    return _T(out), _T(cache_new)


def block_multihead_attention(qkv, key_cache, value_cache, seq_lens_encoder,
                              seq_lens_decoder, seq_lens_this_time,
                              padding_offsets=None, cum_offsets=None,
                              cu_seqlens_q=None, cu_seqlens_k=None,
                              block_tables=None, pre_key_cache=None,
                              pre_value_cache=None,
                              cache_k_quant_scales=None,
                              cache_v_quant_scales=None,
                              cache_k_dequant_scales=None,
                              cache_v_dequant_scales=None,
                              qkv_out_scale=None, qkv_bias=None,
                              out_shift=None, out_smooth=None,
                              rope_emb=None, mask=None, tgt_mask=None,
                              max_seq_len=-1, block_size=64,
                              padded_layout=False, **kw):
    """Paged (block) KV-cache attention (reference: incubate/nn/functional/
    block_multihead_attention.py; CUDA kernel
    block_multi_head_attention_kernel.cu). TPU-native reimplementation:

    - caches are (max_block_num, kv_heads, block_size, head_dim) page
      pools; `block_tables` (batch, blocks_per_seq) maps logical pages to
      physical ones. New k/v tokens scatter into their pages in one
      vectorized `.at[...].set`; attention gathers each sequence's pages
      with one take along the page axis (XLA turns both into dynamic
      slices — no fragmentation problem to fight on TPU, but the paged
      API keeps serving-stack parity).
    - both phases of the reference contract: prefill rows
      (seq_lens_encoder > 0, seq_lens_this_time tokens each, causal) and
      decode rows (one token appended at seq_lens_decoder).
    - returns (out, qkv, key_cache, value_cache) like the reference.

    Cache quantization args are CUDA-layout-specific and unsupported.
    """
    import math
    import numpy as _np
    from paddle_tpu.core.tensor import Tensor as _T

    if any(a is not None for a in (cache_k_quant_scales,
                                   cache_v_quant_scales,
                                   cache_k_dequant_scales,
                                   cache_v_dequant_scales, qkv_out_scale,
                                   out_shift, out_smooth)):
        raise NotImplementedError(
            "cache quant/dequant scales are CUDA-serving-specific")
    if any(a is not None for a in (pre_key_cache, pre_value_cache,
                                   tgt_mask)):
        raise NotImplementedError(
            "pre_key_cache/pre_value_cache/tgt_mask are not supported; "
            "prepend prefix tokens through the paged cache itself")
    if rope_emb is not None:
        raise NotImplementedError(
            "apply rotary embedding before block_multihead_attention on "
            "TPU (fused_rotary_position_embedding)")

    def _a(x):
        return x._value if isinstance(x, _T) else jnp.asarray(x)

    qkv_a = _a(qkv)
    kc = _a(key_cache)
    vc = _a(value_cache)
    # Under jit (traced seq-lens), the ragged host-packed token layout
    # has no static shape — but the PADDED layout does: pass qkv as
    # (batch * s_pad, 3*h*d) with per-row validity in
    # seq_lens_this_time, and the op routes through the engine's
    # jit-traceable paged core (inference/paged.py, r5 — invalid rows'
    # writes go to the trash page). s_pad = tok // batch must divide.
    meta_traced = any(isinstance(_a(t), jax.core.Tracer)
                      for t in (block_tables, seq_lens_encoder,
                                seq_lens_decoder, seq_lens_this_time))
    # traced qkv with CONCRETE metadata keeps the ragged path: its index
    # math is host-side, only the value math traces (pre-r5 behavior)
    if padded_layout or meta_traced:
        if not padded_layout:
            raise TypeError(
                "block_multihead_attention under jit requires the PADDED "
                "token layout, opted into EXPLICITLY: pass "
                "padded_layout=True with qkv rows = batch x s_pad and "
                "real counts in seq_lens_this_time. (The eager ragged "
                "host-packed layout cannot be distinguished from padded "
                "under tracing — a silent misread would corrupt the "
                "cache.)")
        if mask is not None:
            raise NotImplementedError(
                "block_multihead_attention under jit does not apply "
                "`mask` (the eager path does); fold the mask into the "
                "compiled caller or drop it")
        from paddle_tpu.inference.paged import (PagedState,
                                                paged_attention_update)
        bsz = int(seq_lens_this_time.shape[0]) \
            if hasattr(seq_lens_this_time, "shape") \
            else len(seq_lens_this_time)
        tok = qkv_a.shape[0]
        if tok % bsz:
            raise TypeError(
                f"padded_layout: qkv rows ({tok}) must be batch ({bsz}) "
                "x s_pad")
        s_pad = tok // bsz
        mbk, hk_, bs_, d_ = kc.shape
        hq_ = qkv_a.shape[-1] // d_ - 2 * hk_
        if qkv_bias is not None:
            qkv_a = qkv_a + _a(qkv_bias)
        q_, k_, v_ = jnp.split(
            qkv_a.reshape(bsz, s_pad, -1),
            [hq_ * d_, (hq_ + hk_) * d_], axis=-1)
        state = PagedState(
            _a(block_tables),
            jnp.reshape(_a(seq_lens_decoder), (-1,)).astype(jnp.int32),
            jnp.reshape(_a(seq_lens_this_time), (-1,)).astype(jnp.int32))
        out, (kc2, vc2) = paged_attention_update(
            q_.reshape(bsz, s_pad, hq_, d_),
            k_.reshape(bsz, s_pad, hk_, d_),
            v_.reshape(bsz, s_pad, hk_, d_),
            (kc, vc), state)
        out2 = _T(out._value.reshape(tok, hq_ * d_).astype(qkv_a.dtype))
        return out2, _T(qkv_a), _T(kc2._value), _T(vc2._value)
    bt = _np.asarray(_a(block_tables))
    enc = _np.asarray(_a(seq_lens_encoder)).reshape(-1)
    dec = _np.asarray(_a(seq_lens_decoder)).reshape(-1)
    this = _np.asarray(_a(seq_lens_this_time)).reshape(-1)
    if qkv_bias is not None:
        qkv_a = qkv_a + _a(qkv_bias)

    bsz = this.shape[0]
    mb, hk, bs, d = kc.shape
    hq = qkv_a.shape[-1] // d - 2 * hk
    tok = qkv_a.shape[0]
    q, k, v = jnp.split(qkv_a, [hq * d, (hq + hk) * d], axis=-1)
    q = q.reshape(tok, hq, d)
    k = k.reshape(tok, hk, d)
    v = v.reshape(tok, hk, d)

    # host-side token bookkeeping (serving drives this eagerly, like the
    # reference's launcher-side get_padding_offset helper)
    sid = _np.repeat(_np.arange(bsz), this)            # (tok,) seq of token
    local = _np.concatenate([_np.arange(n) for n in this]) \
        if tok else _np.zeros((0,), _np.int64)
    # write start per seq: seq_lens_decoder is the already-cached prefix
    # length for BOTH phases (0 for a fresh prefill; chunked prefill with
    # an existing prefix appends after it)
    base = dec
    pos = base[sid] + local                            # global cache pos
    phys = bt[sid, pos // bs]                          # physical page id
    off = pos % bs

    kc = kc.at[phys, :, off, :].set(k.astype(kc.dtype))
    vc = vc.at[phys, :, off, :].set(v.astype(vc.dtype))

    # gather each sequence's pages -> (bsz, hk, L, d), L = pages * bs
    ks = jnp.moveaxis(kc[bt], 2, 1).reshape(bsz, hk, -1, d)
    vs = jnp.moveaxis(vc[bt], 2, 1).reshape(bsz, hk, -1, d)
    L = ks.shape[2]
    if hq != hk:
        ks = jnp.repeat(ks, hq // hk, axis=1)
        vs = jnp.repeat(vs, hq // hk, axis=1)

    # pad tokens to (bsz, m, hq, d) and attend with per-token prefix mask
    m = int(this.max()) if tok else 0
    qp = jnp.zeros((bsz, m, hq, d), q.dtype)
    qp = qp.at[sid, local].set(q)
    scale = 1.0 / math.sqrt(d)
    s = jnp.einsum("bmhd,bhld->bhml", qp.astype(jnp.float32),
                   ks.astype(jnp.float32)) * scale
    qpos = jnp.asarray(base)[:, None] + jnp.arange(m)[None, :]  # (bsz, m)
    col = jnp.arange(L)
    valid = col[None, None, None, :] <= qpos[:, None, :, None]
    if mask is not None:
        mask_a = _a(mask)  # additive, (bsz, 1|hq, m, =<L) reference layout
        s = s + mask_a[..., :m, :L].astype(jnp.float32)
    s = jnp.where(valid, s, -1e9)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhml,bhld->bmhd", p, vs.astype(jnp.float32))
    out = o[sid, local].reshape(tok, hq * d).astype(qkv_a.dtype)

    if isinstance(key_cache, _T):
        key_cache._value = kc
    if isinstance(value_cache, _T):
        value_cache._value = vc
    return (_T(out), _T(qkv_a), _T(kc) if not isinstance(key_cache, _T)
            else key_cache,
            _T(vc) if not isinstance(value_cache, _T) else value_cache)
