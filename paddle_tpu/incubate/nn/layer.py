"""`paddle.incubate.nn` fused layers (reference:
python/paddle/incubate/nn/layer/{fused_transformer,fused_linear,
fused_dropout_add,fused_ec_moe}.py over the CUDA kernels in
paddle/phi/kernels/fusion/gpu/fused_multi_transformer_op.cu etc.).

TPU-native: each layer is a plain composition of ops expressed so XLA
fuses them — "fused" is the compiler's job here, so these classes exist
for API parity and keep the reference constructor
signatures. (FusedMultiTransformer nests per-layer sublayers rather than
the reference's flat per-layer weight lists; remap names when porting its
state dicts.)
"""
from __future__ import annotations

from paddle_tpu import nn
from paddle_tpu import tensor as T
from paddle_tpu.nn.layer.layers import Layer

__all__ = ['FusedMultiHeadAttention', 'FusedFeedForward',
           'FusedTransformerEncoderLayer', 'FusedMultiTransformer',
           'FusedLinear', 'FusedBiasDropoutResidualLayerNorm',
           'FusedEcMoe', 'FusedDropoutAdd']


class FusedMultiHeadAttention(Layer):
    """(reference: fused_transformer.py FusedMultiHeadAttention —
    pre/post-LN attention with packed qkv weights)."""

    def __init__(self, embed_dim, num_heads, dropout_rate=0.5,
                 attn_dropout_rate=0.5, kdim=None, vdim=None,
                 normalize_before=False, need_weights=False,
                 qkv_weight_attr=None, qkv_bias_attr=None,
                 linear_weight_attr=None, linear_bias_attr=None,
                 pre_ln_scale_attr=None, pre_ln_bias_attr=None,
                 ln_scale_attr=None, ln_bias_attr=None, epsilon=1e-5,
                 nranks=1, ring_id=-1, name=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.normalize_before = normalize_before
        # packed qkv weight, reference layout (3, heads, head_dim, embed)
        self.qkv_weight = self.create_parameter(
            [3, num_heads, self.head_dim, embed_dim], attr=qkv_weight_attr)
        self.qkv_bias = self.create_parameter(
            [3, num_heads, self.head_dim], attr=qkv_bias_attr, is_bias=True)
        self.linear_weight = self.create_parameter(
            [embed_dim, embed_dim], attr=linear_weight_attr)
        self.linear_bias = self.create_parameter(
            [embed_dim], attr=linear_bias_attr, is_bias=True)
        self.pre_ln_scale = self.create_parameter(
            [embed_dim], attr=pre_ln_scale_attr,
            default_initializer=nn.initializer.Constant(1.0))
        self.pre_ln_bias = self.create_parameter(
            [embed_dim], attr=pre_ln_bias_attr, is_bias=True)
        self.ln_scale = self.create_parameter(
            [embed_dim], attr=ln_scale_attr,
            default_initializer=nn.initializer.Constant(1.0))
        self.ln_bias = self.create_parameter([embed_dim], attr=ln_bias_attr,
                                             is_bias=True)
        self.dropout = nn.Dropout(dropout_rate)
        self.attn_dropout_rate = attn_dropout_rate
        self._epsilon = epsilon

    def forward(self, x, attn_mask=None, cache=None):
        if cache is not None:
            raise NotImplementedError(
                "KV-cache decoding: use incubate.nn.functional."
                "masked_multihead_attention for the step-wise path")
        from paddle_tpu.nn import functional as F
        residual = x
        if self.normalize_before:
            x = F.layer_norm(x, [self.embed_dim], self.pre_ln_scale,
                             self.pre_ln_bias, self._epsilon)
        b, s = x.shape[0], x.shape[1]
        w = T.reshape(self.qkv_weight, [3 * self.embed_dim, self.embed_dim])
        qkv = T.matmul(x, T.transpose(w, [1, 0]))
        if self.qkv_bias is not None:
            qkv = qkv + T.reshape(self.qkv_bias, [3 * self.embed_dim])
        qkv = T.reshape(qkv, [b, s, 3, self.num_heads, self.head_dim])
        q, k, v = (qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2])
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask,
            dropout_p=self.attn_dropout_rate if self.training else 0.0,
            is_causal=False)
        out = T.reshape(out, [b, s, self.embed_dim])
        out = T.matmul(out, self.linear_weight)
        if self.linear_bias is not None:
            out = out + self.linear_bias
        out = residual + self.dropout(out)
        if not self.normalize_before:
            out = F.layer_norm(out, [self.embed_dim], self.ln_scale,
                               self.ln_bias, self._epsilon)
        return out


class FusedFeedForward(Layer):
    """(reference: fused_transformer.py FusedFeedForward)."""

    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1,
                 epsilon=1e-5, activation="relu", act_dropout_rate=None,
                 normalize_before=False, linear1_weight_attr=None,
                 linear1_bias_attr=None, linear2_weight_attr=None,
                 linear2_bias_attr=None, ln1_scale_attr=None,
                 ln1_bias_attr=None, ln2_scale_attr=None,
                 ln2_bias_attr=None, nranks=1, ring_id=-1, name=None):
        super().__init__()
        self.d_model = d_model
        self.normalize_before = normalize_before
        self.linear1 = nn.Linear(d_model, dim_feedforward,
                                 weight_attr=linear1_weight_attr,
                                 bias_attr=linear1_bias_attr)
        self.linear2 = nn.Linear(dim_feedforward, d_model,
                                 weight_attr=linear2_weight_attr,
                                 bias_attr=linear2_bias_attr)
        self.norm1 = nn.LayerNorm(d_model, epsilon=epsilon)
        self.norm2 = nn.LayerNorm(d_model, epsilon=epsilon)
        self.dropout = nn.Dropout(dropout_rate)
        self.act_dropout = nn.Dropout(
            dropout_rate if act_dropout_rate is None else act_dropout_rate)
        from paddle_tpu.nn import functional as F
        self.activation = getattr(F, activation)

    def forward(self, src, cache=None):
        if cache is not None:
            raise NotImplementedError("FusedFeedForward has no cache path")
        residual = src
        if self.normalize_before:
            src = self.norm1(src)
        src = self.linear2(self.act_dropout(self.activation(
            self.linear1(src))))
        src = residual + self.dropout(src)
        if not self.normalize_before:
            src = self.norm2(src)
        return src


class FusedTransformerEncoderLayer(Layer):
    """(reference: fused_transformer.py FusedTransformerEncoderLayer)."""

    def __init__(self, d_model, nhead, dim_feedforward, dropout_rate=0.1,
                 activation="relu", attn_dropout_rate=None,
                 act_dropout_rate=None, normalize_before=False):
        super().__init__()
        self.fused_attn = FusedMultiHeadAttention(
            d_model, nhead, dropout_rate=dropout_rate,
            attn_dropout_rate=(dropout_rate if attn_dropout_rate is None
                               else attn_dropout_rate),
            normalize_before=normalize_before)
        self.ffn = FusedFeedForward(
            d_model, dim_feedforward, dropout_rate=dropout_rate,
            activation=activation, act_dropout_rate=act_dropout_rate,
            normalize_before=normalize_before)

    def forward(self, src, src_mask=None, cache=None):
        return self.ffn(self.fused_attn(src, attn_mask=src_mask))


class FusedMultiTransformer(Layer):
    """N-layer fused decoder stack (reference: fused_transformer.py
    FusedMultiTransformer over fused_multi_transformer_op.cu — the
    reference's flagship inference fusion; here one XLA program)."""

    def __init__(self, embed_dim, num_heads, dim_feedforward,
                 dropout_rate=0.0, activation="gelu",
                 normalize_before=True, num_layers=1, nranks=1,
                 trans_qkvw=True, ring_id=-1, name=None, epsilon=1e-5,
                 **kw):
        super().__init__()
        # reference per-layer weight-list kwargs are a different weight
        # layout, not silently ignorable
        unsupported = [k for k in kw if kw[k] is not None]
        if unsupported:
            raise NotImplementedError(
                f"FusedMultiTransformer: unsupported kwargs {unsupported} "
                f"(per-layer weight lists — build the layers and load a "
                f"remapped state dict instead)")
        self.layers = nn.LayerList([
            FusedTransformerEncoderLayer(
                embed_dim, num_heads, dim_feedforward,
                dropout_rate=dropout_rate, activation=activation,
                normalize_before=normalize_before)
            for _ in range(num_layers)])

    def forward(self, src, attn_mask=None, caches=None, **kw):
        if caches is not None:
            raise NotImplementedError(
                "KV-cache decoding: use incubate.nn.functional."
                "masked_multihead_attention for the step-wise path")
        out = src
        for lay in self.layers:
            out = lay(out, src_mask=attn_mask)
        return out


class FusedLinear(Layer):
    """(reference: fused_linear.py FusedLinear over
    fused_gemm_epilogue_kernel.cu — matmul+bias is one XLA fusion).
    transpose_weight=True stores the weight as (out, in) like the
    reference and matmuls with transpose."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, transpose_weight=False, name=None):
        super().__init__()
        self._transpose_weight = transpose_weight
        shape = ([out_features, in_features] if transpose_weight
                 else [in_features, out_features])
        self.weight = self.create_parameter(
            shape, attr=weight_attr,
            default_initializer=nn.initializer.XavierNormal())
        self.bias = (None if bias_attr is False else
                     self.create_parameter([out_features], attr=bias_attr,
                                           is_bias=True))

    def forward(self, x):
        out = T.matmul(x, self.weight,
                       transpose_y=self._transpose_weight)
        if self.bias is not None:
            out = out + self.bias
        return out


class FusedBiasDropoutResidualLayerNorm(Layer):
    """(reference: fused_transformer.py FusedBiasDropoutResidualLayerNorm)."""

    def __init__(self, embed_dim, dropout_rate=0.5, weight_attr=None,
                 bias_attr=None, epsilon=1e-5, name=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.linear_bias = self.create_parameter([embed_dim],
                                                 attr=bias_attr,
                                                 is_bias=True)
        self.ln_scale = self.create_parameter(
            [embed_dim], attr=weight_attr,
            default_initializer=nn.initializer.Constant(1.0))
        self.ln_bias = self.create_parameter([embed_dim], is_bias=True)
        self.dropout = nn.Dropout(dropout_rate)
        self._epsilon = epsilon

    def forward(self, x, residual):
        from paddle_tpu.nn import functional as F
        biased = x if self.linear_bias is None else x + self.linear_bias
        out = residual + self.dropout(biased)
        return F.layer_norm(out, [self.embed_dim], self.ln_scale,
                            self.ln_bias, self._epsilon)


class FusedEcMoe(Layer):
    """Expert-choice MoE layer (reference: fused_ec_moe.py FusedEcMoe over
    the fused_moe kernel). Dense einsum formulation — on TPU the expert
    dim shards over the 'ep' mesh axis and GSPMD emits the all-to-alls."""

    def __init__(self, hidden_size, inter_size, num_experts, act_type="gelu",
                 weight_attr=None, bias_attr=None):
        super().__init__()
        self.gate = nn.Linear(hidden_size, num_experts)
        self.bmm_weight0 = self.create_parameter(
            [num_experts, hidden_size, inter_size], attr=weight_attr)
        self.bmm_bias0 = self.create_parameter([num_experts, 1, inter_size],
                                               attr=bias_attr, is_bias=True)
        self.bmm_weight1 = self.create_parameter(
            [num_experts, inter_size, hidden_size], attr=weight_attr)
        self.bmm_bias1 = self.create_parameter([num_experts, 1, hidden_size],
                                               attr=bias_attr, is_bias=True)
        from paddle_tpu.nn import functional as F
        self.act = getattr(F, act_type)

    def forward(self, x, gate_logits=None):
        from paddle_tpu.nn import functional as F
        # x: (B, S, H); dense expert-choice mix weighted by gate softmax
        gates = F.softmax(self.gate(x) if gate_logits is None
                          else gate_logits, axis=-1)   # (B, S, E)
        h = T.einsum("bsh,ehi->bsei", x, self.bmm_weight0)
        h = h + T.reshape(self.bmm_bias0,
                          [1, 1, gates.shape[-1], -1])
        h = self.act(h)
        h = T.einsum("bsei,eih->bseh", h, self.bmm_weight1)
        h = h + T.reshape(self.bmm_bias1, [1, 1, gates.shape[-1], -1])
        return T.einsum("bseh,bse->bsh", h, gates)


class FusedDropoutAdd(Layer):
    """(reference: fused_dropout_add.py FusedDropoutAdd)."""

    def __init__(self, p=0.5, mode="upscale_in_train", name=None):
        super().__init__()
        self.dropout = nn.Dropout(p, mode=mode)

    def forward(self, x, y):
        return self.dropout(x) + y
