"""`paddle.incubate.asp` — automatic sparsity (2:4 semi-structured)
(reference: python/paddle/incubate/asp/: asp.py decorate/prune_model,
supported_layer_list.py, utils.py check_mask_2d/get_mask_2d_greedy).

TPU note: sparse-MXU execution (like Ampere's 2:4 units) is not a TPU
feature; ASP here provides the PRUNING workflow — 2:4 masks computed by
magnitude, applied at step end so masked weights stay zero through
training (the reference's OptimizerWithSparsityGuarantee) — producing
checkpoints deployable on sparse-capable hardware.
"""
from __future__ import annotations

import weakref

import numpy as np
import jax.numpy as jnp

from paddle_tpu.core.tensor import Tensor

__all__ = ["decorate", "prune_model", "set_excluded_layers",
           "reset_excluded_layers", "calculate_density"]

_excluded: set = set()
_masks: dict = {}


def set_excluded_layers(param_names, main_program=None):
    for n in param_names:
        _excluded.add(n)


def reset_excluded_layers(main_program=None):
    _excluded.clear()


def calculate_density(x):
    arr = np.asarray(x._value if isinstance(x, Tensor) else x)
    return float((arr != 0).sum() / arr.size)


def _mask_2to4(arr):
    """Keep the 2 largest-|.| of every 4 consecutive elements along the
    last axis (reference: utils.py get_mask_1d / 2:4 pattern)."""
    shape = arr.shape
    n = shape[-1]
    pad = (-n) % 4
    a = np.abs(np.pad(arr.reshape(-1, n), ((0, 0), (0, pad))))
    g = a.reshape(a.shape[0], -1, 4)
    order = np.argsort(-g, axis=-1)
    mask = np.zeros_like(g, dtype=bool)
    np.put_along_axis(mask, order[..., :2], True, axis=-1)
    mask = mask.reshape(a.shape)[:, :n].reshape(shape)
    return mask


def _prunable(name, t):
    return (t._value.ndim == 2 and not t.stop_gradient
            and name not in _excluded
            and all(s % 4 == 0 or i == 0
                    for i, s in enumerate(t._value.shape)))


def prune_model(model, n=2, m=4, mask_algo="mask_1d", with_mask=True):
    """Apply 2:4 masks to every prunable weight (reference: asp.py
    prune_model). Returns {param_name: mask}."""
    if (n, m) != (2, 4):
        raise NotImplementedError("only 2:4 sparsity is supported")
    masks = {}
    for name, p in model.named_parameters():
        if not _prunable(name, p):
            continue
        arr = np.asarray(p._value)
        mask = _mask_2to4(arr)
        p._value = jnp.asarray(arr * mask)
        masks[name] = mask
        # keyed by id but validated against a weakref at use: a recycled
        # id must never attach a stale mask to an unrelated parameter
        _masks[id(p)] = (weakref.ref(p), jnp.asarray(mask, p._value.dtype))
    return masks


def decorate(optimizer):
    """Wrap optimizer.step to re-apply masks after each update so pruned
    weights stay zero (reference: asp.py decorate ->
    OptimizerWithSparsityGuarantee)."""
    orig_step = optimizer.step

    def step(*a, **k):
        out = orig_step(*a, **k)
        for p in optimizer._parameter_list:
            entry = _masks.get(id(p))
            if entry is not None and entry[0]() is p:
                p._value = p._value * entry[1]
        return out

    optimizer.step = step
    return optimizer
