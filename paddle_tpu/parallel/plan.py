"""Declarative sharding plans: param-name regex -> PartitionSpec.

This single table replaces three reference mechanisms at once:
- the Megatron split-layer classes (reference: python/paddle/distributed/
  fleet/layers/mpu/mp_layers.py:46 VocabParallelEmbedding, :335
  ColumnParallelLinear, :542 RowParallelLinear) — here plain Linears get
  their weights sharded by name;
- per-op SPMD rules (reference: paddle/phi/infermeta/spmd_rules/*.cc) —
  XLA's sharding propagation infers everything downstream of the
  annotations;
- ZeRO param sharding (reference: .../dygraph_sharding_optimizer.py:48) —
  the 'fsdp' axis in the same specs shards params/grads/optimizer state.

Axis conventions (SURVEY.md §7): 'dp' pure data parallel, 'fsdp' data
parallel with weight sharding (ZeRO-3), 'mp' tensor parallel, 'sp'
sequence/context parallel, 'pp' pipeline stages, 'ep' experts.
"""
from __future__ import annotations

import re
from typing import Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


class ShardingPlan:
    """Ordered (regex, PartitionSpec) rules; first match wins."""

    def __init__(self, rules: Sequence[tuple[str, P]], default: P = P()):
        self.rules = [(re.compile(pat), spec) for pat, spec in rules]
        self.default = default

    def spec_for(self, name: str, ndim: int | None = None) -> P:
        for pat, spec in self.rules:
            if pat.search(name):
                return spec
        return self.default

    def __repr__(self):
        return "ShardingPlan(\n" + "\n".join(
            f"  {pat.pattern!r}: {spec}" for pat, spec in self.rules) + "\n)"


def _axis(mesh_axes, *names):
    """Use the first of `names` present in the mesh (else None = replicate).
    Lets one plan serve pure-DP, TP-only, FSDP+TP, ... meshes."""
    for n in names:
        if n in mesh_axes:
            return n
    return None


def llama_sharding_plan(mesh_axes: Sequence[str]) -> ShardingPlan:
    """Megatron-style TP + ZeRO-3 FSDP plan for the Llama family.

    Column-parallel (q/k/v/gate/up, weight (d_in, d_out)): output dim on
    'mp'. Row-parallel (o_proj/down_proj): input dim on 'mp'. Embedding:
    vocab on 'mp' (VocabParallelEmbedding equivalent). The other weight dim
    shards over 'fsdp' (ZeRO-3); XLA all-gathers at use and reduce-scatters
    grads, which is exactly GroupShardedStage3's hook behaviour (reference:
    group_sharded_stage3.py:553) compiled instead of hand-run.
    """
    mp = _axis(mesh_axes, "mp")
    fsdp = _axis(mesh_axes, "fsdp")
    ep = _axis(mesh_axes, "ep")
    return ShardingPlan([
        (r"embed_tokens\.weight$", P(mp, fsdp)),
        (r"(q_proj|k_proj|v_proj|gate_proj|up_proj"
         r"|qkv_proj|gate_up_fused_proj)\.weight$", P(fsdp, mp)),
        (r"(o_proj|down_proj)\.weight$", P(mp, fsdp)),
        (r"lm_head\.weight$", P(fsdp, mp)),
        # MoE: stacked (E, d_in, d_out) expert weights, expert dim on 'ep'
        # (reference MoELayer expert-parallel groups, moe_layer.py:263)
        (r"experts_(gate|up)_weight$", P(ep, fsdp, mp)),
        (r"experts_down_weight$", P(ep, mp, fsdp)),
        (r"router_weight$", P()),
        (r"(norm|layernorm)\.weight$", P()),
    ], default=P())


def fsdp_partition(plan: ShardingPlan, name: str,
                   axis: str = "fsdp") -> int | None:
    """Which dim of param `name` the plan shards over `axis` — the
    shard_dim the decomposed-collective ring (parallel/overlap.py)
    needs: 0 = contracting dim sharded (column-parallel), 1 = output
    dim (row-parallel). None when the plan leaves the param off `axis`
    (replicated or non-matmul), which disables the ring for it."""
    spec = plan.spec_for(name)
    for dim, entry in enumerate(spec):
        entries = entry if isinstance(entry, tuple) else (entry,)
        if axis in entries:
            return dim
    return None


def batch_spec(mesh_axes: Sequence[str], seq_sharded: bool = True) -> P:
    """Input batch (B, S): batch over dp+fsdp, seq over sp."""
    batch_axes = tuple(a for a in ("dp", "fsdp") if a in mesh_axes)
    sp = "sp" if (seq_sharded and "sp" in mesh_axes) else None
    return P(batch_axes if batch_axes else None, sp)


def apply_plan(model, mesh: Mesh, plan: ShardingPlan):
    """device_put every parameter/buffer of `model` per the plan, in place.
    This is the GSPMD analog of wrapping the model in
    fleet.distributed_model (reference: fleet/model.py:141)."""
    from paddle_tpu.jit.functional import state_tensors
    for name, t in state_tensors(model).items():
        spec = plan.spec_for(name, t._value.ndim)
        t._value = jax.device_put(t._value, NamedSharding(mesh, spec))
    return model
