"""Decomposed FSDP collectives with comm/compute overlap (ISSUE 19).

The ZeRO-3 'fsdp' axis in `parallel/plan.py` shards every projection
weight and leaves the collectives to XLA sharding propagation: the
weight all-gather materializes fully BEFORE the matmul that consumes
it, and the grad reduce-scatter runs after the dW matmul — serial
bubbles in front of the FSDP-critical matmuls that BENCH r04->r05
measured as the MFU plateau. This module rewrites those matmuls as
chunked `ppermute` rings (overlap-via-decomposition, Wang et al.
ASPLOS'23; ZeRO's bucketed comm scheduling): each ring step multiplies
the currently-resident weight shard while `ppermute` ships the next
one, so the collective streams UNDER the compute instead of ahead of
it.

Three local rings (inside a full-manual shard_map — partial-auto
shard_map hits "PartitionId is not supported for SPMD partitioning" on
the 0.4.x line, so like context_parallel's ring attention every mesh
axis is named in the specs):

- contract ring  — w sharded on its CONTRACTING dim (column-parallel
  q/k/v/gate/up: plan spec P(fsdp, mp)): resident rows multiply the
  matching x columns, partial products accumulate in f32.
- assemble ring  — w sharded on its OUTPUT dim (row-parallel
  o_proj/down_proj: plan spec P(mp, fsdp)): resident columns fill
  their slice of the full output.
- reduce-scatter ring — the grad-side contraction dW = x^T @ g: the
  accumulator hops FIRST, then the local partial for the block the
  receiving rank will eventually own is added, so after n steps each
  rank holds exactly its fully-reduced dW shard.

`overlap_all_gather_matmul` / `overlap_matmul_reduce_scatter` are the
public ops (custom_vjp: the backward of each is composed from the
sibling rings, so grads overlap too). Shape contracts follow the house
kernel idiom (`*_shape_problems` / `check_*`: the auto path falls back
silently, a forced kernel="ring" raises naming every misaligned dim)
and kernel="jnp" is the exact-parity XLA-propagated reference the
rings are pinned against in tests.
"""
from __future__ import annotations

import functools
from contextlib import contextmanager

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from paddle_tpu.core.dispatch import defop
from paddle_tpu.core.jax_compat import axis_size, shard_map

__all__ = [
    "overlap_all_gather_matmul", "overlap_matmul_reduce_scatter",
    "overlap_shape_problems", "check_overlap_shapes",
    "overlap_rs_shape_problems", "check_overlap_rs_shapes",
    "overlap_fsdp_guard", "current_overlap", "resolve_overlap_mesh",
    "overlap_fraction_from_spans",
]


# ---------------------------------------------------------------------------
# shape contracts
# ---------------------------------------------------------------------------

def _mesh_axis_problems(x_shape, mesh, axis, chunks):
    """Checks shared by both ops: the mesh/axis exist, the ring is
    enabled, and x's batch(+seq) dims split over their mesh axes."""
    problems = []
    if mesh is None:
        problems.append("no device mesh is active (pass mesh=, enter a "
                        "mesh context, or set_mesh)")
        return problems
    names = mesh.axis_names
    if axis not in names:
        problems.append(f"mesh has no '{axis}' axis "
                        f"(axes: {tuple(names)})")
        return problems
    if chunks < 1:
        problems.append(f"chunks must be >= 1 to run the ring (got "
                        f"{chunks}; 0 disables overlap upstream)")
    if len(x_shape) < 2:
        problems.append(f"x must be rank-2+ (got shape {tuple(x_shape)})")
        return problems
    bsz = 1
    for a in ("dp", axis):
        if a in names:
            bsz *= mesh.shape[a]
    if x_shape[0] % bsz:
        problems.append(f"x dim 0 ({x_shape[0]}) % dp x {axis} extent "
                        f"{bsz} != 0")
    if len(x_shape) >= 3 and "sp" in names \
            and x_shape[1] % mesh.shape["sp"]:
        problems.append(f"x dim 1 ({x_shape[1]}) % 'sp' size "
                        f"{mesh.shape['sp']} != 0")
    return problems


def overlap_shape_problems(x_shape, w_shape, mesh, axis="fsdp",
                           chunks=1, shard_dim=0):
    """Reasons `overlap_all_gather_matmul` cannot take the decomposed
    ring for these global shapes; empty = supported."""
    problems = _mesh_axis_problems(x_shape, mesh, axis, chunks)
    if problems and (mesh is None or axis not in mesh.axis_names):
        return problems
    if len(w_shape) != 2:
        problems.append(f"w must be rank-2 (got shape {tuple(w_shape)})")
        return problems
    if shard_dim not in (0, 1):
        problems.append(f"shard_dim must be 0 (contracting) or 1 "
                        f"(output); got {shard_dim}")
        return problems
    if len(x_shape) >= 2 and x_shape[-1] != w_shape[0]:
        problems.append(f"contracting dims differ: x[-1]={x_shape[-1]} "
                        f"vs w[0]={w_shape[0]}")
    n = mesh.shape[axis]
    if w_shape[shard_dim] % n:
        problems.append(f"w dim {shard_dim} ({w_shape[shard_dim]}) % "
                        f"'{axis}' size {n} != 0")
    mp_sz = mesh.shape["mp"] if "mp" in mesh.axis_names else 1
    if mp_sz > 1 and w_shape[1 - shard_dim] % mp_sz:
        problems.append(f"w dim {1 - shard_dim} "
                        f"({w_shape[1 - shard_dim]}) % 'mp' size "
                        f"{mp_sz} != 0")
    return problems


def check_overlap_shapes(x_shape, w_shape, mesh, axis="fsdp", chunks=1,
                         shard_dim=0):
    problems = overlap_shape_problems(x_shape, w_shape, mesh, axis,
                                      chunks, shard_dim)
    if problems:
        raise ValueError(
            "overlap_all_gather_matmul: shapes cannot take the "
            "decomposed-collective ring — " + "; ".join(problems)
            + '; use kernel="jnp" for the XLA-propagated fallback')


def overlap_rs_shape_problems(x_shape, g_shape, mesh, axis="fsdp",
                              chunks=1, shard_dim=0):
    """Reasons `overlap_matmul_reduce_scatter` cannot take the ring:
    x (..., K) and g (..., N) contract over their shared leading dims
    into a (K, N) result whose `shard_dim` scatters over `axis`."""
    problems = _mesh_axis_problems(x_shape, mesh, axis, chunks)
    if problems and (mesh is None or axis not in mesh.axis_names):
        return problems
    if len(x_shape) != len(g_shape) \
            or tuple(x_shape[:-1]) != tuple(g_shape[:-1]):
        problems.append(f"x and g must share leading (batch) dims: "
                        f"x {tuple(x_shape)} vs g {tuple(g_shape)}")
        return problems
    if shard_dim not in (0, 1):
        problems.append(f"shard_dim must be 0 (rows = x's features) or "
                        f"1 (cols = g's features); got {shard_dim}")
        return problems
    n = mesh.shape[axis]
    out_shape = (x_shape[-1], g_shape[-1])
    if out_shape[shard_dim] % n:
        problems.append(f"result dim {shard_dim} "
                        f"({out_shape[shard_dim]}) % '{axis}' size "
                        f"{n} != 0")
    mp_sz = mesh.shape["mp"] if "mp" in mesh.axis_names else 1
    if mp_sz > 1 and out_shape[1 - shard_dim] % mp_sz:
        problems.append(f"result dim {1 - shard_dim} "
                        f"({out_shape[1 - shard_dim]}) % 'mp' size "
                        f"{mp_sz} != 0")
    return problems


def check_overlap_rs_shapes(x_shape, g_shape, mesh, axis="fsdp",
                            chunks=1, shard_dim=0):
    problems = overlap_rs_shape_problems(x_shape, g_shape, mesh, axis,
                                         chunks, shard_dim)
    if problems:
        raise ValueError(
            "overlap_matmul_reduce_scatter: shapes cannot take the "
            "decomposed-collective ring — " + "; ".join(problems)
            + '; use kernel="jnp" for the XLA-propagated fallback')


# ---------------------------------------------------------------------------
# local rings (operate on LOCAL shards inside a full-manual shard_map)
# ---------------------------------------------------------------------------

def _sub_chunks(size, chunks):
    """Static (offset, length) sub-pieces of one resident shard; the
    last piece absorbs the remainder (uneven chunk counts are legal)."""
    c = max(1, min(int(chunks), int(size)))
    step = -(-size // c)
    return [(off, min(step, size - off)) for off in range(0, size, step)]


def _ring_contract_local(xl, wl, axis, chunks):
    """w sharded on its CONTRACTING dim over `axis` (rank idx holds
    rows [idx*kc, (idx+1)*kc) of the (K, n_out) weight): each scan step
    multiplies the resident row block against the matching x columns
    while ppermute ships the next block. f32 accumulation across ring
    steps (better than chaining low-precision adds; exact for f32)."""
    n = axis_size(axis)
    idx = jax.lax.axis_index(axis)
    perm = [(i, (i + 1) % n) for i in range(n)]
    kc = wl.shape[0]
    pieces = _sub_chunks(kc, chunks)
    acc0 = jnp.zeros(xl.shape[:-1] + (wl.shape[1],), jnp.float32)

    def step(carry, j):
        acc, w_cur = carry
        src = (idx - j) % n              # owner of the resident block
        for off, ln in pieces:
            xs = jax.lax.dynamic_slice_in_dim(
                xl, src * kc + off, ln, xl.ndim - 1)
            acc = acc + jnp.matmul(
                xs, jax.lax.slice_in_dim(w_cur, off, off + ln, axis=0)
            ).astype(jnp.float32)
        w_nxt = jax.lax.ppermute(w_cur, axis, perm)
        return (acc, w_nxt), None

    (acc, _), _ = jax.lax.scan(step, (acc0, wl), jnp.arange(n))
    return acc.astype(jnp.result_type(xl, wl))


def _ring_assemble_local(xl, wl, axis, chunks):
    """w sharded on its OUTPUT dim over `axis` (rank idx holds columns
    [idx*nc, (idx+1)*nc)): each step's matmuls fill the output slice
    the resident block owns while ppermute ships the next block."""
    n = axis_size(axis)
    idx = jax.lax.axis_index(axis)
    perm = [(i, (i + 1) % n) for i in range(n)]
    nc = wl.shape[1]
    pieces = _sub_chunks(nc, chunks)
    out0 = jnp.zeros(xl.shape[:-1] + (n * nc,),
                     jnp.result_type(xl, wl))

    def step(carry, j):
        out, w_cur = carry
        src = (idx - j) % n
        if len(pieces) > 1:
            blk = jnp.concatenate(
                [jnp.matmul(xl, jax.lax.slice_in_dim(
                    w_cur, off, off + ln, axis=1))
                 for off, ln in pieces], axis=-1)
        else:
            blk = jnp.matmul(xl, w_cur)
        out = jax.lax.dynamic_update_slice_in_dim(
            out, blk.astype(out.dtype), src * nc, out.ndim - 1)
        w_nxt = jax.lax.ppermute(w_cur, axis, perm)
        return (out, w_nxt), None

    (out, _), _ = jax.lax.scan(step, (out0, wl), jnp.arange(n))
    return out


def _ring_reduce_scatter_local(xl, gl, axis, chunks, shard_dim):
    """Reduce-scatter ring for the grad contraction dW = x^T @ g: the
    (K, N) result's `shard_dim` scatters over `axis`. The accumulator
    hops FIRST (zeros on step 0 — wasted once, but the scan body stays
    uniform), then the local partial for block (idx + n - 1 - j) % n is
    added: block c visits ranks c+1, c+2, ..., ending fully reduced at
    its owner c after n steps."""
    n = axis_size(axis)
    idx = jax.lax.axis_index(axis)
    perm = [(i, (i + 1) % n) for i in range(n)]
    lead = tuple(range(xl.ndim - 1))
    bc = (xl.shape[-1] if shard_dim == 0 else gl.shape[-1]) // n
    pieces = _sub_chunks(bc, chunks)
    blk_shape = ((bc, gl.shape[-1]) if shard_dim == 0
                 else (xl.shape[-1], bc))

    def block(c):
        outs = []
        for off, ln in pieces:
            if shard_dim == 0:
                xs = jax.lax.dynamic_slice_in_dim(
                    xl, c * bc + off, ln, xl.ndim - 1)
                outs.append(jnp.tensordot(xs, gl, axes=(lead, lead)))
            else:
                gs = jax.lax.dynamic_slice_in_dim(
                    gl, c * bc + off, ln, gl.ndim - 1)
                outs.append(jnp.tensordot(xl, gs, axes=(lead, lead)))
        if len(outs) == 1:
            return outs[0]
        return jnp.concatenate(outs, axis=shard_dim)

    acc0 = jnp.zeros(blk_shape, jnp.float32)

    def step(acc, j):
        acc = jax.lax.ppermute(acc, axis, perm)
        c = (idx + n - 1 - j) % n
        return acc + block(c).astype(jnp.float32), None

    acc, _ = jax.lax.scan(step, acc0, jnp.arange(n))
    return acc.astype(jnp.result_type(xl, gl))


# ---------------------------------------------------------------------------
# global wrappers (full-manual shard_map) + custom_vjp pairing
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=None)
def _build_ops(mesh, axis, chunks, shard_dim):
    """The (all-gather-matmul, matmul-reduce-scatter) op pair for one
    (mesh, axis, chunks, shard_dim) — cached so repeated layer calls
    reuse one custom_vjp identity (one trace cache entry)."""
    names = mesh.axis_names
    mp = "mp" if "mp" in names else None
    batch = tuple(a for a in ("dp", axis) if a in names) or None
    red = tuple(a for a in ("dp", "sp") if a in names)

    def act(ndim, feat):
        """Activation spec (batch..., feature): batch over dp+axis (the
        batch_spec convention), seq over sp on rank-3+, feature
        optionally over mp."""
        sp = "sp" if ("sp" in names and ndim >= 3) else None
        mid = [sp] + [None] * (ndim - 3) if ndim >= 3 else []
        return P(batch, *mid, feat)

    def ag(x, w, sd):
        if sd == 0:        # contracting dim over `axis` (column-parallel)
            specs = (act(x.ndim, None), P(axis, mp), act(x.ndim, mp))
            local = functools.partial(_ring_contract_local,
                                      axis=axis, chunks=chunks)
        else:              # output dim over `axis` (row-parallel)
            specs = (act(x.ndim, mp), P(mp, axis), act(x.ndim, None))

            def local(xl, wl):
                out = _ring_assemble_local(xl, wl, axis, chunks)
                return jax.lax.psum(out, mp) if mp else out
        fn = shard_map(local, mesh=mesh, in_specs=specs[:2],
                       out_specs=specs[2], check_vma=False)
        return fn(x, w)

    def rs(x, g, sd):
        if sd == 0:
            specs = (act(x.ndim, None), act(g.ndim, mp), P(axis, mp))
        else:
            specs = (act(x.ndim, mp), act(g.ndim, None), P(mp, axis))

        def local(xl, gl):
            blk = _ring_reduce_scatter_local(xl, gl, axis, chunks, sd)
            # the ring reduces over `axis`; the other batch(+seq) axes
            # still hold partial sums of their rows
            return jax.lax.psum(blk, red) if red else blk
        fn = shard_map(local, mesh=mesh, in_specs=specs[:2],
                       out_specs=specs[2], check_vma=False)
        return fn(x, g)

    @jax.custom_vjp
    def ag_op(x, w):
        return ag(x, w, shard_dim)

    def ag_fwd(x, w):
        return ag(x, w, shard_dim), (x, w)

    def ag_bwd(res, g):
        x, w = res
        # dx = g @ w^T: w^T's fsdp-sharded dim flips role, so dx is the
        # SIBLING ring (contract <-> assemble); dw is the RS ring
        dx = ag(g, jnp.swapaxes(w, 0, 1), 1 - shard_dim)
        dw = rs(x, g, shard_dim)
        return dx.astype(x.dtype), dw.astype(w.dtype)

    ag_op.defvjp(ag_fwd, ag_bwd)

    @jax.custom_vjp
    def rs_op(x, g):
        return rs(x, g, shard_dim)

    def rs_fwd(x, g):
        return rs(x, g, shard_dim), (x, g)

    def rs_bwd(res, dwb):
        x, g = res
        dx = ag(g, jnp.swapaxes(dwb, 0, 1), 1 - shard_dim)
        dg = ag(x, dwb, shard_dim)
        return dx.astype(x.dtype), dg.astype(g.dtype)

    rs_op.defvjp(rs_fwd, rs_bwd)
    return ag_op, rs_op


# ---------------------------------------------------------------------------
# public ops
# ---------------------------------------------------------------------------

def resolve_overlap_mesh(mesh=None):
    """The mesh the ring runs over: explicit arg > active guard > jax
    mesh-context stack > paddle_tpu global ProcessMesh (the same probe
    order the sharding-aware embedding vjp uses)."""
    if mesh is None and _overlap_state["on"]:
        mesh = _overlap_state["mesh"]
    if mesh is None:
        from paddle_tpu.nn.functional.common import _ambient_mesh
        return _ambient_mesh()
    from paddle_tpu.distributed.mesh import ProcessMesh
    if isinstance(mesh, ProcessMesh):
        mesh = mesh.jax_mesh
    return mesh


def overlap_all_gather_matmul(x, w, axis="fsdp", chunks=1, mesh=None,
                              kernel=None, shard_dim=0):
    """x @ w with w's `shard_dim` sharded over mesh axis `axis`
    (ZeRO-3), as a chunked ppermute ring that overlaps the weight
    all-gather with the dependent matmul. shard_dim=0 = contracting dim
    sharded (column-parallel plan spec P(fsdp, mp)); shard_dim=1 =
    output dim sharded (row-parallel P(mp, fsdp)). kernel: None = auto
    (ring when the shape contract holds, else the XLA-propagated
    matmul), "ring" = forced (raises via check_overlap_shapes),
    "jnp" = the exact-parity propagated reference."""
    if kernel not in (None, "ring", "jnp"):
        raise ValueError(f"kernel must be None, 'ring' or 'jnp' "
                         f"(got {kernel!r})")
    if kernel == "jnp":
        return jnp.matmul(x, w)
    mesh = resolve_overlap_mesh(mesh)
    problems = overlap_shape_problems(x.shape, w.shape, mesh, axis,
                                      chunks, shard_dim)
    if problems:
        if kernel == "ring":
            check_overlap_shapes(x.shape, w.shape, mesh, axis, chunks,
                                 shard_dim)
        return jnp.matmul(x, w)
    ag_op, _ = _build_ops(mesh, axis, int(chunks), int(shard_dim))
    return ag_op(x, w)


def overlap_matmul_reduce_scatter(x, g, axis="fsdp", chunks=1, mesh=None,
                                  kernel=None, shard_dim=0):
    """The grad-side contraction dW = x^T @ g (x (..., K), g (..., N)
    -> (K, N)) with the result's `shard_dim` reduce-scattered over
    `axis`, as a ppermute ring whose accumulator hop overlaps the next
    block's partial matmul. Same kernel dispatch contract as
    `overlap_all_gather_matmul`."""
    if kernel not in (None, "ring", "jnp"):
        raise ValueError(f"kernel must be None, 'ring' or 'jnp' "
                         f"(got {kernel!r})")
    lead = tuple(range(x.ndim - 1))
    if kernel == "jnp":
        return jnp.tensordot(x, g, axes=(lead, lead))
    mesh = resolve_overlap_mesh(mesh)
    problems = overlap_rs_shape_problems(x.shape, g.shape, mesh, axis,
                                         chunks, shard_dim)
    if problems:
        if kernel == "ring":
            check_overlap_rs_shapes(x.shape, g.shape, mesh, axis,
                                    chunks, shard_dim)
        return jnp.tensordot(x, g, axes=(lead, lead))
    _, rs_op = _build_ops(mesh, axis, int(chunks), int(shard_dim))
    return rs_op(x, g)


# Tensor-level entry for the model's projection rewrite (llama.py
# _maybe_overlap_linear): plain jax math wrapped as a tape op, same
# white amp policy as `linear`. The mesh resolves through
# resolve_overlap_mesh at trace time (guard > ambient), so no mesh
# object rides the op's static kwargs.
@defop("overlap_ag_matmul", amp_policy="white",
       spmd_note="decomposed FSDP all-gather matmul: the weight's "
                 "fsdp-sharded dim streams around a ppermute ring "
                 "while resident chunks multiply (parallel/overlap.py)")
def _overlap_linear_op(x, weight, axis="fsdp", chunks=1, shard_dim=0):
    return overlap_all_gather_matmul(x, weight, axis=axis,
                                     chunks=chunks, shard_dim=shard_dim)


def overlap_linear(x, weight, axis="fsdp", chunks=1, shard_dim=0):
    """Tensor-level `F.linear` twin routed through the decomposed
    ring (bias-free: the plan's FSDP projections carry none)."""
    return _overlap_linear_op(x, weight, axis=axis, chunks=chunks,
                              shard_dim=shard_dim)


# ---------------------------------------------------------------------------
# model integration: a context that reroutes FSDP projections
# ---------------------------------------------------------------------------

_overlap_state = {"on": False, "mesh": None, "axis": "fsdp", "chunks": 1}


@contextmanager
def overlap_fsdp_guard(mesh, axis="fsdp", chunks=1):
    """Inside this context the model's FSDP-critical projections
    (llama.py `_maybe_overlap_linear`) route through the decomposed
    rings over `axis` — the trainer enters it around its loss closure
    (TrainStepConfig.overlap_fsdp), mirroring context_parallel_guard."""
    from paddle_tpu.distributed.mesh import ProcessMesh
    if isinstance(mesh, ProcessMesh):
        mesh = mesh.jax_mesh
    prev = dict(_overlap_state)
    _overlap_state.update(on=True, mesh=mesh, axis=axis,
                          chunks=max(1, int(chunks)))
    try:
        yield
    finally:
        _overlap_state.update(prev)


def current_overlap():
    return dict(_overlap_state) if _overlap_state["on"] else None


# ---------------------------------------------------------------------------
# overlap fraction from the chrome-trace span plane
# ---------------------------------------------------------------------------

def overlap_fraction_from_spans(span_list=None):
    """Overlap fraction from the `train.overlap.phase` spans
    `Trainer.measure_phase_seconds` records: comm time hidden under
    compute / total comm time, summed over the fwd/bwd phases, where

        total  = t(propagated) - t(nocomm)    per phase
        hidden = t(propagated) - t(overlapped)

    (`propagated` = XLA-propagated collectives, `overlapped` = the
    rings, `nocomm` = fsdp-replicated params, i.e. no weight-movement
    collectives at all). Reads the live span ring when `span_list` is
    None; newest measurement of each (variant, phase) wins. Returns a
    float in [0, 1], or None when the plane lacks a complete
    measurement (e.g. overlap disabled)."""
    if span_list is None:
        from paddle_tpu.observability import trace
        span_list = trace.spans()
    t = {}
    for s in span_list:
        if s.name != "train.overlap.phase":
            continue
        t[(s.attrs.get("variant"), s.attrs.get("phase"))] = s.dur_us / 1e6
    total = hidden = 0.0
    for ph in ("fwd", "bwd"):
        prop = t.get(("propagated", ph))
        ovl = t.get(("overlapped", ph))
        noc = t.get(("nocomm", ph))
        if prop is None or ovl is None or noc is None:
            return None
        total += max(0.0, prop - noc)
        hidden += max(0.0, prop - ovl)
    if total <= 0.0:
        return None
    return max(0.0, min(1.0, hidden / total))
