"""paddle_tpu.parallel: TPU-native hybrid-parallel training.

Replaces the reference's fleet hybrid-parallel machinery (reference:
python/paddle/distributed/fleet/ — HybridCommunicateGroup topology over
NCCL process groups, ColumnParallelLinear/RowParallelLinear weight-split
layer classes, DygraphShardingOptimizer ZeRO stages, PipelineParallel 1F1B
actors) with the GSPMD recipe: ONE `jax.sharding.Mesh` with named axes
('dp','fsdp','mp','pp','sp','ep'), a declarative param-name -> PartitionSpec
sharding plan, and a single jitted train step whose collectives XLA derives
and schedules over ICI.
"""
from paddle_tpu.parallel.plan import (  # noqa: F401
    ShardingPlan, llama_sharding_plan, apply_plan, fsdp_partition,
)
from paddle_tpu.parallel.overlap import (  # noqa: F401
    overlap_all_gather_matmul, overlap_matmul_reduce_scatter,
    overlap_fsdp_guard, current_overlap, overlap_fraction_from_spans,
)
from paddle_tpu.parallel.trainer import Trainer, TrainStepConfig  # noqa: F401
