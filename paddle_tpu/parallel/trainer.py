"""The fused hybrid-parallel train step.

Replaces the reference's entire per-step runtime — eager op dispatch +
GradNode backward walk + DP reducer hooks + sharding-optimizer
reduce-scatter + TP identity/allreduce ops + LR-scheduler python — with ONE
jitted program (reference call stack: SURVEY.md §3.4). XLA sees forward,
backward, grad clip and the optimizer update together, so it fuses the
update into the backward epilogue and schedules every collective (grad
reduce-scatter over 'dp'/'fsdp', activation collectives over 'mp'/'sp')
against compute over ICI — what the reference approximates with comm
streams and hooks.

Memory notes: params+opt state are donated (buffers reused in place);
compute runs in bf16 with fp32 params (AMP-O2 master-weights contract,
reference: hybrid_parallel_optimizer.py + GradScaler) — on TPU there is no
loss scaling because bf16 has fp32's exponent range.
"""
from __future__ import annotations

import contextlib
from dataclasses import dataclass
from typing import Any, Callable

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu import observability
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.jit.functional import functional_call, state_tensors
from paddle_tpu.parallel.plan import ShardingPlan, batch_spec


@dataclass
class TrainStepConfig:
    compute_dtype: Any = "bfloat16"   # forward/backward dtype; None = as-is
    grad_accum_steps: int = 1         # microbatch loop via lax.scan
    donate: bool = True
    shard_batch_seq: bool = True      # shard (B, S) seq dim over 'sp'
    context_parallel: str | None = None  # 'ring' | 'ulysses' over 'sp'
    # params whose grad gets an optimization_barrier before the optimizer
    # update. XLA fuses the Adam update (3 f32 reads + 3 f32 writes of
    # the weight) into the dW matmul epilogue; for vocab-sized weights
    # that interleaving measured the lm_head dW at 46% MXU eff on v5e —
    # the barrier splits matmul and update (+3% step throughput). A
    # global barrier is WORSE (materializes every grad); name-match only
    # the big vocab params. Env PADDLE_TPU_OPT_BARRIER overrides
    # (comma-separated substrings, '1' = all, '' = unset -> this field).
    opt_barrier_params: tuple = ("lm_head", "embed_tokens")
    # keep Adam moments in PINNED HOST memory between steps (reference:
    # sharding/group_sharded_optimizer_stage2.py offload=True + the
    # pinned allocator, allocator_facade host-pinned pool): frees
    # 8 bytes/param of HBM for activations/batch at the cost of a
    # host<->HBM round trip per step. TPU-native via jax memory kinds.
    offload_opt_state: bool = False
    # non-finite-gradient skip (reference: the check_nan_inf + GradScaler
    # found-inf skip the reference applies under fp16): when any grad
    # (or the loss) is Inf/NaN the whole update is suppressed in-jit —
    # params and optimizer state pass through unchanged — and the step
    # reports skipped=True. Opt-in: enabling adds an isfinite reduction
    # + per-param selects to the compiled step, so the default keeps the
    # hot path byte-identical.
    skip_nonfinite_grads: bool = False
    # consecutive skipped steps before the trainer ABORTS (a diverged
    # run burning pod-hours silently is worse than a crash; bounded like
    # the reference's FLAGS_check_nan_inf hard stop)
    max_consecutive_nonfinite: int = 25
    # how many steps of skip flags to buffer before the host reads them
    # (each read syncs on that step; 1 = check every step, larger keeps
    # more dispatch pipelining and still aborts within the window)
    nonfinite_check_every: int = 1
    # training-sentry health probe (distributed/sentry.py): the compiled
    # step additionally returns probe = [global_grad_norm, applied] and
    # takes a loss-cap scalar input; an update whose loss/grads are
    # non-finite OR whose loss exceeds the cap is suppressed in-jit
    # (same select-don't-branch machinery as skip_nonfinite_grads, which
    # this subsumes — the two knobs are mutually exclusive). The probe
    # rides the step's existing outputs: no extra host sync is added
    # here; reading it is the sentry's decision.
    health_probe: bool = False
    # decomposed FSDP collectives (ISSUE 19; parallel/overlap.py): the
    # loss closure runs under overlap_fsdp_guard so the model's
    # FSDP-critical projections stream their weight all-gather around a
    # chunked ppermute ring UNDER the matmul instead of ahead of it.
    # overlap_chunks = sub-chunks per resident shard (finer
    # pipelining). No-op when the mesh lacks an 'fsdp' axis; off by
    # default so the hot path stays byte-identical.
    overlap_fsdp: bool = False
    overlap_chunks: int = 1


class NonFiniteGradError(RuntimeError):
    """max_consecutive_nonfinite steps in a row produced Inf/NaN grads —
    the run has diverged; aborting beats silently skipping forever."""


def _cast_tree(tree, dtype):
    if dtype is None:
        return tree
    dt = jnp.dtype(dtype)
    return jax.tree.map(
        lambda a: a.astype(dt)
        if jnp.issubdtype(a.dtype, jnp.floating) else a, tree)


def _strip_axis(spec: P, axis: str) -> P:
    """`spec` with `axis` removed from every entry (tuple entries
    keep their other axes) — the nocomm phase-timing twin replicates
    params over 'fsdp' with this."""
    out = []
    for entry in spec:
        if isinstance(entry, tuple):
            kept = tuple(a for a in entry if a != axis)
            out.append(kept if kept else None)
        else:
            out.append(None if entry == axis else entry)
    return P(*out)


def _memories_supported() -> bool:
    """pinned_host placement works on TPU (verified live); the CPU
    emulation backend has the memory SPACES but no lowering for the
    placement custom-call."""
    try:
        dev = jax.devices()[0]
        kinds = {m.kind for m in dev.addressable_memories()}
        return dev.platform == "tpu" and "pinned_host" in kinds
    except Exception:
        return False


def _opt_barrier(grads: dict, cfg) -> dict:
    """optimization_barrier on grads of cfg.opt_barrier_params-matching
    names (see TrainStepConfig.opt_barrier_params for the why)."""
    import os as _os
    env = _os.environ.get("PADDLE_TPU_OPT_BARRIER")
    pats = (env.split(",") if env
            else list(getattr(cfg, "opt_barrier_params", ()) or ()))
    if not pats:
        return grads
    return {n: (jax.lax.optimization_barrier(g)
                if "1" in pats or any(p in n for p in pats)
                else g)
            for n, g in grads.items()}


class Trainer:
    """Functional training state + compiled step for (model, optimizer) on
    a mesh. The eager Layer/Optimizer objects remain the API surface
    (state_dict, checkpointing); this class owns the performance path."""

    def __init__(self, model, optimizer, mesh: Mesh | None = None,
                 plan: ShardingPlan | None = None,
                 config: TrainStepConfig | None = None,
                 loss_fn: Callable | None = None,
                 checkpointer=None):
        from paddle_tpu.distributed.mesh import ProcessMesh
        if isinstance(mesh, ProcessMesh):
            mesh = mesh.jax_mesh
        self.model = model
        self.optimizer = optimizer
        self.mesh = mesh
        self.plan = plan
        # optional distributed.async_checkpoint.AsyncCheckpointer:
        # save_checkpoint() then returns after only the device->host
        # snapshot and the write overlaps subsequent steps
        self.checkpointer = checkpointer
        import dataclasses
        # private copy: the trainer mutates offload_opt_state (model
        # hint / backend fallback) and must not write into a config
        # object the caller may share across trainers
        self.config = dataclasses.replace(config) if config is not None \
            else TrainStepConfig()
        if getattr(model, "_sharding_offload", False):
            # group_sharded_parallel(offload=True) hint
            self.config.offload_opt_state = True
        if self.config.health_probe and self.config.skip_nonfinite_grads:
            raise ValueError(
                "TrainStepConfig.health_probe subsumes "
                "skip_nonfinite_grads (the probe's in-jit suppression "
                "covers non-finite updates); enable only one")
        self._loss_fn = loss_fn
        self._step_fn = None
        self._chaos_poison = False
        # extra compiled-step inputs, in positional order (subset of
        # ("poison", "spike", "loss_cap")), decided at trace time
        self._extra_names: tuple = ()
        self._poison_sites: tuple = ()
        # sentry loss cap: an update with loss above this is suppressed
        # in-jit when health_probe is on (+inf = never; the sentry
        # quantizes its cap so the staged scalar rarely re-transfers)
        self._loss_cap = float("inf")
        self._cap_cache = None
        # transient LR scale (sentry post-rollback dampening ramp)
        self._lr_scale = 1.0
        # the lazy probe array of the most recent step (health_probe):
        # [global_grad_norm, applied]; reading it is the caller's sync
        self.last_probe = None
        # per-(key, ndim) NamedSharding cache for batch leaves: shared
        # by step() and data_iter()'s prefetcher, so a prefetched batch
        # compares equal (same objects) and skips device_put entirely
        self._batch_shardings: dict = {}
        # non-finite skip bookkeeping (host side)
        self._pending_skips: list = []
        self.nonfinite_streak = 0
        self.nonfinite_skipped = 0
        # step telemetry (observability.telemetry.TrainingTelemetry),
        # built lazily on the first step with observability enabled
        self._telemetry = None
        self._tel_last_t = None
        self._tel_prev = None
        self._init_state()

    # -- state -------------------------------------------------------------
    def _init_state(self):
        tensors = state_tensors(self.model)
        self.param_names = [n for n, t in tensors.items()
                            if not t.stop_gradient]
        self.params = {n: t._value for n, t in tensors.items()}
        self.opt_state = self.optimizer.init_state_arrays(
            {n: self.params[n] for n in self.param_names})
        if self.mesh is not None and self.plan is not None:
            self._shard_state()
        if self.config.offload_opt_state:
            if _memories_supported():
                self._offload_opt_state()
            else:
                import warnings
                warnings.warn(
                    "offload_opt_state: this backend has no pinned_host "
                    "memory space (CPU emulation lacks the placement "
                    "op); keeping optimizer state in device memory")
                self.config.offload_opt_state = False

    def _spec(self, name):
        return self.plan.spec_for(name)

    def _opt_leaf_sharding(self, name, v, kind=None):
        """Sharding for one optimizer-state leaf: moments shard like
        their parameter, scalars replicate; `kind` selects the memory
        space ('pinned_host' while parked between steps under
        offload_opt_state, 'device' inside the step)."""
        if self.mesh is not None:
            spec = (self._spec(name)
                    if getattr(v, "ndim", 0) == len(self.params[name].shape)
                    else P())
            return NamedSharding(self.mesh, spec, memory_kind=kind)
        from jax.sharding import SingleDeviceSharding
        return SingleDeviceSharding(jax.devices()[0], memory_kind=kind)

    def _offload_opt_state(self):
        """Park moments in pinned host memory (reference:
        group_sharded_optimizer_stage2.py offload=True; the pinned pool
        of allocator_facade) — HBM holds them only during the update."""
        self.opt_state = {
            n: {k: jax.device_put(
                v, self._opt_leaf_sharding(n, v, "pinned_host"))
                for k, v in st.items()}
            for n, st in self.opt_state.items()}

    @staticmethod
    def _put_global(v, sh):
        """device_put that tolerates COMMITTED local arrays when the
        target sharding spans non-addressable devices (multi-process
        resume: checkpoint loads commit values to local devices; jax
        only re-spreads uncommitted/host values across processes)."""
        try:
            return jax.device_put(v, sh)
        except ValueError:
            import numpy as np
            return jax.device_put(np.asarray(v), sh)

    def _shard_state(self):
        for n in list(self.params):
            sh = NamedSharding(self.mesh, self._spec(n))
            self.params[n] = self._put_global(self.params[n], sh)
        # optimizer moments shard exactly like their parameter; scalars
        # (beta_pow) replicate. This is ZeRO sharding of optimizer state
        # (reference: dygraph_sharding_optimizer.py:48) for free.
        for n, st in self.opt_state.items():
            for k, v in st.items():
                st[k] = self._put_global(v,
                                         self._opt_leaf_sharding(n, v))

    # -- the compiled step -------------------------------------------------
    def _loss_from_batch(self, params_c, batch):
        """batch: dict of arrays -> scalar loss (f32)."""
        targs = {k: Tensor(v, stop_gradient=True) for k, v in batch.items()}
        if self._loss_fn is not None:
            out = self._loss_fn(self.model, params_c, targs)
        else:
            out = functional_call(self.model, params_c, **targs)
        loss = out[0] if isinstance(out, (tuple, list)) else out
        arr = loss._value if isinstance(loss, Tensor) else loss
        return arr.astype(jnp.float32)

    def _make_loss_for(self, overlap: bool | None = None):
        """The step's loss closure (cast + batch sharding constraint +
        context-parallel / FSDP-overlap guards) — shared by
        `_build_step` and the phase-attributed timing twins in
        `measure_phase_seconds`, so phase timings measure the SAME
        program the fused step runs. `overlap` overrides
        cfg.overlap_fsdp (the timing twins force it off to measure the
        propagated baseline against the same weights)."""
        cfg = self.config
        mesh = self.mesh
        if overlap is None:
            overlap = cfg.overlap_fsdp
        overlap = bool(overlap and mesh is not None
                       and "fsdp" in mesh.axis_names)

        def loss_for(params, batch):
            params_c = _cast_tree(params, cfg.compute_dtype)
            if mesh is not None and cfg.shard_batch_seq:
                bspec = batch_spec(mesh.axis_names)
                batch = {
                    k: jax.lax.with_sharding_constraint(
                        v, NamedSharding(mesh, P(*(
                            list(bspec) + [None] * (v.ndim - 2))[:v.ndim])))
                    if v.ndim >= 1 else v
                    for k, v in batch.items()}
            with contextlib.ExitStack() as stack:
                if cfg.context_parallel and mesh is not None:
                    from paddle_tpu.distributed.context_parallel import (
                        context_parallel_guard)
                    stack.enter_context(context_parallel_guard(
                        mesh, axis="sp", mode=cfg.context_parallel))
                if overlap:
                    from paddle_tpu.parallel.overlap import (
                        overlap_fsdp_guard)
                    stack.enter_context(overlap_fsdp_guard(
                        mesh, axis="fsdp",
                        chunks=max(1, cfg.overlap_chunks)))
                return self._loss_from_batch(params_c, batch)

        return loss_for

    def _build_step(self, batch_treedef):
        cfg = self.config
        # chaos injection is gated at TRACE time: with chaos off the
        # compiled step has no poison/spike inputs at all — the hot
        # path stays byte-identical. "trainer.grad"/"train.grad.nan"
        # poison grads with NaN; "train.loss.spike" scales loss AND
        # grads by a finite factor (the sentry's EWMA lever).
        from paddle_tpu.distributed import chaos
        self._poison_sites = tuple(
            s for s in ("trainer.grad", "train.grad.nan")
            if chaos.ENABLED and chaos.site_rate(s) > 0)
        self._chaos_poison = bool(self._poison_sites)
        chaos_spike = bool(chaos.ENABLED
                           and chaos.site_rate("train.loss.spike") > 0)
        names = []
        if self._chaos_poison:
            names.append("poison")
        if chaos_spike:
            names.append("spike")
        if cfg.health_probe:
            names.append("loss_cap")
        self._extra_names = tuple(names)

        loss_for = self._make_loss_for()
        grad_fn = jax.value_and_grad(
            lambda tp, fp, b: loss_for({**fp, **tp}, b))

        def step(params, opt_state, lr, batch, *extra):
            kw = dict(zip(names, extra))
            with self._precision_ctx():
                return _step_inner(params, opt_state, lr, batch, **kw)

        def _step_inner(params, opt_state, lr, batch, poison=None,
                        spike=None, loss_cap=None):
            train_p = {n: params[n] for n in self.param_names}
            frozen_p = {n: v for n, v in params.items()
                        if n not in train_p}
            if cfg.grad_accum_steps > 1:
                n_mb = cfg.grad_accum_steps

                def micro(carry, mb):
                    acc_loss, acc_g = carry
                    l, g = grad_fn(train_p, frozen_p, mb)
                    return (acc_loss + l,
                            jax.tree.map(jnp.add, acc_g, g)), None

                zeros = jax.tree.map(
                    lambda a: jnp.zeros(a.shape, jnp.float32), train_p)
                mbs = {k: v.reshape((n_mb, v.shape[0] // n_mb)
                                    + v.shape[1:])
                       for k, v in batch.items()}
                (loss_sum, grads), _ = jax.lax.scan(
                    micro, (jnp.zeros((), jnp.float32), zeros), mbs)
                loss = loss_sum / n_mb
                grads = jax.tree.map(lambda g: g / n_mb, grads)
            else:
                loss, grads = grad_fn(train_p, frozen_p, batch)
            if spike is not None:
                loss = loss * spike
                grads = jax.tree.map(lambda g: g * spike, grads)
            if poison is not None:
                grads = jax.tree.map(lambda g: g * poison, grads)
            return self._apply_update(loss, grads, params, opt_state,
                                      lr, loss_cap)

        return self._jit_step(step)

    def _precision_ctx(self):
        """The package-global matmul precision is 'highest' so EAGER f32
        numerics match the reference; inside the compiled low-precision
        train step that setting would run every bf16 matmul as multi-pass
        f32 emulation (several x slower on the MXU). bf16 compute with
        f32 accumulation is the intended training numerics."""
        import contextlib
        cfg = self.config
        low_prec = (cfg.compute_dtype is not None and
                    jnp.dtype(cfg.compute_dtype) in
                    (jnp.dtype(jnp.bfloat16), jnp.dtype(jnp.float16)))
        return (jax.default_matmul_precision("default") if low_prec
                else contextlib.nullcontext())

    def _apply_update(self, loss, grads, params, opt_state, lr,
                      loss_cap=None):
        """Shared step epilogue: f32 grads + opt barrier + optimizer;
        with skip_nonfinite_grads the whole update is suppressed in-jit
        when any grad (or the loss) is Inf/NaN. With health_probe the
        suppression generalizes — non-finite OR loss above `loss_cap`
        — and the step additionally returns probe = [global_grad_norm,
        applied] (one more reduction; no extra host sync)."""
        grads = _opt_barrier(
            jax.tree.map(lambda g: g.astype(jnp.float32), grads),
            self.config)
        if self.config.offload_opt_state:
            # pull the parked moments into device memory for the update;
            # out_shardings park the new state back in pinned host
            opt_state = {
                n: {k: jax.device_put(
                    v, self._opt_leaf_sharding(n, v, "device"))
                    for k, v in st.items()}
                for n, st in opt_state.items()}
        train_p = {n: params[n] for n in self.param_names}
        new_p, new_s = self.optimizer.apply_gradients_arrays(
            train_p, grads, opt_state, lr)
        if self.config.health_probe:
            # ONE global reduction: the squared grad norm propagates
            # any NaN/Inf, so isfinite(gnorm2) is the all-grads-finite
            # check and sqrt(gnorm2) the probe's grad-norm — the
            # detection rides values the step computes anyway
            gnorm2 = jnp.zeros((), jnp.float32)
            for g in grads.values():
                gnorm2 = gnorm2 + jnp.sum(
                    jnp.asarray(g, jnp.float32) ** 2)
            healthy = jnp.logical_and(jnp.isfinite(loss),
                                      jnp.isfinite(gnorm2))
            if loss_cap is not None:
                healthy = jnp.logical_and(healthy, loss <= loss_cap)
            new_p = {n: jnp.where(healthy, v, train_p[n])
                     for n, v in new_p.items()}
            new_s = jax.tree.map(
                lambda new, old: jnp.where(healthy, new, old),
                new_s, opt_state)
            out_params = dict(params)
            out_params.update(new_p)
            probe = jnp.stack([jnp.sqrt(gnorm2),
                               healthy.astype(jnp.float32)])
            return loss, out_params, new_s, probe
        if self.config.skip_nonfinite_grads:
            finite = jnp.isfinite(loss)
            for g in grads.values():
                finite = jnp.logical_and(finite,
                                         jnp.all(jnp.isfinite(g)))
            # select, don't branch: one program for both outcomes, and
            # every rank takes the same path by construction
            new_p = {n: jnp.where(finite, v, train_p[n])
                     for n, v in new_p.items()}
            new_s = jax.tree.map(lambda new, old: jnp.where(finite, new,
                                                            old),
                                 new_s, opt_state)
            out_params = dict(params)
            out_params.update(new_p)
            return loss, out_params, new_s, jnp.logical_not(finite)
        out_params = dict(params)
        out_params.update(new_p)
        return loss, out_params, new_s

    def _jit_step(self, step):
        """Shared jit wrapper: donation + param/opt-state shardings.
        Under offload_opt_state the opt-state in/out shardings carry
        memory_kind='pinned_host', so XLA schedules the H2D prefetch and
        the D2H writeback of the moments inside the step."""
        mesh = self.mesh
        donate = (0, 1) if self.config.donate else ()
        park = "pinned_host" if self.config.offload_opt_state else None
        if park:
            donate = (0,) if self.config.donate else ()
        # optional extra inputs (chaos poison/spike, sentry loss cap) /
        # output (skip flag or sentry probe)
        extra_in = (None,) * len(self._extra_names)
        has_extra_out = (self.config.skip_nonfinite_grads
                         or self.config.health_probe)
        if mesh is not None:
            pspec = {n: NamedSharding(mesh, self._spec(n))
                     for n in self.params}
            sspec = {n: {k: self._opt_leaf_sharding(n, v, park)
                         for k, v in st.items()}
                     for n, st in self.opt_state.items()}
            rep = NamedSharding(mesh, P())
            extra_out = (rep,) if has_extra_out else ()
            return jax.jit(
                step, donate_argnums=donate,
                in_shardings=(pspec, sspec, rep, None) + extra_in,
                out_shardings=(rep, pspec, sspec) + extra_out)
        if park:
            sspec = {n: {k: self._opt_leaf_sharding(n, v, park)
                         for k, v in st.items()}
                     for n, st in self.opt_state.items()}
            extra_out = (None,) if has_extra_out else ()
            return jax.jit(step, donate_argnums=donate,
                           in_shardings=(None, sspec, None, None)
                           + extra_in,
                           out_shardings=(None, None, sspec) + extra_out)
        return jax.jit(step, donate_argnums=donate)

    # -- public API --------------------------------------------------------
    def step(self, batch: dict) -> Tensor:
        """One optimizer step on `batch` (dict of np/jax arrays or Tensors).
        Returns the scalar loss as a lazy Tensor: steps dispatch
        asynchronously and only reading the value (float()/numpy()) blocks.
        Through the axon tunnel a per-step host sync costs ~100ms, so the
        old eager float() here serialized dispatch against execution."""
        # numpy leaves stay numpy here: on the mesh path device_put
        # below does ONE direct host->sharded transfer (jnp.asarray
        # first paid an extra staging copy to the default device), and
        # on the meshless path jit dispatch converts identically
        batch = {k: (v._value if isinstance(v, Tensor)
                     else v if isinstance(v, (np.ndarray, jax.Array))
                     else jnp.asarray(v))
                 for k, v in batch.items()}
        if observability.ENABLED:
            self._telemetry_tick(batch)
        elif self._tel_last_t is not None:
            # telemetry was disabled mid-run: drop the stale timestamp
            # so a later re-enable doesn't report the whole disabled
            # gap as one giant step into train.step.seconds
            self._tel_last_t = self._tel_prev = None
        if self.mesh is not None:
            put = {}
            for k, v in batch.items():
                sh = self._batch_sharding(k, v.ndim)
                if getattr(v, "sharding", None) == sh:
                    # already placed (the data_iter prefetch path): the
                    # hot path stays free of device_put — no H2D, no
                    # host->device sync on the dispatch thread
                    put[k] = v
                else:
                    put[k] = jax.device_put(v, sh)
            batch = put
        if self._step_fn is None:
            self._step_fn = self._build_step(None)
        lrv = float(self._lr_value())  # lint: disable=hot-path-sync -- LR schedules are host-side python math, never a device value
        cache = getattr(self, "_lr_cache", None)
        if cache is None or cache[0] != lrv:
            # re-stage the lr scalar only when the schedule moves it: a
            # fresh host->device transfer every step costs several ms
            # through the axon dispatch tunnel
            self._lr_cache = (lrv, jnp.asarray(lrv, jnp.float32))
        args = (self.params, self.opt_state, self._lr_cache[1], batch)
        for extra in self._extra_names:
            if extra == "poison":
                from paddle_tpu.distributed import chaos
                v = 1.0
                if "trainer.grad" in self._poison_sites:
                    v *= chaos.grad_poison("trainer.grad")  # lint: disable=disabled-gate -- _extra_names is derived from chaos.ENABLED at trace time; with chaos off this input does not exist
                if "train.grad.nan" in self._poison_sites:
                    v *= chaos.grad_poison("train.grad.nan")  # lint: disable=disabled-gate -- same trace-time gate as above
                args += (jnp.asarray(v, jnp.float32),)
            elif extra == "spike":
                from paddle_tpu.distributed import chaos
                args += (jnp.asarray(
                    chaos.loss_spike("train.loss.spike"),  # lint: disable=disabled-gate -- same trace-time gate as above
                    jnp.float32),)
            else:   # "loss_cap" (sentry spike threshold)
                capv = self._loss_cap  # already a float (set_loss_cap)
                if self._cap_cache is None \
                        or self._cap_cache[0] != capv:
                    # restaged only when the sentry moves it (the
                    # sentry quantizes, so this is rare) — same
                    # host->device economy as the lr scalar above
                    self._cap_cache = (capv,
                                       jnp.asarray(capv, jnp.float32))
                args += (self._cap_cache[1],)
        # recompile attribution reads the jit trace-cache size around
        # the call: growth = a REAL retrace for this batch's shapes
        # (immune to observability being enabled mid-run, when already-
        # warm shapes must not recount)
        n0 = self._trace_count() if observability.ENABLED else None
        # enter the mesh context for the (first-call) trace so
        # sharding-aware custom vjps (e.g. the embedding grad reshard in
        # nn/functional/common.py) can read the axis names
        with self._mesh_ctx():
            out = self._step_fn(*args)
        if observability.ENABLED and n0 is not None \
                and self._trace_count() > n0:
            observability.inc("train.recompiles",
                              shape=self._batch_sig(batch))
        if self.config.health_probe:
            # the probe stays LAZY: [global_grad_norm, applied]; the
            # sentry (or any caller) decides when to pay the sync
            loss, self.params, self.opt_state, self.last_probe = out
        elif self.config.skip_nonfinite_grads:
            loss, self.params, self.opt_state, skipped = out
            self._note_skip(skipped)
        else:
            loss, self.params, self.opt_state = out
        self.optimizer._step_count += 1
        if self._tel_prev is not None:
            # hand the LAZY loss to the reporter: it materializes a
            # few steps later, when float() no longer forces a sync
            self._tel_prev[2] = loss
        return Tensor(loss, stop_gradient=True)

    def _batch_sharding(self, key, ndim):
        """Cached NamedSharding for batch leaf (key, ndim). step() used
        to rebuild the spec + NamedSharding per tensor per step — pure
        host work on the dispatch thread; the cache makes the repeat
        cost one dict hit, and hands the SAME objects to data_iter's
        prefetcher so placed batches compare equal in step()."""
        if self.mesh is None:
            return None           # prefetcher default-places; step()'s
            #                       jnp.asarray is then a no-op
        sh = self._batch_shardings.get((key, ndim))
        if sh is None:
            bspec = batch_spec(self.mesh.axis_names,
                               self.config.shard_batch_seq)
            spec = P(*(list(bspec) + [None] * (ndim - 2))[:ndim])
            sh = NamedSharding(self.mesh, spec)
            self._batch_shardings[(key, ndim)] = sh
        return sh

    def data_iter(self, loader, depth=2):
        """The idiomatic input-pipeline entry point: wrap a DataLoader
        (or any iterator of {name: array} batches) in a sharding-aware
        device prefetcher matched to this trainer. Batches come out
        already placed with the trainer's own batch shardings, H2D
        overlapped with the previous step's compute on a background
        thread, so step() performs ZERO device_put calls:

            for batch in trainer.data_iter(loader):
                loss = trainer.step(batch)

        Returns a DevicePrefetcher (io/prefetch.py): a context manager
        with close(), bounded to `depth` on-device batches."""
        from paddle_tpu.io.prefetch import DevicePrefetcher
        return DevicePrefetcher(loader, sharding_for=self._batch_sharding,
                                depth=depth)

    def _telemetry_tick(self, batch):
        """Report the PREVIOUS step's telemetry now that its interval
        is known (dispatch is async; the inter-call interval converges
        to device step time under donation backpressure), then stamp
        this step's token count for the next tick. One attribute check
        when observability is disabled (the caller gates)."""
        import time as _time
        now = _time.perf_counter()
        if self._telemetry is None:
            from paddle_tpu.observability.telemetry import (
                TrainingTelemetry)
            self._telemetry = TrainingTelemetry.for_model(self.model)
        if self._tel_prev is not None and self._tel_last_t is not None:
            tokens, seq, loss = self._tel_prev
            self._telemetry.step(tokens, now - self._tel_last_t,
                                 seq_len=seq, loss=loss)
        self._tel_last_t = now
        arr = batch.get("input_ids")
        if arr is None and batch:
            arr = next(iter(batch.values()))
        ndim = getattr(arr, "ndim", 0)
        if ndim >= 2:
            tokens = int(arr.shape[0]) * int(arr.shape[1])
            seq = int(arr.shape[1])
        elif ndim == 1:
            tokens = seq = int(arr.shape[0])
        else:
            tokens = seq = 0
        # the batch is GLOBAL; tokens_per_sec/MFU are catalogued
        # per-CHIP (bench.py's single-chip framing), so divide by the
        # mesh size — otherwise a 4-chip run reads 4x the true MFU
        if self.mesh is not None:
            tokens = tokens / max(1, int(self.mesh.devices.size))
        self._tel_prev = [tokens, seq, None]
        self._note_logits_bytes_saved(tokens)

    def _note_logits_bytes_saved(self, tokens):
        """With a blockwise-CE model config (loss_chunk > 0), publish
        the per-chip bytes of [B*S, vocab] logits the loss path avoids
        materializing this step — the memory evidence behind an MFU
        move (ISSUE 14). One getattr chain + gauge set per step,
        already inside the observability-gated telemetry tick."""
        mcfg = getattr(self.model, "config", None)
        chunk = getattr(mcfg, "loss_chunk", 0) or 0
        vocab = getattr(mcfg, "vocab_size", 0) or 0
        if not (chunk and vocab and tokens):
            return
        dt = self.config.compute_dtype
        itemsize = jnp.dtype(dt).itemsize if dt is not None else 4
        if observability.ENABLED:
            from paddle_tpu.kernels.blockwise_ce import logits_bytes_saved
            observability.set_gauge(
                "train.loss.logits_bytes_saved",
                logits_bytes_saved(
                    int(tokens), int(vocab), int(chunk),
                    int(getattr(mcfg, "loss_vocab_block", 0) or 0),
                    itemsize))

    def _trace_count(self):
        """Traced programs in the step's jit cache (0 before the step
        fn exists, or when this jax version hides the cache): step()
        compares before/after each call, so `train.recompiles` counts
        REAL retraces, labeled with the batch-shape signature that
        triggered them (the ROADMAP bucket-autotune feed). Cardinality
        is bounded by the pipeline's real shape buckets."""
        fn = self._step_fn
        if fn is None:
            return 0
        cache_size = getattr(fn, "_cache_size", None)
        try:
            return int(cache_size()) if cache_size is not None else 0
        except Exception:
            # a private jax API probe; attribution degrades, the step
            # must not (a `return` body is not a silent swallow, so no
            # suppression is needed)
            return 0

    @staticmethod
    def _batch_sig(batch):
        """The `shape` label for train.recompiles: every leaf's name,
        dims, and dtype, sorted — distinct signature = distinct trace."""
        return ",".join(
            f"{k}:{'x'.join(str(d) for d in getattr(v, 'shape', ()))}"
            f":{getattr(v, 'dtype', '?')}"
            for k, v in sorted(batch.items()))

    def fleet_heartbeat(self, store, rank, world_size, **kw):
        """Publish this process's training telemetry into the
        cross-rank heartbeat plane (observability/fleet.py): step,
        tokens/sec, MFU, recompiles and pending async saves land in
        the rendezvous store under ``fleet/hb/{rank}`` every couple of
        seconds, where the rank-0 aggregator (or a serving replica's
        ``GET /debug/fleet``) computes step skew and straggler flags.
        Returns the started FleetHeartbeat — or None when
        observability is disabled: no thread, no store traffic, the
        plane's zero-cost contract."""
        if not observability.ENABLED:
            return None
        from paddle_tpu.observability.fleet import FleetHeartbeat
        return FleetHeartbeat(store, rank, world_size, **kw).start()

    @property
    def telemetry(self):
        """The TrainingTelemetry reporter (None until a step ran with
        observability enabled)."""
        return self._telemetry

    def _note_skip(self, flag):
        """Track consecutive non-finite skips without a per-step host
        sync: flags buffer until nonfinite_check_every of them pend,
        then one blocking read drains the batch; crossing
        max_consecutive_nonfinite raises NonFiniteGradError (the run
        has diverged — checkpoint-and-abort beats skipping forever)."""
        self._pending_skips.append(flag)
        if len(self._pending_skips) < max(
                1, self.config.nonfinite_check_every):
            return
        pending, self._pending_skips = self._pending_skips, []
        for f in pending:
            if bool(np.asarray(f)):
                self.nonfinite_streak += 1
                self.nonfinite_skipped += 1
                if observability.ENABLED:
                    observability.inc("train.nonfinite_skips")
            else:
                self.nonfinite_streak = 0
        if self.nonfinite_streak >= self.config.max_consecutive_nonfinite:
            raise NonFiniteGradError(
                f"{self.nonfinite_streak} consecutive steps produced "
                f"non-finite gradients (limit "
                f"{self.config.max_consecutive_nonfinite}); aborting")

    def _mesh_ctx(self):
        import contextlib
        return self.mesh if self.mesh is not None \
            else contextlib.nullcontext()

    def _lr_value(self):
        return self.optimizer._lr_value() * self._lr_scale

    def set_lr_scale(self, scale):
        """Transient multiplier on the schedule's LR (1.0 = none) —
        the sentry's post-rollback dampening ramp. Host-side python
        math; the staged lr scalar re-transfers only when it moves."""
        self._lr_scale = float(scale)

    def set_loss_cap(self, cap):
        """The sentry's in-jit spike threshold (health_probe only): an
        update whose loss exceeds `cap` is suppressed inside the
        compiled step — params and optimizer state pass through
        unchanged — and the probe reports applied=0. +inf disarms."""
        self._loss_cap = float(cap)

    def lower(self, batch: dict):
        """jax.jit lowering of the step for inspection/AOT-compile."""
        if self._step_fn is None:
            self._step_fn = self._build_step(None)
        if observability.ENABLED:
            # an AOT lowering is a program build for this shape too
            observability.inc("train.recompiles",
                              shape=self._batch_sig(batch))
        lr = jnp.asarray(self._lr_value(), jnp.float32)
        args = (self.params, self.opt_state, lr, batch)
        for extra in self._extra_names:
            v = float("inf") if extra == "loss_cap" else 1.0
            args += (jnp.asarray(v, jnp.float32),)
        # same mesh context as step(): AOT lowering must see the ambient
        # mesh or sharding-aware vjps silently degrade
        with self._mesh_ctx():
            return self._step_fn.lower(*args)

    def _phase_twins(self, loss_for):
        """Forward-only and forward+backward twins of one loss closure
        — they mirror _step_inner EXACTLY, including the grad-accum
        microbatch scan, which is a different program (different peak
        memory / runtime) than one full-batch pass."""
        train_names = set(self.param_names)
        n_mb = self.config.grad_accum_steps

        def _split_mb(b):
            return {k: v.reshape((n_mb, v.shape[0] // n_mb)
                                 + v.shape[1:])
                    for k, v in b.items()}

        def fwd_fn(params, b):
            if n_mb > 1:
                def micro(acc, mb):
                    return acc + loss_for(params, mb), None
                tot, _ = jax.lax.scan(
                    micro, jnp.zeros((), jnp.float32), _split_mb(b))
                return tot / n_mb
            return loss_for(params, b)

        def fwdbwd_fn(params, b):
            tp = {n: params[n] for n in train_names}
            fp = {n: v for n, v in params.items() if n not in train_names}
            gfn = jax.value_and_grad(
                lambda t, mb: loss_for({**fp, **t}, mb))
            if n_mb > 1:
                def micro(carry, mb):
                    acc_l, acc_g = carry
                    l, g = gfn(tp, mb)
                    return (acc_l + l,
                            jax.tree.map(jnp.add, acc_g, g)), None
                zeros = jax.tree.map(
                    lambda a: jnp.zeros(a.shape, jnp.float32), tp)
                (ls, gs), _ = jax.lax.scan(
                    micro, (jnp.zeros((), jnp.float32), zeros),
                    _split_mb(b))
                return ls / n_mb, gs
            return gfn(tp, b)

        return fwd_fn, fwdbwd_fn

    def measure_phase_seconds(self, batch: dict, iters: int = 2):
        """Phase-attributed step timing: where does the step's wall
        time go? Compiles forward-only and forward+backward twins of
        the step's OWN loss machinery (`_make_loss_for` — same cast,
        batch constraint and precision context the fused step traces)
        and attributes

            fwd       = t(loss)
            bwd       = t(value_and_grad) - t(loss)
            optimizer = t(full step)      - t(value_and_grad)

        Each timing is a mean over `iters` synced runs after a compile
        warmup. Records `train.phase.seconds{phase=...}` when
        observability is enabled and always returns
        {"fwd", "bwd", "optimizer", "step"} seconds. NOTE: the
        full-step timing drives `iters + 1` REAL optimizer steps (the
        donated program is the thing being measured) — call this from
        a bench/diagnostic context, not mid-training-run.

        With overlap_fsdp active the twins gain a comm-attribution
        column: two extra twin pairs run — `propagated` (overlap
        forced off, XLA-propagated collectives) and `nocomm` (same
        program with the params REPLICATED over 'fsdp', so no weight
        all-gather exists) — and the result grows
        {"fwd_comm", "bwd_comm"} (collective seconds per phase:
        propagated − nocomm, the overlap-fraction denominator) and
        {"overlap_fraction"} (comm hidden under compute / total comm,
        via the `train.overlap.phase` trace spans all six timings are
        recorded to). The nocomm twin still carries the grad
        reduce over the batch axes in bwd, so the column attributes
        WEIGHT-movement comm, not every collective.
        """
        import time as _time
        batch = {k: (v._value if isinstance(v, Tensor)
                     else v if isinstance(v, (np.ndarray, jax.Array))
                     else jnp.asarray(v))
                 for k, v in batch.items()}
        if self.mesh is not None:
            batch = {k: jax.device_put(
                v, self._batch_sharding(k, v.ndim))
                for k, v in batch.items()}
        loss_for = self._make_loss_for()
        fwd_fn, fwdbwd_fn = self._phase_twins(loss_for)

        def _timed(run):
            # the warmup must DRAIN, not just dispatch: jit returns
            # after async dispatch, and an in-flight warmup execution
            # would bleed into the timed window
            jax.block_until_ready(run())
            t0 = _time.perf_counter()
            for _ in range(max(1, iters)):
                out = run()
            jax.block_until_ready(out)
            return (_time.perf_counter() - t0) / max(1, iters)

        with self._mesh_ctx():
            with self._precision_ctx():
                jf = jax.jit(fwd_fn)
                jg = jax.jit(fwdbwd_fn)
                t_fwd = _timed(lambda: jf(self.params, batch))
                t_fwdbwd = _timed(lambda: jg(self.params, batch))

        def _full():
            loss = self.step(batch)
            # close the dispatch chain so the timing covers execution
            return loss._value

        t_step = _timed(_full)
        phases = {
            "fwd": t_fwd,
            "bwd": max(0.0, t_fwdbwd - t_fwd),
            "optimizer": max(0.0, t_step - t_fwdbwd),
            "step": t_step,
        }
        overlap_on = (self.config.overlap_fsdp and self.mesh is not None
                      and "fsdp" in self.mesh.axis_names)
        if overlap_on:
            from paddle_tpu.observability import trace
            from paddle_tpu.parallel.overlap import (
                overlap_fraction_from_spans)
            # comm-attribution twins: `propagated` = same weights, ring
            # forced off (XLA-propagated collectives); `nocomm` = same
            # PROGRAM with params replicated over 'fsdp' (no weight
            # all-gather exists at all). propagated − nocomm isolates
            # weight-movement comm per phase; propagated − overlapped
            # is how much of it the ring hid.
            pf, pg = self._phase_twins(self._make_loss_for(overlap=False))
            nc_params = {
                n: jax.device_put(v, NamedSharding(
                    self.mesh, _strip_axis(self._spec(n), "fsdp")))
                for n, v in self.params.items()}
            with self._mesh_ctx():
                with self._precision_ctx():
                    jpf, jpg = jax.jit(pf), jax.jit(pg)
                    t_p_fwd = _timed(lambda: jpf(self.params, batch))
                    t_p_fb = _timed(lambda: jpg(self.params, batch))
                    # same jitted twins: new shardings = new cache entry
                    t_n_fwd = _timed(lambda: jpf(nc_params, batch))
                    t_n_fb = _timed(lambda: jpg(nc_params, batch))
            wall = _time.time()
            for variant, f, fb in (
                    ("overlapped", t_fwd, t_fwdbwd),
                    ("propagated", t_p_fwd, t_p_fb),
                    ("nocomm", t_n_fwd, t_n_fb)):
                trace.record_span("train.overlap.phase", wall, f * 1e6,
                                  attrs={"variant": variant,
                                         "phase": "fwd"})
                trace.record_span("train.overlap.phase", wall,
                                  max(0.0, fb - f) * 1e6,
                                  attrs={"variant": variant,
                                         "phase": "bwd"})
            frac = overlap_fraction_from_spans()
            phases["fwd_comm"] = max(0.0, t_p_fwd - t_n_fwd)
            phases["bwd_comm"] = max(
                0.0, (t_p_fb - t_p_fwd) - (t_n_fb - t_n_fwd))
            phases["overlap_fraction"] = frac
            if observability.ENABLED:
                observability.observe("train.overlap.comm.seconds",
                                      phases["fwd_comm"], phase="fwd")
                observability.observe("train.overlap.comm.seconds",
                                      phases["bwd_comm"], phase="bwd")
                if frac is not None:
                    observability.set_gauge("train.overlap.fraction",
                                            frac)
        if observability.ENABLED:
            observability.observe("train.phase.seconds", phases["fwd"],
                                  phase="fwd")
            observability.observe("train.phase.seconds", phases["bwd"],
                                  phase="bwd")
            observability.observe("train.phase.seconds",
                                  phases["optimizer"], phase="optimizer")
        return phases

    def sync_to_model(self):
        """Write the trainer's param arrays back into the Layer tree (for
        state_dict / checkpoint / eval through the eager API)."""
        tensors = state_tensors(self.model)
        for n, arr in self.params.items():
            tensors[n]._value = arr
        return self.model

    # -- checkpointing -----------------------------------------------------
    def checkpoint_state(self):
        """The state a training checkpoint must capture — params AND
        optimizer moments — as a nested dict save_state_dict flattens.
        Resuming params without moments silently restarts Adam's
        bias-correction warmup."""
        return {"params": dict(self.params),
                "opt": {n: dict(st) for n, st in self.opt_state.items()}}

    def save_checkpoint(self, path):
        """Save params + optimizer state into `path`, matching the
        elastic save boundary (run_resilient's ``save_fn(step, path)``
        is ``lambda step, path: trainer.save_checkpoint(path)``). With
        a `checkpointer` attached this returns after only the device->
        host snapshot — hashing and file I/O overlap the following
        steps, and donation is safe because the snapshot materializes
        before return. Without one, a plain synchronous save."""
        sd = self.checkpoint_state()
        if self.checkpointer is not None:
            self.checkpointer.save(sd, path)
        else:
            from paddle_tpu.distributed import checkpoint as ckpt_mod
            ckpt_mod.save_state_dict(sd, path)
        return path

    def load_checkpoint(self, path):
        """Restore params + optimizer state written by save_checkpoint,
        resharded to this trainer's current placements. Flushes the
        attached checkpointer first so an in-flight save of `path` is
        never half-read."""
        from paddle_tpu.distributed import checkpoint as ckpt_mod
        if self.checkpointer is not None:
            self.checkpointer.flush()
        sd = {"params": {n: Tensor(v) for n, v in self.params.items()},
              "opt": {n: {k: Tensor(v) for k, v in st.items()}
                      for n, st in self.opt_state.items()}}
        ckpt_mod.load_state_dict(sd, path)
        self.params = {n: t._value for n, t in sd["params"].items()}
        self.opt_state = {n: {k: t._value for k, t in st.items()}
                          for n, st in sd["opt"].items()}
        # loaded leaves arrive COMMITTED to their restore device, and
        # committed-ness is part of the jit cache key — left as-is, the
        # first step after every restore (elastic resume, sentry
        # rollback) silently retraces the whole program. Re-stage to
        # the same placement __init__ produced: the sharded path
        # re-runs _shard_state, the default path drops commitment by
        # round-tripping through host.
        if self.mesh is not None and self.plan is not None:
            self._shard_state()
        else:
            import numpy as np
            self.params = {n: jnp.asarray(np.asarray(v))
                           for n, v in self.params.items()}
            self.opt_state = {n: {k: jnp.asarray(np.asarray(v))
                                  for k, v in st.items()}
                              for n, st in self.opt_state.items()}
        return path
